"""Benchmark driver: prints ONE JSON line with the headline metric.

Headline (BASELINE.md): ResNet-50 ImageNet-config training throughput
(samples/sec/chip) on one TPU chip — the flagship config from BASELINE.json,
measured the way the reference's PerformanceListener measures throughput
(reference optimize/listeners/PerformanceListener.java). vs_baseline is
reported against the best previously-recorded number in BASELINE.md for the
same config (null when none exists yet).

TPU-first measurement methodology:
 - K train steps run per host dispatch (`lax.scan` inside one XLA program,
   see make_multistep_train_step) so relay/host dispatch latency is amortized;
 - compute dtype defaults to the model's measured-best policy (--f32 /
   --bf16-matmul / --bf16-act force one);
 - inputs are staged device-side once (a (K, B, ...) stack in HBM);
 - only a host read (`float(loss)`) is trusted as a sync point — through the
   axon relay `block_until_ready` returns before remote execution completes;
 - model FLOPs come from XLA's own cost analysis of the compiled program, and
   MFU is reported against the chip's bf16 peak (BENCH_PEAK_FLOPS env, default
   197e12 = TPU v5e).

Usage: python bench.py [--model lenet|resnet50|char_rnn|transformer|word2vec]
                       [--batch N] [--iters N] [--ksteps K] [--seq T]
                       [--vocab V] [--f32 | --bf16-matmul | --bf16-act]
       (default dtype = each model's measured-best config: bf16 activations
       for the flagships, bf16-matmul for the tiny models — BASELINE.md r5)
"""
from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np

# Best previously-recorded number per config (BASELINE.md "Measured" table).
# vs_baseline is reported against these; None -> no baseline yet and the JSON
# record carries vs_baseline: null (NOT 1.0 — a sentinel a reader could misread
# as parity).
BASELINE_SAMPLES_PER_SEC = {
    "resnet50": 1870.0,    # round 3, bf16 matmul, batch 128 (BASELINE.md)
    "lenet": 702374.8,     # round 2 driver record (BENCH_r02.json)
    "char_rnn": 16318.1,   # round 3 first recording (BASELINE.md)
    "transformer": 5169.2,  # round 3 first recording
    "word2vec": 940856.4,  # round 3 first recording
    "attention": 1088790.0,  # round 3 first recording (pallas path)
}
PEAK_FLOPS = float(os.environ.get("BENCH_PEAK_FLOPS", 197e12))


def _xla_flops(jit_fn, *args) -> float:
    """XLA's own flop count for one dispatch of a compiled jit function.

    CAVEAT (verified on this chip, and pinned by
    tests/test_bench_contract.py::test_cost_analysis_counts_scan_body_once):
    XLA's cost analysis counts a `lax.scan`/while-loop BODY ONCE, not
    trip-count times — flops for a K-step scanned program are identical for
    K=1..8. Callers that scan K steps per dispatch must multiply by K
    themselves. Round 2's recorded "0.3% MFU" for LeNet understated real
    utilization by exactly K for this reason.

    Shares the tracker's ``cost_analysis_flops`` helper, which reads the
    analysis off ``lower()`` WITHOUT a second ``compile()`` — the old
    lower+compile-again path here double-compiled every flagship program
    just to count its flops.
    """
    from deeplearning4j_tpu.observability.compile_tracker import \
        cost_analysis_flops
    return max(0.0, cost_analysis_flops(jit_fn, *args))


#: armed by _child_main when --xplane-attribution (or the first-healthy
#: trigger) asks for a trace: {"trigger": ..., "dispatches": N}. Consumed by
#: the FIRST _measure_multistep call of the run (for char_rnn's three-way
#: A/B that is the scan variant), so one bench row pays for one capture.
_PROFILE_SPEC = None

#: models whose bench path runs through _measure_multistep and can therefore
#: re-dispatch the already-compiled program under a trace; the others get a
#: graceful profile_error field instead of a crash
_PROFILE_CAPABLE = frozenset(
    {"lenet", "resnet50", "vgg16", "char_rnn", "transformer", "moe"})

#: models with a --sharding grid axis: flagship fit paths routed through the
#: partition-rule engine's compile seam (parallel/partition.py rule sets)
_SHARDING_CAPABLE = frozenset({"fit_resnet50", "transformer"})


def _profile_capture(dispatch_once, logdir_hint: str = None) -> dict:
    """Run the armed trace capture around ``dispatch_once`` (a thunk
    re-dispatching the compiled program once, ending on a host sync).
    Returns bench-row fields — xplane_attribution + profile_trace on
    success, profile_error on ANY failure; never raises (the capture is
    measurement decoration, the headline number must survive it)."""
    global _PROFILE_SPEC
    spec, _PROFILE_SPEC = _PROFILE_SPEC, None
    if spec is None:
        return {}
    fields = {}
    try:
        from deeplearning4j_tpu.observability.profiler import \
            global_trace_session
        session = global_trace_session()
        logdir = session.start(spec.get("trigger", "bench"),
                               logdir=logdir_hint)
        if logdir is None:
            return {"profile_error": "trace engine busy or profiler refused"}
        fields["profile_trace"] = logdir
        try:
            for _ in range(max(1, int(spec.get("dispatches", 2)))):
                dispatch_once()
        finally:
            summary = session.stop() or {}
        if summary.get("error"):
            fields["profile_error"] = str(summary["error"])
        else:
            fields["xplane_attribution"] = {
                "categories_pct": summary.get("categories_pct", {}),
                "top_ops": summary.get("top_ops", [])[:5],
                "total_device_ns": summary.get("total_device_ns", 0),
            }
    except Exception as e:  # never let attribution sink the headline row
        fields["profile_error"] = repr(e)[:300]
    return fields


def _measure_multistep(conf, xs, ys, iters: int, warmup: int,
                       graph: bool = False, track_fn: str = None) -> dict:
    """Steady-state throughput of K-step scanned training on stacked batches.

    xs/ys: (K, B, ...) stacks (lists of stacks for graph nets). Each timed
    "iter" is ONE host dispatch running K fused train steps on device. The
    donated-params chain means the final float(loss) waits on every step.

    ``track_fn`` names the program in the CompileTracker so the rolling
    ``dl4j_step_mfu{fn=track_fn}`` gauge populates during the run — the
    per-variant MFU channel for A/B twins (note_step after each timed
    dispatch advances by K, matching the fit loops).
    """
    import jax
    import jax.numpy as jnp

    if graph:
        from deeplearning4j_tpu.nn.graph_network import (
            ComputationGraph, make_graph_multistep_train_step)
        net = ComputationGraph(conf).init()
        multi = make_graph_multistep_train_step(conf)
    else:
        from deeplearning4j_tpu.nn.multilayer import (
            MultiLayerNetwork, make_multistep_train_step)
        net = MultiLayerNetwork(conf).init()
        multi = make_multistep_train_step(conf)

    jit_multi = jax.jit(multi, donate_argnums=(0, 1, 2))
    tracker = None
    dispatch = jit_multi
    if track_fn:
        from deeplearning4j_tpu.observability import global_tracker
        tracker = global_tracker()
        dispatch = tracker.wrap(track_fn, jit_multi)
    key = jax.random.PRNGKey(0)
    params, states, upd = net.params_list, net.state_list, net.updater_state

    ksteps = (xs[0].shape[0] if graph else xs.shape[0])
    batch = (xs[0].shape[1] if graph else xs.shape[1])

    # XLA's flop count covers the scan body ONCE (see _xla_flops caveat), so
    # one K-step dispatch executes ksteps x that count
    flops_per_dispatch = ksteps * _xla_flops(jit_multi, params, states, upd,
                                             xs, ys, key, jnp.int32(0))

    for i in range(warmup):
        params, states, upd, loss = dispatch(params, states, upd, xs, ys,
                                             key, jnp.int32(i * ksteps))
    float(loss[-1])  # hard sync: host read (block_until_ready alone is
    #                  unreliable through the axon relay's async dispatch)

    t0 = time.perf_counter()
    for i in range(iters):
        params, states, upd, loss = dispatch(
            params, states, upd, xs, ys, key,
            jnp.int32((warmup + i) * ksteps))
        if tracker is not None:
            tracker.note_step(ksteps, fn=track_fn)
    # the donated-params chain makes this final host read wait on every step
    float(loss[-1])
    dt = time.perf_counter() - t0

    n_steps = iters * ksteps
    flops_per_sec = flops_per_dispatch * iters / dt if flops_per_dispatch else 0.0
    r = {
        "samples_per_sec": batch * n_steps / dt,
        "step_time_ms": dt / n_steps * 1000,
        "batch": batch,
        "iters": iters,
        "ksteps": ksteps,
        "tflops_per_sec": round(flops_per_sec / 1e12, 4),
        "mfu": round(flops_per_sec / PEAK_FLOPS, 6),
    }
    if _PROFILE_SPEC is not None:
        # attribution capture AFTER the timed loop: re-dispatches the
        # already-compiled program (zero extra compiles) under a trace, so
        # the profiled program IS the timed one and the headline number is
        # untouched by trace overhead
        state = {"params": params, "states": states, "upd": upd, "i": 0}

        def dispatch_once():
            state["params"], state["states"], state["upd"], loss = dispatch(
                state["params"], state["states"], state["upd"], xs, ys, key,
                jnp.int32((warmup + iters + state["i"]) * ksteps))
            state["i"] += 1
            float(loss[-1])  # host sync: the trace must contain device work

        r.update(_profile_capture(dispatch_once))
    return r


def _stack(a, k: int):
    import jax.numpy as jnp
    return jnp.broadcast_to(a[None], (k,) + a.shape)


def _onehot_batch(rng, batch: int, n_classes: int):
    y = np.zeros((batch, n_classes), np.float32)
    y[np.arange(batch), rng.integers(0, n_classes, batch)] = 1
    return y


#: LM bench geometry, shared with flagship_setup
LM_VOCAB, LM_SEQ = 256, 256


def flagship_setup(model: str, batch: int, ksteps: int):
    """(conf, xs_stack, ys_stack, is_graph) for a headline config — the ONE
    construction behind both the bench measurements and
    scripts/profile_flagship.py, so the profiled program IS the timed one."""
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    if model == "resnet50":
        from deeplearning4j_tpu.models.resnet import resnet50
        x = jnp.asarray(rng.normal(size=(batch, 224, 224, 3))
                        .astype(np.float32))
        y = jnp.asarray(_onehot_batch(rng, batch, 1000))
        return (resnet50(n_classes=1000, image_size=224),
                [_stack(x, ksteps)], [_stack(y, ksteps)], True)
    if model == "vgg16":
        from deeplearning4j_tpu.models.vgg import vgg16
        x = jnp.asarray(rng.normal(size=(batch, 224, 224, 3))
                        .astype(np.float32))
        y = jnp.asarray(_onehot_batch(rng, batch, 1000))
        return (vgg16(n_classes=1000, image_size=224),
                _stack(x, ksteps), _stack(y, ksteps), False)
    if model == "lenet":
        from deeplearning4j_tpu.models.lenet import lenet_mnist
        x = jnp.asarray(rng.normal(size=(batch, 784)).astype(np.float32))
        y = jnp.asarray(_onehot_batch(rng, batch, 10))
        return lenet_mnist(), _stack(x, ksteps), _stack(y, ksteps), False
    if model in ("transformer", "moe"):
        from deeplearning4j_tpu.models.transformer import (
            moe_transformer_lm, transformer_lm)
        conf = (transformer_lm(vocab_size=LM_VOCAB, width=256, n_layers=4,
                               n_heads=4, max_len=LM_SEQ)
                if model == "transformer" else
                moe_transformer_lm(vocab_size=LM_VOCAB, width=256, n_layers=4,
                                   n_heads=4, n_experts=8, max_len=LM_SEQ))
        ids = rng.integers(0, LM_VOCAB, (batch, LM_SEQ))
        x = jnp.asarray(np.eye(LM_VOCAB, dtype=np.float32)[ids])
        return conf, _stack(x, ksteps), _stack(x, ksteps), False
    raise ValueError(f"no flagship setup for model '{model}'")


def bench_lenet(batch: int, iters: int, ksteps: int, warmup: int = 2) -> dict:
    conf, xs, ys, graph = flagship_setup("lenet", batch, ksteps)
    return _measure_multistep(conf, xs, ys, iters, warmup, graph=graph)


def bench_resnet50(batch: int, iters: int, ksteps: int, warmup: int = 2) -> dict:
    conf, xs, ys, graph = flagship_setup("resnet50", batch, ksteps)
    return _measure_multistep(conf, xs, ys, iters, warmup, graph=graph)


def bench_vgg16(batch: int, iters: int, ksteps: int, warmup: int = 2) -> dict:
    """VGG-16 single-chip throughput (VERDICT #7 grid completion): the
    classic dense-conv stack — ~4x the per-sample flops of ResNet-50 with no
    BN, so it isolates pure conv/matmul throughput from the norm-reduce
    lever."""
    conf, xs, ys, graph = flagship_setup("vgg16", batch, ksteps)
    return _measure_multistep(conf, xs, ys, iters, warmup, graph=graph)


def bench_char_rnn(batch: int, iters: int, ksteps: int, warmup: int = 2,
                   vocab: int = 64, seq: int = 50,
                   hidden: int = 200, lstm_impl: str = "auto") -> dict:
    """GravesLSTM char-RNN (BASELINE config 3): TBPTT-length sequences.

    ``hidden`` >= 1024 is the grid's worst-number config (0.5%% MFU at the
    default 200) — the row the recurrent engine (ops/lstm.py) exists to move.

    Three-way A/B twin (the word2vec dense/scatter pattern): every record
    carries the scan-oracle and fused-scan timings, plus the Pallas
    persistent-cell timing when the dispatch gate would engage it on this
    backend (None fields on CPU, where the kernel never runs). The headline
    ``samples_per_sec`` is whichever variant ``lstm_impl`` selects — "auto"
    resolves through the production gate, so the headline IS the shipping
    default. Each variant is measured under its own CompileTracker program
    name (``char_rnn[<impl>]``), so per-variant MFU flows through the rolling
    ``dl4j_step_mfu{fn}`` gauge."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.char_rnn import char_rnn_lstm
    from deeplearning4j_tpu.ops import lstm as lstm_engine

    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, (batch, seq))
    x = jnp.asarray(np.eye(vocab, dtype=np.float32)[ids])

    def measure(impl: str) -> dict:
        # the gate reads DL4J_LSTM_IMPL at trace time; a fresh conf per
        # variant keeps each measurement's trace (and donated buffers) its own
        saved = os.environ.get(lstm_engine.IMPL_ENV)
        os.environ[lstm_engine.IMPL_ENV] = impl
        try:
            conf = char_rnn_lstm(vocab_size=vocab, hidden=hidden,
                                 tbptt_length=seq)
            conf.backprop_type = "Standard"  # one jitted step over the window
            return _measure_multistep(conf, _stack(x, ksteps),
                                      _stack(x, ksteps), iters, warmup,
                                      track_fn=f"char_rnn[{impl}]")
        finally:
            if saved is None:
                os.environ.pop(lstm_engine.IMPL_ENV, None)
            else:
                os.environ[lstm_engine.IMPL_ENV] = saved

    results = {"scan": measure("scan"), "fused": measure("fused")}
    pallas_engages = lstm_engine.resolve_impl(
        hidden, seq, batch, vocab, impl="pallas")[0] == "pallas"
    if pallas_engages:
        results["pallas"] = measure("pallas")

    headline = lstm_impl
    if headline == "auto":
        headline = lstm_engine.resolve_impl(hidden, seq, batch, vocab,
                                            impl="auto")[0]
    if headline not in results:  # e.g. forced pallas on CPU -> fused fallback
        headline = "fused"
    r = dict(results[headline])
    # an armed attribution capture is consumed by the FIRST variant measured
    # (scan); hoist its fields so the headline row carries them whichever
    # variant wins
    for impl in ("scan", "fused", "pallas"):
        src = results.get(impl, {})
        if any(f in src for f in ("xplane_attribution", "profile_error")):
            for f in ("xplane_attribution", "profile_trace", "profile_error"):
                if f in src:
                    r.setdefault(f, src[f])
            r.setdefault("profile_variant", impl)
            break
    r["chars_per_sec"] = r["samples_per_sec"] * seq
    r["hidden"] = hidden
    r["lstm_impl"] = lstm_impl
    r["lstm_impl_selected"] = headline
    base = results["scan"]["samples_per_sec"]
    r["scan_samples_per_sec"] = round(base, 1)
    r["fused_samples_per_sec"] = round(results["fused"]["samples_per_sec"], 1)
    r["fused_speedup"] = round(results["fused"]["samples_per_sec"] / base, 3)
    if pallas_engages:
        r["pallas_samples_per_sec"] = round(
            results["pallas"]["samples_per_sec"], 1)
        r["pallas_speedup"] = round(
            results["pallas"]["samples_per_sec"] / base, 3)
    else:
        r["pallas_samples_per_sec"] = None
        r["pallas_speedup"] = None
    return r


def _bench_lm(model: str, batch: int, iters: int, ksteps: int,
              warmup: int) -> dict:
    """Shared LM measurement recipe: one-hot [B, T, V] next-token batches
    through the K-step multistep path (used by the transformer and MoE
    benches so the staging/sync methodology cannot diverge)."""
    conf, xs, ys, graph = flagship_setup(model, batch, ksteps)
    r = _measure_multistep(conf, xs, ys, iters, warmup, graph=graph)
    r["tokens_per_sec"] = r["samples_per_sec"] * LM_SEQ
    return r


def bench_transformer(batch: int, iters: int, ksteps: int,
                      warmup: int = 2, sharding: str = None) -> dict:
    """Decoder-only transformer LM over the flash-attention kernel
    (geometry fixed by flagship_setup: LM_VOCAB x LM_SEQ)."""
    if sharding:
        r = _bench_sharded_fit("transformer", batch, iters, ksteps, sharding,
                               warmup)
        r["tokens_per_sec"] = r["samples_per_sec"] * LM_SEQ
        return r
    return _bench_lm("transformer", batch, iters, ksteps, warmup)


def bench_moe(batch: int, iters: int, ksteps: int, warmup: int = 2) -> dict:
    """Switch-style MoE LM (residual attention + top-1 expert FFN blocks,
    load-balance aux loss included in the trained objective; geometry fixed
    by flagship_setup)."""
    return _bench_lm("moe", batch, iters, ksteps, warmup)


def bench_word2vec(batch: int, iters: int, ksteps: int, warmup: int = 2,
                   vocab: int = None, dim: int = 100,
                   negative: int = 5) -> dict:
    """SkipGram negative-sampling pair-kernel throughput (BASELINE config 4).

    Measures the jitted pair update the reference measures as words/sec in
    Word2Vec fit (reference SkipGram.java iterateSample): K scanned batches
    of skip-gram pairs per host dispatch, 5 negatives each.
    """
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nlp.learning import PairBatch, make_train_step

    from deeplearning4j_tpu.nlp import learning

    # DL4J_W2V_VOCAB: sweep vocab from the capture harness (the dense/scatter
    # crossover is vocab-dependent — dense rewrites the whole V x D table
    # per chunk; see nlp/learning.DENSE_UPDATE_MAX_VOCAB)
    vocab = vocab or int(os.environ.get("DL4J_W2V_VOCAB", "10000"))
    step = make_train_step(use_hs=False, negative=negative)
    # A/B twin: the opposite embedding-update path (dense one-hot matmul vs
    # XLA scatter) so one record carries both on-chip numbers
    auto_dense = learning.resolve_dense_update(vocab)
    step_alt = make_train_step(use_hs=False, negative=negative,
                               dense_update=not auto_dense)
    rng = np.random.default_rng(0)
    syn0 = jnp.asarray(rng.normal(size=(vocab, dim)).astype(np.float32) * 0.01)
    syn1 = jnp.zeros((1, dim), jnp.float32)  # HS table unused (negative sampling)
    syn1neg = jnp.zeros((vocab, dim), jnp.float32)
    cum_table = jnp.asarray((np.arange(1, vocab + 1) / vocab).astype(np.float32))

    def mk(shape, hi):
        return jnp.asarray(rng.integers(0, hi, shape).astype(np.int32))

    batches = PairBatch(
        ctx=mk((ksteps, batch, 1), vocab),
        ctx_mask=jnp.ones((ksteps, batch, 1), jnp.float32),
        target=mk((ksteps, batch), vocab),
        points=jnp.zeros((ksteps, batch, 1), jnp.int32),
        codes=jnp.zeros((ksteps, batch, 1), jnp.float32),
        code_mask=jnp.zeros((ksteps, batch, 1), jnp.float32),
        pair_mask=jnp.ones((ksteps, batch), jnp.float32),
        update_dest=mk((ksteps, batch, 1), vocab),
    )
    keys = jax.random.split(jax.random.PRNGKey(0), ksteps)

    def make_multi(stepfn):
        def multi(syn0, syn1, syn1neg, batches, keys):
            def body(carry, inp):
                s0, s1, sn = carry
                b, k = inp
                s0, s1, sn = stepfn(s0, s1, sn, cum_table, b,
                                    jnp.float32(0.025), k)
                return (s0, s1, sn), None

            carry, _ = jax.lax.scan(body, (syn0, syn1, syn1neg),
                                    (batches, keys))
            return carry

        return jax.jit(multi, donate_argnums=(0, 1, 2))

    def time_path(jit_multi, s0, s1, sn):
        for _ in range(warmup):
            s0, s1, sn = jit_multi(s0, s1, sn, batches, keys)
        float(s0[0, 0])  # hard sync: host read (see module docstring)
        t0 = time.perf_counter()
        for _ in range(iters):
            s0, s1, sn = jit_multi(s0, s1, sn, batches, keys)
        float(s0[0, 0])  # chain-forcing host read through donated buffers
        return time.perf_counter() - t0

    jit_multi = make_multi(step)
    # scan body counted once by cost analysis (see _xla_flops) -> x ksteps
    flops_per_dispatch = ksteps * _xla_flops(jit_multi, syn0, syn1, syn1neg,
                                             batches, keys)
    # copies BEFORE timing: both paths donate their input buffers
    alt0, alt1, altn = syn0.copy(), syn1.copy(), syn1neg.copy()
    dt = time_path(jit_multi, syn0, syn1, syn1neg)
    dt_alt = time_path(make_multi(step_alt), alt0, alt1, altn)
    dense_dt, scatter_dt = (dt, dt_alt) if auto_dense else (dt_alt, dt)
    flops_per_sec = flops_per_dispatch * iters / dt if flops_per_dispatch else 0.0
    pairs = batch * ksteps * iters
    return {
        "samples_per_sec": pairs / dt,
        "step_time_ms": dt / (iters * ksteps) * 1000,
        "batch": batch, "iters": iters, "ksteps": ksteps,
        "tflops_per_sec": round(flops_per_sec / 1e12, 4),
        "mfu": round(flops_per_sec / PEAK_FLOPS, 6),
        "update_path": "dense" if auto_dense else "scatter",
        "dense_pairs_per_sec": round(pairs / dense_dt, 1),
        "scatter_pairs_per_sec": round(pairs / scatter_dt, 1),
        "dense_speedup": round(scatter_dt / dense_dt, 3),
    }


def bench_attention(batch: int, iters: int, ksteps: int, warmup: int = 2,
                    seq: int = None, heads: int = 8, dim: int = 64) -> dict:
    """flash_attention (Pallas) vs the identical XLA math, fwd+bwd, causal.

    Reports both paths' timings so one BASELINE.md line can say which path ran
    on the chip and its speedup (VERDICT round-1 item 3). `value` is the
    tokens/sec of whichever path `use_pallas()` selects in production.
    """
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops import pallas_kernels as pk

    # DL4J_ATTN_SEQ: sweep the sequence length from the capture harness (the
    # pallas-vs-XLA crossover is seq-dependent; see FLASH_MIN_SEQ)
    seq = seq or int(os.environ.get("DL4J_ATTN_SEQ", "2048"))
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    shape = (batch, seq, heads, dim)
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)

    def time_path(fn, want_flops: bool = False):
        def loss(q, k, v):
            def body(c, _):
                o = fn(c, k, v)
                return o, jnp.float32(0)
            o, _ = jax.lax.scan(body, q, None, length=ksteps)
            return jnp.sum(o * o)

        g = jax.jit(jax.grad(loss))
        # model flops are taken from the XLA path only: the Pallas program's
        # flops hide inside a custom call XLA can't cost, but the math is
        # identical, so the XLA count is the honest numerator for both paths.
        # Cost analysis counts the K-step scan body once (see _xla_flops), so
        # the count is already per-step — no division by ksteps.
        flops = _xla_flops(g, q, k, v) if want_flops else 0.0
        out = g(q, k, v)
        float(jnp.ravel(out)[0])  # hard sync (see module docstring)
        for _ in range(warmup - 1):
            out = g(q, k, v)
        float(jnp.ravel(out)[0])
        t0 = time.perf_counter()
        for _ in range(iters):
            out = g(q, k, v)
        float(jnp.ravel(out)[0])
        return (time.perf_counter() - t0) / (iters * ksteps), flops

    # the XLA twin materializes [B, H, T, T] scores; at long-context lengths
    # that alone exceeds HBM (16k: ~64 GiB vs 16 GB on v5e), so past a
    # score-bytes budget only the flash path runs and model flops come from
    # the standard analytic count instead of XLA cost analysis
    xla_score_bytes = 4 * batch * heads * seq * seq * 4  # fwd+bwd tiles, f32
    xla_feasible = xla_score_bytes < 8 * 1024 ** 3
    pallas_engaged = pk.use_pallas()
    if xla_feasible:
        t_xla, flops_per_step = time_path(
            lambda q, k, v: pk._attention_xla(q, k, v, True), want_flops=True)
    else:
        t_xla = None
        # fwd: QK^T + PV = 2 matmuls of 2*B*H*T^2*D flops; bwd ~2.5x fwd;
        # causal halves the realized work; x ksteps per dispatch
        flops_per_step = 3.5 * 2 * 2 * batch * heads * seq * seq * dim / 2 \
            * ksteps
    t_pallas = (time_path(lambda q, k, v: pk.flash_attention(q, k, v, True))[0]
                if pallas_engaged else None)

    t_prod = t_pallas if pallas_engaged else t_xla
    if t_prod is None:
        raise RuntimeError(
            f"seq {seq}: XLA attention infeasible ({xla_score_bytes >> 30} "
            "GiB scores) and pallas not engaged — nothing to measure")
    rec = {
        "samples_per_sec": batch * seq / t_prod,
        "step_time_ms": t_prod * 1000,
        "batch": batch, "iters": iters, "ksteps": ksteps,
        "seq": seq, "heads": heads, "head_dim": dim,
        "pallas_engaged": pallas_engaged,
        "xla_ms": round(t_xla * 1000, 3) if t_xla is not None else None,
        "pallas_ms": (round(t_pallas * 1000, 3)
                      if t_pallas is not None else None),
        "pallas_speedup": (round(t_xla / t_pallas, 3)
                           if (t_xla and t_pallas) else None),
        "flops_source": "xla_cost" if xla_feasible else "analytic",
    }

    # DL4J_FLASH_SWEEP=1: time the pallas kernel across tile configs so one
    # relay window finds the best DL4J_FLASH_BLK_Q/K for this chip (VERDICT
    # round-3 item 2's "tile sweep" candidate). Globals are read at trace
    # time; each timing call builds a fresh jit program.
    if pallas_engaged and os.environ.get("DL4J_FLASH_SWEEP") == "1":
        rec.update(_sweep_tiles(
            lambda: time_path(
                lambda q, k, v: pk.flash_attention(q, k, v, True))[0],
            seq))
    flops_per_sec = flops_per_step / t_prod if flops_per_step else 0.0
    rec["tflops_per_sec"] = round(flops_per_sec / 1e12, 4)
    rec["mfu"] = round(flops_per_sec / PEAK_FLOPS, 6)
    return rec


def _sweep_tiles(time_once, seq: int) -> dict:
    """Sweep flash tile configs through ``time_once`` (which must read the
    module tile globals at trace time). Per-config failures (e.g. VMEM
    overflow) are isolated into the record — this runs unattended in the
    auto-capture window and must never kill the surrounding bench."""
    from deeplearning4j_tpu.ops import pallas_kernels as pk

    sweep = {}
    saved = pk._BLK_Q, pk._BLK_K
    for bq, bk in ((64, 128), (128, 128), (128, 256), (256, 128),
                   (256, 256), (128, 512)):
        if seq % bq or seq % bk:
            continue
        pk._BLK_Q, pk._BLK_K = bq, bk
        try:
            sweep[f"{bq}x{bk}"] = round(time_once() * 1000, 3)
        except Exception as e:
            sweep[f"{bq}x{bk}"] = f"error: {e}"[:100]
        finally:
            pk._BLK_Q, pk._BLK_K = saved
    out = {"tile_sweep_ms": sweep}
    timed = {k: v for k, v in sweep.items() if isinstance(v, float)}
    if timed:
        best = min(timed, key=timed.get)
        out["best_tiles"] = best
        out["best_tiles_ms"] = timed[best]
    return out


def _staging_phase_seconds() -> float:
    """Cumulative dl4j_fit_phase_seconds{phase="staging"} across fit loops.
    Under device prefetch the phase records only the consumer-visible wait
    for the already-staged batch, so the fit-bench A/B shows it collapse
    versus the synchronous path (the PR's acceptance signal; the full
    prefetch counters land in the --telemetry-out snapshot)."""
    from deeplearning4j_tpu.observability import global_registry
    fam = global_registry().snapshot().get("dl4j_fit_phase_seconds", {})
    return sum(s.get("sum", 0.0) for s in fam.get("series", [])
               if s.get("labels", {}).get("phase") == "staging")


def _fit_ab(net, data, warmup_data) -> dict:
    """Shared fit-API measurement: warm up, run the epoch once with
    synchronous staging (prefetch off), then once with the default
    double-buffered device prefetch — the headline number. Same net, same
    batches; params advance across both passes (throughput-only bench)."""
    net.fit_iterator(iter(warmup_data))  # compile + warm relay
    float(net.score_value)  # hard sync (see module docstring)

    net.prefetch_depth = 0
    s0 = _staging_phase_seconds()
    t0 = time.perf_counter()
    net.fit_iterator(iter(data))
    float(net.score_value)
    dt_sync = time.perf_counter() - t0
    staging_sync = _staging_phase_seconds() - s0

    net.prefetch_depth = type(net).prefetch_depth  # the shipped default
    s0 = _staging_phase_seconds()
    t0 = time.perf_counter()
    net.fit_iterator(iter(data))
    float(net.score_value)  # waits on the whole param-dependency chain
    dt = time.perf_counter() - t0
    return {
        "dt": dt,
        "staging_s_sync": round(staging_sync, 4),
        "staging_s_prefetch": round(_staging_phase_seconds() - s0, 4),
        "sync_step_time_ms_total": round(dt_sync * 1000, 2),
        "prefetch_speedup": round(dt_sync / dt, 3) if dt else None,
    }


def _sharded_param_bytes(rule_set: str):
    """Per-device sharded-param-bytes gauge value for one rule set (set by
    the compile seam when the wrapper's step compiles)."""
    from deeplearning4j_tpu.observability import global_registry
    fam = global_registry().snapshot().get(
        "dl4j_sharded_param_bytes_per_device", {})
    for s in fam.get("series", []):
        if s.get("labels", {}).get("rule_set") == rule_set:
            return int(s["value"])
    return None


def _bench_sharded_fit(model: str, batch: int, iters: int, ksteps: int,
                       sharding: str, warmup: int = 1) -> dict:
    """--sharding axis: the same flagship geometry trained through the
    partition-rule engine's compile seam (ParallelWrapper.fit on a named
    mesh) instead of the single-device path. One record per rule set so
    bench_log.jsonl carries per-mode samples/s AND the per-device param
    footprint the rule set actually achieved (the zero3 acceptance signal:
    ~1/N of the replicated bytes)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.parallel.mesh import build_mesh
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

    n_dev = len(jax.devices())
    if sharding == "dp_tp":
        if n_dev < 2 or n_dev % 2:
            raise ValueError(
                f"--sharding dp_tp needs an even device count, have {n_dev}")
        mesh = build_mesh({"data": n_dev // 2, "model": 2})
    else:
        mesh = build_mesh({"data": n_dev})

    rng = np.random.default_rng(0)
    if model == "fit_resnet50":
        from deeplearning4j_tpu.models.resnet import resnet50
        from deeplearning4j_tpu.nn.graph_network import ComputationGraph
        x = rng.normal(size=(batch, 224, 224, 3)).astype(np.float32)
        y = _onehot_batch(rng, batch, 1000)
        net = ComputationGraph(resnet50(n_classes=1000, image_size=224)).init()
    else:  # transformer
        from deeplearning4j_tpu.models.transformer import transformer_lm
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        ids = rng.integers(0, LM_VOCAB, (batch, LM_SEQ))
        x = y = np.eye(LM_VOCAB, dtype=np.float32)[ids]
        net = MultiLayerNetwork(transformer_lm(
            vocab_size=LM_VOCAB, width=256, n_layers=4, n_heads=4,
            max_len=LM_SEQ)).init()
    net.dispatch_ksteps = ksteps

    n_batches = iters * ksteps
    data = [DataSet(x, y) for _ in range(n_batches)]
    pw = (ParallelWrapper.builder(net).mesh(mesh).prefetch_buffer(2)
          .sharding(sharding).build())

    pw.fit(ListDataSetIterator(data[:max(1, warmup) * ksteps]))
    jax.block_until_ready(net.params_list)  # compile + warm relay
    t0 = time.perf_counter()
    pw.fit(ListDataSetIterator(data))
    jax.block_until_ready(net.params_list)
    dt = time.perf_counter() - t0
    return {
        "samples_per_sec": batch * n_batches / dt,
        "step_time_ms": dt / n_batches * 1000,
        "batch": batch, "iters": iters, "ksteps": ksteps,
        "tflops_per_sec": 0.0, "mfu": 0.0,
        "api": "ParallelWrapper.fit",
        "sharding": sharding,
        "mesh": {k: int(v) for k, v in zip(mesh.axis_names,
                                           mesh.devices.shape)},
        "param_bytes_per_device": _sharded_param_bytes(sharding),
    }


def bench_fit_resnet50(batch: int, iters: int, ksteps: int,
                       warmup: int = 1, sharding: str = None) -> dict:
    """The PRODUCTION fit(DataSetIterator) path on ResNet-50 — not the raw
    multistep kernel. Measures what a user of the documented API gets:
    host-staged numpy batches, K-step grouping + stacking inside
    fit_iterator, lazy score sync (VERDICT round-2 item 2's acceptance bar:
    within ~15% of the raw multistep bench)."""
    import jax.numpy as jnp

    if sharding:
        return _bench_sharded_fit("fit_resnet50", batch, iters, ksteps,
                                  sharding, warmup)

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models.resnet import resnet50
    from deeplearning4j_tpu.nn.graph_network import ComputationGraph

    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, 224, 224, 3)).astype(np.float32)
    y = _onehot_batch(rng, batch, 1000)
    conf = resnet50(n_classes=1000, image_size=224)
    net = ComputationGraph(conf).init()
    net.dispatch_ksteps = ksteps
    from deeplearning4j_tpu.common import get_policy
    if get_policy().compute_dtype == jnp.bfloat16:
        # compute casts to bf16 anyway; halve the host->device wire bytes
        net.stage_dtype = jnp.bfloat16
    n_batches = iters * ksteps
    data = [DataSet(x, y) for _ in range(n_batches)]

    ab = _fit_ab(net, data, data[:warmup * ksteps])
    dt = ab.pop("dt")
    return {
        "samples_per_sec": batch * n_batches / dt,
        "step_time_ms": dt / n_batches * 1000,
        "batch": batch, "iters": iters, "ksteps": ksteps,
        "tflops_per_sec": 0.0, "mfu": 0.0,  # same program as resnet50 bench
        "api": "ComputationGraph.fit_iterator",
        **ab,
    }


def bench_fit_lenet(batch: int, iters: int, ksteps: int,
                    warmup: int = 1) -> dict:
    """Production MultiLayerNetwork.fit_iterator throughput on LeNet."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models.lenet import lenet_mnist
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, 784)).astype(np.float32)
    y = _onehot_batch(rng, batch, 10)
    net = MultiLayerNetwork(lenet_mnist()).init()
    net.dispatch_ksteps = ksteps
    from deeplearning4j_tpu.common import get_policy
    if get_policy().compute_dtype == jnp.bfloat16:
        net.stage_dtype = jnp.bfloat16  # halve wire bytes (see resnet50 fit)
    n_batches = iters * ksteps
    data = [DataSet(x, y) for _ in range(n_batches)]

    ab = _fit_ab(net, data, data[:warmup * ksteps])
    dt = ab.pop("dt")
    return {
        "samples_per_sec": batch * n_batches / dt,
        "step_time_ms": dt / n_batches * 1000,
        "batch": batch, "iters": iters, "ksteps": ksteps,
        "tflops_per_sec": 0.0, "mfu": 0.0,
        "api": "MultiLayerNetwork.fit_iterator",
        **ab,
    }


def bench_serve(batch, iters, ksteps, serve_qps=None, serve_latency_ms=None,
                serve_batching=None, serve_quant=None,
                serve_replicas=None, serve_sharding=None,
                compile_cache=None, decode_kv=None, decode_page_size=None,
                decode_spec_draft=None, serve_tracing=None,
                serve_autoscale=None):
    """Micro-batching A/B on the serving engine (ISSUE 9 headline).

    Unlike the fit benches this is fully CPU-measurable: the win is
    dispatch amortization, not MXU width. The harness first calibrates the
    UNBATCHED saturation point (closed-loop peak through the real HTTP
    stack), then offers 1.5x that rate open-loop to both configurations —
    so "unbatched saturates" holds on any host without hand-tuned QPS —
    and reports the batched achieved throughput as the headline. The full
    A/B record (p50/p99, achieved QPS, batch occupancy, recompile count)
    is appended to scripts/serve_load.jsonl next to bench_log, and
    steady-state health is pinned by recompiles == bucket count.

    Round 11 adds the DECODE section: the token-streaming A/B
    (``run_decode_ab`` on a char-RNN) at one fixed offered sessions/sec
    for every phase — iteration-level continuous batching vs static
    request-level batching, and int8 weight-only decode vs dense. The
    ``serve_batching``/``serve_quant`` axes pick which phase supplies the
    row's decode_tokens_per_sec / decode_ttft_p99_ms numbers
    (config-distinct: a static or int8 capture must never stand in for
    the continuous dense row), and the cross-phase ratios ride along.

    Round 12 adds the REPLICA SCALING section: QPS-vs-replicas through the
    least-queue-depth router (``run_replica_ab``) at equal offered load,
    calibrated off the single-replica batched saturation point. The
    ``serve_replicas``/``serve_sharding`` axes are config-distinct; with
    ``serve_sharding="dp_tp"`` each replica pins its params sharded over
    its own mesh slice (the parent driver forces an 8-device CPU host
    platform for sharded rows, like ps_async). Per-replica steady-state
    health is pinned by recompiles == bucket count PER replica.

    Round 15 adds the TIME-TO-READY section: wall time of one full
    registration with parallel AOT warmup over every micro-batch bucket up
    to 16, cold (executable cache off — every bucket is an XLA compile)
    vs warm (every bucket deserialized from the compile cache). The warm
    number is what an elastic respawn or replica spawn actually pays; the
    ``compile_cache`` axis picks which one is the row's headline
    ``time_to_ready_s``.

    Round 17 adds the TRACING OVERHEAD section: the same warm MicroBatcher
    submit loop timed with the trace store disabled (every span a no-op
    singleton) vs enabled at 100% sampling, reported as
    ``trace_overhead_pct`` — the serve-path cost of always-on request
    tracing, budgeted at <= 2% by the tier-1 contract test. The
    ``serve_tracing`` axis is config-distinct (an untraced capture never
    stands in for the tracing-on default row).

    Round 18 adds the AUTOSCALE section (``serve_autoscale="on"``): the
    open-loop ramp A/B (``run_ramp_ab``) — a 10x offered-load swing
    against the SLO-driven autoscaled fleet vs a static fleet sized to
    the autoscaled run's time-weighted average replica count. The row
    carries ``ramp_slo_violation_seconds_auto/static`` (the acceptance
    floor), ``ramp_lost_requests`` (drain-without-loss scale-in) and
    ``ramp_scale_out_latency_s`` (warm-path decision-to-routable). Off
    by default: the ramp costs ~15s of wall clock.
    """
    import numpy as np

    from deeplearning4j_tpu.keras_server import (InferenceServer,
                                                 ModelRegistry)
    from deeplearning4j_tpu.keras_server.loadgen import (
        run_ab, run_closed_loop, run_closed_loop_proc)
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    # deliberately small: serving capacity on tiny per-request batches is
    # dispatch-overhead-bound, which is exactly what micro-batching
    # amortizes; a wide model just re-measures matmul FLOPs
    n_in, hidden, n_out = 16, 128, 8
    conf = (NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.1).updater("adam")
            .weight_init("xavier")
            .list()
            .layer(DenseLayer(n_in=n_in, n_out=hidden, activation="relu"))
            .layer(DenseLayer(n_in=hidden, n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_in=hidden, n_out=n_out, loss="mcxent",
                               activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()
    example = np.random.default_rng(0).normal(
        size=(1, n_in)).astype(np.float32)

    if serve_qps:
        qps = float(serve_qps)
        unbatched_peak = None
    else:
        # calibrate: unbatched closed-loop peak (client out-of-process,
        # like the measured phases) = the saturation point
        registry = ModelRegistry()
        registry.register("serve_mlp", net, version="cal")
        cal = InferenceServer(registry, max_batch=1, max_latency_s=0.0,
                              max_queue=512).start()
        try:
            run_closed_loop(cal.port, "serve_mlp", example, workers=1,
                            requests_per_worker=8)  # warm the compile
            peak = run_closed_loop_proc(cal.port, "serve_mlp",
                                        example.shape, workers=8,
                                        requests_per_worker=150)
        finally:
            cal.stop()
        unbatched_peak = peak["achieved_qps"]
        qps = max(50.0, round(1.5 * unbatched_peak, 1))

    record_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts",
        "serve_load.jsonl")
    rec = run_ab(net, model="serve_mlp", qps=qps,
                 duration_s=max(float(iters), 1.0), max_batch=batch,
                 max_latency_s=(serve_latency_ms or 4.0) / 1e3,
                 max_queue=2048, example=example, record_path=record_path)
    batched, unbatched = rec["batched"], rec["unbatched"]

    # decode section: continuous-vs-static + int8-vs-dense token streaming
    from deeplearning4j_tpu.keras_server.loadgen import run_decode_ab
    from deeplearning4j_tpu.models.char_rnn import char_rnn_lstm
    dec_net = MultiLayerNetwork(char_rnn_lstm(32, hidden=64, layers=2)).init()
    drec = run_decode_ab(dec_net, model="bench_serve_decode", slots=8,
                         n_sessions=256, record_path=record_path)
    serve_batching = serve_batching or "continuous"
    serve_quant = serve_quant or "none"
    phase = (drec["int8"] if serve_quant == "int8"
             else drec[serve_batching])
    decode = {
        "serve_batching": serve_batching,
        "serve_quant": serve_quant,
        "decode_tokens_per_sec": phase["tokens_per_sec"],
        "decode_ttft_p99_ms": phase["ttft_p99_ms"],
        "decode_offered_sps": drec["offered_sps"],
        "decode_slot_occupancy": phase["mean_occupancy"],
        "decode_recompiles": phase["recompiles"],
        "decode_bucket_count": phase["bucket_count"],
        "decode_speedup": drec["tokens_per_sec_ratio"],
        "decode_ttft_p99_improvement": drec["ttft_p99_ratio"],
        "int8_prob_drift": drec["int8_vs_dense"]["mean_prob_drift"],
        "int8_top1_agreement": drec["int8_vs_dense"]["top1_agreement"],
        "int8_param_bytes_ratio": drec["int8_vs_dense"]["param_bytes_ratio"],
    }

    # paged KV memory plane + speculative decode section (ISSUE 16): the
    # dense-vs-paged A/B runs at EQUAL device state bytes (the pool is
    # sized to the dense engine's KV block, minus the trash page), so
    # sessions_ratio is the sessions-per-chip headline, and the spec A/B
    # measures the draft-verify speedup at whatever acceptance the tiny
    # draft earns — both streams pinned bitwise against the dense/greedy
    # oracle inside the harness itself
    from deeplearning4j_tpu.keras_server.loadgen import (run_paged_ab,
                                                         run_spec_ab)
    from deeplearning4j_tpu.models.transformer import transformer_lm
    decode_kv = decode_kv or "paged"
    page_size = int(decode_page_size or 16)
    spec_draft = decode_spec_draft or "tiny"
    tf_net = MultiLayerNetwork(transformer_lm(
        vocab_size=32, width=32, n_layers=2, n_heads=2, max_len=128,
        seed=5)).init()
    prec = run_paged_ab(tf_net, model="bench_serve_paged", dense_slots=4,
                        max_context=128, page_size=page_size,
                        n_sessions=24, max_new_tokens=16,
                        record_path=record_path)
    paged_sec = {
        "decode_kv": decode_kv,
        "decode_page_size": page_size,
        "decode_spec_draft": spec_draft,
        "paged_sessions_ratio": prec["sessions_ratio"],
        "paged_state_bytes": prec["paged"]["state_bytes"],
        "dense_state_bytes": prec["dense"]["state_bytes"],
        "paged_bitwise_equal": prec["bitwise_equal"],
        "paged_tokens_per_sec": prec[decode_kv]["tokens_per_sec"],
        "paged_prefix_share_ratio": prec["paged"]["prefix_share_ratio"],
        "spec_tokens_per_sec": None,
        "spec_speedup": None,
        "spec_acceptance": None,
        "spec_bitwise_equal": None,
    }
    if spec_draft != "none":
        draft_net = MultiLayerNetwork(transformer_lm(
            vocab_size=32, width=16, n_layers=1, n_heads=2, max_len=128,
            seed=9)).init()
        srec = run_spec_ab(tf_net, draft_net, model="bench_serve_spec",
                           slots=4, max_context=128, n_sessions=12,
                           max_new_tokens=16, record_path=record_path)
        paged_sec.update({
            "spec_tokens_per_sec": srec["spec"]["tokens_per_sec"],
            "spec_speedup": srec["tokens_per_sec_ratio"],
            "spec_acceptance": srec["acceptance"],
            "spec_bitwise_equal": srec["bitwise_equal"],
        })

    # replica scaling section: N pinned programs behind the least-queue
    # router. Wider than the dispatch-bound A/B model on purpose — replica
    # scale-out multiplies DEVICE capacity, so the scaled resource must be
    # device time; on the tiny MLP above both phases would sit on the same
    # host-dispatch ceiling and the ratio would measure nothing
    from deeplearning4j_tpu.keras_server.loadgen import run_replica_ab
    n_rep = int(serve_replicas or 2)
    shard = None if serve_sharding in (None, "none") else serve_sharding
    rn_in, rhidden, rn_out = 64, 256, 8
    rconf = (NeuralNetConfiguration.builder()
             .seed(11).learning_rate(0.1).updater("adam")
             .weight_init("xavier")
             .list()
             .layer(DenseLayer(n_in=rn_in, n_out=rhidden, activation="relu"))
             .layer(DenseLayer(n_in=rhidden, n_out=rhidden,
                               activation="relu"))
             .layer(DenseLayer(n_in=rhidden, n_out=rhidden,
                               activation="relu"))
             .layer(OutputLayer(n_in=rhidden, n_out=rn_out, loss="mcxent",
                                activation="softmax"))
             .build())
    rep_net = MultiLayerNetwork(rconf).init()
    rep_example = np.random.default_rng(1).normal(
        size=(1, rn_in)).astype(np.float32)
    # calibrate the single-replica BATCHED saturation point, then offer 2x
    # it to both phases: the baseline saturates, the scaled phase shows
    # its real headroom at the same offered load
    registry = ModelRegistry()
    registry.register("serve_rep", rep_net, version="cal")
    cal = InferenceServer(registry, max_batch=batch,
                          max_latency_s=(serve_latency_ms or 4.0) / 1e3,
                          max_queue=2048).start()
    try:
        run_closed_loop(cal.port, "serve_rep", rep_example, workers=2,
                        requests_per_worker=8)
        rpeak = run_closed_loop_proc(cal.port, "serve_rep",
                                     rep_example.shape, workers=8,
                                     requests_per_worker=100)
    finally:
        cal.stop()
    rep_qps = max(50.0, round(2.0 * rpeak["achieved_qps"], 1))
    rrec = run_replica_ab(
        rep_net, model="serve_rep", replicas=n_rep, sharding=shard,
        qps=rep_qps, duration_s=max(float(iters), 1.0), max_batch=batch,
        max_latency_s=(serve_latency_ms or 4.0) / 1e3, max_queue=4096,
        example=rep_example, record_path=record_path)
    replica_sec = {
        "serve_replicas": n_rep,
        "serve_sharding": serve_sharding or "none",
        "replica_offered_qps": rep_qps,
        "replica_qps_1": rrec["replicas_1"]["achieved_qps"],
        "replica_qps_n": rrec["replicas_n"]["achieved_qps"],
        "replica_speedup": rrec["replica_speedup"],
        "replica_recompiles_match_buckets":
            rrec["recompiles_match_buckets"],
    }

    # time-to-ready section: cold vs warm-start pin with full bucket
    # warmup. Three pins against a fresh store: cache off (baseline XLA
    # compiles), cache on (populates the store, untimed headline-wise),
    # cache on again (the measured warm pin — every bucket resolves via
    # deserialize_and_load, which is what a respawn/spawn pays).
    import tempfile

    ready_max_batch = 16
    compile_cache = compile_cache or "on"

    def _pin_once() -> float:
        reg = ModelRegistry(warmup_max_batch=ready_max_batch)
        fresh = MultiLayerNetwork(conf).init()
        t0 = time.perf_counter()
        reg.register("ready_mlp", fresh)
        return time.perf_counter() - t0

    def _with_cache(value, directory, fn):
        saved = {k: os.environ.get(k)
                 for k in ("DL4J_COMPILE_CACHE", "DL4J_COMPILE_CACHE_DIR")}
        os.environ["DL4J_COMPILE_CACHE"] = value
        os.environ["DL4J_COMPILE_CACHE_DIR"] = directory
        try:
            return fn()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    with tempfile.TemporaryDirectory(prefix="dl4j-xc-bench-") as xcdir:
        cold_s = _with_cache("0", xcdir, _pin_once)
        _with_cache("1", xcdir, _pin_once)   # populate the store
        warm_s = _with_cache("1", xcdir, _pin_once)
    ready = {
        "compile_cache": compile_cache,
        "warmup_max_batch": ready_max_batch,
        "warmup_buckets": len(ModelRegistry.warmup_buckets(ready_max_batch)),
        "time_to_ready_cold_s": round(cold_s, 4),
        "time_to_ready_warm_s": round(warm_s, 4),
        "time_to_ready_s": round(
            cold_s if compile_cache == "off" else warm_s, 4),
        "time_to_ready_speedup": (round(cold_s / warm_s, 2)
                                  if warm_s > 0 else None),
    }
    # tracing overhead section: A/B the in-process submit path (registry +
    # MicroBatcher, no HTTP — socket jitter would swamp a 2% signal) with
    # the trace store disabled vs enabled at 100% sampling. Warm first so
    # neither phase pays the bucket compile.
    from deeplearning4j_tpu.observability.tracing import (
        TraceStore, global_trace_store, set_global_trace_store, trace_span)

    serve_tracing = serve_tracing or "on"
    tr_registry = ModelRegistry()
    tr_registry.register("trace_mlp", MultiLayerNetwork(conf).init())
    tr_example = np.random.default_rng(3).normal(
        size=(1, n_in)).astype(np.float32)
    from deeplearning4j_tpu.keras_server.batcher import MicroBatcher
    tr_batcher = MicroBatcher(tr_registry, max_batch=8,
                              max_latency_s=0.0005, max_queue=1024)
    tr_requests = 400

    def _trace_phase() -> float:
        # each submit runs under a per-request root span, mirroring the
        # HTTP handler's `http /v1/predict` root (admission + batch.queue
        # become children, not root traces of their own); with the store
        # disabled trace_span returns the no-op singleton so the off
        # phase pays nothing
        for f in [tr_batcher.submit("trace_mlp", tr_example)
                  for _ in range(32)]:
            f.result(timeout=30)  # warm: compile + settle the dispatcher
        t0 = time.perf_counter()
        for _ in range(tr_requests // 8):
            futs = []
            for _ in range(8):
                with trace_span("bench.request"):
                    futs.append(tr_batcher.submit("trace_mlp", tr_example))
            for f in futs:
                f.result(timeout=30)
        return time.perf_counter() - t0

    saved_store = global_trace_store()
    try:
        set_global_trace_store(TraceStore(enabled=False))
        trace_off_s = _trace_phase()
        set_global_trace_store(
            TraceStore(enabled=True, sample=1.0, capacity=256))
        trace_on_s = _trace_phase()
    finally:
        set_global_trace_store(saved_store)
        tr_batcher.close()
    # the in-process A/B isolates the absolute tracing cost per request
    # (HTTP jitter would swamp it); the pct expresses that cost against
    # the REAL serve-path request latency from the batched phase above
    trace_us = max(0.0, (trace_on_s - trace_off_s) / tr_requests * 1e6)
    tr_p50_us = batched["p50_ms"] * 1e3
    trace_sec = {
        "serve_tracing": serve_tracing,
        "trace_cost_us_per_request": round(trace_us, 1),
        "trace_overhead_pct": (round(trace_us / tr_p50_us * 100.0, 2)
                               if tr_p50_us > 0 else None),
    }

    # autoscale ramp section: only when armed — the three-segment ramp
    # plus the static control is the most expensive serve phase by far
    serve_autoscale = serve_autoscale or "off"
    autoscale_sec = {"serve_autoscale": serve_autoscale}
    if serve_autoscale == "on":
        from deeplearning4j_tpu.keras_server.loadgen import run_ramp_ab
        ramp_low = max(5.0, round(0.15 * unbatched_peak, 1))
        ramp = run_ramp_ab(
            net, model="ramp_mlp", qps_low=ramp_low,
            qps_high=10.0 * ramp_low, segment_s=2.0,
            slo_ms=float(os.environ.get("DL4J_SLO_P99_MS", "250")),
            min_replicas=1, max_replicas=4, cooldown_s=1.0,
            interval_s=0.2, max_batch=batch, max_queue=64,
            example=example, workers=16, record_path=record_path)
        autoscale_sec.update({
            "ramp_qps_low": ramp["qps_low"],
            "ramp_qps_high": ramp["qps_high"],
            "ramp_avg_replicas_auto": ramp["avg_replicas_auto"],
            "ramp_static_replicas": ramp["static_replicas"],
            "ramp_slo_violation_seconds_auto":
                ramp["slo_violation_seconds_auto"],
            "ramp_slo_violation_seconds_static":
                ramp["slo_violation_seconds_static"],
            "ramp_lost_requests": ramp["lost_requests"],
            "ramp_scale_out_latency_s": ramp["scale_out_latency_s"],
            "ramp_scale_events": ramp["scale_events"],
            "ramp_auto_beats_static": ramp["auto_beats_static"],
        })

    return {
        "samples_per_sec": batched["achieved_qps"],  # headline: batched QPS
        "offered_qps": qps,
        "calibrated_unbatched_peak_qps": unbatched_peak,
        "unbatched_qps": unbatched["achieved_qps"],
        "batched_speedup": rec["batched_speedup"],
        "p50_ms_unbatched": unbatched["p50_ms"],
        "p99_ms_unbatched": unbatched["p99_ms"],
        "p50_ms_batched": batched["p50_ms"],
        "p99_ms_batched": batched["p99_ms"],
        "p99_improvement": rec["p99_improvement"],
        "batch_occupancy": batched["batch_occupancy"],
        "bucket_count": batched["bucket_count"],
        "recompiles": batched["recompiles"],
        "max_batch": batch,
        "serve_record": record_path,
        **decode,
        **paged_sec,
        **replica_sec,
        **ready,
        **trace_sec,
        **autoscale_sec,
        "api": "keras_server.InferenceServer /v1/predict + /v1/generate",
    }


class _StragglerIterator:
    """Sync-DP straggler model: the barrier waits for the slowest worker
    every step, so one k×-slow worker stalls EVERY iteration by its extra
    step time. Injected as a per-batch sleep in front of the fused sync
    step (a fused DP step has no per-worker thread to slow down)."""

    def __init__(self, batches, stall_s: float):
        self._batches = batches
        self._stall = stall_s

    def reset(self):
        pass

    def __iter__(self):
        for ds in self._batches:
            time.sleep(self._stall)
            yield ds


def _transport_push_ab(base_params, workers: int, rounds: int = 60) -> dict:
    """Push-window throughput twin for the host data plane (ISSUE 14): W
    concurrent workers hammering pull+push rounds of the flat LeNet param
    vector through a real TCP frontend, once over plain TCP frames and once
    over the shared-memory rings. Same server code, same arithmetic — the
    ratio is pure byte-plane cost. Staleness cap is effectively off so
    every push applies (throughput, not convergence, is under test)."""
    import threading

    from deeplearning4j_tpu.parallel import ps_transport as pst
    from deeplearning4j_tpu.parallel.param_server import (ParameterServer,
                                                          flatten_tree)

    flat, _ = flatten_tree(base_params)
    delta = np.zeros_like(flat)

    def run(kind: str):
        srv = ParameterServer([flat.copy()], staleness_cap=1 << 40)
        fe = pst.ParameterServerTcpFrontend(srv).start()
        cls = pst.ShmTransport if kind == "shm" else pst.TcpTransport
        transports = [cls(("127.0.0.1", fe.port)) for _ in range(workers)]
        try:
            for t in transports:
                t.pull()  # connect (and for shm: negotiate) untimed
            if kind == "shm" and not all(
                    t.shm_active for t in transports):
                return None  # negotiation refused (no /dev/shm): no number
            barrier = threading.Barrier(workers + 1)

            def work(t):
                v, _ = t.pull()
                barrier.wait()
                for _ in range(rounds):
                    v = t.push(delta, v).version
                barrier.wait()

            threads = [threading.Thread(target=work, args=(t,), daemon=True)
                       for t in transports]
            for th in threads:
                th.start()
            barrier.wait()
            t0 = time.perf_counter()
            barrier.wait()
            dt = time.perf_counter() - t0
            for th in threads:
                th.join(timeout=10.0)
            return workers * rounds / dt
        finally:
            for t in transports:
                t.close()
            fe.stop()

    tcp = run("tcp")
    shm = run("shm")
    return {
        "push_ab_workers": workers,
        "push_ab_rounds": rounds,
        "push_ab_param_bytes": int(flat.nbytes),
        "tcp_push_windows_per_sec": round(tcp, 1) if tcp else None,
        "shm_push_windows_per_sec": round(shm, 1) if shm else None,
        "shm_push_speedup": (round(shm / tcp, 3) if (tcp and shm) else None),
    }


def bench_ps_async(batch, iters, ksteps, ps_workers=None, ps_straggler=None,
                   ps_transport=None):
    """Straggler A/B: async parameter server vs the sync-DP barrier
    (ISSUE 10 headline). CPU-measured by design, like serve: the win is
    host-side orchestration (no per-step barrier), not MXU width — the
    parent driver forces JAX_PLATFORMS=cpu + an 8-device host platform so
    the sync phase gets a real data mesh on any box.

    Phase A (throughput + time-to-loss): one worker of W sleeps k× the
    median per-step delay. Sync = ParallelWrapper over a data mesh at equal
    worker count, stalled every step by the straggler's extra time (the
    barrier semantic); async = ParameterServerParallelWrapper with the same
    sleeps injected per worker thread — the straggler only slows its own
    pushes. Phase B (loss parity at equal samples): 2 separate-process TCP
    workers with bf16 delta compression vs a single-process sync-DP fit of
    the same LeNet on the same batches — 2 epochs each, so parity is
    measured at the label-noise plateau both paths converge to (comparing
    mid-descent would measure descent speed, not fidelity).

    ISSUE 14 adds the host-data-plane section: ``ps_transport`` picks the
    wire the phase-B workers ride ("tcp" frames or the "shm" rings), and
    every record carries the W-worker push-window throughput twin
    (``tcp_push_windows_per_sec`` / ``shm_push_windows_per_sec`` /
    ``shm_push_speedup``) so one row proves what the shared-memory plane
    buys at this worker count.
    """
    import jax

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.models.lenet import lenet_mnist
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.mesh import build_mesh
    from deeplearning4j_tpu.parallel.param_server import (
        ParameterServerParallelWrapper)
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

    W = int(ps_workers or 4)
    k = float(ps_straggler or 4.0)
    transport = ps_transport or "tcp"
    delay_s = 0.02  # median per-step worker delay; straggler sleeps k*this
    push_frequency, staleness_cap = 4, 8
    n_batches = iters * ksteps

    # learnable 10-class cluster data on the LeNet input shape, so the
    # time-to-loss and parity numbers track real convergence; 25% label
    # noise gives the loss an irreducible floor (~1.0 nats) both paths
    # plateau at — a relative parity gap near zero loss is meaningless
    rng = np.random.default_rng(0)
    means = rng.normal(0.0, 1.0, (10, 784)).astype(np.float32)
    data = []
    for _ in range(n_batches):
        lab = rng.integers(0, 10, batch)
        x = (means[lab] + rng.normal(0, 0.5, (batch, 784))).astype(np.float32)
        noisy = np.where(rng.random(batch) < 0.25,
                         rng.integers(0, 10, batch), lab)
        data.append(DataSet(x, np.eye(10, dtype=np.float32)[noisy]))
    gx = np.concatenate([d.features for d in data])
    gy = np.concatenate([d.labels for d in data])

    base = MultiLayerNetwork(lenet_mnist()).init()

    # --- phase A sync: the barrier pays the straggler's extra time per step
    sync_net = base.clone()
    mesh = build_mesh({"data": min(W, len(jax.devices()))})
    pw = ParallelWrapper(sync_net, prefetch=0, mesh=mesh)
    pw.fit(ListDataSetIterator(data[:2]))  # compile outside the timed loop
    t0 = time.perf_counter()
    pw.fit(_StragglerIterator(data, k * delay_s))  # barrier = slowest worker
    sync_dt = time.perf_counter() - t0
    sync_loss = float(sync_net.score(gx, gy))

    # --- phase A async: same sleeps per worker thread, no barrier
    async_net = base.clone()
    delays = [k * delay_s] + [delay_s] * (W - 1)
    ps = (ParameterServerParallelWrapper.builder(async_net)
          .workers(W).push_frequency(push_frequency)
          .staleness(staleness_cap).transport("inproc")
          .worker_delays(*delays).build())
    ps.fit(ListDataSetIterator(data[:2]))  # compile outside the timed loop
    t0 = time.perf_counter()
    ps.fit(ListDataSetIterator(data))
    async_dt = time.perf_counter() - t0
    async_loss = float(async_net.score(gx, gy))

    # --- phase B: 2-process TCP async vs single-process sync-DP, equal
    # samples from the same init (loss-parity proof; bf16 deltas on the wire)
    tcp_net = base.clone()
    # push_frequency 2 here: shorter windows keep wire staleness ~0-1 and
    # let the background puller rebase mid-window, which is what holds the
    # parity gap down (measured: 2.8% at pf=2 vs 4.6% at pf=4)
    tcp = (ParameterServerParallelWrapper.builder(tcp_net)
           .workers(2).push_frequency(2)
           .staleness(staleness_cap).transport(transport)
           .compression("bf16").build())
    t0 = time.perf_counter()
    tcp.fit(ListDataSetIterator(data), epochs=2)
    tcp_dt = time.perf_counter() - t0
    oracle = base.clone()
    oracle.fit_iterator(ListDataSetIterator(data), epochs=2)
    tcp_loss = float(tcp_net.score(gx, gy))
    sync_dp_loss = float(oracle.score(gx, gy))

    r = {
        "samples_per_sec": batch * n_batches / async_dt,
        "sync_samples_per_sec": batch * n_batches / sync_dt,
        "async_speedup": (batch * n_batches / async_dt)
        / (batch * n_batches / sync_dt),
        "async_time_s": async_dt, "sync_time_s": sync_dt,
        "async_loss": async_loss, "sync_loss": sync_loss,
        "workers": W, "straggler_factor": k,
        "straggler_base_delay_ms": delay_s * 1e3,
        "push_frequency": push_frequency, "staleness_cap": staleness_cap,
        "applied_pushes": ps.server.pushes,
        "rejected_pushes": ps.server.rejected,
        "tcp_workers": 2, "tcp_epochs": 2, "tcp_time_s": tcp_dt,
        "tcp_async_loss": tcp_loss, "sync_dp_loss": sync_dp_loss,
        "tcp_loss_gap": abs(tcp_loss / sync_dp_loss - 1.0),
        "tcp_worker_stats": tcp.worker_stats,
        "ps_transport": transport,
        "batch": batch, "iters": iters, "ksteps": ksteps,
        "api": "parallel.ParameterServerParallelWrapper",
        **_transport_push_ab(base.params_list, W),
    }
    _append_ps_ab("ps_async", r)
    return r


def _append_ps_ab(model: str, record: dict) -> None:
    """Append one PS A/B row to scripts/ps_ab.jsonl: the straggler record
    (ps_async, ISSUE 10) and the worker-kill record (elastic) accrete side
    by side so the fleet-health story is one file. Measurement log only —
    never read back for bench_log config matching."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "scripts", "ps_ab.jsonl")
    row = {"ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
           "model": model, "record": record}
    try:
        with open(path, "a") as f:
            f.write(json.dumps(row) + "\n")
    except OSError:  # lint: swallowed-exception-ok (read-only checkout must not fail the bench)
        pass


def _bench_elastic_once(batch, iters, ksteps, elastic_workers=None,
                        elastic_kill=None, ps_transport=None,
                        compile_cache_label=None):
    """Worker-kill A/B on the elastic trainer (ISSUE 13 headline):
    SIGKILL one of W separate-process workers mid-fit and measure the
    throughput dip plus the recovery time back to 90% of the pre-kill
    rate (lease expiry -> shard handoff -> replacement registers,
    restores from the PS, and resumes the shard at the committed broker
    offset). CPU-measured by design like ps_async: the number under test
    is host-side membership/handoff orchestration, not MXU width.

    Throughput proxy: the PS version counter advances once per applied
    push window (push_frequency steps x batch samples), sampled on a
    timeline thread; rates are versions/sec over a sliding window scaled
    to samples/sec. The kill fires when the fleet reaches
    ``elastic_kill`` of the expected total push windows.
    """
    import threading

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.elastic import ElasticTrainer

    W = int(elastic_workers or 4)
    kill_frac = float(elastic_kill if elastic_kill is not None else 0.5)
    transport = ps_transport or "tcp"
    push_frequency, delay_s = 4, 0.2
    n_batches = iters * ksteps

    # learnable 10-class cluster data so the loss trend stays meaningful
    rng = np.random.default_rng(0)
    means = rng.normal(0.0, 1.0, (10, 64)).astype(np.float32)
    data = []
    for _ in range(n_batches):
        lab = rng.integers(0, 10, batch)
        x = (means[lab] + rng.normal(0, 0.5, (batch, 64))).astype(np.float32)
        data.append(DataSet(x, np.eye(10, dtype=np.float32)[lab]))

    # the worker net is deliberately DEEP (46 dense layers, ~7ms/step):
    # a respawned replacement's recovery is dominated by its cold XLA
    # compile of the adam train step (~3s here, minutes for real models)
    # — exactly the tax the round-15 executable cache removes, so the
    # cold-vs-warm recovery A/B measures the mechanism and not the noise
    # floor of a sub-300ms toy compile. He init + adam keep a stack this
    # deep actually learning; no conv so process start itself stays fast
    # (it is part of the measured recovery)
    lb = (NeuralNetConfiguration.builder()
          .seed(12345).learning_rate(0.001).updater("adam")
          .weight_init("relu")
          .list()
          .layer(DenseLayer(n_in=64, n_out=128, activation="relu")))
    for _ in range(44):
        lb = lb.layer(DenseLayer(n_in=128, n_out=128, activation="relu"))
    conf = (lb.layer(OutputLayer(n_in=128, n_out=10, loss="mcxent",
                                 activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()

    trainer = (ElasticTrainer.builder(net)
               .workers(W).push_frequency(push_frequency)
               .staleness(8).lease_timeout(10.0)
               .respawn(True)
               .transport(transport)
               .worker_delays(*([delay_s] * W))
               .fit_timeout(180.0).build())

    # expected applied windows over the whole run; the kill fires at
    # kill_frac of that — "halfway" by work done, not wall time
    expected_versions = max(1, n_batches // push_frequency)
    kill_at = max(1, int(expected_versions * kill_frac))

    timeline = []  # (t, version) samples
    killed_at = [None]  # wall-clock instant of the SIGKILL

    def _observe() -> None:
        while trainer.server is None and not fit_done.is_set():
            time.sleep(0.01)
        while not fit_done.is_set():
            v = trainer.server.version
            timeline.append((time.perf_counter(), v))
            if (kill_frac > 0 and killed_at[0] is None and v >= kill_at
                    and trainer.chaos_kill(0)):
                killed_at[0] = time.perf_counter()
            time.sleep(0.25)

    fit_done = threading.Event()
    obs = threading.Thread(target=_observe, daemon=True,
                           name="elastic-bench-observer")
    obs.start()
    t0 = time.perf_counter()
    try:
        trainer.fit(ListDataSetIterator(data))
    finally:
        fit_done.set()
    fit_dt = time.perf_counter() - t0
    obs.join(timeout=2.0)

    # sliding-window rates (versions/sec over the trailing second),
    # scaled to samples/sec via window size x batch
    scale = push_frequency * batch

    def _rates(points):
        out = []
        for i in range(1, len(points)):
            j = i
            while j > 0 and points[i][0] - points[j - 1][0] < 1.0:
                j -= 1
            dt = points[i][0] - points[j][0]
            if dt > 0:
                out.append((points[i][0],
                            (points[i][1] - points[j][1]) / dt * scale))
        return out

    rates = _rates(timeline)
    dip_pct = recovery_s = None
    pre_rate = post_min = None
    if killed_at[0] is not None and rates:
        pre = [r for t, r in rates if t <= killed_at[0] and r > 0]
        post = [(t, r) for t, r in rates if t > killed_at[0]]
        if pre and post:
            pre_rate = float(np.median(pre))
            # recovery = first instant the rate is back at >=90% of the
            # pre-kill median AND stays there for a full second (push
            # windows are bursty; a single sample above the bar is noise,
            # not a respawned worker)
            recovery_s = fit_dt - (killed_at[0] - t0)  # worst case: never
            recovered_t = None
            for i, (t, r) in enumerate(post):
                if r < 0.9 * pre_rate:
                    continue
                hold = [q for u, q in post[i:] if u - t <= 1.0]
                if all(q >= 0.9 * pre_rate for q in hold):
                    recovery_s = t - killed_at[0]
                    recovered_t = t
                    break
            # the dip is what the fleet lost BETWEEN kill and recovery —
            # the end-of-run drain taper (shards finishing) must not
            # masquerade as preemption damage
            dip_end = recovered_t if recovered_t is not None \
                else killed_at[0] + 10.0
            dipped = [r for t, r in post if t <= dip_end]
            if dipped:
                post_min = min(dipped)
                dip_pct = max(0.0, (1.0 - post_min / pre_rate) * 100.0)

    st = trainer.stats
    r = {
        "samples_per_sec": batch * n_batches / fit_dt,
        "fit_time_s": fit_dt,
        "worker_loss_dip_pct": dip_pct,
        "recovery_seconds": recovery_s,
        "pre_kill_samples_per_sec": pre_rate,
        "post_kill_min_samples_per_sec": post_min,
        "workers": W, "kill_fraction": kill_frac, "killed_shard": 0,
        "kill_at_version": kill_at,
        "worker_step_delay_ms": delay_s * 1e3,
        "push_frequency": push_frequency,
        "published_batches": st["published"],
        "worker_steps": st["steps"],
        "handoffs": st["handoffs"], "fenced": st["fenced"],
        "lease_expiries": st["lease_expiries"], "joins": st["joins"],
        "final_loss": float(net.score(
            np.concatenate([d.features for d in data]),
            np.concatenate([d.labels for d in data]))),
        "ps_transport": transport,
        "compile_cache": compile_cache_label,
        "batch": batch, "iters": iters, "ksteps": ksteps,
        "api": "parallel.ElasticTrainer",
    }
    _append_ps_ab("elastic", r)
    return r


def bench_elastic(batch, iters, ksteps, elastic_workers=None,
                  elastic_kill=None, ps_transport=None, compile_cache=None):
    """Elastic worker-kill A/B, compile-cache-aware (round 15).

    The measured recovery window is compile-bound: the respawned worker
    process pays a cold XLA compile of the train step before its first
    push. With the executable cache on (the default), gen-0 workers
    persist their step executables and the respawn warm-loads from disk
    — so the run itself exercises the warm path. ``--compile-cache off``
    measures only the cold world; the default runs BOTH (cold first, in
    the same fresh store with the cache disabled) and reports the warm
    run's numbers as the headline with ``recovery_seconds_cold`` riding
    along for the A/B.
    """
    import tempfile

    mode = compile_cache or "on"

    def once(cache_on: str, directory: str, label: str):
        saved = {k: os.environ.get(k)
                 for k in ("DL4J_COMPILE_CACHE", "DL4J_COMPILE_CACHE_DIR")}
        os.environ["DL4J_COMPILE_CACHE"] = cache_on
        os.environ["DL4J_COMPILE_CACHE_DIR"] = directory
        try:
            return _bench_elastic_once(
                batch, iters, ksteps, elastic_workers=elastic_workers,
                elastic_kill=elastic_kill, ps_transport=ps_transport,
                compile_cache_label=label)
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    if mode == "off":
        with tempfile.TemporaryDirectory(prefix="dl4j-xc-bench-") as d:
            return once("0", d, "off")
    with tempfile.TemporaryDirectory(prefix="dl4j-xc-bench-") as d:
        cold = once("0", d, "off")
        warm = once("1", d, "on")
    r = dict(warm)
    r["compile_cache"] = "on"
    r["recovery_seconds_cold"] = cold["recovery_seconds"]
    r["samples_per_sec_cold"] = cold["samples_per_sec"]
    if warm.get("recovery_seconds") and cold.get("recovery_seconds"):
        r["recovery_improvement"] = round(
            1.0 - warm["recovery_seconds"] / cold["recovery_seconds"], 3)
    return r


def bench_ingest(batch, iters, ksteps, ingest_codec=None):
    """Native vs python ingest-decode A/B (ISSUE 14): MB/s turning broker
    frame payloads of raw record bytes into float32. ``batch`` is the
    record size in KB (default 4 — sample-sized: a CIFAR image is 3 KB),
    ``iters`` the timing repetitions (best-of wins: the number under test
    is decoder bandwidth, not scheduler noise on a shared host); records
    ride ~512 KB frames, ~128 MB total per rep.

    This is the consumer-side seam the ISSUE names: the python path is
    the per-record frombuffer/astype fallback — one GIL-bound numpy
    round-trip per record, fixed cost dominating at sample-sized
    records — while the native path decodes each frame's payload in ONE
    fused off-GIL pass (the batched decoder) and splits records as
    views. CPU-measured by design: host-side ingest, not MXU width.
    """
    from deeplearning4j_tpu import nativert

    codec = ingest_codec or "u8"
    record_kb = int(batch)
    rec_bytes = record_kb * 1024
    per_frame = max(1, (512 << 10) // rec_bytes)
    frame_bytes = per_frame * rec_bytes
    n_frames = max(1, (128 << 20) // frame_bytes)
    total_mb = n_frames * frame_bytes / (1 << 20)

    rng = np.random.default_rng(0)
    if codec == "u8":
        frames = [rng.integers(0, 256, frame_bytes,
                               dtype=np.uint8).tobytes()
                  for _ in range(n_frames)]
    else:
        width = nativert._INGEST_WIDTH[nativert.INGEST_CODECS[codec]]
        n = frame_bytes // width
        if codec == "bf16":
            import ml_dtypes
            payload = rng.standard_normal(n, dtype=np.float32).astype(
                ml_dtypes.bfloat16).tobytes()
        else:
            payload = rng.standard_normal(n, dtype=np.float32).tobytes()
        frames = [payload for _ in range(n_frames)]

    def _py_run():
        t0 = time.perf_counter()
        for frame in frames:
            v = memoryview(frame)
            for i in range(per_frame):
                nativert.decode_records_py(
                    v[i * rec_bytes:(i + 1) * rec_bytes], codec)
        return total_mb / (time.perf_counter() - t0)

    def _native_run():
        t0 = time.perf_counter()
        for frame in frames:
            out = nativert.decode_records(frame, codec)
            np.split(out, per_frame)  # per-record views, no copy
        return total_mb / (time.perf_counter() - t0)

    native_ok = nativert.native_available()
    py_mb = max(_py_run() for _ in range(iters))
    native_mb = max(_native_run() for _ in range(iters)) if native_ok else None

    r = {
        "samples_per_sec": native_mb if native_mb is not None else py_mb,
        "path": "native" if native_mb is not None else "python",
        "record_kb": record_kb,
        "records_per_frame": per_frame,
        "frames": n_frames,
        "total_mb": round(total_mb, 1),
        "ingest_codec": codec,
        "python_mb_per_sec": round(py_mb, 1),
        "native_mb_per_sec": (round(native_mb, 1)
                              if native_mb is not None else None),
        "ingest_speedup": (round(native_mb / py_mb, 3)
                           if native_mb is not None else None),
        "native_available": native_ok,
        "batch": batch, "iters": iters, "ksteps": ksteps,
        "api": "nativert.decode_records",
    }
    _append_ps_ab("ingest", r)
    return r


_METRICS = {
    "lenet": "lenet_mnist_samples_per_sec",
    "fit_lenet": "lenet_fit_api_samples_per_sec",
    "fit_resnet50": "resnet50_fit_api_samples_per_sec",
    "char_rnn": "char_rnn_samples_per_sec",
    "transformer": "transformer_lm_samples_per_sec",
    "moe": "moe_transformer_samples_per_sec",
    "resnet50": "resnet50_samples_per_sec_per_chip",
    "vgg16": "vgg16_samples_per_sec_per_chip",
    "word2vec": "word2vec_pairs_per_sec",
    "attention": "flash_attention_tokens_per_sec",
    "serve": "serve_batched_requests_per_sec",
    "ps_async": "ps_async_samples_per_sec",
    "elastic": "elastic_ps_samples_per_sec",
    "ingest": "native_ingest_decode_mb_per_sec",
}

#: models whose headline is not a training samples/sec number
_UNITS = {"serve": "requests/sec", "ingest": "MB/sec"}

_DEFAULT_MODEL = "resnet50"  # the flagship; bare bench.py runs it

_DEFAULTS = {  # model -> (batch, iters, ksteps)
    "lenet": (128, 20, 16),
    "fit_lenet": (128, 20, 16),
    "resnet50": (128, 5, 16),  # K=16 measured +1.5% over K=8 (r5)
    "vgg16": (64, 4, 8),  # ~4x ResNet-50 flops/sample: half the batch
    "fit_resnet50": (64, 4, 8),
    "char_rnn": (32, 5, 8),
    "transformer": (16, 5, 8),
    "moe": (8, 5, 4),
    "word2vec": (1024, 10, 32),
    "attention": (4, 5, 4),
    "serve": (32, 3, 1),  # batch = serving max_batch, iters = seconds/phase
    "ps_async": (32, 48, 1),  # iters = total minibatches through each path
    "elastic": (32, 192, 1),  # iters = total minibatches across the fleet
    "ingest": (4, 4, 1),  # batch = record KB, iters = timing reps
}


def _bench_fns():
    return {"lenet": bench_lenet, "resnet50": bench_resnet50,
            "vgg16": bench_vgg16,
            "fit_lenet": bench_fit_lenet, "fit_resnet50": bench_fit_resnet50,
            "char_rnn": bench_char_rnn, "transformer": bench_transformer,
            "moe": bench_moe,
            "word2vec": bench_word2vec, "attention": bench_attention,
            "serve": bench_serve, "ps_async": bench_ps_async,
            "elastic": bench_elastic, "ingest": bench_ingest}


#: per-model default dtype policy = the measured-best config on chip
#: (BASELINE.md round-5): bf16 activations win big on the flagships
#: (+22% ResNet-50, +52% transformer) but LOSE on tiny models where the
#: convert ops dominate (LeNet: 240k vs 374k samples/s). A bare
#: `python bench.py --model X` therefore reports each model's production
#: configuration; --f32/--bf16-matmul/--bf16-act force a specific one.
_DTYPE_DEFAULT = {"lenet": "bf16", "fit_lenet": "bf16",
                  "word2vec": "bf16", "attention": "bf16",
                  # serving measures f32 end-to-end request latency; bf16
                  # convert ops on tiny batches would dominate like LeNet
                  "serve": "f32",
                  # PS A/B measures host-side orchestration (barrier vs
                  # async push/pull), not MXU width: f32 like serve
                  "ps_async": "f32",
                  # elastic measures membership/handoff orchestration on
                  # subprocess CPU workers: same reasoning as ps_async
                  "elastic": "f32",
                  # ingest decodes record bytes on the host: no matmuls
                  "ingest": "f32"}


def _dtype_mode(model: str, *, bf16_act: bool, bf16_matmul: bool,
                f32: bool) -> str:
    if f32:
        return "f32"
    if bf16_matmul:
        return "bf16"
    if bf16_act:
        return "bf16_act"
    return _DTYPE_DEFAULT.get(model, "bf16_act")


def _reduction_mode(dtype_mode: str, reduction_dtype: str | None) -> str:
    """Resolved reduction policy: explicit --reduction-dtype wins; the
    bf16-act flagship path defaults to bf16 single-pass statistics (the
    round-6 reduction-precision subsystem — see BASELINE.md), every other
    mode defaults to classic at-least-f32 statistics."""
    if reduction_dtype:
        return reduction_dtype
    return "bf16" if dtype_mode == "bf16_act" else "f32"


def _child_main(args) -> None:
    """Run one benchmark in-process and print its JSON record."""
    global _PROFILE_SPEC
    mode = _dtype_mode(args.model, bf16_act=args.bf16_act,
                       bf16_matmul=args.bf16_matmul, f32=args.f32)
    rmode = _reduction_mode(mode, args.reduction_dtype)
    if mode == "bf16":
        from deeplearning4j_tpu.common import bf16_matmul_policy
        bf16_matmul_policy()
    elif mode == "bf16_act":
        if rmode == "bf16":
            # the measured flagship recipe: bf16 single-pass norm statistics
            # + f32-pinned weight-grad accumulation
            from deeplearning4j_tpu.common import flagship_bf16_policy
            flagship_bf16_policy()
        else:
            from deeplearning4j_tpu.common import full_bf16_policy
            full_bf16_policy()
    if mode != "bf16_act" and rmode == "bf16":
        # explicit opt-in on a non-flagship mode: bf16 stats + f32 grad accum
        # on top of whatever base policy is installed
        import jax.numpy as jnp
        from deeplearning4j_tpu.common import set_policy
        set_policy(reduction_dtype=jnp.bfloat16, grad_accum_dtype=jnp.float32)

    if args.seq:
        os.environ["DL4J_ATTN_SEQ"] = str(args.seq)
    if args.vocab:
        os.environ["DL4J_W2V_VOCAB"] = str(args.vocab)
    db, di, dk = _DEFAULTS[args.model]
    kwargs = {}
    if args.hidden and args.model == "char_rnn":
        kwargs["hidden"] = args.hidden
    if args.lstm_impl and args.model == "char_rnn":
        kwargs["lstm_impl"] = args.lstm_impl
    if args.model == "serve":
        if args.serve_qps:
            kwargs["serve_qps"] = args.serve_qps
        if args.serve_latency_ms:
            kwargs["serve_latency_ms"] = args.serve_latency_ms
        if args.serve_batching:
            kwargs["serve_batching"] = args.serve_batching
        if args.serve_quant:
            kwargs["serve_quant"] = args.serve_quant
        if args.serve_replicas:
            kwargs["serve_replicas"] = args.serve_replicas
        if args.serve_sharding:
            kwargs["serve_sharding"] = args.serve_sharding
        if args.compile_cache:
            kwargs["compile_cache"] = args.compile_cache
        if args.decode_kv:
            kwargs["decode_kv"] = args.decode_kv
        if args.decode_page_size:
            kwargs["decode_page_size"] = args.decode_page_size
        if args.decode_spec_draft:
            kwargs["decode_spec_draft"] = args.decode_spec_draft
        if args.serve_tracing:
            kwargs["serve_tracing"] = args.serve_tracing
        if args.serve_autoscale:
            kwargs["serve_autoscale"] = args.serve_autoscale
    if args.model == "ps_async":
        if args.ps_workers:
            kwargs["ps_workers"] = args.ps_workers
        if args.ps_straggler:
            kwargs["ps_straggler"] = args.ps_straggler
    if args.model == "elastic":
        if args.elastic_workers:
            kwargs["elastic_workers"] = args.elastic_workers
        if args.elastic_kill is not None:
            kwargs["elastic_kill"] = args.elastic_kill
        if args.compile_cache:
            kwargs["compile_cache"] = args.compile_cache
    if args.model in ("ps_async", "elastic") and args.ps_transport:
        kwargs["ps_transport"] = args.ps_transport
    if args.model == "ingest" and args.ingest_codec:
        kwargs["ingest_codec"] = args.ingest_codec
    if getattr(args, "sharding", None):
        if args.model not in _SHARDING_CAPABLE:
            raise SystemExit(
                f"--sharding supports {sorted(_SHARDING_CAPABLE)}, "
                f"not '{args.model}'")
        kwargs["sharding"] = args.sharding

    # arm the attribution capture: explicit --xplane-attribution, or the
    # first-healthy trigger bench_capture.sh exports (ROADMAP item 1 —
    # the first healthy relay window after an outage is capture-first)
    from deeplearning4j_tpu.observability import profiler as _profiler
    profile_trigger = None
    if getattr(args, "xplane_attribution", False):
        profile_trigger = "bench"
    elif _profiler.first_healthy_due():
        profile_trigger = "first-healthy"
    if profile_trigger and args.model in _PROFILE_CAPABLE:
        _PROFILE_SPEC = {"trigger": profile_trigger}

    r = _bench_fns()[args.model](args.batch or db, args.iters or di,
                                 args.ksteps or dk, **kwargs)

    if profile_trigger:
        if args.model not in _PROFILE_CAPABLE:
            r["profile_error"] = (
                f"model '{args.model}' does not run through the multistep "
                "harness; xplane attribution unsupported")
        elif r.get("profile_trace") and profile_trigger == "first-healthy":
            # a capture happened in this healthy window: later grid rows
            # inside the cool-down skip the trace overhead
            _profiler.mark_first_healthy()

    base = BASELINE_SAMPLES_PER_SEC.get(args.model)
    vs = round(r["samples_per_sec"] / base, 3) if base else None
    import jax
    r["backend"] = jax.default_backend()
    r["dtype"] = mode
    r["reduction_dtype"] = rmode
    if args.telemetry_out:
        # registry snapshot goes to a FILE beside the headline JSON — stdout
        # carries exactly one JSON line (the parent's parse contract)
        from deeplearning4j_tpu.observability import (global_registry,
                                                      global_tracker)
        global_registry().write_jsonl(
            args.telemetry_out, source="bench",
            model=args.model, dtype=mode, reduction_dtype=rmode,
            compile_events=global_tracker().snapshot_events())
    print(json.dumps({
        "metric": _METRICS[args.model],
        "value": round(r["samples_per_sec"], 2),
        "unit": _UNITS.get(args.model, "samples/sec"),
        "vs_baseline": vs,
        "detail": r,
    }), flush=True)


def main() -> None:
    """Parent driver: run the benchmark in a subprocess with bounded retries.

    The TPU relay on this box wedges intermittently (backend init raises
    UNAVAILABLE, or dispatch hangs indefinitely). The reference's measurement
    surface (PerformanceListener.java:1) assumes a healthy local device; here we
    must not — so each attempt runs in a killable subprocess with a hard
    timeout, and after the retry budget we still print ONE valid JSON record
    (an error record, never a stack trace) so the round always captures a
    parseable result.
    """
    import subprocess
    import sys

    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=_DEFAULT_MODEL,
                    choices=sorted(_METRICS))
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--iters", type=int, default=None)
    ap.add_argument("--seq", type=int, default=None,
                    help="attention bench sequence length (config-distinct "
                         "in bench_log matching, unlike the env override)")
    ap.add_argument("--vocab", type=int, default=None,
                    help="word2vec bench vocab size (config-distinct)")
    ap.add_argument("--hidden", type=int, default=None,
                    help="char_rnn LSTM hidden width (config-distinct); "
                         ">=1024 is the MFU-floor grid row")
    ap.add_argument("--ksteps", type=int, default=None,
                    help="train steps fused per host dispatch")
    ap.add_argument("--lstm-impl", default=None,
                    choices=("auto", "scan", "fused", "pallas"),
                    help="char_rnn recurrent-engine headline variant "
                         "(config-distinct). Every record also carries the "
                         "three-way A/B fields (scan/fused/pallas "
                         "samples_per_sec + *_speedup); this picks which "
                         "one is the headline. Default: auto (the "
                         "production DL4J_LSTM_IMPL gate)")
    dt = ap.add_mutually_exclusive_group()
    dt.add_argument("--f32", action="store_true",
                    help="float32 compute")
    dt.add_argument("--bf16-matmul", action="store_true",
                    help="bfloat16 matmuls/convs with f32 activations (the "
                         "pre-round-5 default)")
    dt.add_argument("--bf16-act", action="store_true",
                    help="full_bf16_policy: bfloat16 activations too (halves "
                         "activation HBM traffic; norm stats/losses stay "
                         "f32). THE DEFAULT since round 5: on-chip it is "
                         "+22%% on ResNet-50 and +52%% on the transformer "
                         "with loss curves matching (BASELINE.md round-5)")
    ap.add_argument("--reduction-dtype", choices=("f32", "bf16"), default=None,
                    help="normalization-statistics reduction dtype. Default: "
                         "bf16 under --bf16-act (the flagship single-pass "
                         "recipe — kills the standalone f32 upcast-reduce "
                         "fusions, ~23%% of r5 ResNet-50 bf16 device time; "
                         "weight-grad accumulation stays f32-pinned via "
                         "preferred_element_type), f32 everywhere else. "
                         "'f32' restores the classic at-least-f32 statistics "
                         "on the bf16-act path")
    ap.add_argument("--sharding", default=None,
                    choices=("dp", "dp_tp", "zero3"),
                    help="train through the partition-rule sharding engine "
                         "(ParallelWrapper.fit on a named mesh) instead of "
                         "the single-device path; fit_resnet50/transformer "
                         "only (config-distinct). The record carries the "
                         "achieved param_bytes_per_device from "
                         "dl4j_sharded_param_bytes_per_device")
    ap.add_argument("--serve-qps", type=float, default=None,
                    help="serve bench offered open-loop request rate "
                         "(config-distinct). Default: auto-calibrate — "
                         "measure the unbatched closed-loop saturation "
                         "point through the real HTTP stack, then offer "
                         "1.5x that rate to both A/B phases")
    ap.add_argument("--serve-latency-ms", type=float, default=None,
                    help="serve bench micro-batcher max coalescing wait "
                         "(config-distinct); default 4ms")
    ap.add_argument("--serve-batching", default=None,
                    choices=("continuous", "static"),
                    help="serve bench decode scheduling for the row's "
                         "decode_tokens_per_sec / decode_ttft_p99_ms "
                         "(config-distinct); default continuous — "
                         "iteration-level slot admission/eviction vs "
                         "request-level full-batch drain")
    ap.add_argument("--serve-quant", default=None, choices=("int8", "none"),
                    help="serve bench decode weight quantization for the "
                         "row's decode numbers (config-distinct); default "
                         "none (policy-dtype dense weights)")
    ap.add_argument("--serve-replicas", type=int, default=None,
                    help="serve bench replica count for the QPS-vs-replicas "
                         "scaling section (config-distinct); default 2 — N "
                         "independent pinned programs behind the least-"
                         "queue-depth router vs a single replica at equal "
                         "offered load")
    ap.add_argument("--serve-sharding", default=None,
                    choices=("dp_tp", "none"),
                    help="serve bench replica pin placement "
                         "(config-distinct); default none (one device per "
                         "replica). dp_tp shards each replica's pinned "
                         "params over its own mesh slice via the partition-"
                         "rule engine — bitwise-equal gather-at-use "
                         "serving, forced onto an 8-device CPU host "
                         "platform (NOT the fit path's --sharding axis: "
                         "serve rows never take --sharding)")
    ap.add_argument("--decode-kv", default=None, choices=("paged", "dense"),
                    help="serve bench decode KV layout for the row's "
                         "paged_tokens_per_sec (config-distinct); default "
                         "paged — page-table pool + CoW prefix sharing vs "
                         "dense per-slot [cap, max_context] blocks; both "
                         "phases always run (the A/B pins bitwise "
                         "equality), the axis picks the headline phase")
    ap.add_argument("--decode-page-size", type=int, default=None,
                    help="serve bench paged-decode physical page size in "
                         "tokens (config-distinct); default 16")
    ap.add_argument("--decode-spec-draft", default=None,
                    choices=("tiny", "none"),
                    help="serve bench speculative-decode draft model "
                         "(config-distinct); default tiny (a 1-layer "
                         "width-16 transformer proposing 3 tokens/round); "
                         "'none' skips the spec section (its fields "
                         "report null)")
    ap.add_argument("--serve-tracing", default=None, choices=("on", "off"),
                    help="serve bench request-tracing axis (config-"
                         "distinct); default on — the overhead A/B always "
                         "runs both phases and trace_overhead_pct reports "
                         "the serve-path cost of 100%%-sampled tracing "
                         "(budget <= 2%%, pinned by test_bench_contract)")
    ap.add_argument("--serve-autoscale", default=None,
                    choices=("on", "off"),
                    help="serve bench autoscaling ramp axis (config-"
                         "distinct); default off. 'on' runs the open-loop "
                         "ramp A/B: SLO-driven autoscaled fleet vs a "
                         "static fleet at the same average replica count "
                         "(ramp_slo_violation_seconds_auto/static, "
                         "ramp_lost_requests, ramp_scale_out_latency_s)")
    ap.add_argument("--ps-workers", type=int, default=None,
                    help="ps_async bench worker count for the straggler A/B "
                         "(config-distinct); default 4")
    ap.add_argument("--ps-straggler", type=float, default=None,
                    help="ps_async bench straggler factor: one worker of "
                         "--ps-workers sleeps this multiple of the median "
                         "per-step delay (config-distinct); default 4")
    ap.add_argument("--elastic-workers", type=int, default=None,
                    help="elastic bench fleet size: separate-process "
                         "workers behind the membership oracle "
                         "(config-distinct); default 4")
    ap.add_argument("--elastic-kill", type=float, default=None,
                    help="elastic bench kill point: SIGKILL shard 0's "
                         "worker when this fraction of the expected push "
                         "windows has landed (config-distinct); default "
                         "0.5, 0 disables the kill")
    ap.add_argument("--compile-cache", choices=("on", "off"), default=None,
                    help="serve/elastic: executable-cache mode for the "
                         "warm-start sections. 'off' measures only the "
                         "cold world (time_to_ready_s / recovery_seconds "
                         "are cold numbers); the default 'on' reports the "
                         "warm numbers with the cold A/B riding along")
    ap.add_argument("--ps-transport", choices=("tcp", "shm"), default=None,
                    help="ps_async/elastic bench PS byte plane: 'tcp' "
                         "loopback frames or 'shm' shared-memory segments "
                         "negotiated over the same socket (config-distinct); "
                         "default tcp")
    ap.add_argument("--ingest-codec", choices=("u8", "bf16", "f32"),
                    default=None,
                    help="ingest bench record codec for the native-vs-"
                         "python decode A/B (config-distinct); default u8")
    ap.add_argument("--telemetry-out", default=None,
                    help="append a metrics-registry snapshot (JSONL) to this "
                         "file beside the headline JSON; measurement-only — "
                         "ignored for bench_log config matching")
    ap.add_argument("--xplane-attribution", action="store_true",
                    help="after the timed loop, re-dispatch the compiled "
                         "program under a TraceSession capture and attach "
                         "the per-op category split (xplane_attribution) to "
                         "the record — or a profile_error field when capture/"
                         "parsing fails; measurement-only, ignored for "
                         "bench_log config matching")
    ap.add_argument("--flight-recorder-dir", default=None, metavar="DIR",
                    help="arm the flight recorder: bundles (crash, signal, "
                         "device-unreachable) are written under DIR instead "
                         "of next to scripts/bench_log.jsonl")
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    # worst case must finish inside the harness's own command timeout
    # (round-1 artifacts show it kills at ~600s): 2 x 240s + 5s backoff < 500s
    ap.add_argument("--attempts", type=int, default=2)
    ap.add_argument("--attempt-timeout", type=float, default=240.0)
    args = ap.parse_args()

    if args.flight_recorder_dir:
        from deeplearning4j_tpu.observability import (
            global_recorder, install_signal_handlers,
        )
        global_recorder().set_dump_dir(args.flight_recorder_dir)
        if args.child:
            install_signal_handlers()

    if args.child:
        _child_main(args)
        return

    # forward our full argv so new flags can never silently drop from the
    # child (--child's parser ignores --attempts/--attempt-timeout)
    cmd = [sys.executable, os.path.abspath(__file__), "--child"] + sys.argv[1:]

    # ps_async and elastic measure host-side orchestration and are
    # CPU-measured by design (the straggler A/B needs a data mesh at
    # worker count on any box, TPU relay or not; the elastic coordinator
    # and its subprocess workers must not contend for the relay); a
    # sharded-replica serve row likewise needs an 8-device host platform
    # so each replica gets a real mesh slice; every other model inherits
    # the env untouched
    child_env = None
    if args.model in ("ps_async", "elastic", "ingest") or (
            args.model == "serve"
            and getattr(args, "serve_sharding", None) == "dp_tp"):
        child_env = os.environ.copy()
        child_env["JAX_PLATFORMS"] = "cpu"
        child_env["PALLAS_AXON_POOL_IPS"] = ""
        child_env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"

    def _scan_json(stdout) -> dict | None:
        if isinstance(stdout, bytes):
            stdout = stdout.decode("utf-8", errors="replace")
        for line in reversed((stdout or "").strip().splitlines()):
            try:
                rec = json.loads(line)
            except (json.JSONDecodeError, ValueError):
                continue
            if isinstance(rec, dict) and "metric" in rec:
                return rec
        return None

    def _tail(s) -> str:
        if isinstance(s, bytes):
            s = s.decode("utf-8", errors="replace")
        return (s or "")[-600:]

    from deeplearning4j_tpu.observability import global_recorder

    last_err = ""
    last_was_timeout = False
    retry_timeline = []
    for attempt in range(args.attempts):
        t_attempt = time.time()
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=args.attempt_timeout,
                                  env=child_env)
            rec = _scan_json(proc.stdout)
            if rec is None:
                last_was_timeout = False
                last_err = (f"attempt {attempt + 1}: rc={proc.returncode}; "
                            + _tail(proc.stderr or proc.stdout))
        except subprocess.TimeoutExpired as e:
            # the child may have printed its record and then wedged in relay
            # teardown — a timeout after a valid JSON line is still a success
            rec = _scan_json(e.stdout)
            if rec is None:
                last_was_timeout = True
                last_err = (f"attempt {attempt + 1}: timed out after "
                            f"{args.attempt_timeout}s; stderr tail: "
                            + _tail(e.stderr))
        retry_timeline.append({
            "attempt": attempt + 1, "started": t_attempt,
            "elapsed_s": time.time() - t_attempt,
            "outcome": ("ok" if rec is not None
                        else "timeout" if last_was_timeout else "crash"),
            "error": None if rec is not None else last_err,
        })
        global_recorder().record("bench_attempt", **retry_timeline[-1])
        if rec is not None:
            rec["detail"] = dict(rec.get("detail", {}), attempt=attempt + 1)
            print(json.dumps(rec), flush=True)
            return
        if attempt + 1 < args.attempts:
            time.sleep(5 * (attempt + 1))

    # Retry budget exhausted: always emit a machine-readable error record.
    # Classify by the FINAL attempt: a timeout looks like the wedging relay
    # (retryable infra — exit 0 so the record is the signal); a child crash
    # is a deterministic code failure and must NOT be masked as flakiness
    # (exit 1, same record).
    kind = ("device unreachable after retries"
            if last_was_timeout else "benchmark child crashed on every attempt")
    rec = {
        "metric": _METRICS[args.model],
        "value": 0.0,
        "unit": _UNITS.get(args.model, "samples/sec"),
        "vs_baseline": 0.0,
        "error": kind + ": " + last_err.replace("\n", " | "),
    }
    if last_was_timeout:
        # relay outage, not a framework failure: embed the most recent
        # healthy on-chip record for this config (scripts/bench_log.jsonl,
        # appended by every bench_capture.sh run) so the artifact still
        # carries a real number, clearly marked as prior
        prior = _last_healthy_from_log(" ".join(sys.argv[1:]))
        if prior is not None:
            rec["note"] = ("transient TPU-relay outage at measurement time; "
                           "last_healthy is the most recent on-chip capture "
                           "of this config (see also BASELINE.md)")
            rec["last_healthy"] = prior
        else:
            rec["note"] = ("transient TPU-relay outage at measurement time "
                           "and no prior on-chip capture of this config in "
                           "scripts/bench_log.jsonl; BASELINE.md's measured "
                           "tables hold the last recorded numbers")
        # self-diagnosing outage artifact: a flight-recorder bundle (env,
        # retry timeline, the record we emitted, the prior healthy number)
        # next to the bench log — or under --flight-recorder-dir if armed
        bundle_dir = args.flight_recorder_dir or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "scripts")
        bundle = global_recorder().dump(
            dir=bundle_dir, reason="device-unreachable",
            extra={"retry_timeline": retry_timeline, "last_healthy": prior,
                   "record": rec})
        if bundle:
            rec["flight_bundle"] = bundle
    print(json.dumps(rec), flush=True)
    if not last_was_timeout:
        sys.exit(1)


#: when the per-model dtype defaults landed (round 5) — bare rows logged
#: before this instant were measured under the old global bf16-matmul default
_DTYPE_DEFAULT_CHANGE_TS = "2026-07-31T04:35:00Z"

#: when bf16 reductions became the bf16-act default (round 6) — bf16-act rows
#: logged before this instant ran classic at-least-f32 statistics
_RDTYPE_DEFAULT_CHANGE_TS = "2026-08-05T00:00:00Z"

#: when the recurrent engine landed (round 6) — bare char_rnn rows logged
#: before this instant measured the old scan path, not today's fused default
_LSTM_IMPL_DEFAULT_CHANGE_TS = "2026-08-05T12:00:00Z"

#: when bench rows grew xplane attribution (round 7) — rows logged before
#: this instant can never carry the fields below. --xplane-attribution is
#: measurement-only (like --telemetry-out): it must NOT make a config
#: distinct in bench_log matching, so a prior healthy row without the
#: fields still stands in for an attribution-armed request during an outage
_XPLANE_ATTRIBUTION_LANDED_TS = "2026-08-05T16:00:00Z"

#: the exact attribution field names a bench row may carry (the bench-row
#: contract; pinned by tests/test_bench_contract.py)
XPLANE_ATTRIBUTION_FIELDS = ("xplane_attribution", "profile_trace",
                             "profile_error", "profile_variant")

#: when the --sharding grid axis landed (round 8) — rows logged before this
#: instant all measured the single-device fit path, so during an outage they
#: may stand in only for an UNSHARDED request, never for a --sharding row
_SHARDING_AXIS_LANDED_TS = "2026-08-05T20:00:00Z"

#: when the serving-engine grid axes landed (round 9) — no bench_log row
#: before this instant can be a '--model serve' row at all, and rows logged
#: since carry the offered-QPS / coalescing-latency knobs as config axes so
#: an outage can never serve a number measured under a different load shape
_SERVE_AXIS_LANDED_TS = "2026-08-05T22:00:00Z"

#: when the async parameter-server engine landed (round 10) — no bench_log
#: row before this instant can be a '--model ps_async' row at all, and rows
#: logged since carry the worker-count / straggler-factor knobs as config
#: axes so an outage can never serve a number measured under a different
#: straggler shape
_PS_AXIS_LANDED_TS = "2026-08-05T22:00:30Z"

#: when the continuous-batching decode section landed on the serve bench
#: (round 11) — serve rows logged before this instant carry no decode
#: numbers (their axes normalize to None, never equal to a live request's
#: resolved "continuous"/"none"), so an outage can never serve a
#: decode-less row for a request whose headline now includes
#: decode_tokens_per_sec; rows since carry the scheduling-mode /
#: weight-quantization knobs as config axes so a static or int8 capture
#: can never stand in for the continuous dense row
_SERVE_DECODE_AXIS_LANDED_TS = "2026-08-05T23:30:00Z"

#: when the sharded multi-replica serving section landed (round 12) —
#: serve rows logged before this instant predate the ReplicaSet and carry
#: no replica-scaling numbers (their axes normalize to None), so an outage
#: can never serve a replica-less row for a request whose headline now
#: includes replica_speedup; rows since carry the replica-count / pin-
#: placement knobs as config axes so a 4-replica or dp_tp-sharded capture
#: can never stand in for the standard 2-replica single-device row
_SERVE_REPLICA_AXIS_LANDED_TS = "2026-08-06T00:00:00Z"

#: when the elastic trainer landed (round 13) — no bench_log row before
#: this instant can be a '--model elastic' row at all, and rows logged
#: since carry the fleet-size / kill-point knobs as config axes so an
#: outage can never serve a no-kill or 8-worker capture for the standard
#: 4-worker kill-at-50% recovery row
_ELASTIC_AXIS_LANDED_TS = "2026-08-06T02:00:00Z"

#: when the host data plane landed (ISSUE 14): rows before this predate
#: --ps-transport (all PS traffic rode tcp frames) and the ingest model;
#: a pre-plane tcp row must not stand in for today's shm capture
_DATAPLANE_AXIS_LANDED_TS = "2026-08-06T06:00:00Z"

#: when the warm-start compile plane landed (ISSUE 15): rows before this
#: predate --compile-cache and the time_to_ready / warm-recovery sections;
#: an all-cold row must not stand in for today's warm-headline capture
_COMPILE_CACHE_AXIS_LANDED_TS = "2026-08-06T10:00:00Z"

#: when the paged decode memory plane landed (ISSUE 16): serve rows before
#: this predate --decode-kv / --decode-page-size / --decode-spec-draft
#: (all decode traffic ran dense KV, no draft model existed), so an old
#: dense capture must never stand in for today's paged-headline row, and a
#: no-draft capture must never stand in for the spec-decode speedup row
_PAGED_DECODE_AXIS_LANDED_TS = "2026-08-07T08:00:00Z"

#: when the request-tracing plane landed (ISSUE 17): serve rows before
#: this predate --serve-tracing and the trace_overhead_pct field (requests
#: ran untraced), so an untraced capture must never stand in for today's
#: tracing-on default row whose headline carries the overhead budget
_SERVE_TRACING_AXIS_LANDED_TS = "2026-08-07T12:00:00Z"

#: when the autoscaling serving fleet landed (ISSUE 18): serve rows before
#: this predate --serve-autoscale and the ramp A/B section (fleets were a
#: fixed --serve-replicas guess), so a static-fleet capture must never
#: stand in for the autoscaled ramp row and vice versa
_SERVE_AUTOSCALE_AXIS_LANDED_TS = "2026-08-07T16:00:00Z"


def _config_key(args_str: str, ts: str = None) -> dict:
    """The fields that make two bench invocations the SAME config: model,
    dtype mode, explicit batch/ksteps. Unrecognized flags are ignored."""
    toks = args_str.split()

    def val(flag):
        return toks[toks.index(flag) + 1] if (flag in toks
                                              and toks.index(flag) + 1
                                              < len(toks)) else None

    # normalize argparse defaults so a BARE invocation (the driver's
    # end-of-round run) is the SAME config as explicit '--model resnet50
    # --bf16-act' capture rows; dtype resolution mirrors _dtype_mode
    model = val("--model") or _DEFAULT_MODEL
    mode = _dtype_mode(model,
                       bf16_act="--bf16-act" in toks,
                       bf16_matmul="--bf16-matmul" in toks,
                       f32="--f32" in toks)
    if ts is not None and ts < _DTYPE_DEFAULT_CHANGE_TS \
            and not any(f in toks for f in ("--bf16-act", "--bf16-matmul",
                                            "--f32")):
        # rows logged before round 5's per-model defaults ran bare under the
        # old bf16-matmul default; reinterpreting them as bf16_act would let
        # an outage serve a wrong-dtype number (+22-52%% apart on flagships)
        mode = "bf16"
    rdtype = val("--reduction-dtype") or _reduction_mode(mode, None)
    if ts is not None and ts < _RDTYPE_DEFAULT_CHANGE_TS \
            and "--reduction-dtype" not in toks:
        # pre-round-6 rows predate the reduction-precision subsystem: they
        # all ran at-least-f32 statistics regardless of dtype mode
        rdtype = "f32"
    lstm_impl = None
    if model == "char_rnn":
        lstm_impl = val("--lstm-impl") or "auto"
        if ts is not None and ts < _LSTM_IMPL_DEFAULT_CHANGE_TS \
                and "--lstm-impl" not in toks:
            # pre-engine rows measured the reference scan path; an outage
            # must not serve an old scan number for today's fused/auto row
            lstm_impl = "scan"
    sharding = None
    if model in _SHARDING_CAPABLE:
        sharding = val("--sharding")
        if ts is not None and ts < _SHARDING_AXIS_LANDED_TS:
            # pre-round-8 rows predate the sharding engine: they all measured
            # the single-device fit path, whatever flags a later reader asks
            sharding = None
    serve_qps = serve_latency_ms = None
    if model == "serve" and not (ts is not None
                                 and ts < _SERVE_AXIS_LANDED_TS):
        # 'auto' (the calibrated default) is its own config: a row captured
        # at an explicit --serve-qps must not stand in for a calibrated run
        serve_qps = val("--serve-qps") or "auto"
        serve_latency_ms = val("--serve-latency-ms") or "4"
    serve_batching = serve_quant = None
    if model == "serve" and not (ts is not None
                                 and ts < _SERVE_DECODE_AXIS_LANDED_TS):
        # defaults are their own config: a static-batching or int8 capture
        # must never stand in for the continuous dense decode row
        serve_batching = val("--serve-batching") or "continuous"
        serve_quant = val("--serve-quant") or "none"
    serve_replicas = serve_sharding = None
    if model == "serve" and not (ts is not None
                                 and ts < _SERVE_REPLICA_AXIS_LANDED_TS):
        # defaults are their own config: a 4-replica or dp_tp-sharded
        # capture must never stand in for the 2-replica single-device row
        serve_replicas = val("--serve-replicas") or "2"
        serve_sharding = val("--serve-sharding") or "none"
    ps_workers = ps_straggler = None
    if model == "ps_async" and not (ts is not None
                                    and ts < _PS_AXIS_LANDED_TS):
        # defaults are their own config: a 2-worker or 8x-straggler capture
        # must never stand in for the standard 4-worker/4x A/B
        ps_workers = val("--ps-workers") or "4"
        ps_straggler = val("--ps-straggler") or "4"
    elastic_workers = elastic_kill = None
    if model == "elastic" and not (ts is not None
                                   and ts < _ELASTIC_AXIS_LANDED_TS):
        # defaults are their own config: a no-kill or 8-worker capture
        # must never stand in for the 4-worker kill-at-50% recovery row
        elastic_workers = val("--elastic-workers") or "4"
        elastic_kill = val("--elastic-kill") or "0.5"
    ps_transport = ingest_codec = None
    if model in ("ps_async", "elastic") and not (
            ts is not None and ts < _DATAPLANE_AXIS_LANDED_TS):
        # defaults are their own config: an shm capture must never stand
        # in for the tcp baseline row (the A/B the headline compares)
        ps_transport = val("--ps-transport") or "tcp"
    if model == "ingest" and not (ts is not None
                                  and ts < _DATAPLANE_AXIS_LANDED_TS):
        ingest_codec = val("--ingest-codec") or "u8"
    compile_cache = None
    if model in ("serve", "elastic") and not (
            ts is not None and ts < _COMPILE_CACHE_AXIS_LANDED_TS):
        # defaults are their own config: a cold-only --compile-cache off
        # capture must never stand in for the warm-headline default row
        compile_cache = val("--compile-cache") or "on"
    decode_kv = decode_page_size = decode_spec_draft = None
    if model == "serve" and not (
            ts is not None and ts < _PAGED_DECODE_AXIS_LANDED_TS):
        # defaults are their own config: a dense-KV or no-draft capture
        # must never stand in for the paged + spec-decode headline row
        decode_kv = val("--decode-kv") or "paged"
        decode_page_size = val("--decode-page-size") or "16"
        decode_spec_draft = val("--decode-spec-draft") or "tiny"
    serve_tracing = None
    if model == "serve" and not (
            ts is not None and ts < _SERVE_TRACING_AXIS_LANDED_TS):
        # default-on is its own config: an untraced capture must never
        # stand in for the tracing-on row (and vice versa)
        serve_tracing = val("--serve-tracing") or "on"
    serve_autoscale = None
    if model == "serve" and not (
            ts is not None and ts < _SERVE_AUTOSCALE_AXIS_LANDED_TS):
        # default-off is its own config: a row without the ramp A/B must
        # never stand in for the autoscaled capture (and vice versa)
        serve_autoscale = val("--serve-autoscale") or "off"
    return {"model": model, "batch": val("--batch"),
            "ksteps": val("--ksteps"), "dtype": mode, "rdtype": rdtype,
            "seq": val("--seq"), "vocab": val("--vocab"),
            "hidden": val("--hidden"), "lstm_impl": lstm_impl,
            "sharding": sharding, "serve_qps": serve_qps,
            "serve_latency_ms": serve_latency_ms,
            "serve_batching": serve_batching, "serve_quant": serve_quant,
            "serve_replicas": serve_replicas,
            "serve_sharding": serve_sharding,
            "ps_workers": ps_workers, "ps_straggler": ps_straggler,
            "elastic_workers": elastic_workers,
            "elastic_kill": elastic_kill,
            "ps_transport": ps_transport, "ingest_codec": ingest_codec,
            "compile_cache": compile_cache, "decode_kv": decode_kv,
            "decode_page_size": decode_page_size,
            "decode_spec_draft": decode_spec_draft,
            "serve_tracing": serve_tracing,
            "serve_autoscale": serve_autoscale}


def _last_healthy_from_log(args_str: str, path: str = None):
    """Most recent successful record of the SAME config (model + dtype mode
    + batch) in scripts/bench_log.jsonl (one row per bench_capture.sh run)
    — a bf16 or batch-swept row must not stand in for an fp32 default run."""
    if path is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "scripts", "bench_log.jsonl")
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return None
    want = _config_key(args_str)
    for line in reversed(lines):
        try:
            row = json.loads(line)
        except (json.JSONDecodeError, ValueError):
            continue
        if not isinstance(row, dict):
            continue
        r = row.get("rec")
        if (isinstance(r, dict) and r.get("value") and not r.get("error")
                and _config_key(row.get("args", ""),
                                ts=row.get("ts")) == want):
            return {"ts": row.get("ts"), "args": row.get("args"),
                    "record": r}
    return None


if __name__ == "__main__":
    main()
