"""Benchmark driver: prints ONE JSON line with the headline metric.

Headline (BASELINE.md): LeNet-5 MNIST training throughput (samples/sec) on one TPU
chip — the reference's LenetMnistExample config measured by its PerformanceListener
(reference optimize/listeners/PerformanceListener.java). The reference publishes no
numbers (BASELINE.md), so vs_baseline is reported against the first empirical
recording in BASELINE.md once established.

Usage: python bench.py [--model lenet|resnet50] [--batch N] [--iters N]
"""
from __future__ import annotations

import argparse
import json
import time

import numpy as np

BASELINE_SAMPLES_PER_SEC = None  # populated from first recorded round; see BASELINE.md


def bench_lenet(batch: int, iters: int, warmup: int = 5) -> dict:
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.lenet import lenet_mnist
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork, make_train_step

    net = MultiLayerNetwork(lenet_mnist()).init()
    step = jax.jit(make_train_step(net.conf), donate_argnums=(0, 1, 2))

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, 784)).astype(np.float32))
    y_np = np.zeros((batch, 10), np.float32)
    y_np[np.arange(batch), rng.integers(0, 10, batch)] = 1
    y = jnp.asarray(y_np)
    key = jax.random.PRNGKey(0)

    params, states, upd = net.params_list, net.state_list, net.updater_state
    for i in range(warmup):
        params, states, upd, loss = step(params, states, upd, x, y, key,
                                         jnp.int32(i))
    float(loss)  # hard sync: host read (block_until_ready alone is
    #              unreliable through the axon relay's async dispatch)

    t0 = time.perf_counter()
    for i in range(iters):
        params, states, upd, loss = step(params, states, upd, x, y, key,
                                         jnp.int32(i))
    # the donated-params chain makes this final host read wait on every step
    float(loss)
    dt = time.perf_counter() - t0
    return {
        "samples_per_sec": batch * iters / dt,
        "step_time_ms": dt / iters * 1000,
        "batch": batch,
        "iters": iters,
    }


def bench_resnet50(batch: int, iters: int, warmup: int = 3) -> dict:
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.resnet import resnet50
    from deeplearning4j_tpu.nn.graph_network import ComputationGraph, make_graph_train_step

    net = ComputationGraph(resnet50(n_classes=1000, image_size=224)).init()
    step = jax.jit(make_graph_train_step(net.conf), donate_argnums=(0, 1, 2))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, 224, 224, 3)).astype(np.float32))
    y_np = np.zeros((batch, 1000), np.float32)
    y_np[np.arange(batch), rng.integers(0, 1000, batch)] = 1
    y = jnp.asarray(y_np)
    key = jax.random.PRNGKey(0)
    params, states, upd = net.params_list, net.state_list, net.updater_state
    for i in range(warmup):
        params, states, upd, loss = step(params, states, upd, [x], [y], key,
                                         jnp.int32(i))
    float(loss)  # hard sync (see bench_lenet)
    t0 = time.perf_counter()
    for i in range(iters):
        params, states, upd, loss = step(params, states, upd, [x], [y], key,
                                         jnp.int32(i))
    float(loss)  # chain-forcing host read
    dt = time.perf_counter() - t0
    return {
        "samples_per_sec": batch * iters / dt,
        "step_time_ms": dt / iters * 1000,
        "batch": batch,
        "iters": iters,
    }


def bench_char_rnn(batch: int, iters: int, warmup: int = 3,
                   vocab: int = 64, seq: int = 50) -> dict:
    """GravesLSTM char-RNN (BASELINE config 3): TBPTT-length sequences."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.char_rnn import char_rnn_lstm
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork, make_train_step

    conf = char_rnn_lstm(vocab_size=vocab, hidden=200, tbptt_length=seq)
    conf.backprop_type = "Standard"  # one jitted step over the tbptt window
    net = MultiLayerNetwork(conf).init()
    step = jax.jit(make_train_step(net.conf), donate_argnums=(0, 1, 2))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, (batch, seq))
    x = jnp.asarray(np.eye(vocab, dtype=np.float32)[ids])
    y = x
    key = jax.random.PRNGKey(0)
    params, states, upd = net.params_list, net.state_list, net.updater_state
    for i in range(warmup):
        params, states, upd, loss = step(params, states, upd, x, y, key,
                                         jnp.int32(i))
    float(loss)
    t0 = time.perf_counter()
    for i in range(iters):
        params, states, upd, loss = step(params, states, upd, x, y, key,
                                         jnp.int32(i))
    float(loss)
    dt = time.perf_counter() - t0
    return {"samples_per_sec": batch * iters / dt,
            "chars_per_sec": batch * seq * iters / dt,
            "step_time_ms": dt / iters * 1000, "batch": batch, "iters": iters}


def bench_transformer(batch: int, iters: int, warmup: int = 3,
                      vocab: int = 256, seq: int = 256) -> dict:
    """Decoder-only transformer LM over the flash-attention kernel."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.transformer import transformer_lm
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork, make_train_step

    conf = transformer_lm(vocab_size=vocab, width=256, n_layers=4, n_heads=4,
                          max_len=seq)
    net = MultiLayerNetwork(conf).init()
    step = jax.jit(make_train_step(net.conf), donate_argnums=(0, 1, 2))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, (batch, seq))
    x = jnp.asarray(np.eye(vocab, dtype=np.float32)[ids])
    key = jax.random.PRNGKey(0)
    params, states, upd = net.params_list, net.state_list, net.updater_state
    for i in range(warmup):
        params, states, upd, loss = step(params, states, upd, x, x, key,
                                         jnp.int32(i))
    float(loss)
    t0 = time.perf_counter()
    for i in range(iters):
        params, states, upd, loss = step(params, states, upd, x, x, key,
                                         jnp.int32(i))
    float(loss)
    dt = time.perf_counter() - t0
    return {"samples_per_sec": batch * iters / dt,
            "tokens_per_sec": batch * seq * iters / dt,
            "step_time_ms": dt / iters * 1000, "batch": batch, "iters": iters}


_METRICS = {
    "lenet": "lenet_mnist_samples_per_sec",
    "char_rnn": "char_rnn_samples_per_sec",
    "transformer": "transformer_lm_samples_per_sec",
    "resnet50": "resnet50_samples_per_sec_per_chip",
}


def _child_main(args) -> None:
    """Run one benchmark in-process and print its JSON record."""
    if args.bf16:
        from deeplearning4j_tpu.common import bf16_matmul_policy
        bf16_matmul_policy()

    if args.model == "lenet":
        r = bench_lenet(args.batch or 128, args.iters or 50)
    elif args.model == "char_rnn":
        r = bench_char_rnn(args.batch or 32, args.iters or 10)
    elif args.model == "transformer":
        r = bench_transformer(args.batch or 16, args.iters or 10)
    else:
        r = bench_resnet50(args.batch or 32, args.iters or 10)

    vs = (r["samples_per_sec"] / BASELINE_SAMPLES_PER_SEC
          if BASELINE_SAMPLES_PER_SEC else 1.0)
    import jax
    r["backend"] = jax.default_backend()
    print(json.dumps({
        "metric": _METRICS[args.model],
        "value": round(r["samples_per_sec"], 2),
        "unit": "samples/sec",
        "vs_baseline": round(vs, 3),
        "detail": r,
    }), flush=True)


def main() -> None:
    """Parent driver: run the benchmark in a subprocess with bounded retries.

    The TPU relay on this box wedges intermittently (backend init raises
    UNAVAILABLE, or dispatch hangs indefinitely). The reference's measurement
    surface (PerformanceListener.java:1) assumes a healthy local device; here we
    must not — so each attempt runs in a killable subprocess with a hard
    timeout, and after the retry budget we still print ONE valid JSON record
    (an error record, never a stack trace) so the round always captures a
    parseable result.
    """
    import os
    import subprocess
    import sys

    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="lenet",
                    choices=["lenet", "resnet50", "char_rnn", "transformer"])
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--iters", type=int, default=None)
    ap.add_argument("--bf16", action="store_true",
                    help="bfloat16 matmul/conv compute (f32 params)")
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    # worst case must finish inside the harness's own command timeout
    # (round-1 artifacts show it kills at ~600s): 2 x 240s + 5s backoff < 500s
    ap.add_argument("--attempts", type=int, default=2)
    ap.add_argument("--attempt-timeout", type=float, default=240.0)
    args = ap.parse_args()

    if args.child:
        _child_main(args)
        return

    # forward our full argv so new flags can never silently drop from the
    # child (--child's parser ignores --attempts/--attempt-timeout)
    cmd = [sys.executable, os.path.abspath(__file__), "--child"] + sys.argv[1:]

    def _scan_json(stdout) -> dict | None:
        if isinstance(stdout, bytes):
            stdout = stdout.decode("utf-8", errors="replace")
        for line in reversed((stdout or "").strip().splitlines()):
            try:
                rec = json.loads(line)
            except (json.JSONDecodeError, ValueError):
                continue
            if isinstance(rec, dict) and "metric" in rec:
                return rec
        return None

    def _tail(s) -> str:
        if isinstance(s, bytes):
            s = s.decode("utf-8", errors="replace")
        return (s or "")[-600:]

    last_err = ""
    last_was_timeout = False
    for attempt in range(args.attempts):
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=args.attempt_timeout)
            rec = _scan_json(proc.stdout)
            if rec is None:
                last_was_timeout = False
                last_err = (f"attempt {attempt + 1}: rc={proc.returncode}; "
                            + _tail(proc.stderr or proc.stdout))
        except subprocess.TimeoutExpired as e:
            # the child may have printed its record and then wedged in relay
            # teardown — a timeout after a valid JSON line is still a success
            rec = _scan_json(e.stdout)
            if rec is None:
                last_was_timeout = True
                last_err = (f"attempt {attempt + 1}: timed out after "
                            f"{args.attempt_timeout}s; stderr tail: "
                            + _tail(e.stderr))
        if rec is not None:
            rec["detail"] = dict(rec.get("detail", {}), attempt=attempt + 1)
            print(json.dumps(rec), flush=True)
            return
        if attempt + 1 < args.attempts:
            time.sleep(5 * (attempt + 1))

    # Retry budget exhausted: always emit a machine-readable error record.
    # Classify by the FINAL attempt: a timeout looks like the wedging relay
    # (retryable infra — exit 0 so the record is the signal); a child crash
    # is a deterministic code failure and must NOT be masked as flakiness
    # (exit 1, same record).
    kind = ("device unreachable after retries"
            if last_was_timeout else "benchmark child crashed on every attempt")
    print(json.dumps({
        "metric": _METRICS[args.model],
        "value": 0.0,
        "unit": "samples/sec",
        "vs_baseline": 0.0,
        "error": kind + ": " + last_err.replace("\n", " | "),
    }), flush=True)
    if not last_was_timeout:
        sys.exit(1)


if __name__ == "__main__":
    main()
