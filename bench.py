"""Benchmark driver: prints ONE JSON line with the headline metric.

Headline (BASELINE.md): LeNet-5 MNIST training throughput (samples/sec) on one TPU
chip — the reference's LenetMnistExample config measured by its PerformanceListener
(reference optimize/listeners/PerformanceListener.java). The reference publishes no
numbers (BASELINE.md), so vs_baseline is reported against the first empirical
recording in BASELINE.md once established.

Usage: python bench.py [--model lenet|resnet50] [--batch N] [--iters N]
"""
from __future__ import annotations

import argparse
import json
import time

import numpy as np

BASELINE_SAMPLES_PER_SEC = None  # populated from first recorded round; see BASELINE.md


def bench_lenet(batch: int, iters: int, warmup: int = 5) -> dict:
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.lenet import lenet_mnist
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork, make_train_step

    net = MultiLayerNetwork(lenet_mnist()).init()
    step = jax.jit(make_train_step(net.conf), donate_argnums=(0, 1, 2))

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, 784)).astype(np.float32))
    y_np = np.zeros((batch, 10), np.float32)
    y_np[np.arange(batch), rng.integers(0, 10, batch)] = 1
    y = jnp.asarray(y_np)
    key = jax.random.PRNGKey(0)

    params, states, upd = net.params_list, net.state_list, net.updater_state
    for i in range(warmup):
        params, states, upd, loss = step(params, states, upd, x, y, key,
                                         jnp.int32(i))
    float(loss)  # hard sync: host read (block_until_ready alone is
    #              unreliable through the axon relay's async dispatch)

    t0 = time.perf_counter()
    for i in range(iters):
        params, states, upd, loss = step(params, states, upd, x, y, key,
                                         jnp.int32(i))
    # the donated-params chain makes this final host read wait on every step
    float(loss)
    dt = time.perf_counter() - t0
    return {
        "samples_per_sec": batch * iters / dt,
        "step_time_ms": dt / iters * 1000,
        "batch": batch,
        "iters": iters,
    }


def bench_resnet50(batch: int, iters: int, warmup: int = 3) -> dict:
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.resnet import resnet50
    from deeplearning4j_tpu.nn.graph_network import ComputationGraph, make_graph_train_step

    net = ComputationGraph(resnet50(n_classes=1000, image_size=224)).init()
    step = jax.jit(make_graph_train_step(net.conf), donate_argnums=(0, 1, 2))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, 224, 224, 3)).astype(np.float32))
    y_np = np.zeros((batch, 1000), np.float32)
    y_np[np.arange(batch), rng.integers(0, 1000, batch)] = 1
    y = jnp.asarray(y_np)
    key = jax.random.PRNGKey(0)
    params, states, upd = net.params_list, net.state_list, net.updater_state
    for i in range(warmup):
        params, states, upd, loss = step(params, states, upd, [x], [y], key,
                                         jnp.int32(i))
    float(loss)  # hard sync (see bench_lenet)
    t0 = time.perf_counter()
    for i in range(iters):
        params, states, upd, loss = step(params, states, upd, [x], [y], key,
                                         jnp.int32(i))
    float(loss)  # chain-forcing host read
    dt = time.perf_counter() - t0
    return {
        "samples_per_sec": batch * iters / dt,
        "step_time_ms": dt / iters * 1000,
        "batch": batch,
        "iters": iters,
    }


def bench_char_rnn(batch: int, iters: int, warmup: int = 3,
                   vocab: int = 64, seq: int = 50) -> dict:
    """GravesLSTM char-RNN (BASELINE config 3): TBPTT-length sequences."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.char_rnn import char_rnn_lstm
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork, make_train_step

    conf = char_rnn_lstm(vocab_size=vocab, hidden=200, tbptt_length=seq)
    conf.backprop_type = "Standard"  # one jitted step over the tbptt window
    net = MultiLayerNetwork(conf).init()
    step = jax.jit(make_train_step(net.conf), donate_argnums=(0, 1, 2))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, (batch, seq))
    x = jnp.asarray(np.eye(vocab, dtype=np.float32)[ids])
    y = x
    key = jax.random.PRNGKey(0)
    params, states, upd = net.params_list, net.state_list, net.updater_state
    for i in range(warmup):
        params, states, upd, loss = step(params, states, upd, x, y, key,
                                         jnp.int32(i))
    float(loss)
    t0 = time.perf_counter()
    for i in range(iters):
        params, states, upd, loss = step(params, states, upd, x, y, key,
                                         jnp.int32(i))
    float(loss)
    dt = time.perf_counter() - t0
    return {"samples_per_sec": batch * iters / dt,
            "chars_per_sec": batch * seq * iters / dt,
            "step_time_ms": dt / iters * 1000, "batch": batch, "iters": iters}


def bench_transformer(batch: int, iters: int, warmup: int = 3,
                      vocab: int = 256, seq: int = 256) -> dict:
    """Decoder-only transformer LM over the flash-attention kernel."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.transformer import transformer_lm
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork, make_train_step

    conf = transformer_lm(vocab_size=vocab, width=256, n_layers=4, n_heads=4,
                          max_len=seq)
    net = MultiLayerNetwork(conf).init()
    step = jax.jit(make_train_step(net.conf), donate_argnums=(0, 1, 2))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, (batch, seq))
    x = jnp.asarray(np.eye(vocab, dtype=np.float32)[ids])
    key = jax.random.PRNGKey(0)
    params, states, upd = net.params_list, net.state_list, net.updater_state
    for i in range(warmup):
        params, states, upd, loss = step(params, states, upd, x, x, key,
                                         jnp.int32(i))
    float(loss)
    t0 = time.perf_counter()
    for i in range(iters):
        params, states, upd, loss = step(params, states, upd, x, x, key,
                                         jnp.int32(i))
    float(loss)
    dt = time.perf_counter() - t0
    return {"samples_per_sec": batch * iters / dt,
            "tokens_per_sec": batch * seq * iters / dt,
            "step_time_ms": dt / iters * 1000, "batch": batch, "iters": iters}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="lenet",
                    choices=["lenet", "resnet50", "char_rnn", "transformer"])
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--iters", type=int, default=None)
    ap.add_argument("--bf16", action="store_true",
                    help="bfloat16 matmul/conv compute (f32 params)")
    args = ap.parse_args()

    if args.bf16:
        from deeplearning4j_tpu.common import bf16_matmul_policy
        bf16_matmul_policy()

    if args.model == "lenet":
        r = bench_lenet(args.batch or 128, args.iters or 50)
        metric = "lenet_mnist_samples_per_sec"
    elif args.model == "char_rnn":
        r = bench_char_rnn(args.batch or 32, args.iters or 10)
        metric = "char_rnn_samples_per_sec"
    elif args.model == "transformer":
        r = bench_transformer(args.batch or 16, args.iters or 10)
        metric = "transformer_lm_samples_per_sec"
    else:
        r = bench_resnet50(args.batch or 32, args.iters or 10)
        metric = "resnet50_samples_per_sec_per_chip"

    vs = (r["samples_per_sec"] / BASELINE_SAMPLES_PER_SEC
          if BASELINE_SAMPLES_PER_SEC else 1.0)
    print(json.dumps({
        "metric": metric,
        "value": round(r["samples_per_sec"], 2),
        "unit": "samples/sec",
        "vs_baseline": round(vs, 3),
        "detail": r,
    }))


if __name__ == "__main__":
    main()
