"""deeplearning4j-graph equivalents: graph structure, random walks, DeepWalk
(reference TestGraph, TestRandomWalkIterator, DeepWalkGradientCheck/TestDeepWalk)."""
import numpy as np
import pytest

from deeplearning4j_tpu.graph import (
    DeepWalk, Graph, RandomWalkIterator, WeightedRandomWalkIterator,
)
from deeplearning4j_tpu.graph.walkers import EXCEPTION_ON_DISCONNECTED


def _two_cliques(k=6):
    """Two k-cliques joined by one bridge edge: walks stay mostly inside a clique."""
    g = Graph(2 * k)
    for a in range(k):
        for b in range(a + 1, k):
            g.add_edge(a, b)
            g.add_edge(k + a, k + b)
    g.add_edge(0, k)  # bridge
    return g


def test_graph_structure():
    g = Graph(4)
    g.add_edge(0, 1)
    g.add_edge(1, 2, directed=True)
    assert g.num_vertices() == 4
    assert set(g.get_connected_vertex_indices(0)) == {1}
    assert set(g.get_connected_vertex_indices(1)) == {0, 2}
    assert g.get_connected_vertex_indices(2) == []  # directed edge: no back edge
    assert g.get_vertex_degree(1) == 2


def test_edge_list_loader(tmp_path):
    p = tmp_path / "edges.txt"
    p.write_text("# comment\n0 1\n1 2\n2 3\n")
    g = Graph.load_edge_list(str(p), 4)
    assert g.get_connected_vertex_indices(1) == [0, 2]


def test_adjacency_list_loader(tmp_path):
    p = tmp_path / "adj.txt"
    p.write_text("0 1 2\n1 0\n2 0\n")
    g = Graph.load_adjacency_list(str(p))
    assert set(g.get_connected_vertex_indices(0)) == {1, 2}


def test_random_walks_stay_on_edges():
    g = _two_cliques()
    it = RandomWalkIterator(g, walk_length=10, seed=1)
    walks = list(it)
    assert len(walks) == g.num_vertices()
    for walk in walks:
        assert len(walk) == 11
        for a, b in zip(walk, walk[1:]):
            assert b in g.get_connected_vertex_indices(a) or a == b


def test_disconnected_vertex_handling():
    g = Graph(3)
    g.add_edge(0, 1)
    # vertex 2 disconnected: self-loop mode walks in place
    walk = RandomWalkIterator(g, 5, seed=0).walk_from(2)
    assert walk == [2] * 6
    with pytest.raises(ValueError):
        RandomWalkIterator(g, 5, no_edge_handling=EXCEPTION_ON_DISCONNECTED).walk_from(2)


def test_weighted_walks_follow_weights():
    g = Graph(3)
    g.add_edge(0, 1, weight=1000.0)
    g.add_edge(0, 2, weight=0.001)
    it = WeightedRandomWalkIterator(g, 1, seed=3)
    hits = sum(it.walk_from(0)[1] == 1 for _ in range(50))
    assert hits >= 48  # overwhelmingly follows the heavy edge


def test_deepwalk_embeds_cliques():
    g = _two_cliques(6)
    dw = (DeepWalk.builder().vector_size(24).window_size(4)
          .learning_rate(0.05).epochs(5).seed(11).build())
    dw.fit(g, walk_length=20, walks_per_vertex=4)
    # same-clique pairs more similar than cross-clique pairs on average
    same = np.mean([dw.similarity(1, 2), dw.similarity(2, 3),
                    dw.similarity(7, 8), dw.similarity(8, 9)])
    cross = np.mean([dw.similarity(1, 7), dw.similarity(2, 8),
                     dw.similarity(3, 9), dw.similarity(4, 10)])
    assert same > cross, (same, cross)
    vec = dw.get_vertex_vector(0)
    assert vec.shape == (24,) and np.all(np.isfinite(vec))
