"""ui-components HTML rendering + EvaluationTools exports + ModelGuesser."""
import numpy as np

from deeplearning4j_tpu.eval.evaluation import Evaluation
from deeplearning4j_tpu.eval.roc import ROC, ROCMultiClass
from deeplearning4j_tpu.ui.components import (
    ChartHistogram, ChartLine, ChartScatter, ComponentTable, ComponentText,
    render_page,
)
from deeplearning4j_tpu.utils.evaluation_tools import (
    export_evaluation_to_html_file, export_roc_charts_to_html_file,
    export_roc_multi_class_to_html_file,
)


def test_render_page_line_chart():
    c = (ChartLine("Loss", x_label="iteration", y_label="loss")
         .add_series("train", [0, 1, 2, 3], [1.0, 0.6, 0.4, 0.3])
         .add_series("val", [0, 1, 2, 3], [1.1, 0.8, 0.7, 0.65]))
    html = render_page("Training report", c, ComponentText("done"))
    assert "<!DOCTYPE html>" in html and "<svg" in html
    assert "polyline" in html and "viz-legend" in html
    assert "train" in html and "val" in html


def test_single_series_has_no_legend():
    c = ChartLine("Loss").add_series("loss", [0, 1], [1, 0])
    assert "viz-legend" not in c.render()


def test_histogram_and_scatter_and_table():
    h = ChartHistogram("weights", [0, 1, 2], [1, 2, 3], [5, 9, 2])
    s = ChartScatter("tsne").add_series("a", [0.0, 1.0], [1.0, 0.0])
    t = ComponentTable(["k", "v"], [["acc", 0.98]], title="metrics")
    page = render_page("r", h, s, t)
    assert page.count("<rect") == 3
    assert "<circle" in page and "<table>" in page and "0.98" in page


def test_roc_html_export(tmp_path):
    rng = np.random.default_rng(0)
    labels = np.zeros((100, 2), np.float32)
    cls = rng.integers(0, 2, 100)
    labels[np.arange(100), cls] = 1
    probs = np.clip(cls * 0.7 + rng.uniform(0, 0.5, 100), 0, 1)
    preds = np.stack([1 - probs, probs], axis=1)
    roc = ROC(threshold_steps=20)
    roc.eval(labels, preds)
    p = tmp_path / "roc.html"
    export_roc_charts_to_html_file(roc, str(p))
    html = p.read_text()
    assert "AUC" in html and "<svg" in html

    mc = ROCMultiClass(threshold_steps=20)
    mc.eval(labels, preds)
    p2 = tmp_path / "roc_mc.html"
    export_roc_multi_class_to_html_file(mc, str(p2))
    assert "average AUC" in p2.read_text()


def test_evaluation_html_export(tmp_path):
    e = Evaluation()
    labels = np.eye(3, dtype=np.float32)[[0, 1, 2, 0, 1, 2]]
    preds = np.eye(3, dtype=np.float32)[[0, 1, 2, 0, 1, 1]]
    e.eval(labels, preds)
    p = tmp_path / "eval.html"
    export_evaluation_to_html_file(e, str(p))
    html = p.read_text()
    assert "Confusion matrix" in html and "accuracy" in html


def test_model_guesser_roundtrip(tmp_path):
    from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.utils.model_serializer import guess_model, write_model

    conf = (NeuralNetConfiguration.builder().seed(3).list()
            .layer(DenseLayer(n_in=4, n_out=5, activation="relu"))
            .layer(OutputLayer(n_in=5, n_out=2, loss="mcxent",
                               activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()
    p = str(tmp_path / "m.zip")
    write_model(net, p)
    loaded = guess_model(p)
    x = np.ones((2, 4), np.float32)
    np.testing.assert_allclose(np.asarray(loaded.output(x)),
                               np.asarray(net.output(x)), rtol=1e-6)
