"""Solver tests (reference optimize/solvers + TestOptimizers.java)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.solvers import (
    Solver, minimize_cg, minimize_lbfgs, minimize_line_gd,
)


def _rosenbrock(v):
    x, y = v[0], v[1]
    return (1 - x) ** 2 + 100.0 * (y - x ** 2) ** 2


def _quadratic(v):
    # ill-conditioned convex quadratic
    scales = jnp.array([1.0, 10.0, 100.0, 3.0])
    return jnp.sum(scales * (v - jnp.arange(4.0)) ** 2)


def test_lbfgs_rosenbrock():
    x0 = jnp.array([-1.2, 1.0])
    res = jax.jit(lambda x: minimize_lbfgs(_rosenbrock, x, max_iters=200))(x0)
    assert float(res.loss) < 1e-6
    np.testing.assert_allclose(np.asarray(res.x), [1.0, 1.0], atol=1e-3)


def test_cg_quadratic():
    x0 = jnp.zeros(4)
    res = jax.jit(lambda x: minimize_cg(_quadratic, x, max_iters=200))(x0)
    assert float(res.loss) < 1e-5
    np.testing.assert_allclose(np.asarray(res.x), np.arange(4.0), atol=1e-2)


def test_line_gd_quadratic():
    x0 = jnp.zeros(4)
    res = jax.jit(lambda x: minimize_line_gd(_quadratic, x, max_iters=300))(x0)
    assert float(res.loss) < 1e-3


def _net(algo):
    conf = (NeuralNetConfiguration.builder()
            .seed(42).learning_rate(0.1).optimization_algo(algo)
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent", activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    labels = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(int) + (x[:, 2] > 1).astype(int)
    y = np.zeros((n, 3), np.float32)
    y[np.arange(n), labels] = 1
    return x, y


@pytest.mark.parametrize("algo", ["lbfgs", "conjugate_gradient",
                                  "line_gradient_descent"])
def test_solver_trains_network(algo):
    net = _net(algo)
    x, y = _data()
    s0 = net.score(x, y)
    solver = Solver(net, max_iters=50)
    s1 = solver.optimize(x, y)
    assert s1 < s0 * 0.7, (s0, s1)
    # solver should beat a handful of plain SGD steps on the full batch
    sgd = _net("stochastic_gradient_descent")
    for _ in range(10):
        sgd.fit(x, y)
    assert s1 < sgd.score(x, y)


def test_lbfgs_beats_short_sgd():
    net = _net("lbfgs")
    x, y = _data()
    Solver(net, max_iters=100).optimize(x, y)
    assert net.score(x, y) < 0.35


def test_fit_honors_optimization_algo():
    """fit() must dispatch on optimization_algo (reference Solver.java:55) —
    an LBFGS config trains via the LBFGS minimizer, not silently SGD.
    LBFGS full-batch on a convex-ish tiny problem reaches a far lower loss
    in one fit() call than a single SGD step possibly could."""
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 4)).astype(np.float32)
    w = rng.normal(size=(4, 2)).astype(np.float32)
    y = x @ w

    def conf_with(algo):
        return (NeuralNetConfiguration.builder()
                .seed(3).learning_rate(0.1)
                .optimization_algo(algo)
                .iterations(30)
                .list()
                .layer(OutputLayer(n_in=4, n_out=2, loss="mse",
                                   activation="identity"))
                .build())

    net = MultiLayerNetwork(conf_with("lbfgs")).init()
    s0 = net.score(x, y)
    net.fit(x, y)
    s_lbfgs = net.score(x, y)
    assert net.iteration > 0
    assert s_lbfgs < s0 * 1e-2, (s0, s_lbfgs)  # near-exact convex solve

    # iterator path also routes through the solver
    from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
    net2 = MultiLayerNetwork(conf_with("conjugate_gradient")).init()
    s0 = net2.score(x, y)
    net2.fit_iterator(ArrayDataSetIterator(x, y, batch=32))
    assert net2.score(x, y) < s0 * 0.1
