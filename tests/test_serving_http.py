"""HTTP front-end tests: /v1/predict, 429 backpressure, streaming, status.

Runs the real stdlib server stack on loopback (same as tests/test_ui.py);
every test binds port 0 so parallel runs never collide.
"""
import http.client
import json
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.keras_server import (
    InferenceServer, ModelRegistry, set_global_model_registry,
)
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    DenseLayer, GravesLSTM, OutputLayer, RnnOutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

N_IN, N_OUT = 12, 3


def _mlp(seed=7):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(0.1).updater("adam")
            .weight_init("xavier")
            .list()
            .layer(DenseLayer(n_in=N_IN, n_out=24, activation="relu"))
            .layer(OutputLayer(n_in=24, n_out=N_OUT, loss="mcxent",
                               activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def _lstm(seed=3):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(0.1).updater("adam")
            .weight_init("xavier")
            .list()
            .layer(GravesLSTM(n_in=5, n_out=8, activation="tanh"))
            .layer(RnnOutputLayer(n_in=8, n_out=2, loss="mcxent",
                                  activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def _post(port, path, obj, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(obj),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


@pytest.fixture()
def server():
    registry = ModelRegistry()
    registry.register("mlp", _mlp(), version="v1")
    registry.register("rnn", _lstm(), version="v1")
    srv = InferenceServer(registry, max_batch=8, max_latency_s=0.002,
                          max_queue=64).start()
    yield srv
    srv.stop()


def test_predict_roundtrip_and_status(server):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(3, N_IN)).astype(np.float32)
    status, _, body = _post(server.port, "/v1/predict",
                            {"model": "mlp", "inputs": x.tolist()})
    assert status == 200
    out = json.loads(body)
    assert np.asarray(out["predictions"]).shape == (3, N_OUT)
    assert out["model"] == "mlp" and out["version"] == "v1"
    # per-request vs HTTP-batched: same numbers end to end
    ref = np.asarray(server.registry.active("mlp").predict_fn(x))
    assert np.array_equal(np.asarray(out["predictions"], np.float32),
                          ref.astype(np.float32))

    status, body = _get(server.port, "/serve/status")
    st = json.loads(body)
    assert status == 200
    assert st["models"]["mlp"]["active"] == "v1"
    assert st["queue"]["dispatches"] >= 1
    assert "max_batch" in st["queue"]


def test_unknown_model_404_malformed_400(server):
    status, _, body = _post(server.port, "/v1/predict",
                            {"model": "nope", "inputs": [[0.0] * N_IN]})
    assert status == 404
    status, _, body = _post(server.port, "/v1/predict", {"model": "mlp"})
    assert status == 400
    status, body = _get(server.port, "/no/such/route")
    assert status == 404


def test_http_429_backpressure_and_gauge_agree():
    registry = ModelRegistry()
    mv = registry.register("mlp", _mlp(seed=9), version="v1")
    release = threading.Event()
    real_pf = mv.predict_fn

    class _Blocking:
        calls = 0

        def __call__(self, x):
            release.wait(timeout=30)
            return real_pf(x)

    srv = InferenceServer(registry, max_batch=1, max_latency_s=0.0,
                          max_queue=3).start()
    mv.predict_fn = _Blocking()
    statuses, lock = [], threading.Lock()

    def client():
        s, headers, body = _post(
            srv.port, "/v1/predict",
            {"model": "mlp", "inputs": [[0.0] * N_IN]})
        with lock:
            statuses.append((s, headers, body))
    try:
        threads = [threading.Thread(target=client) for _ in range(8)]
        for t in threads:
            t.start()
        deadline = time.time() + 10
        while srv.batcher.admission.rejected == 0 and time.time() < deadline:
            time.sleep(0.005)
        # while wedged: what 429s claim and what the gauge says must agree
        assert srv.batcher.admission.pending == 3
        metrics_text = None
        status, body = _get(srv.port, "/metrics")
        assert status == 200
        for line in body.decode().splitlines():
            if line.startswith("dl4j_serve_queue_depth"):
                metrics_text = float(line.rsplit(" ", 1)[1])
        assert metrics_text == 3.0
        release.set()
        for t in threads:
            t.join()
    finally:
        release.set()
        srv.stop()
    got = sorted(s for s, _, _ in statuses)
    assert got.count(200) == 3
    assert got.count(429) == 5
    for s, headers, body in statuses:
        if s == 429:
            assert float(headers["Retry-After"]) > 0
            err = json.loads(body)
            assert err["pending"] == 3 and err["limit"] == 3


def test_stream_sessions_persist_across_requests(server):
    rng = np.random.default_rng(1)
    seq = rng.normal(size=(1, 4, 5)).astype(np.float32)
    # one request, 4 timesteps, session A
    status, _, body = _post(server.port, "/v1/stream",
                            {"model": "rnn", "session": "A",
                             "inputs": seq.tolist()})
    assert status == 200
    lines = [json.loads(l) for l in body.decode().strip().splitlines()]
    assert lines[-1]["done"] and lines[-1]["timesteps"] == 4
    steps_a = [l["output"] for l in lines[:-1]]
    assert len(steps_a) == 4
    # two requests, 2 timesteps each, session B: state must carry over
    _post(server.port, "/v1/stream",
          {"model": "rnn", "session": "B", "inputs": seq[:, :2].tolist()})
    status, _, body = _post(server.port, "/v1/stream",
                            {"model": "rnn", "session": "B",
                             "inputs": seq[:, 2:].tolist()})
    lines = [json.loads(l) for l in body.decode().strip().splitlines()]
    steps_b = [l["output"] for l in lines[:-1]]
    assert np.allclose(np.asarray(steps_b), np.asarray(steps_a[2:]),
                       atol=1e-5)
    # reset drops the parked state
    status, _, body = _post(server.port, "/v1/stream/reset",
                            {"model": "rnn", "session": "B"})
    assert json.loads(body)["reset"] is True


def test_ui_server_serve_status_route():
    from deeplearning4j_tpu.ui.server import UIServer

    registry = ModelRegistry()
    registry.register("uim", _mlp(seed=13), version="v7")
    prev = set_global_model_registry(registry)
    ui = UIServer(port=0)
    try:
        status, body = _get(ui.port, "/serve/status")
        assert status == 200
        st = json.loads(body)
        assert st["models"]["uim"]["active"] == "v7"
    finally:
        ui.stop()
        set_global_model_registry(prev)
