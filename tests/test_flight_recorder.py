"""Failure-diagnostics tests: flight-recorder ring semantics and bundle
completeness, the fused training-health monitor through real fits (NaN
injection), the step watchdog (stall fires once, healthy run silent),
signal/exception dump egress, the MFU gauge, the /train/health endpoints,
and the shared invalid-score predicate."""
import json
import os
import signal
import threading
import time
import urllib.request

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.observability import (
    FlightRecorder, HealthMonitor, MetricsRegistry, NanAlertListener,
    StepWatchdog, TrainingDivergedError, global_recorder, health_terms,
    install_signal_handlers, is_invalid_score, uninstall_signal_handlers,
)
from deeplearning4j_tpu.observability import flight_recorder as fr_mod
from deeplearning4j_tpu.observability.flight_recorder import dump_on_unhandled
from deeplearning4j_tpu.ui import UIServer


def _small_net():
    conf = (NeuralNetConfiguration.builder()
            .seed(0).learning_rate(0.1)
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                               activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def _xy(n=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.zeros((n, 3), np.float32)
    y[np.arange(n), rng.integers(0, 3, n)] = 1
    return x, y


# ------------------------------------------------------------- ring buffer

def test_ring_buffer_bounds_and_eviction():
    rec = FlightRecorder(capacity=4)
    for i in range(10):
        rec.record("step", it=i)
    assert len(rec) == 4
    assert rec.dropped == 6
    # oldest evicted, newest kept, order preserved
    assert [e["it"] for e in rec.snapshot()] == [6, 7, 8, 9]
    assert all(e["kind"] == "step" and e["ts"] > 0 for e in rec.snapshot())
    rec.clear()
    assert len(rec) == 0 and rec.dropped == 0


def test_ring_buffer_thread_safety():
    rec = FlightRecorder(capacity=64)
    n_threads, n_each = 8, 500

    def writer(tid):
        for i in range(n_each):
            rec.record("step", tid=tid, i=i)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(rec) == 64
    assert rec.dropped == n_threads * n_each - 64
    assert all(e["kind"] == "step" for e in rec.snapshot())


def test_kill_switch():
    rec = FlightRecorder(capacity=8)
    rec.set_enabled(False)
    rec.record("step", it=0)
    assert len(rec) == 0 and not rec.enabled
    rec.set_enabled(True)
    rec.record("step", it=1)
    assert len(rec) == 1


# ------------------------------------------------------------------ bundles

BUNDLE_FILES = ("manifest.json", "events.jsonl", "metrics.json",
                "environment.json", "threads.txt", "cost_analysis.json")


def _assert_complete_bundle(path, expect_extra=False):
    for fname in BUNDLE_FILES + (("extra.json",) if expect_extra else ()):
        assert os.path.isfile(os.path.join(path, fname)), f"missing {fname}"
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    assert set(manifest["files"]) >= set(BUNDLE_FILES)
    for fname in ("metrics.json", "environment.json", "cost_analysis.json"):
        with open(os.path.join(path, fname)) as f:
            json.load(f)
    with open(os.path.join(path, "events.jsonl")) as f:
        events = [json.loads(line) for line in f]
    with open(os.path.join(path, "threads.txt")) as f:
        threads_txt = f.read()
    assert "--- thread" in threads_txt
    return manifest, events


def test_dump_bundle_completeness(tmp_path):
    reg = MetricsRegistry()
    reg.counter("dl4j_probe_total", "probe").labels(k="x").inc(3)
    rec = FlightRecorder(capacity=16, dump_dir=str(tmp_path), registry=reg)
    rec.record("step", it=1, dispatch_s=0.01)
    rec.record("health_alarm", why="nonfinite-grads", iteration=1)
    path = rec.dump(reason="manual test!", extra={"note": "hello"})
    assert path is not None and path.startswith(str(tmp_path))
    manifest, events = _assert_complete_bundle(path, expect_extra=True)
    assert manifest["reason"] == "manual test!"
    assert manifest["events"] == 2 and manifest["events_dropped"] == 0
    assert [e["kind"] for e in events] == ["step", "health_alarm"]
    with open(os.path.join(path, "environment.json")) as f:
        env = json.load(f)
    assert env["pid"] == os.getpid() and "python" in env
    with open(os.path.join(path, "metrics.json")) as f:
        assert "dl4j_probe_total" in json.load(f)
    with open(os.path.join(path, "extra.json")) as f:
        assert json.load(f) == {"note": "hello"}
    # dump bumps its own counter in the bundle's registry
    snap = reg.snapshot()["dl4j_flight_dumps_total"]
    assert snap["series"][0]["value"] == 1.0

    # no directory configured -> automatic dump sites are free no-ops
    assert FlightRecorder(capacity=4).dump(reason="nowhere") is None


def test_list_bundles_newest_first(tmp_path):
    rec = FlightRecorder(capacity=4, dump_dir=str(tmp_path),
                         registry=MetricsRegistry())
    rec.dump(reason="first")
    rec.dump(reason="second")
    bundles = rec.list_bundles()
    assert len(bundles) == 2
    assert bundles[0]["reason"] == "second"  # newest first (seq in dir name)
    assert all(os.path.isdir(b["path"]) for b in bundles)


# --------------------------------------------------------- exception egress

def test_exception_escape_dumps_once(tmp_path, monkeypatch):
    rec = FlightRecorder(capacity=16, dump_dir=str(tmp_path),
                         registry=MetricsRegistry())
    monkeypatch.setattr(fr_mod, "_GLOBAL", rec)

    @dump_on_unhandled("outer.fit")
    def outer():
        return inner()

    @dump_on_unhandled("inner.fit_iterator")
    def inner():
        raise ValueError("boom")

    with pytest.raises(ValueError, match="boom"):
        outer()
    # both frames record an event, but the exception produces ONE bundle
    kinds = [(e["kind"], e.get("site")) for e in rec.snapshot()]
    assert ("exception", "inner.fit_iterator") in kinds
    assert ("exception", "outer.fit") in kinds
    bundles = rec.list_bundles()
    assert len(bundles) == 1
    assert bundles[0]["reason"] == "exception-inner.fit_iterator"
    _assert_complete_bundle(bundles[0]["path"])


def test_signal_handler_dumps(tmp_path, monkeypatch):
    rec = FlightRecorder(capacity=16, dump_dir=str(tmp_path),
                         registry=MetricsRegistry())
    previous = install_signal_handlers(rec, signals=(signal.SIGUSR1,))
    try:
        os.kill(os.getpid(), signal.SIGUSR1)
        # the interpreter runs the handler at the next bytecode boundary
        deadline = time.time() + 5.0
        while len(rec) == 0 and time.time() < deadline:
            time.sleep(0.01)
        events = rec.snapshot()
        assert any(e["kind"] == "signal" and e["name"] == "SIGUSR1"
                   for e in events)
        bundles = rec.list_bundles()
        assert len(bundles) == 1
        assert bundles[0]["reason"] == "signal-SIGUSR1"
    finally:
        uninstall_signal_handlers(previous)
    assert signal.getsignal(signal.SIGUSR1) == previous[signal.SIGUSR1]


# ------------------------------------------------------------ health monitor

def test_health_terms_values():
    import jax.numpy as jnp

    grads = [jnp.ones((2, 2)), jnp.zeros(3)]
    params = [jnp.zeros((2, 2)), jnp.zeros(3)]
    new_params = [jnp.full((2, 2), 0.5), jnp.zeros(3)]
    packed = np.asarray(jax.jit(health_terms)(grads, params, new_params,
                                              jnp.float32(1.25)))
    grad_norm, upd_norm, nonfinite, loss = [float(v) for v in packed]
    assert grad_norm == pytest.approx(2.0)      # sqrt(4 * 1)
    assert upd_norm == pytest.approx(1.0)       # sqrt(4 * 0.25)
    assert nonfinite == 0.0
    assert loss == pytest.approx(1.25)

    grads[0] = grads[0].at[0, 0].set(jnp.nan)
    packed = np.asarray(jax.jit(health_terms)(grads, params, new_params,
                                              jnp.float32(1.25)))
    assert packed[2] == 1.0  # one non-finite grad element counted


def test_health_cadence_logic():
    hm = HealthMonitor(cadence=50)
    assert hm.due(0) and hm.due(100) and not hm.due(49)
    assert hm.due_index(0, 8) == 0
    assert hm.due_index(48, 8) == 2   # 50 falls in [48, 56)
    assert hm.due_index(51, 8) is None
    assert hm.due_range(96, 8) and not hm.due_range(101, 8)
    assert HealthMonitor(cadence=0).due_index(0, 8) is None


def test_healthy_fit_checks_without_alarm(tmp_path):
    reg = MetricsRegistry()
    rec = FlightRecorder(capacity=32, dump_dir=str(tmp_path), registry=reg)
    net = _small_net()
    hm = HealthMonitor(cadence=4, recorder=rec, registry=reg).attach(net)
    net.set_listeners(NanAlertListener(raise_on_alarm=True))
    x, y = _xy()
    net.fit_iterator(ListDataSetIterator([DataSet(x, y)] * 12))
    assert hm.checks > 0
    assert hm.alarms == 0 and hm.alarm is None
    assert hm.last is not None and np.isfinite(hm.last["loss"])
    assert rec.list_bundles() == []  # healthy run writes nothing
    snap = reg.snapshot()
    assert snap["dl4j_health_checks_total"]["series"][0]["value"] == hm.checks
    assert "dl4j_health_grad_norm" in snap
    assert "dl4j_health_loss_ema" in snap


def test_nan_injection_alarms_and_dumps(tmp_path):
    """Forced-NaN acceptance: a NaN in the batch reaches the grads, the
    fused health check catches it on the device, the listener raises, and a
    complete bundle lands on disk."""
    reg = MetricsRegistry()
    rec = FlightRecorder(capacity=32, dump_dir=str(tmp_path), registry=reg)
    net = _small_net()
    hm = HealthMonitor(cadence=1, recorder=rec, registry=reg).attach(net)
    net.set_listeners(NanAlertListener(raise_on_alarm=True))
    x, y = _xy()
    x[0, 0] = np.nan
    with pytest.raises(TrainingDivergedError, match="nonfinite-grads"):
        net.fit_iterator(ListDataSetIterator([DataSet(x, y)] * 4))
    assert hm.alarms >= 1
    assert hm.alarm["why"] == "nonfinite-grads"
    assert hm.alarm["nonfinite_grads"] > 0
    snap = reg.snapshot()["dl4j_health_alarms_total"]["series"]
    assert any(dict(s["labels"])["why"] == "nonfinite-grads" for s in snap)
    bundles = rec.list_bundles()
    assert any(b["reason"] == "health-alarm-nonfinite-grads"
               for b in bundles)
    path = [b for b in bundles
            if b["reason"] == "health-alarm-nonfinite-grads"][0]["path"]
    _, events = _assert_complete_bundle(path)
    assert any(e["kind"] == "health_alarm" for e in events)


def test_nan_alert_listener_score_fallback(tmp_path):
    """Without a monitor the listener degrades to the reference
    NanScoreWatcher idiom: it syncs score_value and alarms on NaN."""
    rec = FlightRecorder(capacity=16, dump_dir=str(tmp_path),
                         registry=MetricsRegistry())

    class FakeModel:
        score_value = float("nan")

    listener = NanAlertListener(raise_on_alarm=True, recorder=rec)
    with pytest.raises(TrainingDivergedError, match="invalid score"):
        listener.iteration_done(FakeModel(), 1)
    assert any(b["reason"] == "health-alarm-invalid-score"
               for b in rec.list_bundles())


def test_invalid_score_predicate_shared():
    from deeplearning4j_tpu.earlystopping.termination import (
        InvalidScoreIterationTerminationCondition,
    )

    cond = InvalidScoreIterationTerminationCondition()
    for bad in (float("nan"), float("inf"), float("-inf")):
        assert cond.terminate(bad) and is_invalid_score(bad)
    for ok in (0.0, -3.5, 1e30):
        assert not cond.terminate(ok) and not is_invalid_score(ok)
    assert is_invalid_score(None) and is_invalid_score("not-a-number")


# ---------------------------------------------------------------- watchdog

def test_watchdog_fires_once_on_stall(tmp_path, caplog):
    import logging

    reg = MetricsRegistry()
    rec = FlightRecorder(capacity=16, dump_dir=str(tmp_path), registry=reg)
    wd = StepWatchdog(threshold_s=0.15, poll_s=0.03, recorder=rec,
                      registry=reg)
    with caplog.at_level(logging.ERROR,
                         logger="deeplearning4j_tpu.observability.watchdog"):
        with wd:
            wd.heartbeat(step=7)
            deadline = time.time() + 5.0
            while wd.stalls == 0 and time.time() < deadline:
                time.sleep(0.02)
            # fired once; no further alarms without a new heartbeat
            time.sleep(0.3)
    assert wd.stalls == 1
    assert reg.snapshot()["dl4j_watchdog_stalls_total"]["series"][0][
        "value"] == 1.0
    assert any(e["kind"] == "watchdog_stall" and e["step"] == 7
               for e in rec.snapshot())
    bundles = rec.list_bundles()
    assert len(bundles) == 1 and bundles[0]["reason"] == "watchdog-stall"
    _assert_complete_bundle(bundles[0]["path"])
    # the hang site is in the training log even if the process dies later
    assert any("all-thread stacks follow" in r.getMessage()
               for r in caplog.records)


def test_watchdog_silent_on_healthy_run(tmp_path):
    rec = FlightRecorder(capacity=16, dump_dir=str(tmp_path),
                         registry=MetricsRegistry())
    wd = StepWatchdog(threshold_s=0.3, poll_s=0.03, recorder=rec,
                      registry=MetricsRegistry())
    with wd:
        for step in range(10):
            wd.heartbeat(step=step)
            time.sleep(0.05)  # each beat well inside the threshold
    assert wd.stalls == 0
    assert rec.list_bundles() == []


def test_watchdog_unarmed_until_first_beat(tmp_path):
    wd = StepWatchdog(threshold_s=0.05, poll_s=0.02,
                      recorder=FlightRecorder(capacity=4),
                      registry=MetricsRegistry())
    with wd:
        time.sleep(0.2)  # installed but idle: never fires
    assert wd.stalls == 0


def test_global_watchdog_beat_hook():
    from deeplearning4j_tpu.observability import (
        beat, global_watchdog, install_watchdog, uninstall_watchdog,
    )

    assert global_watchdog() is None
    beat(3)  # no-op without an installed watchdog
    wd = install_watchdog(threshold_s=60.0, poll_s=0.05,
                          recorder=FlightRecorder(capacity=4),
                          registry=MetricsRegistry())
    try:
        assert global_watchdog() is wd
        beat(42)
        assert wd._last_step == 42
    finally:
        uninstall_watchdog()
    assert global_watchdog() is None


# -------------------------------------------------------------------- MFU

def test_mfu_gauge_with_peak_override(monkeypatch):
    import jax.numpy as jnp

    from deeplearning4j_tpu.observability.compile_tracker import (
        CompileTracker,
    )

    monkeypatch.setenv("DL4J_PEAK_FLOPS", "1e12")
    reg = MetricsRegistry()
    tracker = CompileTracker(registry=reg)
    fn = tracker.wrap("mfu_probe", jax.jit(lambda a: a @ a))
    x = jnp.ones((64, 64), jnp.float32)
    fn(x).block_until_ready()
    flops = tracker.flops_for("mfu_probe")
    assert flops and flops > 0
    tracker.note_step(fn="mfu_probe")  # first sample only records the clock
    fn(x).block_until_ready()
    tracker.note_step(fn="mfu_probe")
    series = reg.snapshot()["dl4j_step_mfu"]["series"]
    by_fn = {dict(s["labels"])["fn"]: s["value"] for s in series}
    assert 0.0 < by_fn["mfu_probe"] <= 1.0


def test_mfu_silent_without_peak(monkeypatch):
    import jax.numpy as jnp

    from deeplearning4j_tpu.observability.compile_tracker import (
        CompileTracker,
    )

    monkeypatch.delenv("DL4J_PEAK_FLOPS", raising=False)
    monkeypatch.delenv("BENCH_PEAK_FLOPS", raising=False)
    reg = MetricsRegistry()
    tracker = CompileTracker(registry=reg)
    fn = tracker.wrap("mfu_cpu", jax.jit(lambda a: a + 1))
    x = jnp.ones((8,), jnp.float32)
    fn(x).block_until_ready()
    tracker.note_step(fn="mfu_cpu")
    fn(x).block_until_ready()
    tracker.note_step(fn="mfu_cpu")
    # CPU backend, no override: the gauge deliberately stays unset
    assert "dl4j_step_mfu" not in reg.snapshot()


# ---------------------------------------------------------------- UI routes

def test_train_health_endpoints(tmp_path, monkeypatch):
    rec = FlightRecorder(capacity=16, dump_dir=str(tmp_path))
    monkeypatch.setattr(fr_mod, "_GLOBAL", rec)
    rec.record("step", it=0)
    rec.dump(reason="endpoint test")

    server = UIServer(port=0)
    try:
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(base + "/train/health") as r:
            assert r.status == 200
            health = json.loads(r.read())
        with urllib.request.urlopen(base + "/train/health/bundles") as r:
            assert r.status == 200
            bundles = json.loads(r.read())
    finally:
        server.stop()
    assert health["recorder"]["enabled"] is True
    assert health["recorder"]["events"] >= 1
    assert health["recorder"]["capacity"] == 16
    assert isinstance(health["metrics"], dict)
    assert len(bundles["bundles"]) == 1
    assert bundles["bundles"][0]["reason"] == "endpoint test"


# ------------------------------------------------------------ bench egress

def test_bench_unreachable_writes_bundle(tmp_path):
    """When every bench attempt times out ("device unreachable"), the parent
    writes a flight-recorder bundle carrying the env, the retry timeline,
    and the emitted record."""
    import subprocess
    import sys

    import bench

    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    cmd = [sys.executable,
           os.path.join(os.path.dirname(bench.__file__), "bench.py"),
           "--model", "lenet", "--batch", "8", "--iters", "1",
           "--attempts", "1", "--attempt-timeout", "0.01",
           "--flight-recorder-dir", str(tmp_path)]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120,
                          env=env)
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert "device unreachable" in rec["error"]
    assert proc.returncode == 0  # retryable infra: the record is the signal
    bundle = rec.get("flight_bundle")
    assert bundle and bundle.startswith(str(tmp_path))
    _assert_complete_bundle(bundle, expect_extra=True)
    with open(os.path.join(bundle, "extra.json")) as f:
        extra = json.load(f)
    assert extra["retry_timeline"][0]["outcome"] == "timeout"
    assert "record" in extra


# ----------------------------------------------------------- fit-path events

def test_fit_records_step_events():
    rec_global = global_recorder()
    before = len(rec_global)
    net = _small_net()
    x, y = _xy()
    net.fit_iterator(ListDataSetIterator([DataSet(x, y)] * 4))
    events = rec_global.snapshot()
    assert len(events) > before
    steps = [e for e in events if e["kind"] == "step"
             and "MultiLayerNetwork" in e.get("path", "")]
    assert steps, "fit loop recorded no step events"
    assert all("it" in e and "dispatch_s" in e for e in steps)
