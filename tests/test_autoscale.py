"""SLO-driven autoscaling fleet: the ISSUE-18 acceptance set.

Pinned contracts:
- ``add_replica()`` / ``remove_replica()`` mutate the set atomically:
  monotonic never-reused indices, the new replica pre-registers the whole
  catalog before it becomes routable, the primary and the last replica
  cannot be removed;
- scale-in is drain-without-loss: every request admitted to a replica
  before its removal completes with a correct answer;
- a replica whose membership lease was evicted is fenced out of the
  router, and the autoscaler's zombie sweep evicts-and-backfills it
  outside the hysteresis window;
- hysteresis holds: at most ONE scale event per cooldown window, one step
  at a time, bounds respected, scale-in only after ``headroom_ticks``
  consecutive low-pressure ticks;
- priority shedding order: under saturation ``low`` is refused (with
  ``dl4j_serve_shed_total{tenant,priority}`` accounting) while ``high``
  still admits — a high-priority 429 means the queue is hard-full;
- warm scale-up: with the persistent compile cache populated,
  ``add_replica()`` resolves every bucket program from disk — zero fresh
  XLA compiles on a hot scale-up;
- the HTTP front door exposes the autoscaler block and honors the
  priority/tenant headers; the CLI grows the --autoscale axis;
- ``run_ramp_ab`` produces the full A/B record shape with zero lost
  requests (the strict auto<static violation floor is asserted on the
  capture host's record, not re-measured here — wall-clock SLO math on a
  loaded CI box is noise).
"""
import json
import time

import numpy as np
import pytest

from deeplearning4j_tpu.cloud import MembershipOracle
from deeplearning4j_tpu.keras_server import Autoscaler, ReplicaSet
from deeplearning4j_tpu.keras_server.admission import (
    PRIORITY_FLOORS, PRIORITY_LEVELS, AdmissionController, RejectedError,
    normalize_priority,
)
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.observability import names as _n
from deeplearning4j_tpu.observability.metrics import global_registry

N_IN, N_OUT = 12, 3


def _mlp(seed=7):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(0.1).updater("adam")
            .weight_init("xavier")
            .list()
            .layer(DenseLayer(n_in=N_IN, n_out=16, activation="relu"))
            .layer(OutputLayer(n_in=16, n_out=N_OUT, loss="mcxent",
                               activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def _x(n=2, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, N_IN)).astype(np.float32)


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class _FakeSLO:
    """Duck-typed SLOEngine: the autoscaler only reads evaluate()."""

    def __init__(self, burn=0.0, alerting=False):
        self.burn = burn
        self.alerting = alerting

    def evaluate(self):
        return [{"name": "latency", "alerting": self.alerting,
                 "windows": [{"burn_rate": self.burn}]}]


def _counter_value(name, **labels):
    series = global_registry().snapshot().get(name, {}).get("series", [])
    for s in series:
        if all(s["labels"].get(k) == v for k, v in labels.items()):
            return s["value"]
    return 0


# ------------------------------------------------------- fleet mutation API

def test_add_remove_replica_atomic():
    rs = ReplicaSet(2, max_batch=8, max_latency_s=0.001, max_queue=32)
    try:
        rs.register("mlp", _mlp(), version="v1")

        r2 = rs.add_replica(reason="t-atomic")
        assert r2.index == 2 and rs.n_replicas == 3
        # catalog seeded BEFORE the replica became routable: it serves the
        # registered model at the active version immediately
        assert r2.registry.active("mlp").version == "v1"
        out = r2.batcher.submit("mlp", _x()).result(timeout=30)
        assert np.asarray(out["predictions"]).shape == (2, N_OUT)
        assert out["version"] == "v1" and out["replica"] == 2
        assert _counter_value(_n.SERVE_SCALE_EVENTS_TOTAL,
                              direction="out", reason="t-atomic") == 1

        # a later register() rolls onto the added replica too
        rs.register("mlp", _mlp(seed=9), version="v2")
        for r in rs.replicas:
            assert r.registry.active("mlp").version == "v2"

        # default removal takes the highest-index non-primary replica
        assert rs.remove_replica(reason="t-atomic") is True
        assert rs.n_replicas == 2
        assert sorted(r.index for r in rs.replicas) == [0, 1]
        assert _counter_value(_n.SERVE_SCALE_EVENTS_TOTAL,
                              direction="in", reason="t-atomic") == 1
        # unknown index: soft miss; primary: hard refusal
        assert rs.remove_replica(index=99) is False
        with pytest.raises(ValueError):
            rs.remove_replica(index=0)
        assert rs.remove_replica(index=1) is True
        with pytest.raises(ValueError):
            rs.remove_replica()
        # indices are never reused across churn
        assert rs.add_replica(reason="t-atomic").index == 3
        # the fleet gauge tracks the live count
        assert _counter_value(_n.SERVE_FLEET_SIZE) == rs.n_replicas == 2
    finally:
        rs.close()


def test_scale_in_drains_without_loss():
    # a generous batching window keeps singles queued long enough that the
    # removal genuinely races in-flight work
    rs = ReplicaSet(2, max_batch=8, max_latency_s=0.05, max_queue=64)
    try:
        rs.register("mlp", _mlp(), version="v1")
        victim = [r for r in rs.replicas if r.index == 1][0]
        futures = [victim.batcher.submit("mlp", _x(1, seed=i))
                   for i in range(12)]
        assert rs.remove_replica(index=1, reason="t-drain") is True
        for f in futures:
            out = f.result(timeout=30)
            assert np.asarray(out["predictions"]).shape == (1, N_OUT)
            assert out["replica"] == 1
        assert victim.batcher.admission.rejected == 0
        assert rs.n_replicas == 1
    finally:
        rs.close()


# ----------------------------------------------------------- zombie fencing

def test_zombie_lease_fencing_and_backfill():
    oracle = MembershipOracle(role="replica", lease_timeout_s=60.0)
    rs = ReplicaSet(2, max_batch=8, max_latency_s=0.001, max_queue=32,
                    membership=oracle)
    try:
        rs.register("mlp", _mlp(), version="v1")
        zombie = [r for r in rs.replicas if r.index == 1][0]
        assert oracle.evict(zombie.lease.member, reason="chaos") is True
        assert [r.index for r in rs.fenced_replicas()] == [1]

        # the router never dispatches to a fenced replica
        for i in range(6):
            rs.submit("mlp", _x(1, seed=i)).result(timeout=30)
        routed = {s["replica"]: s["routed"] for s in rs.stats()["replicas"]}
        assert routed[0] == 6 and routed[1] == 0
        assert [s["replica"] for s in rs.stats()["replicas"]
                if s["fenced"]] == [1]

        # the autoscaler sweep evicts the zombie and backfills to
        # min_replicas outside the cooldown window
        asc = Autoscaler(rs, min_replicas=2, max_replicas=4,
                         cooldown_s=300.0)
        asc.tick()
        assert rs.n_replicas == 2
        assert rs.fenced_replicas() == []
        assert sorted(r.index for r in rs.replicas) == [0, 2]
        # the backfilled replica carries the catalog and a fresh lease
        fresh = [r for r in rs.replicas if r.index == 2][0]
        assert fresh.registry.active("mlp").version == "v1"
        assert oracle.validate(fresh.lease.member, fresh.lease.epoch)
        assert _counter_value(_n.SERVE_SCALE_EVENTS_TOTAL, direction="in",
                              reason="lease-fenced") >= 1
        assert _counter_value(_n.SERVE_SCALE_EVENTS_TOTAL, direction="out",
                              reason="replace-fenced") >= 1
        # heartbeat cannot resurrect the evicted lease
        rs.heartbeat()
        assert not oracle.validate(zombie.lease.member, zombie.lease.epoch)
    finally:
        rs.close()


# --------------------------------------------------------------- hysteresis

def test_hysteresis_one_event_per_cooldown_window():
    clock = _Clock()
    slo = _FakeSLO(burn=5.0)
    rs = ReplicaSet(1, max_batch=4, max_latency_s=0.001, max_queue=16)
    try:
        asc = Autoscaler(rs, slo_engine=slo, min_replicas=1, max_replicas=3,
                         cooldown_s=10.0, headroom_ticks=3, clock=clock)
        assert asc.tick() == "out" and rs.n_replicas == 2
        # burning hard the whole window: every tick inside the cooldown is
        # a no-op — at most one scale event per cooldown_s
        for _ in range(9):
            clock.advance(1.0)
            assert asc.tick() == "none"
        assert rs.n_replicas == 2
        clock.advance(1.0)
        assert asc.tick() == "out" and rs.n_replicas == 3
        # max bound: still burning, but the fleet never exceeds max_replicas
        clock.advance(11.0)
        assert asc.tick() == "none" and rs.n_replicas == 3

        # scale-in needs headroom_ticks CONSECUTIVE low ticks, then one
        # step per cooldown window
        slo.burn = 0.0
        clock.advance(11.0)
        assert asc.tick() == "none"      # low tick 1
        clock.advance(1.0)
        assert asc.tick() == "none"      # low tick 2
        slo.burn = 5.0                   # blip resets the streak but the
        clock.advance(1.0)               # fleet is at max: no event
        assert asc.tick() == "none"
        slo.burn = 0.0
        for _ in range(2):
            clock.advance(1.0)
            assert asc.tick() == "none"
        clock.advance(1.0)
        assert asc.tick() == "in" and rs.n_replicas == 2

        st = asc.status()
        assert st["n_replicas"] == 2
        assert st["last_decision"] == "in"
        assert st["last_reason"] == "headroom"
        assert st["min_replicas"] == 1 and st["max_replicas"] == 3
        assert st["last_scale_out_latency_s"] is not None
        assert st["events"] and st["events"][-1]["direction"] == "in"
    finally:
        rs.close()


def test_autoscaler_bounds_validation():
    rs = ReplicaSet(1, max_batch=4, max_queue=16)
    try:
        with pytest.raises(ValueError):
            Autoscaler(rs, min_replicas=0)
        with pytest.raises(ValueError):
            Autoscaler(rs, min_replicas=4, max_replicas=2)
    finally:
        rs.close()


# --------------------------------------------------------- priority shedding

def test_priority_shed_order_low_before_high():
    assert PRIORITY_LEVELS == ("low", "normal", "high")
    assert normalize_priority(None) == "high"
    assert normalize_priority("LOW") == "low"
    assert normalize_priority("gibberish") == "high"

    ac = AdmissionController(max_pending=10, expected_latency_s=0.01)
    assert ac.limit_for("low") == 5
    assert ac.limit_for("normal") == 7
    assert ac.limit_for("high") == 10

    ac.admit(5, priority="high", tenant="acme-18")
    # past low's floor: low is shed while normal and high still admit
    with pytest.raises(RejectedError) as ei:
        ac.admit(priority="low", tenant="free-18")
    assert ei.value.shed is True and ei.value.priority == "low"
    ac.admit(2, priority="normal", tenant="acme-18")     # 7 pending
    with pytest.raises(RejectedError) as ei:
        ac.admit(priority="normal", tenant="acme-18")
    assert ei.value.shed is True
    # high admits to the hard cap; only THEN does it see a 429, and that
    # refusal is a hard-full reject, not a shed
    ac.admit(3, priority="high", tenant="acme-18")       # 10 pending
    with pytest.raises(RejectedError) as ei:
        ac.admit(priority="high", tenant="acme-18")
    assert ei.value.shed is False and ei.value.priority == "high"

    assert ac.shed == 2 and ac.rejected == 3
    assert _counter_value(_n.SERVE_SHED_TOTAL,
                          tenant="free-18", priority="low") == 1
    assert _counter_value(_n.SERVE_SHED_TOTAL,
                          tenant="acme-18", priority="normal") == 1
    # the hard-full high reject never lands in the shed counter
    assert _counter_value(_n.SERVE_SHED_TOTAL,
                          tenant="acme-18", priority="high") == 0


def test_priority_flows_through_router():
    rs = ReplicaSet(2, max_batch=8, max_latency_s=0.001, max_queue=32)
    try:
        rs.register("mlp", _mlp(), version="v1")
        out = rs.submit("mlp", _x(), priority="low",
                        tenant="acme-18").result(timeout=30)
        assert np.asarray(out["predictions"]).shape == (2, N_OUT)
    finally:
        rs.close()


# ----------------------------------------------------------- warm scale-up

def test_scale_out_warm_hits_compile_cache(monkeypatch):
    from deeplearning4j_tpu.observability.compile_tracker import (
        global_tracker,
    )
    monkeypatch.setenv("DL4J_COMPILE_CACHE", "1")
    rs = ReplicaSet(1, max_batch=8, max_latency_s=0.001, max_queue=32,
                    warmup=True)
    try:
        # cold: replica 0's warmup populates the persistent cache with
        # every bucket program
        rs.register("mlp", _mlp(), version="v1")
        n0 = len(global_tracker().snapshot_events())
        r = rs.add_replica(reason="t-warm")
        ev = global_tracker().snapshot_events()[n0:]
        # the pinned acceptance: a hot scale-up resolves EVERY program from
        # disk (the fingerprint sheds the ~r<i> decoration) — no fresh XLA
        # compile stands between the decision and a routable replica
        assert ev, "scale-out must warm every bucket program"
        assert all(e.get("cache_hit") for e in ev), \
            f"fresh compile on hot scale-up: {ev}"
        out = r.batcher.submit("mlp", _x()).result(timeout=30)
        assert np.asarray(out["predictions"]).shape == (2, N_OUT)
    finally:
        rs.close()


# ------------------------------------------------------- names, HTTP, CLI

def test_autoscale_metric_names_registered():
    for name in (_n.SERVE_FLEET_SIZE, _n.SERVE_SCALE_EVENTS_TOTAL,
                 _n.SERVE_SHED_TOTAL):
        assert name in _n.ALL_METRIC_NAMES
        assert name.startswith("dl4j_serve_")


def test_http_autoscaler_status_and_priority_headers():
    import http.client

    from deeplearning4j_tpu.keras_server import InferenceServer
    from deeplearning4j_tpu.keras_server.serving import (
        PRIORITY_HEADER, TENANT_HEADER,
    )

    srv = InferenceServer(autoscale=True, min_replicas=1, max_replicas=2,
                          autoscale_cooldown_s=300.0, max_batch=8,
                          max_latency_s=0.002, max_queue=64)
    srv.register("mlp", _mlp(), version="v1")
    srv.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)
        x = np.zeros((2, N_IN), np.float32)
        conn.request("POST", "/v1/predict",
                     body=json.dumps({"model": "mlp",
                                      "inputs": x.tolist()}),
                     headers={"Content-Type": "application/json",
                              PRIORITY_HEADER: "low",
                              TENANT_HEADER: "acme-18"})
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 200
        assert np.asarray(body["predictions"]).shape == (2, N_OUT)

        conn.request("GET", "/serve/status")
        st = json.loads(conn.getresponse().read())
        asc = st["autoscaler"]
        assert asc["running"] is True
        assert asc["min_replicas"] == 1 and asc["max_replicas"] == 2
        assert asc["n_replicas"] >= 1 and "cooldown_s" in asc
        assert "last_scale_out_latency_s" in asc
    finally:
        srv.stop()


def test_cli_serve_autoscale_parser():
    from deeplearning4j_tpu.cli import build_parser

    args = build_parser().parse_args(
        ["serve", "--model", "m.zip", "--autoscale", "--min-replicas", "1",
         "--max-replicas", "4", "--autoscale-cooldown-s", "5", "--port",
         "0"])
    assert args.autoscale is True
    assert args.min_replicas == 1 and args.max_replicas == 4
    assert args.autoscale_cooldown_s == 5.0
    # the axis is opt-in: a bare serve invocation stays static
    base = build_parser().parse_args(["serve", "--model", "m.zip"])
    assert base.autoscale is False
    assert base.min_replicas is None and base.max_replicas is None


# ------------------------------------------------------------ ramp A/B shape

def test_ramp_ab_record_shape(tmp_path):
    from deeplearning4j_tpu.keras_server import run_ramp_ab

    rec_path = tmp_path / "ramp.jsonl"
    rec = run_ramp_ab(
        _mlp(), model="mlp", qps_low=15.0, segment_s=0.6, slo_ms=1000.0,
        min_replicas=1, max_replicas=2, cooldown_s=0.5, interval_s=0.1,
        max_batch=8, max_latency_s=0.002, max_queue=64,
        example=np.zeros((1, N_IN), np.float32), workers=4,
        record_path=str(rec_path))

    assert rec["harness"] == "keras_server.loadgen.run_ramp_ab"
    assert rec["model"] == "mlp"
    assert rec["qps_high"] == pytest.approx(150.0)
    assert rec["min_replicas"] == 1 and rec["max_replicas"] == 2
    assert rec["avg_replicas_auto"] >= 1.0
    assert rec["static_replicas"] >= 1
    for phase in ("auto", "static"):
        ph = rec[phase]
        assert ph["requests"] > 0 and ph["ok"] > 0
        assert ph["p99_ms"] >= ph["p50_ms"] >= 0.0
        assert "slo_violation_seconds" in ph and "rejected" in ph
    # the acceptance floor fields the capture host asserts on
    assert rec["slo_violation_seconds_auto"] == \
        rec["auto"]["slo_violation_seconds"]
    assert rec["slo_violation_seconds_static"] == \
        rec["static"]["slo_violation_seconds"]
    assert isinstance(rec["auto_beats_static"], bool)
    assert "scale_out_latency_s" in rec and "scale_events" in rec
    # zero lost in-flight requests across the whole autoscaled ramp — the
    # drain-without-loss contract under real churn
    assert rec["lost_requests"] == 0
    assert rec["auto"]["lost"] == 0

    lines = rec_path.read_text().strip().splitlines()
    assert len(lines) == 1 and json.loads(lines[0])["model"] == "mlp"
