"""Distributed (parameter-averaging) Word2Vec + TextPipeline."""
import numpy as np

from deeplearning4j_tpu.nlp.distributed import SparkWord2Vec, TextPipeline


CORPUS = ([f"the king sits on the royal throne {i}" for i in range(10)]
          + [f"the queen sits on the royal throne {i}" for i in range(10)]
          + [f"dogs chase cats in the garden {i}" for i in range(10)]
          + [f"cats flee from dogs in the garden {i}" for i in range(10)])


def test_text_pipeline_tokenize_and_vocab():
    p = TextPipeline(num_workers=3, min_word_frequency=2)
    seqs = p.tokenize(["The king! The KING.", "a queen?"])
    assert seqs[0][0] == seqs[0][2] == "the"
    counts = p.word_counts(seqs)
    assert counts["the"] == 2 and counts["king"] == 2
    cache = p.build_vocab(seqs)
    assert cache.word_for("the") is not None
    assert cache.word_for("queen") is None  # below min frequency


def test_spark_word2vec_learns_cooccurrence():
    w2v = SparkWord2Vec(num_workers=3, averaging_rounds=2,
                        vector_length=24, window=3, min_word_frequency=2,
                        seed=7, use_hierarchic_softmax=False,
                        negative=5, learning_rate=0.05)
    w2v.fit(CORPUS)
    assert w2v.get_word_vector("king").shape == (24,)
    # words from the same topic should be closer than cross-topic words
    royal = w2v.similarity("king", "queen")
    cross = w2v.similarity("king", "garden")
    assert np.isfinite(royal) and np.isfinite(cross)
    assert royal > cross
    assert "king" not in w2v.words_nearest("king", 3)


def test_averaging_is_deterministic():
    kw = dict(num_workers=2, vector_length=8, window=2, seed=3,
              min_word_frequency=1, use_hierarchic_softmax=True)
    a = SparkWord2Vec(**kw).fit(CORPUS[:8])
    b = SparkWord2Vec(**kw).fit(CORPUS[:8])
    np.testing.assert_allclose(np.asarray(a.master.lookup.syn0),
                               np.asarray(b.master.lookup.syn0))
