"""Trace-attribution engine tests: the stdlib XPlane parser against the
committed golden fixture (top-op ordering, category split closure,
truncation -> error record), the TraceSession single-owner lock +
persistent index, the anomaly/first-healthy triggers (fake clock: fires
once, cool-down re-arm, disabled off), span flight-recorder events, the
/train/profiles endpoints, and an end-to-end CPU trace capture through a
real fit() via ProfilerListener."""
import json
import os
import urllib.parse
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.observability import (FlightRecorder, MetricsRegistry,
                                              span)
from deeplearning4j_tpu.observability import profiler as prof_mod
from deeplearning4j_tpu.observability import xplane
from deeplearning4j_tpu.observability.names import (PROFILE_CAPTURES_TOTAL,
                                                    PROFILE_COLLISIONS_TOTAL)
from deeplearning4j_tpu.observability.profiler import (StepAnomalyWatcher,
                                                       TraceSession,
                                                       note_dispatch,
                                                       set_global_trace_session,
                                                       uninstall_anomaly_watcher)
from deeplearning4j_tpu.optimize.listeners import ProfilerListener
from deeplearning4j_tpu.ui import UIServer

GOLDEN = os.path.join(os.path.dirname(__file__), "golden", "xplane_golden.pb")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _small_net():
    conf = (NeuralNetConfiguration.builder()
            .seed(0).learning_rate(0.1)
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                               activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def _xy(n=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.zeros((n, 3), np.float32)
    y[np.arange(n), rng.integers(0, 3, n)] = 1
    return x, y


def _session(tmp_path, **kw):
    """Private TraceSession: its own registry + recorder, index under tmp."""
    reg = MetricsRegistry()
    rec = FlightRecorder(capacity=64)
    return TraceSession(base_dir=str(tmp_path / "profiles"), registry=reg,
                        recorder=rec, **kw), reg, rec


# ------------------------------------------------------------ XPlane parser

def test_golden_top_op_ordering_and_plane_selection():
    s = xplane.summarize(GOLDEN)
    assert "error" not in s
    # device plane preferred; host plane excluded from the op summary
    assert s["summarized_planes"] == ["/device:TPU:0"]
    assert s["planes"] == ["/device:TPU:0", "/host:CPU"]
    ops = [o["op"].split(" ")[0] for o in s["top_ops"]]
    assert ops == ["%convolution.42", "%dot.3", "%convert_reduce_fusion.7",
                   "%multiply_add_fusion.9", "%all-reduce.1", "%copy.4"]
    assert [o["pct"] for o in s["top_ops"]] == [40.0, 30.0, 20.0, 5.0,
                                                3.0, 2.0]
    # the while wrapper (99ms) and the XLA Modules container line were
    # excluded: counted total is exactly the six real ops
    assert s["total_device_ns"] == 100_000


def test_golden_category_split_sums_to_total():
    s = xplane.summarize(GOLDEN)
    assert s["categories_pct"] == {
        "conv": 40.0, "matmul/custom": 30.0, "fusion:reduce": 20.0,
        "fusion:compute": 5.0, "collective": 3.0, "datamovement": 2.0}
    assert sum(s["categories_pct"].values()) == pytest.approx(100.0, abs=0.1)


def test_golden_fn_share_and_bookkeeping_filter():
    s = xplane.summarize(GOLDEN)
    # host pjit spans -> per-fn share; the $profiler bookkeeping event
    # (4.4s, bigger than everything) is filtered, not attributed
    assert s["fn_pct"] == {"multistep": 70.0, "train_step": 30.0}
    assert not any("start_trace" in o["op"] for o in s["top_ops"])


def test_generator_matches_committed_fixture():
    """The committed binary is exactly what the generator emits — edit the
    generator, rerun it, and commit both or this fails."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "make_xplane_golden",
        os.path.join(os.path.dirname(__file__), "golden",
                     "make_xplane_golden.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    with open(GOLDEN, "rb") as f:
        assert mod.build() == f.read()


def test_truncated_and_malformed_proto_error_record(tmp_path):
    with open(GOLDEN, "rb") as f:
        data = f.read()
    trunc = tmp_path / "t" / "host.xplane.pb"
    trunc.parent.mkdir()
    trunc.write_bytes(data[:len(data) // 2])
    s = xplane.summarize(str(tmp_path / "t"))
    assert "error" in s and "top_ops" not in s  # record, not a crash
    trunc.write_bytes(b"\x0f\xff\xff\xff")  # wire type 7: malformed
    assert "error" in xplane.summarize(str(tmp_path / "t"))
    with pytest.raises(xplane.XPlaneParseError):
        xplane.parse_planes(data[:len(data) // 2])


def test_summarize_empty_dir_error(tmp_path):
    s = xplane.summarize(str(tmp_path))
    assert "error" in s and "no xplane.pb" in s["error"]


# ------------------------------------------------------------- TraceSession

def test_trace_session_lock_collision_and_index(tmp_path, caplog):
    session, reg, rec = _session(tmp_path)
    logdir = session.start("manual")
    try:
        assert logdir is not None and os.path.isdir(logdir)
        assert session.active == "manual"
        # second owner: warning + no-op + collision counter, never a raise
        with caplog.at_level("WARNING"):
            assert session.start("listener") is None
        assert "already active" in caplog.text
        assert reg.counter(PROFILE_COLLISIONS_TOTAL, "").labels(
            trigger="listener").value == 1
    finally:
        session.stop(summarize=False)
    assert session.active is None
    assert reg.counter(PROFILE_CAPTURES_TOTAL, "").labels(
        trigger="manual").value == 1
    kinds = [e["kind"] for e in rec.snapshot()]
    assert "profile_start" in kinds and "profile_capture" in kinds
    # persistent index: a NEW session over the same base_dir sees the capture
    fresh = TraceSession(base_dir=session.base_dir,
                         registry=MetricsRegistry(), recorder=rec)
    entries = fresh.index_entries()
    assert len(entries) == 1
    assert entries[0]["logdir"] == logdir
    assert entries[0]["trigger"] == "manual"


def test_trace_session_capture_contextmanager_busy(tmp_path):
    session, reg, _ = _session(tmp_path)
    with session.capture("outer") as outer:
        assert outer is not None
        with session.capture("inner") as inner:
            assert inner is None  # busy: yields None, skips the stop
        assert session.active == "outer"  # inner ctx did not stop the outer
    assert session.active is None


def test_trace_session_stop_without_start_is_noop(tmp_path):
    session, _, _ = _session(tmp_path)
    assert session.stop() is None


# ---------------------------------------------------------- anomaly trigger

class _FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


class _FakeSession:
    """Duck-typed TraceSession: counts starts/stops, no real profiler."""

    def __init__(self):
        self.starts = []
        self.stops = 0

    def start(self, trigger, logdir=None):
        self.starts.append(trigger)
        return f"/fake/{len(self.starts)}"

    def stop(self, summarize=True):
        self.stops += 1
        return {}

    def _rec(self):
        return None


def test_anomaly_fires_once_and_rearms_after_cooldown():
    clock = _FakeClock()
    fake = _FakeSession()
    w = StepAnomalyWatcher(session=fake, k=3.0, min_samples=4,
                           cooldown_s=100.0, capture_dispatches=2,
                           clock=clock)
    for _ in range(4):
        w.observe(0.01)
    w.observe(0.5)  # > 3 x p50: fires
    assert fake.starts == ["anomaly"] and w.fired == 1
    # the next two dispatches run under the trace, then it closes
    w.observe(0.01)
    assert fake.stops == 0
    w.observe(0.01)
    assert fake.stops == 1
    # inside the cool-down: another slow step does NOT re-fire
    w.observe(0.6)
    assert w.fired == 1 and len(fake.starts) == 1
    # past the cool-down: re-arms
    clock.t += 101.0
    w.observe(0.6)
    assert w.fired == 2 and fake.starts == ["anomaly", "anomaly"]


def test_anomaly_quiet_below_threshold_and_never_raises():
    fake = _FakeSession()
    w = StepAnomalyWatcher(session=fake, k=3.0, min_samples=4,
                           cooldown_s=100.0, clock=_FakeClock())
    for _ in range(50):
        w.observe(0.01)
    w.observe(0.029)  # 2.9x p50: below k
    assert fake.starts == [] and w.fired == 0
    w.observe(float("nan"))  # pathological input must not raise
    w.observe("not-a-number")


def test_anomaly_capture_counts_in_registry(tmp_path):
    """Acceptance pin: an injected slow step captures a REAL trace exactly
    once, asserted via dl4j_profile_captures_total{trigger="anomaly"}."""
    session, reg, _ = _session(tmp_path)
    clock = _FakeClock()
    w = StepAnomalyWatcher(session=session, k=3.0, min_samples=4,
                           cooldown_s=3600.0, capture_dispatches=1,
                           clock=clock)
    for _ in range(4):
        w.observe(0.01)
    w.observe(0.5)   # fires: real jax.profiler trace starts
    w.observe(0.01)  # closes the window -> stop + summarize + index
    w.observe(0.5)   # inside cool-down: must not fire again
    assert w.fired == 1
    assert reg.counter(PROFILE_CAPTURES_TOTAL, "").labels(
        trigger="anomaly").value == 1
    entries = session.index_entries()
    assert len(entries) == 1 and entries[0]["trigger"] == "anomaly"


def test_note_dispatch_disabled_off(monkeypatch):
    monkeypatch.delenv(prof_mod.TRIGGER_ENV, raising=False)
    uninstall_anomaly_watcher()
    try:
        note_dispatch(99.0)  # resolves to "off" once...
        assert prof_mod._WATCHER is None and prof_mod._WATCHER_RESOLVED
        note_dispatch(99.0)  # ...then short-circuits forever
        assert prof_mod._WATCHER is None
    finally:
        uninstall_anomaly_watcher()


def test_note_dispatch_env_resolution(monkeypatch):
    monkeypatch.setenv(prof_mod.TRIGGER_ENV, "anomaly")
    monkeypatch.setenv(prof_mod.ANOMALY_K_ENV, "5.5")
    uninstall_anomaly_watcher()
    try:
        note_dispatch(0.01)
        w = prof_mod._WATCHER
        assert isinstance(w, StepAnomalyWatcher) and w.k == 5.5
        assert len(w._times) == 1
    finally:
        uninstall_anomaly_watcher()


def test_fit_loop_feeds_note_dispatch():
    """The multilayer dispatch sites call note_dispatch: an installed
    watcher sees one sample per fit dispatch."""
    fake = _FakeSession()
    w = StepAnomalyWatcher(session=fake, k=1e9, min_samples=2,
                           cooldown_s=1.0, clock=_FakeClock())
    prof_mod.install_anomaly_watcher(w)
    try:
        net = _small_net()
        x, y = _xy()
        net.fit_iterator(ListDataSetIterator([DataSet(x, y)] * 5))
        # the multistep engine may coalesce all 5 batches into one dispatch;
        # at least one sample must land either way
        assert len(w._times) >= 1
        assert fake.starts == []  # k=1e9: healthy run never triggers
    finally:
        uninstall_anomaly_watcher()


# ------------------------------------------------------ first-healthy trigger

def test_first_healthy_marker_cross_process(tmp_path, monkeypatch):
    base = str(tmp_path / "p")
    monkeypatch.setenv(prof_mod.TRIGGER_ENV, "first-healthy")
    monkeypatch.setenv(prof_mod.DIR_ENV, base)
    assert prof_mod.first_healthy_due() is True
    prof_mod.mark_first_healthy()
    assert prof_mod.first_healthy_due() is False  # inside the cool-down
    assert prof_mod.first_healthy_due(cooldown_s=0.0) is True  # expired
    monkeypatch.setenv(prof_mod.TRIGGER_ENV, "anomaly")
    assert prof_mod.first_healthy_due() is False  # wrong trigger mode
    monkeypatch.delenv(prof_mod.TRIGGER_ENV)
    assert prof_mod.first_healthy_due() is False


# ----------------------------------------------- e2e capture through fit()

def test_e2e_cpu_fit_capture_via_profiler_listener(tmp_path):
    """Acceptance pin: a TraceSession capture through a real CPU fit()
    produces a trace dir + attribution JSON whose category shares sum to
    ~100%, with no direct jax.profiler calls in the listener."""
    prev = set_global_trace_session(
        TraceSession(base_dir=str(tmp_path / "profiles")))
    try:
        listener = ProfilerListener(str(tmp_path / "trace"),
                                    start_iteration=2, num_iterations=2)
        net = _small_net()
        net.listeners.append(listener)
        x, y = _xy()
        net.fit_iterator(ListDataSetIterator([DataSet(x, y)] * 8))
        assert len(listener.windows) == 1
        logdir = listener.windows[0]
        assert xplane.find_trace(logdir) is not None  # real .xplane.pb
        summary = listener.summaries[0]
        assert summary is not None and "error" not in summary, summary
        shares = summary["categories_pct"]
        assert shares and sum(shares.values()) == pytest.approx(100.0,
                                                                abs=1.0)
        # ...and the attribution JSON sits next to the trace
        with open(os.path.join(logdir, prof_mod.ATTRIBUTION_FILE)) as f:
            assert json.load(f)["categories_pct"] == shares
        # the capture is in the persistent index
        entries = prof_mod.global_trace_session().index_entries()
        assert any(e["logdir"] == logdir and e["trigger"] == "listener"
                   for e in entries)
    finally:
        set_global_trace_session(prev)


def test_no_direct_profiler_calls_outside_engine():
    """profile_flagship.py and ProfilerListener must not drive
    jax.profiler.start_trace/stop_trace themselves — all capture flows
    through the single locked TraceSession."""
    for rel in ("scripts/profile_flagship.py",
                "deeplearning4j_tpu/optimize/listeners.py"):
        with open(os.path.join(REPO, rel)) as f:
            src = f.read()
        assert "jax.profiler.start_trace" not in src, rel
        assert "jax.profiler.stop_trace" not in src, rel
        assert "profiler.start_trace" not in src, rel


# ------------------------------------------------------------- span events

def test_span_emits_flight_recorder_events():
    rec = FlightRecorder(capacity=16)
    reg = MetricsRegistry()
    with span("epoch/0/fwd", metric_name="epoch", registry=reg,
              recorder=rec):
        pass
    kinds = [(e["kind"], e["name"]) for e in rec.snapshot()]
    assert kinds == [("span_enter", "epoch/0/fwd"),
                     ("span_exit", "epoch/0/fwd")]
    exit_ev = rec.snapshot()[-1]
    assert exit_ev["dur_s"] >= 0.0


def test_span_exit_recorded_on_exception():
    rec = FlightRecorder(capacity=16)
    with pytest.raises(RuntimeError):
        with span("doomed", registry=MetricsRegistry(), recorder=rec):
            raise RuntimeError("boom")
    assert [e["kind"] for e in rec.snapshot()] == ["span_enter", "span_exit"]


# ------------------------------------------------------------ UI endpoints

def test_train_profiles_endpoints(tmp_path):
    session = TraceSession(base_dir=str(tmp_path / "profiles"))
    prev = set_global_trace_session(session)
    server = UIServer(port=0)
    try:
        logdir = session.start("manual")
        assert logdir is not None
        session.stop()  # summarize=True writes attribution.json (even as
        #                 an error record when the trace is host-only/empty)
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(base + "/train/profiles") as r:
            assert r.status == 200
            idx = json.loads(r.read())
        assert idx["active"] is None
        assert len(idx["profiles"]) == 1
        assert idx["profiles"][0]["logdir"] == logdir
        q = urllib.parse.quote(logdir, safe="")
        with urllib.request.urlopen(
                base + f"/train/profiles/summary?trace={q}") as r:
            assert r.status == 200
            summary = json.loads(r.read())
        assert "categories_pct" in summary or "error" in summary
        # unknown trace: the index is the allow-list
        with urllib.request.urlopen(
                base + "/train/profiles/summary?trace=%2Fetc%2Fpasswd") as r:
            assert json.loads(r.read())["error"] == \
                "trace not in the profile index"
    finally:
        server.stop()
        set_global_trace_session(prev)


# -------------------------------------------------------- bench integration

@pytest.mark.slow
def test_bench_xplane_attribution_end_to_end(tmp_path):
    """bench.py --xplane-attribution attaches the category split (or a
    graceful profile_error) to the record without touching the headline."""
    import subprocess
    import sys

    import bench

    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               DL4J_PROFILE_DIR=str(tmp_path / "profiles"))
    env.pop("DL4J_PROFILE_TRIGGER", None)
    cmd = [sys.executable, os.path.join(os.path.dirname(bench.__file__),
                                        "bench.py"),
           "--model", "lenet", "--batch", "8", "--iters", "2",
           "--ksteps", "1", "--xplane-attribution",
           "--attempts", "1", "--attempt-timeout", "180"]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=200,
                          env=env)
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert "error" not in rec, rec
    assert rec["value"] > 0
    detail = rec["detail"]
    if "profile_error" in detail:  # graceful degradation is in-contract
        assert isinstance(detail["profile_error"], str)
    else:
        att = detail["xplane_attribution"]
        assert sum(att["categories_pct"].values()) == pytest.approx(
            100.0, abs=1.0)
        assert detail["profile_trace"].startswith(str(tmp_path))
