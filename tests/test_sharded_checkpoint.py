"""Sharded orbax checkpointing on the virtual 8-device mesh (SURVEY §5:
"orbax-style checkpoint of {config-json, params, opt-state, normalizer}" —
the TPU-native alternative to the single-host zip container)."""
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _trained_net():
    conf = (NeuralNetConfiguration.builder()
            .seed(5).learning_rate(0.05).updater("adam")
            .list()
            .layer(DenseLayer(n_in=4, n_out=16, activation="tanh"))
            .layer(OutputLayer(n_in=16, n_out=3, loss="mcxent",
                               activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 4)).astype(np.float32)
    y = np.zeros((16, 3), np.float32)
    y[np.arange(16), rng.integers(0, 3, 16)] = 1
    net.fit(x, y)
    net.fit(x, y)
    return net, x, y


def test_save_restore_roundtrip_and_resume(tmp_path):
    import jax

    from deeplearning4j_tpu.utils.sharded_checkpoint import (
        restore_sharded, save_sharded)

    net, x, y = _trained_net()
    out_before = np.asarray(net.output(x))
    save_sharded(str(tmp_path / "ckpt"), net, step=2)

    restored = restore_sharded(str(tmp_path / "ckpt"))  # rebuilt from config
    assert restored.iteration == net.iteration
    np.testing.assert_allclose(np.asarray(restored.output(x)), out_before,
                               rtol=1e-6, atol=1e-7)
    # updater state restored exactly -> identical continued trajectory
    net.fit(x, y)
    restored.fit(x, y)
    for a, b in zip(jax.tree_util.tree_leaves(net.params_list),
                    jax.tree_util.tree_leaves(restored.params_list)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_restore_onto_mesh_sharding(tmp_path):
    """Restore places leaves DIRECTLY onto a mesh sharding — the multi-host
    path where no single host materializes the full tree."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.parallel.mesh import build_mesh
    from deeplearning4j_tpu.utils.sharded_checkpoint import (
        restore_sharded, save_sharded)

    net, x, _ = _trained_net()
    save_sharded(str(tmp_path / "ckpt"), net)

    mesh = build_mesh({"model": 8})
    # shard every 2-D param's output dim over 'model'; replicate the rest
    shardings = [
        {name: NamedSharding(mesh,
                             P(None, "model") if p.ndim == 2
                             and p.shape[1] % 8 == 0 else P())
         for name, p in layer_params.items()}
        for layer_params in net.params_list]
    restored = restore_sharded(str(tmp_path / "ckpt"),
                               MultiLayerNetwork(net.conf),
                               shardings=shardings)
    w0 = restored.params_list[0]["W"]  # (4, 16) sharded over 8 devices
    assert len(w0.sharding.device_set) == 8
    np.testing.assert_allclose(np.asarray(w0),
                               np.asarray(net.params_list[0]["W"]),
                               rtol=0, atol=0)
    np.testing.assert_allclose(np.asarray(restored.output(x)),
                               np.asarray(net.output(x)),
                               rtol=1e-6, atol=1e-6)


def test_async_saver_overlaps_and_roundtrips(tmp_path):
    """AsyncShardedSaver: the save returns before the write lands (training
    continues), wait() flushes it, and the checkpoint restores identically
    to the synchronous path."""
    from deeplearning4j_tpu.utils.sharded_checkpoint import (
        AsyncShardedSaver, restore_sharded)

    net, x, y = _trained_net()
    ckdir = str(tmp_path / "async_ck")
    with AsyncShardedSaver() as saver:
        saver.save(ckdir, net)
        net.fit(x, y)  # training continues while the write is in flight
        saver.wait()
    restored = restore_sharded(ckdir)
    # the checkpoint captured the PRE-continuation state (device buffers
    # snapshot at save time, not at wait time): params differ from the
    # post-fit net but the restored net must be internally consistent
    out_r = np.asarray(restored.output(x))
    assert np.isfinite(out_r).all()
    assert restored.iteration <= net.iteration
    # bitwise match against a sync save taken at the same point is pinned
    # by saving again synchronously and comparing restored trees
    from deeplearning4j_tpu.utils.sharded_checkpoint import save_sharded
    sync_dir = str(tmp_path / "sync_ck")
    save_sharded(sync_dir, net)
    sync_restored = restore_sharded(sync_dir)
    out_s = np.asarray(sync_restored.output(x))
    assert out_s.shape == out_r.shape


def test_checkpoint_listener_sharded_mode(tmp_path):
    """CheckpointListener(sharded=True): the listener SPI writes orbax
    sharded directories with rotation + LATEST pointer, and the pointed-at
    checkpoint restores a working net (crash-resume without host gather)."""
    from deeplearning4j_tpu.optimize.listeners import CheckpointListener
    from deeplearning4j_tpu.utils.sharded_checkpoint import restore_sharded

    net, x, y = _trained_net()
    d = str(tmp_path / "ck")
    lis = CheckpointListener(d, every_n_iterations=1, every_n_epochs=None,
                             keep_last=2, sharded=True)
    net.listeners.append(lis)
    for _ in range(4):
        net.fit(x, y)
    import os
    dirs = [p for p in os.listdir(d) if p.startswith("checkpoint_")]
    assert len(dirs) == 2  # rotation kept last 2
    last = CheckpointListener.last_checkpoint(d)
    assert last is not None and os.path.isdir(last)
    restored = restore_sharded(last)
    assert np.isfinite(np.asarray(restored.output(x))).all()
    assert restored.iteration == net.iteration


def test_rolling_saves_to_one_directory(tmp_path):
    """Repeated saves to the same directory replace the previous state
    (orbax refuses overwrites; the savers clear stale state first) — both
    sync and async paths."""
    from deeplearning4j_tpu.utils.sharded_checkpoint import (
        AsyncShardedSaver, restore_sharded, save_sharded)

    net, x, y = _trained_net()
    d = str(tmp_path / "roll")
    save_sharded(d, net)
    net.fit(x, y)
    save_sharded(d, net)          # second sync save, same dir
    assert restore_sharded(d).iteration == net.iteration
    with AsyncShardedSaver() as saver:
        net.fit(x, y)
        saver.save(d, net)        # async over an existing sync checkpoint
        net.fit(x, y)
        saver.save(d, net)        # rolling async save
    assert restore_sharded(d).iteration == net.iteration


def test_async_sidecar_commits_only_after_wait(tmp_path):
    """The config/meta sidecar is the checkpoint's COMMIT MARKER: it must
    not exist while the background array write is (possibly still) in
    flight, and must appear once wait() confirms the write landed — so a
    crash mid-save can never leave a sidecar endorsing torn array state."""
    import os

    from deeplearning4j_tpu.utils.sharded_checkpoint import (
        AsyncShardedSaver, restore_sharded)

    net, x, _ = _trained_net()
    d = str(tmp_path / "commit_ck")
    with AsyncShardedSaver() as saver:
        saver.save(d, net)
        assert not os.path.exists(os.path.join(d, "meta.json"))
        assert not os.path.exists(os.path.join(d, "config.json"))
        saver.wait()
        assert os.path.exists(os.path.join(d, "meta.json"))
        assert os.path.exists(os.path.join(d, "config.json"))

    restored = restore_sharded(d)
    assert restored.iteration == net.iteration
    np.testing.assert_allclose(np.asarray(restored.output(x)),
                               np.asarray(net.output(x)), rtol=1e-6)


def test_async_rolling_save_commits_previous_directory(tmp_path):
    """A second save() first waits out the in-flight write and commits ITS
    sidecar — rolling saves across directories leave every completed
    checkpoint committed, with the snapshot taken at save() time (the
    committed iteration matches the arrays, not later training)."""
    import json
    import os

    from deeplearning4j_tpu.utils.sharded_checkpoint import AsyncShardedSaver

    net, x, y = _trained_net()
    d1 = str(tmp_path / "ck1")
    d2 = str(tmp_path / "ck2")
    with AsyncShardedSaver() as saver:
        saver.save(d1, net)
        it1 = int(net.iteration)
        net.fit(x, y)  # train on while the write is in flight
        saver.save(d2, net)
        # the first checkpoint must now be fully committed...
        assert os.path.exists(os.path.join(d1, "meta.json"))
        # ...with the iteration captured at ITS save() time
        with open(os.path.join(d1, "meta.json")) as f:
            assert json.load(f)["iteration"] == it1
        # the second is still uncommitted until wait()
        assert not os.path.exists(os.path.join(d2, "meta.json"))
    assert os.path.exists(os.path.join(d2, "meta.json"))


def test_restore_refuses_uncommitted_checkpoint(tmp_path):
    """Array state without the sidecar == a save that crashed before
    wait()/close(): restore must refuse loudly instead of resurrecting a
    torn checkpoint."""
    import os

    import pytest as _pytest

    from deeplearning4j_tpu.utils.sharded_checkpoint import (
        restore_sharded, save_sharded)

    net, _, _ = _trained_net()
    d = str(tmp_path / "torn_ck")
    save_sharded(d, net)
    # simulate the crash window: arrays landed, commit marker never written
    os.remove(os.path.join(d, "meta.json"))
    os.remove(os.path.join(d, "config.json"))
    with _pytest.raises(RuntimeError, match="no committed sidecar"):
        restore_sharded(d)
