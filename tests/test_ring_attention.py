"""Ring/Ulysses attention == single-device attention on the virtual 8-device mesh
(the equivalence-test pattern of reference
TestCompareParameterAveragingSparkVsSingleMachine applied to context parallelism)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from deeplearning4j_tpu.parallel.ring_attention import (
    attention_reference, ring_attention, ulysses_attention,
)


def _mesh(n=8, name="sp"):
    return Mesh(np.array(jax.devices()[:n]), (name,))


def _qkv(B=2, T=64, H=8, D=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, T, H, D)).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    q, k, v = _qkv()
    expect = attention_reference(q, k, v, causal=causal)
    got = ring_attention(q, k, v, _mesh(), causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_reference(causal):
    q, k, v = _qkv()
    expect = attention_reference(q, k, v, causal=causal)
    got = ulysses_attention(q, k, v, _mesh(), causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_gradients_match():
    q, k, v = _qkv(B=1, T=32, H=4, D=8, seed=3)
    mesh = _mesh(4)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


def test_ulysses_rejects_indivisible_heads():
    q, k, v = _qkv(H=6)
    with pytest.raises(ValueError):
        ulysses_attention(q, k, v, _mesh(8))


def test_ring_attention_long_sequence_sharded_memory():
    """Each device only ever holds T/N keys — run a longer sequence through and
    check output correctness end-to-end."""
    q, k, v = _qkv(B=1, T=256, H=4, D=16, seed=9)
    expect = attention_reference(q, k, v, causal=True)
    got = ring_attention(q, k, v, _mesh(), causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=2e-4, atol=2e-5)


def test_ulysses_pallas_interpret_matches_reference():
    """The pallas flash kernel running UNDER shard_map (interpret mode on the
    CPU mesh) must equal the reference math — without this, the TPU ulysses
    path would ship exercised only through the XLA fallback."""
    import numpy as np

    from deeplearning4j_tpu.parallel.mesh import build_mesh
    from deeplearning4j_tpu.parallel.ring_attention import (
        attention_reference, ulysses_attention)

    n = 4
    mesh = build_mesh({"sp": n})
    rng = np.random.default_rng(0)
    # T multiple of blk after gather; H divisible by axis
    q, k, v = (jnp.asarray(rng.normal(size=(2, 16 * n, n, 8))
                           .astype(np.float32)) for _ in range(3))
    got = ulysses_attention(q, k, v, mesh, causal=True, interpret=True)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_ulysses_flash_kernel_engages_in_sharded_body(monkeypatch):
    """The equivalence test above can pass even if dispatch silently routes
    to the O(T^2) XLA fallback (both paths compute the same math). This pins
    ENGAGEMENT: inside ulysses' check_vma=False shard_map body, _pallas_ok
    must accept and the flash kernel must actually be entered."""
    from deeplearning4j_tpu.ops import pallas_kernels as pk
    from deeplearning4j_tpu.parallel.mesh import build_mesh

    calls = []
    real = pk._flash_forward

    def counting(*args, **kwargs):
        calls.append(1)
        return real(*args, **kwargs)

    monkeypatch.setattr(pk, "_flash_forward", counting)

    n = 4
    mesh = build_mesh({"sp": n})
    rng = np.random.default_rng(1)
    q, k, v = (jnp.asarray(rng.normal(size=(2, 16 * n, n, 8))
                           .astype(np.float32)) for _ in range(3))
    got = ulysses_attention(q, k, v, mesh, causal=True, interpret=True)
    assert calls, ("flash kernel never engaged inside the ulysses shard_map "
                   "body — dispatch regressed to the XLA fallback")
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)
