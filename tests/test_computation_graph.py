"""ComputationGraph tests: DAG topology, multi-input/output, gradient flow.

Reference analog: deeplearning4j-core TestComputationGraphNetwork +
GradientCheckTestsComputationGraph.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graphconf import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ConvolutionLayer, DenseLayer, GravesLSTM, OutputLayer, SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.vertices import (
    ElementWiseVertex, L2NormalizeVertex, LastTimeStepVertex, MergeVertex,
    ScaleVertex, StackVertex, SubsetVertex, UnstackVertex,
)
from deeplearning4j_tpu.nn.graph_network import ComputationGraph, MultiDataSet


def test_simple_chain_equals_mlp():
    conf = (NeuralNetConfiguration.builder()
            .seed(1).learning_rate(0.1)
            .graph_builder()
            .add_inputs("in")
            .add_layer("dense", DenseLayer(n_in=4, n_out=8, activation="tanh"), "in")
            .add_layer("out", OutputLayer(n_in=8, n_out=3, loss="mcxent",
                                          activation="softmax"), "dense")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    x = np.random.default_rng(0).normal(size=(5, 4)).astype(np.float32)
    outs = net.output(x)
    assert len(outs) == 1
    assert outs[0].shape == (5, 3)
    np.testing.assert_allclose(np.asarray(jnp.sum(outs[0], -1)), 1.0, rtol=1e-5)


def test_merge_vertex_two_towers():
    conf = (NeuralNetConfiguration.builder()
            .seed(2).learning_rate(0.1)
            .graph_builder()
            .add_inputs("in1", "in2")
            .add_layer("d1", DenseLayer(n_in=3, n_out=4, activation="relu"), "in1")
            .add_layer("d2", DenseLayer(n_in=5, n_out=6, activation="relu"), "in2")
            .add_vertex("merge", MergeVertex(), "d1", "d2")
            .add_layer("out", OutputLayer(n_in=10, n_out=2, loss="mcxent",
                                          activation="softmax"), "merge")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(1)
    x1 = rng.normal(size=(4, 3)).astype(np.float32)
    x2 = rng.normal(size=(4, 5)).astype(np.float32)
    outs = net.output(x1, x2)
    assert outs[0].shape == (4, 2)
    # training decreases loss
    y = np.zeros((4, 2), np.float32)
    y[:, 0] = 1
    mds = MultiDataSet([x1, x2], [y])
    s0 = net.score(mds)
    for _ in range(30):
        net.fit(mds)
    assert net.score(mds) < s0


def test_residual_elementwise_add():
    conf = (NeuralNetConfiguration.builder()
            .seed(3).learning_rate(0.05)
            .graph_builder()
            .add_inputs("in")
            .add_layer("d1", DenseLayer(n_in=6, n_out=6, activation="relu"), "in")
            .add_vertex("residual", ElementWiseVertex(op="add"), "d1", "in")
            .add_layer("out", OutputLayer(n_in=6, n_out=2, loss="mse",
                                          activation="identity"), "residual")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    x = np.random.default_rng(0).normal(size=(3, 6)).astype(np.float32)
    assert net.output(x)[0].shape == (3, 2)


def test_multi_output():
    conf = (NeuralNetConfiguration.builder()
            .seed(4).learning_rate(0.1)
            .graph_builder()
            .add_inputs("in")
            .add_layer("shared", DenseLayer(n_in=4, n_out=8, activation="tanh"), "in")
            .add_layer("out1", OutputLayer(n_in=8, n_out=3, loss="mcxent",
                                           activation="softmax"), "shared")
            .add_layer("out2", OutputLayer(n_in=8, n_out=1, loss="mse",
                                           activation="identity"), "shared")
            .set_outputs("out1", "out2")
            .build())
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(2)
    x = rng.normal(size=(6, 4)).astype(np.float32)
    y1 = np.zeros((6, 3), np.float32)
    y1[:, 1] = 1
    y2 = rng.normal(size=(6, 1)).astype(np.float32)
    outs = net.output(x)
    assert outs[0].shape == (6, 3) and outs[1].shape == (6, 1)
    mds = MultiDataSet([x], [y1, y2])
    s0 = net.score(mds)
    for _ in range(40):
        net.fit(mds)
    assert net.score(mds) < s0


def test_cnn_input_type_propagation():
    conf = (NeuralNetConfiguration.builder()
            .seed(5).learning_rate(0.01)
            .graph_builder()
            .add_inputs("in")
            .add_layer("conv", ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                                activation="relu"), "in")
            .add_layer("pool", SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)), "conv")
            .add_layer("dense", DenseLayer(n_out=16, activation="relu"), "pool")
            .add_layer("out", OutputLayer(n_out=3, loss="mcxent",
                                          activation="softmax"), "dense")
            .set_outputs("out")
            .set_input_types(InputType.convolutional(10, 10, 2))
            .build())
    # conv n_in inferred from channels; dense n_in from flattened pool output 4*4*4
    assert conf.vertices["conv"].layer.n_in == 2
    assert conf.vertices["dense"].layer.n_in == 4 * 4 * 4
    net = ComputationGraph(conf).init()
    x = np.random.default_rng(0).normal(size=(2, 10, 10, 2)).astype(np.float32)
    assert net.output(x)[0].shape == (2, 3)


def test_last_time_step_vertex():
    conf = (NeuralNetConfiguration.builder()
            .seed(6).learning_rate(0.05)
            .graph_builder()
            .add_inputs("in")
            .add_layer("lstm", GravesLSTM(n_in=3, n_out=5, activation="tanh"), "in")
            .add_vertex("last", LastTimeStepVertex(), "lstm")
            .add_layer("out", OutputLayer(n_in=5, n_out=2, loss="mcxent",
                                          activation="softmax"), "last")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    x = np.random.default_rng(0).normal(size=(4, 7, 3)).astype(np.float32)
    assert net.output(x)[0].shape == (4, 2)


def test_stack_unstack_shared_weights():
    conf = (NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.05)
            .graph_builder()
            .add_inputs("a", "b")
            .add_vertex("stack", StackVertex(), "a", "b")
            .add_layer("shared", DenseLayer(n_in=4, n_out=6, activation="tanh"), "stack")
            .add_vertex("ua", UnstackVertex(index=0, num_stacks=2), "shared")
            .add_vertex("ub", UnstackVertex(index=1, num_stacks=2), "shared")
            .add_vertex("merged", MergeVertex(), "ua", "ub")
            .add_layer("out", OutputLayer(n_in=12, n_out=2, loss="mse",
                                          activation="identity"), "merged")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(3)
    a = rng.normal(size=(3, 4)).astype(np.float32)
    b = rng.normal(size=(3, 4)).astype(np.float32)
    assert net.output(a, b)[0].shape == (3, 2)


def test_graph_json_roundtrip():
    conf = (NeuralNetConfiguration.builder()
            .seed(8).learning_rate(0.1).updater("adam")
            .graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_in=4, n_out=8, activation="relu"), "in")
            .add_vertex("norm", L2NormalizeVertex(), "d")
            .add_vertex("scaled", ScaleVertex(scale=2.0), "norm")
            .add_layer("out", OutputLayer(n_in=8, n_out=3, loss="mcxent",
                                          activation="softmax"), "scaled")
            .set_outputs("out")
            .build())
    s = conf.to_json()
    conf2 = ComputationGraphConfiguration.from_json(s)
    assert conf2.to_json() == s
    net = ComputationGraph(conf2).init()
    x = np.random.default_rng(0).normal(size=(2, 4)).astype(np.float32)
    assert net.output(x)[0].shape == (2, 3)


def test_graph_gradients_match_numeric():
    """Spot gradient check on a small DAG (reference GradientCheckTestsComputationGraph)."""
    conf = (NeuralNetConfiguration.builder()
            .seed(9).learning_rate(0.1)
            .graph_builder()
            .add_inputs("in")
            .add_layer("d1", DenseLayer(n_in=3, n_out=4, activation="tanh"), "in")
            .add_layer("d2", DenseLayer(n_in=3, n_out=4, activation="sigmoid"), "in")
            .add_vertex("sum", ElementWiseVertex(op="add"), "d1", "d2")
            .add_layer("out", OutputLayer(n_in=4, n_out=2, loss="mcxent",
                                          activation="softmax"), "sum")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(5)
    x = rng.normal(size=(4, 3)).astype(np.float32)
    y = np.zeros((4, 2), np.float32)
    y[np.arange(4), rng.integers(0, 2, 4)] = 1
    grads, score = net.gradient_and_score([x], [y])
    # numeric check on a few params of d1.W
    import jax

    eps = 1e-3
    w = np.asarray(net.params_list["d1"]["W"]).copy()
    for (i, j) in [(0, 0), (1, 2), (2, 3)]:
        wp = w.copy(); wp[i, j] += eps
        wm = w.copy(); wm[i, j] -= eps
        net.params_list["d1"]["W"] = jnp.asarray(wp)
        _, sp = net.gradient_and_score([x], [y])
        net.params_list["d1"]["W"] = jnp.asarray(wm)
        _, sm = net.gradient_and_score([x], [y])
        net.params_list["d1"]["W"] = jnp.asarray(w)
        numeric = (sp - sm) / (2 * eps)
        analytic = float(grads["d1"]["W"][i, j])
        assert abs(numeric - analytic) < 5e-3 * max(1.0, abs(analytic)), (numeric, analytic)


def test_graph_rnn_time_step_streaming():
    """CG streaming inference == full-sequence forward (reference
    ComputationGraph.rnnTimeStep:1788), mirroring the MLN streaming test."""
    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer

    conf = (NeuralNetConfiguration.builder()
            .seed(5)
            .graph_builder()
            .add_inputs("in")
            .add_layer("lstm", GravesLSTM(n_in=3, n_out=6, activation="tanh"),
                       "in")
            .add_layer("out", RnnOutputLayer(n_in=6, n_out=2, loss="mcxent",
                                             activation="softmax"), "lstm")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    x = np.random.default_rng(1).normal(size=(2, 6, 3)).astype(np.float32)
    full = np.asarray(net.output(x)[0])
    net.rnn_clear_previous_state()
    outs = [np.asarray(net.rnn_time_step(x[:, t:t + 1])[0]) for t in range(6)]
    streamed = np.concatenate(outs, axis=1)
    np.testing.assert_allclose(full, streamed, atol=1e-5)
    # clearing state restarts the stream
    net.rnn_clear_previous_state()
    again = np.asarray(net.rnn_time_step(x[:, :1])[0])
    np.testing.assert_allclose(again, outs[0], atol=1e-6)


def test_graph_tbptt_runs_and_learns():
    """CG TBPTT chunks the time axis and carries LSTM state (reference
    ComputationGraph fit with BackpropType.TruncatedBPTT)."""
    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer

    rng = np.random.default_rng(0)
    B, T, C = 8, 20, 3
    x = rng.normal(size=(B, T, C)).astype(np.float32)
    y = np.zeros((B, T, C), np.float32)
    y[..., 0] = 1
    conf = (NeuralNetConfiguration.builder()
            .seed(5).learning_rate(0.05)
            .graph_builder()
            .add_inputs("in")
            .add_layer("lstm", GravesLSTM(n_in=C, n_out=8, activation="tanh"),
                       "in")
            .add_layer("out", RnnOutputLayer(n_in=8, n_out=C, loss="mcxent",
                                             activation="softmax"), "lstm")
            .set_outputs("out")
            .backprop_type("TruncatedBPTT")
            .t_bptt_forward_length(5)
            .build())
    net = ComputationGraph(conf).init()
    s0 = None
    for _ in range(5):
        net.fit([x], [y])
        if s0 is None:
            s0 = net.score_value
    assert net.iteration == 20  # 5 epochs x (20 timesteps / 5 per chunk)
    assert np.isfinite(net.score_value)
    assert net.score_value < s0


def test_graph_char_rnn_streaming_generation():
    """Char-RNN-style sampling through the CG streaming API, mirroring the
    MLN char-RNN example loop: prime with a sequence, then feed back one
    sampled step at a time (reference GravesLSTMCharModellingExample)."""
    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer

    V = 12
    conf = (NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.1)
            .graph_builder()
            .add_inputs("in")
            .add_layer("lstm", GravesLSTM(n_in=V, n_out=16, activation="tanh"),
                       "in")
            .add_layer("out", RnnOutputLayer(n_in=16, n_out=V, loss="mcxent",
                                             activation="softmax"), "lstm")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(3)
    prime = np.eye(V, dtype=np.float32)[rng.integers(0, V, (1, 5))]
    net.rnn_clear_previous_state()
    out = net.rnn_time_step(prime)[0]
    generated = []
    for _ in range(8):
        probs = np.asarray(out)[0, -1]
        nxt = int(np.argmax(probs))
        generated.append(nxt)
        onehot = np.zeros((1, 1, V), np.float32)
        onehot[0, 0, nxt] = 1
        out = net.rnn_time_step(onehot)[0]
        assert out.shape == (1, 1, V)
        np.testing.assert_allclose(np.asarray(out).sum(axis=-1), 1.0,
                                   atol=1e-5)
    assert len(generated) == 8


def test_graph_evaluate_threads_label_masks():
    """CG.evaluate must honor labels_mask — masked timesteps don't count
    (reference ComputationGraph.evaluate:2230; parity with
    MultiLayerNetwork.evaluate's mask threading)."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ExistingDataSetIterator
    from deeplearning4j_tpu.eval.evaluation import Evaluation
    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer

    rng = np.random.default_rng(0)
    B, T, C = 4, 6, 3
    x = rng.normal(size=(B, T, C)).astype(np.float32)
    y = np.eye(C, dtype=np.float32)[rng.integers(0, C, (B, T))]
    lmask = np.ones((B, T), np.float32)
    lmask[:, T // 2:] = 0  # second half of every sequence is padding
    conf = (NeuralNetConfiguration.builder()
            .seed(3).learning_rate(0.05)
            .graph_builder()
            .add_inputs("in")
            .add_layer("lstm", GravesLSTM(n_in=C, n_out=8, activation="tanh"),
                       "in")
            .add_layer("out", RnnOutputLayer(n_in=8, n_out=C, loss="mcxent",
                                             activation="softmax"), "lstm")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    it = ExistingDataSetIterator([DataSet(x, y, labels_mask=lmask)])
    ev = net.evaluate(it)
    # reference accumulation: identical forward, mask applied by hand
    expect = Evaluation()
    expect.eval(y, np.asarray(net.output(x)[0]), mask=lmask)
    assert ev.num_examples == expect.num_examples == B * (T // 2)
    assert ev.accuracy() == expect.accuracy()
    # and differs from the mask-blind count
    assert ev.num_examples != B * T


def test_graph_evaluate_multi_output_and_top_n():
    """Every network output is scored against its label stream; top_n and
    labels_list ride through (reference ComputationGraph.evaluate:2253)."""
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer

    rng = np.random.default_rng(1)
    x = rng.normal(size=(10, 4)).astype(np.float32)
    y1 = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 10)]
    y2 = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 10)]
    conf = (NeuralNetConfiguration.builder()
            .seed(4).learning_rate(0.1)
            .graph_builder()
            .add_inputs("in")
            .add_layer("shared", DenseLayer(n_in=4, n_out=8,
                                            activation="tanh"), "in")
            .add_layer("out1", OutputLayer(n_in=8, n_out=3, loss="mcxent",
                                           activation="softmax"), "shared")
            .add_layer("out2", OutputLayer(n_in=8, n_out=3, loss="mcxent",
                                           activation="softmax"), "shared")
            .set_outputs("out1", "out2")
            .build())
    net = ComputationGraph(conf).init()
    mds = MultiDataSet([x], [y1, y2])
    ev = net.evaluate(iter([mds]), labels_list=["a", "b", "c"], top_n=2)
    assert ev.num_examples == 20  # both output streams accumulated
    assert ev.top_n_accuracy() >= ev.accuracy()
    assert "Top-2 Accuracy" in ev.stats() and "a" in ev.stats()


def test_graph_pretrain_layer_and_pretrain():
    """CG layerwise pretraining parity (reference ComputationGraph
    pretrain:509 / pretrainLayer:540): only the target vertex's params move,
    its unsupervised loss decreases, and pretrain() walks every pretrainable
    vertex in topological order."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ExistingDataSetIterator
    from deeplearning4j_tpu.nn.conf.layers import (
        AutoEncoder, DenseLayer, OutputLayer, VariationalAutoencoder,
    )

    rng = np.random.default_rng(7)
    x = rng.normal(size=(16, 6)).astype(np.float32)
    conf = (NeuralNetConfiguration.builder()
            .seed(11).learning_rate(0.05)
            .graph_builder()
            .add_inputs("in")
            .add_layer("ae", AutoEncoder(n_in=6, n_out=5,
                                         activation="sigmoid"), "in")
            .add_layer("vae", VariationalAutoencoder(
                n_in=5, n_out=4, encoder_layer_sizes=(8,),
                decoder_layer_sizes=(8,)), "ae")
            .add_layer("out", OutputLayer(n_in=4, n_out=3, loss="mcxent",
                                          activation="softmax"), "vae")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    it = ExistingDataSetIterator(
        [DataSet(x, np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)])])

    p_before = {n: jax.tree_util.tree_map(np.asarray, p)
                for n, p in net.params_list.items()}
    # pretrain the VAE vertex alone: ae + out params must not move
    losses = []
    for _ in range(15):
        net.pretrain_layer("vae", it)
        losses.append(net.score_value)
    assert losses[-1] < losses[0], losses
    for pname, val in net.params_list["ae"].items():
        np.testing.assert_array_equal(np.asarray(val), p_before["ae"][pname])
    for pname, val in net.params_list["out"].items():
        np.testing.assert_array_equal(np.asarray(val), p_before["out"][pname])
    moved = any(not np.array_equal(np.asarray(v), p_before["vae"][k])
                for k, v in net.params_list["vae"].items())
    assert moved

    # pretrain() walks both pretrainable vertices (ae then vae)
    net2 = ComputationGraph(conf).init()
    p0 = {n: jax.tree_util.tree_map(np.asarray, p)
          for n, p in net2.params_list.items()}
    net2.pretrain(it)
    for vertex_name in ("ae", "vae"):
        assert any(
            not np.array_equal(np.asarray(v), p0[vertex_name][k])
            for k, v in net2.params_list[vertex_name].items()), vertex_name
    for pname, val in net2.params_list["out"].items():
        np.testing.assert_array_equal(np.asarray(val), p0["out"][pname])

    # actionable errors
    with pytest.raises(ValueError, match="not pretrainable"):
        net.pretrain_layer("out", it)
    with pytest.raises(ValueError, match="Unknown vertex"):
        net.pretrain_layer("nope", it)


def test_graph_rbm_vertex_pretrains():
    """An RBM vertex pretrains under CG pretrain_layer: its CD surrogate
    objective moves only its own params and free energy of the data drops
    (CD's objective is not a true loss, so descent — not FD — is the check)."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ExistingDataSetIterator
    from deeplearning4j_tpu.nn.conf.layers import OutputLayer, RBM

    rng = np.random.default_rng(8)
    x = (rng.uniform(size=(32, 6)) > 0.5).astype(np.float32)
    conf = (NeuralNetConfiguration.builder()
            .seed(21).learning_rate(0.1)
            .graph_builder()
            .add_inputs("in")
            .add_layer("rbm", RBM(n_in=6, n_out=8,
                                  activation="sigmoid"), "in")
            .add_layer("out", OutputLayer(n_in=8, n_out=2, loss="mcxent",
                                          activation="softmax"), "rbm")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    it = ExistingDataSetIterator(
        [DataSet(x, np.eye(2, dtype=np.float32)[rng.integers(0, 2, 32)])])

    def recon_err(params, v):
        # CD's observable progress metric: one up-down pass reconstruction
        def sigmoid(a):
            return 1.0 / (1.0 + np.exp(-a))
        h = sigmoid(v @ np.asarray(params["W"]) + np.asarray(params["b"]))
        vr = sigmoid(h @ np.asarray(params["W"]).T + np.asarray(params["vb"]))
        return float(np.mean((v - vr) ** 2))

    err0 = recon_err(net.params_list["rbm"], x)
    out_before = jax.tree_util.tree_map(np.asarray, net.params_list["out"])
    for _ in range(30):
        net.pretrain_layer("rbm", it)
    err1 = recon_err(net.params_list["rbm"], x)
    assert err1 < err0, (err0, err1)
    for pname, val in net.params_list["out"].items():
        np.testing.assert_array_equal(np.asarray(val), out_before[pname])
