"""ComputationGraph tests: DAG topology, multi-input/output, gradient flow.

Reference analog: deeplearning4j-core TestComputationGraphNetwork +
GradientCheckTestsComputationGraph.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graphconf import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ConvolutionLayer, DenseLayer, GravesLSTM, OutputLayer, SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.vertices import (
    ElementWiseVertex, L2NormalizeVertex, LastTimeStepVertex, MergeVertex,
    ScaleVertex, StackVertex, SubsetVertex, UnstackVertex,
)
from deeplearning4j_tpu.nn.graph_network import ComputationGraph, MultiDataSet


def test_simple_chain_equals_mlp():
    conf = (NeuralNetConfiguration.builder()
            .seed(1).learning_rate(0.1)
            .graph_builder()
            .add_inputs("in")
            .add_layer("dense", DenseLayer(n_in=4, n_out=8, activation="tanh"), "in")
            .add_layer("out", OutputLayer(n_in=8, n_out=3, loss="mcxent",
                                          activation="softmax"), "dense")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    x = np.random.default_rng(0).normal(size=(5, 4)).astype(np.float32)
    outs = net.output(x)
    assert len(outs) == 1
    assert outs[0].shape == (5, 3)
    np.testing.assert_allclose(np.asarray(jnp.sum(outs[0], -1)), 1.0, rtol=1e-5)


def test_merge_vertex_two_towers():
    conf = (NeuralNetConfiguration.builder()
            .seed(2).learning_rate(0.1)
            .graph_builder()
            .add_inputs("in1", "in2")
            .add_layer("d1", DenseLayer(n_in=3, n_out=4, activation="relu"), "in1")
            .add_layer("d2", DenseLayer(n_in=5, n_out=6, activation="relu"), "in2")
            .add_vertex("merge", MergeVertex(), "d1", "d2")
            .add_layer("out", OutputLayer(n_in=10, n_out=2, loss="mcxent",
                                          activation="softmax"), "merge")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(1)
    x1 = rng.normal(size=(4, 3)).astype(np.float32)
    x2 = rng.normal(size=(4, 5)).astype(np.float32)
    outs = net.output(x1, x2)
    assert outs[0].shape == (4, 2)
    # training decreases loss
    y = np.zeros((4, 2), np.float32)
    y[:, 0] = 1
    mds = MultiDataSet([x1, x2], [y])
    s0 = net.score(mds)
    for _ in range(30):
        net.fit(mds)
    assert net.score(mds) < s0


def test_residual_elementwise_add():
    conf = (NeuralNetConfiguration.builder()
            .seed(3).learning_rate(0.05)
            .graph_builder()
            .add_inputs("in")
            .add_layer("d1", DenseLayer(n_in=6, n_out=6, activation="relu"), "in")
            .add_vertex("residual", ElementWiseVertex(op="add"), "d1", "in")
            .add_layer("out", OutputLayer(n_in=6, n_out=2, loss="mse",
                                          activation="identity"), "residual")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    x = np.random.default_rng(0).normal(size=(3, 6)).astype(np.float32)
    assert net.output(x)[0].shape == (3, 2)


def test_multi_output():
    conf = (NeuralNetConfiguration.builder()
            .seed(4).learning_rate(0.1)
            .graph_builder()
            .add_inputs("in")
            .add_layer("shared", DenseLayer(n_in=4, n_out=8, activation="tanh"), "in")
            .add_layer("out1", OutputLayer(n_in=8, n_out=3, loss="mcxent",
                                           activation="softmax"), "shared")
            .add_layer("out2", OutputLayer(n_in=8, n_out=1, loss="mse",
                                           activation="identity"), "shared")
            .set_outputs("out1", "out2")
            .build())
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(2)
    x = rng.normal(size=(6, 4)).astype(np.float32)
    y1 = np.zeros((6, 3), np.float32)
    y1[:, 1] = 1
    y2 = rng.normal(size=(6, 1)).astype(np.float32)
    outs = net.output(x)
    assert outs[0].shape == (6, 3) and outs[1].shape == (6, 1)
    mds = MultiDataSet([x], [y1, y2])
    s0 = net.score(mds)
    for _ in range(40):
        net.fit(mds)
    assert net.score(mds) < s0


def test_cnn_input_type_propagation():
    conf = (NeuralNetConfiguration.builder()
            .seed(5).learning_rate(0.01)
            .graph_builder()
            .add_inputs("in")
            .add_layer("conv", ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                                activation="relu"), "in")
            .add_layer("pool", SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)), "conv")
            .add_layer("dense", DenseLayer(n_out=16, activation="relu"), "pool")
            .add_layer("out", OutputLayer(n_out=3, loss="mcxent",
                                          activation="softmax"), "dense")
            .set_outputs("out")
            .set_input_types(InputType.convolutional(10, 10, 2))
            .build())
    # conv n_in inferred from channels; dense n_in from flattened pool output 4*4*4
    assert conf.vertices["conv"].layer.n_in == 2
    assert conf.vertices["dense"].layer.n_in == 4 * 4 * 4
    net = ComputationGraph(conf).init()
    x = np.random.default_rng(0).normal(size=(2, 10, 10, 2)).astype(np.float32)
    assert net.output(x)[0].shape == (2, 3)


def test_last_time_step_vertex():
    conf = (NeuralNetConfiguration.builder()
            .seed(6).learning_rate(0.05)
            .graph_builder()
            .add_inputs("in")
            .add_layer("lstm", GravesLSTM(n_in=3, n_out=5, activation="tanh"), "in")
            .add_vertex("last", LastTimeStepVertex(), "lstm")
            .add_layer("out", OutputLayer(n_in=5, n_out=2, loss="mcxent",
                                          activation="softmax"), "last")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    x = np.random.default_rng(0).normal(size=(4, 7, 3)).astype(np.float32)
    assert net.output(x)[0].shape == (4, 2)


def test_stack_unstack_shared_weights():
    conf = (NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.05)
            .graph_builder()
            .add_inputs("a", "b")
            .add_vertex("stack", StackVertex(), "a", "b")
            .add_layer("shared", DenseLayer(n_in=4, n_out=6, activation="tanh"), "stack")
            .add_vertex("ua", UnstackVertex(index=0, num_stacks=2), "shared")
            .add_vertex("ub", UnstackVertex(index=1, num_stacks=2), "shared")
            .add_vertex("merged", MergeVertex(), "ua", "ub")
            .add_layer("out", OutputLayer(n_in=12, n_out=2, loss="mse",
                                          activation="identity"), "merged")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(3)
    a = rng.normal(size=(3, 4)).astype(np.float32)
    b = rng.normal(size=(3, 4)).astype(np.float32)
    assert net.output(a, b)[0].shape == (3, 2)


def test_graph_json_roundtrip():
    conf = (NeuralNetConfiguration.builder()
            .seed(8).learning_rate(0.1).updater("adam")
            .graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_in=4, n_out=8, activation="relu"), "in")
            .add_vertex("norm", L2NormalizeVertex(), "d")
            .add_vertex("scaled", ScaleVertex(scale=2.0), "norm")
            .add_layer("out", OutputLayer(n_in=8, n_out=3, loss="mcxent",
                                          activation="softmax"), "scaled")
            .set_outputs("out")
            .build())
    s = conf.to_json()
    conf2 = ComputationGraphConfiguration.from_json(s)
    assert conf2.to_json() == s
    net = ComputationGraph(conf2).init()
    x = np.random.default_rng(0).normal(size=(2, 4)).astype(np.float32)
    assert net.output(x)[0].shape == (2, 3)


def test_graph_gradients_match_numeric():
    """Spot gradient check on a small DAG (reference GradientCheckTestsComputationGraph)."""
    conf = (NeuralNetConfiguration.builder()
            .seed(9).learning_rate(0.1)
            .graph_builder()
            .add_inputs("in")
            .add_layer("d1", DenseLayer(n_in=3, n_out=4, activation="tanh"), "in")
            .add_layer("d2", DenseLayer(n_in=3, n_out=4, activation="sigmoid"), "in")
            .add_vertex("sum", ElementWiseVertex(op="add"), "d1", "d2")
            .add_layer("out", OutputLayer(n_in=4, n_out=2, loss="mcxent",
                                          activation="softmax"), "sum")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(5)
    x = rng.normal(size=(4, 3)).astype(np.float32)
    y = np.zeros((4, 2), np.float32)
    y[np.arange(4), rng.integers(0, 2, 4)] = 1
    grads, score = net.gradient_and_score([x], [y])
    # numeric check on a few params of d1.W
    import jax

    eps = 1e-3
    w = np.asarray(net.params_list["d1"]["W"]).copy()
    for (i, j) in [(0, 0), (1, 2), (2, 3)]:
        wp = w.copy(); wp[i, j] += eps
        wm = w.copy(); wm[i, j] -= eps
        net.params_list["d1"]["W"] = jnp.asarray(wp)
        _, sp = net.gradient_and_score([x], [y])
        net.params_list["d1"]["W"] = jnp.asarray(wm)
        _, sm = net.gradient_and_score([x], [y])
        net.params_list["d1"]["W"] = jnp.asarray(w)
        numeric = (sp - sm) / (2 * eps)
        analytic = float(grads["d1"]["W"][i, j])
        assert abs(numeric - analytic) < 5e-3 * max(1.0, abs(analytic)), (numeric, analytic)
