"""Elastic preemption-tolerant training tests (reference Spark
TrainingMaster fault tolerance + deeplearning4j-aws provisioning):
membership-oracle lease math with a fake clock, epoch fencing of zombie
pushes (inproc and over the TCP wire), TcpTransport half-open-socket retry
bounds, broker consumer-group shard handoff semantics, worker-process
cleanup, and the slow chaos test — SIGKILL a worker mid-fit and prove loss
parity with an uninterrupted baseline at equal consumed samples."""
import json
import os
import queue
import socket
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.cloud import MembershipOracle, WorkerLease
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.observability import names as _n
from deeplearning4j_tpu.observability.flight_recorder import global_recorder
from deeplearning4j_tpu.observability.metrics import global_registry
from deeplearning4j_tpu.parallel.elastic import ElasticTrainer
from deeplearning4j_tpu.parallel.param_server import ParameterServer
from deeplearning4j_tpu.parallel.ps_transport import (
    ParameterServerTcpFrontend, TcpTransport, TransportError,
)
from deeplearning4j_tpu.streaming.broker import (
    BrokerProducer, LoopbackBroker, ReconnectingConsumer,
)


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _oracle(timeout=15.0):
    clock = FakeClock()
    return MembershipOracle(lease_timeout_s=timeout, clock=clock), clock


def _net(seed=12345, lr=0.1):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(lr).updater("sgd")
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                               activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def _counter_value(name: str) -> float:
    snap = global_registry().snapshot().get(name, {"series": []})
    return sum(s["value"] for s in snap["series"])


# --------------------------------------------------------- membership oracle

def test_register_draws_globally_monotonic_epochs():
    oracle, _ = _oracle()
    a = oracle.register(0, worker="a")
    b = oracle.register(1, worker="b")
    assert (a.member, a.epoch) == (1, 1)
    assert (b.member, b.epoch) == (2, 2)
    assert oracle.joins == 2
    assert {l.name for l in oracle.live_members()} == {"a", "b"}


def test_heartbeat_renews_lease_until_it_lapses():
    oracle, clock = _oracle(timeout=15.0)
    lease = oracle.register(0)
    clock.advance(10.0)
    assert oracle.heartbeat(lease.member, lease.epoch)
    clock.advance(10.0)  # 20s total but renewed at t=10: still live
    assert oracle.heartbeat(lease.member, lease.epoch)
    clock.advance(16.0)  # past the renewed deadline
    assert not oracle.heartbeat(lease.member, lease.epoch)
    assert oracle.lease_expiries == 1
    assert oracle.lease(lease.member).reason == "lease-lapsed"
    # dead is permanent: a later heartbeat can never resurrect the lease
    clock.advance(-20.0)
    assert not oracle.heartbeat(lease.member, lease.epoch)


def test_validate_fences_but_never_renews():
    oracle, clock = _oracle(timeout=10.0)
    lease = oracle.register(0)
    clock.advance(9.0)
    assert oracle.validate(lease.member, lease.epoch)
    # validate at t=9 must NOT have pushed the deadline out: only
    # heartbeats prove liveness (a zombie busy-pushing stays dead)
    clock.advance(2.0)
    assert not oracle.validate(lease.member, lease.epoch)
    assert oracle.lease_expiries == 1
    assert not oracle.validate(99, 99)  # unknown member
    live = oracle.register(0)
    assert not oracle.validate(live.member, live.epoch + 1)  # wrong epoch


def test_expire_sweep_returns_only_newly_dead():
    oracle, clock = _oracle(timeout=5.0)
    a = oracle.register(0, worker="a")
    b = oracle.register(1, worker="b")
    clock.advance(4.0)
    oracle.heartbeat(b.member, b.epoch)
    clock.advance(2.0)  # a is 6s silent; b renewed 2s ago
    lapsed = oracle.expire()
    assert [l.member for l in lapsed] == [a.member]
    assert oracle.expire() == []  # already declared: not newly dead again
    assert [l.member for l in oracle.live_members()] == [b.member]


def test_deregister_is_graceful_not_an_expiry():
    oracle, _ = _oracle()
    lease = oracle.register(0)
    assert oracle.deregister(lease.member, lease.epoch, reason="done")
    assert oracle.lease_expiries == 0
    assert not oracle.validate(lease.member, lease.epoch)
    assert not oracle.deregister(lease.member, lease.epoch)  # already gone


def test_evict_fences_immediately_without_expiry_count():
    oracle, _ = _oracle()
    lease = oracle.register(3, worker="w")
    assert oracle.evict(lease.member, reason="exit-rc137")
    assert oracle.lease_expiries == 0
    assert oracle.lease(lease.member).reason == "exit-rc137"
    assert not oracle.validate(lease.member, lease.epoch)
    assert not oracle.evict(lease.member)


def test_replacement_supersedes_by_epoch():
    oracle, _ = _oracle()
    old = oracle.register(0, worker="shard0-gen0")
    oracle.evict(old.member)
    new = oracle.register(0, worker="shard0-gen1")
    assert new.epoch > old.epoch
    assert oracle.live_member_for_shard(0).member == new.member
    assert oracle.member_by_name("shard0-gen1").member == new.member


# -------------------------------------------------------------- epoch fencing

def test_zombie_push_is_fenced_and_counted():
    oracle, clock = _oracle(timeout=5.0)
    srv = ParameterServer([np.zeros(8, np.float32)], membership=oracle)
    lease = oracle.register(0)
    delta = np.ones(8, np.float32)

    res = srv.push_delta(delta, 0, member=lease.member, epoch=lease.epoch)
    assert res.accepted and not res.fenced and srv.version == 1

    before = _counter_value(_n.ELASTIC_FENCED_PUSHES_TOTAL)
    clock.advance(6.0)  # lease lapses: the worker is now a zombie
    res = srv.push_delta(delta, 1, member=lease.member, epoch=lease.epoch)
    assert res.fenced and not res.accepted
    assert srv.version == 1  # the model never saw the zombie's delta
    assert srv.fenced == 1 and srv.rejected == 1
    assert _counter_value(_n.ELASTIC_FENCED_PUSHES_TOTAL) == before + 1
    # the fenced reply still carries fresh state (reject-carries-state)
    assert res.params.shape == (8,)

    # a replacement on the same shard pushes fine under its NEW epoch
    repl = oracle.register(0)
    res = srv.push_delta(delta, 1, member=repl.member, epoch=repl.epoch)
    assert res.accepted and srv.version == 2


def test_identityless_push_bypasses_fencing():
    # static-shard workers (ISSUE 10 mode) carry no identity; a server
    # with an oracle attached must keep accepting them unchanged
    oracle, _ = _oracle()
    srv = ParameterServer([np.zeros(4, np.float32)], membership=oracle)
    res = srv.push_delta(np.ones(4, np.float32), 0)
    assert res.accepted and not res.fenced


# ------------------------------------------------------------ wire membership

def test_membership_verbs_over_tcp_frontend():
    oracle, clock = _oracle(timeout=5.0)
    srv = ParameterServer([np.zeros(6, np.float32)], membership=oracle)
    frontend = ParameterServerTcpFrontend(srv).start()
    t = TcpTransport(("127.0.0.1", frontend.port))
    try:
        reg = t.register(2, worker="w0")
        assert reg["member"] == reg["epoch"] == 1
        assert reg["lease_s"] == 5.0
        t.bind_member(reg["member"], reg["epoch"])
        assert t.heartbeat()

        res = t.push(np.ones(6, np.float32), 0)
        assert res.accepted and not res.fenced

        assert t.deregister("done")
        res = t.push(np.ones(6, np.float32), 1)
        assert res.fenced and not res.accepted  # fence crosses the wire
        assert not t.heartbeat()
    finally:
        t.close()
        frontend.stop()


def test_membership_verbs_require_an_oracle():
    srv = ParameterServer([np.zeros(4, np.float32)])  # no membership
    frontend = ParameterServerTcpFrontend(srv).start()
    t = TcpTransport(("127.0.0.1", frontend.port))
    try:
        with pytest.raises(RuntimeError, match="membership"):
            t.register(0)
    finally:
        t.close()
        frontend.stop()


# ------------------------------------------------------- transport robustness

def test_half_open_socket_raises_transport_error_in_bounded_time():
    # a listener that accepts and then never replies: the old transport
    # blocked forever in recv; now every RPC has a read timeout + bounded
    # retry budget and surfaces TransportError
    lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(4)
    accepted = []

    def _accept_and_hold():
        try:
            while True:
                conn, _ = lsock.accept()
                accepted.append(conn)  # hold open, never reply
        except OSError:
            pass

    threading.Thread(target=_accept_and_hold, daemon=True).start()
    t = TcpTransport(lsock.getsockname(), timeout=0.2, connect_timeout=0.5,
                     retries=2, backoff_s=0.05, backoff_cap_s=0.1)
    t0 = time.monotonic()
    try:
        with pytest.raises(TransportError):
            t.pull()
    finally:
        elapsed = time.monotonic() - t0
        t.close()
        lsock.close()
        for c in accepted:
            c.close()
    # 3 attempts x 0.2s read timeout + backoffs; far under the old forever
    assert elapsed < 5.0


def test_connection_refused_raises_transport_error():
    probe = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    probe.bind(("127.0.0.1", 0))
    addr = probe.getsockname()
    probe.close()  # nothing listens here now
    t = TcpTransport(addr, timeout=0.2, connect_timeout=0.3,
                     retries=1, backoff_s=0.01)
    with pytest.raises(TransportError):
        t.pull()
    t.close()


def test_server_error_reply_is_not_retried():
    # RuntimeError = the server is alive and answered "no"; burning the
    # retry budget on it would turn a protocol bug into a slow hang
    srv = ParameterServer([np.zeros(4, np.float32)])
    frontend = ParameterServerTcpFrontend(srv).start()
    t = TcpTransport(("127.0.0.1", frontend.port),
                     retries=3, backoff_s=5.0)  # retries would cost >15s
    t0 = time.monotonic()
    try:
        with pytest.raises(RuntimeError, match="unknown PS op"):
            with t._lock:
                t._rpc({"op": "definitely-not-an-op"})
    finally:
        elapsed = time.monotonic() - t0
        t.close()
        frontend.stop()
    assert elapsed < 2.0  # no backoff sleeps happened


# ------------------------------------------------------ broker shard handoff

def _publish(broker, topic, n):
    producer = BrokerProducer(broker.address)
    try:
        for i in range(n):
            producer.publish(topic, {"x": np.full((2,), i, np.float32)},
                             meta={"idx": i})
    finally:
        producer.close()


def test_group_resume_at_committed_plus_one():
    broker = LoopbackBroker().start()
    try:
        _publish(broker, "shard-0", 8)
        assert broker.committed("shard-0", "g") == -1

        # worker A consumes 6 messages but only commits through the 4th
        # (its last landed push window), then "crashes" (close, no commit)
        a = ReconnectingConsumer(broker.address, "shard-0", group="g")
        seen_a = []
        for _ in range(6):
            meta, arrays = a.get(timeout=1.0)
            seen_a.append(meta["idx"])
            if meta["idx"] == 3:
                assert a.commit_delivered() == 3
        a.close()
        assert seen_a == [0, 1, 2, 3, 4, 5]
        assert broker.committed("shard-0", "g") == 3

        # the replacement resumes the SAME group at committed+1: offsets
        # 4 and 5 redeliver (at-least-once, bounded by one commit window),
        # nothing is skipped, and its final commit drains the topic
        b = ReconnectingConsumer(broker.address, "shard-0", group="g")
        seen_b = []
        while True:
            try:
                meta, _ = b.get(timeout=0.3)
            except queue.Empty:
                break
            seen_b.append(meta["idx"])
        assert seen_b == [4, 5, 6, 7]
        assert b.commit_delivered() == 7
        assert broker.committed("shard-0", "g") == 7
        b.close()

        duplicates = set(seen_a) & set(seen_b)
        assert duplicates == {4, 5}  # exactly the uncommitted window
        assert set(seen_a) | set(seen_b) == set(range(8))  # zero loss
    finally:
        broker.stop()


def test_commit_delivered_before_any_get_is_a_noop():
    broker = LoopbackBroker().start()
    try:
        _publish(broker, "t", 1)
        c = ReconnectingConsumer(broker.address, "t", group="g2")
        assert c.commit_delivered() is None
        assert broker.committed("t", "g2") == -1
        c.close()
    finally:
        broker.stop()


# ----------------------------------------------------------- worker process

def test_ps_worker_main_cleans_npz_and_records_exit(tmp_path, capsys):
    from deeplearning4j_tpu.nn.conf.serde import to_json
    from deeplearning4j_tpu.parallel import ps_worker

    net = _net()
    srv = ParameterServer(net.params_list)
    frontend = ParameterServerTcpFrontend(srv).start()

    conf_path = tmp_path / "conf.json"
    conf_path.write_text(to_json(net.conf))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(4, 8, 4)).astype(np.float32)
    y = np.tile(np.eye(3, dtype=np.float32)[[0, 1, 2, 0, 1, 2, 0, 1]],
                (4, 1, 1))
    data_path = tmp_path / "worker0.npz"
    np.savez(data_path, x=x, y=y)

    try:
        rc = ps_worker.main([
            "--addr", f"127.0.0.1:{frontend.port}",
            "--conf", str(conf_path), "--data", str(data_path),
            "--worker-id", "7", "--push-frequency", "2"])
    finally:
        frontend.stop()

    assert rc == 0
    assert not data_path.exists()  # shard file removed in finally
    assert srv.pushes >= 1
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert stats["steps"] == 4 and stats["exit_reason"] == "done"
    exits = [e for e in global_recorder().snapshot()
             if e["kind"] == "worker_exit" and e.get("worker") == "7"]
    assert exits and exits[-1]["reason"] == "done"


def test_ps_worker_main_rejects_ambiguous_modes(tmp_path):
    from deeplearning4j_tpu.parallel import ps_worker

    with pytest.raises(SystemExit):
        ps_worker.main(["--addr", "127.0.0.1:1", "--conf", "c.json"])
    with pytest.raises(SystemExit):
        ps_worker.main(["--addr", "127.0.0.1:1", "--conf", "c.json",
                        "--data", "d.npz", "--broker", "127.0.0.1:2",
                        "--topic", "t", "--group", "g"])
    with pytest.raises(SystemExit):  # broker mode needs topic+group
        ps_worker.main(["--addr", "127.0.0.1:1", "--conf", "c.json",
                        "--broker", "127.0.0.1:2"])


# ------------------------------------------------------------ restore-on-join

def test_maybe_restore_only_from_committed_sidecar(tmp_path):
    from deeplearning4j_tpu.utils.sharded_checkpoint import save_sharded

    src = _net(seed=7)
    for _ in range(3):
        src.fit(np.ones((4, 4), np.float32),
                np.eye(3, dtype=np.float32)[[0, 1, 2, 0]])
    ckpt = tmp_path / "ckpt"
    save_sharded(str(ckpt), src)

    fresh = _net(seed=99)
    trainer = ElasticTrainer(fresh, checkpoint_dir=str(ckpt))
    trainer._maybe_restore()
    assert trainer.restored_from_checkpoint
    np.testing.assert_allclose(np.asarray(fresh.params_list[0]["W"]),
                               np.asarray(src.params_list[0]["W"]))

    # a torn save (sidecar missing) is ignored by contract
    os.unlink(ckpt / "meta.json")
    t2 = ElasticTrainer(_net(seed=99), checkpoint_dir=str(ckpt))
    t2._maybe_restore()
    assert not t2.restored_from_checkpoint


# ------------------------------------------------------------- observability

def test_elastic_metric_names_registered():
    for name in (_n.ELASTIC_LIVE_WORKERS, _n.ELASTIC_LEASE_EXPIRIES_TOTAL,
                 _n.ELASTIC_FENCED_PUSHES_TOTAL, _n.ELASTIC_HANDOFFS_TOTAL,
                 _n.ELASTIC_JOINS_TOTAL):
        assert name in _n.ALL_METRIC_NAMES
        assert name.startswith("dl4j_elastic_")


def test_cli_elastic_train_parser():
    from deeplearning4j_tpu.cli import build_parser

    args = build_parser().parse_args(
        ["elastic-train", "--model", "m.zip", "--workers", "3",
         "--lease-timeout", "7.5", "--no-respawn"])
    assert args.workers == 3
    assert args.lease_timeout == 7.5
    assert args.no_respawn


def test_builder_validates_compression():
    with pytest.raises(ValueError, match="compression"):
        ElasticTrainer(_net(), compression="zstd")


# ----------------------------------------------------------- multi-process

@pytest.mark.slow
def test_chaos_sigkill_respawn_loss_parity():
    """SIGKILL one of two workers mid-fit: the shard hands off, the
    replacement resumes at the committed offset, and the final loss stays
    within parity of an uninterrupted single-process fit at equal consumed
    samples. Acceptance: broker offsets account for every batch — no
    sample window is silently dropped."""
    rng = np.random.default_rng(0)
    means = rng.normal(0.0, 1.0, (3, 4)).astype(np.float32)
    data = []
    for _ in range(24):
        lab = rng.integers(0, 3, 16)
        x = (means[lab] + rng.normal(0, 0.5, (16, 4))).astype(np.float32)
        noisy = np.where(rng.random(16) < 0.25, rng.integers(0, 3, 16), lab)
        data.append(DataSet(x, np.eye(3, dtype=np.float32)[noisy]))
    gx = np.concatenate([d.features for d in data])
    gy = np.concatenate([d.labels for d in data])

    base = _net()
    oracle_net = base.clone()
    for ds in data:
        oracle_net.fit(ds.features, ds.labels)
    sync_loss = float(oracle_net.score(gx, gy))

    elastic_net = base.clone()
    trainer = (ElasticTrainer.builder(elastic_net)
               .workers(2).push_frequency(2)
               .lease_timeout(10.0).respawn(True)
               .worker_delays(0.05, 0.05)
               .fit_timeout(240.0).build())

    killed = threading.Event()

    def _assassin():
        # wait for real progress (both workers up and pushing), then
        # SIGKILL shard 0's worker mid-shard
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            if trainer.server is not None and trainer.server.version >= 2:
                if trainer.chaos_kill(0):
                    killed.set()
                return
            time.sleep(0.05)

    t = threading.Thread(target=_assassin, daemon=True)
    t.start()
    trainer.fit(ListDataSetIterator(data))
    t.join(timeout=5.0)

    assert killed.is_set(), "chaos kill never fired: fixture too fast"
    assert trainer.handoffs >= 1
    assert trainer.published == 24
    # the no-silent-drop proof: every shard's group committed through its
    # fin marker — each batch was consumed (and pushed) at least once
    for sc in trainer.shard_commits:
        assert sc["committed"] >= sc["fin"] >= 0, sc
    st = trainer.stats
    assert st["joins"] == 2 + trainer.handoffs
    assert st["fenced"] == 0  # SIGKILL leaves no zombie to fence

    elastic_loss = float(elastic_net.score(gx, gy))
    assert abs(elastic_loss / sync_loss - 1.0) < 0.15, \
        f"elastic {elastic_loss:.4f} vs sync {sync_loss:.4f}"
    assert elastic_loss < 1.0986  # better than uniform ln(3)
