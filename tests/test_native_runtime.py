"""Native C++ runtime: IDX/CIFAR parsing, async prefetch loader, CSV reader,
stats codec wire-format equivalence with the Python encoder."""
import struct

import numpy as np
import pytest

from deeplearning4j_tpu import nativert
from deeplearning4j_tpu.ui.stats import StatsReport

pytestmark = pytest.mark.skipif(not nativert.native_available(),
                                reason="native runtime not built")


def _write_idx(path, arr):
    arr = np.asarray(arr, np.uint8)
    with open(path, "wb") as f:
        f.write(struct.pack(">i", 0x0800 | arr.ndim))
        for d in arr.shape:
            f.write(struct.pack(">i", d))
        f.write(arr.tobytes())


def test_idx_roundtrip(tmp_path):
    arr = np.arange(2 * 5 * 4, dtype=np.uint8).reshape(2, 5, 4)
    p = tmp_path / "t.idx"
    _write_idx(p, arr)
    out = nativert.read_idx(str(p))
    assert out.shape == (2, 5, 4)
    np.testing.assert_array_equal(out, arr)


def test_idx_bad_file(tmp_path):
    p = tmp_path / "bad.idx"
    p.write_bytes(b"\x00\x01\x02")
    assert nativert.read_idx(str(p)) is None


def test_loader_ordered_batches():
    n, feat, ncls, batch = 12, 6, 3, 4
    feats = np.arange(n * feat, dtype=np.uint8).reshape(n, feat)
    labels = (np.arange(n) % ncls).astype(np.uint8)
    ld = nativert.AsyncNativeLoader.from_arrays(
        feats, labels, ncls, batch, shuffle=False, normalize=False)
    batches = list(ld)
    assert len(batches) == 3
    x0, y0 = batches[0]
    np.testing.assert_allclose(x0, feats[:4].astype(np.float32))
    np.testing.assert_array_equal(np.argmax(y0, axis=1), labels[:4])
    assert y0.sum() == batch  # one-hot
    # epoch exhausted; reset restarts
    assert ld.next() is None
    ld.reset()
    assert len(list(ld)) == 3
    ld.close()


def test_loader_shuffle_covers_all():
    n, feat, batch = 16, 2, 4
    feats = np.repeat(np.arange(n, dtype=np.uint8)[:, None], feat, axis=1)
    labels = np.zeros(n, np.uint8)
    ld = nativert.AsyncNativeLoader.from_arrays(
        feats, labels, 2, batch, shuffle=True, seed=7, normalize=False)
    seen = sorted(int(x[0]) for xb, _ in ld for x in xb)
    assert seen == list(range(n))
    ld.close()


def test_mnist_loader_from_idx_files(tmp_path):
    imgs = np.random.default_rng(0).integers(0, 256, (10, 28, 28)).astype(np.uint8)
    lbls = (np.arange(10) % 10).astype(np.uint8)
    _write_idx(tmp_path / "img.idx", imgs)
    _write_idx(tmp_path / "lbl.idx", lbls)
    ld = nativert.AsyncNativeLoader.mnist(
        str(tmp_path / "img.idx"), str(tmp_path / "lbl.idx"), batch=5,
        shuffle=False)
    assert ld.num_examples == 10 and ld.feature_size == 784
    x, y = ld.next()
    np.testing.assert_allclose(
        x, imgs[:5].reshape(5, -1).astype(np.float32) / 255.0, atol=1e-6)
    np.testing.assert_array_equal(np.argmax(y, axis=1), lbls[:5])
    ld.close()


def test_cifar_loader(tmp_path):
    # CIFAR-10 binary: [label u8][3072 pixels u8] per record
    rng = np.random.default_rng(1)
    n = 6
    recs = bytearray()
    labels = []
    for i in range(n):
        lab = int(rng.integers(0, 10))
        labels.append(lab)
        recs.append(lab)
        recs += rng.integers(0, 256, 3072).astype(np.uint8).tobytes()
    p = tmp_path / "data_batch_1.bin"
    p.write_bytes(bytes(recs))
    ld = nativert.AsyncNativeLoader.cifar([str(p)], batch=3, shuffle=False)
    assert ld.num_examples == n and ld.feature_size == 3072
    _, y = ld.next()
    np.testing.assert_array_equal(np.argmax(y, axis=1), labels[:3])
    ld.close()


def test_csv_reader(tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("# header\n1.5,2,3\n4,5.25,6\n7,8,9\n")
    out = nativert.read_csv_numeric(str(p), skip_lines=1)
    np.testing.assert_allclose(
        out, [[1.5, 2, 3], [4, 5.25, 6], [7, 8, 9]])


def _sample_report():
    r = StatsReport("sess-1", "worker-0", 1234567890123)
    r.iteration = 42
    r.score = 0.125
    r.iteration_time_ms = 3.5
    r.samples_per_sec = 1000.25
    r.mem_rss_bytes = 1 << 30
    r.device_mem_bytes = 2 << 30
    r.param_stats["layer0_W"] = (0.5, [1, 2, 3, 4], (-1.0, 1.0))
    r.gradient_stats["layer0_W"] = (0.01, [4, 3, 2, 1], (-0.1, 0.1))
    r.update_stats["layer0_b"] = (0.001, [7], (0.0, 0.002))
    return r


def test_stats_codec_matches_python(monkeypatch):
    r = _sample_report()
    native_bytes = r.encode()
    monkeypatch.setenv("DL4J_TPU_DISABLE_NATIVE", "1")
    python_bytes = r.encode()
    assert native_bytes == python_bytes


def test_stats_codec_decode_roundtrip():
    r = _sample_report()
    d = StatsReport.decode(r.encode())
    assert d.session_id == "sess-1" and d.worker_id == "worker-0"
    assert d.iteration == 42 and d.score == 0.125
    assert d.param_stats["layer0_W"] == (0.5, [1, 2, 3, 4], (-1.0, 1.0))
    assert d.update_stats["layer0_b"] == (0.001, [7], (0.0, 0.002))


def test_csv_trailing_delim_and_whitespace_fields(tmp_path):
    p = tmp_path / "e.csv"
    p.write_text("1,2,\n4,5,6\n")   # trailing empty field on row 1
    out = nativert.read_csv_numeric(str(p))
    np.testing.assert_allclose(out, [[1, 2, 0], [4, 5, 6]])
    p2 = tmp_path / "w.csv"
    p2.write_text("1, \n2,3\n")     # whitespace field must not eat next line
    out2 = nativert.read_csv_numeric(str(p2))
    np.testing.assert_allclose(out2, [[1, 0], [2, 3]])


def test_loader_use_after_close_raises():
    feats = np.zeros((4, 2), np.uint8)
    ld = nativert.AsyncNativeLoader.from_arrays(
        feats, np.zeros(4, np.uint8), 2, 2, shuffle=False)
    ld.close()
    with pytest.raises(ValueError):
        ld.next()
    with pytest.raises(ValueError):
        ld.reset()


def _python_counts(path, common):
    from collections import Counter
    from deeplearning4j_tpu.nlp.tokenization import CommonPreprocessor
    pre = CommonPreprocessor() if common else None
    c = Counter()
    with open(path) as f:
        for line in f:
            for tok in line.split():
                if pre is not None:
                    tok = pre.pre_process(tok)
                if tok:
                    c[tok] += 1
    return dict(c)


@pytest.mark.parametrize("common", [False, True])
def test_vocab_counter_matches_python(tmp_path, common):
    """Native parallel token counts == the Python tokenizer pipeline
    (reference VocabConstructor.java parallel count phase)."""
    p = tmp_path / "corpus.txt"
    text = ("The quick brown fox, jumps over the lazy dog!\n"
            "the quick RED fox; and the dog sleeps.\n" * 50)
    p.write_text(text)
    got = nativert.count_tokens_file(str(p), common_preprocess=common,
                                     nthreads=3)
    assert got is not None
    assert dict(got) == _python_counts(str(p), common)
    # deterministic ordering: count desc, then word asc
    counts = [c for _, c in got]
    assert counts == sorted(counts, reverse=True)
    for (w1, c1), (w2, c2) in zip(got, got[1:]):
        if c1 == c2:
            assert w1 < w2


def test_vocab_counter_separator_chars_match_python(tmp_path):
    """\x1c-\x1f are whitespace for str.split(); the native scan must agree."""
    p = tmp_path / "corpus.txt"
    p.write_bytes(b"a\x1cb a\x1db c\x1fd\n")
    got = nativert.count_tokens_file(str(p))
    assert got is not None
    assert dict(got) == _python_counts(str(p), False)


def test_vocab_counter_rejects_non_ascii(tmp_path):
    p = tmp_path / "corpus.txt"
    p.write_bytes("caf\xc3\xa9 au lait".encode("latin-1"))
    assert nativert.count_tokens_file(str(p)) is None


def test_vocab_constructor_native_equals_python(tmp_path):
    """VocabConstructor.build_from_file: native fast path == forced-Python
    fallback, including Huffman codes."""
    from deeplearning4j_tpu.nlp.tokenization import (
        CommonPreprocessor, DefaultTokenizerFactory)
    from deeplearning4j_tpu.nlp.vocab import VocabConstructor

    p = tmp_path / "corpus.txt"
    p.write_text("one two two three three three four four four four\n" * 20)
    tf = DefaultTokenizerFactory()
    tf.set_token_pre_processor(CommonPreprocessor())
    vc = VocabConstructor(min_word_frequency=1)
    native = vc.build_from_file(str(p), tf)

    class _NotDefault(DefaultTokenizerFactory):
        pass  # subclass => native path declines, Python pipeline runs

    tf2 = _NotDefault()
    tf2.set_token_pre_processor(CommonPreprocessor())
    python = vc.build_from_file(str(p), tf2)

    assert native.words() == python.words()
    for w in native.words():
        nw, pw = native.word_for(w), python.word_for(w)
        assert nw.count == pw.count
        assert nw.code == pw.code and nw.points == pw.points


def test_vocab_from_file_specials_always_present(tmp_path):
    """Specials absent from the corpus still enter the vocab, matching
    build_vocab's caller-side injection, on BOTH the native and Python
    paths."""
    from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
    from deeplearning4j_tpu.nlp.vocab import VocabConstructor

    p = tmp_path / "corpus.txt"
    p.write_text("alpha beta beta gamma\n" * 5)
    vc = VocabConstructor(min_word_frequency=1, special=("<UNK>",))
    native = vc.build_from_file(str(p))

    class _NotDefault(DefaultTokenizerFactory):
        pass

    python = vc.build_from_file(str(p), _NotDefault())
    assert "<UNK>" in native and "<UNK>" in python
    assert native.words() == python.words()
    for w in native.words():
        assert native.word_for(w).count == python.word_for(w).count
