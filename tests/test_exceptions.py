

def test_uninitialized_network_clear_errors():
    """output/score before init() raise the actionable not-initialized error
    on both network types, never a NoneType crash."""
    import numpy as np
    import pytest

    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.graph_network import (
        ComputationGraph, MultiDataSet)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().list()
            .layer(DenseLayer(n_in=4, n_out=4, activation="relu"))
            .layer(OutputLayer(n_in=4, n_out=2, loss="mse",
                               activation="identity")).build())
    net = MultiLayerNetwork(conf)
    x = np.zeros((2, 4), np.float32)
    y = np.zeros((2, 2), np.float32)
    with pytest.raises(RuntimeError, match="not initialized"):
        net.output(x)
    with pytest.raises(RuntimeError, match="not initialized"):
        net.score(x, y)

    g = (NeuralNetConfiguration.builder().graph_builder()
         .add_inputs("in")
         .add_layer("out", OutputLayer(n_in=4, n_out=2, loss="mse",
                                       activation="identity"), "in")
         .set_outputs("out").build())
    cg = ComputationGraph(g)
    with pytest.raises(RuntimeError, match="not initialized"):
        cg.output(x)
    with pytest.raises(RuntimeError, match="not initialized"):
        cg.score(MultiDataSet([x], [y]))
