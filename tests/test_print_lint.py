"""Lint: no bare ``print(`` in library training/ops/parallel/data code.

Library code must report through logging or the listener pipeline so output
is routable and rate-limitable (and so bench.py's one-JSON-line stdout
contract can't be broken by a stray debug print). Tokenize-based so strings,
comments, and docstrings mentioning print don't false-positive.
"""
import io
import pathlib
import token
import tokenize

PKG = pathlib.Path(__file__).resolve().parents[1] / "deeplearning4j_tpu"
LINTED_DIRS = ("nn", "ops", "parallel", "datasets", "utils")


def _bare_print_calls(path: pathlib.Path):
    """Yield (line, text) for each NAME ``print`` followed by ``(``."""
    toks = list(tokenize.generate_tokens(
        io.StringIO(path.read_text()).readline))
    for i, t in enumerate(toks):
        if t.type == token.NAME and t.string == "print":
            # skip attribute access (x.print) and keyword-arg (print=...)
            if i and toks[i - 1].type == token.OP and toks[i - 1].string == ".":
                continue
            nxt = next((n for n in toks[i + 1:]
                        if n.type not in (token.NL, token.NEWLINE,
                                          token.COMMENT)), None)
            if nxt is not None and nxt.type == token.OP and nxt.string == "(":
                yield t.start[0], t.line.strip()


def test_no_bare_print_in_library_code():
    offenders = []
    for d in LINTED_DIRS:
        for path in sorted((PKG / d).rglob("*.py")):
            for line_no, text in _bare_print_calls(path):
                offenders.append(
                    f"{path.relative_to(PKG.parent)}:{line_no}: {text}")
    assert not offenders, (
        "bare print() in library code (use logging or a listener):\n"
        + "\n".join(offenders))
