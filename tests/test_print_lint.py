"""Lint: no bare ``print(`` anywhere in the library package.

Library code must report through logging or the listener pipeline so output
is routable and rate-limitable (and so bench.py's one-JSON-line stdout
contract can't be broken by a stray debug print). The check itself lives in
graftlint's ``bare-print`` rule (deeplearning4j_tpu/lint) — tokenize-based,
CLI entry points scoped out, deliberate prints suppressed inline with a
reason; this test pins the whole-package run of that one rule.
"""
import pathlib

import deeplearning4j_tpu.lint as lint

PKG = pathlib.Path(lint.__file__).resolve().parents[1]


def test_no_bare_print_in_library_code():
    res = lint.run_paths([PKG], ["bare-print"])
    offenders = [f"{v.path}:{v.line}: {v.snippet}".rstrip()
                 for v in res.violations]
    assert not offenders, (
        "bare print() in library code (use logging or a listener):\n"
        + "\n".join(offenders))
    assert res.errors == []
