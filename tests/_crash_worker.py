"""Worker for the crash-resume fault-injection test: trains with a
per-iteration CheckpointListener, then dies hard (os._exit — no cleanup, no
atexit, the moral equivalent of a preempted TPU host) at iteration 5."""
import os
import sys

import numpy as np


def main() -> None:
    ckpt_dir = sys.argv[1]
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tests.test_checkpoint_finetune import _data, _net

    from deeplearning4j_tpu.optimize.listeners import (
        CheckpointListener, IterationListener)

    net = _net()

    class CrashAt(IterationListener):
        def iteration_done(self, model, iteration):
            if iteration == 5:
                print("CRASHING at iteration 5", flush=True)
                os._exit(17)

    # listener order matters: checkpoint BEFORE the crash hook
    net.set_listeners(CheckpointListener(ckpt_dir, every_n_iterations=1),
                      CrashAt())
    x, y = _data()
    for _ in range(10):
        net.fit(x, y)
    print("never reached", flush=True)


if __name__ == "__main__":
    main()
