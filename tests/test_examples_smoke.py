"""Examples must stay runnable (the reference ships runnable examples as its
de-facto integration suite). Two fast ones run end-to-end via subprocess;
the heavier CNN/parallel examples are covered by their underlying API tests.
"""
import os
import subprocess
import sys
from pathlib import Path

import pytest

_ROOT = Path(__file__).resolve().parent.parent


def _run_example(name: str, *args: str, extra_env: dict = None) -> str:
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra_env or {})
    out = subprocess.run(
        [sys.executable, str(_ROOT / "examples" / name), *args],
        capture_output=True, text=True, timeout=420, env=env, cwd=str(_ROOT))
    assert out.returncode == 0, out.stderr[-800:]
    return out.stdout


def test_word2vec_example():
    stdout = _run_example("word2vec.py")
    assert "nearest to" in stdout


def test_moe_lm_example():
    stdout = _run_example("moe_lm.py", "--steps", "4")
    assert "load-balance term" in stdout


def test_vae_anomaly_example():
    stdout = _run_example("vae_anomaly.py", "--steps", "8")
    assert "anomalous=" in stdout  # self-asserts anomalies score higher


def test_long_context_sp_example():
    # the 8-device mesh is the point: ppermute/all_to_all must actually run
    import re

    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    stdout = _run_example(
        "long_context_sp.py",
        extra_env={"XLA_FLAGS":
                   (flags + " --xla_force_host_platform_device_count=8")
                   .strip()})
    assert "mesh: 8 devices" in stdout
    assert "sequence parallelism OK" in stdout
