"""Examples must stay runnable (the reference ships runnable examples as its
de-facto integration suite). ALL nine examples run end-to-end via subprocess
with few-step budgets (round-4 verdict: partial smoke coverage let examples
rot silently).
"""
import os
import re
import subprocess
import sys
from pathlib import Path

import pytest

_ROOT = Path(__file__).resolve().parent.parent


def _mesh8_env() -> dict:
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    return {"XLA_FLAGS":
            (flags + " --xla_force_host_platform_device_count=8").strip()}


def _run_example(name: str, *args: str, extra_env: dict = None) -> str:
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra_env or {})
    out = subprocess.run(
        [sys.executable, str(_ROOT / "examples" / name), *args],
        capture_output=True, text=True, timeout=420, env=env, cwd=str(_ROOT))
    assert out.returncode == 0, out.stderr[-800:]
    return out.stdout


def test_word2vec_example():
    stdout = _run_example("word2vec.py")
    assert "nearest to" in stdout


def test_moe_lm_example():
    stdout = _run_example("moe_lm.py", "--steps", "4")
    assert "load-balance term" in stdout


def test_vae_anomaly_example():
    stdout = _run_example("vae_anomaly.py", "--steps", "8")
    assert "anomalous=" in stdout  # self-asserts anomalies score higher


def test_long_context_sp_example():
    # the 8-device mesh is the point: ppermute/all_to_all must actually run
    stdout = _run_example("long_context_sp.py", extra_env=_mesh8_env())
    assert "mesh: 8 devices" in stdout
    assert "sequence parallelism OK" in stdout
    assert "config+fit sequence parallelism OK" in stdout


def test_moe_lm_expert_parallel_example():
    stdout = _run_example("moe_lm.py", "--steps", "4", "--experts", "8",
                          "--expert-parallel", extra_env=_mesh8_env())
    assert "expert-parallel fit OK over 8 devices" in stdout


def test_lenet_mnist_example():
    stdout = _run_example("lenet_mnist.py", "--epochs", "1", "--batch", "64",
                          "--num-examples", "256")
    assert "Accuracy" in stdout or "accuracy" in stdout


def test_char_rnn_example():
    stdout = _run_example("char_rnn.py", "--steps", "4")
    assert "sample:" in stdout


def test_graph_char_rnn_example():
    stdout = _run_example("graph_char_rnn.py", "--steps", "4")
    assert "generated:" in stdout


def test_parallel_training_example():
    stdout = _run_example("parallel_training.py", extra_env=_mesh8_env())
    assert "DP done" in stdout


def test_tensor_parallel_checkpoint_example():
    stdout = _run_example("tensor_parallel_checkpoint.py",
                          extra_env=_mesh8_env())
    assert "restored W1" in stdout
