"""Model-zoo architecture smoke tests: the reference-era ImageNet CNN
families (AlexNet, VGG, GoogLeNet/Inception, ResNet) build, forward, and
train at reduced size; parameter counts at full size match the literature."""
import numpy as np
import pytest

from deeplearning4j_tpu.nn.graph_network import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _onehot(n, c, seed=0):
    rng = np.random.default_rng(seed)
    y = np.zeros((n, c), np.float32)
    y[np.arange(n), rng.integers(0, c, n)] = 1
    return y


def test_alexnet_builds_and_trains_small():
    from deeplearning4j_tpu.models import alexnet

    conf = alexnet(n_classes=5, image_size=64)
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(0).normal(size=(2, 64, 64, 3)) \
        .astype(np.float32)
    out = np.asarray(net.output(x))
    assert out.shape == (2, 5)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-5)
    net.fit(x, _onehot(2, 5))
    assert np.isfinite(net.score_value)


def test_alexnet_param_count_matches_literature():
    from deeplearning4j_tpu.models import alexnet

    net = MultiLayerNetwork(alexnet(n_classes=1000, image_size=224)).init()
    n = net.num_params()
    assert 55e6 < n < 66e6, n  # ungrouped AlexNet ~61M


def test_googlenet_builds_and_trains_small():
    from deeplearning4j_tpu.models import googlenet

    conf = googlenet(n_classes=5, image_size=64)
    net = ComputationGraph(conf).init()
    x = np.random.default_rng(1).normal(size=(2, 64, 64, 3)) \
        .astype(np.float32)
    out = np.asarray(net.output(x)[0])
    assert out.shape == (2, 5)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-5)
    net.fit([x], [_onehot(2, 5)])
    assert np.isfinite(net.score_value)


def test_googlenet_param_count_matches_literature():
    from deeplearning4j_tpu.models import googlenet

    net = ComputationGraph(googlenet(n_classes=1000, image_size=224)).init()
    n = net.num_params()
    assert 5.5e6 < n < 7.5e6, n  # Inception-v1 main branch ~6M


def test_moe_transformer_lm_trains():
    import numpy as np

    from deeplearning4j_tpu.models.transformer import moe_transformer_lm
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = moe_transformer_lm(vocab_size=20, width=32, n_layers=2, n_heads=2,
                              n_experts=4, max_len=12, learning_rate=0.01)
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 20, (4, 12))
    x = np.eye(20, dtype=np.float32)[ids]
    l0 = net.score(x, x)
    for _ in range(12):
        net.fit(x, x)
    assert net.score(x, x) < l0
