"""Partition-rule sharding engine + compile seam (parallel/partition.py,
parallel/compile_seam.py).

Pins the PR-8 contract: named-tree walking, regex rule matching with
first-match-wins precedence, scalar/tiny fall-through, the hard error on
unmatched non-scalar leaves, divisibility demotion, the Megatron dp_tp
semantics, ZeRO-3 per-device byte accounting (gauge), the
Pallas-under-shard_map engagement fix through the seam, and — the
gold-standard check (reference TestCompareParameterAveragingSparkVs
SingleMachine, SURVEY.md §4) — that dp / dp_tp / zero3 training through
``.sharding(rule_set)`` is numerically equivalent to single-device fit.
"""
import jax
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.models import transformer_lm
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.observability.metrics import (
    global_registry, tree_nbytes)
from deeplearning4j_tpu.parallel import partition
from deeplearning4j_tpu.parallel.compile_seam import compile_step
from deeplearning4j_tpu.parallel.mesh import build_mesh
from deeplearning4j_tpu.parallel.partition import (
    Col, FirstDivisible, PartitionRuleError, Row, dp_tp_rules,
    match_partition_rules, model_top_names, named_tree_map, pspec as P,
    per_device_bytes, rules_for, zero3_rules)
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper


# --------------------------------------------------------------- tree walk
def test_named_tree_map_joins_paths():
    tree = {"a": {"W": np.zeros((2, 2))}, "b": [np.zeros(3), np.zeros(2)]}
    seen = {}
    named_tree_map(lambda p, leaf: seen.setdefault(p, leaf.shape), tree)
    assert sorted(seen) == ["a/W", "b/0", "b/1"]


def test_named_tree_map_top_names_rewrite():
    tree = [{"W": np.zeros((2, 2))}, {"W": np.zeros((2, 2))}]
    paths = []
    named_tree_map(lambda p, _l: paths.append(p), tree,
                   top_names={"0": "0.DenseLayer", "1": "1.OutputLayer"})
    assert sorted(paths) == ["0.DenseLayer/W", "1.OutputLayer/W"]


def test_model_top_names_from_list_conf():
    conf = (NeuralNetConfiguration.builder().list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=2)).build())
    names = model_top_names([{}, {}], conf)
    assert names == {"0": "0.DenseLayer", "1": "1.OutputLayer"}


# ------------------------------------------------------------ rule matching
def test_rule_precedence_first_match_wins():
    mesh = build_mesh({"data": 8})
    tree = {"layer": {"W": np.zeros((8, 4)), "V": np.zeros((8, 4))}}
    rules = [(r"/W(/|$)", FirstDivisible("data")), (r".*", P())]
    specs = match_partition_rules(rules, tree, mesh=mesh)
    assert specs["layer"]["W"] == P("data")
    assert specs["layer"]["V"] == P()
    # the same specific rule AFTER the catch-all never fires: precedence is
    # positional, so "prepend to override" is the extension idiom
    flipped = match_partition_rules(list(reversed(rules)), tree, mesh=mesh)
    assert flipped["layer"]["W"] == P()


def test_scalar_and_tiny_leaves_fall_through():
    mesh = build_mesh({"data": 8})
    tree = {"l": {"s": np.float32(3.0), "one": np.zeros((1,)),
                  "tiny": np.zeros((3,)), "big": np.zeros((8,))}}
    specs = match_partition_rules([(r".*", FirstDivisible("data"))],
                                  tree, mesh=mesh)
    assert specs["l"]["s"] == P()          # scalar: never consults rules
    assert specs["l"]["one"] == P()        # size-1
    assert specs["l"]["tiny"] == P()       # 1-D below TINY_VECTOR
    assert specs["l"]["big"] == P("data")  # at the floor: rules apply


def test_unmatched_nonscalar_leaf_is_a_hard_error():
    with pytest.raises(PartitionRuleError, match="no partition rule"):
        match_partition_rules([(r"/W(/|$)", P())],
                              {"layer": {"Q": np.zeros((8, 8))}})
    # ... but scalars don't need a rule at all
    specs = match_partition_rules([], {"layer": {"s": np.float32(0)}})
    assert specs["layer"]["s"] == P()


def test_rule_values_are_rank_polymorphic():
    mesh = build_mesh({"data": 4, "model": 2})
    tree = {"l": {"dense": np.zeros((8, 16)),
                  "conv": np.zeros((3, 3, 8, 16)),
                  "experts": np.zeros((4, 8, 6)),
                  "bias": np.zeros((16,))}}
    col = match_partition_rules([(r".*", Col("model"))], tree, mesh=mesh)
    assert col["l"]["dense"] == P(None, "model")
    assert col["l"]["conv"] == P(None, None, None, "model")
    assert col["l"]["experts"] == P(None, None, "model")
    assert col["l"]["bias"] == P("model")
    row = match_partition_rules([(r".*", Row("model"))], tree, mesh=mesh)
    assert row["l"]["dense"] == P("model", None)
    assert row["l"]["conv"] == P(None, None, "model", None)
    assert row["l"]["bias"] == P()        # 1-D: row-split bias replicates
    z = match_partition_rules([(r".*", FirstDivisible("data"))], tree,
                              mesh=mesh)
    assert z["l"]["dense"] == P("data")            # 8 % 4 == 0
    assert z["l"]["experts"] == P("data")          # dim0 4 % 4 == 0
    assert z["l"]["conv"] == P(None, None, "data")  # 3,3 indivisible; 8 is


def test_indivisible_dims_demote_to_replicated():
    mesh = build_mesh({"data": 4, "model": 2})
    tree = {"l": {"odd": np.zeros((8, 15)), "skinny": np.zeros((5, 3))}}
    specs = match_partition_rules([(r".*", Col("model"))], tree, mesh=mesh)
    assert specs["l"]["odd"] == P()       # 15 % 2 != 0
    assert specs["l"]["skinny"] == P()
    # a plain-PartitionSpec rule value demotes the same way
    specs = match_partition_rules([(r".*", P("data"))], tree, mesh=mesh)
    assert specs["l"]["odd"] == P("data")  # 8 % 4 == 0
    assert specs["l"]["skinny"] == P()     # 5 % 4 != 0


def test_dp_tp_rules_megatron_semantics():
    """Column-split up-projections + their biases; row-split down-projections
    with replicated biases; gate/norm params replicated. One rule covers a
    param and its optimizer moments (the moment path extends the param's)."""
    mesh = build_mesh({"data": 4, "model": 2})
    blk = {"Wqkv": np.zeros((32, 96)), "Wo": np.zeros((32, 32)),
           "W1": np.zeros((32, 64)), "W2": np.zeros((64, 32)),
           "b1": np.zeros((64,)), "b2": np.zeros((32,)),
           "Wg": np.zeros((32, 8)), "g1": np.zeros((32,))}
    tree = {"blk": blk,
            "opt": {"Wqkv": {"m": np.zeros((32, 96))}}}
    specs = match_partition_rules(dp_tp_rules(), tree, mesh=mesh)
    assert specs["blk"]["Wqkv"] == P(None, "model")
    assert specs["blk"]["Wo"] == P("model", None)
    assert specs["blk"]["W1"] == P(None, "model")
    assert specs["blk"]["W2"] == P("model", None)
    assert specs["blk"]["b1"] == P("model")
    assert specs["blk"]["b2"] == P()   # row-split partner bias: replicated
    assert specs["blk"]["Wg"] == P()   # MoE gate: replicated
    assert specs["blk"]["g1"] == P()   # norm gain: replicated
    # the moment inherits the param's rule via the extended path .../Wqkv/m
    assert specs["opt"]["Wqkv"]["m"] == P(None, "model")


def test_rules_for_unknown_name():
    with pytest.raises(ValueError, match="unknown rule set"):
        rules_for("fsdp2")


# ---------------------------------------------------- byte accounting/gauge
def test_per_device_bytes_and_gauge_zero3():
    mesh = build_mesh({"data": 8})
    tree = {"l": {"W": np.zeros((16, 4), np.float32),
                  "b": np.zeros((3,), np.float32)}}
    specs = match_partition_rules(zero3_rules(), tree, mesh=mesh)
    # W sharded 8-way (256 -> 32), tiny b stays whole (12)
    assert per_device_bytes(tree, specs, mesh) == 32 + 12
    # a bare P() prefix means fully replicated
    assert per_device_bytes(tree, P(), mesh) == tree_nbytes(tree)

    recorded = partition.record_param_bytes("ut_zero3", tree, specs, mesh)
    assert recorded == 44
    series = global_registry().snapshot()[
        "dl4j_sharded_param_bytes_per_device"]["series"]
    vals = {s["labels"]["rule_set"]: s["value"] for s in series}
    assert vals["ut_zero3"] == 44


def test_spec_counter_records_resolved_specs():
    before = _spec_counts("ut_counter")
    partition.record_specs("ut_counter",
                           [P("data"), P()], {"x": P(None, "model")})
    after = _spec_counts("ut_counter")
    assert after.get("P(data)", 0) - before.get("P(data)", 0) == 1
    assert after.get("P()", 0) - before.get("P()", 0) == 1
    assert after.get("P(None,model)", 0) - before.get("P(None,model)", 0) == 1


def _spec_counts(rule_set):
    snap = global_registry().snapshot().get(
        "dl4j_sharding_spec_total", {"series": []})
    return {s["labels"]["spec"]: s["value"] for s in snap["series"]
            if s["labels"]["rule_set"] == rule_set}


# ------------------------------------------- pallas engagement through seam
def _dispatch_counts():
    snap = global_registry().snapshot().get(
        "dl4j_pallas_dispatch_total", {"series": []})
    return {(s["labels"]["kernel"], s["labels"]["engaged"]): s["value"]
            for s in snap["series"]}


def test_pallas_engages_under_seam_shard_map():
    """THE regression the seam's check_vma=False default exists for: a flash
    kernel inside a shard_map body compiled through compile_step must ENGAGE
    (interpret mode on CPU), where a vma-checked body silently downgrades it
    to XLA math. Pinned via the dispatch counter, which counts per trace."""
    from deeplearning4j_tpu.ops.pallas_kernels import flash_attention

    mesh = build_mesh({"data": 8})
    rng = np.random.default_rng(0)
    q, k, v = (np.asarray(rng.normal(size=(8, 64, 2, 8)), np.float32)
               for _ in range(3))

    def body(qq, kk, vv):
        return flash_attention(qq, kk, vv, False, interpret=True)

    before = _dispatch_counts()
    step = compile_step("ut.flash_unchecked", body, mesh=mesh,
                        rule_set="dp", in_specs=(P("data"),) * 3,
                        out_specs=P("data"), strategy="shard_map",
                        check_vma=False)
    out = np.asarray(step(q, k, v))
    assert out.shape == q.shape and np.isfinite(out).all()
    mid = _dispatch_counts()
    key_t = ("flash_attention", "true")
    key_f = ("flash_attention", "false")
    assert mid.get(key_t, 0) > before.get(key_t, 0)

    # contrast: the checked body must NOT engage (counter says so too)
    checked = compile_step("ut.flash_checked", body, mesh=mesh,
                           rule_set="dp", in_specs=(P("data"),) * 3,
                           out_specs=P("data"), strategy="shard_map",
                           check_vma=True)
    np.asarray(checked(q, k, v))
    after = _dispatch_counts()
    assert after.get(key_f, 0) > mid.get(key_f, 0)
    assert after.get(key_t, 0) == mid.get(key_t, 0)


def test_compile_step_rejects_unknown_strategy():
    mesh = build_mesh({"data": 8})
    with pytest.raises(ValueError, match="unknown compile strategy"):
        compile_step("ut.bad", lambda x: x, mesh=mesh, rule_set="dp",
                     strategy="pmap")


# -------------------------------------------------------- equivalence suite
VOCAB, WIDTH, HEADS, T, B = 8, 32, 4, 16, 8


def _lm_batches(n=3, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        ids = rng.integers(0, VOCAB, size=(B, T + 1))
        x = np.eye(VOCAB, dtype=np.float32)[ids[:, :-1]]
        y = np.eye(VOCAB, dtype=np.float32)[ids[:, 1:]]
        out.append(DataSet(x, y))
    return out


def _dense_conf(seed=7):
    return (NeuralNetConfiguration.builder().seed(seed).learning_rate(0.05)
            .updater("adam").list()
            .layer(DenseLayer(n_in=8, n_out=16, activation="tanh"))
            .layer(OutputLayer(n_in=16, n_out=3, loss="mcxent",
                               activation="softmax")).build())


def _dense_batches(n=4, seed=0, b=32):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        x = rng.normal(size=(b, 8)).astype(np.float32)
        y = np.zeros((b, 3), np.float32)
        y[np.arange(b), rng.integers(0, 3, b)] = 1
        out.append(DataSet(x, y))
    return out


def _single_device_fit(conf, batches):
    net = MultiLayerNetwork(conf).init()
    for ds in batches:
        net.fit(ds.features, ds.labels)
    return net


def test_dp_tp_sharding_equals_single_device():
    """.sharding('dp_tp') on a {data, model} mesh: Megatron splits on the
    attention/MLP weights, same numbers as dense single-device training —
    the specs are layout hints, GSPMD inserts the collectives."""
    batches = _lm_batches()
    conf = lambda: transformer_lm(VOCAB, width=WIDTH, n_layers=2,
                                  n_heads=HEADS, max_len=T,
                                  learning_rate=0.01)
    single = _single_device_fit(conf(), batches)

    net = MultiLayerNetwork(conf()).init()
    mesh = build_mesh({"data": 4, "model": 2})
    pw = (ParallelWrapper.builder(net).mesh(mesh).prefetch_buffer(0)
          .sharding("dp_tp").build())
    pw.fit(ListDataSetIterator(batches))
    np.testing.assert_allclose(np.asarray(single.params()),
                               np.asarray(net.params()),
                               atol=1e-4, rtol=1e-4)
    # the engine actually split something: a TP-sharded leaf holds half
    wqkv = next(p["Wqkv"] for p in net.params_list if "Wqkv" in p)
    assert wqkv.addressable_shards[0].data.nbytes * 2 == wqkv.nbytes


def test_zero3_sharding_equals_single_device():
    """.sharding('zero3'): params AND moments live ~1/N per device (pinned
    through the new gauge), training equals single-device fit exactly."""
    batches = _dense_batches()
    single = _single_device_fit(_dense_conf(), batches)

    net = MultiLayerNetwork(_dense_conf()).init()
    pw = (ParallelWrapper.builder(net).workers(8).prefetch_buffer(0)
          .sharding("zero3").build())
    pw.fit(ListDataSetIterator(batches))
    np.testing.assert_allclose(np.asarray(single.params()),
                               np.asarray(net.params()), atol=2e-6)
    w = net.params_list[0]["W"]                  # (8, 16): dim0 8-way
    assert w.addressable_shards[0].data.nbytes * 8 == w.nbytes
    m = net.updater_state[1]["W"]["m"]           # moments ride the same rule
    assert m.addressable_shards[0].data.nbytes * 8 == m.nbytes

    series = global_registry().snapshot()[
        "dl4j_sharded_param_bytes_per_device"]["series"]
    vals = {s["labels"]["rule_set"]: s["value"] for s in series}
    total = tree_nbytes(net.params_list)
    # every non-tiny leaf divides by 8 here, so per-device ~ total/8 (the
    # 12-byte output bias is the only replicated remainder)
    assert total / 8 <= vals["zero3"] <= total / 8 + 16


def test_zero3_multistep_prefetch_equals_single_device():
    """The fused K-step dispatch path (k_step_groups) + device prefetch,
    compiled through the same seam with the same zero3 spec trees, stays
    numerically identical — 10 uniform batches form an 8-group + remainder,
    exercising sync_multistep AND sync_step under sharded specs."""
    batches = _dense_batches(n=10, seed=3)
    single = _single_device_fit(_dense_conf(seed=11), batches)

    net = MultiLayerNetwork(_dense_conf(seed=11)).init()
    pw = (ParallelWrapper.builder(net).workers(8).prefetch_buffer(2)
          .sharding("zero3").build())
    pw.fit(ListDataSetIterator(batches))
    np.testing.assert_allclose(np.asarray(single.params()),
                               np.asarray(net.params()), atol=2e-6)


def test_sharding_rule_set_validation():
    net = MultiLayerNetwork(_dense_conf()).init()
    with pytest.raises(ValueError, match="unknown sharding rule set"):
        ParallelWrapper.builder(net).workers(8).sharding("3d").build()
    with pytest.raises(ValueError, match="'model' axis"):
        ParallelWrapper.builder(net).workers(8).sharding("dp_tp").build()
    with pytest.raises(ValueError, match="averaging_frequency"):
        (ParallelWrapper.builder(net)
         .mesh(build_mesh({"data": 4, "model": 2}))
         .averaging_frequency(4).sharding("dp_tp").build())


def test_dp_tp_engage_or_fail():
    """An explicit dp_tp request on a net where NO dim divides the model
    axis must raise, not silently replicate everything (the engage-or-fail
    principle shared with .expert_parallel())."""
    conf = (NeuralNetConfiguration.builder().seed(1).list()
            .layer(DenseLayer(n_in=5, n_out=7, activation="tanh"))
            .layer(OutputLayer(n_in=7, n_out=3, loss="mcxent",
                               activation="softmax")).build())
    net = MultiLayerNetwork(conf).init()
    pw = (ParallelWrapper.builder(net)
          .mesh(build_mesh({"data": 4, "model": 2})).prefetch_buffer(0)
          .sharding("dp_tp").build())
    with pytest.raises(ValueError, match="nothing would shard"):
        pw.fit(ListDataSetIterator(_odd_batches()))


def _odd_batches():
    rng = np.random.default_rng(2)
    x = rng.normal(size=(8, 5)).astype(np.float32)
    y = np.zeros((8, 3), np.float32)
    y[np.arange(8), rng.integers(0, 3, 8)] = 1
    return [DataSet(x, y)]
