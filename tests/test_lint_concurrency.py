"""graftlint concurrency plane: rule fixtures + runtime witness units.

Each of the four rules (lockguard, lock-order, blocking-under-lock,
thread-lifecycle) gets a true-positive fixture — including the seeded
race and the two-lock deadlock the plane exists to catch — a negative
fixture, and a suppressed fixture. The annotation grammar
(``#: guarded-by:`` / ``#: requires-lock:``) and the parallel runner's
determinism are covered below; the whole-package clean gate lives in
test_lint_engine.py and picks these rules up through the registry.
"""
import pathlib
import textwrap
import threading

import pytest

import deeplearning4j_tpu.lint as lint
from deeplearning4j_tpu.lint import witness

PKG = pathlib.Path(lint.__file__).resolve().parents[1]

CONCURRENCY_RULES = ["lockguard", "lock-order", "blocking-under-lock",
                     "thread-lifecycle"]


def lint_src(tmp_path, source, name="fixture.py", rules=CONCURRENCY_RULES):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    return lint.run_paths([f], rules)


def rules_of(result):
    return [v.rule for v in result.violations]


# ------------------------------------------------------------------ lockguard
def test_lockguard_seeded_race_flagged(tmp_path):
    """The seeded race: an attribute the class itself locks in one method,
    mutated bare in another — and from a Thread target, the worst case."""
    res = lint_src(tmp_path, """\
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
                t = threading.Thread(target=self._work, daemon=True)
                t.start()

            def bump(self):
                with self._lock:
                    self._n += 1

            def _work(self):
                self._n += 1
        """)
    assert rules_of(res) == ["lockguard"]
    v = res.violations[0]
    assert v.line == 15
    assert "_n" in v.message and "Thread target" in v.message


def test_lockguard_negative_consistent_and_init_exempt(tmp_path):
    res = lint_src(tmp_path, """\
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0      # construction precedes sharing: exempt

            def bump(self):
                with self._lock:
                    self._n += 1

            def reset(self):
                with self._lock:
                    self._n = 0
        """)
    assert res.violations == []


def test_lockguard_guarded_by_annotation_flags_bare_read(tmp_path):
    res = lint_src(tmp_path, """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                #: guarded-by: _lock
                self._items = []

            def add(self, x):
                with self._lock:
                    self._items.append(x)

            def peek(self):
                return self._items[-1]
        """)
    assert rules_of(res) == ["lockguard"]
    assert res.violations[0].line == 14


def test_lockguard_requires_lock_annotation_negative(tmp_path):
    """A helper declared ``requires-lock`` is analysed with the lock held:
    its writes are locked writes, not bare ones."""
    res = lint_src(tmp_path, """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def bump(self):
                with self._lock:
                    self._inc()

            #: requires-lock: _lock
            def _inc(self):
                self._n += 1
        """)
    assert res.violations == []


def test_lockguard_suppressed(tmp_path):
    res = lint_src(tmp_path, """\
        import threading

        class Stat:
            def __init__(self):
                self._lock = threading.Lock()
                self._hits = 0

            def note(self):
                with self._lock:
                    self._hits += 1

            def roughly(self):
                self._hits += 1  # lint: lockguard-ok (stat is advisory; torn increments tolerated)
        """)
    assert res.violations == []
    assert [v.rule for v in res.suppressed] == ["lockguard"]


# ----------------------------------------------------------------- lock-order
def test_lock_order_two_lock_cycle_flagged(tmp_path):
    """The seeded deadlock: the same two locks nested in both orders."""
    res = lint_src(tmp_path, """\
        import threading

        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def fwd(self):
                with self._a:
                    with self._b:
                        pass

            def rev(self):
                with self._b:
                    with self._a:
                        pass
        """)
    assert rules_of(res) == ["lock-order"]
    assert "_a" in res.violations[0].message
    assert "_b" in res.violations[0].message


def test_lock_order_cycle_through_method_call_flagged(tmp_path):
    """Interprocedural: the inner acquisition hides in a callee."""
    res = lint_src(tmp_path, """\
        import threading

        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def fwd(self):
                with self._a:
                    self._take_b()

            def _take_b(self):
                with self._b:
                    pass

            def rev(self):
                with self._b:
                    with self._a:
                        pass
        """)
    assert rules_of(res) == ["lock-order"]


def test_lock_order_negative_consistent_nesting(tmp_path):
    res = lint_src(tmp_path, """\
        import threading

        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._a:
                    with self._b:
                        pass
        """)
    assert res.violations == []


def test_lock_order_self_deadlock_on_plain_lock(tmp_path):
    """Re-acquiring a non-reentrant Lock you already hold blocks forever."""
    res = lint_src(tmp_path, """\
        import threading

        class Oops:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
        """)
    assert "lock-order" in rules_of(res)


def test_lock_order_rlock_reentry_negative(tmp_path):
    res = lint_src(tmp_path, """\
        import threading

        class Fine:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
        """)
    assert res.violations == []


def test_lock_order_suppressed(tmp_path):
    res = lint_src(tmp_path, """\
        import threading

        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def fwd(self):
                with self._a:
                    # lint: lock-order-ok (rev only runs in the single-threaded teardown path)
                    with self._b:
                        pass

            def rev(self):
                with self._b:
                    with self._a:
                        pass
        """)
    assert res.violations == []
    assert [v.rule for v in res.suppressed] == ["lock-order"]


# -------------------------------------------------------- blocking-under-lock
def test_blocking_under_lock_positive(tmp_path):
    res = lint_src(tmp_path, """\
        import threading
        import time

        class Slow:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = None

            def nap(self):
                with self._lock:
                    time.sleep(0.5)

            def drain(self):
                with self._lock:
                    return self._q.get(timeout=1.0)
        """)
    assert rules_of(res) == ["blocking-under-lock"] * 2
    assert {v.line for v in res.violations} == {11, 15}


def test_blocking_under_lock_callee_positive(tmp_path):
    """Depth-1 interprocedural: the sleep hides one call down."""
    res = lint_src(tmp_path, """\
        import threading
        import time

        class Slow:
            def __init__(self):
                self._lock = threading.Lock()

            def nap(self):
                with self._lock:
                    self._backoff()

            def _backoff(self):
                time.sleep(0.5)
        """)
    assert rules_of(res) == ["blocking-under-lock"]


def test_blocking_under_lock_negative_wait_and_unlocked_sleep(tmp_path):
    """Condition.wait on your own condition releases the lock — that is
    the one blocking call that belongs under it. Sleeping outside any
    lock is also fine."""
    res = lint_src(tmp_path, """\
        import threading
        import time

        class Waiter:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)

            def park(self):
                with self._cond:
                    self._cond.wait(timeout=1.0)

            def backoff(self):
                time.sleep(0.5)
        """)
    assert res.violations == []


def test_blocking_under_lock_suppressed(tmp_path):
    res = lint_src(tmp_path, """\
        import threading
        import time

        class Slow:
            def __init__(self):
                self._lock = threading.Lock()

            def nap(self):
                with self._lock:
                    time.sleep(0.5)  # lint: blocking-under-lock-ok (cold init path, lock is the init serializer)
        """)
    assert res.violations == []
    assert [v.rule for v in res.suppressed] == ["blocking-under-lock"]


# ----------------------------------------------------------- thread-lifecycle
def test_thread_lifecycle_unjoined_undeclared_flagged(tmp_path):
    res = lint_src(tmp_path, """\
        import threading

        def spawn(work):
            t = threading.Thread(target=work)
            t.start()
        """)
    assert rules_of(res) == ["thread-lifecycle"]


def test_thread_lifecycle_negatives(tmp_path):
    res = lint_src(tmp_path, """\
        import threading

        def daemon_kwarg(work):
            t = threading.Thread(target=work, daemon=True)
            t.start()

        def daemon_attr(work):
            t = threading.Thread(target=work)
            t.daemon = True
            t.start()

        def joined(work):
            t = threading.Thread(target=work)
            t.start()
            t.join()

        class Owner:
            def __init__(self, work):
                self._t = threading.Thread(target=work)
                self._t.start()

            def close(self):
                self._t.join()
        """)
    assert res.violations == []


def test_thread_lifecycle_suppressed(tmp_path):
    res = lint_src(tmp_path, """\
        import threading

        def fire_and_forget(work):
            # lint: thread-lifecycle-ok (process-lifetime worker; dies with the interpreter by design)
            t = threading.Thread(target=work)
            t.start()
        """)
    assert res.violations == []
    assert [v.rule for v in res.suppressed] == ["thread-lifecycle"]


# ------------------------------------------------------------ parallel runner
def test_jobs_output_is_deterministic(tmp_path):
    """--jobs N must be byte-equivalent to sequential: same violations,
    same order, same suppressed set, whatever the worker count."""
    pkg = tmp_path / "fixpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    for i in range(6):
        (pkg / f"mod{i}.py").write_text(textwrap.dedent(f"""\
            import threading

            class C{i}:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def locked(self):
                    with self._lock:
                        self._n += 1

                def bare(self):
                    self._n += {i + 1}
            """))
    seq = lint.run_paths([pkg], CONCURRENCY_RULES, jobs=1)
    par = lint.run_paths([pkg], CONCURRENCY_RULES, jobs=3)
    assert [v.to_json() for v in seq.violations] \
        == [v.to_json() for v in par.violations]
    assert len(seq.violations) == 6
    assert seq.files_scanned == par.files_scanned == 7
    assert seq.errors == par.errors == []


def test_rule_versions_change_with_rule_source():
    """The baseline keys suppressions to these hashes — they must be
    stable within a run and present for every registered rule."""
    vers = lint.rule_versions()
    assert set(vers) == set(lint.rule_names())
    assert all(len(h) == 12 for h in vers.values())
    assert vers == lint.rule_versions()  # deterministic
    # distinct rules hash distinctly (sha1 of distinct sources)
    assert len(set(vers.values())) == len(vers)


# ---------------------------------------------------------- runtime witness
@pytest.fixture()
def fresh_witness():
    witness.reset()
    witness.install()
    try:
        yield witness
    finally:
        witness.uninstall()
        witness.reset()


def test_witness_records_order_and_passes_when_acyclic(fresh_witness):
    a = threading.Lock()
    b = threading.RLock()
    with a:
        with b:
            pass
    with a:  # same order again: still one edge
        with b:
            pass
    assert len(fresh_witness.edges()) == 1
    fresh_witness.assert_acyclic()


def test_witness_detects_inverted_order(fresh_witness):
    a = threading.Lock()
    b = threading.Lock()
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    assert len(fresh_witness.cycles()) == 1
    with pytest.raises(AssertionError) as ei:
        fresh_witness.assert_acyclic()
    assert "cyclic acquisition order" in str(ei.value)


def test_witness_rlock_reentry_is_not_an_edge(fresh_witness):
    r = threading.RLock()
    with r:
        with r:
            pass
    assert fresh_witness.edges() == {}
    fresh_witness.assert_acyclic()


def test_witness_condition_wait_roundtrip(fresh_witness):
    """Condition over a witnessed lock: wait() fully releases (the lock
    leaves the held stack) and the re-acquire on wake records no edge."""
    outer = threading.Lock()
    cond = threading.Condition()  # default RLock comes from the patched factory

    def waker():
        with cond:
            cond.notify_all()

    with cond:
        t = threading.Thread(target=waker)
        t.start()
        cond.wait(timeout=5.0)
        t.join()
    with outer:  # after the roundtrip the stack must be clean
        pass
    assert all(n not in e for e in fresh_witness.edges()
               for n in ("outer",))
    fresh_witness.assert_acyclic()


def test_witness_cross_thread_edges_merge(fresh_witness):
    """Edges from different threads land in one graph: thread 1 takes
    a->b, thread 2 takes b->a, and only the union shows the deadlock."""
    a = threading.Lock()
    b = threading.Lock()
    done = threading.Barrier(2)

    def t1():
        with a:
            done.wait()  # hold a until t2 holds b: real lock juggling,
        done.wait()      # sequenced so the test itself cannot deadlock
        with a:
            with b:
                pass

    def t2():
        with b:
            done.wait()
        done.wait()
        with b:
            with a:
                pass

    th1 = threading.Thread(target=t1)
    th2 = threading.Thread(target=t2)
    th1.start(); th2.start()
    th1.join(); th2.join()
    assert len(fresh_witness.cycles()) == 1


def test_witness_uninstall_restores_real_factories():
    real_lock, real_rlock = threading.Lock, threading.RLock
    witness.install()
    assert threading.Lock is not real_lock
    witness.uninstall()
    assert threading.Lock is real_lock
    assert threading.RLock is real_rlock
