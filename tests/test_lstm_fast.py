"""Equivalence suite for the three-variant recurrent engine (ops/lstm.py).

The scan variant is the oracle: fused and pallas (interpret mode on CPU) must
reproduce its forward within 1e-5 relative in f32 and its gradients through
their own backward paths (autodiff through the fused scan, the hand-derived
custom VJP for the kernel). Dispatch-gate selection is pinned per env
override, and the serving seam is held to a bitwise contract: a T-step
rnnTimeStep loop equals one fused-scan forward exactly in f32.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (GravesBidirectionalLSTM,
                                               GravesLSTM, LSTM,
                                               RnnOutputLayer)
from deeplearning4j_tpu.nn.gradientcheck import check_gradients
from deeplearning4j_tpu.nn.multilayer import (MultiLayerNetwork,
                                              make_multistep_train_step)
from deeplearning4j_tpu.ops import lstm as eng
from deeplearning4j_tpu.ops.activations import get_activation

B, T, F, H = 3, 7, 5, 6
ACT, GATE = get_activation("tanh"), get_activation("sigmoid")


def _params(peephole: bool, seed: int = 0, n_in: int = F, hidden: int = H):
    rng = np.random.default_rng(seed)
    p = {"W": jnp.asarray(rng.normal(0, 0.3, (n_in, 4 * hidden)), jnp.float32),
         "RW": jnp.asarray(rng.normal(0, 0.3, (hidden, 4 * hidden)),
                           jnp.float32),
         "b": jnp.asarray(rng.normal(0, 0.1, (4 * hidden,)), jnp.float32)}
    if peephole:
        for k in ("pI", "pF", "pO"):
            p[k] = jnp.asarray(rng.normal(0, 0.2, (hidden,)), jnp.float32)
    return p


def _inputs(seed: int = 0, batch: int = B, seq: int = T, n_in: int = F,
            masked: bool = True):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(0, 1, (batch, seq, n_in)), jnp.float32)
    mask = (jnp.asarray((rng.random((batch, seq)) > 0.3)
                        .astype(np.float32)) if masked else None)
    return x, mask


def _run(impl, p, x, mask, peephole, h0=None, c0=None):
    z = jnp.zeros((x.shape[0], p["RW"].shape[0]), jnp.float32)
    return eng.lstm_sequence(p, x, ACT, GATE,
                             z if h0 is None else h0,
                             z if c0 is None else c0,
                             peephole, mask, impl=impl,
                             interpret=(impl == "pallas"))


# --------------------------------------------------------- forward vs oracle
@pytest.mark.parametrize("impl", ["fused", "pallas"])
@pytest.mark.parametrize("peephole", [False, True])
@pytest.mark.parametrize("masked", [False, True])
def test_forward_matches_scan_oracle(impl, peephole, masked):
    p = _params(peephole)
    x, mask = _inputs(masked=masked)
    ys0, (h0, c0) = _run("scan", p, x, mask, peephole)
    ys1, (h1, c1) = _run(impl, p, x, mask, peephole)
    np.testing.assert_allclose(ys1, ys0, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(h1, h0, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(c1, c0, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("seq", [1, 8, 16, 33])
def test_pallas_block_padding_all_seq_lengths(seq):
    """Any T is serviceable: the engine pads to a block multiple with zero
    mask, the kernel freezes state on the pad, the engine trims the pad."""
    p = _params(True, seed=3)
    x, mask = _inputs(seed=3, seq=seq)
    ys0, (h0, c0) = _run("scan", p, x, mask, True)
    ys1, (h1, c1) = _run("pallas", p, x, mask, True)
    assert ys1.shape == ys0.shape
    np.testing.assert_allclose(ys1, ys0, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(h1, h0, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(c1, c0, rtol=1e-5, atol=1e-6)


# -------------------------------------------------------- gradients vs oracle
@pytest.mark.parametrize("impl", ["fused", "pallas"])
@pytest.mark.parametrize("peephole", [False, True])
@pytest.mark.parametrize("masked", [False, True])
def test_grad_matches_scan_oracle(impl, peephole, masked):
    """d(params), d(x), and d(h0, c0) — the initial-state cotangents are what
    TBPTT chunk boundaries hand backward, so they get checked too."""
    p = _params(peephole, seed=1)
    x, mask = _inputs(seed=1)
    rng = np.random.default_rng(9)
    h0 = jnp.asarray(rng.normal(0, 1, (B, H)), jnp.float32)
    c0 = jnp.asarray(rng.normal(0, 1, (B, H)), jnp.float32)

    def grads(which):
        def loss(p_, x_, h0_, c0_):
            ys, (h, c) = _run(which, p_, x_, mask, peephole, h0_, c0_)
            return (jnp.sum(jnp.cos(ys)) + jnp.sum(h * h)
                    + jnp.sum(jnp.sin(c)))
        return jax.grad(loss, argnums=(0, 1, 2, 3))(p, x, h0, c0)

    g0, g1 = grads("scan"), grads(impl)
    for k in g0[0]:
        np.testing.assert_allclose(g1[0][k], g0[0][k], rtol=1e-4, atol=1e-5,
                                   err_msg=f"d{k}")
    for a, b, name in ((g1[1], g0[1], "dx"), (g1[2], g0[2], "dh0"),
                      (g1[3], g0[3], "dc0")):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5, err_msg=name)


def test_pallas_custom_vjp_gradientcheck(monkeypatch):
    """Numeric-vs-analytic check THROUGH the kernel's hand-derived backward:
    check_gradients swaps in an all-f64 policy, and the kernel's compute
    dtype promotes with the operands, so the interpret-mode run really is
    checked at f64 resolution."""
    monkeypatch.setenv(eng.IMPL_ENV, "pallas")
    monkeypatch.setenv("DL4J_LSTM_INTERPRET", "1")
    net = MultiLayerNetwork(
        NeuralNetConfiguration.builder().seed(7).list()
        .layer(GravesLSTM(n_in=4, n_out=5, activation="tanh"))
        .layer(RnnOutputLayer(n_in=5, n_out=3, loss="mcxent",
                              activation="softmax"))
        .build())
    net.init()
    rng = np.random.default_rng(5)
    x = rng.normal(size=(2, 6, 4)).astype(np.float32)
    ids = rng.integers(0, 3, (2, 6))
    y = np.eye(3, dtype=np.float32)[ids]
    assert check_gradients(net, x, y, subset=60, verbose=True)


# ----------------------------------------------------------- layer-level path
@pytest.mark.parametrize("impl", ["fused", "pallas"])
def test_bidirectional_layer_matches_scan(impl, monkeypatch):
    layer = GravesBidirectionalLSTM(n_in=F, n_out=H, activation="tanh")
    params = layer.init_params(jax.random.PRNGKey(0), InputType.recurrent(F))
    x, mask = _inputs(seed=2)

    def run(which):
        monkeypatch.setenv(eng.IMPL_ENV, which)
        monkeypatch.setenv("DL4J_LSTM_INTERPRET",
                           "1" if which == "pallas" else "0")
        ys, _ = layer.apply(params, {}, x, mask=mask)
        return ys

    np.testing.assert_allclose(run(impl), run("scan"), rtol=1e-5, atol=1e-6)


def test_rnn_time_step_loop_bitwise_equals_fused_forward(monkeypatch):
    """The serving seam's contract (ISSUE 6 satellite): T single-step
    apply_streaming calls reproduce one fused-scan forward BITWISE in f32 —
    both paths run the identical per-step cell primitives, so streaming
    inference cannot drift from training numerics."""
    monkeypatch.setenv(eng.IMPL_ENV, "fused")
    layer = LSTM(n_in=F, n_out=H, activation="tanh")
    params = layer.init_params(jax.random.PRNGKey(1), InputType.recurrent(F))
    x, _ = _inputs(seed=4, masked=False)
    full, _ = layer.apply(params, {}, x)
    state = {}
    steps = []
    for t in range(T):
        yt, state = layer.apply_streaming(params, state, x[:, t:t + 1])
        steps.append(yt)
    loop = jnp.concatenate(steps, axis=1)
    assert np.array_equal(np.asarray(full), np.asarray(loop))


@pytest.mark.parametrize("impl", ["scan", "fused", "pallas"])
def test_multistep_kgroup_training_matches_oracle(impl, monkeypatch):
    """K-step fused-dispatch training (the bench/fit hot path) reaches the
    same losses and parameters under every variant — the dispatch decision
    holds for the whole K-group trace, fwd AND bwd."""
    from deeplearning4j_tpu.models.char_rnn import char_rnn_lstm

    def train(which):
        monkeypatch.setenv(eng.IMPL_ENV, which)
        monkeypatch.setenv("DL4J_LSTM_INTERPRET",
                           "1" if which == "pallas" else "0")
        conf = char_rnn_lstm(vocab_size=8, hidden=6, layers=1,
                             tbptt_length=5)
        conf.backprop_type = "Standard"
        net = MultiLayerNetwork(conf).init()
        multi = make_multistep_train_step(conf)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 8, (3, 2, 5))  # [K, B, T]
        xs = jnp.asarray(np.eye(8, dtype=np.float32)[ids])
        params, states, upd, loss = multi(
            net.params_list, net.state_list, net.updater_state, xs, xs,
            jax.random.PRNGKey(0), jnp.int32(0))
        return params, loss

    p0, l0 = train("scan")
    p1, l1 = train(impl)
    np.testing.assert_allclose(l1, l0, rtol=1e-4, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p0)):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


# ------------------------------------------------------------- dispatch gate
class TestDispatchGate:
    def test_default_is_fused_on_cpu(self, monkeypatch):
        monkeypatch.delenv(eng.IMPL_ENV, raising=False)
        assert eng.resolve_impl(H, T, B, F) == ("fused", None)

    @pytest.mark.parametrize("forced", ["scan", "fused"])
    def test_env_forces_variant(self, forced, monkeypatch):
        monkeypatch.setenv(eng.IMPL_ENV, forced)
        assert eng.resolve_impl(1024, 1024, 64, 256) == (forced, None)

    def test_forced_pallas_on_cpu_degrades_to_fused(self, monkeypatch):
        monkeypatch.setenv(eng.IMPL_ENV, "pallas")
        assert eng.resolve_impl(1024, 1024, 64, 256) == ("fused", None)

    def test_forced_pallas_engages_under_interpret(self):
        sel, bt = eng.resolve_impl(H, T, B, F, impl="pallas", interpret=True)
        assert sel == "pallas" and bt in eng.BLOCK_CHOICES

    def test_auto_thresholds_hidden_and_seq(self, monkeypatch):
        monkeypatch.setenv("DL4J_LSTM_PALLAS_MIN_HIDDEN", "8")
        monkeypatch.setenv("DL4J_LSTM_PALLAS_MIN_SEQ", "8")
        sel, bt = eng.resolve_impl(8, 16, 2, 4, impl="auto", interpret=True)
        assert sel == "pallas" and bt is not None
        assert eng.resolve_impl(4, 16, 2, 4, impl="auto",
                                interpret=True)[0] == "fused"  # hidden below
        assert eng.resolve_impl(8, 4, 2, 4, impl="auto",
                                interpret=True)[0] == "fused"  # seq below

    def test_block_autotune_prefers_least_padding(self):
        # T=16: blocks 16 and 8 pad nothing, 32 pads 16 -> largest no-pad
        # block wins
        assert eng.resolve_impl(H, 16, B, F, impl="pallas",
                                interpret=True)[1] == 16
        # T=64: all divide; largest block wins
        assert eng.resolve_impl(H, 64, B, F, impl="pallas",
                                interpret=True)[1] == 32

    def test_block_env_override(self, monkeypatch):
        monkeypatch.setenv("DL4J_LSTM_BLOCK", "16")
        assert eng.resolve_impl(H, 64, B, F, impl="pallas",
                                interpret=True)[1] == 16

    def test_vmem_budget_rules_out_pallas(self, monkeypatch):
        """The (hidden, seq, batch)-keyed feasibility half of the gate:
        hidden=1024 f32 puts W+dW alone at ~67MB, over any real budget."""
        monkeypatch.setenv("DL4J_LSTM_VMEM_BUDGET", str(1024))
        assert eng.resolve_impl(8, 16, 2, 4, impl="pallas",
                                interpret=True) == ("fused", None)

    def test_nonstandard_activation_rules_out_pallas(self):
        assert eng.resolve_impl(H, 16, B, F, impl="pallas", interpret=True,
                                act_name="relu") == ("fused", None)
        assert eng.resolve_impl(H, 16, B, F, impl="pallas", interpret=True,
                                gate_name="hardsigmoid") == ("fused", None)

    def test_unknown_impl_raises(self):
        with pytest.raises(ValueError):
            eng.resolve_impl(H, T, B, F, impl="cudnn")

    def test_dispatch_counter_increments(self):
        from deeplearning4j_tpu.observability.metrics import global_registry
        p = _params(False)
        x, _ = _inputs(masked=False)
        _run("fused", p, x, None, False)
        text = global_registry().prometheus_text()
        assert 'dl4j_lstm_dispatch_total{impl="fused",requested="fused"}' \
            in text
