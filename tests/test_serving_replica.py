"""Sharded multi-replica serving: the ISSUE-12 acceptance set.

Pinned contracts:
- a ``sharding="dp_tp"`` PredictFn on the 8-device virtual mesh is
  **bitwise-identical** to the single-device program at every batch size,
  including batches the data axis doesn't divide (gather-at-use: the params
  shard at rest, the compute keeps the single-device reduction order);
- the per-device resident bytes really drop (shard check on the weight
  buffers) and the ``dl4j_sharded_param_bytes_per_device`` gauge agrees
  with ``partition.per_device_bytes``;
- int8 quantization composes with sharding (the codes shard);
- multi-input ComputationGraphs serve through PredictFn AND the
  MicroBatcher (per-position concat/pad, one group per input signature);
- a rolling hot swap across 3 replicas loses zero in-flight requests;
- the least-queue-depth router shifts traffic off a slow replica;
- the HTTP front door exposes per-replica status and metrics.
"""
import json
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from deeplearning4j_tpu.keras_server import (
    MicroBatcher, ModelRegistry, ReplicaSet,
)
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization, DenseLayer, OutputLayer,
)
from deeplearning4j_tpu.nn.graph_network import ComputationGraph
from deeplearning4j_tpu.nn.inference import make_predict_fn
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.observability import names as _n
from deeplearning4j_tpu.observability.metrics import global_registry
from deeplearning4j_tpu.parallel import partition
from deeplearning4j_tpu.parallel.mesh import build_mesh

N_IN, N_OUT = 16, 4


def _mlp(seed=7):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(0.1).updater("adam")
            .weight_init("xavier")
            .list()
            .layer(DenseLayer(n_in=N_IN, n_out=32, activation="relu"))
            .layer(BatchNormalization(n_in=32))
            .layer(OutputLayer(n_in=32, n_out=N_OUT, loss="mcxent",
                               activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def _two_input_graph(seed=5):
    from deeplearning4j_tpu.nn.conf.vertices import MergeVertex
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(0.1).updater("adam")
            .weight_init("xavier")
            .graph_builder()
            .add_inputs("a", "b")
            .add_layer("da", DenseLayer(n_in=4, n_out=6, activation="tanh"),
                       "a")
            .add_layer("db", DenseLayer(n_in=3, n_out=6, activation="tanh"),
                       "b")
            .add_vertex("merged", MergeVertex(), "da", "db")
            .add_layer("out", OutputLayer(n_in=12, n_out=2, loss="mse",
                                          activation="identity"), "merged")
            .set_outputs("out")
            .build())
    return ComputationGraph(conf).init()


def _sharded_gauge():
    snap = global_registry().snapshot()
    series = snap[_n.SHARDED_PARAM_BYTES_PER_DEVICE]["series"]
    return {s["labels"]["rule_set"]: s["value"] for s in series}


# ------------------------------------------------------- sharded PredictFn

def test_sharded_predict_bitwise_and_per_device_bytes():
    net = _mlp()
    mesh = build_mesh({"data": 4, "model": 2})
    ref = make_predict_fn(net)
    pf = make_predict_fn(net, sharding="dp_tp", mesh=mesh)
    rng = np.random.default_rng(0)
    # batch sizes the data axis divides AND ones it doesn't (3, 1): the
    # odd tails dispatch replicated via partition.batch_spec
    for n in (1, 2, 3, 4, 8, 32):
        x = rng.normal(size=(n, N_IN)).astype(np.float32)
        a, b = np.asarray(ref(x)), np.asarray(pf(x))
        assert a.shape == (n, N_OUT)
        assert np.array_equal(a, b), f"sharded output drifted at batch {n}"
    # the params really live split: the 16x32 weight holds half its bytes
    # per device on the model=2 axis
    import jax
    leaves = [leaf for leaf in jax.tree_util.tree_leaves(
        pf.params_snapshot()) if leaf.nbytes == N_IN * 32 * 4]
    assert leaves, "expected the 16x32 f32 dense kernel in the snapshot"
    w = leaves[0]
    assert w.addressable_shards[0].data.nbytes * 2 == w.nbytes
    # per-device accounting: property == partition math == recorded gauge
    per_dev = pf.per_device_param_bytes
    assert per_dev is not None and per_dev < pf.param_bytes
    assert per_dev == partition.per_device_bytes(
        pf.params_snapshot(), pf.param_specs, mesh)
    assert _sharded_gauge()["dp_tp"] == per_dev
    assert ref.per_device_param_bytes is None


def test_batch_spec_odd_tail_replicates():
    mesh = build_mesh({"data": 4, "model": 2})
    assert partition.batch_spec(mesh, 8) == partition.pspec("data")
    assert partition.batch_spec(mesh, 4) == partition.pspec("data")
    # not divisible by the data factor -> replicated, never an error
    assert partition.batch_spec(mesh, 3) == partition.pspec()
    assert partition.batch_spec(mesh, 1) == partition.pspec()


def test_sharded_int8_composes_bitwise():
    # wide enough that the dense kernels clear ops.quant.MIN_QUANT_ELEMS
    conf = (NeuralNetConfiguration.builder()
            .seed(11).learning_rate(0.1).updater("adam")
            .weight_init("xavier")
            .list()
            .layer(DenseLayer(n_in=N_IN, n_out=64, activation="relu"))
            .layer(DenseLayer(n_in=64, n_out=64, activation="relu"))
            .layer(OutputLayer(n_in=64, n_out=N_OUT, loss="mcxent",
                               activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()
    mesh = build_mesh({"data": 4, "model": 2})
    q_ref = make_predict_fn(net, quant="int8")
    q_pf = make_predict_fn(net, quant="int8", sharding="dp_tp", mesh=mesh)
    assert q_pf.name.endswith("+int8")
    rng = np.random.default_rng(1)
    for n in (2, 8):
        x = rng.normal(size=(n, N_IN)).astype(np.float32)
        assert np.array_equal(np.asarray(q_ref(x)), np.asarray(q_pf(x)))
    # int8 codes shard too: the quantized pin stays below the f32 pin
    assert q_pf.param_bytes < make_predict_fn(net).param_bytes


def test_predictfn_placement_validation():
    net = _mlp()
    mesh = build_mesh({"data": 4, "model": 2})
    with pytest.raises(ValueError, match="mesh"):
        make_predict_fn(net, sharding="dp_tp")
    import jax
    with pytest.raises(ValueError, match="not both"):
        make_predict_fn(net, sharding="dp_tp", mesh=mesh,
                        device=jax.devices()[0])


# ----------------------------------------------------- multi-input serving

def test_multi_input_graph_through_predictfn_and_batcher():
    net = _two_input_graph()
    rng = np.random.default_rng(3)
    a = rng.normal(size=(3, 4)).astype(np.float32)
    b = rng.normal(size=(3, 3)).astype(np.float32)
    want = np.asarray(net.output(a, b)[0])

    pf = make_predict_fn(net)
    assert pf.n_inputs == 2
    assert np.array_equal(np.asarray(pf(a, b)), want)
    with pytest.raises(ValueError, match="2 input"):
        pf(a)

    registry = ModelRegistry()
    registry.register("g", net, version="v1")
    batcher = MicroBatcher(registry, max_batch=8, max_latency_s=0.002)
    try:
        futs = [batcher.submit("g", [a[i:i + 1], b[i:i + 1]])
                for i in range(3)]
        for i, f in enumerate(futs):
            res = f.result(timeout=30)
            assert np.allclose(np.asarray(res["predictions"]),
                               want[i:i + 1], atol=1e-6)
        # mismatched leading dims are an input error, not a dispatch crash
        with pytest.raises(ValueError):
            batcher.submit("g", [a, b[:2]])
    finally:
        batcher.close()


# ----------------------------------------------------- replica set + router

def test_replica_set_sharded_placement_disjoint():
    import jax
    rs = ReplicaSet(4, sharding="dp_tp", max_latency_s=0.001)
    try:
        assert rs.n_replicas == 4
        seen = []
        for r in rs.replicas:
            devs = r.devices()
            assert len(devs) == 2  # 8 virtual devices / 4 replicas
            seen.extend(devs)
        assert len(seen) == len(set(seen)) == len(jax.devices())
        rs.register("m", _mlp(), version="v1")
        x = np.zeros((2, N_IN), np.float32)
        res = rs.submit("m", x).result(timeout=60)
        assert res["version"] == "v1" and res["replica"] in range(4)
    finally:
        rs.close()


def test_rolling_hot_swap_three_replicas_zero_loss():
    rs = ReplicaSet(3, max_latency_s=0.001, drain_timeout_s=30.0)
    try:
        rs.register("m", _mlp(seed=1), version="v1")
        x = np.zeros((1, N_IN), np.float32)
        results, errors = [], []
        done = threading.Event()

        def client():
            got = []
            while not (done.is_set() and len(got) >= 100):
                try:
                    got.append(rs.submit("m", x).result(timeout=60))
                except Exception as e:  # any loss fails the test
                    errors.append(e)
                    break
                time.sleep(0.0005)
            results.extend(got)

        threads = [threading.Thread(target=client) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.05)  # let v1 traffic establish
        rs.register("m", _mlp(seed=2), version="v2")
        done.set()
        for t in threads:
            t.join(timeout=120)
        assert not errors, f"requests lost during the roll: {errors[:3]}"
        assert len(results) >= 300
        versions = {r["version"] for r in results}
        assert versions <= {"v1", "v2"} and "v1" in versions \
            and "v2" in versions
        # every replica ends on v2 (the roll visited the whole fleet)
        for r in rs.replicas:
            assert r.registry.active("m").version == "v2"
            assert not r.draining
        # the active-version gauge flipped series: v1 -> 0, v2 -> 1
        snap = global_registry().snapshot()
        series = snap[_n.SERVE_REPLICA_ACTIVE_VERSION]["series"]
        active = {(s["labels"]["replica"], s["labels"]["version"]):
                  s["value"] for s in series
                  if s["labels"]["model"] == "m"}
        for i in range(3):
            assert active[(str(i), "v1")] == 0
            assert active[(str(i), "v2")] == 1
        # versions are immutable at set level
        with pytest.raises(ValueError, match="immutable"):
            rs.register("m", _mlp(), version="v2")
    finally:
        rs.close()


def test_router_prefers_shorter_queue_under_slow_replica():
    rs = ReplicaSet(2, max_batch=1, max_latency_s=0.0)
    try:
        rs.register("m", _mlp(), version="v1")
        x = np.zeros((1, N_IN), np.float32)
        # warm both replicas' bucket-1 programs so compile time doesn't
        # masquerade as queue depth
        for r in rs.replicas:
            r.batcher.submit("m", x).result(timeout=60)
        # wedge replica 0: every dispatch sleeps, so its queue stays deep
        mv0 = rs.replicas[0].registry.active("m")
        real = mv0.predict_fn

        def slow(*xs):
            time.sleep(0.05)
            return real(*xs)

        mv0.predict_fn = slow
        # paced offered load: the fast replica drains between arrivals, so
        # queue depth — the router's signal — tracks service rate, and the
        # wedged replica's depth pins at 1 while it sleeps
        futs = []
        for _ in range(40):
            futs.append(rs.submit("m", x))
            time.sleep(0.002)
        by_replica = {0: 0, 1: 0}
        for f in futs:
            by_replica[f.result(timeout=60)["replica"]] += 1
        assert by_replica[1] > by_replica[0], by_replica
        st = rs.stats()
        routed = {r["replica"]: r["routed"] for r in st["replicas"]}
        assert routed[1] > routed[0]
    finally:
        rs.close()


# ------------------------------------------------------------ HTTP + names

def test_http_replica_mode_status_and_metrics():
    import http.client

    from deeplearning4j_tpu.keras_server import InferenceServer

    srv = InferenceServer(replicas=2, max_batch=8, max_latency_s=0.002,
                          max_queue=64)
    srv.register("mlp", _mlp(), version="v1")
    srv.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)
        x = np.zeros((2, N_IN), np.float32)
        conn.request("POST", "/v1/predict",
                     body=json.dumps({"model": "mlp",
                                      "inputs": x.tolist()}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 200
        assert body["version"] == "v1" and body["replica"] in (0, 1)
        assert np.asarray(body["predictions"]).shape == (2, N_OUT)

        conn.request("GET", "/serve/status")
        st = json.loads(conn.getresponse().read())
        assert st["replicas"]["n_replicas"] == 2
        assert len(st["replicas"]["replicas"]) == 2
        assert st["queue"]["replicas"] == 2 and "queue_depth" in st["queue"]
        for rep in st["replicas"]["replicas"]:
            assert rep["active"] == {"mlp": "v1"}

        conn.request("GET", "/metrics")
        text = conn.getresponse().read().decode()
        conn.close()
        assert _n.SERVE_REPLICA_QUEUE_DEPTH in text
        assert _n.SERVE_REPLICA_ACTIVE_VERSION in text
        assert _n.SERVE_REPLICA_ROUTED_TOTAL in text
    finally:
        srv.stop()


def test_replica_mode_refuses_external_registry():
    from deeplearning4j_tpu.keras_server import InferenceServer

    with pytest.raises(ValueError, match="replica mode"):
        InferenceServer(ModelRegistry(), replicas=2)


def test_new_metric_names_registered():
    for name in (_n.SERVE_REPLICA_QUEUE_DEPTH, _n.SERVE_REPLICA_OCCUPANCY,
                 _n.SERVE_REPLICA_ACTIVE_VERSION,
                 _n.SERVE_REPLICA_ROUTED_TOTAL):
        assert name in _n.ALL_METRIC_NAMES
        assert name.startswith("dl4j_serve_replica_")


def test_cli_serve_parser():
    from deeplearning4j_tpu.cli import build_parser

    args = build_parser().parse_args(
        ["serve", "--model", "m.zip", "--replicas", "4",
         "--sharding", "dp_tp", "--quant", "int8", "--port", "0"])
    assert args.replicas == 4 and args.sharding == "dp_tp"
    assert args.quant == "int8" and args.max_batch == 32
    assert args.name == "default" and args.max_latency_ms == 2.0


def test_fleet_reads_race_free_under_churn():
    """Regression: n_replicas and primary_registry read _replicas bare
    while remove_replica rebinds the list under _lock. Readers could see
    a mid-rebind list (or index an empty snapshot during construction of
    the rebound one). Hammer both read paths while the fleet churns; the
    primary (index 0) is never removable, so primary_registry must stay
    valid through every mutation."""
    rs = ReplicaSet(2, max_batch=4, max_latency_s=0.001, max_queue=8)
    stop = threading.Event()
    errors = []

    def reader():
        try:
            while not stop.is_set():
                n = rs.n_replicas
                assert n >= 1
                assert rs.primary_registry is rs.replicas[0].registry
        except Exception as e:  # pragma: no cover - the regression itself
            errors.append(e)

    readers = [threading.Thread(target=reader) for _ in range(4)]
    try:
        for t in readers:
            t.start()
        # churn: grow to 4, shrink back to 2, five times over. The empty
        # catalog keeps add_replica cheap (no programs to warm).
        for _ in range(5):
            rs.add_replica(reason="t-churn")
            rs.add_replica(reason="t-churn")
            assert rs.remove_replica(reason="t-churn") is True
            assert rs.remove_replica(reason="t-churn") is True
    finally:
        stop.set()
        for t in readers:
            t.join()
        rs.close()
    assert errors == []
    assert rs.n_replicas == 2
