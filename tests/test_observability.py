"""Telemetry subsystem tests: MetricsRegistry semantics, Prometheus
exposition, CompileTracker compile/retrace accounting, the dtype-policy
recompile-storm acceptance path, TelemetryListener end-to-end, and the
/metrics + /train/telemetry/data UI endpoints."""
import json
import logging
import re
import threading
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deeplearning4j_tpu.common as C
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.observability import (
    CompileTracker, MetricsRegistry, TelemetryListener, global_registry,
    global_tracker, record_hbm_gauges, span, tree_nbytes,
)
from deeplearning4j_tpu.observability import compile_tracker as ct_mod
from deeplearning4j_tpu.ui import UIServer


def _small_net():
    conf = (NeuralNetConfiguration.builder()
            .seed(0).learning_rate(0.1)
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                               activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def _xy(n=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.zeros((n, 3), np.float32)
    y[np.arange(n), rng.integers(0, 3, n)] = 1
    return x, y


# --------------------------------------------------------------- registry

def test_counter_semantics():
    reg = MetricsRegistry()
    c = reg.counter("req_total", "requests")
    c.labels(route="/a").inc()
    c.labels(route="/a").inc(2)
    c.labels(route="/b").inc()
    snap = reg.snapshot()["req_total"]
    assert snap["type"] == "counter"
    by_route = {dict(s["labels"])["route"]: s["value"]
                for s in snap["series"]}
    assert by_route == {"/a": 3.0, "/b": 1.0}
    with pytest.raises(ValueError):
        c.labels(route="/a").inc(-1)


def test_gauge_and_histogram_semantics():
    reg = MetricsRegistry()
    g = reg.gauge("temp", "temperature")
    g.set(3.5)
    g.set(-2.0)
    assert reg.snapshot()["temp"]["series"][0]["value"] == -2.0

    h = reg.histogram("lat", "latency", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    s = reg.snapshot()["lat"]["series"][0]
    assert s["count"] == 3
    assert s["sum"] == pytest.approx(5.55)
    # per-bucket (non-cumulative) counts: <=0.1, <=1.0, +Inf overflow
    assert s["bucket_counts"] == [1, 1, 1]


def test_labels_memoized_and_type_conflict():
    reg = MetricsRegistry()
    c = reg.counter("x_total")
    assert c.labels(a="1") is c.labels(a="1")
    assert reg.counter("x_total") is c          # get-or-create
    with pytest.raises(ValueError):
        reg.gauge("x_total")                    # same name, different type


def test_kill_switch_disables_mutation():
    reg = MetricsRegistry()
    c = reg.counter("k_total")
    c.inc()
    reg.set_enabled(False)
    c.inc(100)
    reg.gauge("k_gauge").set(9)
    reg.set_enabled(True)
    c.inc()
    snap = reg.snapshot()
    assert snap["k_total"]["series"][0]["value"] == 2.0
    assert snap["k_gauge"]["series"][0]["value"] == 0.0


def test_concurrent_increments_are_exact():
    reg = MetricsRegistry()
    c = reg.counter("conc_total").labels(t="x")
    h = reg.histogram("conc_hist").labels(t="x")
    n_threads, n_incs = 8, 1000
    barrier = threading.Barrier(n_threads)

    def worker():
        barrier.wait()
        for _ in range(n_incs):
            c.inc()
            h.observe(0.01)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = reg.snapshot()
    assert snap["conc_total"]["series"][0]["value"] == n_threads * n_incs
    assert snap["conc_hist"]["series"][0]["count"] == n_threads * n_incs


_PROM_LINE = re.compile(
    r'^(# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .*'
    r'|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? '
    r'(-?[0-9]+(\.[0-9]+)?([eE][+-]?[0-9]+)?|[+-]?Inf|NaN))$')


def _assert_valid_prometheus(text):
    lines = [ln for ln in text.splitlines() if ln]
    assert lines, "empty exposition"
    for ln in lines:
        assert _PROM_LINE.match(ln), f"invalid Prometheus line: {ln!r}"


def test_prometheus_text_parses():
    reg = MetricsRegistry()
    reg.counter("c_total", "a counter").labels(op="x").inc(2)
    reg.gauge("g_bytes", "a gauge").set(1.5e9)
    h = reg.histogram("h_seconds", "a histogram", buckets=(0.1, 1.0))
    h.labels(phase="fit").observe(0.05)
    h.labels(phase="fit").observe(0.5)
    text = reg.prometheus_text()
    _assert_valid_prometheus(text)
    assert '# TYPE c_total counter' in text
    assert 'c_total{op="x"} 2' in text
    # histogram: cumulative buckets, +Inf last, _sum and _count present
    assert 'h_seconds_bucket{le="0.1",phase="fit"} 1' in text \
        or 'h_seconds_bucket{phase="fit",le="0.1"} 1' in text
    assert '+Inf' in text
    assert "h_seconds_sum" in text and "h_seconds_count" in text


def test_write_jsonl_appends_snapshot(tmp_path):
    reg = MetricsRegistry()
    reg.counter("w_total").inc(4)
    path = tmp_path / "telemetry.jsonl"
    reg.write_jsonl(str(path), source="test")
    reg.write_jsonl(str(path), source="test")
    lines = path.read_text().splitlines()
    assert len(lines) == 2
    rec = json.loads(lines[0])
    assert rec["source"] == "test" and "ts" in rec
    assert rec["metrics"]["w_total"]["series"][0]["value"] == 4.0


def test_tree_nbytes():
    tree = {"w": np.zeros((4, 8), np.float32), "b": np.zeros((8,), np.float32)}
    assert tree_nbytes(tree) == 4 * 8 * 4 + 8 * 4
    # works on abstract/traced values too (shape+dtype only)
    assert tree_nbytes(jax.ShapeDtypeStruct((2, 2), jnp.bfloat16)) == 8


def test_span_records_histogram():
    reg = MetricsRegistry()
    with span("epoch/0/stage", registry=reg):
        pass
    s = reg.snapshot()["dl4j_span_seconds"]["series"][0]
    assert dict(s["labels"]) == {"name": "epoch/0/stage"}
    assert s["count"] == 1 and s["sum"] >= 0.0


# --------------------------------------------------------- compile tracker

def test_compile_tracker_cached_call_and_forced_retrace():
    reg = MetricsRegistry()
    tracker = CompileTracker(registry=reg)

    def f(x):
        return x * 2.0

    tracked = tracker.wrap("test.f", jax.jit(f))
    x4 = np.ones((4,), np.float32)
    tracked(x4)
    tracked(x4)                       # cached re-call: no new compile
    assert len(tracker.snapshot_events()) == 1
    ev = tracker.snapshot_events()[0]
    assert ev["fn"] == "test.f" and ev["wall_s"] > 0.0

    tracked(np.ones((8,), np.float32))  # forced retrace: new shape
    assert len(tracker.snapshot_events()) == 2
    snap = reg.snapshot()
    assert snap["dl4j_jit_compile_total"]["series"][0]["value"] == 2.0
    assert snap["dl4j_jit_compile_seconds"]["series"][0]["count"] == 2


def test_compile_tracker_storm_warning_rate_limited(caplog):
    tracker = CompileTracker(registry=MetricsRegistry(),
                             storm_threshold=3, storm_window_steps=100)
    with caplog.at_level(logging.WARNING,
                         logger="deeplearning4j_tpu.observability"
                                ".compile_tracker"):
        for i in range(6):
            tracker.record_compile("storm.fn", wall_s=0.01)
            tracker.note_step()
    storms = [r for r in caplog.records if "recompile storm" in r.message]
    assert len(storms) == 1          # rate-limited: one warning per window
    snap = tracker.registry.snapshot()
    assert snap["dl4j_recompile_storm_warnings_total"]["series"][0]["value"] \
        == 1.0


@pytest.fixture
def _restore_policy():
    yield
    C.set_policy(jnp.float32, jnp.float32, jnp.float32,
                 reduction_dtype=None, grad_accum_dtype=None)


def test_policy_flip_counts_new_compile_and_trips_storm(
        monkeypatch, caplog, _restore_policy):
    """Acceptance: a deliberate dtype-policy flip mid-run is counted as a
    fresh compile of the same step function and trips the storm warning."""
    fresh = CompileTracker(registry=MetricsRegistry(),
                           storm_threshold=2, storm_window_steps=50)
    monkeypatch.setattr(ct_mod, "_GLOBAL", fresh)

    net = _small_net()
    x, y = _xy()
    with caplog.at_level(logging.WARNING,
                         logger="deeplearning4j_tpu.observability"
                                ".compile_tracker"):
        net.fit(x, y)
        events_before = [e for e in fresh.snapshot_events()
                         if "train_step" in e["fn"]]
        assert len(events_before) == 1
        C.set_policy(jnp.bfloat16, jnp.float32, jnp.float32)
        net.fit(x, y)
    events_after = [e for e in fresh.snapshot_events()
                    if "train_step" in e["fn"]]
    assert len(events_after) == 2     # policy flip re-keyed -> new compile
    assert events_after[0]["policy"] != events_after[1]["policy"]
    assert any("recompile storm" in r.message for r in caplog.records)
    snap = fresh.registry.snapshot()
    assert snap["dl4j_recompile_storm_warnings_total"]["series"][0]["value"] \
        >= 1.0


# ------------------------------------------------- listener + endpoints

@pytest.fixture(scope="module")
def telemetry_run():
    """One instrumented training run feeding the process-global registry:
    2-layer net + TelemetryListener, 5 iterations."""
    net = _small_net()
    listener = TelemetryListener(sync_every=1, hbm_every=1,
                                 worker_id="obs_test")
    net.set_listeners(listener)
    x, y = _xy()
    for _ in range(5):
        net.fit(x, y)
    return net


def test_telemetry_listener_acceptance(telemetry_run):
    snap = global_registry().snapshot()
    # >= 1 compile event with a wall time
    total = sum(s["value"]
                for s in snap["dl4j_jit_compile_total"]["series"])
    assert total >= 1
    assert any(s["count"] >= 1 and s["sum"] > 0.0
               for s in snap["dl4j_jit_compile_seconds"]["series"])
    assert any(e["wall_s"] > 0.0 for e in global_tracker().snapshot_events())
    # per-step host-time histogram
    hosts = [s for s in snap["dl4j_step_host_seconds"]["series"]
             if dict(s["labels"])["worker"] == "obs_test"]
    assert hosts and hosts[0]["count"] >= 4     # 5 iters -> >= 4 deltas
    # device sync time sampled from the trusted float(loss) point
    syncs = [s for s in snap["dl4j_step_device_sync_seconds"]["series"]
             if dict(s["labels"])["worker"] == "obs_test"]
    assert syncs and syncs[0]["count"] >= 1
    # HBM gauge exists per local device (0.0 on CPU: memory_stats is None)
    assert len(snap["dl4j_device_hbm_bytes"]["series"]) \
        == len(jax.local_devices())
    # fit-phase attribution populated by the instrumented fit loop
    phases = {dict(s["labels"])["phase"]
              for s in snap["dl4j_fit_phase_seconds"]["series"]}
    assert {"staging", "dispatch", "listeners"} <= phases


def test_record_hbm_gauges_direct():
    record_hbm_gauges(global_registry())
    series = global_registry().snapshot()["dl4j_device_hbm_bytes"]["series"]
    assert all(s["value"] >= 0.0 for s in series)


def test_metrics_endpoint_serves_prometheus(telemetry_run):
    server = UIServer(port=0)
    try:
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(base + "/metrics") as r:
            assert r.status == 200
            assert r.headers.get("Content-Type", "").startswith("text/plain")
            text = r.read().decode()
        _assert_valid_prometheus(text)
        for series in ("dl4j_jit_compile_total",
                       "dl4j_step_host_seconds_bucket",
                       "dl4j_device_hbm_bytes",
                       "dl4j_fit_phase_seconds_sum"):
            assert series in text, f"missing {series} in /metrics"
    finally:
        server.stop()


def test_telemetry_data_endpoint(telemetry_run):
    server = UIServer(port=0)
    try:
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(base + "/train/telemetry/data") as r:
            assert r.status == 200
            data = json.loads(r.read())
    finally:
        server.stop()
    assert "dl4j_jit_compile_total" in data["metrics"]
    assert isinstance(data["compile_events"], list) and data["compile_events"]
    assert isinstance(data["step"], int) and data["step"] >= 5


def test_telemetry_listener_snapshot_path(tmp_path):
    from deeplearning4j_tpu.datasets.dataset import DataSet

    net = _small_net()
    out = tmp_path / "epochs.jsonl"
    net.set_listeners(TelemetryListener(sync_every=1, hbm_every=1,
                                        snapshot_path=str(out),
                                        worker_id="snap_test"))
    x, y = _xy(8, seed=1)
    net.fit_iterator([DataSet(x, y)], epochs=2)   # epoch hooks fire here
    lines = out.read_text().splitlines()
    assert len(lines) == 2
    rec = json.loads(lines[0])
    assert rec["source"] == "TelemetryListener"
    assert "dl4j_step_host_seconds" in rec["metrics"]
