"""Serving-engine semantics: non-donation, bucketed batching, backpressure.

The contracts pinned here are the ISSUE-9 acceptance set:
- the inference dispatch path never donates inputs or params (100 served
  requests leave every parameter buffer bit-identical);
- batched-and-padded output bitwise-equals per-request output across
  bucket boundaries (batch 1, boundary, boundary+1);
- the compile cache stays bounded under 1k mixed-shape requests
  (CompileTracker event count == bucket count);
- admission overflow rejects AND the queue-depth gauge agrees;
- hot-swapping the active version mid-flight loses zero requests.
"""
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.keras_server import (
    AdmissionController, MicroBatcher, ModelRegistry, RejectedError,
    batch_bucket)
from deeplearning4j_tpu.keras_server.streaming import StreamSessions
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization, DenseLayer, GravesLSTM, OutputLayer, RnnOutputLayer,
)
from deeplearning4j_tpu.nn.inference import (
    PREDICT_PROGRAM_NAME, make_predict_fn,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.observability.compile_tracker import global_tracker
from deeplearning4j_tpu.observability.metrics import MetricsRegistry
from deeplearning4j_tpu.observability import names as _n

N_IN, N_OUT = 16, 4


def _mlp(seed=7):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(0.1).updater("adam")
            .weight_init("xavier")
            .list()
            .layer(DenseLayer(n_in=N_IN, n_out=32, activation="relu"))
            .layer(BatchNormalization(n_in=32))
            .layer(OutputLayer(n_in=32, n_out=N_OUT, loss="mcxent",
                               activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def _lstm(seed=3):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(0.1).updater("adam")
            .weight_init("xavier")
            .list()
            .layer(GravesLSTM(n_in=5, n_out=8, activation="tanh"))
            .layer(RnnOutputLayer(n_in=8, n_out=2, loss="mcxent",
                                  activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def _params_bytes(tree) -> bytes:
    import jax
    return b"".join(np.asarray(leaf).tobytes()
                    for leaf in jax.tree_util.tree_leaves(tree))


def _serve_compiles() -> int:
    return sum(1 for e in global_tracker().snapshot_events()
               if PREDICT_PROGRAM_NAME in e.get("fn", ""))


def _x(rng, n):
    return rng.normal(size=(n, N_IN)).astype(np.float32)


# --------------------------------------------------------------- bucketing
def test_batch_bucket_powers_of_two():
    assert [batch_bucket(n, 8) for n in (1, 2, 3, 4, 5, 7, 8, 9, 100)] \
        == [1, 2, 4, 4, 8, 8, 8, 8, 8]
    assert batch_bucket(1, 1) == 1


# ------------------------------------------------------------ non-donation
def test_serving_100_requests_params_bit_identical():
    """Satellite 2: the serving dispatch never donates params or inputs."""
    net = _mlp()
    registry = ModelRegistry()
    mv = registry.register("m", net, version="v1")
    before_pinned = _params_bytes(mv.predict_fn.params_snapshot())
    before_source = _params_bytes(net.params_list)
    batcher = MicroBatcher(registry, max_batch=8, max_latency_s=0.001)
    try:
        rng = np.random.default_rng(0)
        futs = [batcher.submit("m", _x(rng, 1 + i % 4)) for i in range(100)]
        outs = [f.result(timeout=30) for f in futs]
    finally:
        batcher.close()
    assert len(outs) == 100
    assert all(o["version"] == "v1" for o in outs)
    assert _params_bytes(mv.predict_fn.params_snapshot()) == before_pinned
    assert _params_bytes(net.params_list) == before_source


def test_predict_fn_isolated_from_training_donation():
    """fit() after pinning must not corrupt the serving snapshot."""
    net = _mlp()
    pf = make_predict_fn(net)
    rng = np.random.default_rng(1)
    x = _x(rng, 4)
    before = np.asarray(pf(x))
    pinned = _params_bytes(pf.params_snapshot())
    y = np.eye(N_OUT, dtype=np.float32)[rng.integers(0, N_OUT, 4)]
    for _ in range(3):
        net.fit(x, y)  # donates the NET's buffers, not the snapshot
    assert _params_bytes(pf.params_snapshot()) == pinned
    assert np.array_equal(np.asarray(pf(x)), before)


# ------------------------------------------------- bitwise batch semantics
def test_batched_padded_output_bitwise_equals_per_request():
    """Across bucket boundaries: coalesced+padded == served alone."""
    net = _mlp()
    registry = ModelRegistry()
    mv = registry.register("m", net, version="v1")
    rng = np.random.default_rng(2)
    boundary = 4  # max_batch=4: buckets 1,2,4
    for k in (1, boundary, boundary + 1):
        xs = [_x(rng, 1) for _ in range(k)]
        refs = [np.asarray(mv.predict_fn(x)) for x in xs]  # per-request
        batcher = MicroBatcher(registry, max_batch=boundary,
                               max_latency_s=0.25)
        try:
            futs = [batcher.submit("m", x) for x in xs]
            outs = [f.result(timeout=30) for f in futs]
        finally:
            batcher.close()
        if k > 1:
            # the high max_latency guarantees the first `boundary` requests
            # coalesced into one padded dispatch — the property under test
            assert max(o["batch_rows"] for o in outs) > 1
        for o, ref in zip(outs, refs):
            assert np.array_equal(np.asarray(o["predictions"]), ref), \
                f"bitwise mismatch at k={k}"


# ------------------------------------------------------ bounded compile cache
def test_compile_cache_bounded_under_1k_mixed_shape_requests():
    net = _mlp(seed=11)
    registry = ModelRegistry()
    registry.register("m", net, version="v1")
    batcher = MicroBatcher(registry, max_batch=8, max_latency_s=0.0005,
                           max_queue=2000)
    compiles_before = _serve_compiles()
    try:
        rng = np.random.default_rng(3)
        futs = [batcher.submit("m", _x(rng, int(rng.integers(1, 9))))
                for _ in range(1000)]
        for f in futs:
            f.result(timeout=60)
        stats = batcher.stats()
    finally:
        batcher.close()
    compiles = _serve_compiles() - compiles_before
    # the pinned bound: one compile per padded bucket, nothing else — with
    # max_batch=8 the buckets are {1,2,4,8}, so at most 4 compiles for 1000
    # mixed-shape requests, and every compile is a bucket actually used
    assert compiles == stats["bucket_count"], stats
    assert compiles <= 4, f"{compiles} compiles for 1000 requests"


# ------------------------------------------------------------- backpressure
def test_backpressure_rejects_and_queue_depth_gauge_agrees():
    net = _mlp(seed=5)
    registry = ModelRegistry()
    mv = registry.register("m", net, version="v1")
    release = threading.Event()
    real_pf = mv.predict_fn

    class _Blocking:
        calls = 0

        def __call__(self, x):
            release.wait(timeout=30)
            return real_pf(x)

    mv.predict_fn = _Blocking()
    metrics = MetricsRegistry()
    admission = AdmissionController(max_pending=4, metrics=metrics)
    batcher = MicroBatcher(registry, max_batch=1, max_latency_s=0.0,
                           admission=admission, metrics=metrics)
    try:
        rng = np.random.default_rng(4)
        futs = [batcher.submit("m", _x(rng, 1)) for _ in range(4)]
        with pytest.raises(RejectedError) as exc:
            batcher.submit("m", _x(rng, 1))
        assert exc.value.pending == 4
        assert exc.value.limit == 4
        assert exc.value.retry_after_s > 0

        def _gauge():
            snap = metrics.snapshot()[_n.SERVE_QUEUE_DEPTH]
            return snap["series"][0]["value"]

        # the gauge must agree with what the 429 claimed
        assert _gauge() == 4
        assert admission.pending == 4
        release.set()
        for f in futs:
            f.result(timeout=30)
        deadline = time.time() + 10
        while admission.pending and time.time() < deadline:
            time.sleep(0.01)
        assert _gauge() == 0
        snap = metrics.snapshot()[_n.SERVE_REJECTED_TOTAL]
        assert snap["series"][0]["value"] == 1
    finally:
        release.set()
        batcher.close()


# ----------------------------------------------------------------- hot swap
def test_hot_swap_mid_flight_loses_zero_requests():
    registry = ModelRegistry()
    registry.register("m", _mlp(seed=21), version="v1")
    batcher = MicroBatcher(registry, max_batch=8, max_latency_s=0.001,
                           max_queue=512)
    results, errors = [], []
    lock = threading.Lock()

    def client(seed):
        rng = np.random.default_rng(seed)
        for _ in range(50):
            try:
                fut = batcher.submit("m", _x(rng, 1))
                out = fut.result(timeout=30)
                with lock:
                    results.append(out["version"])
            except Exception as e:  # any loss/failure fails the test
                with lock:
                    errors.append(repr(e))
            time.sleep(0.001)
    try:
        threads = [threading.Thread(target=client, args=(s,))
                   for s in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.04)  # mid-flight
        registry.register("m", _mlp(seed=22), version="v2")
        for t in threads:
            t.join()
    finally:
        batcher.close()
    assert not errors, errors
    assert len(results) == 200
    assert "v2" in set(results)  # the swap actually took effect mid-run
    assert registry.active("m").version == "v2"


def test_registry_versioning_and_rollback():
    registry = ModelRegistry()
    registry.register("m", _mlp(seed=31))
    registry.register("m", _mlp(seed=32))
    assert registry.active("m").version == "v2"
    registry.set_active("m", "v1")  # rollback
    assert registry.active("m").version == "v1"
    with pytest.raises(ValueError):
        registry.register("m", _mlp(seed=33), version="v1")
    with pytest.raises(KeyError):
        registry.active("nope")
    st = registry.status()
    assert sorted(st["models"]["m"]["versions"]) == ["v1", "v2"]


# ---------------------------------------------------------------- streaming
def test_streaming_sessions_match_full_sequence():
    net = _lstm()
    registry = ModelRegistry()
    registry.register("rnn", net, version="v1")
    sessions = StreamSessions(registry)
    rng = np.random.default_rng(6)
    seq = rng.normal(size=(1, 6, 5)).astype(np.float32)
    full = np.asarray(net.output(seq))  # [B,T,O]
    streamed = []
    for t in range(6):
        out = sessions.step("rnn", "s1", seq[:, t:t + 1, :])
        streamed.append(out["output"][:, -1, :])
    streamed = np.stack(streamed, axis=1)
    assert np.allclose(streamed, full, atol=1e-5), \
        np.max(np.abs(streamed - full))
    # state is per-session: a fresh session re-starts from zero state
    out2 = sessions.step("rnn", "s2", seq[:, 0:1, :])
    assert np.allclose(out2["output"][:, -1, :], full[:, 0, :], atol=1e-5)
    assert sessions.reset("rnn", "s1")
    assert not sessions.reset("rnn", "s1")


def test_model_serializer_zip_roundtrip_serves():
    import tempfile, os
    from deeplearning4j_tpu.utils.model_serializer import write_model
    net = _mlp(seed=41)
    registry = ModelRegistry()
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model.zip")
        write_model(net, path)
        mv = registry.load("m", path)
    rng = np.random.default_rng(7)
    x = _x(rng, 2)
    assert np.allclose(np.asarray(mv.predict_fn(x)),
                       np.asarray(net.output(x)), atol=1e-6)
    assert mv.source.endswith("model.zip")
