"""Continuous-batching decode engine: the ISSUE-11 acceptance set.

Contracts pinned here:
- a session's token stream is BITWISE identical under continuous and
  static scheduling, across different slot capacities (matmul row
  independence — the same padding property test_serving.py pins);
- the engine's emissions equal the model's own greedy oracle
  (``rnn_time_step`` for the LSTM stack, full-sequence ``output`` for the
  transformer stack), so slot-state threading loses nothing;
- mid-decode admission/eviction loses zero tokens: every session gets
  exactly its budget no matter how slots churn;
- int8 weight-only decode stays inside the documented drift bound
  (ops/quant.py: mean |prob drift| <= 2e-2, >= 90% greedy top-1
  agreement) on BOTH model kinds;
- compile count == capacity bucket count (prompt length and batch
  composition are not compile axes);
- slot-state device bytes do not grow as sessions churn, and the
  StreamSessions TTL/reset eviction releases parked device state (the 1k
  churn regression — the PR's serving-memory fix);
- /v1/generate streams ndjson tokens over the real HTTP stack.
"""
import http.client
import json
import time

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.keras_server import (
    InferenceServer, ModelRegistry, RejectedError,
)
from deeplearning4j_tpu.keras_server.decode import (
    DECODE_PROGRAM_NAME, DecodeEngine,
)
from deeplearning4j_tpu.keras_server.streaming import StreamSessions
from deeplearning4j_tpu.models.char_rnn import char_rnn_lstm
from deeplearning4j_tpu.models.transformer import transformer_lm
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    DenseLayer, GravesLSTM, OutputLayer, RnnOutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.observability.compile_tracker import global_tracker

V = 24


def _lstm_net(seed=11, hidden=32):
    return MultiLayerNetwork(
        char_rnn_lstm(vocab_size=V, hidden=hidden, seed=seed)).init()


def _tf_net(seed=5, width=32):
    return MultiLayerNetwork(
        transformer_lm(vocab_size=V, width=width, n_layers=2, n_heads=2,
                       max_len=64, seed=seed)).init()


def _workload(n, rng=None, lo=2, hi=9):
    rng = rng or np.random.default_rng(0)
    prompts = [list(map(int, rng.integers(0, V,
                                          size=int(rng.integers(1, 5)))))
               for _ in range(n)]
    budgets = [int(rng.integers(lo, hi)) for _ in range(n)]
    return prompts, budgets


def _run(eng, prompts, budgets):
    sessions = [eng.submit(p, b) for p, b in zip(prompts, budgets)]
    for s in sessions:
        s.result(timeout=300)
    return sessions


def _decode_compiles() -> int:
    return sum(1 for e in global_tracker().snapshot_events()
               if DECODE_PROGRAM_NAME in e.get("fn", ""))


# ------------------------------------------------- scheduling equivalence
def test_continuous_vs_static_bitwise_equal():
    """Same sessions, same tokens AND same probability rows bit-for-bit,
    whether slots churn (continuous, growing buckets) or drain in lockstep
    (static, fixed capacity) — scheduling is not allowed to touch math."""
    net = _lstm_net()
    prompts, budgets = _workload(10)
    cont = DecodeEngine(net, min_slots=2, max_slots=8, capture_probs=True)
    stat = DecodeEngine(net, min_slots=4, max_slots=4, mode="static",
                        capture_probs=True)
    try:
        cs = _run(cont, prompts, budgets)
        ss = _run(stat, prompts, budgets)
    finally:
        cont.close()
        stat.close()
    for c, s in zip(cs, ss):
        assert c.tokens == s.tokens
        for cp, sp in zip(c.probs, s.probs):
            assert np.array_equal(cp, sp)


def test_lstm_matches_rnn_time_step_oracle():
    net = _lstm_net(seed=7, hidden=48)
    eng = DecodeEngine(net, min_slots=2, max_slots=4)
    prompt, budget = [3, 9, 1], 6
    try:
        toks = eng.submit(prompt, budget).result(timeout=300)
    finally:
        eng.close()
    net.rnn_clear_previous_state()
    ref = []
    for step in range(len(prompt) + budget - 1):
        t = prompt[step] if step < len(prompt) else ref[-1]
        x = np.zeros((1, 1, V), np.float32)
        x[0, 0, t] = 1
        out = np.asarray(net.rnn_time_step(x))
        if step >= len(prompt) - 1:
            ref.append(int(out[0, -1].argmax()))
    assert toks == ref


def test_transformer_matches_full_sequence_oracle():
    """The slot KV cache + position-masked single-query attention must
    reproduce the full-sequence causal forward exactly."""
    net = _tf_net()
    eng = DecodeEngine(net, min_slots=2, max_slots=4, max_context=32,
                       capture_probs=True)
    prompt, budget = [3, 9, 1], 6
    try:
        sess = eng.submit(prompt, budget)
        toks = sess.result(timeout=300)
    finally:
        eng.close()
    seq, ref = list(prompt), []
    for _ in range(budget):
        x = np.zeros((1, len(seq), V), np.float32)
        for i, t in enumerate(seq):
            x[0, i, t] = 1
        out = np.asarray(net.output(x))
        nxt = int(out[0, -1].argmax())
        ref.append(nxt)
        seq.append(nxt)
    assert toks == ref


# -------------------------------------------------- admission / eviction
def test_mid_decode_admission_eviction_zero_loss():
    """Sessions arrive while others are mid-decode; slots free and refill.
    Every session still gets exactly its budget (no dropped or duplicated
    tokens), evictions are accounted, and more sessions than slots ran."""
    net = _lstm_net()
    rng = np.random.default_rng(3)
    prompts, budgets = _workload(14, rng)
    eng = DecodeEngine(net, min_slots=2, max_slots=2)
    try:
        sessions = []
        for i, (p, b) in enumerate(zip(prompts, budgets)):
            sessions.append(eng.submit(p, b))
            if i % 3 == 0:
                time.sleep(0.01)  # land mid-flight, not as one burst
        for s in sessions:
            s.result(timeout=300)
        st = eng.stats()
    finally:
        eng.close()
    for s, b in zip(sessions, budgets):
        assert len(s.tokens) == b
        assert s.evict_reason == "max_tokens"
        assert len(s.token_times) == b
    assert st["evictions"] == len(sessions) > st["max_slots"]


def test_eos_evicts_early_and_queue_rejects_when_full():
    net = _lstm_net()
    # eos that the greedy argmax actually emits: steal it from a dry run
    probe = DecodeEngine(net, min_slots=1, max_slots=1)
    try:
        toks = probe.submit([3, 9], 4).result(timeout=300)
    finally:
        probe.close()
    eng = DecodeEngine(net, min_slots=1, max_slots=1, eos_id=toks[0],
                       max_queue=1)
    try:
        s = eng.submit([3, 9], 32)
        assert s.result(timeout=300) == toks[:1]
        assert s.evict_reason == "eos"
    finally:
        eng.close()
    eng = DecodeEngine(net, min_slots=1, max_slots=1, max_queue=1)
    try:
        blocker = eng.submit([1], 400)
        deadline = time.monotonic() + 30
        while eng.stats()["queue_depth"] and time.monotonic() < deadline:
            time.sleep(0.002)  # wait until the blocker owns the only slot
        queued = eng.submit([1], 1)
        with pytest.raises(RejectedError):
            eng.submit([1], 1)
        blocker.result(timeout=300)
        queued.result(timeout=300)
    finally:
        eng.close()


# ----------------------------------------------------------- int8 decode
@pytest.mark.parametrize("make_net,kwargs", [
    (_lstm_net, {}),
    (_tf_net, {"max_context": 32}),
], ids=["char_rnn", "transformer"])
def test_int8_decode_within_drift_bound(make_net, kwargs):
    """Weight-only int8 decode vs dense on the same sessions: inside the
    documented bound (ops/quant.py) and ~4x smaller pinned params."""
    net = make_net()
    prompts, budgets = _workload(6)
    q_eng = DecodeEngine(net, min_slots=4, max_slots=4, quant="int8",
                         capture_probs=True, **kwargs)
    d_eng = DecodeEngine(net, min_slots=4, max_slots=4,
                         capture_probs=True, **kwargs)
    try:
        qs = _run(q_eng, prompts, budgets)
        ds = _run(d_eng, prompts, budgets)
        q_bytes = q_eng.stats()["param_bytes"]
        d_bytes = d_eng.stats()["param_bytes"]
    finally:
        q_eng.close()
        d_eng.close()
    agree, drift = [], []
    for q, d in zip(qs, ds):
        n = min(len(q.probs), len(d.probs))
        qp, dp = np.stack(q.probs[:n]), np.stack(d.probs[:n])
        drift.append(float(np.mean(np.abs(qp - dp))))
        agree.append(float(np.mean(qp.argmax(-1) == dp.argmax(-1))))
    assert float(np.mean(drift)) <= 2e-2
    assert float(np.mean(agree)) >= 0.9
    assert d_bytes / q_bytes > 2.5


# ------------------------------------------------------- compile economy
def test_compile_count_equals_bucket_count():
    """Growing 2 -> 4 -> 8 slots under backlog compiles exactly once per
    bucket; prompt length, batch composition and session churn add none."""
    net = _lstm_net()
    prompts, budgets = _workload(24, np.random.default_rng(9))
    before = _decode_compiles()
    eng = DecodeEngine(net, min_slots=2, max_slots=8)
    try:
        _run(eng, prompts, budgets)
        st = eng.stats()
    finally:
        eng.close()
    assert st["bucket_count"] == len(st["buckets"]) >= 2
    assert _decode_compiles() - before == st["bucket_count"]


def test_slot_state_bytes_constant_under_churn():
    """The preallocated slot blocks ARE the decode memory: 30 churned
    sessions at a fixed capacity allocate zero additional state."""
    net = _lstm_net()
    eng = DecodeEngine(net, min_slots=4, max_slots=4)
    try:
        prompts, budgets = _workload(4)
        _run(eng, prompts, budgets)
        baseline = eng.state_bytes()
        prompts, budgets = _workload(30, np.random.default_rng(2))
        _run(eng, prompts, budgets)
        assert eng.state_bytes() == baseline
    finally:
        eng.close()


# ------------------------------------------- streaming TTL churn regression
def _stream_lstm(seed=3):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(0.1).updater("adam")
            .weight_init("xavier")
            .list()
            .layer(GravesLSTM(n_in=5, n_out=8, activation="tanh"))
            .layer(RnnOutputLayer(n_in=8, n_out=2, loss="mcxent",
                                  activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def _live_device_bytes() -> int:
    return sum(a.nbytes for a in jax.live_arrays() if not a.is_deleted())


def test_stream_ttl_eviction_releases_device_state_1k_churn():
    """1000 sessions churned through TTL eviction leave device-resident
    bytes flat: eviction deletes every parked leaf AND un-aliases the
    clone's live ``_rnn_state`` (the most recently stepped session's
    parked tree is that attribute by reference — dropping the dict entry
    alone would keep it resident forever)."""
    registry = ModelRegistry()
    registry.register("rnn", _stream_lstm(), version="v1")
    sessions = StreamSessions(registry, ttl_s=0.0)  # next touch evicts
    x = np.zeros((1, 5), np.float32)
    sessions.step("rnn", "warm", x)  # compile + park once
    baseline = _live_device_bytes()
    for i in range(1000):
        sessions.step("rnn", f"s{i}", x)
    sessions.reset("rnn", "s999")
    grown = _live_device_bytes() - baseline
    # every parked block was released (<= 0: the run ends with ZERO parked
    # sessions, one fewer than the baseline's warm session holds)
    assert grown <= 0, f"device bytes grew by {grown} after 1k sessions"
    assert sessions.status() == {"rnn@v1": []}


def test_stream_reset_deletes_parked_leaves():
    registry = ModelRegistry()
    registry.register("rnn", _stream_lstm(), version="v1")
    sessions = StreamSessions(registry, ttl_s=300.0)
    x = np.zeros((1, 5), np.float32)
    sessions.step("rnn", "a", x)
    sm, _ = sessions._model("rnn")
    parked = sm.states["a"][0]
    leaves = jax.tree_util.tree_leaves(parked)
    assert leaves and not any(l.is_deleted() for l in leaves)
    assert sessions.reset("rnn", "a")
    assert all(l.is_deleted() for l in leaves)
    assert not sessions.reset("rnn", "a")  # idempotent


# -------------------------------------------------------------- HTTP seam
def test_v1_generate_streams_ndjson_tokens():
    registry = ModelRegistry()
    registry.register("char", _lstm_net(), version="v1")
    server = InferenceServer(registry, decode_min_slots=2,
                             decode_max_slots=4).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=60)
        conn.request("POST", "/v1/generate",
                     body=json.dumps({"model": "char", "prompt": [3, 9, 1],
                                      "max_new_tokens": 5}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        lines = [json.loads(l) for l in resp.read().splitlines() if l]
        conn.close()
        final = lines[-1]
        assert final["done"] and final["reason"] == "max_tokens"
        assert len(final["tokens"]) == 5
        streamed = [l["token"] for l in lines if "token" in l]
        assert streamed == final["tokens"]
        assert final["ttft_s"] > 0
        status = server.status()
        assert "char@v1" in status["decode"]
        assert status["decode"]["char@v1"]["tokens"] >= 5
    finally:
        server.stop()


# ------------------------------------------------------------- validation
def test_decode_rejects_unstreamable_stacks():
    mlp = MultiLayerNetwork(
        (NeuralNetConfiguration.builder()
         .seed(1).learning_rate(0.1).updater("adam").weight_init("xavier")
         .list()
         .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
         .layer(OutputLayer(n_in=8, n_out=4, loss="mcxent",
                            activation="softmax"))
         .build())).init()
    with pytest.raises(ValueError, match="time-distributed output head"):
        DecodeEngine(mlp)
    net = _lstm_net()
    eng = DecodeEngine(net, min_slots=1, max_slots=1)
    try:
        with pytest.raises(ValueError, match="outside vocab"):
            eng.submit([V + 3], 2)
        with pytest.raises(ValueError, match="at least one token"):
            eng.submit([], 2)
    finally:
        eng.close()
    with pytest.raises(ValueError, match="mode must be one of"):
        DecodeEngine(net, mode="windowed")
