"""Distributed-training tests (reference dl4j-spark
TestCompareParameterAveragingSparkVsSingleMachine + ParameterServerParallelWrapperTest,
run on the virtual 8-device CPU mesh instead of Spark local[N])."""
import jax
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.param_server import ParameterServerParallelWrapper
from deeplearning4j_tpu.parallel.training_master import (
    DistributedMultiLayer, ParameterAveragingTrainingMaster,
)


def _net(updater="sgd", lr=0.1, seed=12345):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(lr).updater(updater)
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent", activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def _batches(n_batches=16, batch=8, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_batches):
        x = rng.normal(size=(batch, 4)).astype(np.float32)
        labels = (x[:, 0] + x[:, 1] > 0).astype(int)
        y = np.zeros((batch, 3), np.float32)
        y[np.arange(batch), labels] = 1
        out.append(DataSet(x, y))
    return out


def test_param_averaging_freq1_equals_single_machine():
    """With averaging_frequency=1 and plain SGD, training D workers on D
    minibatches then averaging == training one machine on the concatenated
    global batch (the reference's gold-standard equivalence)."""
    D = 4
    data = _batches(n_batches=D, batch=8)

    dist_net = _net("sgd")
    master = (ParameterAveragingTrainingMaster.Builder(D)
              .averaging_frequency(1).build())
    DistributedMultiLayer(dist_net, master).fit(data)

    single_net = _net("sgd")
    gx = np.concatenate([ds.features for ds in data])
    gy = np.concatenate([ds.labels for ds in data])
    single_net.fit(gx, gy)

    for a, b in zip(jax.tree_util.tree_leaves(dist_net.params_list),
                    jax.tree_util.tree_leaves(single_net.params_list)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_param_averaging_multiple_rounds_trains():
    data = _batches(n_batches=32)
    net = _net("adam", lr=0.05)
    master = (ParameterAveragingTrainingMaster.Builder(8)
              .averaging_frequency(2).collect_training_stats(True).build())
    front = DistributedMultiLayer(net, master)
    s0 = net.score(np.concatenate([d.features for d in data]),
                   np.concatenate([d.labels for d in data]))
    front.fit(data, epochs=3)
    s1 = net.score(np.concatenate([d.features for d in data]),
                   np.concatenate([d.labels for d in data]))
    assert s1 < s0 * 0.8, (s0, s1)
    stats = master.get_training_stats()
    assert stats is not None
    assert "WorkerFit" in stats.phases()
    assert "AverageParameters" in stats.phases()


def test_training_stats_html_export(tmp_path):
    data = _batches(n_batches=8)
    net = _net()
    master = (ParameterAveragingTrainingMaster.Builder(4)
              .collect_training_stats(True).build())
    DistributedMultiLayer(net, master).fit(data)
    path = str(tmp_path / "stats.html")
    master.get_training_stats().export_html(path)
    html = open(path).read()
    assert "svg" in html and "WorkerFit" in html


def test_parameter_server_async_trains():
    data = _batches(n_batches=24)
    net = _net("sgd", lr=0.05)
    gx = np.concatenate([d.features for d in data])
    gy = np.concatenate([d.labels for d in data])
    s0 = net.score(gx, gy)
    wrapper = (ParameterServerParallelWrapper.builder(net)
               .workers(2).push_frequency(2).build())
    wrapper.fit(ListDataSetIterator(data), epochs=3)
    s1 = net.score(gx, gy)
    assert s1 < s0 * 0.9, (s0, s1)


def test_distributed_evaluation_matches_single_device():
    import numpy as np
    from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
    from deeplearning4j_tpu.parallel.training_master import (
        DistributedMultiLayer, ParameterAveragingTrainingMaster,
    )

    conf = (NeuralNetConfiguration.builder().seed(5).learning_rate(0.1)
            .list().layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                               activation="softmax")).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 3, 50)  # 50: not divisible by 4 -> pad path
    x = rng.normal(0, 0.3, (50, 4)).astype(np.float32)
    x[np.arange(50), labels] += 2.0
    y = np.eye(3, dtype=np.float32)[labels]
    it = ArrayDataSetIterator(x, y, batch=25, shuffle=False)
    master = ParameterAveragingTrainingMaster.Builder(4).build()
    dist = DistributedMultiLayer(net, master)
    e_dist = dist.evaluate(it)
    e_single = net.evaluate(it)
    assert e_dist.accuracy() == e_single.accuracy()
    np.testing.assert_array_equal(e_dist.confusion.matrix,
                                  e_single.confusion.matrix)


def test_parameter_server_training_hooks():
    """Training-hook SPI fires around every worker update (reference
    dl4j-spark-parameterserver ParameterServerTrainingHook.java)."""
    import threading

    from deeplearning4j_tpu.parallel.param_server import (
        ParameterServerParallelWrapper, ParameterServerTrainingHook)

    class Recorder(ParameterServerTrainingHook):
        def __init__(self):
            self.pre = 0
            self.post = 0
            self._lock = threading.Lock()

        def pre_update(self, dataset, model):
            with self._lock:
                self.pre += 1

        def post_update(self, dataset, model):
            with self._lock:
                self.post += 1

    net = _net()
    hook = Recorder()
    wrapper = (ParameterServerParallelWrapper.builder(net)
               .workers(2).push_frequency(2).training_hooks(hook).build())
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    y = np.zeros((64, 3), np.float32)
    y[np.arange(64), rng.integers(0, 3, 64)] = 1
    from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
    wrapper.fit(ArrayDataSetIterator(x, y, batch=16), epochs=1)
    assert hook.pre == 4  # 64/16 batches
    assert hook.post == 4
