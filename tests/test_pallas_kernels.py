"""Pallas kernels == XLA reference math, in interpret mode on CPU (the
reference's backend-equivalence pattern: CuDNNGradientChecks compares the
accelerated helper path against the built-in path)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops.pallas_kernels import (
    _attention_xla, flash_attention, softmax_cross_entropy,
)
from deeplearning4j_tpu.parallel.ring_attention import attention_reference


def _qkv(B=2, T=128, H=4, D=32, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, T, H, D)).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_reference(causal):
    q, k, v = _qkv()
    expect = attention_reference(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal, True)  # interpret mode
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=2e-4, atol=2e-5)


def test_flash_attention_gradient_flows():
    q, k, v = _qkv(B=1, T=64, H=2, D=16, seed=2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


def test_flash_attention_rejects_ragged_blocks():
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(1, 130, 2, 16)).astype(np.float32))
    from deeplearning4j_tpu.ops.pallas_kernels import _flash_forward

    with pytest.raises(ValueError):
        _flash_forward(q, q, q, False)


def test_softmax_xent_matches_xla():
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(256, 10)).astype(np.float32))
    labels_idx = rng.integers(0, 10, 256)
    labels = jnp.asarray(np.eye(10, dtype=np.float32)[labels_idx])
    loss_p, grad_p = softmax_cross_entropy(logits, labels, interpret=True)
    # XLA reference
    logp = jax.nn.log_softmax(logits, axis=-1)
    loss_x = -jnp.sum(labels * logp, axis=-1)
    grad_x = jnp.exp(logp) - labels
    np.testing.assert_allclose(np.asarray(loss_p), np.asarray(loss_x),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(grad_p), np.asarray(grad_x),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("T", [64, 130])  # 130: not a block multiple
def test_chunked_backward_matches_reference(causal, T):
    q, k, v = _qkv(B=1, T=T, H=2, D=16, seed=3)
    g = jnp.ones_like(q)
    from deeplearning4j_tpu.ops.pallas_kernels import _attention_bwd_chunked
    got = _attention_bwd_chunked(q, k, v, g, causal, blk_q=32)
    _, vjp = jax.vjp(lambda a, b, c: attention_reference(a, b, c, causal),
                     q, k, v)
    expect = vjp(g)
    for a, b in zip(got, expect):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


def test_flash_attention_non_tileable_falls_back():
    # Public entry must not error on ragged sequence lengths even when the
    # pallas path is selected (interpret=True routes it): T=130 falls back.
    q, k, v = _qkv(B=1, T=130, H=2, D=16, seed=4)
    got = flash_attention(q, k, v, False, True)
    expect = attention_reference(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_backward_matches_reference(causal):
    """The tiled pallas backward (dQ + dK/dV kernels from the saved forward
    logsumexp) must match autodiff of the reference math, with multiple
    q- and k-blocks in flight (blk 32 over T=128 -> 4x4 block grid)."""
    from deeplearning4j_tpu.ops.pallas_kernels import (
        _flash_backward, _flash_forward)
    q, k, v = _qkv(B=2, T=128, H=2, D=32, seed=5)
    rng = np.random.default_rng(6)
    g = jnp.asarray(rng.normal(size=q.shape).astype(np.float32))
    out, lse = _flash_forward(q, k, v, causal, blk_q=32, blk_k=32,
                              interpret=True)
    got = _flash_backward(q, k, v, out, lse, g, causal, blk_q=32, blk_k=32,
                          interpret=True)
    _, vjp = jax.vjp(lambda a, b, c: attention_reference(a, b, c, causal),
                     q, k, v)
    expect = vjp(g)
    for name, a, b in zip(("dq", "dk", "dv"), got, expect):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5, err_msg=name)


def test_pallas_backward_cross_attention_lengths():
    """Tq != Tk (cross-attention shapes) through the pallas backward."""
    from deeplearning4j_tpu.ops.pallas_kernels import (
        _flash_backward, _flash_forward)
    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.normal(size=(1, 64, 2, 16)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(1, 128, 2, 16)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(1, 128, 2, 16)).astype(np.float32))
    g = jnp.asarray(rng.normal(size=q.shape).astype(np.float32))
    out, lse = _flash_forward(q, k, v, False, blk_q=32, blk_k=32,
                              interpret=True)
    got = _flash_backward(q, k, v, out, lse, g, False, blk_q=32, blk_k=32,
                          interpret=True)
    _, vjp = jax.vjp(lambda a, b, c: attention_reference(a, b, c, False),
                     q, k, v)
    expect = vjp(g)
    for name, a, b in zip(("dq", "dk", "dv"), got, expect):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5, err_msg=name)


@pytest.mark.parametrize("causal", [False, True])
def test_masked_attention_pallas_matches_xla(causal):
    """masked_attention's tiled pallas path (interpret=True) == the XLA
    reference math, forward and gradients, including fully-masked rows."""
    from deeplearning4j_tpu.ops.pallas_kernels import (
        _masked_attention_xla, masked_attention)
    q, k, v = _qkv(B=2, T=64, H=2, D=16, seed=8)
    rng = np.random.default_rng(9)
    mask = np.ones((2, 64), np.float32)
    mask[0, 40:] = 0.0           # padded tail
    mask[1, :] = 0.0             # one sequence fully masked
    mask = jnp.asarray(mask)

    expect = _masked_attention_xla(q, k, v, mask, causal)
    got = masked_attention(q, k, v, mask, causal, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=2e-4, atol=2e-5)

    g = jnp.asarray(rng.normal(size=q.shape).astype(np.float32))

    def loss_p(q, k, v):
        return jnp.sum(masked_attention(q, k, v, mask, causal, True) * g)

    def loss_x(q, k, v):
        return jnp.sum(_masked_attention_xla(q, k, v, mask, causal) * g)

    gp = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss_x, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip(("dq", "dk", "dv"), gp, gx):
        assert np.all(np.isfinite(np.asarray(a))), name
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5, err_msg=name)


def test_fused_xent_loss_path_matches_xla():
    """mcxent through the fused Pallas softmax-xent custom_vjp (forced via
    DL4J_FUSED_XENT=1, interpret on CPU) must match the XLA autodiff path in
    value AND gradient, including masked time-series input — this is the
    production wiring of ops/pallas_kernels.softmax_cross_entropy."""
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.ops import losses

    rng = np.random.default_rng(0)
    cases = [
        (rng.normal(size=(8, 5)).astype(np.float32),
         np.eye(5, dtype=np.float32)[rng.integers(0, 5, 8)], None),
        # integer one-hot labels: the fused path must cast, not crash
        (rng.normal(size=(8, 5)).astype(np.float32),
         np.eye(5, dtype=np.int32)[rng.integers(0, 5, 8)], None),
        (rng.normal(size=(4, 6, 3)).astype(np.float32),
         np.eye(3, dtype=np.float32)[rng.integers(0, 3, (4, 6))],
         (rng.uniform(size=(4, 6)) > 0.3).astype(np.float32)),
    ]
    act = jax.nn.softmax
    for preout, labels, mask in cases:
        preout, labels = jnp.asarray(preout), jnp.asarray(labels)
        m = jnp.asarray(mask) if mask is not None else None

        def run():
            f = lambda p: losses.mcxent(labels, p, act, m)
            return float(f(preout)), np.asarray(jax.grad(f)(preout))

        try:
            os.environ["DL4J_FUSED_XENT"] = "0"
            v_xla, g_xla = run()
            os.environ["DL4J_FUSED_XENT"] = "1"
            v_fused, g_fused = run()
        finally:
            os.environ.pop("DL4J_FUSED_XENT", None)
        assert abs(v_xla - v_fused) < 1e-5, (v_xla, v_fused)
        np.testing.assert_allclose(g_fused, g_xla, rtol=1e-4, atol=1e-6)


def test_fused_xent_falls_back_under_shard_map():
    """Inside a shard_map trace the fused kernel must yield to the XLA math
    (the vma checker rejects the pallas_call there — this crashed
    ParallelWrapper local-SGD until round 4). Forced engagement + an
    explicit shard_map reproduce the original failure path."""
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P
    from deeplearning4j_tpu.jax_compat import shard_map

    from deeplearning4j_tpu.ops import losses
    from deeplearning4j_tpu.parallel.mesh import build_mesh

    mesh = build_mesh({"data": 8})
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(16, 5)).astype(np.float32))
    y = jnp.asarray(np.eye(5, dtype=np.float32)[rng.integers(0, 5, 16)])

    def local_loss(xx, yy):
        return losses.mcxent(yy, xx, jax.nn.softmax)[None]

    try:
        os.environ["DL4J_FUSED_XENT"] = "1"
        per_shard = jax.jit(shard_map(
            local_loss, mesh=mesh, in_specs=(P("data"), P("data")),
            out_specs=P("data")))(x, y)
        os.environ["DL4J_FUSED_XENT"] = "0"
        expect = jax.jit(shard_map(
            local_loss, mesh=mesh, in_specs=(P("data"), P("data")),
            out_specs=P("data")))(x, y)
    finally:
        os.environ.pop("DL4J_FUSED_XENT", None)
    np.testing.assert_allclose(np.asarray(per_shard), np.asarray(expect),
                               rtol=1e-5)


def test_flash_attention_falls_back_under_checked_shard_map():
    """flash_attention inside a check_vma=True shard_map must fall back to
    the XLA math (same crash class as the xent kernel); inside ulysses'
    check_vma=False shard_map the pallas kernel still engages (covered by
    test_ulysses_pallas_interpret_matches_reference)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from deeplearning4j_tpu.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    from deeplearning4j_tpu.ops import pallas_kernels as pk
    from deeplearning4j_tpu.parallel.mesh import build_mesh

    mesh = build_mesh({"data": 4})
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(size=(4, 16, 2, 8)).astype(np.float32))
               for _ in range(3))

    def local(qq, kk, vv):
        # interpret=True would normally force the pallas path; the vma guard
        # must override it here
        return pk.flash_attention(qq, kk, vv, True, interpret=True)

    got = jax.jit(shard_map(local, mesh=mesh,
                            in_specs=(P("data"), P("data"), P("data")),
                            out_specs=P("data")))(q, k, v)
    want = pk._attention_xla(q, k, v, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_fused_xent_integrations_bf16_and_lbfgs():
    """Force-engaged fused xent must train under the bfloat16_full policy
    and through the LBFGS solver path (integration seams where the
    custom_vjp meets dtype policies and jitted while_loop optimizers)."""
    import os

    import numpy as np

    from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer

    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
    try:
        os.environ["DL4J_FUSED_XENT"] = "1"
        conf = (NeuralNetConfiguration.builder().seed(0).learning_rate(0.1)
                .dtype("bfloat16_full")
                .list()
                .layer(DenseLayer(n_in=6, n_out=16, activation="tanh"))
                .layer(OutputLayer(n_in=16, n_out=3, loss="mcxent",
                                   activation="softmax"))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.fit(x, y)
        s0 = net.score_value
        for _ in range(20):
            net.fit(x, y)
        assert net.score_value < s0

        conf2 = (NeuralNetConfiguration.builder().seed(1).learning_rate(0.5)
                 .optimization_algo("lbfgs")
                 .list()
                 .layer(DenseLayer(n_in=6, n_out=16, activation="tanh"))
                 .layer(OutputLayer(n_in=16, n_out=3, loss="mcxent",
                                    activation="softmax"))
                 .build())
        net2 = MultiLayerNetwork(conf2).init()
        net2.fit(x, y)
        s0 = net2.score_value
        for _ in range(5):
            net2.fit(x, y)
        assert net2.score_value <= s0
    finally:
        os.environ.pop("DL4J_FUSED_XENT", None)


def test_pick_blk_divisor_fallback():
    """Round-5 calibration raised the default K block to 512; _pick_blk must
    fall back to smaller standard tiles for 128-divisible-but-not-512-
    divisible lengths instead of silently dropping to the O(T^2) XLA path."""
    from deeplearning4j_tpu.ops.pallas_kernels import _pick_blk, _tileable

    assert _pick_blk(2048, 512) == 512
    assert _pick_blk(1280, 512) == 256
    assert _pick_blk(3200, 512) == 128
    assert _pick_blk(1000, 512) is None       # not 128-divisible
    assert _pick_blk(64, 512) == 64           # short seq: one block
    assert _tileable(1280, 3200)
    assert not _tileable(2048, 1000)


def test_min_seq_gates_pallas_dispatch(monkeypatch):
    """Production dispatch engages the flash kernel only at/above
    DL4J_FLASH_MIN_SEQ (short sequences measured faster on the fused XLA
    path in-model); interpret mode bypasses the gate so CPU tests keep
    exercising the kernel."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops import pallas_kernels as pk

    q = jnp.zeros((1, 256, 2, 8), jnp.float32)
    qlong = jnp.zeros((1, 2048, 2, 8), jnp.float32)
    monkeypatch.setattr(pk, "use_pallas", lambda: True)
    assert not pk._pallas_ok(q, q, interpret=False)       # 256 < 1024
    assert pk._pallas_ok(qlong, qlong, interpret=False)   # 2048 >= 1024
    assert pk._pallas_ok(q, q, interpret=True)            # tests bypass

    # the tiled backward has its own, higher threshold
    assert not pk._pallas_bwd_enabled(2048)
    assert pk._pallas_bwd_enabled(4096)
    monkeypatch.setenv("DL4J_FLASH_PALLAS_BWD", "1")
    assert pk._pallas_bwd_enabled(64)                     # explicit override


def test_force_pallas_bypasses_length_gate_not_hard_constraints(monkeypatch):
    """force_pallas is the per-call opt-in for workloads whose measured
    crossover differs from _MIN_SEQ: it must bypass the length heuristic on
    both flash and masked entry points, and must NEVER override the
    vma-checked shard_map guard (pallas_call is rejected there outright)."""
    from jax.sharding import PartitionSpec as P

    from deeplearning4j_tpu.jax_compat import shard_map
    from deeplearning4j_tpu.ops import pallas_kernels as pk
    from deeplearning4j_tpu.parallel.mesh import build_mesh

    rng = np.random.default_rng(0)
    # T=64: tileable, but far below _MIN_SEQ (1024)
    q, k, v = (jnp.asarray(rng.normal(size=(4, 64, 2, 8)).astype(np.float32))
               for _ in range(3))

    calls = []

    def fake_forward(qq, kk, vv, causal, interpret=False, key_mask=None):
        calls.append(1)
        if key_mask is not None:
            return pk._masked_attention_xla(qq, kk, vv, key_mask, causal), None
        return pk._attention_xla(qq, kk, vv, causal), None

    # pretend the TPU kernel path is available so the length heuristic (not
    # hardware support) is what decides
    monkeypatch.setattr(pk, "use_pallas", lambda: True)
    monkeypatch.setattr(pk, "_flash_forward", fake_forward)

    out = pk.flash_attention(q, k, v, False)
    assert not calls, "short sequence must stay on the XLA path by default"
    forced = pk.flash_attention(q, k, v, False, force_pallas=True)
    assert calls, "force_pallas did not bypass the _MIN_SEQ gate"
    np.testing.assert_allclose(np.asarray(forced), np.asarray(out),
                               rtol=1e-5, atol=1e-6)

    # masked entry point shares the one dispatch predicate
    km = jnp.ones((4, 64), jnp.float32)
    calls.clear()
    pk.masked_attention(q, k, v, km, False)
    assert not calls
    pk.masked_attention(q, k, v, km, False, force_pallas=True)
    assert calls

    # hard constraint wins over force: inside a CHECKED shard_map the kernel
    # must still fall back (engaging would crash on the vma checker, not
    # merely run slow)
    mesh = build_mesh({"data": 4})
    calls.clear()
    got = jax.jit(shard_map(
        lambda a, b, c: pk.flash_attention(a, b, c, False, force_pallas=True),
        mesh=mesh, in_specs=(P("data"), P("data"), P("data")),
        out_specs=P("data")))(q, k, v)
    assert not calls, "force_pallas must not override the checked-shard_map guard"
    np.testing.assert_allclose(np.asarray(got), np.asarray(out),
                               rtol=1e-5, atol=1e-6)
