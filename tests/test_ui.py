"""Observability tests (reference TestStatsListener, TestPlayUI,
TestRemoteReceiver — headless equivalents)."""
import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ui import (
    FileStatsStorage, InMemoryStatsStorage, StatsListener, StatsReport, UIServer,
)
from deeplearning4j_tpu.ui.server import RemoteUIStatsStorageRouter


def _trained_net_with_listener(storage, iters=5):
    conf = (NeuralNetConfiguration.builder()
            .seed(0).learning_rate(0.1)
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent", activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.set_listeners(StatsListener(storage, session_id="test_session"))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 4)).astype(np.float32)
    y = np.zeros((16, 3), np.float32)
    y[np.arange(16), rng.integers(0, 3, 16)] = 1
    for _ in range(iters):
        net.fit(x, y)
    return net


def test_stats_report_codec_roundtrip():
    r = StatsReport("sess", "w0", 12345)
    r.iteration = 7
    r.score = 1.25
    r.iteration_time_ms = 3.5
    r.mem_rss_bytes = 1 << 30
    r.param_stats["l0_W"] = (0.25, [1, 2, 3, 4], (-1.0, 1.0))
    r.gradient_stats["l0_W"] = (0.01, [4, 3, 2, 1], (-0.1, 0.1))
    out = StatsReport.decode(r.encode())
    assert out.session_id == "sess" and out.worker_id == "w0"
    assert out.iteration == 7 and out.score == 1.25
    assert out.param_stats["l0_W"][0] == 0.25
    assert out.param_stats["l0_W"][1] == [1, 2, 3, 4]
    assert out.gradient_stats["l0_W"][2] == (-0.1, 0.1)


def test_listener_populates_storage():
    storage = InMemoryStatsStorage()
    _trained_net_with_listener(storage)
    assert storage.list_session_ids() == ["test_session"]
    updates = storage.get_all_updates_after("test_session", StatsReport.TYPE_ID,
                                            "main", -1)
    assert len(updates) == 5
    reports = [StatsReport.decode(u) for u in updates]
    assert all(np.isfinite(r.score) for r in reports)
    assert any(r.param_stats for r in reports)
    # update stats appear from the second iteration on
    assert reports[-1].update_stats


def test_file_stats_storage_roundtrip(tmp_path):
    path = str(tmp_path / "stats.db")
    storage = FileStatsStorage(path)
    _trained_net_with_listener(storage, iters=3)
    storage.close()
    re = FileStatsStorage(path)
    assert re.list_session_ids() == ["test_session"]
    assert re.get_num_updates("test_session", StatsReport.TYPE_ID, "main") == 3
    latest = StatsReport.decode(
        re.get_latest_update("test_session", StatsReport.TYPE_ID, "main"))
    assert latest.iteration == 3
    re.close()


def test_storage_listener_events():
    storage = InMemoryStatsStorage()
    events = []
    storage.register_stats_storage_listener(events.append)
    _trained_net_with_listener(storage, iters=2)
    kinds = [e.kind for e in events]
    assert "PostUpdate" in kinds


def test_ui_server_endpoints():
    server = UIServer(port=0)
    try:
        storage = InMemoryStatsStorage()
        server.attach(storage)
        _trained_net_with_listener(storage, iters=4)
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(base + "/train/overview") as r:
            assert r.status == 200 and b"Training overview" in r.read()
        with urllib.request.urlopen(base + "/train/overview/data") as r:
            data = json.loads(r.read())
        assert len(data["scores"]) == 4
        assert data["iterations"] == [1, 2, 3, 4]
        with urllib.request.urlopen(base + "/train/model/data") as r:
            model = json.loads(r.read())
        assert any("W" in k for k in model["layers"])
        with urllib.request.urlopen(base + "/train/system/data") as r:
            system = json.loads(r.read())
        assert len(system["memRssBytes"]) == 4
    finally:
        server.stop()


def test_remote_router_posts_to_server():
    server = UIServer(port=0)
    try:
        server.enable_remote_listener()
        router = RemoteUIStatsStorageRouter(f"http://127.0.0.1:{server.port}")
        r = StatsReport("remote_sess", "w1", 99)
        r.iteration = 1
        r.score = 0.5
        router.put_update(r)
        data = server.overview_data()
        assert data["scores"] == [0.5]
        assert "remote_sess" in server.sessions()
    finally:
        server.stop()


def test_histogram_and_tsne_endpoints():
    import json as _json
    import urllib.request
    from deeplearning4j_tpu.ui.server import UIServer
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
    from deeplearning4j_tpu.ui.stats import StatsReport

    server = UIServer(port=0)
    try:
        storage = InMemoryStatsStorage()
        server.attach(storage)
        r = StatsReport("s1", "w0", 1000)
        r.iteration = 7
        r.param_stats["l0_W"] = (0.5, [1, 2, 3], (-1.0, 1.0))
        storage.put_update(r)
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(f"{base}/train/histograms/data") as resp:
            d = _json.loads(resp.read())
        assert d["iteration"] == 7
        assert d["params"]["l0_W"]["bins"] == [1, 2, 3]
        # tsne upload + fetch
        payload = _json.dumps({"coords": [[0.1, 0.2], [0.3, 0.4]],
                               "labels": ["a", "b"]}).encode()
        req = urllib.request.Request(f"{base}/tsne/upload", data=payload,
                                     method="POST")
        with urllib.request.urlopen(req) as resp:
            assert _json.loads(resp.read())["status"] == "ok"
        with urllib.request.urlopen(f"{base}/tsne/data") as resp:
            t = _json.loads(resp.read())
        assert t["labels"] == ["a", "b"] and len(t["coords"]) == 2
    finally:
        server.stop()


def test_rendered_pages_and_model_graph():
    """Model/System/Convolutional pages render (reference PlayUIServer
    TrainModule model+system tabs, FlowModule, ConvolutionalListenerModule)."""
    from deeplearning4j_tpu.ui.server import UIServer, describe_model

    server = UIServer(port=0)
    try:
        base = f"http://127.0.0.1:{server.port}"
        for path, marker in [("/train/model", "Network graph"),
                             ("/train/system", "System"),
                             ("/train/convolutional", "Convolutional")]:
            html = urllib.request.urlopen(base + path).read().decode()
            assert marker in html
            assert "<canvas" in html or "maps" in html

        # model graph endpoint: attach an MLN, nodes/edges follow the chain
        conf = (NeuralNetConfiguration.builder().seed(1)
                .list()
                .layer(DenseLayer(n_in=4, n_out=6, activation="relu"))
                .layer(OutputLayer(n_in=6, n_out=2, loss="mcxent",
                                   activation="softmax"))
                .build())
        net = MultiLayerNetwork(conf).init()
        server.attach_model(net)
        g = json.loads(urllib.request.urlopen(
            base + "/train/model/graph").read())
        names = [n["name"] for n in g["nodes"]]
        assert names == ["input", "layer_0", "layer_1"]
        assert ["input", "layer_0"] in g["edges"]
        assert g["nodes"][1]["nParams"] == 4 * 6 + 6

        # CG graphs include vertices and multi-input edges
        from deeplearning4j_tpu.nn.graph_network import ComputationGraph
        gconf = (NeuralNetConfiguration.builder().seed(1)
                 .graph_builder()
                 .add_inputs("in")
                 .add_layer("d", DenseLayer(n_in=4, n_out=6,
                                            activation="relu"), "in")
                 .add_layer("out", OutputLayer(n_in=6, n_out=2, loss="mcxent",
                                               activation="softmax"), "d")
                 .set_outputs("out")
                 .build())
        cg = ComputationGraph(gconf).init()
        gd = describe_model(cg)
        assert {"in", "d", "out"} <= {n["name"] for n in gd["nodes"]}
        assert ["in", "d"] in gd["edges"]
    finally:
        server.stop()


def test_convolutional_listener_posts_activations():
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (
        ConvolutionLayer, SubsamplingLayer)
    from deeplearning4j_tpu.ui.server import (
        ConvolutionalIterationListener, UIServer)

    server = UIServer(port=0)
    try:
        conf = (NeuralNetConfiguration.builder().seed(2).learning_rate(0.05)
                .list()
                .layer(ConvolutionLayer(n_out=3, kernel_size=(3, 3),
                                        stride=(1, 1), activation="relu"))
                .layer(SubsamplingLayer(pooling_type="max",
                                        kernel_size=(2, 2), stride=(2, 2)))
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=2, loss="mcxent",
                                   activation="softmax"))
                .set_input_type(InputType.convolutional(8, 8, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        probe = rng.normal(size=(2, 8, 8, 1)).astype(np.float32)
        net.set_listeners(ConvolutionalIterationListener(server, probe,
                                                         frequency=1))
        x = rng.normal(size=(8, 8, 8, 1)).astype(np.float32)
        y = np.zeros((8, 2), np.float32)
        y[:, 0] = 1
        net.fit(x, y)

        data = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/train/convolutional/data")
            .read())
        assert data["maps"], "listener posted no maps"
        assert data["maps"][0]["layer"] == "layer_0"
        ch = np.asarray(data["maps"][0]["channels"])
        assert ch.shape[0] == 3 and ch.ndim == 3  # 3 channels of 2-D maps
    finally:
        server.stop()


def test_ui_i18n_pages_and_language_switch():
    """UI pages localize via ?lang= / Accept-Language (reference
    DefaultI18N.java): placeholder keys never leak, Japanese strings render,
    and /lang/setCurrent changes the server default."""
    import urllib.request

    from deeplearning4j_tpu.ui.i18n import I18N
    from deeplearning4j_tpu.ui.server import UIServer

    ui = UIServer(port=0)
    try:
        base = f"http://127.0.0.1:{ui.port}"

        def get(path, headers=None):
            req = urllib.request.Request(base + path, headers=headers or {})
            return urllib.request.urlopen(req).read().decode()

        en = get("/train/overview")
        assert "Training overview" in en and "{{" not in en
        ja = get("/train/overview?lang=ja")
        assert "トレーニング概要" in ja and "{{" not in ja
        # Accept-Language header resolution (q-values stripped)
        de = get("/train/overview", {"Accept-Language": "de;q=0.9,en;q=0.8"})
        assert "Trainingsübersicht" in de
        # default-language switch (reference /lang/setCurrent route)
        get("/lang/setCurrent?lang=fr")
        fr = get("/train/model")
        assert "Graphe du réseau" in fr
        # unknown language falls back to English, never the raw key
        zz = get("/train/system?lang=zz")
        assert "Host RSS" in zz and "{{" not in zz
        assert "ja" in I18N.available_languages()
    finally:
        # the singleton default is process-global state: always restore
        I18N.get_instance().set_default_language("en")
        ui.stop()


def test_ui_histograms_rendered_page():
    """/train/histograms renders ChartHistogram SVGs server-side from the
    latest stats report (reference HistogramModule + ui-components)."""
    import urllib.request

    import numpy as np

    from deeplearning4j_tpu.ui.server import UIServer
    from deeplearning4j_tpu.ui.stats import StatsListener
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage

    ui = UIServer(port=0)
    try:
        storage = InMemoryStatsStorage()
        ui.attach(storage)
        from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer

        conf = (NeuralNetConfiguration.builder().seed(0).learning_rate(0.1)
                .list()
                .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
                .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                                   activation="softmax"))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.set_listeners(StatsListener(storage, session_id="histsess"))
        rng = np.random.default_rng(0)
        net.fit(rng.normal(size=(16, 4)).astype(np.float32),
                np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)])

        base = f"http://127.0.0.1:{ui.port}"
        page = urllib.request.urlopen(base + "/train/histograms").read().decode()
        assert "<svg" in page and "Parameters" in page
        assert "{{" not in page
        # localized variant
        ja = urllib.request.urlopen(
            base + "/train/histograms?lang=ja").read().decode()
        assert "パラメータ" in ja
        # empty storage renders the no-data message, not an error
        ui2 = UIServer(port=0)
        try:
            empty = urllib.request.urlopen(
                f"http://127.0.0.1:{ui2.port}/train/histograms").read().decode()
            assert "no statistics recorded yet" in empty
        finally:
            ui2.stop()
    finally:
        ui.stop()
