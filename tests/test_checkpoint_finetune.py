"""Checkpoint/resume determinism, NaN guard, and the VGG-16-style Keras
import fine-tune path (BASELINE config 5 at test scale)."""
import json

import numpy as np
import pytest

from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.listeners import (
    CheckpointListener, NanScoreWatcher,
)
from deeplearning4j_tpu.utils.model_serializer import (
    restore_multi_layer_network, write_model,
)


def _net(seed=0, lr=0.05):
    conf = (NeuralNetConfiguration.builder().seed(seed).learning_rate(lr)
            .updater("adam")
            .list().layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=2, loss="mcxent",
                               activation="softmax")).build())
    return MultiLayerNetwork(conf).init()


def _data(n=32, seed=0):
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 2, n)
    x = rng.normal(0, 0.3, (n, 4)).astype(np.float32)
    x[np.arange(n), labels] += 2.0
    return x, np.eye(2, dtype=np.float32)[labels]


def test_resume_from_checkpoint_is_deterministic(tmp_path):
    x, y = _data()
    # train 4 steps, checkpoint, then 4 more
    a = _net()
    for i in range(4):
        a.fit(x, y)
    ckpt = str(tmp_path / "mid.zip")
    write_model(a, ckpt)
    for i in range(4):
        a.fit(x, y)

    # restore at step 4 and replay the last 4 steps: updater state is in the
    # checkpoint so the trajectory must match exactly (SURVEY.md §5)
    b = restore_multi_layer_network(ckpt)
    for i in range(4):
        b.fit(x, y)
    np.testing.assert_allclose(np.asarray(a.params()), np.asarray(b.params()),
                               rtol=1e-6, atol=1e-7)


def test_checkpoint_listener_rotation(tmp_path):
    net = _net()
    x, y = _data()
    lst = CheckpointListener(str(tmp_path), every_n_iterations=1,
                             every_n_epochs=None, keep_last=2)
    net.set_listeners(lst)
    for _ in range(5):
        net.fit(x, y)
    zips = sorted(p.name for p in tmp_path.glob("checkpoint_*.zip"))
    assert len(zips) == 2  # rotated
    assert CheckpointListener.last_checkpoint(str(tmp_path)) is not None
    restored = restore_multi_layer_network(
        CheckpointListener.last_checkpoint(str(tmp_path)))
    np.testing.assert_allclose(np.asarray(restored.params()),
                               np.asarray(net.params()), rtol=1e-6)


def test_nan_watcher_raises():
    net = _net(lr=0.05)
    net.set_listeners(NanScoreWatcher())
    x, y = _data()
    net.fit(x, y)  # healthy step passes
    x_bad = x.copy()
    x_bad[0, 0] = np.nan
    with pytest.raises(FloatingPointError):
        net.fit(x_bad, y)


@pytest.mark.skipif(
    not __import__("deeplearning4j_tpu.modelimport.hdf5",
                   fromlist=["hdf5_available"]).hdf5_available(),
    reason="libhdf5 not present")
def test_vgg_style_keras_import_finetune(tmp_path):
    """BASELINE config 5 shape: import a (tiny) VGG-16-style conv archive and
    fine-tune with data-parallel averaging."""
    from deeplearning4j_tpu.modelimport.hdf5 import H5File
    from deeplearning4j_tpu.modelimport.keras_import import KerasModelImport
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
    from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator

    rng = np.random.default_rng(0)
    size, nc = 16, 4
    # VGG topology at toy scale: conv-conv-pool / conv-pool / flatten-dense
    layers = [
        ("Convolution2D", {"name": "block1_conv1", "nb_filter": 4,
                           "nb_row": 3, "nb_col": 3, "border_mode": "same",
                           "dim_ordering": "tf", "activation": "relu",
                           "batch_input_shape": [None, size, size, 3]}),
        ("Convolution2D", {"name": "block1_conv2", "nb_filter": 4,
                           "nb_row": 3, "nb_col": 3, "border_mode": "same",
                           "dim_ordering": "tf", "activation": "relu"}),
        ("MaxPooling2D", {"name": "block1_pool", "pool_size": [2, 2]}),
        ("Convolution2D", {"name": "block2_conv1", "nb_filter": 8,
                           "nb_row": 3, "nb_col": 3, "border_mode": "same",
                           "dim_ordering": "tf", "activation": "relu"}),
        ("MaxPooling2D", {"name": "block2_pool", "pool_size": [2, 2]}),
        ("Flatten", {"name": "flatten"}),
        ("Dense", {"name": "fc1", "output_dim": 16, "activation": "relu"}),
        ("Dense", {"name": "predictions", "output_dim": nc,
                   "activation": "softmax"}),
    ]
    mc = {"class_name": "Sequential",
          "config": [{"class_name": c, "config": cfg} for c, cfg in layers]}
    weights = {}
    shapes = {"block1_conv1": [(3, 3, 3, 4), (4,)],
              "block1_conv2": [(3, 3, 4, 4), (4,)],
              "block2_conv1": [(3, 3, 4, 8), (8,)],
              "fc1": [(4 * 4 * 8, 16), (16,)],
              "predictions": [(16, nc), (nc,)]}
    for lname, (ws, bs) in shapes.items():
        weights[lname] = [
            (f"{lname}_W", rng.normal(0, 0.1, ws).astype(np.float32)),
            (f"{lname}_b", np.zeros(bs, np.float32))]
    p = tmp_path / "vgg_tiny.h5"
    with H5File(str(p), "w") as f:
        f.write_attr("/", "model_config", json.dumps(mc))
        f.write_attr("/", "training_config",
                     json.dumps({"loss": "categorical_crossentropy"}))
        f.create_group("/model_weights")
        f.write_attr("/model_weights", "layer_names", list(weights))
        for lname, ws in weights.items():
            f.create_group(f"/model_weights/{lname}")
            f.write_attr(f"/model_weights/{lname}", "weight_names",
                         [wn for wn, _ in ws])
            for wn, arr in ws:
                f.write_dataset(f"/model_weights/{lname}/{wn}", arr)

    net = KerasModelImport.import_keras_sequential_model_and_weights(str(p))
    # fine-tune data-parallel: class = dominant color channel pattern
    n = 64
    labels = rng.integers(0, nc, n)
    x = rng.normal(0, 0.2, (n, size, size, 3)).astype(np.float32)
    for i in range(n):
        x[i, :, :, labels[i] % 3] += 1.0 + (labels[i] // 3)
    y = np.eye(nc, dtype=np.float32)[labels]
    it = ArrayDataSetIterator(x, y, batch=16, shuffle=True, seed=0)
    wrapper = ParallelWrapper(net, workers=2, prefetch=0)
    first = None
    for _ in range(6):
        wrapper.fit(it, epochs=1)
        if first is None:
            first = net.score_value
    assert net.score_value < first
    assert np.asarray(net.output(x[:2])).shape == (2, nc)


def test_crash_resume_matches_uninterrupted_run(tmp_path):
    """Fault injection (SURVEY §5): a training process that dies hard
    (os._exit mid-fit, simulating host preemption) resumes from the
    CheckpointListener's latest.zip and reproduces the uninterrupted
    trajectory exactly — the reference's deterministic-restart contract
    (ModelSerializer zips include updater state)."""
    import os
    import subprocess
    import sys

    worker = os.path.join(os.path.dirname(__file__), "_crash_worker.py")
    ckpt_dir = str(tmp_path / "ckpts")
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, worker, ckpt_dir], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 17, proc.stderr[-1500:]  # died as planned
    assert "CRASHING at iteration 5" in proc.stdout

    latest = CheckpointListener.last_checkpoint(ckpt_dir)
    assert latest is not None
    resumed = restore_multi_layer_network(latest, load_updater=True)
    assert resumed.iteration == 5

    x, y = _data()
    for _ in range(5):
        resumed.fit(x, y)

    # oracle: uninterrupted 10 steps in this process
    oracle = _net()
    for _ in range(10):
        oracle.fit(x, y)
    np.testing.assert_allclose(np.asarray(resumed.params()),
                               np.asarray(oracle.params()),
                               rtol=1e-6, atol=1e-7)
