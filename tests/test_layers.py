"""Layer forward-pass shape/semantics tests (reference: deeplearning4j-core layer tests)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer, AutoEncoder, BatchNormalization, ConvolutionLayer, DenseLayer,
    DropoutLayer, EmbeddingLayer, GlobalPoolingLayer, GravesBidirectionalLSTM,
    GravesLSTM, LocalResponseNormalization, LSTM, OutputLayer, RBM, SubsamplingLayer,
    VariationalAutoencoder,
)

KEY = jax.random.PRNGKey(0)


def test_dense_forward():
    layer = DenseLayer(n_in=4, n_out=8, activation="relu", weight_init="xavier")
    params = layer.init_params(KEY, InputType.feed_forward(4))
    assert params["W"].shape == (4, 8)
    x = jnp.ones((3, 4))
    y, _ = layer.apply(params, {}, x)
    assert y.shape == (3, 8)
    assert (np.asarray(y) >= 0).all()


def test_conv_shapes():
    layer = ConvolutionLayer(n_in=3, n_out=16, kernel_size=(3, 3), stride=(1, 1),
                             activation="relu", weight_init="relu")
    itype = InputType.convolutional(8, 8, 3)
    params = layer.init_params(KEY, itype)
    assert params["W"].shape == (3, 3, 3, 16)
    x = jnp.ones((2, 8, 8, 3))
    y, _ = layer.apply(params, {}, x)
    assert y.shape == (2, 6, 6, 16)
    ot = layer.output_type(itype)
    assert (ot.height, ot.width, ot.channels) == (6, 6, 16)


def test_conv_same_mode():
    layer = ConvolutionLayer(n_in=3, n_out=4, kernel_size=(3, 3), stride=(2, 2),
                             convolution_mode="same", activation="identity")
    x = jnp.ones((1, 9, 9, 3))
    params = layer.init_params(KEY, InputType.convolutional(9, 9, 3))
    y, _ = layer.apply(params, {}, x)
    assert y.shape == (1, 5, 5, 4)


def test_subsampling_max_avg():
    x = jnp.arange(16.0).reshape(1, 4, 4, 1)
    mx = SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2))
    y, _ = mx.apply({}, {}, x)
    assert y.shape == (1, 2, 2, 1)
    assert float(y[0, 0, 0, 0]) == 5.0
    avg = SubsamplingLayer(pooling_type="avg", kernel_size=(2, 2), stride=(2, 2))
    y2, _ = avg.apply({}, {}, x)
    assert float(y2[0, 0, 0, 0]) == 2.5


def test_batchnorm_train_vs_eval():
    layer = BatchNormalization(n_in=5, activation="identity")
    itype = InputType.feed_forward(5)
    params = layer.init_params(KEY, itype)
    state = layer.init_state(itype)
    x = jax.random.normal(KEY, (64, 5)) * 3 + 2
    y, new_state = layer.apply(params, state, x, train=True)
    # normalized output roughly zero-mean unit-var
    assert abs(float(jnp.mean(y))) < 0.1
    assert abs(float(jnp.std(y)) - 1.0) < 0.15
    # running stats moved toward batch stats
    assert float(new_state["mean"].mean()) != 0.0


def test_lstm_shapes_and_mask():
    layer = GravesLSTM(n_in=6, n_out=10, activation="tanh")
    itype = InputType.recurrent(6)
    params = layer.init_params(KEY, itype)
    assert params["W"].shape == (6, 40)
    assert params["RW"].shape == (10, 40)
    x = jax.random.normal(KEY, (2, 7, 6))
    y, _ = layer.apply(params, {}, x)
    assert y.shape == (2, 7, 10)
    # mask freezes state after the masked timestep
    mask = jnp.array([[1, 1, 1, 0, 0, 0, 0], [1, 1, 1, 1, 1, 1, 1]], jnp.float32)
    ym, _ = layer.apply(params, {}, x, mask=mask)
    np.testing.assert_allclose(np.asarray(ym[0, 3]), np.asarray(ym[0, 2]), rtol=1e-5)


def test_bidirectional_lstm():
    layer = GravesBidirectionalLSTM(n_in=4, n_out=6, activation="tanh")
    params = layer.init_params(KEY, InputType.recurrent(4))
    x = jax.random.normal(KEY, (3, 5, 4))
    y, _ = layer.apply(params, {}, x)
    assert y.shape == (3, 5, 6)


def test_lstm_streaming_matches_full():
    layer = LSTM(n_in=4, n_out=6, activation="tanh")
    params = layer.init_params(KEY, InputType.recurrent(4))
    x = jax.random.normal(KEY, (2, 6, 4))
    full, _ = layer.apply(params, {}, x)
    # stream one timestep at a time
    state = {"h": jnp.zeros((2, 6)), "c": jnp.zeros((2, 6))}
    outs = []
    for t in range(6):
        y, state = layer.apply_streaming(params, state, x[:, t:t + 1])
        outs.append(y)
    streamed = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(full), np.asarray(streamed), atol=1e-5)


def test_embedding():
    layer = EmbeddingLayer(n_in=50, n_out=8, activation="identity")
    params = layer.init_params(KEY, InputType.feed_forward(50))
    idx = jnp.array([[0], [3], [49]])
    y, _ = layer.apply(params, {}, idx)
    assert y.shape == (3, 8)
    np.testing.assert_allclose(np.asarray(y[1]),
                               np.asarray(params["W"][3] + params["b"]), rtol=1e-6)


def test_dropout_train_only():
    layer = DropoutLayer(dropout=0.5)
    x = jnp.ones((10, 20))
    y_eval, _ = layer.apply({}, {}, x, train=False)
    np.testing.assert_array_equal(np.asarray(y_eval), np.asarray(x))
    y_train, _ = layer.apply({}, {}, x, train=True, rng=KEY)
    arr = np.asarray(y_train)
    assert ((arr == 0) | (arr == 2.0)).all()
    assert (arr == 0).any()


def test_lrn():
    layer = LocalResponseNormalization()
    x = jax.random.normal(KEY, (2, 4, 4, 8))
    y, _ = layer.apply({}, {}, x)
    assert y.shape == x.shape
    assert float(jnp.max(jnp.abs(y))) <= float(jnp.max(jnp.abs(x)))


def test_global_pooling():
    layer = GlobalPoolingLayer(pooling_type="avg")
    x = jnp.ones((2, 4, 4, 8))
    y, _ = layer.apply({}, {}, x)
    assert y.shape == (2, 8)


def test_autoencoder_pretrain_loss():
    layer = AutoEncoder(n_in=10, n_out=5, activation="sigmoid",
                        corruption_level=0.3, weight_init="xavier")
    params = layer.init_params(KEY, InputType.feed_forward(10))
    x = jax.random.uniform(KEY, (8, 10))
    loss = layer.pretrain_loss(params, x, rng=KEY)
    assert float(loss) > 0


def test_vae_elbo_and_forward():
    layer = VariationalAutoencoder(n_in=12, n_out=4, activation="tanh",
                                   encoder_layer_sizes=(16,), decoder_layer_sizes=(16,),
                                   reconstruction_distribution="bernoulli",
                                   weight_init="xavier")
    params = layer.init_params(KEY, InputType.feed_forward(12))
    x = (jax.random.uniform(KEY, (4, 12)) > 0.5).astype(jnp.float32)
    loss = layer.pretrain_loss(params, x, rng=KEY)
    assert np.isfinite(float(loss))
    y, _ = layer.apply(params, {}, x)
    assert y.shape == (4, 4)


def test_rbm_cd_runs():
    layer = RBM(n_in=8, n_out=6, activation="sigmoid", weight_init="xavier")
    params = layer.init_params(KEY, InputType.feed_forward(8))
    x = (jax.random.uniform(KEY, (4, 8)) > 0.5).astype(jnp.float32)
    loss = layer.pretrain_loss(params, x, rng=KEY)
    grads = jax.grad(lambda p: layer.pretrain_loss(p, x, rng=KEY))(params)
    assert np.isfinite(float(loss))
    assert all(np.isfinite(np.asarray(g)).all() for g in grads.values())


class TestExceptionMessages:
    """Config-error tests (reference deeplearning4j-core exceptions suite):
    typos must fail fast with actionable messages listing the known names."""

    def test_unknown_activation_lists_known(self):
        from deeplearning4j_tpu.ops.activations import get_activation
        with pytest.raises(ValueError, match="relu"):
            get_activation("rellu")

    def test_unknown_loss_lists_known(self):
        from deeplearning4j_tpu.ops.losses import get_loss
        with pytest.raises(ValueError, match="mcxent"):
            get_loss("mcxnet")

    def test_unknown_updater(self):
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.updaters import UpdaterSpec, updater_init
        with pytest.raises(ValueError, match="Unknown updater"):
            updater_init(UpdaterSpec(name="adamw_typo"), jnp.zeros((2,)))

    def test_unknown_lr_policy(self):
        from deeplearning4j_tpu.nn.updaters import effective_lr
        with pytest.raises(ValueError, match="Unknown lr policy"):
            effective_lr(0.1, "cosine_typo", 0)

    def test_unknown_reconstruction_distribution(self):
        from deeplearning4j_tpu.nn.conf.layers.variational import (
            resolve_reconstruction_distribution)
        with pytest.raises(ValueError, match="gaussian"):
            resolve_reconstruction_distribution("gausian")

    def test_output_layer_required_for_supervised_loss(self):
        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        import numpy as np
        conf = (NeuralNetConfiguration.builder().seed(1)
                .list()
                .layer(DenseLayer(n_in=3, n_out=2, activation="tanh"))
                .build())
        net = MultiLayerNetwork(conf).init()
        with pytest.raises(ValueError, match="no loss"):
            net.fit(np.zeros((2, 3), np.float32), np.zeros((2, 2), np.float32))

    def test_uninitialized_network_message(self):
        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        import numpy as np
        conf = (NeuralNetConfiguration.builder().seed(1)
                .list()
                .layer(DenseLayer(n_in=3, n_out=2, activation="tanh"))
                .layer(OutputLayer(n_in=2, n_out=2, loss="mse",
                                   activation="identity"))
                .build())
        net = MultiLayerNetwork(conf)  # init() not called
        with pytest.raises(RuntimeError, match="init"):
            net.fit(np.zeros((2, 3), np.float32),
                    np.zeros((2, 2), np.float32))
