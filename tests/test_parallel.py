"""Data-parallel training tests on the virtual 8-device CPU mesh.

The gold-standard pattern is the reference's
TestCompareParameterAveragingSparkVsSingleMachine (SURVEY.md §4): distributed training
must equal single-device training for matched configs.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.mesh import build_mesh, data_parallel_mesh
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper


def _conf(seed=1, lr=0.1, updater="sgd"):
    return (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(lr).updater(updater)
            .list()
            .layer(DenseLayer(n_in=6, n_out=10, activation="tanh"))
            .layer(OutputLayer(n_in=10, n_out=3, loss="mcxent", activation="softmax"))
            .build())


def _batches(n_batches=6, batch=32, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_batches):
        x = rng.normal(size=(batch, 6)).astype(np.float32)
        y = np.zeros((batch, 3), np.float32)
        y[np.arange(batch), rng.integers(0, 3, batch)] = 1
        out.append(DataSet(x, y))
    return out


def test_sync_dp_equals_single_device():
    """averaging_frequency=1 DP over 8 devices == plain single-device fit on the
    same global batches (reference TestCompareParameterAveragingSparkVsSingleMachine)."""
    batches = _batches()

    single = MultiLayerNetwork(_conf()).init()
    for ds in batches:
        single.fit(ds.features, ds.labels)

    dp_net = MultiLayerNetwork(_conf()).init()
    pw = (ParallelWrapper.builder(dp_net)
          .workers(8).prefetch_buffer(0).averaging_frequency(1)
          .build())
    pw.fit(ListDataSetIterator(batches))

    np.testing.assert_allclose(np.asarray(single.params()),
                               np.asarray(dp_net.params()), atol=2e-6)


def test_sync_dp_adam_equals_single_device():
    batches = _batches(4)
    single = MultiLayerNetwork(_conf(updater="adam")).init()
    for ds in batches:
        single.fit(ds.features, ds.labels)
    dp_net = MultiLayerNetwork(_conf(updater="adam")).init()
    ParallelWrapper.builder(dp_net).workers(8).prefetch_buffer(0).build() \
        .fit(ListDataSetIterator(batches))
    np.testing.assert_allclose(np.asarray(single.params()),
                               np.asarray(dp_net.params()), atol=2e-6)


def test_local_sgd_averaging():
    """averaging_frequency=4 local-SGD: runs, stays finite, and final params are
    synchronized across replicas (reference ParallelWrapper averaging :179-212)."""
    batches = _batches(8)
    net = MultiLayerNetwork(_conf()).init()
    p0 = np.asarray(net.params())
    pw = (ParallelWrapper.builder(net)
          .workers(8).prefetch_buffer(0).averaging_frequency(4)
          .build())
    pw.fit(ListDataSetIterator(batches))
    p1 = np.asarray(net.params())
    assert np.isfinite(p1).all()
    assert not np.allclose(p0, p1)  # actually trained


def test_local_sgd_freq1_equals_sync():
    """local-SGD path with freq=1 must equal the fused sync path (same math,
    different transport) — validates the shard_map implementation."""
    batches = _batches(3)
    netA = MultiLayerNetwork(_conf()).init()
    ParallelWrapper.builder(netA).workers(8).prefetch_buffer(0) \
        .averaging_frequency(1).build().fit(ListDataSetIterator(batches))

    netB = MultiLayerNetwork(_conf()).init()
    pw = ParallelWrapper(netB, workers=8, prefetch=0, averaging_frequency=2)
    # force the local-SGD machinery even for freq comparison: use freq=1 via local path
    pw.averaging_frequency = 1
    pw._fit_local_sgd(ListDataSetIterator(batches), epochs=1)
    np.testing.assert_allclose(np.asarray(netA.params()),
                               np.asarray(netB.params()), atol=1e-5)


def test_tensor_parallel_sharding_applies():
    from deeplearning4j_tpu.parallel.mesh import shard_params_for_tp

    mesh = build_mesh({"data": 4, "model": 2})
    net = MultiLayerNetwork(_conf()).init()
    sharded = shard_params_for_tp(net.params_list, net.conf, mesh)
    # dense W sharded over model axis on output dim
    w = sharded[0]["W"]
    assert w.shape == (6, 10)
    # forward still correct under sharding
    x = np.random.default_rng(0).normal(size=(8, 6)).astype(np.float32)
    ref = np.asarray(net.output(x))
    net.params_list = sharded
    net._jit_cache.clear()
    out = np.asarray(net.output(x))
    np.testing.assert_allclose(ref, out, atol=1e-6)


def test_local_sgd_multi_io_graph():
    """Multi-input/multi-output CG local-SGD (closes the round-2 wrapper
    NotImplementedError gate; reference ParallelWrapper handles MultiDataSet
    fit, ParallelWrapper.java:117): runs with averaging_frequency>1, params
    stay finite, and the model still learns."""
    from deeplearning4j_tpu.nn.conf.vertices import MergeVertex
    from deeplearning4j_tpu.nn.graph_network import (
        ComputationGraph, MultiDataSet)

    conf = (NeuralNetConfiguration.builder()
            .seed(4).learning_rate(0.1).updater("sgd")
            .graph_builder()
            .add_inputs("a", "b")
            .add_layer("da", DenseLayer(n_in=3, n_out=6, activation="tanh"),
                       "a")
            .add_layer("db", DenseLayer(n_in=2, n_out=6, activation="tanh"),
                       "b")
            .add_vertex("m", MergeVertex(), "da", "db")
            .add_layer("out", OutputLayer(n_in=12, n_out=2, loss="mcxent",
                                          activation="softmax"), "m")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(0)
    batches = []
    for _ in range(8):
        xa = rng.normal(size=(16, 3)).astype(np.float32)
        xb = rng.normal(size=(16, 2)).astype(np.float32)
        labels = (xa[:, 0] + xb[:, 0] > 0).astype(int)
        y = np.zeros((16, 2), np.float32)
        y[np.arange(16), labels] = 1
        batches.append(MultiDataSet([xa, xb], [y]))
    mds = MultiDataSet([np.concatenate([b.features[0] for b in batches])[:32],
                        np.concatenate([b.features[1] for b in batches])[:32]],
                       [np.concatenate([b.labels[0] for b in batches])[:32]])
    s0 = net.score(mds)
    pw = (ParallelWrapper.builder(net)
          .workers(8).prefetch_buffer(0).averaging_frequency(2)
          .build())
    for _ in range(6):
        pw.fit(ListDataSetIterator(batches))
    s1 = net.score(mds)
    assert np.isfinite(s1)
    assert s1 < s0, (s0, s1)


def test_hybrid_mesh_single_slice_fallback():
    """build_hybrid_mesh degrades to a plain product mesh on one slice (the
    CPU test environment) with identical axis names, and a DP-over-dcn x
    TP-over-ici sharded step still executes."""
    from deeplearning4j_tpu.parallel.mesh import build_hybrid_mesh

    mesh = build_hybrid_mesh({"data": 2, "model": 2}, {"data": 2})
    assert mesh.axis_names == ("data", "model")
    assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2

    from jax.sharding import NamedSharding, PartitionSpec as P
    x = jnp.arange(8 * 4, dtype=jnp.float32).reshape(8, 4)
    w = jnp.ones((4, 4), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P("data")))
    ws = jax.device_put(w, NamedSharding(mesh, P(None, "model")))
    y = jax.jit(jnp.matmul)(xs, ws)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x) @ np.asarray(w))

    with pytest.raises(ValueError, match="not present"):
        build_hybrid_mesh({"data": 2}, {"expert": 2})
