"""Dtype-policy tests: full-bf16 activations train correctly.

The reference has one global dtype (Nd4j data type); here the policy is the
TPU lever: bf16 matmuls (MXU) and optionally bf16 activations (halved HBM
traffic), with float32 params, norm statistics, and loss entry points.
Mirrors the reference's backend-equivalence testing discipline
(deeplearning4j-cuda CuDNNGradientChecks.java: accelerated path must match
the baseline path within tolerance).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deeplearning4j_tpu.common as C


@pytest.fixture(autouse=True)
def _restore_policy():
    yield
    C.set_policy(jnp.float32, jnp.float32, jnp.float32)


def _toy_batch(rng, n=16):
    x = rng.normal(size=(n, 784)).astype(np.float32)
    y = np.zeros((n, 10), np.float32)
    y[np.arange(n), rng.integers(0, 10, n)] = 1
    return x, y


def test_full_bf16_lenet_trains_and_keeps_f32_invariants():
    from deeplearning4j_tpu.models.lenet import lenet_mnist
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    C.full_bf16_policy()
    net = MultiLayerNetwork(lenet_mnist()).init()
    rng = np.random.default_rng(0)
    x, y = _toy_batch(rng)
    l0 = net.score(x, y)
    for _ in range(10):
        net.fit(x, y)
    l1 = net.score(x, y)
    assert l1 < l0, f"loss did not decrease under full_bf16: {l0} -> {l1}"
    # params (and therefore updater math) stay float32
    assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(net.params_list))
    # activations flow as bfloat16
    assert net.output(x).dtype == jnp.bfloat16


def test_full_bf16_batchnorm_state_stays_f32():
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (
        BatchNormalization, DenseLayer, OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    C.full_bf16_policy()
    conf = (NeuralNetConfiguration.builder().seed(1)
            .list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(BatchNormalization())
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 6)).astype(np.float32)
    y = np.zeros((32, 4), np.float32)
    y[np.arange(32), rng.integers(0, 4, 32)] = 1
    net.fit(x, y)
    bn_state = net.state_list[1]
    assert bn_state["mean"].dtype == jnp.float32
    assert bn_state["var"].dtype == jnp.float32
    # running stats actually moved (EMA update happened in f32)
    assert float(jnp.abs(bn_state["mean"]).sum()) > 0


def test_conf_declared_dtype_overrides_global_policy():
    """GlobalConf.dtype pins the network's programs to a named policy
    regardless of the ambient global policy, and serializes with the config
    (the declarative equivalent of the reference's one global Nd4j dtype)."""
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.conf.multilayer import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(3).dtype("bfloat16_full")
            .list()
            .layer(DenseLayer(n_in=6, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=4, activation="softmax",
                               loss="mcxent"))
            .build())
    # survives JSON round-trip
    conf2 = MultiLayerConfiguration.from_json(conf.to_json())
    assert conf2.global_conf.dtype == "bfloat16_full"

    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 6)).astype(np.float32)
    y = np.zeros((8, 4), np.float32)
    y[np.arange(8), rng.integers(0, 4, 8)] = 1

    net = MultiLayerNetwork(conf2).init()
    # ambient policy is f32; the conf-declared policy must win
    assert net.output(x).dtype == jnp.bfloat16
    l0 = net.score(x, y)
    for _ in range(5):
        net.fit(x, y)
    assert net.score(x, y) < l0
    assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(net.params_list))

    # typos fail fast at build time, not at first trace
    with pytest.raises(ValueError, match="Unknown dtype policy"):
        (NeuralNetConfiguration.builder().dtype("bf16").list()
         .layer(OutputLayer(n_in=2, n_out=2, loss="mse",
                            activation="identity")).build())


def test_peephole_lstm_trains_under_full_bf16():
    """GravesLSTM's peephole terms must not promote the scan carry dtype
    (bf16 carry + f32 peephole params would crash lax.scan at trace time)."""
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer

    C.full_bf16_policy()
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.builder().seed(5).list()
            .layer(GravesLSTM(n_in=6, n_out=8, activation="tanh"))
            .layer(RnnOutputLayer(n_in=8, n_out=6, loss="mcxent",
                                  activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 6, (4, 10))
    x = np.eye(6, dtype=np.float32)[ids]
    l0 = net.score(x, x)
    for _ in range(4):
        net.fit(x, x)
    assert net.score(x, x) < l0
    assert net.output(x).dtype == jnp.bfloat16


def test_full_bf16_forward_close_to_f32():
    """Same params, same input: bf16-activation forward stays within bf16
    tolerance of the f32 forward (the two programs compute the same math)."""
    from deeplearning4j_tpu.models.transformer import transformer_lm
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    rng = np.random.default_rng(0)
    ids = rng.integers(0, 50, (2, 16))
    x = np.eye(50, dtype=np.float32)[ids]

    net = MultiLayerNetwork(
        transformer_lm(vocab_size=50, width=64, n_layers=2, n_heads=2,
                       max_len=16)).init()
    ref = np.asarray(net.output(x), np.float32)

    # switching the policy must retrace automatically (jit cache is keyed on
    # the active policy, not just the program name)
    C.full_bf16_policy()
    got = np.asarray(net.output(x), np.float32)
    assert np.allclose(ref, got, atol=0.05, rtol=0.05), (
        np.abs(ref - got).max())
