"""Dtype-policy tests: full-bf16 activations train correctly.

The reference has one global dtype (Nd4j data type); here the policy is the
TPU lever: bf16 matmuls (MXU) and optionally bf16 activations (halved HBM
traffic), with float32 params, norm statistics, and loss entry points.
Mirrors the reference's backend-equivalence testing discipline
(deeplearning4j-cuda CuDNNGradientChecks.java: accelerated path must match
the baseline path within tolerance).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deeplearning4j_tpu.common as C


@pytest.fixture(autouse=True)
def _restore_policy():
    yield
    C.set_policy(jnp.float32, jnp.float32, jnp.float32,
                 reduction_dtype=None, grad_accum_dtype=None)


def _toy_batch(rng, n=16):
    x = rng.normal(size=(n, 784)).astype(np.float32)
    y = np.zeros((n, 10), np.float32)
    y[np.arange(n), rng.integers(0, 10, n)] = 1
    return x, y


def test_full_bf16_lenet_trains_and_keeps_f32_invariants():
    from deeplearning4j_tpu.models.lenet import lenet_mnist
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    C.full_bf16_policy()
    net = MultiLayerNetwork(lenet_mnist()).init()
    rng = np.random.default_rng(0)
    x, y = _toy_batch(rng)
    l0 = net.score(x, y)
    for _ in range(10):
        net.fit(x, y)
    l1 = net.score(x, y)
    assert l1 < l0, f"loss did not decrease under full_bf16: {l0} -> {l1}"
    # params (and therefore updater math) stay float32
    assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(net.params_list))
    # activations flow as bfloat16
    assert net.output(x).dtype == jnp.bfloat16


def test_full_bf16_batchnorm_state_stays_f32():
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (
        BatchNormalization, DenseLayer, OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    C.full_bf16_policy()
    conf = (NeuralNetConfiguration.builder().seed(1)
            .list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(BatchNormalization())
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 6)).astype(np.float32)
    y = np.zeros((32, 4), np.float32)
    y[np.arange(32), rng.integers(0, 4, 32)] = 1
    net.fit(x, y)
    bn_state = net.state_list[1]
    assert bn_state["mean"].dtype == jnp.float32
    assert bn_state["var"].dtype == jnp.float32
    # running stats actually moved (EMA update happened in f32)
    assert float(jnp.abs(bn_state["mean"]).sum()) > 0


def test_conf_declared_dtype_overrides_global_policy():
    """GlobalConf.dtype pins the network's programs to a named policy
    regardless of the ambient global policy, and serializes with the config
    (the declarative equivalent of the reference's one global Nd4j dtype)."""
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.conf.multilayer import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(3).dtype("bfloat16_full")
            .list()
            .layer(DenseLayer(n_in=6, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=4, activation="softmax",
                               loss="mcxent"))
            .build())
    # survives JSON round-trip
    conf2 = MultiLayerConfiguration.from_json(conf.to_json())
    assert conf2.global_conf.dtype == "bfloat16_full"

    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 6)).astype(np.float32)
    y = np.zeros((8, 4), np.float32)
    y[np.arange(8), rng.integers(0, 4, 8)] = 1

    net = MultiLayerNetwork(conf2).init()
    # ambient policy is f32; the conf-declared policy must win
    assert net.output(x).dtype == jnp.bfloat16
    l0 = net.score(x, y)
    for _ in range(5):
        net.fit(x, y)
    assert net.score(x, y) < l0
    assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(net.params_list))

    # typos fail fast at build time, not at first trace
    with pytest.raises(ValueError, match="Unknown dtype policy"):
        (NeuralNetConfiguration.builder().dtype("bf16").list()
         .layer(OutputLayer(n_in=2, n_out=2, loss="mse",
                            activation="identity")).build())


def test_peephole_lstm_trains_under_full_bf16():
    """GravesLSTM's peephole terms must not promote the scan carry dtype
    (bf16 carry + f32 peephole params would crash lax.scan at trace time)."""
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer

    C.full_bf16_policy()
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.builder().seed(5).list()
            .layer(GravesLSTM(n_in=6, n_out=8, activation="tanh"))
            .layer(RnnOutputLayer(n_in=8, n_out=6, loss="mcxent",
                                  activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 6, (4, 10))
    x = np.eye(6, dtype=np.float32)[ids]
    l0 = net.score(x, x)
    for _ in range(4):
        net.fit(x, x)
    assert net.score(x, x) < l0
    assert net.output(x).dtype == jnp.bfloat16


def test_full_bf16_forward_close_to_f32():
    """Same params, same input: bf16-activation forward stays within bf16
    tolerance of the f32 forward (the two programs compute the same math)."""
    from deeplearning4j_tpu.models.transformer import transformer_lm
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    rng = np.random.default_rng(0)
    ids = rng.integers(0, 50, (2, 16))
    x = np.eye(50, dtype=np.float32)[ids]

    net = MultiLayerNetwork(
        transformer_lm(vocab_size=50, width=64, n_layers=2, n_heads=2,
                       max_len=16)).init()
    ref = np.asarray(net.output(x), np.float32)

    # switching the policy must retrace automatically (jit cache is keyed on
    # the active policy, not just the program name)
    C.full_bf16_policy()
    got = np.asarray(net.output(x), np.float32)
    assert np.allclose(ref, got, atol=0.05, rtol=0.05), (
        np.abs(ref - got).max())


def test_flagship_policy_serde_key_and_sentinels():
    """The reduction-precision knobs are first-class policy state: named
    policy resolution, config-JSON round-trip, jit-cache key identity, and
    set_policy's unset-sentinel semantics (None IS a meaningful value)."""
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.conf.multilayer import MultiLayerConfiguration

    pol = C.resolve_policy("bfloat16_flagship")
    assert pol.reduction_dtype == jnp.bfloat16
    assert pol.grad_accum_dtype == jnp.float32

    conf = (NeuralNetConfiguration.builder().seed(7)
            .dtype("bfloat16_flagship").list()
            .layer(DenseLayer(n_in=6, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=4, activation="softmax",
                               loss="mcxent"))
            .build())
    conf2 = MultiLayerConfiguration.from_json(conf.to_json())
    assert conf2.global_conf.dtype == "bfloat16_flagship"

    # the compiled-program cache key distinguishes the knobs: flagship and
    # full-bf16 share storage dtypes but must never share traced programs
    C.flagship_bf16_policy()
    k_flag = C.policy_key()
    C.full_bf16_policy()
    k_full = C.policy_key()
    assert k_flag[:3] == k_full[:3]
    assert k_flag != k_full
    assert k_flag[3:] == ("bfloat16", "float32")
    assert k_full[3:] == (None, None)

    # updating a storage dtype must not clobber the knobs (unset sentinel)...
    C.flagship_bf16_policy()
    C.set_policy(param_dtype=jnp.float32)
    assert C.get_policy().reduction_dtype == jnp.bfloat16
    assert C.get_policy().grad_accum_dtype == jnp.float32
    # ...while an explicit None clears them
    C.set_policy(reduction_dtype=None, grad_accum_dtype=None)
    assert C.get_policy().reduction_dtype is None
    assert C.get_policy().grad_accum_dtype is None

    # accum_dtype only ever WIDENS: wide operands lower exactly as before
    C.flagship_bf16_policy()
    assert C.accum_dtype(jnp.bfloat16) == jnp.float32
    assert C.accum_dtype(jnp.float32) is None
    assert C.accum_dtype(jnp.float64) is None
    # stat_dtype: explicit bf16 wins, except the f64 gradcheck path
    assert C.get_policy().stat_dtype(jnp.bfloat16) == jnp.bfloat16
    assert C.get_policy().stat_dtype(jnp.float32) == jnp.bfloat16
    assert C.get_policy().stat_dtype(jnp.float64) == jnp.float64


def test_bn_reduction_numerics_bounds():
    """Pins the accuracy cost of the reduction_dtype knob: f32 single-pass
    statistics on bf16 activations are exact to ~1e-5 of the f64 reference,
    bf16 statistics are within bf16-accumulation tolerance — bounded, and
    measurably worse than f32 (the knob is a real precision/speed trade)."""
    from deeplearning4j_tpu.ops.pallas_kernels import batch_norm_train

    rng = np.random.default_rng(0)
    xb = jnp.asarray(rng.normal(size=(512, 16)), jnp.bfloat16)
    g = jnp.ones((16,), jnp.float32)
    b = jnp.zeros((16,), jnp.float32)
    x64 = np.asarray(xb, np.float64)
    ref_m, ref_v = x64.mean(0), x64.var(0)

    _, m32, v32 = batch_norm_train(xb, g, b, (0,), 1e-5, jnp.float32)
    _, m16, v16 = batch_norm_train(xb, g, b, (0,), 1e-5, jnp.bfloat16)
    assert m32.dtype == jnp.float32 and m16.dtype == jnp.bfloat16

    e32m = np.abs(np.asarray(m32, np.float64) - ref_m).max()
    e16m = np.abs(np.asarray(m16, np.float64) - ref_m).max()
    e32v = np.abs(np.asarray(v32, np.float64) - ref_v).max()
    e16v = np.abs(np.asarray(v16, np.float64) - ref_v).max()
    assert e32m <= 1e-6, e32m
    assert e32v <= 1e-5, e32v
    assert e16m <= 2e-2, e16m
    assert e16v <= 5e-1, e16v
    assert e16m > e32m and e16v > e32v
    # E[x^2] - mean^2 cancellation is clamped: variance never goes negative
    assert float(np.asarray(v16, np.float64).min()) >= 0.0


def test_bn_hlo_single_fused_reduce_no_f32_upcast():
    """HLO regression for the tentpole: under the flagship policy, BN
    fwd+bwd on a bf16 activation lowers to exactly TWO variadic reduces
    (fwd sum/sum-sq, bwd dbeta/dgamma), both bf16 end-to-end — no standalone
    f32 convert-the-whole-tensor-then-reduce fusion anywhere (23% of r5
    ResNet-50 bf16 device time)."""
    import re

    from deeplearning4j_tpu.nn.conf.layers.normalization import (
        BatchNormalization)

    C.flagship_bf16_policy()
    bn = BatchNormalization(n_in=16)
    params = {"gamma": jnp.ones((16,), jnp.float32),
              "beta": jnp.zeros((16,), jnp.float32)}
    state = {"mean": jnp.zeros((16,), jnp.float32),
             "var": jnp.ones((16,), jnp.float32)}

    def fwd_bwd(params, x, dy):
        def f(p, xx):
            out, _ = bn.apply(p, state, xx, train=True)
            return out
        out, vjp = jax.vjp(f, params, x)
        return out, vjp(dy)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(64, 16)), jnp.bfloat16)
    dy = jnp.ones_like(x)
    txt = jax.jit(fwd_bwd).lower(params, x, dy).as_text()

    reduce_ops = re.findall(r"stablehlo\.reduce[^\n]*", txt)
    assert len(reduce_ops) == 2, txt
    for op in reduce_ops:
        assert "f32" not in op, op  # reduce operands/results all bf16
    # nothing upcasts the full activation tensor to f32 anywhere in the
    # program (the old two-pass mean/var path materialized exactly that)
    assert "tensor<64x16xf32>" not in txt


def test_flagship_weight_grads_accumulate_f32():
    """preferred_element_type routing: under the flagship policy, the dense
    forward is a bf16 x bf16 -> f32 contraction and BOTH transpose-rule
    contractions (dW, dx) accumulate f32; under full_bf16 (knobs cleared)
    the very same program stays all-bf16, unchanged from before."""
    import re

    from deeplearning4j_tpu.nn.conf.layers.feedforward import _dense

    rng = np.random.default_rng(0)
    params = {"W": jnp.asarray(rng.normal(size=(8, 4)), jnp.float32),
              "b": jnp.zeros((4,), jnp.float32)}
    x = jnp.asarray(rng.normal(size=(16, 8)), jnp.bfloat16)

    def make(tag):
        def fwd_and_grads(p, x, _tag=tag):
            def loss(p, xx):
                return _dense(p, xx).astype(jnp.float32).sum()
            return jax.value_and_grad(loss, argnums=(0, 1))(p, x)
        return fwd_and_grads

    def dot_sigs(txt):
        return re.findall(r"dot_general[^\n]*-> (tensor<[^>]*>)", txt)

    C.flagship_bf16_policy()
    txt = jax.jit(make("flagship")).lower(params, x).as_text()
    sigs = dot_sigs(txt)
    assert sigs and all(s.endswith("xf32>") for s in sigs), sigs
    assert re.search(r"\(tensor<[^)]*xbf16>, tensor<[^)]*xbf16>\)"
                     r" -> tensor<[^>]*xf32>", txt), "forward not bf16->f32"

    C.full_bf16_policy()
    txt = jax.jit(make("full")).lower(params, x).as_text()
    sigs = dot_sigs(txt)
    assert sigs and all(s.endswith("xbf16>") for s in sigs), sigs


def test_flagship_bf16_lenet_trains():
    """End-to-end acceptance: conv + BN-free lenet trains under the flagship
    policy (bf16 statistics + f32-pinned weight-grad accumulation through
    the custom conv vjp), params stay f32, activations flow bf16."""
    from deeplearning4j_tpu.models.lenet import lenet_mnist
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    C.flagship_bf16_policy()
    net = MultiLayerNetwork(lenet_mnist()).init()
    rng = np.random.default_rng(0)
    x, y = _toy_batch(rng)
    l0 = net.score(x, y)
    for _ in range(10):
        net.fit(x, y)
    assert net.score(x, y) < l0
    assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(net.params_list))
    assert net.output(x).dtype == jnp.bfloat16


def test_flagship_batchnorm_net_matches_f32_reference():
    """A BN network under the flagship policy stays close to its f32 run
    (same init): bf16 single-pass statistics change numerics within bf16
    tolerance, not semantics. EMA state stays f32."""
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (
        BatchNormalization, DenseLayer, OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    def build():
        conf = (NeuralNetConfiguration.builder().seed(11)
                .list()
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(BatchNormalization())
                .layer(OutputLayer(n_out=4, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(6)).build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 6)).astype(np.float32)
    y = np.zeros((32, 4), np.float32)
    y[np.arange(32), rng.integers(0, 4, 32)] = 1

    ref = build()
    for _ in range(3):
        ref.fit(x, y)
    ref_out = np.asarray(ref.output(x), np.float32)

    C.flagship_bf16_policy()
    net = build()
    for _ in range(3):
        net.fit(x, y)
    got = np.asarray(net.output(x), np.float32)
    assert np.allclose(ref_out, got, atol=0.06, rtol=0.06), (
        np.abs(ref_out - got).max())
    bn_state = net.state_list[1]
    assert bn_state["mean"].dtype == jnp.float32
    assert bn_state["var"].dtype == jnp.float32
