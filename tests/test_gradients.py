"""Gradient-check battery: numeric vs analytic gradients per layer family.

Reference: deeplearning4j-core gradientcheck/{GradientCheckTests, CNNGradientCheckTest,
BNGradientCheckTest, GradientCheckTestsMasking, LossFunctionGradientCheck}.java —
the reference's correctness backbone (SURVEY.md §4), reproduced against JAX autodiff.
"""
import jax
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization, ConvolutionLayer, DenseLayer, GravesLSTM, OutputLayer,
    RnnOutputLayer, SubsamplingLayer,
)
from deeplearning4j_tpu.nn.gradientcheck import check_gradients
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

SEED = 7


def build(layers, input_type=None, **global_kw):
    b = NeuralNetConfiguration.builder().seed(SEED)
    for k, v in global_kw.items():
        b = getattr(b, k)(v)
    lb = b.list()
    for l in layers:
        lb = lb.layer(l)
    if input_type is not None:
        lb = lb.set_input_type(input_type)
    net = MultiLayerNetwork(lb.build())
    net.init()
    return net


def rand(shape, seed=0):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


def onehot(n, c, seed=1):
    rng = np.random.default_rng(seed)
    y = np.zeros((n, c), np.float32)
    y[np.arange(n), rng.integers(0, c, n)] = 1
    return y


class TestGradientCheckMLP:
    def test_dense_softmax_mcxent(self):
        net = build([DenseLayer(n_in=4, n_out=6, activation="tanh"),
                     OutputLayer(n_in=6, n_out=3, loss="mcxent", activation="softmax")])
        assert check_gradients(net, rand((5, 4)), onehot(5, 3), verbose=True)

    def test_dense_sigmoid_xent(self):
        net = build([DenseLayer(n_in=4, n_out=6, activation="relu"),
                     OutputLayer(n_in=6, n_out=2, loss="xent", activation="sigmoid")])
        y = (np.random.default_rng(2).uniform(size=(5, 2)) > 0.5).astype(np.float32)
        assert check_gradients(net, rand((5, 4)), y)

    def test_mse_identity(self):
        net = build([DenseLayer(n_in=3, n_out=5, activation="tanh"),
                     OutputLayer(n_in=5, n_out=2, loss="mse", activation="identity")])
        assert check_gradients(net, rand((4, 3)), rand((4, 2), seed=3))

    def test_with_l1_l2(self):
        net = build([DenseLayer(n_in=4, n_out=5, activation="sigmoid", l1=0.01, l2=0.02),
                     OutputLayer(n_in=5, n_out=3, loss="mcxent", activation="softmax",
                                 l1=0.01, l2=0.02)],
                    use_regularization=True)
        assert check_gradients(net, rand((5, 4)), onehot(5, 3))


class TestGradientCheckCNN:
    def test_cnn_dense_output(self):
        net = build([ConvolutionLayer(n_out=3, kernel_size=(2, 2), stride=(1, 1),
                                      activation="tanh"),
                     SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                      stride=(2, 2)),
                     DenseLayer(n_out=8, activation="relu"),
                     OutputLayer(n_out=2, loss="mcxent", activation="softmax")],
                    input_type=InputType.convolutional(6, 6, 2))
        x = rand((3, 6, 6, 2))
        assert check_gradients(net, x, onehot(3, 2), subset=60, verbose=True)

    def test_batchnorm(self):
        net = build([DenseLayer(n_in=4, n_out=6, activation="identity"),
                     BatchNormalization(n_in=6),
                     OutputLayer(n_in=6, n_out=3, loss="mcxent", activation="softmax")])
        assert check_gradients(net, rand((8, 4)), onehot(8, 3), subset=40)

    @pytest.mark.parametrize("mode", ["same", "truncate"])
    def test_convolution_modes(self, mode):
        """ConvolutionMode parity (reference CNNGradientCheckTest runs the
        battery per mode; nn/conf/ConvolutionMode.java)."""
        net = build([ConvolutionLayer(n_out=3, kernel_size=(3, 3),
                                      stride=(2, 2), convolution_mode=mode,
                                      activation="tanh"),
                     DenseLayer(n_out=6, activation="relu"),
                     OutputLayer(n_out=2, loss="mcxent",
                                 activation="softmax")],
                    input_type=InputType.convolutional(7, 7, 2))
        assert check_gradients(net, rand((3, 7, 7, 2)), onehot(3, 2),
                               subset=60, verbose=True)

    @pytest.mark.parametrize("pooling", ["max", "avg", "pnorm"])
    def test_pooling_types(self, pooling):
        """All reference PoolingTypes backprop correctly through
        lax.reduce_window (reference SubsamplingLayer pooling battery)."""
        net = build([ConvolutionLayer(n_out=2, kernel_size=(2, 2),
                                      stride=(1, 1), activation="tanh"),
                     SubsamplingLayer(pooling_type=pooling, kernel_size=(2, 2),
                                      stride=(2, 2), pnorm=2),
                     OutputLayer(n_out=2, loss="mcxent",
                                 activation="softmax")],
                    input_type=InputType.convolutional(5, 5, 1))
        assert check_gradients(net, rand((3, 5, 5, 1)), onehot(3, 2),
                               subset=60, verbose=True)

    @pytest.mark.parametrize("pooling", ["avg", "max", "sum"])
    def test_global_pooling(self, pooling):
        from deeplearning4j_tpu.nn.conf.layers import GlobalPoolingLayer
        net = build([ConvolutionLayer(n_out=3, kernel_size=(2, 2),
                                      stride=(1, 1), activation="tanh"),
                     GlobalPoolingLayer(pooling_type=pooling),
                     OutputLayer(n_out=2, loss="mcxent",
                                 activation="softmax")],
                    input_type=InputType.convolutional(5, 5, 2))
        assert check_gradients(net, rand((3, 5, 5, 2)), onehot(3, 2),
                               subset=60)

    def test_upsampling_zeropadding(self):
        from deeplearning4j_tpu.nn.conf.layers import (
            Upsampling2D, ZeroPaddingLayer)
        net = build([ZeroPaddingLayer(padding=(1, 1)),
                     ConvolutionLayer(n_out=2, kernel_size=(3, 3),
                                      stride=(1, 1), activation="tanh"),
                     Upsampling2D(size=(2, 2)),
                     DenseLayer(n_out=6, activation="relu"),
                     OutputLayer(n_out=2, loss="mcxent",
                                 activation="softmax")],
                    input_type=InputType.convolutional(4, 4, 1))
        assert check_gradients(net, rand((2, 4, 4, 1)), onehot(2, 2),
                               subset=60)

    def test_dilated_convolution(self):
        net = build([ConvolutionLayer(n_out=3, kernel_size=(2, 2),
                                      stride=(1, 1), dilation=(2, 2),
                                      activation="tanh"),
                     DenseLayer(n_out=6, activation="relu"),
                     OutputLayer(n_out=2, loss="mcxent",
                                 activation="softmax")],
                    input_type=InputType.convolutional(7, 7, 1))
        assert check_gradients(net, rand((2, 7, 7, 1)), onehot(2, 2),
                               subset=60)


class TestGradientCheckRNN:
    def test_lstm_rnn_output(self):
        net = build([GravesLSTM(n_in=3, n_out=4, activation="tanh"),
                     RnnOutputLayer(n_in=4, n_out=2, loss="mcxent",
                                    activation="softmax")])
        x = rand((2, 5, 3))
        rng = np.random.default_rng(4)
        y = np.zeros((2, 5, 2), np.float32)
        idx = rng.integers(0, 2, (2, 5))
        for b in range(2):
            for t in range(5):
                y[b, t, idx[b, t]] = 1
        assert check_gradients(net, x, y, subset=60, verbose=True)

    def test_lstm_masked(self):
        from deeplearning4j_tpu.nn.multilayer import loss_fn
        import jax.numpy as jnp

        net = build([GravesLSTM(n_in=3, n_out=4, activation="tanh"),
                     RnnOutputLayer(n_in=4, n_out=2, loss="mcxent",
                                    activation="softmax")])
        x = rand((2, 4, 3))
        y = np.zeros((2, 4, 2), np.float32)
        y[..., 0] = 1
        mask = np.array([[1, 1, 0, 0], [1, 1, 1, 1]], np.float32)

        # analytic gradient wrt masked-out timestep inputs must not affect loss:
        loss1, _ = loss_fn(net.conf, net.params_list, net.state_list,
                           jnp.asarray(x), jnp.asarray(y), None,
                           jnp.asarray(mask), jnp.asarray(mask))
        x2 = x.copy()
        x2[0, 3] += 100.0  # perturb masked timestep
        loss2, _ = loss_fn(net.conf, net.params_list, net.state_list,
                           jnp.asarray(x2), jnp.asarray(y), None,
                           jnp.asarray(mask), jnp.asarray(mask))
        np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-6)


class TestGradientCheckAttentionMoE:
    def test_self_attention_block(self):
        from deeplearning4j_tpu.nn.conf.layers import SelfAttentionLayer
        net = build([SelfAttentionLayer(n_in=6, n_out=6, n_heads=2,
                                        causal=True, activation="identity"),
                     RnnOutputLayer(n_in=6, n_out=3, loss="mcxent",
                                    activation="softmax")],
                    input_type=InputType.recurrent(6, 5))
        x = rand((2, 5, 6), seed=11)
        y = np.zeros((2, 5, 3), np.float32)
        y[..., 0] = 1
        check_gradients(net, x, y)

    def test_transformer_block(self):
        from deeplearning4j_tpu.nn.conf.layers import TransformerBlock
        net = build([TransformerBlock(n_in=6, n_out=6, n_heads=2,
                                      ffn_multiplier=2, causal=True),
                     RnnOutputLayer(n_in=6, n_out=3, loss="mcxent",
                                    activation="softmax")],
                    input_type=InputType.recurrent(6, 4))
        x = rand((2, 4, 6), seed=12)
        y = np.zeros((2, 4, 3), np.float32)
        y[..., 1] = 1
        check_gradients(net, x, y)

    def test_moe_transformer_block(self):
        from deeplearning4j_tpu.nn.conf.layers.moe import MoETransformerBlock
        net = build([MoETransformerBlock(n_in=6, n_out=6, n_heads=2,
                                         n_experts=3, expert_hidden=8,
                                         causal=True, activation="identity")
                     ,
                     RnnOutputLayer(n_in=6, n_out=3, loss="mcxent",
                                    activation="softmax")],
                    input_type=InputType.recurrent(6, 4))
        x = rand((2, 4, 6), seed=14)
        y = np.zeros((2, 4, 3), np.float32)
        y[..., 2] = 1
        assert check_gradients(net, x, y, subset=60)

    def test_moe_layer(self):
        from deeplearning4j_tpu.nn.conf.layers.moe import MoELayer
        net = build([MoELayer(n_in=6, n_out=6, n_experts=3, expert_hidden=8,
                              activation="identity"),
                     RnnOutputLayer(n_in=6, n_out=3, loss="mcxent",
                                    activation="softmax")],
                    input_type=InputType.recurrent(6, 4))
        x = rand((2, 4, 6), seed=13)
        y = np.zeros((2, 4, 3), np.float32)
        y[..., 2] = 1
        # router argmax is piecewise-constant but a.e. differentiable; with
        # eps=1e-6 in f64 no routing flip occurs at this seed
        check_gradients(net, x, y)


class TestGradientCheckPretrain:
    """Pretrain-objective gradient checks (reference VaeGradientCheckTests.java,
    GradientCheckUtil.checkGradientsPretrainLayer:305)."""

    def test_vae_gaussian(self):
        from deeplearning4j_tpu.nn.conf.layers import VariationalAutoencoder
        from deeplearning4j_tpu.nn.gradientcheck import check_pretrain_gradients
        net = build([VariationalAutoencoder(
                        n_in=5, n_out=3, encoder_layer_sizes=(6,),
                        decoder_layer_sizes=(6,), activation="tanh",
                        reconstruction_distribution="gaussian"),
                     OutputLayer(n_in=3, n_out=2, loss="mcxent",
                                 activation="softmax")])
        assert check_pretrain_gradients(net, 0, rand((4, 5)), subset=60,
                                        verbose=True)

    def test_vae_bernoulli(self):
        from deeplearning4j_tpu.nn.conf.layers import VariationalAutoencoder
        from deeplearning4j_tpu.nn.gradientcheck import check_pretrain_gradients
        net = build([VariationalAutoencoder(
                        n_in=5, n_out=3, encoder_layer_sizes=(6,),
                        decoder_layer_sizes=(6,), activation="tanh",
                        reconstruction_distribution="bernoulli"),
                     OutputLayer(n_in=3, n_out=2, loss="mcxent",
                                 activation="softmax")])
        x = (np.random.default_rng(3).uniform(size=(4, 5)) > 0.5) \
            .astype(np.float32)
        assert check_pretrain_gradients(net, 0, x, subset=60)

    def test_vae_exponential_and_composite(self):
        from deeplearning4j_tpu.nn.conf.layers import VariationalAutoencoder
        from deeplearning4j_tpu.nn.conf.layers.variational import (
            BernoulliReconstructionDistribution,
            CompositeReconstructionDistribution,
            ExponentialReconstructionDistribution,
            GaussianReconstructionDistribution,
        )
        from deeplearning4j_tpu.nn.gradientcheck import check_pretrain_gradients

        comp = (CompositeReconstructionDistribution()
                .add(2, GaussianReconstructionDistribution())
                .add(2, BernoulliReconstructionDistribution())
                .add(2, ExponentialReconstructionDistribution()))
        net = build([VariationalAutoencoder(
                        n_in=6, n_out=3, encoder_layer_sizes=(5,),
                        decoder_layer_sizes=(5,), activation="tanh",
                        reconstruction_distribution=comp),
                     OutputLayer(n_in=3, n_out=2, loss="mcxent",
                                 activation="softmax")])
        rng = np.random.default_rng(4)
        x = np.concatenate([
            rng.normal(size=(4, 2)),                       # gaussian slice
            (rng.uniform(size=(4, 2)) > 0.5).astype(float),  # bernoulli
            rng.exponential(size=(4, 2)),                  # exponential
        ], axis=1).astype(np.float32)
        assert check_pretrain_gradients(net, 0, x, subset=80, verbose=True)

    def test_autoencoder(self):
        from deeplearning4j_tpu.nn.conf.layers import AutoEncoder
        from deeplearning4j_tpu.nn.gradientcheck import check_pretrain_gradients
        net = build([AutoEncoder(n_in=5, n_out=4, activation="sigmoid",
                                 corruption_level=0.3),
                     OutputLayer(n_in=4, n_out=2, loss="mcxent",
                                 activation="softmax")])
        assert check_pretrain_gradients(net, 0, rand((6, 5)), subset=50)

    def test_rbm_cd_surrogate_matches_cd_update(self):
        """RBM's CD-1 surrogate is NOT a finite-differencable loss (the
        Gibbs chain is data under stop_gradient); instead verify autodiff of
        the surrogate reproduces the hand-derived CD update
        dW = -(<v+ h+> - <v- h->)/n etc. (reference RBM.java
        computeGradientAndScore)."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.nn.conf.layers import RBM
        layer = RBM(n_in=4, n_out=3, k=1, activation="sigmoid")
        layer.weight_init = "xavier"
        params = layer.init_params(jax.random.PRNGKey(0),
                                   InputType.feed_forward(4))
        rng = np.random.default_rng(5)
        x = jnp.asarray((rng.uniform(size=(6, 4)) > 0.5).astype(np.float32))
        key = jax.random.PRNGKey(9)
        grads = jax.grad(lambda p: layer.pretrain_loss(p, x, rng=key))(params)

        # replicate the chain deterministically (same keys, same sampling)
        def sample(k, p):
            return jax.random.bernoulli(k, p).astype(p.dtype)

        keys = jax.random.split(key, 3)
        ph = layer.prop_up(params, x)
        hk = sample(keys[0], ph)
        vk = layer.prop_down(params, hk)
        vk = sample(keys[1], vk)
        hk_prob = layer.prop_up(params, vk)
        n = x.shape[0]
        expect_dW = -(np.asarray(jnp.matmul(x.T, ph))
                      - np.asarray(jnp.matmul(vk.T, hk_prob))) / n
        expect_dvb = -(np.asarray(jnp.mean(x, 0)) - np.asarray(jnp.mean(vk, 0)))
        expect_db = -(np.asarray(jnp.mean(ph, 0))
                      - np.asarray(jnp.mean(hk_prob, 0)))
        np.testing.assert_allclose(np.asarray(grads["W"]), expect_dW,
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(grads["vb"]), expect_dvb,
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(grads["b"]), expect_db,
                                   rtol=1e-5, atol=1e-6)


class TestGradientCheckLRN:
    def test_lrn_in_cnn_stack(self):
        from deeplearning4j_tpu.nn.conf.layers import (
            LocalResponseNormalization)
        net = build([ConvolutionLayer(n_out=4, kernel_size=(2, 2),
                                      stride=(1, 1), activation="tanh"),
                     LocalResponseNormalization(n=3),
                     DenseLayer(n_out=6, activation="relu"),
                     OutputLayer(n_out=2, loss="mcxent",
                                 activation="softmax")],
                    input_type=InputType.convolutional(5, 5, 2))
        assert check_gradients(net, rand((3, 5, 5, 2)), onehot(3, 2),
                               subset=60, verbose=True)


def _labels_for(loss: str, n: int, c: int, seed: int = 11) -> np.ndarray:
    """Valid-label generator per loss family (reference
    LossFunctionGradientCheck.java builds exactly such a table: each
    ILossFunction gets labels from its domain)."""
    rng = np.random.default_rng(seed)
    if loss in ("mcxent", "negativeloglikelihood", "kl_divergence"):
        p = rng.uniform(0.1, 1.0, size=(n, c))
        return (p / p.sum(axis=1, keepdims=True)).astype(np.float32)
    if loss in ("xent", "reconstruction_crossentropy"):
        return (rng.uniform(size=(n, c)) > 0.5).astype(np.float32)
    if loss in ("hinge", "squared_hinge"):
        return (2 * (rng.uniform(size=(n, c)) > 0.5) - 1).astype(np.float32)
    if loss == "poisson":
        return rng.integers(0, 4, size=(n, c)).astype(np.float32)
    if loss in ("mape",):
        return rng.uniform(0.5, 2.0, size=(n, c)).astype(np.float32)
    if loss in ("msle",):
        return rng.uniform(0.0, 2.0, size=(n, c)).astype(np.float32)
    return rng.normal(size=(n, c)).astype(np.float32)


class TestLossFunctionGradientCheck:
    """Every loss x compatible output activation, numeric vs analytic
    (reference gradientcheck/LossFunctionGradientCheck.java — the full
    ILossFunction battery)."""

    CASES = [
        ("mse", "identity"), ("mse", "tanh"),
        ("l2", "identity"),
        ("mae", "identity"),
        ("l1", "identity"),
        ("mape", "sigmoid"),
        ("msle", "softplus"),
        ("mcxent", "softmax"),
        ("negativeloglikelihood", "softmax"),
        ("xent", "sigmoid"),
        ("reconstruction_crossentropy", "sigmoid"),
        ("hinge", "identity"),
        ("squared_hinge", "identity"),
        ("kl_divergence", "softmax"),
        ("poisson", "softplus"),
        ("cosine_proximity", "identity"),
    ]

    @pytest.mark.parametrize("loss,act", CASES,
                             ids=[f"{l}-{a}" for l, a in CASES])
    def test_loss_gradients(self, loss, act):
        net = build([DenseLayer(n_in=4, n_out=6, activation="tanh"),
                     OutputLayer(n_in=6, n_out=3, loss=loss, activation=act)])
        y = _labels_for(loss, 5, 3)
        assert check_gradients(net, rand((5, 4)), y, verbose=True)


class TestGradientCheckpointing:
    """jax.checkpoint remat (gradient_checkpointing conf flag) must be
    gradient-invisible: identical loss and gradients, only memory/FLOPs
    change."""

    def test_mln_remat_gradients_identical(self):
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.nn.multilayer import loss_fn

        def build(remat):
            conf = (NeuralNetConfiguration.builder()
                    .seed(7).learning_rate(0.05)
                    .gradient_checkpointing(remat)
                    .list()
                    .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
                    .layer(DenseLayer(n_in=8, n_out=8, activation="relu"))
                    .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                                       activation="softmax"))
                    .build())
            return MultiLayerNetwork(conf).init()

        x = jnp.asarray(rand((6, 4)))
        y = jnp.asarray(onehot(6, 3))
        nets = [build(False), build(True)]
        outs = []
        for net in nets:
            g = jax.grad(lambda p, n=net: loss_fn(n.conf, p, n.state_list,
                                                  x, y, None)[0])(
                net.params_list)
            outs.append(g)
        for a, b in zip(jax.tree_util.tree_leaves(outs[0]),
                        jax.tree_util.tree_leaves(outs[1])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)

    def test_graph_remat_training_matches(self):
        import jax

        from deeplearning4j_tpu.nn.graph_network import ComputationGraph

        def build(remat):
            conf = (NeuralNetConfiguration.builder()
                    .seed(7).learning_rate(0.05).updater("sgd")
                    .gradient_checkpointing(remat)
                    .graph_builder()
                    .add_inputs("in")
                    .add_layer("d1", DenseLayer(n_in=4, n_out=8,
                                                activation="tanh"), "in")
                    .add_layer("out", OutputLayer(n_in=8, n_out=3,
                                                  loss="mcxent",
                                                  activation="softmax"), "d1")
                    .set_outputs("out")
                    .build())
            return ComputationGraph(conf).init()

        x = rand((6, 4), seed=5)
        y = onehot(6, 3, seed=6)
        nets = [build(False), build(True)]
        for net in nets:
            for _ in range(3):
                net.fit([x], [y])
        for a, b in zip(jax.tree_util.tree_leaves(nets[0].params_list),
                        jax.tree_util.tree_leaves(nets[1].params_list)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)


def test_graph_vertex_pretrain_gradients():
    """CG pretrain objectives gradient-check per vertex (reference
    GradientCheckUtil.checkGradientsPretrainLayer applied to graph vertices).
    The RBM vertex is excluded from FD checking — its CD surrogate is not a
    true loss (see test_rbm_cd_surrogate_matches_cd_update); its graph-
    pretrain path is covered by the descent test in test_computation_graph."""
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import (
        AutoEncoder, OutputLayer, VariationalAutoencoder,
    )
    from deeplearning4j_tpu.nn.gradientcheck import check_graph_pretrain_gradients
    from deeplearning4j_tpu.nn.graph_network import ComputationGraph

    rng = np.random.default_rng(2)
    x = rng.normal(size=(4, 5)).astype(np.float64)
    conf = (NeuralNetConfiguration.builder()
            .seed(3).learning_rate(0.05)
            .graph_builder()
            .add_inputs("in")
            .add_layer("vae", VariationalAutoencoder(
                n_in=5, n_out=4, encoder_layer_sizes=(6,),
                decoder_layer_sizes=(6,)), "in")
            .add_layer("ae", AutoEncoder(n_in=4, n_out=4,
                                         activation="sigmoid"), "vae")
            .add_layer("out", OutputLayer(n_in=4, n_out=3, loss="mcxent",
                                          activation="softmax"), "ae")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    assert check_graph_pretrain_gradients(net, "vae", [x], subset=60)
    assert check_graph_pretrain_gradients(net, "ae", [x], subset=60)
