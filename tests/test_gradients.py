"""Gradient-check battery: numeric vs analytic gradients per layer family.

Reference: deeplearning4j-core gradientcheck/{GradientCheckTests, CNNGradientCheckTest,
BNGradientCheckTest, GradientCheckTestsMasking, LossFunctionGradientCheck}.java —
the reference's correctness backbone (SURVEY.md §4), reproduced against JAX autodiff.
"""
import jax
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization, ConvolutionLayer, DenseLayer, GravesLSTM, OutputLayer,
    RnnOutputLayer, SubsamplingLayer,
)
from deeplearning4j_tpu.nn.gradientcheck import check_gradients
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

SEED = 7


def build(layers, input_type=None, **global_kw):
    b = NeuralNetConfiguration.builder().seed(SEED)
    for k, v in global_kw.items():
        b = getattr(b, k)(v)
    lb = b.list()
    for l in layers:
        lb = lb.layer(l)
    if input_type is not None:
        lb = lb.set_input_type(input_type)
    net = MultiLayerNetwork(lb.build())
    net.init()
    return net


def rand(shape, seed=0):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


def onehot(n, c, seed=1):
    rng = np.random.default_rng(seed)
    y = np.zeros((n, c), np.float32)
    y[np.arange(n), rng.integers(0, c, n)] = 1
    return y


class TestGradientCheckMLP:
    def test_dense_softmax_mcxent(self):
        net = build([DenseLayer(n_in=4, n_out=6, activation="tanh"),
                     OutputLayer(n_in=6, n_out=3, loss="mcxent", activation="softmax")])
        assert check_gradients(net, rand((5, 4)), onehot(5, 3), verbose=True)

    def test_dense_sigmoid_xent(self):
        net = build([DenseLayer(n_in=4, n_out=6, activation="relu"),
                     OutputLayer(n_in=6, n_out=2, loss="xent", activation="sigmoid")])
        y = (np.random.default_rng(2).uniform(size=(5, 2)) > 0.5).astype(np.float32)
        assert check_gradients(net, rand((5, 4)), y)

    def test_mse_identity(self):
        net = build([DenseLayer(n_in=3, n_out=5, activation="tanh"),
                     OutputLayer(n_in=5, n_out=2, loss="mse", activation="identity")])
        assert check_gradients(net, rand((4, 3)), rand((4, 2), seed=3))

    def test_with_l1_l2(self):
        net = build([DenseLayer(n_in=4, n_out=5, activation="sigmoid", l1=0.01, l2=0.02),
                     OutputLayer(n_in=5, n_out=3, loss="mcxent", activation="softmax",
                                 l1=0.01, l2=0.02)],
                    use_regularization=True)
        assert check_gradients(net, rand((5, 4)), onehot(5, 3))


class TestGradientCheckCNN:
    def test_cnn_dense_output(self):
        net = build([ConvolutionLayer(n_out=3, kernel_size=(2, 2), stride=(1, 1),
                                      activation="tanh"),
                     SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                      stride=(2, 2)),
                     DenseLayer(n_out=8, activation="relu"),
                     OutputLayer(n_out=2, loss="mcxent", activation="softmax")],
                    input_type=InputType.convolutional(6, 6, 2))
        x = rand((3, 6, 6, 2))
        assert check_gradients(net, x, onehot(3, 2), subset=60, verbose=True)

    def test_batchnorm(self):
        net = build([DenseLayer(n_in=4, n_out=6, activation="identity"),
                     BatchNormalization(n_in=6),
                     OutputLayer(n_in=6, n_out=3, loss="mcxent", activation="softmax")])
        assert check_gradients(net, rand((8, 4)), onehot(8, 3), subset=40)


class TestGradientCheckRNN:
    def test_lstm_rnn_output(self):
        net = build([GravesLSTM(n_in=3, n_out=4, activation="tanh"),
                     RnnOutputLayer(n_in=4, n_out=2, loss="mcxent",
                                    activation="softmax")])
        x = rand((2, 5, 3))
        rng = np.random.default_rng(4)
        y = np.zeros((2, 5, 2), np.float32)
        idx = rng.integers(0, 2, (2, 5))
        for b in range(2):
            for t in range(5):
                y[b, t, idx[b, t]] = 1
        assert check_gradients(net, x, y, subset=60, verbose=True)

    def test_lstm_masked(self):
        from deeplearning4j_tpu.nn.multilayer import loss_fn
        import jax.numpy as jnp

        net = build([GravesLSTM(n_in=3, n_out=4, activation="tanh"),
                     RnnOutputLayer(n_in=4, n_out=2, loss="mcxent",
                                    activation="softmax")])
        x = rand((2, 4, 3))
        y = np.zeros((2, 4, 2), np.float32)
        y[..., 0] = 1
        mask = np.array([[1, 1, 0, 0], [1, 1, 1, 1]], np.float32)

        # analytic gradient wrt masked-out timestep inputs must not affect loss:
        loss1, _ = loss_fn(net.conf, net.params_list, net.state_list,
                           jnp.asarray(x), jnp.asarray(y), None,
                           jnp.asarray(mask), jnp.asarray(mask))
        x2 = x.copy()
        x2[0, 3] += 100.0  # perturb masked timestep
        loss2, _ = loss_fn(net.conf, net.params_list, net.state_list,
                           jnp.asarray(x2), jnp.asarray(y), None,
                           jnp.asarray(mask), jnp.asarray(mask))
        np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-6)


class TestGradientCheckAttentionMoE:
    def test_self_attention_block(self):
        from deeplearning4j_tpu.nn.conf.layers import SelfAttentionLayer
        net = build([SelfAttentionLayer(n_in=6, n_out=6, n_heads=2,
                                        causal=True, activation="identity"),
                     RnnOutputLayer(n_in=6, n_out=3, loss="mcxent",
                                    activation="softmax")],
                    input_type=InputType.recurrent(6, 5))
        x = rand((2, 5, 6), seed=11)
        y = np.zeros((2, 5, 3), np.float32)
        y[..., 0] = 1
        check_gradients(net, x, y)

    def test_transformer_block(self):
        from deeplearning4j_tpu.nn.conf.layers import TransformerBlock
        net = build([TransformerBlock(n_in=6, n_out=6, n_heads=2,
                                      ffn_multiplier=2, causal=True),
                     RnnOutputLayer(n_in=6, n_out=3, loss="mcxent",
                                    activation="softmax")],
                    input_type=InputType.recurrent(6, 4))
        x = rand((2, 4, 6), seed=12)
        y = np.zeros((2, 4, 3), np.float32)
        y[..., 1] = 1
        check_gradients(net, x, y)

    def test_moe_layer(self):
        from deeplearning4j_tpu.nn.conf.layers.moe import MoELayer
        net = build([MoELayer(n_in=6, n_out=6, n_experts=3, expert_hidden=8,
                              activation="identity"),
                     RnnOutputLayer(n_in=6, n_out=3, loss="mcxent",
                                    activation="softmax")],
                    input_type=InputType.recurrent(6, 4))
        x = rand((2, 4, 6), seed=13)
        y = np.zeros((2, 4, 3), np.float32)
        y[..., 2] = 1
        # router argmax is piecewise-constant but a.e. differentiable; with
        # eps=1e-6 in f64 no routing flip occurs at this seed
        check_gradients(net, x, y)
