"""Fleet observability federation tests (ISSUE 20).

Merge algebra exactness (counter sums, bucket-wise histogram merge
associativity/order-independence, gauge last-write), zombie fencing of
metric frames (a fenced epoch's series stop updating and its gauges drop
from export while its counters stay frozen), restart monotonicity across
epochs (a respawned worker's fresh-from-zero counters never double-count),
the seq guard, the publisher's final-flush exactness, traceparent riding
broker meta + PS frame headers, the fleet collector's merged timeline +
dead-bundle folding, the ``fleet-truth`` lint rule, the ``/fleet/*``
routes, and the acceptance pin: a real 4-worker elastic run whose
``GET /fleet/metrics`` worker-step totals exactly equal the sum of the
workers' process-local counters, with one stitched cross-process trace
(publish -> consume -> push window -> push -> apply) in the coordinator
TraceStore.
"""
import json
import os
import re
import textwrap
import time
import urllib.request

import numpy as np
import pytest

import deeplearning4j_tpu.lint as lint
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.observability import names as _n
from deeplearning4j_tpu.observability.federation import (
    DEFAULT_INTERVAL_S, FederatedRegistry, FleetCollector, MetricsPublisher,
    fleet_metrics_text, fleet_status, global_federation, merge_snapshots,
    register_status_provider, set_global_federation,
    set_global_fleet_collector, strip_gauges, tag_snapshot,
)
from deeplearning4j_tpu.observability.flight_recorder import (
    FlightRecorder, global_recorder,
)
from deeplearning4j_tpu.observability.metrics import (
    MetricsRegistry, render_prometheus,
)
from deeplearning4j_tpu.observability.tracing import (
    TraceStore, global_trace_store, set_global_trace_store, trace_span,
)
from deeplearning4j_tpu.cloud import MembershipOracle
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.parallel.elastic import ElasticTrainer
from deeplearning4j_tpu.parallel.param_server import ParameterServer
from deeplearning4j_tpu.parallel.ps_transport import (
    InprocTransport, ParameterServerTcpFrontend, TcpTransport,
)
from deeplearning4j_tpu.streaming.broker import (
    BrokerProducer, LoopbackBroker, ReconnectingConsumer,
)


@pytest.fixture()
def fresh_trace_store():
    prev = global_trace_store()
    st = TraceStore()
    set_global_trace_store(st)
    yield st
    set_global_trace_store(prev)


class FakeClock:
    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _net(seed=12345, lr=0.1):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(lr).updater("sgd")
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                               activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def _snap(counters=(), gauges=(), hists=()):
    """Build a real registry snapshot: counters/gauges as (name, labels,
    value), hists as (name, labels, [observations])."""
    reg = MetricsRegistry()
    for name, labels, value in counters:
        reg.counter(name).labels(**labels).inc(value)
    for name, labels, value in gauges:
        reg.gauge(name).labels(**labels).set(value)
    for name, labels, obs in hists:
        h = reg.histogram(name).labels(**labels)
        for v in obs:
            h.observe(v)
    return reg.snapshot()


def _series(snapshot, name):
    return snapshot.get(name, {}).get("series", [])


def _value(snapshot, name, **labels):
    for row in _series(snapshot, name):
        if all(row["labels"].get(k) == v for k, v in labels.items()):
            return row.get("value", row.get("count"))
    return None


# ----------------------------------------------------------- merge algebra

def test_merge_counters_exact_sum():
    a = _snap(counters=[("dl4j_x_total", {"w": "a"}, 3)])
    b = _snap(counters=[("dl4j_x_total", {"w": "a"}, 4),
                        ("dl4j_x_total", {"w": "b"}, 10)])
    m = merge_snapshots([a, b])
    assert _value(m, "dl4j_x_total", w="a") == 7
    assert _value(m, "dl4j_x_total", w="b") == 10


def test_merge_histograms_bucketwise_associative_order_independent():
    obs = ([0.001, 0.01, 5.0], [0.002, 0.3], [0.5, 0.5, 0.5, 9.0])
    snaps = [_snap(hists=[("dl4j_h_seconds", {}, o)]) for o in obs]

    left = merge_snapshots([merge_snapshots(snaps[:2]), snaps[2]])
    right = merge_snapshots([snaps[0], merge_snapshots(snaps[1:])])
    anyorder = merge_snapshots([snaps[2], snaps[0], snaps[1]])
    assert left == right == anyorder

    row = _series(left, "dl4j_h_seconds")[0]
    flat = [v for o in obs for v in o]
    assert row["count"] == len(flat)
    assert row["sum"] == pytest.approx(sum(flat))
    # bucket-wise: every cumulative le count equals a recount of the raw
    # observations — the merge added buckets element-wise, not just totals
    cum = 0
    for edge, n in zip(row["buckets"], row["bucket_counts"]):
        cum += n
        assert cum == sum(1 for v in flat if v <= edge)


def test_merge_skewed_buckets_degrade_into_inf_only():
    a = _snap(hists=[("dl4j_h_seconds", {}, [0.001])])
    b = _snap(hists=[("dl4j_h_seconds", {}, [0.002, 0.004])])
    bad = json.loads(json.dumps(b))
    bad["dl4j_h_seconds"]["series"][0]["buckets"] = [1.0, float("inf")]
    bad["dl4j_h_seconds"]["series"][0]["bucket_counts"] = [2, 0]
    m = merge_snapshots([a, bad])
    row = _series(m, "dl4j_h_seconds")[0]
    assert row["count"] == 3 and row["bucket_counts"][-1] == 2


def test_merge_gauges_last_write_and_strip():
    a = _snap(gauges=[("dl4j_g", {}, 1.0)])
    b = _snap(gauges=[("dl4j_g", {}, 7.0)])
    assert _value(merge_snapshots([a, b]), "dl4j_g") == 7.0
    assert _value(merge_snapshots([b, a]), "dl4j_g") == 1.0
    assert strip_gauges(a) == {}


def test_tag_snapshot_labels_every_series_without_mutating_source():
    a = _snap(counters=[("dl4j_x_total", {"op": "push"}, 2)])
    t = tag_snapshot(a, {"worker": "w0", "role": "worker"})
    assert _value(t, "dl4j_x_total", op="push", worker="w0",
                  role="worker") == 2
    assert _series(a, "dl4j_x_total")[0]["labels"] == {"op": "push"}


# ------------------------------------------------------- federated registry

def _fed(validate=None, clock=None):
    return FederatedRegistry(validate=validate, registry=MetricsRegistry(),
                             trace_store=TraceStore(),
                             clock=clock or FakeClock())


def test_zombie_fenced_frames_rejected_gauges_dropped_counters_frozen():
    alive = {("1", "1"): True}

    def validate(member, epoch):
        return alive.get((str(member), str(epoch)), False)

    fed = _fed(validate=validate)
    frame = _snap(counters=[("dl4j_steps_total", {}, 5)],
                  gauges=[("dl4j_depth", {}, 3.0)])
    res = fed.ingest(name="w0", epoch=1, member=1, seq=1, snapshot=frame)
    assert res["accepted"] and not res["fenced"]
    assert _value(fed.totals(), "dl4j_steps_total") == 5
    assert _value(fed.totals(), "dl4j_depth") == 3.0

    alive[("1", "1")] = False  # lease lapsed: the worker is a zombie now
    late = _snap(counters=[("dl4j_steps_total", {}, 50)],
                 gauges=[("dl4j_depth", {}, 9.0)])
    res = fed.ingest(name="w0", epoch=1, member=1, seq=2, snapshot=late)
    assert res["fenced"] and not res["accepted"]
    # series stopped at their last accepted values; gauges left the export
    assert _value(fed.totals(), "dl4j_steps_total") == 5
    assert _value(fed.totals(), "dl4j_depth") is None
    st = fed.status()["members"][0]
    assert st["fenced"] and not st["live"]


def test_restart_new_epoch_is_a_fresh_series_and_totals_stay_monotonic():
    fed = _fed()
    seen = []

    def total():
        v = _value(fed.totals(), "dl4j_steps_total") or 0
        seen.append(v)
        return v

    fed.ingest(name="shard0-gen0", epoch=1, member=1, seq=1,
               snapshot=_snap(counters=[("dl4j_steps_total", {}, 4)],
                              hists=[("dl4j_push_seconds", {},
                                      [0.1, 0.2])]))
    assert total() == 4
    fed.ingest(name="shard0-gen0", epoch=1, member=1, seq=2, final=True,
               snapshot=_snap(counters=[("dl4j_steps_total", {}, 7)],
                              hists=[("dl4j_push_seconds", {},
                                      [0.1, 0.2, 0.3])]))
    assert total() == 7
    # the replacement registers a NEW epoch and reports from zero: its 3
    # steps ADD to the dead generation's frozen 7 (no double count, no
    # reset) — cumulative-by-generation is what makes this exact
    fed.ingest(name="shard0-gen1", epoch=2, member=2, seq=1,
               snapshot=_snap(counters=[("dl4j_steps_total", {}, 3)],
                              hists=[("dl4j_push_seconds", {}, [0.4])]))
    assert total() == 10
    hist = _series(fed.totals(), "dl4j_push_seconds")[0]
    assert hist["count"] == 4  # 3 final from gen0 + 1 from gen1
    assert seen == sorted(seen), "fleet counters must never decrease"


def test_seq_guard_discards_duplicate_and_reordered_frames():
    fed = _fed()
    fed.ingest(name="w0", epoch=1, member=1, seq=5,
               snapshot=_snap(counters=[("dl4j_steps_total", {}, 9)]))
    stale = fed.ingest(name="w0", epoch=1, member=1, seq=4,
                       snapshot=_snap(
                           counters=[("dl4j_steps_total", {}, 2)]))
    assert not stale["accepted"] and not stale["fenced"]
    dup = fed.ingest(name="w0", epoch=1, member=1, seq=5,
                     snapshot=_snap(
                         counters=[("dl4j_steps_total", {}, 2)]))
    assert not dup["accepted"]
    assert _value(fed.totals(), "dl4j_steps_total") == 9


def test_final_frame_bypasses_fencing():
    # the exit flush races the deregister on the membership oracle: a
    # graceful worker must still land its last cumulative frame
    fed = _fed(validate=lambda member, epoch: False)
    res = fed.ingest(name="w0", epoch=1, member=1, seq=1, final=True,
                     snapshot=_snap(
                         counters=[("dl4j_steps_total", {}, 6)]))
    assert res["accepted"]
    assert _value(fed.totals(), "dl4j_steps_total") == 6
    # final also means done: gauges would no longer export
    assert not fed.status()["members"][0]["live"]


def test_fleet_snapshot_labels_members_and_coordinator():
    fed = _fed()
    fed.ingest(name="w0", epoch=1, member=1, seq=1, role="worker",
               snapshot=_snap(counters=[("dl4j_steps_total", {}, 2)]))
    fed.ingest(name="r0", epoch=2, member=2, seq=1, role="replica",
               snapshot=_snap(counters=[("dl4j_steps_total", {}, 3)]))
    snap = fed.fleet_snapshot(local=False)
    assert _value(snap, "dl4j_steps_total", worker="w0",
                  role="worker") == 2
    assert _value(snap, "dl4j_steps_total", replica="r0",
                  role="replica") == 3
    text = fed.prometheus_text()
    assert 'worker="w0"' in text and 'replica="r0"' in text


def test_shared_renderer_keeps_local_and_fleet_exposition_identical():
    reg = MetricsRegistry()
    reg.counter("dl4j_x_total", "help here").labels(op="a").inc(2)
    reg.histogram("dl4j_h_seconds").labels().observe(0.01)
    assert reg.prometheus_text() == render_prometheus(reg.snapshot())


# --------------------------------------------------------------- publisher

def test_publisher_final_flush_makes_totals_exact_over_inproc():
    fed = _fed()
    worker_reg = MetricsRegistry()
    rec = FlightRecorder(capacity=64)
    t = InprocTransport(None, federation=fed)
    t.bind_member(1, 1)
    pub = MetricsPublisher(t, name="w0", interval_s=999.0,
                           registry=worker_reg, recorder=rec,
                           trace_store=TraceStore())
    steps = worker_reg.counter("dl4j_steps_total").labels()
    steps.inc(5)
    rec.record("push_window", window=1)
    assert pub.flush()
    assert _value(fed.totals(), "dl4j_steps_total") == 5
    steps.inc(3)  # the last window lands after the final periodic flush
    pub.stop(final=True)
    assert _value(fed.totals(), "dl4j_steps_total") == 8
    assert fed.member_events()["w0@1"][0]["kind"] == "push_window"
    assert pub.frames_sent == 2 and not pub.fenced


def test_publisher_marks_itself_fenced_on_rejection():
    fed = _fed(validate=lambda member, epoch: False)
    t = InprocTransport(None, federation=fed)
    t.bind_member(1, 1)
    pub = MetricsPublisher(t, name="w0", interval_s=999.0,
                           registry=MetricsRegistry(),
                           recorder=FlightRecorder(capacity=8),
                           trace_store=TraceStore())
    assert not pub.flush()
    assert pub.fenced


# ------------------------------------------------- trace propagation (wire)

def test_traceparent_rides_broker_meta_and_consumer_stitches(fresh_trace_store):
    broker = LoopbackBroker().start()
    producer = BrokerProducer(broker.address)
    consumer = ReconnectingConsumer(broker.address, "t0", group="g0")
    try:
        with trace_span("shard.publish", topic="t0") as root:
            root_ref = root.ref()
            producer.publish("t0", {"x": np.ones(2, np.float32)})
        meta, arrays = consumer.get(timeout=2.0)
        assert meta["traceparent"].split("-")[1] == root_ref.trace_id
        assert consumer.last_trace_ref is not None
        assert consumer.last_trace_ref.trace_id == root_ref.trace_id
        # the consume span itself is already finalized into the local store
        rec = global_trace_store().get(root_ref.trace_id)
        names = {s["name"]: s for s in rec["spans"]}
        assert names["broker.consume"]["parent_id"] == root_ref.span_id
    finally:
        consumer.close()
        producer.close()
        broker.stop()


def test_ps_push_traced_across_tcp_frontend(fresh_trace_store):
    oracle = MembershipOracle(lease_timeout_s=30.0)
    srv = ParameterServer([np.zeros(6, np.float32)], membership=oracle)
    frontend = ParameterServerTcpFrontend(srv).start()
    t = TcpTransport(("127.0.0.1", frontend.port))
    try:
        reg = t.register(0, worker="w0")
        t.bind_member(reg["member"], reg["epoch"])
        with trace_span("test.root") as root:
            res = t.push(np.ones(6, np.float32), 0)
            assert res.accepted
        rec = global_trace_store().get(root.trace_id)
        by_name = {s["name"]: s for s in rec["spans"]}
        assert by_name["ps.push"]["parent_id"] == root.span_id
        # the server-side handling span parented from the frame header:
        # the whole point of wire propagation
        assert by_name["ps.apply"]["parent_id"] \
            == by_name["ps.push"]["span_id"]
        assert by_name["ps.apply"]["attrs"]["member"] == reg["member"]
    finally:
        t.close()
        frontend.stop()


def test_parentless_rpcs_open_no_span(fresh_trace_store):
    # heartbeats and the background puller must not mint root-trace noise
    srv = ParameterServer([np.zeros(4, np.float32)])
    frontend = ParameterServerTcpFrontend(srv).start()
    t = TcpTransport(("127.0.0.1", frontend.port))
    try:
        store = global_trace_store()
        before = len(store)
        t.pull()
        t.push(np.ones(4, np.float32), 0)
        assert len(store) == before
    finally:
        t.close()
        frontend.stop()


# ------------------------------------------------------- fleet collector

def test_fleet_collector_merges_timelines_and_dead_bundles(tmp_path):
    rec = FlightRecorder(capacity=64, dump_dir=str(tmp_path))
    rec.record("coordinator_event", step=1)
    fed = _fed()
    fed.ingest(name="w0", epoch=1, member=1, seq=1, snapshot={},
               events=[{"kind": "worker_event", "ts": 1.5}])
    # a dead worker's last on-disk bundle (foreign pid)
    dead = tmp_path / "flight-20260101-000000-p99999-001-sigkill"
    dead.mkdir()
    (dead / "events.jsonl").write_text(
        json.dumps({"kind": "dead_event", "ts": 1.0}) + "\n")
    (dead / "manifest.json").write_text(json.dumps(
        {"reason": "sigkill", "pid": 99999, "ts": 1.0, "events": 1}))

    col = FleetCollector(federation=fed, recorder=rec,
                         registry=MetricsRegistry())
    path = col.dump(reason="shard-handoff")
    assert path is not None and os.path.basename(path).startswith("fleet-")
    lines = [json.loads(l) for l in
             open(os.path.join(path, "merged_timeline.jsonl"))]
    sources = {e["source"] for e in lines}
    assert "coordinator" in sources and "w0@1" in sources
    assert any(s.startswith("bundle:flight-") for s in sources)
    ts = [e.get("ts", 0.0) for e in lines]
    assert ts == sorted(ts), "merged timeline must be time-ordered"
    manifest = json.load(open(os.path.join(path, "manifest.json")))
    assert manifest["fleet"] and manifest["reason"] == "shard-handoff"
    totals = json.load(open(os.path.join(path, "metrics.json")))
    assert isinstance(totals, dict)
    # rate limit: an immediate second trigger is a free no-op
    assert col.dump(reason="shard-handoff") is None
    assert col.dump(reason="api", force=True) is not None


def test_fleet_collector_without_dump_dir_is_a_noop():
    col = FleetCollector(recorder=FlightRecorder(capacity=8, dump_dir=None),
                         registry=MetricsRegistry())
    assert col.dump(reason="manual", force=True) is None


# ------------------------------------------------------------- fleet routes

def test_fleet_status_composes_provider_blocks_with_error_isolation():
    old_fed = global_federation()
    try:
        set_global_federation(None)
        register_status_provider("good", lambda: {"ok": 1})

        def boom():
            raise RuntimeError("sick subsystem")

        register_status_provider("bad", boom)
        st = fleet_status()
        assert st["federation"] is None
        assert st["good"] == {"ok": 1}
        assert "error" in st["bad"]
    finally:
        register_status_provider("good", None)
        register_status_provider("bad", None)
        set_global_federation(old_fed)


def test_fleet_metrics_text_fallback_is_honestly_labeled():
    old_fed = global_federation()
    try:
        set_global_federation(None)
        text = fleet_metrics_text()
        assert 'role="local"' in text
        assert f'-{os.getpid()}"' in text
    finally:
        set_global_federation(old_fed)


# ---------------------------------------------------- satellite: child env

def test_write_conf_ships_flight_recorder_dir_to_workers(tmp_path):
    rec = global_recorder()
    old = rec.dump_dir
    try:
        rec.set_dump_dir(str(tmp_path))
        trainer = ElasticTrainer(_net(), workers=2)
        trainer._write_conf(str(tmp_path))
        env = trainer._env_conf["env"]
        # the regression: set_dump_dir() never touches os.environ, so the
        # plain environ copy dropped the dir and dead workers' bundles
        # landed nowhere the fleet collector could see
        assert env["DL4J_FLIGHT_RECORDER_DIR"] == str(tmp_path)
    finally:
        rec.set_dump_dir(old)


# ------------------------------------------------------- fleet-truth lint

def _lint_src(tmp_path, source, name="fixture.py"):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    return lint.run_paths([f], ["fleet-truth"])


def test_fleet_truth_flags_local_snapshot_in_fleet_function(tmp_path):
    res = _lint_src(tmp_path, """\
        from deeplearning4j_tpu.observability.metrics import global_registry

        def fleet_metrics():
            return global_registry().snapshot()
        """)
    assert [v.rule for v in res.violations] == ["fleet-truth"]
    assert res.violations[0].line == 4


def test_fleet_truth_flags_fleet_route_branch_only(tmp_path):
    res = _lint_src(tmp_path, """\
        def do_GET(self, path, registry):
            if path == "/metrics":
                return registry.prometheus_text()   # local route: legal
            elif path == "/fleet/metrics":
                return registry.prometheus_text()   # fleet truth lie
        """)
    assert [v.rule for v in res.violations] == ["fleet-truth"]
    assert res.violations[0].line == 5


def test_fleet_truth_negative_federated_reads_are_legal(tmp_path):
    res = _lint_src(tmp_path, """\
        def do_GET(self, path, federation):
            if path == "/fleet/metrics":
                return federation.prometheus_text()

        def fleet_status_data(self):
            from deeplearning4j_tpu.observability.federation import \\
                fleet_status
            return fleet_status()
        """)
    assert res.violations == []


def test_fleet_truth_clean_over_real_tree():
    import pathlib
    pkg = pathlib.Path(lint.__file__).resolve().parents[1]
    res = lint.run_paths([pkg], ["fleet-truth"])
    assert res.violations == []


# ------------------------------------- serving: one trace across the stack

def test_http_request_batcher_replica_stitch_into_one_trace(fresh_trace_store):
    from deeplearning4j_tpu.keras_server.serving import InferenceServer

    server = InferenceServer(port=0, replicas=2).start()
    try:
        server.register("m", _net())
        body = json.dumps(
            {"model": "m", "inputs": np.ones((1, 4)).tolist()}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/predict", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200
            tp = r.headers["traceparent"]
        trace_id = tp.split("-")[1]
        # the ROOT span finalizes right after the response bytes go out —
        # give the handler thread a beat
        rec = None
        deadline = time.time() + 5.0
        while rec is None and time.time() < deadline:
            rec = global_trace_store().get(trace_id)
            if rec is None:
                time.sleep(0.01)
        assert rec is not None
        spans = rec["spans"]
        assert all(s["trace_id"] == trace_id for s in spans)
        by_name = {}
        for s in spans:
            by_name.setdefault(s["name"], s)
        root = by_name["http /v1/predict"]
        assert root["parent_id"] is None
        assert "replica.route" in by_name  # dispatch seam in the same tree
        ids = {s["span_id"] for s in spans}
        for s in spans:
            if s["parent_id"] is not None:
                assert s["parent_id"] in ids, \
                    f"span {s['name']} parent outside the tree"
        # /fleet/status now carries the serving block (status provider)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/fleet/status",
                timeout=10) as r:
            st = json.loads(r.read())
        assert "serving" in st and "queue" in st["serving"]
    finally:
        server.stop()


# ------------------------------ acceptance: 4-worker elastic run, exact sum

def _fetch(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.read().decode()


def test_fleet_metrics_exact_and_trace_stitched_over_elastic_run(fresh_trace_store):
    """The acceptance pin: run a REAL 4-subprocess elastic fit, then (a)
    ``GET /fleet/metrics`` worker-step totals exactly equal the sum of the
    per-worker process-local counters each worker printed at exit, and (b)
    the coordinator TraceStore holds one stitched cross-process tree
    publish -> consume -> push window -> push -> apply under a single
    trace id with correct parent ids."""
    rng = np.random.default_rng(7)
    data = [DataSet(rng.normal(size=(8, 4)).astype(np.float32),
                    np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)])
            for _ in range(8)]
    trainer = (ElasticTrainer.builder(_net())
               .workers(4).push_frequency(2)
               .lease_timeout(30.0).fit_timeout(300.0).build())
    trainer.fit(ListDataSetIterator(data))

    assert len(trainer.worker_stats) == 4
    local_steps = sum(int(s["steps"]) for s in trainer.worker_stats)
    assert local_steps == 8  # every batch trained exactly once

    # (a) exactness, straight off the federation object... (the counter
    # carries one series per worker label: sum them all)
    fed = trainer.federation
    total = sum(row["value"]
                for row in _series(fed.totals(), _n.PS_WORKER_STEPS_TOTAL))
    assert total == local_steps

    # ...and over the HTTP surface. Sum only role="worker" series: the
    # coordinator's own registry rides the same page under
    # role="coordinator" and must not pollute the pin.
    from deeplearning4j_tpu.ui.server import UIServer
    ui = UIServer(port=0)
    try:
        text = _fetch(f"http://127.0.0.1:{ui.port}/fleet/metrics")
        pat = re.compile(
            re.escape(_n.PS_WORKER_STEPS_TOTAL) + r"\{([^}]*)\}\s+(\S+)")
        http_total = sum(
            float(m.group(2)) for m in pat.finditer(text)
            if 'role="worker"' in m.group(1))
        assert http_total == local_steps
        st = json.loads(_fetch(f"http://127.0.0.1:{ui.port}/fleet/status"))
        assert st["federation"]["generations"] >= 4
        assert st["elastic"]["steps"] == local_steps
        names = {m["name"] for m in st["federation"]["members"]}
        assert {"shard0-gen0", "shard1-gen0",
                "shard2-gen0", "shard3-gen0"} <= names
    finally:
        ui.stop()

    # (b) the stitched cross-process trace tree
    store = global_trace_store()
    stitched = None
    for entry in store.list():
        rec = store.get(entry["trace_id"])
        names = {s["name"] for s in rec["spans"]}
        if {"shard.publish", "broker.consume", "ps.push_window",
                "ps.push", "ps.apply"} <= names:
            stitched = rec
            break
    assert stitched is not None, \
        "no trace stitched across coordinator + worker + wire"
    spans = stitched["spans"]
    assert all(s["trace_id"] == stitched["trace_id"] for s in spans)
    by_id = {s["span_id"]: s for s in spans}

    def parent_name(s):
        p = by_id.get(s["parent_id"])
        return p["name"] if p else None

    roots = [s for s in spans if s["parent_id"] is None]
    assert [s["name"] for s in roots] == ["shard.publish"]
    for s in spans:
        if s["name"] == "broker.consume":
            assert parent_name(s) == "shard.publish"
        elif s["name"] == "ps.push_window":
            assert parent_name(s) == "broker.consume"
        elif s["name"] == "ps.push":
            assert parent_name(s) == "ps.push_window"
        elif s["name"] == "ps.apply":
            assert parent_name(s) == "ps.push"
