"""Parity extras: eval metadata, ParamAndGradient listener, berkeley-style
collections, CLI, ExistingDataSetIterator, EarlyStoppingParallelTrainer."""
import numpy as np
import pytest

from deeplearning4j_tpu.eval.evaluation import Evaluation
from deeplearning4j_tpu.utils.collections import Counter, PriorityQueue


def test_evaluation_prediction_metadata():
    e = Evaluation()
    labels = np.eye(3, dtype=np.float32)[[0, 1, 2, 0]]
    preds = np.eye(3, dtype=np.float32)[[0, 2, 2, 1]]
    meta = [f"rec{i}" for i in range(4)]
    e.eval(labels, preds, record_meta_data=meta)
    errors = e.get_prediction_errors()
    assert [(p.actual, p.predicted, p.record_meta_data) for p in errors] == [
        (1, 2, "rec1"), (0, 1, "rec3")]
    assert len(e.get_predictions_by_actual_class(0)) == 2
    assert len(e.get_predictions(1, 2)) == 1


def test_param_and_gradient_listener(tmp_path):
    from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.optimize.listeners import (
        ParamAndGradientIterationListener,
    )
    conf = (NeuralNetConfiguration.builder().seed(0).learning_rate(0.1)
            .list().layer(DenseLayer(n_in=4, n_out=4, activation="relu"))
            .layer(OutputLayer(n_in=4, n_out=2, loss="mcxent",
                               activation="softmax")).build())
    net = MultiLayerNetwork(conf).init()
    out = tmp_path / "pg.jsonl"
    lst = ParamAndGradientIterationListener(output_file=str(out),
                                            print_mean_magnitudes=False)
    net.set_listeners(lst)
    x = np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[np.arange(8) % 2]
    net.fit(x, y, epochs=3)
    assert len(lst.rows) == 3
    assert any(k.startswith("param_") for k in lst.rows[0])
    assert any(k.startswith("update_") for k in lst.rows[1])
    assert out.read_text().count("\n") == 3


def test_counter_and_priority_queue():
    c = Counter()
    c.increment_count("a", 2.0)
    c.increment_count("b", 1.0)
    c.increment_count("a", 1.0)
    assert c.argmax() == "a" and c.get_count("a") == 3.0
    c.normalize()
    assert abs(c.total_count() - 1.0) < 1e-12
    q = PriorityQueue()
    q.put("low", 1.0)
    q.put("high", 9.0)
    q.put("mid", 5.0)
    assert q.peek() == "high" and q.get_priority() == 9.0
    assert list(q) == ["high", "mid", "low"]


def test_existing_dataset_iterator():
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ExistingDataSetIterator
    ds = [DataSet(np.ones((4, 2), np.float32), np.ones((4, 1), np.float32))]
    assert sum(1 for _ in ExistingDataSetIterator(ds)) == 1


def test_cli_parallel_train_and_parser(tmp_path):
    from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.utils.model_serializer import write_model
    from deeplearning4j_tpu.cli import main

    conf = (NeuralNetConfiguration.builder().seed(0).learning_rate(0.05)
            .list().layer(DenseLayer(n_in=2, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=2, loss="mcxent",
                               activation="softmax")).build())
    net = MultiLayerNetwork(conf).init()
    mpath = tmp_path / "m.zip"
    write_model(net, str(mpath))
    csv = tmp_path / "d.csv"
    rng = np.random.default_rng(0)
    rows = []
    for i in range(64):
        lab = i % 2
        a, b = rng.normal(lab, 0.2), rng.normal(-lab, 0.2)
        rows.append(f"{a},{b},{lab}")
    csv.write_text("\n".join(rows) + "\n")
    out = tmp_path / "trained.zip"
    rc = main(["parallel-train", "--model", str(mpath), "--dataset", str(csv),
               "--workers", "2", "--batch", "16", "--num-classes", "2",
               "--label-index", "2", "--epochs", "2",
               "--output", str(out)])
    assert rc == 0 and out.exists()


def test_cli_pipeline_train(tmp_path):
    """ParallelWrapperMain-equivalent CLI drives pipeline parallelism too:
    --pipeline trains any model zip with a homogeneous block stack through
    PipelineTrainer from the command line."""
    from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.utils.model_serializer import write_model
    from deeplearning4j_tpu.cli import main

    conf = (NeuralNetConfiguration.builder().seed(0).learning_rate(0.05)
            .list()
            .layer(DenseLayer(n_in=2, n_out=8, activation="relu"))
            .layer(DenseLayer(n_in=8, n_out=8, activation="tanh"))
            .layer(DenseLayer(n_in=8, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=2, loss="mcxent",
                               activation="softmax")).build())
    net = MultiLayerNetwork(conf).init()
    mpath = tmp_path / "m.zip"
    write_model(net, str(mpath))
    csv = tmp_path / "d.csv"
    rng = np.random.default_rng(0)
    rows = []
    for i in range(64):
        lab = i % 2
        a, b = rng.normal(lab, 0.2), rng.normal(-lab, 0.2)
        rows.append(f"{a},{b},{lab}")
    csv.write_text("\n".join(rows) + "\n")
    out = tmp_path / "trained.zip"
    rc = main(["parallel-train", "--model", str(mpath), "--dataset", str(csv),
               "--pipeline", "--workers", "2", "--microbatches", "2",
               "--batch", "16", "--num-classes", "2", "--label-index", "2",
               "--output", str(out)])
    assert rc == 0 and out.exists()


def test_early_stopping_parallel_trainer():
    from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.earlystopping.config import EarlyStoppingConfiguration
    from deeplearning4j_tpu.earlystopping.savers import InMemoryModelSaver
    from deeplearning4j_tpu.earlystopping.scorecalc import DataSetLossCalculator
    from deeplearning4j_tpu.earlystopping.termination import (
        MaxEpochsTerminationCondition,
    )
    from deeplearning4j_tpu.earlystopping.trainer import (
        EarlyStoppingParallelTrainer,
    )
    from deeplearning4j_tpu.datasets.mnist import IrisDataSetIterator

    conf = (NeuralNetConfiguration.builder().seed(1).learning_rate(0.1)
            .list().layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                               activation="softmax")).build())
    net = MultiLayerNetwork(conf).init()
    it = IrisDataSetIterator(batch=24, num_examples=144)
    cfg = EarlyStoppingConfiguration(
        model_saver=InMemoryModelSaver(),
        score_calculator=DataSetLossCalculator(it),
        epoch_termination_conditions=[MaxEpochsTerminationCondition(3)])
    trainer = EarlyStoppingParallelTrainer(cfg, net, it, workers=2)
    result = trainer.fit()
    assert result.total_epochs == 3
    assert result.best_model is not None


def test_profiler_listener_captures_trace(tmp_path):
    """ProfilerListener writes an XPlane trace over its iteration window
    (SURVEY §5 tracing parity: jax.profiler is the TPU-native timeline)."""
    import glob

    import numpy as np

    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.optimize.listeners import ProfilerListener

    conf = (NeuralNetConfiguration.builder()
            .seed(1).learning_rate(0.1)
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=2, loss="mse",
                               activation="identity"))
            .build())
    net = MultiLayerNetwork(conf).init()
    listener = ProfilerListener(str(tmp_path), start_iteration=2,
                                num_iterations=3)
    net.set_listeners(listener)
    x = np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32)
    y = np.zeros((8, 2), np.float32)
    for _ in range(8):
        net.fit(x, y)
    assert listener.windows, "no trace window completed"
    files = glob.glob(str(tmp_path) + "/**/*.xplane.pb", recursive=True)
    assert files, "no xplane trace written"


def test_evaluation_top_n_accuracy():
    """Top-N accuracy counts a guess when the true class is among the N
    highest-probability outputs (reference Evaluation(topN) / topNAccuracy)."""
    e = Evaluation(top_n=2)
    labels = np.eye(3, dtype=np.float32)[[0, 1, 2, 0]]
    preds = np.array([[0.6, 0.3, 0.1],   # top-1 hit
                      [0.5, 0.4, 0.1],   # top-1 miss, top-2 hit (cls 1)
                      [0.5, 0.4, 0.1],   # top-2 miss (cls 2 is last)
                      [0.1, 0.5, 0.4]],  # both miss... top-2 of row = {1,2}, actual 0 -> miss
                     np.float32)
    e.eval(labels, preds)
    assert e.accuracy() == 0.25
    assert e.top_n_accuracy() == 0.5
    assert f"Top-2 Accuracy" in e.stats()


def test_evaluation_label_names_in_stats():
    """Class-label names render in stats()/confusion output (reference
    eval/Evaluation.java labeled constructors)."""
    e = Evaluation(labels=["cat", "dog", "fish"])
    labels = np.eye(3, dtype=np.float32)[[0, 1, 2, 2]]
    preds = np.eye(3, dtype=np.float32)[[0, 1, 1, 2]]
    e.eval(labels, preds)
    s = e.stats()
    assert "cat" in s and "dog" in s and "fish" in s
    assert e.label_name(1) == "dog"
    # merge preserves names and top-n counters
    e2 = Evaluation()
    e2.eval(labels, preds)
    e2.merge(e)
    assert e2.labels == ["cat", "dog", "fish"]
    assert e2.num_examples == 8


def test_score_examples_and_rnn_state_api():
    """Round-4 surface parity: scoreExamples (un-reduced, per example;
    reference MultiLayerNetwork:1755 / ComputationGraph:1502),
    pretrainLayer on MLN, f1Score, rnnGet/SetPreviousState, CG.clone."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ExistingDataSetIterator
    from deeplearning4j_tpu.nn.conf.layers import (
        AutoEncoder, DenseLayer, GravesLSTM, OutputLayer, RnnOutputLayer,
    )
    from deeplearning4j_tpu.nn.graph_network import ComputationGraph

    rng = np.random.default_rng(0)
    x = rng.normal(size=(12, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 12)]
    conf = (NeuralNetConfiguration.builder().seed(1).learning_rate(0.1)
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                               activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()
    per = net.score_examples(DataSet(x, y))
    assert per.shape == (12,)
    # mean of per-example scores == score() minus regularization (none here)
    assert abs(per.mean() - net.score(x, y)) < 1e-5
    per_reg = net.score_examples(x, y, add_regularization=True)
    assert per_reg.shape == (12,)
    assert 0.0 <= net.f1_score(x, y) <= 1.0

    # MLN pretrain_layer: trains only that layer; errors are actionable
    conf2 = (NeuralNetConfiguration.builder().seed(2).learning_rate(0.05)
             .list()
             .layer(AutoEncoder(n_in=4, n_out=6, activation="sigmoid"))
             .layer(OutputLayer(n_in=6, n_out=3, loss="mcxent",
                                activation="softmax"))
             .build())
    net2 = MultiLayerNetwork(conf2).init()
    out_before = jax.tree_util.tree_map(np.asarray, net2.params_list[1])
    ae_before = np.asarray(net2.params_list[0]["W"])
    net2.pretrain_layer(0, ExistingDataSetIterator([DataSet(x, y)]))
    assert not np.array_equal(np.asarray(net2.params_list[0]["W"]), ae_before)
    for k, v in net2.params_list[1].items():
        np.testing.assert_array_equal(np.asarray(v), out_before[k])
    with pytest.raises(ValueError, match="not pretrainable"):
        net2.pretrain_layer(1, ExistingDataSetIterator([DataSet(x, y)]))

    # rnn state get/set roundtrip: restored state reproduces the next step
    rconf = (NeuralNetConfiguration.builder().seed(3).learning_rate(0.1)
             .list()
             .layer(GravesLSTM(n_in=3, n_out=6, activation="tanh"))
             .layer(RnnOutputLayer(n_in=6, n_out=3, loss="mcxent",
                                   activation="softmax"))
             .build())
    rnet = MultiLayerNetwork(rconf).init()
    seq = rng.normal(size=(2, 4, 3)).astype(np.float32)
    rnet.rnn_time_step(seq)
    saved = jax.tree_util.tree_map(np.asarray, rnet.rnn_get_previous_state())
    step_in = rng.normal(size=(2, 1, 3)).astype(np.float32)
    out_a = np.asarray(rnet.rnn_time_step(step_in))
    rnet.rnn_set_previous_state(saved)
    out_b = np.asarray(rnet.rnn_time_step(step_in))
    np.testing.assert_allclose(out_a, out_b, rtol=1e-6)

    # CG: clone independence + score_examples
    gconf = (NeuralNetConfiguration.builder().seed(4).learning_rate(0.1)
             .graph_builder()
             .add_inputs("in")
             .add_layer("d", DenseLayer(n_in=4, n_out=8, activation="tanh"),
                        "in")
             .add_layer("out", OutputLayer(n_in=8, n_out=3, loss="mcxent",
                                           activation="softmax"), "d")
             .set_outputs("out")
             .build())
    g = ComputationGraph(gconf).init()
    gper = g.score_examples(DataSet(x, y))
    assert gper.shape == (12,)
    from deeplearning4j_tpu.nn.graph_network import MultiDataSet
    assert abs(gper.mean() - g.score(MultiDataSet([x], [y]))) < 1e-5
    g2 = g.clone()
    g.fit([x], [y])
    assert not np.allclose(np.asarray(g.params()), np.asarray(g2.params()))


def test_score_examples_honors_label_masks():
    """scoreExamples with a masked time-series DataSet: padded timesteps
    must not count (matches fit()'s mask semantics on both network types)."""
    import jax.numpy as jnp

    from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer

    rng = np.random.default_rng(0)
    B, T, C = 4, 6, 3
    x = rng.normal(size=(B, T, C)).astype(np.float32)
    y = np.eye(C, dtype=np.float32)[rng.integers(0, C, (B, T))]
    lmask = np.ones((B, T), np.float32)
    lmask[:, T // 2:] = 0
    conf = (NeuralNetConfiguration.builder().seed(5).learning_rate(0.1)
            .list()
            .layer(GravesLSTM(n_in=C, n_out=6, activation="tanh"))
            .layer(RnnOutputLayer(n_in=6, n_out=C, loss="mcxent",
                                  activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()
    masked = net.score_examples(DataSet(x, y, labels_mask=lmask))
    unmasked = net.score_examples(DataSet(x, y))
    assert masked.shape == (B,)
    assert not np.allclose(masked, unmasked)
    # masked per-example score == full-sequence score of the valid half
    half = net.score_examples(DataSet(x[:, :T // 2], y[:, :T // 2]))
    np.testing.assert_allclose(masked, half, rtol=1e-4, atol=1e-6)


def test_score_examples_per_stream_none_masks_and_feature_mask():
    """CG score_examples with per-stream None mask entries must not crash
    (the 'only one output masked' MultiDataSet case); MLN score_examples
    threads features_mask through the forward like fit() does."""
    from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.conf.layers import (
        DenseLayer, GravesLSTM, OutputLayer, RnnOutputLayer,
    )
    from deeplearning4j_tpu.nn.graph_network import (
        ComputationGraph, MultiDataSet)

    rng = np.random.default_rng(1)
    x = rng.normal(size=(4, 4)).astype(np.float32)
    y1 = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 4)]
    y2 = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 4)]
    conf = (NeuralNetConfiguration.builder().seed(6).learning_rate(0.1)
            .graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_in=4, n_out=8, activation="tanh"),
                       "in")
            .add_layer("o1", OutputLayer(n_in=8, n_out=3, loss="mcxent",
                                         activation="softmax"), "d")
            .add_layer("o2", OutputLayer(n_in=8, n_out=3, loss="mcxent",
                                         activation="softmax"), "d")
            .set_outputs("o1", "o2")
            .build())
    g = ComputationGraph(conf).init()
    mask = np.ones((4,), np.float32)
    mask[2:] = 0
    mds = MultiDataSet([x], [y1, y2], labels_masks=[mask, None])
    per = g.score_examples(mds)
    assert per.shape == (4,)

    # MLN: feature mask changes LSTM activations, so scores must differ
    B, T, C = 3, 5, 2
    xs = rng.normal(size=(B, T, C)).astype(np.float32)
    ys = np.eye(C, dtype=np.float32)[rng.integers(0, C, (B, T))]
    fm = np.ones((B, T), np.float32)
    fm[:, 3:] = 0
    rconf = (NeuralNetConfiguration.builder().seed(7).learning_rate(0.1)
             .list()
             .layer(GravesLSTM(n_in=C, n_out=4, activation="tanh"))
             .layer(RnnOutputLayer(n_in=4, n_out=C, loss="mcxent",
                                   activation="softmax"))
             .build())
    net = MultiLayerNetwork(rconf).init()
    with_fm = net.score_examples(DataSet(xs, ys, features_mask=fm,
                                         labels_mask=fm))
    without = net.score_examples(DataSet(xs, ys))
    assert with_fm.shape == (B,)
    assert not np.allclose(with_fm, without)
    # score(dataset=) honors the same masks: equals mean of per-example
    s_masked = net.score(dataset=DataSet(xs, ys, features_mask=fm,
                                         labels_mask=fm))
    assert abs(s_masked - with_fm.mean()) < 1e-5
    assert abs(net.score(xs, ys) - without.mean()) < 1e-5
