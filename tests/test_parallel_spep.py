"""Sequence- and expert-parallelism as first-class fit() features.

Round-4 verdict: ring/Ulysses attention and GShard MoE dispatch existed only
as hand-written shard_map demos. These tests pin the framework contract —
a plain ``transformer_lm`` / ``moe_transformer_lm`` config trains sequence-
or expert-parallel through ParallelWrapper.fit() alone, and the result
equals single-device dense training (the reference's gold-standard pattern:
TestCompareParameterAveragingSparkVsSingleMachine, SURVEY.md §4).
"""
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.models import moe_transformer_lm, transformer_lm
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.mesh import build_mesh
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

VOCAB, WIDTH, HEADS, T, B = 8, 32, 4, 16, 8


def _lm_batches(n=3, seed=0, vocab=VOCAB, t=T, b=B):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        ids = rng.integers(0, vocab, size=(b, t + 1))
        x = np.eye(vocab, dtype=np.float32)[ids[:, :-1]]
        y = np.eye(vocab, dtype=np.float32)[ids[:, 1:]]
        out.append(DataSet(x, y))
    return out


def _single_device_fit(conf, batches):
    net = MultiLayerNetwork(conf).init()
    for ds in batches:
        net.fit(ds.features, ds.labels)
    return net


@pytest.mark.parametrize("mode", ["ulysses", "ring"])
def test_sequence_parallel_fit_equals_single_device(mode):
    """transformer_lm config + .sequence_parallel() == dense single-device
    training; nothing in the model code mentions the mesh."""
    batches = _lm_batches()
    conf = lambda: transformer_lm(VOCAB, width=WIDTH, n_layers=2,
                                  n_heads=HEADS, max_len=T, learning_rate=0.01)
    single = _single_device_fit(conf(), batches)

    sp_net = MultiLayerNetwork(conf()).init()
    mesh = build_mesh({"data": 2, "sp": 4})
    pw = (ParallelWrapper.builder(sp_net)
          .mesh(mesh).prefetch_buffer(0)
          .sequence_parallel("sp", mode=mode)
          .build())
    pw.fit(ListDataSetIterator(batches))

    np.testing.assert_allclose(np.asarray(single.params()),
                               np.asarray(sp_net.params()),
                               atol=5e-5, rtol=1e-4)


def test_expert_parallel_fit_equals_dense():
    """moe_transformer_lm config + .expert_parallel() == dense single-device
    training when capacity admits every token (capacity_factor=n_experts)."""
    n_experts = 8
    batches = _lm_batches()
    conf = lambda: moe_transformer_lm(VOCAB, width=WIDTH, n_layers=2,
                                      n_heads=HEADS, n_experts=n_experts,
                                      max_len=T, learning_rate=0.01)
    single = _single_device_fit(conf(), batches)

    ep_net = MultiLayerNetwork(conf()).init()
    mesh = build_mesh({"data": 8})
    pw = (ParallelWrapper.builder(ep_net)
          .mesh(mesh).prefetch_buffer(0)
          .expert_parallel("data", capacity_factor=float(n_experts))
          .build())
    pw.fit(ListDataSetIterator(batches))

    np.testing.assert_allclose(np.asarray(single.params()),
                               np.asarray(ep_net.params()),
                               atol=5e-5, rtol=1e-4)


def test_expert_parallel_drops_tokens_at_tight_capacity():
    """With a tight capacity factor the EP path still trains (overflow
    tokens dropped, GShard/Switch semantics) and stays finite."""
    batches = _lm_batches(2)
    conf = moe_transformer_lm(VOCAB, width=WIDTH, n_layers=1, n_heads=HEADS,
                              n_experts=8, max_len=T, learning_rate=0.01)
    net = MultiLayerNetwork(conf).init()
    mesh = build_mesh({"data": 8})
    pw = (ParallelWrapper.builder(net)
          .mesh(mesh).prefetch_buffer(0)
          .expert_parallel("data", capacity_factor=1.0)
          .build())
    pw.fit(ListDataSetIterator(batches))
    assert np.isfinite(np.asarray(net.params())).all()
    assert np.isfinite(float(net.score_value))


def test_seq_and_expert_parallel_compose():
    """SP and EP in one mesh/fit: MoE LM with the sequence axis sharded for
    attention and the data axis doubling as the expert axis."""
    batches = _lm_batches(2)
    conf = lambda: moe_transformer_lm(VOCAB, width=WIDTH, n_layers=1,
                                      n_heads=HEADS, n_experts=4, max_len=T,
                                      learning_rate=0.01)
    single = _single_device_fit(conf(), batches)

    net = MultiLayerNetwork(conf()).init()
    mesh = build_mesh({"data": 2, "sp": 2})
    pw = (ParallelWrapper.builder(net)
          .mesh(mesh).prefetch_buffer(0)
          .sequence_parallel("sp")
          .expert_parallel("data", capacity_factor=4.0)
          .build())
    pw.fit(ListDataSetIterator(batches))
    np.testing.assert_allclose(np.asarray(single.params()),
                               np.asarray(net.params()),
                               atol=5e-5, rtol=1e-4)


def test_expert_parallel_rejects_indivisible_experts():
    """Explicit .expert_parallel() must engage or fail loudly — a silent
    dense fallback would defeat the request."""
    conf = moe_transformer_lm(VOCAB, width=WIDTH, n_layers=1, n_heads=HEADS,
                              n_experts=6, max_len=T)
    net = MultiLayerNetwork(conf).init()
    with pytest.raises(ValueError, match="not divisible"):
        (ParallelWrapper.builder(net).workers(8)
         .expert_parallel("data").build())
    lm = MultiLayerNetwork(transformer_lm(VOCAB, width=WIDTH, n_layers=1,
                                          n_heads=HEADS, max_len=T)).init()
    with pytest.raises(ValueError, match="no MoE"):
        (ParallelWrapper.builder(lm).workers(8)
         .expert_parallel("data").build())


def test_sequence_parallel_computation_graph():
    """The SP context also reaches attention layers inside a
    ComputationGraph (the wrapper serves both network types; reference
    ParallelWrapper wraps either)."""
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import (
        EmbeddingLayer, RnnOutputLayer, TransformerBlock)
    from deeplearning4j_tpu.nn.graph_network import ComputationGraph

    def conf():
        return (NeuralNetConfiguration.builder().seed(2).learning_rate(0.01)
                .updater("adam").graph_builder()
                .add_inputs("ids")
                .add_layer("emb", EmbeddingLayer(n_in=VOCAB, n_out=WIDTH),
                           "ids")
                .add_layer("blk", TransformerBlock(n_in=WIDTH, n_out=WIDTH,
                                                   n_heads=HEADS, causal=True),
                           "emb")
                .add_layer("out", RnnOutputLayer(n_in=WIDTH, n_out=VOCAB,
                                                 loss="mcxent",
                                                 activation="softmax"), "blk")
                .set_outputs("out").build())

    batches = _lm_batches(2)
    single = ComputationGraph(conf()).init()
    for ds in batches:
        single.fit([ds.features], [ds.labels])

    net = ComputationGraph(conf()).init()
    pw = (ParallelWrapper.builder(net)
          .mesh(build_mesh({"data": 2, "sp": 4})).prefetch_buffer(0)
          .sequence_parallel("sp").build())
    pw.fit(ListDataSetIterator(batches))
    np.testing.assert_allclose(np.asarray(single.params()),
                               np.asarray(net.params()),
                               atol=5e-5, rtol=1e-4)


def test_expert_parallel_computation_graph():
    """EP dispatch also reaches MoE layers inside a ComputationGraph, and
    the wrapper's expert-count validation sees graph vertices."""
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import (
        EmbeddingLayer, RnnOutputLayer)
    from deeplearning4j_tpu.nn.conf.layers.moe import MoETransformerBlock
    from deeplearning4j_tpu.nn.graph_network import ComputationGraph

    def conf():
        return (NeuralNetConfiguration.builder().seed(5).learning_rate(0.01)
                .updater("adam").graph_builder()
                .add_inputs("ids")
                .add_layer("emb", EmbeddingLayer(n_in=VOCAB, n_out=WIDTH),
                           "ids")
                .add_layer("moe", MoETransformerBlock(
                    n_in=WIDTH, n_out=WIDTH, n_heads=HEADS, n_experts=8,
                    causal=True), "emb")
                .add_layer("out", RnnOutputLayer(n_in=WIDTH, n_out=VOCAB,
                                                 loss="mcxent",
                                                 activation="softmax"), "moe")
                .set_outputs("out").build())

    batches = _lm_batches(2)
    single = ComputationGraph(conf()).init()
    for ds in batches:
        single.fit([ds.features], [ds.labels])

    net = ComputationGraph(conf()).init()
    pw = (ParallelWrapper.builder(net).workers(8).prefetch_buffer(0)
          .expert_parallel("data", capacity_factor=8.0).build())
    pw.fit(ListDataSetIterator(batches))
    np.testing.assert_allclose(np.asarray(single.params()),
                               np.asarray(net.params()),
                               atol=5e-5, rtol=1e-4)


def test_zero1_optimizer_sharding_equals_single_device():
    """ZeRO-1 (.shard_optimizer_state()): Adam moments live sharded over the
    data axis — per-device optimizer memory drops n_workers-fold — and
    training still equals single-device fit exactly (the sharding only
    changes WHERE the state lives; GSPMD inserts the collectives)."""
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer

    def conf():
        return (NeuralNetConfiguration.builder().seed(3).learning_rate(0.05)
                .updater("adam").list()
                .layer(DenseLayer(n_in=6, n_out=16, activation="tanh"))
                .layer(OutputLayer(n_in=16, n_out=3, loss="mcxent",
                                   activation="softmax")).build())

    rng = np.random.default_rng(0)
    batches = []
    for _ in range(4):
        x = rng.normal(size=(32, 6)).astype(np.float32)
        y = np.zeros((32, 3), np.float32)
        y[np.arange(32), rng.integers(0, 3, 32)] = 1
        batches.append(DataSet(x, y))

    single = MultiLayerNetwork(conf()).init()
    for ds in batches:
        single.fit(ds.features, ds.labels)

    net = MultiLayerNetwork(conf()).init()
    pw = (ParallelWrapper.builder(net).workers(8).prefetch_buffer(0)
          .shard_optimizer_state().build())
    pw.fit(ListDataSetIterator(batches))

    np.testing.assert_allclose(np.asarray(single.params()),
                               np.asarray(net.params()), atol=2e-6)
    # the memory contract: a shardable moment leaf holds 1/8 per device
    m = net.updater_state[1]["W"]["m"]          # (16, 3): 16 % 8 == 0
    assert m.addressable_shards[0].data.nbytes * 8 == m.nbytes
    b = net.updater_state[1]["b"]["m"]          # (3,): indivisible -> full
    assert b.addressable_shards[0].data.nbytes == b.nbytes
    with pytest.raises(ValueError, match="ZeRO"):
        (ParallelWrapper.builder(net).workers(8).averaging_frequency(2)
         .shard_optimizer_state().build())


def test_fsdp_parameter_sharding_equals_single_device():
    """FSDP (.shard_parameters() + .shard_optimizer_state()): params AND
    moments live 1/n per device; XLA all-gathers weights just-in-time and
    reduce-scatters grads; training equals single-device fit exactly."""
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer

    def conf():
        return (NeuralNetConfiguration.builder().seed(4).learning_rate(0.05)
                .updater("adam").list()
                .layer(DenseLayer(n_in=6, n_out=16, activation="tanh"))
                .layer(OutputLayer(n_in=16, n_out=3, loss="mcxent",
                                   activation="softmax")).build())

    rng = np.random.default_rng(1)
    batches = []
    for _ in range(4):
        x = rng.normal(size=(32, 6)).astype(np.float32)
        y = np.zeros((32, 3), np.float32)
        y[np.arange(32), rng.integers(0, 3, 32)] = 1
        batches.append(DataSet(x, y))

    single = MultiLayerNetwork(conf()).init()
    for ds in batches:
        single.fit(ds.features, ds.labels)

    net = MultiLayerNetwork(conf()).init()
    pw = (ParallelWrapper.builder(net).workers(8).prefetch_buffer(0)
          .shard_parameters().shard_optimizer_state().build())
    pw.fit(ListDataSetIterator(batches))

    np.testing.assert_allclose(np.asarray(single.params()),
                               np.asarray(net.params()), atol=2e-6)
    w = net.params_list[1]["W"]                 # (16, 3): dim0 sharded
    assert w.addressable_shards[0].data.nbytes * 8 == w.nbytes
    # inference still works on the sharded params (GSPMD gathers on use)
    out = np.asarray(net.output(batches[0].features))
    assert np.isfinite(out).all()


def test_local_sgd_rejects_sp():
    conf = transformer_lm(VOCAB, width=WIDTH, n_layers=1, n_heads=HEADS,
                          max_len=T)
    net = MultiLayerNetwork(conf).init()
    with pytest.raises(ValueError, match="averaging_frequency"):
        (ParallelWrapper.builder(net)
         .mesh(build_mesh({"data": 2, "sp": 4}))
         .averaging_frequency(4).sequence_parallel("sp").build())


def test_sequence_parallel_rejects_indivisible_sequence_length():
    """A batch whose time axis doesn't divide the sequence mesh axis must
    fail at staging with the axis and length NAMED — not as an opaque
    device_put/sharding error deep inside jit dispatch."""
    conf = transformer_lm(VOCAB, width=WIDTH, n_layers=1, n_heads=HEADS,
                          max_len=32)
    net = MultiLayerNetwork(conf).init()
    mesh = build_mesh({"data": 2, "sp": 4})
    pw = (ParallelWrapper.builder(net)
          .mesh(mesh).prefetch_buffer(0)
          .sequence_parallel("sp")
          .build())

    # divisible lengths stage with the [data, sp] spec
    from jax.sharding import PartitionSpec as P
    good = np.zeros((8, 16, VOCAB), np.float32)
    assert pw._batch_spec(good) == P("data", "sp")

    bad = np.zeros((8, 18, VOCAB), np.float32)  # 18 % 4 != 0
    with pytest.raises(ValueError) as ei:
        pw._batch_spec(bad)
    msg = str(ei.value)
    assert "'sp'" in msg and "18" in msg and "4" in msg
    # 2-D batches (no time axis) are untouched by the validation
    assert pw._batch_spec(np.zeros((8, 5), np.float32)) == P("data")
