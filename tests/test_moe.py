"""MoE layer (dense top-1 routing) and expert-parallel all_to_all execution."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.moe import MoELayer
from deeplearning4j_tpu.parallel.mesh import build_mesh
from deeplearning4j_tpu.parallel.moe import ExpertParallelMoE


def _layer_and_params(F=8, E=4, H=16, seed=0):
    lyr = MoELayer(n_in=F, n_out=F, n_experts=E, expert_hidden=H,
                   activation="identity")
    params = lyr.init_params(jax.random.PRNGKey(seed),
                             InputType.recurrent(F, 4))
    return lyr, params


def test_dense_moe_routes_top1():
    lyr, params = _layer_and_params()
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 4, 8)),
                    jnp.float32)
    y, _ = lyr.apply(params, {}, x)
    assert y.shape == x.shape
    # manual: each token through its argmax expert, gated
    x2d = x.reshape(-1, 8)
    eidx, gate, _ = lyr.route(params, x2d)
    for s in [0, 3, 7]:
        e = int(eidx[s])
        h = jax.nn.relu(x2d[s] @ params["W1"][e] + params["b1"][e])
        expect = (h @ params["W2"][e] + params["b2"][e]) * gate[s]
        np.testing.assert_allclose(np.asarray(y.reshape(-1, 8)[s]),
                                   np.asarray(expect), rtol=1e-5, atol=1e-6)


def test_expert_parallel_matches_dense():
    lyr, params = _layer_and_params(E=8)
    mesh = build_mesh({"expert": 4})
    ep = ExpertParallelMoE(lyr, mesh, capacity_factor=8.0)  # no drops
    x = jnp.asarray(np.random.default_rng(1).normal(size=(8, 4, 8)),
                    jnp.float32)
    got = ep(params, x)
    expect, _ = lyr.apply(params, {}, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=2e-4, atol=2e-5)


def test_expert_parallel_capacity_drops_tokens():
    lyr, params = _layer_and_params(E=4)
    # router forced to expert 0: all tokens collide, tiny capacity drops most
    params["Wg"] = jnp.zeros_like(params["Wg"]).at[:, 0].set(0.0)
    params["Wg"] = params["Wg"].at[0, 0].add(100.0)
    mesh = build_mesh({"expert": 4})
    ep = ExpertParallelMoE(lyr, mesh, capacity_factor=0.25)
    x = jnp.abs(jnp.asarray(np.random.default_rng(2).normal(size=(4, 4, 8)),
                            jnp.float32)) + 0.1
    got = np.asarray(ep(params, x))
    # some token outputs must be exactly zero (dropped), some nonzero
    norms = np.linalg.norm(got.reshape(-1, 8), axis=1)
    assert (norms == 0).any() and (norms > 0).any()


def test_load_balance_loss_bounds():
    lyr, params = _layer_and_params(E=4)
    x2d = jnp.asarray(np.random.default_rng(3).normal(size=(64, 8)),
                      jnp.float32)
    lb = float(lyr.load_balance_loss(params, x2d))
    # >= 1 by Cauchy-Schwarz (perfect balance == 1), finite and positive
    assert 0.99 <= lb < 4.0


def test_moe_gradients_flow():
    lyr, params = _layer_and_params()
    x = jnp.asarray(np.random.default_rng(4).normal(size=(2, 4, 8)),
                    jnp.float32)

    def loss(p):
        y, _ = lyr.apply(p, {}, x)
        return jnp.sum(y ** 2)

    g = jax.grad(loss)(params)
    assert float(jnp.abs(g["W1"]).sum()) > 0
    assert float(jnp.abs(g["Wg"]).sum()) > 0  # gate term keeps router trainable


def test_expert_parallel_applies_activation():
    lyr = MoELayer(n_in=8, n_out=8, n_experts=4, expert_hidden=16,
                   activation="tanh")
    params = lyr.init_params(jax.random.PRNGKey(9),
                             InputType.recurrent(8, 4))
    mesh = build_mesh({"expert": 4})
    ep = ExpertParallelMoE(lyr, mesh, capacity_factor=8.0)
    x = jnp.asarray(np.random.default_rng(9).normal(size=(4, 4, 8)),
                    jnp.float32)
    got = ep(params, x)
    expect, _ = lyr.apply(params, {}, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=2e-4, atol=2e-5)


def test_aux_load_balance_loss_enters_training_objective():
    """The Switch load-balance term must be part of the training loss (top-1
    routing collapses without it) and push gradient into the router weights."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import OutputLayer
    from deeplearning4j_tpu.nn.conf.layers.moe import MoELayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork, loss_fn

    def build(aux_w):
        conf = (NeuralNetConfiguration.builder().seed(11).list()
                .layer(MoELayer(n_in=6, n_out=6, n_experts=4,
                                expert_hidden=8, activation="relu",
                                aux_loss_weight=aux_w))
                .layer(OutputLayer(n_in=6, n_out=3, loss="mcxent",
                                   activation="softmax"))
                .build())
        return MultiLayerNetwork(conf).init(seed=11), conf

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(32, 6)).astype(np.float32))
    y = np.zeros((32, 3), np.float32)
    y[np.arange(32), rng.integers(0, 3, 32)] = 1
    y = jnp.asarray(y)

    net0, conf0 = build(0.0)
    net1, conf1 = build(0.5)
    key = jax.random.PRNGKey(1)
    l0, _ = loss_fn(conf0, net0.params_list, net0.state_list, x, y, key)
    l1, _ = loss_fn(conf1, net1.params_list, net1.state_list, x, y, key)
    # identical params/routing; the only difference is the weighted aux term
    assert float(l1) > float(l0)

    g1 = jax.grad(lambda p: loss_fn(conf1, p, net1.state_list, x, y, key)[0])(
        net1.params_list)
    g0 = jax.grad(lambda p: loss_fn(conf0, p, net0.state_list, x, y, key)[0])(
        net0.params_list)
    diff = float(jnp.abs(g1[0]["Wg"] - g0[0]["Wg"]).max())
    assert diff > 0, "aux loss contributes no router gradient"

    # inference keeps the published aux term at zero
    out, ns = conf1.layers[0].apply(net1.params_list[0], net1.state_list[0],
                                    x, train=False)
    assert float(ns["aux_loss"]) == 0.0


def test_moe_vertex_graph_tbptt_keeps_balance_term():
    """A MoE vertex trained under graph TBPTT must keep its load-balance
    term in the objective (round-3 gap: make_graph_tbptt_step dropped
    aux_loss; reference computeGradientAndScore:952 adds every layer's
    contribution regardless of backprop type)."""
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.graph_network import ComputationGraph

    def build(aux_w):
        conf = (NeuralNetConfiguration.builder()
                .seed(9).learning_rate(0.0)  # lr 0: params frozen, pure loss probe
                .graph_builder()
                .add_inputs("in")
                .add_layer("lstm", GravesLSTM(n_in=4, n_out=8,
                                              activation="tanh"), "in")
                .add_layer("moe", MoELayer(n_in=8, n_out=8, n_experts=4,
                                           expert_hidden=8,
                                           activation="identity",
                                           aux_loss_weight=aux_w), "lstm")
                .add_layer("out", RnnOutputLayer(n_in=8, n_out=4,
                                                 loss="mcxent",
                                                 activation="softmax"), "moe")
                .set_outputs("out")
                .backprop_type("TruncatedBPTT")
                .t_bptt_forward_length(4)
                .build())
        return ComputationGraph(conf).init()

    rng = np.random.default_rng(5)
    x = rng.normal(size=(4, 8, 4)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, (4, 8))]

    losses = {}
    for w in (0.0, 0.5):
        net = build(w)
        net.fit([x], [y])
        losses[w] = float(net.score_value)
    # same data, same seed, lr=0 -> identical data loss; the only
    # difference is the weighted balance term (>= 1.0 by construction)
    assert losses[0.5] > losses[0.0] + 0.4
