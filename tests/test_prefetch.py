"""Device-prefetch pipeline tests (datasets/prefetch.py + rewired fit loops).

Pins the three ISSUE-level guarantees on the CPU mesh:
  * overlap ordering — the next group's ``jax.device_put`` is issued before
    the previous dispatch's host-side completion (listener phase),
  * prefetch-on (default) vs prefetch-off numerical equivalence over
    ``fit_iterator`` — bit-identical params,
  * donation safety — depth-2 prefetch over reused host buffers never
    trips a deleted-buffer error (batch inputs are not in donate_argnums),
plus the AsyncDataSetIterator producer-thread-leak regression and the
prefetch metric families.
"""
import threading

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import (
    AsyncDataSetIterator, ListDataSetIterator,
)
from deeplearning4j_tpu.datasets.prefetch import DevicePrefetcher
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    DenseLayer, GravesLSTM, OutputLayer, RnnOutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.observability.metrics import global_registry


def _mlp_net(seed=12, lr=0.1):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(lr)
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                               activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init(seed=seed)


def _batches(n, batch=8, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        x = rng.normal(size=(batch, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, batch)]
        out.append(DataSet(x, y))
    return out


def _leaves(net):
    return [np.asarray(p) for p in jax.tree_util.tree_leaves(net.params_list)]


# ------------------------------------------------------------- DevicePrefetcher
def test_prefetcher_orders_and_stages():
    pf = DevicePrefetcher(iter(range(10)), lambda i: i * 2, depth=2, path=None)
    assert list(pf) == [i * 2 for i in range(10)]
    assert not pf.thread.is_alive()


def test_depth_zero_is_synchronous_inline():
    pf = DevicePrefetcher(iter(range(5)), lambda i: i + 1, depth=0, path=None)
    assert list(pf) == [1, 2, 3, 4, 5]
    assert pf.thread is None  # no producer thread at all


def test_error_propagates_after_prior_items():
    def src():
        yield 1
        yield 2
        raise RuntimeError("boom")

    got = []
    with pytest.raises(RuntimeError, match="boom"):
        for v in DevicePrefetcher(src(), None, depth=2, path=None):
            got.append(v)
    # same observable prefix as the synchronous loop
    assert got == [1, 2]


def test_stage_error_propagates_after_prior_items():
    def stage(i):
        if i == 2:
            raise ValueError("bad batch")
        return i

    got = []
    with pytest.raises(ValueError, match="bad batch"):
        for v in DevicePrefetcher(iter(range(5)), stage, depth=2, path=None):
            got.append(v)
    assert got == [0, 1]


def test_producer_runs_ahead_of_consumer():
    """While the consumer holds item 0, the producer stages item 1 in the
    background — the overlap DevicePrefetcher exists for."""
    staged_next = threading.Event()

    def stage(i):
        if i == 1:
            staged_next.set()
        return i

    pf = DevicePrefetcher(iter(range(4)), stage, depth=2, path=None)
    it = iter(pf)
    assert next(it) == 0
    # the consumer is "computing" on item 0 right now; item 1 must get
    # staged concurrently without another next() call
    assert staged_next.wait(timeout=10.0)
    assert list(it) == [1, 2, 3]


def test_close_unblocks_full_queue_producer():
    """A consumer that abandons iteration must not strand the producer on a
    full queue (the reference AsyncDataSetIterator leak)."""
    pf = DevicePrefetcher(iter(range(100)), None, depth=1, path=None)
    it = iter(pf)
    assert next(it) == 0  # producer now refilling a full queue
    pf.close()
    pf.thread.join(timeout=5.0)
    assert not pf.thread.is_alive()
    pf.close()  # idempotent


def test_async_iterator_early_exit_no_thread_leak():
    """Regression: breaking out of an AsyncDataSetIterator loop used to leave
    the producer thread blocked forever on its bounded queue."""
    ait = AsyncDataSetIterator(ListDataSetIterator(_batches(50)), queue_size=2)
    for _ in ait:
        break  # abandon mid-iteration
    ait.close()
    t = ait._pf.thread
    t.join(timeout=5.0)
    assert not t.is_alive()
    # the iterator is reusable after the abandoned pass
    assert sum(1 for _ in ait) == 50
    ait.close()


def test_async_iterator_reset_joins_producer():
    ait = AsyncDataSetIterator(ListDataSetIterator(_batches(20)), queue_size=2)
    it = iter(ait)
    next(it)
    old = ait._pf.thread
    ait.reset()
    old.join(timeout=5.0)
    assert not old.is_alive()
    assert sum(1 for _ in ait) == 20
    ait.close()


# ------------------------------------------------------------ fit-path overlap
def test_overlap_ordering_put_before_host_completion(monkeypatch):
    """The ordering the tentpole promises: the NEXT group's device_put is
    issued while the PREVIOUS dispatch's host-side completion (listener
    phase) is still pending."""
    next_group_in_flight = threading.Event()
    n_puts = [0]
    real_put = jax.device_put

    def spy(x, *a, **kw):
        n_puts[0] += 1
        # group 1 stages via puts 1-2 (xs, ys); put 3 = group 2 in flight
        if n_puts[0] >= 3:
            next_group_in_flight.set()
        return real_put(x, *a, **kw)

    monkeypatch.setattr(jax, "device_put", spy)

    overlap = []

    class BlockingListener:
        def iteration_done(self, model, iteration):
            if not overlap:
                # we are inside dispatch 1's host-side completion; a working
                # prefetcher issues group 2's transfer concurrently
                overlap.append(next_group_in_flight.wait(timeout=30.0))

    net = _mlp_net(seed=3)
    net.dispatch_ksteps = 2
    net.prefetch_depth = 2
    net.set_listeners(BlockingListener())
    net.fit_iterator(ListDataSetIterator(_batches(8)))
    assert overlap and overlap[0], (
        "next group's device_put was not issued before the previous "
        "dispatch's host-side completion")


# -------------------------------------------------------- numerical equivalence
def test_prefetch_on_off_bit_identical_params():
    """Default prefetch (depth 2) must produce BIT-identical params to the
    synchronous depth-0 path over fit_iterator, including the ragged tail
    that flushes a short group."""
    data = _batches(7) + _batches(1, batch=5, seed=99)

    def run(depth):
        net = _mlp_net(seed=7)
        net.dispatch_ksteps = 2
        net.prefetch_depth = depth
        net.fit_iterator(ListDataSetIterator(data), epochs=2)
        return _leaves(net)

    on, off = run(2), run(0)
    assert len(on) == len(off)
    for a, b in zip(on, off):
        assert np.array_equal(a, b)


def test_prefetch_equivalence_with_masked_fallback():
    """Masked batches route through the per-batch fallback mid-stream; the
    grouped/fallback interleaving must be order-identical with and without
    prefetch (bit-identical params)."""
    B, T, C = 4, 5, 3
    rng = np.random.default_rng(3)

    def seq_ds(masked=False):
        x = rng.normal(size=(B, T, C)).astype(np.float32)
        y = np.eye(C, dtype=np.float32)[rng.integers(0, C, (B, T))]
        lm = None
        if masked:
            lm = np.ones((B, T), np.float32)
            lm[:, T // 2:] = 0
        return DataSet(x, y, labels_mask=lm)

    data = [seq_ds(), seq_ds(), seq_ds(masked=True), seq_ds(), seq_ds()]
    conf_b = (NeuralNetConfiguration.builder().seed(5).learning_rate(0.1)
              .list()
              .layer(GravesLSTM(n_in=C, n_out=6, activation="tanh"))
              .layer(RnnOutputLayer(n_in=6, n_out=C, loss="mcxent",
                                    activation="softmax")))

    def run(depth):
        net = MultiLayerNetwork(conf_b.build()).init(seed=5)
        net.dispatch_ksteps = 2
        net.prefetch_depth = depth
        net.fit_iterator(ListDataSetIterator(data))
        return _leaves(net)

    for a, b in zip(run(2), run(0)):
        assert np.array_equal(a, b)


def test_wrapper_prefetch_equivalence():
    """ParallelWrapper sync DP with device prefetch == without (same sharded
    staging, same order), bit-for-bit."""
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

    def conf():
        return (NeuralNetConfiguration.builder()
                .seed(1).learning_rate(0.1)
                .list()
                .layer(DenseLayer(n_in=6, n_out=10, activation="tanh"))
                .layer(OutputLayer(n_in=10, n_out=3, loss="mcxent",
                                   activation="softmax"))
                .build())

    rng = np.random.default_rng(0)
    data = []
    for _ in range(6):
        x = rng.normal(size=(32, 6)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
        data.append(DataSet(x, y))

    def run(prefetch):
        net = MultiLayerNetwork(conf()).init(seed=1)
        (ParallelWrapper.builder(net)
         .workers(8).prefetch_buffer(prefetch).averaging_frequency(1)
         .build()).fit(ListDataSetIterator(data))
        return _leaves(net)

    for a, b in zip(run(2), run(0)):
        assert np.array_equal(a, b)


# -------------------------------------------------------------- donation safety
def test_donation_safety_under_depth2_prefetch():
    """Depth-2 prefetch stages batches from the SAME host arrays every step
    while the donated (params/states/updater) dispatch is in flight. Staged
    buffers are fresh, non-donated device arrays, so nothing may raise a
    deleted-buffer error and the net stays usable."""
    net = _mlp_net(seed=5)
    net.dispatch_ksteps = 2
    net.prefetch_depth = 2
    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
    data = [DataSet(x, y) for _ in range(8)]  # shared backing buffers
    net.fit_iterator(ListDataSetIterator(data), epochs=2)
    for p in _leaves(net):
        assert np.isfinite(p).all()
    out = np.asarray(net.output(x))
    assert np.isfinite(out).all()


# ------------------------------------------------------------------- telemetry
def test_prefetch_metric_families_exposed():
    net = _mlp_net(seed=9)
    net.dispatch_ksteps = 2
    net.fit_iterator(ListDataSetIterator(_batches(6)))
    snap = global_registry().snapshot()
    for fam in ("dl4j_prefetch_depth", "dl4j_prefetch_bytes_total",
                "dl4j_prefetch_staging_seconds_total",
                "dl4j_prefetch_wait_seconds_total",
                "dl4j_prefetch_overlap_ratio"):
        assert fam in snap, fam
    by_path = {s["labels"].get("path"): s
               for s in snap["dl4j_prefetch_bytes_total"]["series"]}
    assert by_path["multilayer"]["value"] > 0
    ratios = [s["value"]
              for s in snap["dl4j_prefetch_overlap_ratio"]["series"]
              if s["labels"].get("path") == "multilayer"]
    assert ratios and 0.0 <= ratios[0] <= 1.0
