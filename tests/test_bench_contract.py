"""Pins the XLA behaviors bench.py's MFU accounting depends on.

bench.py multiplies XLA's cost-analysis flop count by K for K-step scanned
dispatches because cost analysis counts a scan body ONCE, not trip-count
times. If an XLA upgrade changes that, this test fails and bench.py's
`_xla_flops` callers must drop their `* ksteps`.
"""
import jax
import jax.numpy as jnp
import numpy as np


def _cost_flops(jit_fn, *args) -> float:
    cost = jit_fn.lower(*args).compile().cost_analysis()
    cost = cost[0] if isinstance(cost, (list, tuple)) else cost
    return float((cost or {}).get("flops", 0.0))


def test_cost_analysis_counts_scan_body_once():
    w = jnp.asarray(np.random.default_rng(0).normal(size=(64, 64)),
                    jnp.float32)

    def multi(w, xs):
        def body(carry, x):
            return carry, jnp.sum(jnp.dot(x, w))

        _, outs = jax.lax.scan(body, 0.0, xs)
        return outs

    jit_multi = jax.jit(multi)
    costs = []
    for k in (1, 4):
        xs = jnp.ones((k, 32, 64), jnp.float32)
        costs.append(_cost_flops(jit_multi, w, xs))
    assert costs[0] > 0
    # body counted once: flops near-identical despite 4x the executed steps
    # (a couple of scalar loop-counter flops may differ; 4x would mean XLA
    # started scaling with trip count)
    assert costs[1] < costs[0] * 1.5, (
        "XLA cost analysis now scales scan flops with trip count; "
        "remove the `* ksteps` factors in bench.py::_xla_flops callers")


def test_outage_record_carries_last_healthy(tmp_path):
    """A relay-outage error record must embed the most recent healthy
    on-chip capture of the same config from scripts/bench_log.jsonl (round-3
    lesson: an outage at round end erased all perf evidence)."""
    import json

    import bench

    log = tmp_path / "bench_log.jsonl"
    rows = [
        {"args": "--model resnet50 --bf16-matmul", "ts": "t1",
         "rec": {"metric": "m", "value": 100.0}},
        {"args": "--model resnet50 --bf16-act", "ts": "t2",
         "rec": {"metric": "m", "value": 200.0}},
        {"args": "--model resnet50 --bf16-act --batch 256", "ts": "t3",
         "rec": {"metric": "m", "value": 300.0}},
        {"args": "--model resnet50", "ts": "t4",
         "rec": {"metric": "m", "value": 0.0, "error": "down"}},
    ]
    log.write_text("\n".join(json.dumps(r) for r in rows))
    # SAME config only: a different-dtype or batch-swept row must not stand
    # in for the default run; measurement-only flags are ignored. Since
    # round 5 a bare invocation IS the model's measured-best dtype
    # (bf16_act for resnet50), so it matches explicit --bf16-act rows.
    got = bench._last_healthy_from_log("--model resnet50 --attempts 1",
                                       path=str(log))
    assert got["ts"] == "t2" and got["record"]["value"] == 200.0
    got = bench._last_healthy_from_log("--model resnet50 --bf16-matmul",
                                       path=str(log))
    assert got["ts"] == "t1"
    got = bench._last_healthy_from_log(
        "--model resnet50 --bf16-act --batch 256", path=str(log))
    assert got["ts"] == "t3"
    assert bench._last_healthy_from_log("--model word2vec",
                                        path=str(log)) is None
    # per-model dtype defaults: tiny models keep bf16-matmul (bf16-act
    # measured slower there — BASELINE.md round-5)
    assert bench._config_key("--model lenet")["dtype"] == "bf16"
    assert bench._config_key("--model transformer")["dtype"] == "bf16_act"
    # the driver's end-of-round run is BARE: it must resolve to the same
    # config as explicit '--model resnet50 --bf16-act' capture rows, or an
    # outage round serves no last_healthy at all (the round-3/4 failure)
    got = bench._last_healthy_from_log("--attempts 1", path=str(log))
    assert got is not None and got["ts"] == "t2"


def test_tile_sweep_isolates_failures_and_picks_best():
    """The flash tile sweep runs unattended in the auto-capture window: a
    failing config must record an error string (not kill the bench), the
    best config is the fastest timed one, and the module tile globals are
    restored afterwards."""
    import bench
    from deeplearning4j_tpu.ops import pallas_kernels as pk

    calls = []

    def fake_time_once():
        calls.append((pk._BLK_Q, pk._BLK_K))
        if pk._BLK_Q == 256 and pk._BLK_K == 128:
            raise RuntimeError("VMEM OOM")
        return 0.001 * pk._BLK_Q / pk._BLK_K  # fastest: 128x512

    saved = pk._BLK_Q, pk._BLK_K
    out = bench._sweep_tiles(fake_time_once, seq=2048)
    assert (pk._BLK_Q, pk._BLK_K) == saved  # globals restored
    assert out["best_tiles"] == "128x512"  # smallest bq/bk ratio timed
    assert out["tile_sweep_ms"]["256x128"].startswith("error:")
    assert len(calls) == 6  # every config visited despite the failure


def test_reduction_dtype_config_resolution():
    """--reduction-dtype resolution and bench_log config matching: explicit
    flag wins; bf16-act defaults to bf16 statistics (round 6); every other
    mode defaults to f32; and rows logged BEFORE the round-6 default change
    are reinterpreted as f32 so an outage can never serve a wrong-reduction
    number as 'the same config'."""
    import bench

    assert bench._reduction_mode("bf16_act", None) == "bf16"
    assert bench._reduction_mode("bf16_act", "f32") == "f32"
    assert bench._reduction_mode("bf16", None) == "f32"
    assert bench._reduction_mode("f32", "bf16") == "bf16"

    # ts after the round-6 change: bare bf16-act rows mean bf16 statistics
    key = bench._config_key("--model resnet50 --bf16-act",
                            ts="2026-08-06T00:00:00Z")
    assert key["rdtype"] == "bf16"
    # ts before the change: the same args ran at-least-f32 statistics
    key = bench._config_key("--model resnet50 --bf16-act",
                            ts="2026-08-01T00:00:00Z")
    assert key["rdtype"] == "f32"
    # an explicit flag is authoritative regardless of age
    key = bench._config_key("--model resnet50 --bf16-act "
                            "--reduction-dtype f32",
                            ts="2026-08-06T00:00:00Z")
    assert key["rdtype"] == "f32"
    # the two reduction modes are DIFFERENT configs for outage matching
    a = bench._config_key("--model resnet50 --bf16-act")
    b = bench._config_key("--model resnet50 --bf16-act --reduction-dtype f32")
    assert a != b


def test_bench_reduction_dtype_flag_end_to_end(tmp_path):
    """bench.py --reduction-dtype runs the flagship recipe clean on CPU and
    stamps the resolved reduction mode into the record (the BASELINE.md
    provenance requirement: every number names its reduction policy)."""
    import json
    import os
    import subprocess
    import sys

    import bench

    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    cmd = [sys.executable, os.path.join(os.path.dirname(bench.__file__),
                                        "bench.py"),
           "--model", "lenet", "--batch", "8", "--iters", "2",
           "--ksteps", "1", "--bf16-act", "--reduction-dtype", "bf16",
           "--attempts", "1", "--attempt-timeout", "180"]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=200,
                          env=env)
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert "error" not in rec, rec
    assert rec["value"] > 0
    assert rec["detail"]["dtype"] == "bf16_act"
    assert rec["detail"]["reduction_dtype"] == "bf16"


def test_telemetry_overhead_budget():
    """Telemetry (including the prefetch families AND the training-health
    monitor at its check cadence) must cost <=2% of a LeNet fit step.
    Budget-style rather than a wall-clock A/B (which flakes on shared CI
    hosts): measure the real per-step time of the instrumented loop —
    driven through fit_iterator with device prefetch ON and a HealthMonitor
    + NanAlertListener attached so the health metrics are in the measured
    window — microbenchmark the registry primitives it calls, bound the
    ops issued per step from registry deltas, and require
    ops_per_step * per_op_cost <= 2% of the step time."""
    import time

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.models.lenet import lenet_mnist
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.observability import (
        HealthMonitor, MetricsRegistry, NanAlertListener, TelemetryListener,
        global_registry,
    )

    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 784)).astype(np.float32)
    y = np.zeros((8, 10), np.float32)
    y[np.arange(8), rng.integers(0, 10, 8)] = 1
    ksteps = 2
    health_cadence = 4
    net = MultiLayerNetwork(lenet_mnist()).init()
    net.dispatch_ksteps = ksteps
    HealthMonitor(cadence=health_cadence, dump_on_alarm=False).attach(net)
    net.set_listeners(TelemetryListener(sync_every=1, hbm_every=1,
                                        worker_id="overhead_budget"),
                      NanAlertListener())
    # warmup: compile the fused step (both health variants) outside the
    # measured window
    net.fit_iterator(ListDataSetIterator([DataSet(x, y)] * 2 * ksteps))

    def _mutation_count(reg):
        # counter value == #incs (unit increments in the fit path),
        # histogram count == #observes; add every gauge series as one
        # set per step (upper bound: they are set at most once a step).
        # Quantity counters (*_bytes_total / *_seconds_total) increment by
        # measured amounts, not by 1 — their value is NOT an op count, so
        # they are excluded here and charged explicitly below. The health
        # gauges hold arbitrary floats (norms, EMA) rather than op counts,
        # so they too are excluded and charged explicitly per cadence.
        total = 0.0
        for name, fam in reg.snapshot().items():
            if name.endswith(("_bytes_total", "_seconds_total")):
                continue
            for s in fam["series"]:
                if fam["type"] == "gauge" and name.startswith("dl4j_health_"):
                    continue
                total += s["count"] if "count" in s else max(s["value"], 1.0)
        return total

    before = _mutation_count(global_registry())
    n_steps = 12
    data = [DataSet(x, y) for _ in range(n_steps)]
    t0 = time.perf_counter()
    net.fit_iterator(ListDataSetIterator(data))
    score = net.score_value
    float(score() if callable(score) else score)
    step_s = (time.perf_counter() - t0) / n_steps
    ops_per_step = (_mutation_count(global_registry()) - before) / n_steps
    # HBM gauges are 0.0 on CPU (memory_stats is None) so their sets are
    # invisible to the value delta — add them explicitly.
    ops_per_step += 2 * len(jax.local_devices()) + 2
    # DevicePrefetcher ops excluded or invisible above, charged per GROUP
    # (k steps): producer staging.inc + bytes.inc + depth.set, consumer
    # wait.inc + depth.set + overlap.set = 6 (the wait_series observe is a
    # histogram count, already in the delta).
    ops_per_step += 6 / ksteps
    # health gauges excluded above, charged per CHECK: grad/update/nonfinite
    # norm sets + loss-EMA set = 4 (the checks counter inc is a unit counter,
    # already in the delta). The fused K-group path checks at most once per
    # group, so the effective cadence is max(cadence, ksteps).
    ops_per_step += 4 / max(health_cadence, ksteps)
    assert ops_per_step > 0  # the loop really is instrumented

    probe = MetricsRegistry()
    c = probe.counter("probe_total").labels(k="x")
    h = probe.histogram("probe_seconds").labels(k="x")
    n_probe = 20000
    t0 = time.perf_counter()
    for _ in range(n_probe):
        c.inc()
        h.observe(0.001)
    per_op_s = (time.perf_counter() - t0) / (2 * n_probe)

    overhead = ops_per_step * per_op_s
    assert overhead <= 0.02 * step_s, (
        f"telemetry budget blown: {ops_per_step:.0f} registry ops/step x "
        f"{per_op_s * 1e6:.2f}us = {overhead * 1e3:.3f}ms vs step "
        f"{step_s * 1e3:.1f}ms")


def test_federation_overhead_budget():
    """The federation publisher must cost <=2% of an elastic worker's
    wall clock. Budget-style like the telemetry test above: the publisher
    is TIME-driven (one flush per DEFAULT_INTERVAL_S on its own thread),
    so its duty cycle is flush_cost / interval regardless of how many fit
    steps land inside an interval — requiring
    ``flush_cost <= 0.02 * DEFAULT_INTERVAL_S`` bounds the overhead at 2%
    of ANY elastic fit step schedule. Measured over a real TcpTransport to
    a live frontend with a representatively-populated registry, so the
    cost includes snapshotting, JSON framing, the socket round trip, and
    the coordinator-side merge."""
    import time

    from deeplearning4j_tpu.observability.federation import (
        DEFAULT_INTERVAL_S, FederatedRegistry, MetricsPublisher,
    )
    from deeplearning4j_tpu.observability.flight_recorder import (
        FlightRecorder,
    )
    from deeplearning4j_tpu.observability.metrics import MetricsRegistry
    from deeplearning4j_tpu.observability.tracing import TraceStore
    from deeplearning4j_tpu.parallel.param_server import ParameterServer
    from deeplearning4j_tpu.parallel.ps_transport import (
        ParameterServerTcpFrontend, TcpTransport,
    )

    # a registry shaped like a real elastic worker's: a handful of counter
    # series, the push/step histograms with spread-out observations, gauges
    reg = MetricsRegistry()
    for i in range(8):
        reg.counter("dl4j_ps_worker_steps_total").labels(
            worker=str(i)).inc(100 + i)
    h = reg.histogram("dl4j_ps_push_seconds").labels()
    hs = reg.histogram("dl4j_step_seconds").labels()
    for i in range(64):
        h.observe(0.001 * (i + 1))
        hs.observe(0.002 * (i + 1))
    reg.gauge("dl4j_ps_version").labels().set(123)
    rec = FlightRecorder(capacity=256, registry=reg)
    for i in range(32):
        rec.record("push_window", window=i)

    fed = FederatedRegistry(registry=MetricsRegistry(),
                            trace_store=TraceStore())
    srv = ParameterServer([np.zeros(8, np.float32)])
    frontend = ParameterServerTcpFrontend(srv, federation=fed).start()
    t = TcpTransport(("127.0.0.1", frontend.port))
    try:
        pub = MetricsPublisher(t, name="budget-w0", interval_s=999.0,
                               registry=reg, recorder=rec,
                               trace_store=TraceStore())
        assert pub.flush()  # warm the path outside the measured window
        n = 50
        t0 = time.perf_counter()
        for i in range(n):
            reg.counter("dl4j_ps_worker_steps_total").labels(
                worker="0").inc()  # the snapshot must not be cached
            assert pub.flush()
        flush_s = (time.perf_counter() - t0) / n
    finally:
        t.close()
        frontend.stop()
    assert flush_s <= 0.02 * DEFAULT_INTERVAL_S, (
        f"federation budget blown: flush costs {flush_s * 1e3:.3f}ms, "
        f"duty cycle {flush_s / DEFAULT_INTERVAL_S * 100:.2f}% of the "
        f"{DEFAULT_INTERVAL_S * 1e3:.0f}ms publish interval (budget 2%)")


def test_grid_rows_vgg16_and_lstm_hidden():
    """The round-6 grid additions are wired end-to-end: vgg16 is a
    first-class model (metric name, defaults, bench fn) and --hidden is a
    config-distinguishing axis for the char_rnn MFU-floor row."""
    import bench

    assert bench._METRICS["vgg16"] == "vgg16_samples_per_sec_per_chip"
    assert "vgg16" in bench._DEFAULTS
    assert "vgg16" in bench._bench_fns()
    # --hidden distinguishes configs in outage matching: the hidden>=1024
    # MFU-floor row must never be served by a hidden=200 capture
    a = bench._config_key("--model char_rnn")
    b = bench._config_key("--model char_rnn --hidden 1024")
    assert a != b and b["hidden"] == "1024"


def test_config_key_lstm_impl_axis():
    """--lstm-impl is config-distinct for char_rnn rows (an explicit scan-
    headline row must not stand in for the auto/fused default), and rows
    logged before the recurrent engine landed reinterpret as the scan path
    they actually measured — the same timestamp-guard pattern as the dtype
    and reduction-dtype default changes."""
    import bench

    a = bench._config_key("--model char_rnn --hidden 1024")
    b = bench._config_key("--model char_rnn --hidden 1024 --lstm-impl scan")
    assert a != b and a["lstm_impl"] == "auto" and b["lstm_impl"] == "scan"
    # non-recurrent models don't grow a phantom axis
    assert bench._config_key("--model resnet50")["lstm_impl"] is None
    # pre-engine bare rows ran the old scan path
    old = bench._config_key("--model char_rnn",
                            ts="2026-08-05T11:59:59Z")
    new = bench._config_key("--model char_rnn",
                            ts="2026-08-05T12:00:01Z")
    assert old["lstm_impl"] == "scan" and new["lstm_impl"] == "auto"


def test_config_key_sharding_axis():
    """--sharding is config-distinct for the flagship fit models (a dp_tp
    row must not stand in for the single-device headline), non-capable
    models don't grow a phantom axis, and rows logged before the sharding
    engine landed reinterpret as the single-device path they actually
    measured — the same timestamp-guard pattern as the other axis gates."""
    import bench

    a = bench._config_key("--model transformer")
    b = bench._config_key("--model transformer --sharding dp_tp")
    assert a != b and a["sharding"] is None and b["sharding"] == "dp_tp"
    assert bench._config_key(
        "--model fit_resnet50 --sharding zero3")["sharding"] == "zero3"
    # non-capable models don't grow a phantom axis
    assert bench._config_key("--model char_rnn")["sharding"] is None
    assert bench._SHARDING_CAPABLE == frozenset(
        {"fit_resnet50", "transformer"})
    # pre-engine rows measured the single-device path, whatever a later
    # reader asks for
    old = bench._config_key("--model transformer --sharding dp",
                            ts="2026-08-05T19:59:59Z")
    new = bench._config_key("--model transformer --sharding dp",
                            ts="2026-08-05T20:00:01Z")
    assert old["sharding"] is None and new["sharding"] == "dp"
    ts = bench._SHARDING_AXIS_LANDED_TS
    assert ts.endswith("Z") and ts > bench._XPLANE_ATTRIBUTION_LANDED_TS


def test_xplane_attribution_contract():
    """xplane attribution is measurement-only and ts-gated: the flag never
    makes a config distinct (a prior healthy row stands in during an
    outage), the landed-ts postdates the lstm-impl gate it stacks on, and
    the attribution field names bench rows carry are pinned."""
    import bench

    a = bench._config_key("--model resnet50")
    b = bench._config_key("--model resnet50 --xplane-attribution")
    assert a == b  # like --telemetry-out: does not change what is measured
    # same measurement-only rule on a recurrent row with its impl axis set
    assert bench._config_key(
        "--model char_rnn --hidden 1024 --xplane-attribution") == \
        bench._config_key("--model char_rnn --hidden 1024")

    ts = bench._XPLANE_ATTRIBUTION_LANDED_TS
    assert ts.endswith("Z") and len(ts) == len("2026-08-05T16:00:00Z")
    assert ts > bench._LSTM_IMPL_DEFAULT_CHANGE_TS  # ISO-8601 sorts

    assert bench.XPLANE_ATTRIBUTION_FIELDS == (
        "xplane_attribution", "profile_trace", "profile_error",
        "profile_variant")
    # the capture-capable set covers every multistep-harness model; models
    # outside it must degrade to profile_error, never crash (pinned so a
    # new model is consciously added or consciously excluded)
    assert bench._PROFILE_CAPABLE == frozenset(
        {"lenet", "resnet50", "vgg16", "char_rnn", "transformer", "moe"})


def test_config_key_serve_axes():
    """The serving A/B's load shape is config-distinct: an explicit
    --serve-qps row must not stand in for the auto-calibrated headline
    (offered rate IS the config under an open-loop client), the coalescing
    window is an axis for the same reason, other models don't grow phantom
    serve axes, and the ts-gate ignores the axes on rows that predate the
    serving engine — the same pattern as the sharding gate."""
    import bench

    a = bench._config_key("--model serve")
    b = bench._config_key("--model serve --serve-qps 800")
    c = bench._config_key("--model serve --serve-latency-ms 8")
    assert a != b and a["serve_qps"] == "auto" and b["serve_qps"] == "800"
    assert a != c and c["serve_latency_ms"] == "8"
    assert a["serve_latency_ms"] == "4"  # the bench_serve default, pinned
    # non-serve models don't grow phantom axes
    r = bench._config_key("--model resnet50")
    assert r["serve_qps"] is None and r["serve_latency_ms"] is None
    # rows logged before the serving engine landed cannot be serve rows;
    # the gate strips the axes rather than invent a config for them
    old = bench._config_key("--model serve --serve-qps 800",
                            ts="2026-08-05T21:59:59Z")
    new = bench._config_key("--model serve --serve-qps 800",
                            ts="2026-08-05T22:00:01Z")
    assert old["serve_qps"] is None and new["serve_qps"] == "800"
    ts = bench._SERVE_AXIS_LANDED_TS
    assert ts.endswith("Z") and ts > bench._SHARDING_AXIS_LANDED_TS


def test_config_key_serve_decode_axes():
    """The decode section's scheduling mode and weight quantization are
    config-distinct serve axes: a static-batching or int8 capture must
    never stand in for the continuous dense row (they measure different
    engines), other models don't grow phantom axes, and the ts-gate
    strips the axes on rows that predate the decode section — those rows
    carry no decode numbers, so normalizing their axes to None (never
    equal to a live request's resolved defaults) keeps an outage from
    serving a decode-less row for a decode-bearing request."""
    import bench

    a = bench._config_key("--model serve")
    b = bench._config_key("--model serve --serve-batching static")
    c = bench._config_key("--model serve --serve-quant int8")
    assert a != b and a["serve_batching"] == "continuous" \
        and b["serve_batching"] == "static"
    assert a != c and a["serve_quant"] == "none" \
        and c["serve_quant"] == "int8"
    # non-serve models don't grow phantom axes
    r = bench._config_key("--model resnet50")
    assert r["serve_batching"] is None and r["serve_quant"] is None
    # rows logged before the decode section landed never match post-landing
    # requests (axes None vs resolved defaults)
    old = bench._config_key("--model serve", ts="2026-08-05T23:29:59Z")
    new = bench._config_key("--model serve", ts="2026-08-05T23:30:01Z")
    assert old["serve_batching"] is None and old["serve_quant"] is None
    assert new["serve_batching"] == "continuous" \
        and new["serve_quant"] == "none"
    assert old != bench._config_key("--model serve")
    ts = bench._SERVE_DECODE_AXIS_LANDED_TS
    assert ts.endswith("Z") and ts > bench._PS_AXIS_LANDED_TS


def test_config_key_serve_replica_axes():
    """The replica-scaling section's fleet size and serving rule set are
    config-distinct serve axes: a 4-replica or dp_tp-sharded capture must
    never stand in for the 2-replica single-device row (they measure
    different serving topologies), other models don't grow phantom axes,
    and the ts-gate strips the axes on rows that predate the ReplicaSet —
    those rows carry no replica-scaling numbers, so normalizing their axes
    to None keeps an outage from serving a replica-less row. The serve
    scenario's sharding rides its OWN ``--serve-sharding`` flag, never the
    fit path's ``--sharding`` axis."""
    import bench

    a = bench._config_key("--model serve")
    b = bench._config_key("--model serve --serve-replicas 4")
    c = bench._config_key("--model serve --serve-sharding dp_tp")
    assert a != b and a["serve_replicas"] == "2" \
        and b["serve_replicas"] == "4"
    assert a != c and a["serve_sharding"] == "none" \
        and c["serve_sharding"] == "dp_tp"
    # non-serve models don't grow phantom axes
    r = bench._config_key("--model resnet50")
    assert r["serve_replicas"] is None and r["serve_sharding"] is None
    # rows logged before the replica section landed never match
    # post-landing requests (axes None vs resolved defaults)
    old = bench._config_key("--model serve", ts="2026-08-05T23:59:59Z")
    new = bench._config_key("--model serve", ts="2026-08-06T00:00:01Z")
    assert old["serve_replicas"] is None and old["serve_sharding"] is None
    assert new["serve_replicas"] == "2" and new["serve_sharding"] == "none"
    assert old != bench._config_key("--model serve")
    ts = bench._SERVE_REPLICA_AXIS_LANDED_TS
    assert ts.endswith("Z") and ts > bench._SERVE_DECODE_AXIS_LANDED_TS
    # serve never joins the fit path's sharding grid
    assert "serve" not in bench._SHARDING_CAPABLE


def test_grid_row_serve():
    """The serve scenario is wired through the whole bench surface: grid
    membership, the requests/sec unit (the one non-samples/sec headline),
    the f32 dtype default (bf16 convert ops would dominate the tiny
    serving model like they do LeNet), and profile-incapable (the A/B
    runs its own servers, not the multistep harness)."""
    import bench

    assert bench._METRICS["serve"] == "serve_batched_requests_per_sec"
    assert "serve" in bench._DEFAULTS and "serve" in bench._bench_fns()
    assert bench._UNITS["serve"] == "requests/sec"
    assert bench._DTYPE_DEFAULT["serve"] == "f32"
    assert "serve" not in bench._PROFILE_CAPABLE
    assert "serve" not in bench._SHARDING_CAPABLE
    batch, iters, _ = bench._DEFAULTS["serve"]
    assert batch >= 8  # max_batch: must exercise multiple pow2 buckets
    assert iters >= 2  # seconds per phase


def test_config_key_ps_axes():
    """The ps_async A/B's straggler shape is config-distinct: a 2-worker or
    8x-straggler capture must never stand in for the standard 4-worker/4x
    row (the barrier cost being measured IS a function of both), other
    models don't grow phantom ps axes, and the ts-gate strips the axes on
    rows that predate the async-PS engine — same pattern as serve."""
    import bench

    a = bench._config_key("--model ps_async")
    b = bench._config_key("--model ps_async --ps-workers 8")
    c = bench._config_key("--model ps_async --ps-straggler 2")
    assert a != b and a["ps_workers"] == "4" and b["ps_workers"] == "8"
    assert a != c and c["ps_straggler"] == "2"
    assert a["ps_straggler"] == "4"  # the bench_ps_async default, pinned
    # non-ps models don't grow phantom axes
    r = bench._config_key("--model lenet")
    assert r["ps_workers"] is None and r["ps_straggler"] is None
    # rows logged before the async-PS engine landed cannot be ps rows
    old = bench._config_key("--model ps_async --ps-workers 8",
                            ts="2026-08-05T22:00:29Z")
    new = bench._config_key("--model ps_async --ps-workers 8",
                            ts="2026-08-05T22:00:31Z")
    assert old["ps_workers"] is None and new["ps_workers"] == "8"
    ts = bench._PS_AXIS_LANDED_TS
    assert ts.endswith("Z") and ts > bench._SERVE_AXIS_LANDED_TS


def test_grid_row_ps_async():
    """The ps_async scenario is wired through the whole bench surface:
    grid membership, samples/sec unit, f32 dtype default (the A/B measures
    host-side barrier vs async orchestration, not MXU width — dtype
    conversion noise would pollute it), and neither profile- nor
    sharding-capable (it runs its own ParallelWrapper/PS harnesses, not
    the multistep harness those frozensets describe)."""
    import bench

    assert bench._METRICS["ps_async"] == "ps_async_samples_per_sec"
    assert "ps_async" in bench._DEFAULTS and "ps_async" in bench._bench_fns()
    assert "ps_async" not in bench._UNITS  # samples/sec, the default unit
    assert bench._DTYPE_DEFAULT["ps_async"] == "f32"
    assert "ps_async" not in bench._PROFILE_CAPABLE
    assert "ps_async" not in bench._SHARDING_CAPABLE
    batch, iters, ksteps = bench._DEFAULTS["ps_async"]
    # enough minibatches that every worker pushes several windows per phase
    # and the loss-parity phase reaches the label-noise plateau
    assert iters * ksteps >= 32


def test_config_key_elastic_axes():
    """The elastic kill A/B's fleet shape is config-distinct: a no-kill or
    8-worker capture must never stand in for the standard 4-worker
    kill-at-50% recovery row (the dip and recovery being measured ARE
    functions of both), other models don't grow phantom elastic axes, and
    the ts-gate strips the axes on rows that predate the elastic trainer —
    same pattern as serve and ps_async."""
    import bench

    a = bench._config_key("--model elastic")
    b = bench._config_key("--model elastic --elastic-workers 8")
    c = bench._config_key("--model elastic --elastic-kill 0")
    assert a != b and a["elastic_workers"] == "4" \
        and b["elastic_workers"] == "8"
    assert a != c and c["elastic_kill"] == "0"
    assert a["elastic_kill"] == "0.5"  # the bench_elastic default, pinned
    # non-elastic models don't grow phantom axes
    r = bench._config_key("--model ps_async")
    assert r["elastic_workers"] is None and r["elastic_kill"] is None
    # rows logged before the elastic trainer landed cannot be elastic rows
    old = bench._config_key("--model elastic --elastic-workers 8",
                            ts="2026-08-06T01:59:59Z")
    new = bench._config_key("--model elastic --elastic-workers 8",
                            ts="2026-08-06T02:00:01Z")
    assert old["elastic_workers"] is None and new["elastic_workers"] == "8"
    ts = bench._ELASTIC_AXIS_LANDED_TS
    assert ts.endswith("Z") and ts > bench._SERVE_REPLICA_AXIS_LANDED_TS


def test_grid_row_elastic():
    """The elastic scenario is wired through the whole bench surface: grid
    membership, samples/sec unit, f32 dtype default (the kill A/B measures
    membership/handoff orchestration on subprocess CPU workers, not MXU
    width), and neither profile- nor sharding-capable (it runs its own
    coordinator + worker-process harness, not the multistep harness those
    frozensets describe)."""
    import bench

    assert bench._METRICS["elastic"] == "elastic_ps_samples_per_sec"
    assert "elastic" in bench._DEFAULTS and "elastic" in bench._bench_fns()
    assert "elastic" not in bench._UNITS  # samples/sec, the default unit
    assert bench._DTYPE_DEFAULT["elastic"] == "f32"
    assert "elastic" not in bench._PROFILE_CAPABLE
    assert "elastic" not in bench._SHARDING_CAPABLE
    batch, iters, ksteps = bench._DEFAULTS["elastic"]
    # enough minibatches that the fit comfortably outlives a worker
    # respawn (~3s): the recovery-to-90% number must be measurable before
    # the surviving shards drain
    assert iters * ksteps >= 128


def test_config_key_dataplane_axes():
    """The host-data-plane axes (ISSUE 14) are config-distinct: an shm
    capture must never stand in for the tcp baseline (the A/B the headline
    compares), an f32 ingest row must never stand in for the default u8
    one, other models don't grow phantom axes, and the ts-gate strips both
    on rows that predate the plane — same pattern as the elastic axes."""
    import bench

    a = bench._config_key("--model ps_async")
    b = bench._config_key("--model ps_async --ps-transport shm")
    assert a != b and a["ps_transport"] == "tcp" \
        and b["ps_transport"] == "shm"
    e = bench._config_key("--model elastic --ps-transport shm")
    assert e["ps_transport"] == "shm"
    i = bench._config_key("--model ingest")
    j = bench._config_key("--model ingest --ingest-codec f32")
    assert i != j and i["ingest_codec"] == "u8" \
        and j["ingest_codec"] == "f32"
    # non-dataplane models don't grow phantom axes (ingest likewise never
    # grows a transport axis: it exercises the decoder, not the PS)
    r = bench._config_key("--model serve")
    assert r["ps_transport"] is None and r["ingest_codec"] is None
    assert i["ps_transport"] is None
    # rows logged before the data plane landed cannot carry its axes
    old = bench._config_key("--model ps_async --ps-transport shm",
                            ts="2026-08-06T05:59:59Z")
    new = bench._config_key("--model ps_async --ps-transport shm",
                            ts="2026-08-06T06:00:01Z")
    assert old["ps_transport"] is None and new["ps_transport"] == "shm"
    ts = bench._DATAPLANE_AXIS_LANDED_TS
    assert ts.endswith("Z") and ts > bench._ELASTIC_AXIS_LANDED_TS


def test_grid_row_ingest():
    """The ingest decode A/B is wired through the whole bench surface:
    grid membership, MB/sec unit (it is a decoder-bandwidth row, not a
    training row), f32 dtype default (no matmuls at all), and neither
    profile- nor sharding-capable (it never enters the multistep
    harness)."""
    import bench

    assert bench._METRICS["ingest"] == "native_ingest_decode_mb_per_sec"
    assert "ingest" in bench._DEFAULTS and "ingest" in bench._bench_fns()
    assert bench._UNITS["ingest"] == "MB/sec"
    assert bench._DTYPE_DEFAULT["ingest"] == "f32"
    assert "ingest" not in bench._PROFILE_CAPABLE
    assert "ingest" not in bench._SHARDING_CAPABLE
    batch, iters, ksteps = bench._DEFAULTS["ingest"]
    # sample-sized records (the regime where the per-record GIL-bound
    # fallback's fixed cost shows) and best-of reps for a stable bandwidth
    assert batch <= 16 and iters >= 2


def test_config_key_compile_cache_axes():
    """The warm-start compile plane's axis (ISSUE 15) is config-distinct
    on BOTH models that report warm numbers: a cold-only --compile-cache
    off capture must never stand in for the warm-headline default serve or
    elastic row; other models don't grow the axis; and the ts-gate strips
    it on rows that predate the plane."""
    import bench

    a = bench._config_key("--model serve")
    b = bench._config_key("--model serve --compile-cache off")
    assert a != b and a["compile_cache"] == "on" \
        and b["compile_cache"] == "off"
    c = bench._config_key("--model elastic")
    d = bench._config_key("--model elastic --compile-cache off")
    assert c != d and c["compile_cache"] == "on" \
        and d["compile_cache"] == "off"
    # no phantom axis on models without a warm-start section
    assert bench._config_key("--model ps_async")["compile_cache"] is None
    assert bench._config_key("--model resnet50")["compile_cache"] is None
    # rows logged before the plane landed cannot carry the axis
    gate = bench._COMPILE_CACHE_AXIS_LANDED_TS
    old = bench._config_key("--model serve --compile-cache off",
                            ts="2026-08-06T09:59:59Z")
    new = bench._config_key("--model serve --compile-cache off",
                            ts="2026-08-06T10:00:01Z")
    assert old["compile_cache"] is None and new["compile_cache"] == "off"
    assert gate.endswith("Z") and gate > bench._DATAPLANE_AXIS_LANDED_TS


def test_config_key_decode_kv_axes():
    """The paged decode memory plane's axes (ISSUE 16) are config-distinct
    serve axes: a dense-KV, odd-page-size, or no-draft capture must never
    stand in for the paged + tiny-draft headline row (they measure
    different decode engines); other models don't grow the axes; and the
    ts-gate strips them on rows that predate the plane — those rows ran
    dense KV with no draft model in the repo at all."""
    import bench

    a = bench._config_key("--model serve")
    b = bench._config_key("--model serve --decode-kv dense")
    c = bench._config_key("--model serve --decode-page-size 32")
    d = bench._config_key("--model serve --decode-spec-draft none")
    assert a != b and a["decode_kv"] == "paged" \
        and b["decode_kv"] == "dense"
    assert a != c and a["decode_page_size"] == "16" \
        and c["decode_page_size"] == "32"
    assert a != d and a["decode_spec_draft"] == "tiny" \
        and d["decode_spec_draft"] == "none"
    # no phantom axes on models without a decode section
    for model in ("resnet50", "ps_async", "elastic"):
        r = bench._config_key(f"--model {model}")
        assert r["decode_kv"] is None and r["decode_page_size"] is None \
            and r["decode_spec_draft"] is None
    # rows logged before the plane landed cannot carry the axes
    gate = bench._PAGED_DECODE_AXIS_LANDED_TS
    old = bench._config_key("--model serve --decode-kv dense",
                            ts="2026-08-07T07:59:59Z")
    new = bench._config_key("--model serve --decode-kv dense",
                            ts="2026-08-07T08:00:01Z")
    assert old["decode_kv"] is None and old["decode_page_size"] is None \
        and old["decode_spec_draft"] is None
    assert new["decode_kv"] == "dense" and new["decode_page_size"] == "16"
    assert old != bench._config_key("--model serve --decode-kv dense")
    assert gate.endswith("Z") \
        and gate > bench._COMPILE_CACHE_AXIS_LANDED_TS


def test_config_key_serve_tracing_axis():
    """--serve-tracing (ISSUE 17) is a config-distinct serve axis: an
    untraced capture must never stand in for the tracing-on default row
    (whose headline carries trace_overhead_pct, the <=2% always-on
    tracing budget); other models don't grow the axis; and the ts-gate
    strips it from rows that predate the tracing plane — those requests
    ran with no tracing code in the repo at all."""
    import bench

    a = bench._config_key("--model serve")
    b = bench._config_key("--model serve --serve-tracing off")
    assert a != b and a["serve_tracing"] == "on" \
        and b["serve_tracing"] == "off"
    # no phantom axis on models without a serve section
    for model in ("resnet50", "ps_async", "char_rnn"):
        assert bench._config_key(f"--model {model}")["serve_tracing"] is None
    # rows logged before the plane landed cannot carry the axis
    gate = bench._SERVE_TRACING_AXIS_LANDED_TS
    old = bench._config_key("--model serve", ts="2026-08-07T11:59:59Z")
    new = bench._config_key("--model serve", ts="2026-08-07T12:00:01Z")
    assert old["serve_tracing"] is None and new["serve_tracing"] == "on"
    assert old != bench._config_key("--model serve")
    assert gate.endswith("Z") and gate > bench._PAGED_DECODE_AXIS_LANDED_TS

def test_config_key_serve_autoscale_axis():
    """--serve-autoscale (ISSUE 18) is a config-distinct serve axis: the
    static default row must never stand in for the open-loop ramp A/B
    capture (whose headline carries ramp_slo_violation_seconds_auto/
    static, the zero-loss count and the warm scale-out latency); other
    models don't grow the axis; and the ts-gate strips it from rows that
    predate the autoscaling fleet."""
    import bench

    a = bench._config_key("--model serve")
    b = bench._config_key("--model serve --serve-autoscale on")
    assert a != b and a["serve_autoscale"] == "off" \
        and b["serve_autoscale"] == "on"
    # no phantom axis on models without a serve section
    for model in ("resnet50", "ps_async", "char_rnn"):
        assert bench._config_key(
            f"--model {model}")["serve_autoscale"] is None
    # rows logged before the plane landed cannot carry the axis
    gate = bench._SERVE_AUTOSCALE_AXIS_LANDED_TS
    old = bench._config_key("--model serve", ts="2026-08-07T15:59:59Z")
    new = bench._config_key("--model serve", ts="2026-08-07T16:00:01Z")
    assert old["serve_autoscale"] is None and new["serve_autoscale"] == "off"
    assert old != bench._config_key("--model serve")
    assert gate.endswith("Z") and gate > bench._SERVE_TRACING_AXIS_LANDED_TS
