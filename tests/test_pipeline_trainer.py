"""Pipeline parallelism as a first-class training path.

Round-4 verdict items 3c/4: a transformer_lm config must train end-to-end
THROUGH parallel/pipeline.py, equivalently to single-device fit, and the
executor must not psum-replicate its output stack. Equivalence follows the
reference's gold-standard distributed-vs-single pattern (SURVEY.md §4).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.models import transformer_lm
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.mesh import build_mesh
from deeplearning4j_tpu.parallel.pipeline_trainer import (
    PipelineTrainer, find_block_run)

VOCAB, WIDTH, HEADS, T, B = 8, 32, 4, 16, 8


def _lm_batches(n=3, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        ids = rng.integers(0, VOCAB, size=(B, T + 1))
        x = np.eye(VOCAB, dtype=np.float32)[ids[:, :-1]]
        y = np.eye(VOCAB, dtype=np.float32)[ids[:, 1:]]
        out.append(DataSet(x, y))
    return out


def _conf(n_layers=4):
    return transformer_lm(VOCAB, width=WIDTH, n_layers=n_layers,
                          n_heads=HEADS, max_len=T, learning_rate=0.01)


def test_find_block_run():
    conf = _conf(4)
    assert find_block_run(conf.layers) == (1, 5)  # embed | 4 blocks | output


def test_pipeline_fit_equals_single_device():
    batches = _lm_batches()
    single = MultiLayerNetwork(_conf()).init()
    for ds in batches:
        single.fit(ds.features, ds.labels)

    pp_net = MultiLayerNetwork(_conf()).init()
    trainer = PipelineTrainer(pp_net, mesh=build_mesh({"stage": 4}),
                              n_microbatches=4)
    trainer.fit(ListDataSetIterator(batches))

    np.testing.assert_allclose(np.asarray(single.params()),
                               np.asarray(pp_net.params()),
                               atol=5e-5, rtol=1e-4)


def test_pipeline_training_reduces_loss():
    """Loss decreases training through the pipeline (round-4 verdict item 4's
    'loss-decreases test training through the pipeline')."""
    batches = _lm_batches(1)
    net = MultiLayerNetwork(_conf(2)).init()
    trainer = PipelineTrainer(net, mesh=build_mesh({"stage": 2}),
                              n_microbatches=4)
    trainer.fit(ListDataSetIterator(batches))
    first = float(net.score_value)
    trainer.fit(ListDataSetIterator(batches), epochs=15)
    assert float(net.score_value) < first


def test_pipeline_output_stays_staged():
    """The executor's output is sharded over the stage axis (no psum
    replication): per-device output bytes stay O(1/S) of the stack."""
    from deeplearning4j_tpu.nn.conf.layers import TransformerBlock
    from deeplearning4j_tpu.parallel.pipeline import (
        PipelineParallel, stack_block_params)
    from deeplearning4j_tpu.nn.conf.inputs import InputType

    mesh = build_mesh({"stage": 4})
    block = TransformerBlock(n_in=WIDTH, n_out=WIDTH, n_heads=HEADS,
                             causal=True, activation="identity")
    key = jax.random.PRNGKey(0)
    params = [block.init_params(k, InputType.recurrent(WIDTH, T))
              for k in jax.random.split(key, 4)]
    stacked = stack_block_params(params)
    pipe = PipelineParallel(
        mesh, lambda p, x: block.apply(p, {}, x, train=False, rng=None)[0],
        n_blocks=4, n_microbatches=4)
    x = jax.random.normal(jax.random.PRNGKey(1), (B, T, WIDTH), jnp.float32)
    out = pipe(stacked, x)
    ref = pipe.reference_forward(stacked, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=1e-4)


def test_pipeline_memory_is_per_stage():
    """Per-device memory contract (round-4 verdict item 4): each stage holds
    only its 1/S slice of the block parameters, and the executor's output
    stack is staged (sharded over 'stage'), not psum-replicated."""
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import TransformerBlock
    from deeplearning4j_tpu.parallel.pipeline import stack_block_params
    from jax.sharding import NamedSharding, PartitionSpec as P

    S = 8
    mesh = build_mesh({"stage": S})
    block = TransformerBlock(n_in=WIDTH, n_out=WIDTH, n_heads=HEADS,
                             causal=True, activation="identity")
    params = [block.init_params(k, InputType.recurrent(WIDTH, T))
              for k in jax.random.split(jax.random.PRNGKey(0), S)]
    stacked = {k: jax.device_put(v, NamedSharding(mesh, P("stage")))
               for k, v in stack_block_params(params).items()}
    for k, v in stacked.items():
        shard = v.addressable_shards[0].data
        assert shard.nbytes * S == v.nbytes, (k, shard.shape, v.shape)

    # executor output before the final slice is sharded over 'stage':
    # out[(S-1)*M:] pulls ONE stage's shard, so no device ever holds the
    # full S*M stack (the pre-fix psum replicated it everywhere)
    from deeplearning4j_tpu.parallel.pipeline import PipelineParallel
    pipe = PipelineParallel(
        mesh, lambda p, x: block.apply(p, {}, x, train=False, rng=None)[0],
        n_blocks=S, n_microbatches=4)
    x = jax.random.normal(jax.random.PRNGKey(1), (B, T, WIDTH), jnp.float32)
    out = pipe(stacked, x)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(pipe.reference_forward(stacked, x)),
                               atol=2e-5, rtol=1e-4)


def test_rejects_non_homogeneous():
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer

    conf = (NeuralNetConfiguration.builder().seed(1).learning_rate(0.1)
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(DenseLayer(n_in=8, n_out=3, activation="tanh"))
            .layer(OutputLayer(n_in=3, n_out=3, loss="mcxent",
                               activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()
    with pytest.raises(ValueError, match="homogeneous"):
        PipelineTrainer(net, mesh=build_mesh({"stage": 2}))


def test_pipeline_with_gradient_checkpointing():
    """PipelineTrainer honors the config's gradient_checkpointing flag
    (remat inside each stage block and for the non-pipelined layers) and
    still matches single-device training — remat changes memory, not math."""
    batches = _lm_batches(2)

    def conf():
        c = _conf(2)
        c.global_conf.gradient_checkpointing = True
        return c

    single = MultiLayerNetwork(conf()).init()
    for ds in batches:
        single.fit(ds.features, ds.labels)
    net = MultiLayerNetwork(conf()).init()
    PipelineTrainer(net, mesh=build_mesh({"stage": 2}), n_microbatches=2) \
        .fit(ListDataSetIterator(batches))
    np.testing.assert_allclose(np.asarray(single.params()),
                               np.asarray(net.params()),
                               atol=5e-5, rtol=1e-4)
