"""graftlint engine + rule-catalog tests.

Each rule gets a true-positive fixture, a negative fixture, and a
suppressed fixture; the suppression grammar itself (reason required,
unknown rule names rejected) and the CLI contract (JSON shape, exit
codes) are covered below. The final test runs the full registry over the
real package tree — the gate the repo ships under: zero unsuppressed
violations, every suppression carrying a reason.
"""
import json
import pathlib
import textwrap

import pytest

import deeplearning4j_tpu.lint as lint
from deeplearning4j_tpu.lint import BAD_SUPPRESSION, REGISTRY, rule_names
from deeplearning4j_tpu.lint.__main__ import main as lint_main

PKG = pathlib.Path(lint.__file__).resolve().parents[1]


def lint_src(tmp_path, source, name="fixture.py", rules=None):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    return lint.run_paths([f], rules)


def rules_of(result):
    return [v.rule for v in result.violations]


# ---------------------------------------------------------------- bare-print
def test_bare_print_positive(tmp_path):
    res = lint_src(tmp_path, """\
        def report(x):
            print("loss:", x)
        """, rules=["bare-print"])
    assert rules_of(res) == ["bare-print"]
    assert res.violations[0].line == 2


def test_bare_print_negative(tmp_path):
    res = lint_src(tmp_path, '''\
        import logging
        log = logging.getLogger(__name__)

        def report(x, sink):
            """print() in a docstring is not a call."""
            log.info("loss: %s", x)
            sink.print(x)        # attribute access
            return dict(print=x)  # keyword argument
        ''', rules=["bare-print"])
    assert res.violations == []


def test_bare_print_suppressed(tmp_path):
    res = lint_src(tmp_path, """\
        def banner():
            print("=" * 40)  # lint: bare-print-ok (interactive demo output)
        """, rules=["bare-print"])
    assert res.violations == []
    assert [v.rule for v in res.suppressed] == ["bare-print"]
    assert res.suppressed[0].reason == "interactive demo output"


# ------------------------------------------------------ host-sync-in-hot-loop
def test_host_sync_positive(tmp_path):
    res = lint_src(tmp_path, """\
        import numpy as np

        def train_step(model, batch):
            loss = model.loss(batch)
            host = np.asarray(loss)
            loss.block_until_ready()
            scalar = loss.item()
            return float(loss), scalar, host
        """, rules=["host-sync-in-hot-loop"])
    assert rules_of(res) == ["host-sync-in-hot-loop"] * 4


def test_host_sync_negative(tmp_path):
    res = lint_src(tmp_path, """\
        import numpy as np

        def summarize(model, batch):
            # not a hot-path name: syncs here are allowed
            return float(model.loss(batch))

        def train_step(model, batch):
            scale = float(0.5)  # literal float() is not a device sync
            return model.loss(batch) * scale
        """, rules=["host-sync-in-hot-loop"])
    assert res.violations == []


def test_host_sync_nested_def_inherits_hotness(tmp_path):
    res = lint_src(tmp_path, """\
        def fit(model, it):
            def stage(ds):
                return ds.features.item()
            for ds in it:
                model.step(stage(ds))
        """, rules=["host-sync-in-hot-loop"])
    assert rules_of(res) == ["host-sync-in-hot-loop"]


def test_host_sync_suppressed(tmp_path):
    res = lint_src(tmp_path, """\
        import numpy as np

        def fit(model, it):
            for ds in it:
                x = np.asarray(ds.features)  # lint: host-sync-in-hot-loop-ok (host staging of iterator output)
                model.step(x)
        """, rules=["host-sync-in-hot-loop"])
    assert res.violations == []
    assert [v.rule for v in res.suppressed] == ["host-sync-in-hot-loop"]


# ----------------------------------------------------------- recompile-hazard
def test_recompile_hazard_positive(tmp_path):
    res = lint_src(tmp_path, """\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x, opts={}):
            bias = jnp.array([1.0, 2.0])
            if x.shape[0] > 2:
                return x + bias
            return x
        """, rules=["recompile-hazard"])
    msgs = [v.message for v in res.violations]
    assert rules_of(res) == ["recompile-hazard"] * 3
    assert any("mutable default" in m for m in msgs)
    assert any("Python literal" in m for m in msgs)
    assert any("trace-time shape" in m for m in msgs)


def test_recompile_hazard_shape_taint_flows_through_locals(tmp_path):
    res = lint_src(tmp_path, """\
        import jax

        @jax.jit
        def f(x):
            n = x.shape[0]
            half = n // 2
            if half > 4:
                return x[:half]
            return x
        """, rules=["recompile-hazard"])
    assert rules_of(res) == ["recompile-hazard"]


def test_recompile_hazard_negative(tmp_path):
    res = lint_src(tmp_path, """\
        import jax
        import jax.numpy as jnp

        _BIAS = jnp.array([1.0, 2.0])  # module scope: traced once

        @jax.jit
        def f(x, y):
            return x + _BIAS + jnp.asarray(y)  # non-literal arg is fine

        def host_branching(x):
            # not traced: shape branching on the host is normal code
            if x.shape[0] > 2:
                return x[:2]
            return x
        """, rules=["recompile-hazard"])
    assert res.violations == []


def test_recompile_hazard_naming_convention_and_method_exemption(tmp_path):
    res = lint_src(tmp_path, """\
        import jax.numpy as jnp

        def make_fns():
            def local_step(x):  # factory-built trace body: eligible
                return x + jnp.array([1.0])
            return local_step

        class Net:
            def rnn_time_step(self, x):  # host API method: exempt
                return x + jnp.array([1.0])
        """, rules=["recompile-hazard"])
    assert rules_of(res) == ["recompile-hazard"]
    assert "local_step" in res.violations[0].message


def test_recompile_hazard_suppressed(tmp_path):
    res = lint_src(tmp_path, """\
        import jax

        @jax.jit
        def f(x):
            if x.shape[0] % 8 != 0:  # lint: recompile-hazard-ok (static pad guard; batch is fixed)
                raise ValueError("unpadded batch")
            return x
        """, rules=["recompile-hazard"])
    assert res.violations == []
    assert [v.rule for v in res.suppressed] == ["recompile-hazard"]


# ------------------------------------------------------------- donation-alias
def test_donation_alias_positive(tmp_path):
    res = lint_src(tmp_path, """\
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(params, x):
            return params + x

        def fit(params, xs):
            for x in xs:
                out = step(params, x)
            return params + out
        """, rules=["donation-alias"])
    assert rules_of(res) == ["donation-alias"]
    assert "'params'" in res.violations[0].message


def test_donation_alias_rebind_idiom_negative(tmp_path):
    res = lint_src(tmp_path, """\
        import jax

        def _step(params, x):
            return params + x

        step = jax.jit(_step, donate_argnums=(0,))

        def fit(params, xs):
            for x in xs:
                params = step(params, x)  # safe: rebound from the result
            return params
        """, rules=["donation-alias"])
    assert res.violations == []


def test_donation_alias_suppressed(tmp_path):
    res = lint_src(tmp_path, """\
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(params, x):
            return params + x

        def fit(params, xs):
            out = step(params, xs)
            return params  # lint: donation-alias-ok (CPU-only test helper; no donation on CPU)
        """, rules=["donation-alias"])
    assert res.violations == []
    assert [v.rule for v in res.suppressed] == ["donation-alias"]


# --------------------------------------------------------------- unseeded-rng
def test_unseeded_rng_positive(tmp_path):
    res = lint_src(tmp_path, """\
        import random
        import numpy as np
        from random import shuffle

        def init(n):
            w = np.random.rand(n)          # global numpy RNG
            g = np.random.default_rng()    # OS-entropy, unseeded
            random.random()                # stdlib global RNG
            shuffle(w)                     # from-import of stdlib random
            return w, g
        """, rules=["unseeded-rng"])
    assert rules_of(res) == ["unseeded-rng"] * 4


def test_unseeded_rng_negative(tmp_path):
    res = lint_src(tmp_path, """\
        import random
        import numpy as np
        import jax

        def init(n, seed):
            rng = np.random.default_rng(seed)
            local = random.Random(seed)
            key = jax.random.PRNGKey(seed)
            return rng.normal(size=n), local.random(), \\
                jax.random.normal(key, (n,))
        """, rules=["unseeded-rng"])
    assert res.violations == []


def test_unseeded_rng_suppressed(tmp_path):
    res = lint_src(tmp_path, """\
        import numpy as np

        def jitter():
            return np.random.rand()  # lint: unseeded-rng-ok (backoff jitter; determinism not wanted)
        """, rules=["unseeded-rng"])
    assert res.violations == []
    assert [v.rule for v in res.suppressed] == ["unseeded-rng"]


# ---------------------------------------------------------- metric-name-drift
def _metric_fixture(tmp_path, client_src):
    pkg = tmp_path / "pkg"
    (pkg / "observability").mkdir(parents=True)
    (pkg / "observability" / "names.py").write_text(
        'GOOD_TOTAL = "dl4j_good_total"\n')
    (pkg / "client.py").write_text(textwrap.dedent(client_src))
    return lint.run_paths([pkg], ["metric-name-drift"])


def test_metric_drift_hardcoded_literal_positive(tmp_path):
    res = _metric_fixture(tmp_path, """\
        def wire(reg):
            reg.counter("dl4j_adhoc_total").inc()
        """)
    assert rules_of(res) == ["metric-name-drift"]
    assert "hardcoded metric name" in res.violations[0].message


def test_metric_drift_stale_import_positive(tmp_path):
    res = _metric_fixture(tmp_path, """\
        from pkg.observability.names import MISSING_TOTAL

        def wire(reg):
            reg.gauge(MISSING_TOTAL).set(1)
        """)
    assert rules_of(res) == ["metric-name-drift"]
    assert "not defined there" in res.violations[0].message


def test_metric_drift_unprefixed_name_in_names_module(tmp_path):
    pkg = tmp_path / "pkg"
    (pkg / "observability").mkdir(parents=True)
    (pkg / "observability" / "names.py").write_text(
        'BAD = "plain_name_total"\n')
    res = lint.run_paths([pkg], ["metric-name-drift"])
    assert rules_of(res) == ["metric-name-drift"]
    assert "lacks the dl4j_ namespace prefix" in res.violations[0].message


def test_metric_drift_negative(tmp_path):
    res = _metric_fixture(tmp_path, """\
        import numpy as np
        from pkg.observability.names import GOOD_TOTAL

        def wire(reg, data):
            reg.counter(GOOD_TOTAL).inc()      # the central-constant idiom
            np.histogram(data, 10)             # not a metrics registry
        """)
    assert res.violations == []


def test_metric_drift_suppressed(tmp_path):
    res = _metric_fixture(tmp_path, """\
        def wire(reg):
            reg.counter("dl4j_scratch_total")  # lint: metric-name-drift-ok (throwaway bench-local series)
        """)
    assert res.violations == []
    assert [v.rule for v in res.suppressed] == ["metric-name-drift"]


# -------------------------------------------------------- swallowed-exception
def test_swallowed_exception_positive(tmp_path):
    res = lint_src(tmp_path, """\
        def load(path):
            try:
                return open(path).read()
            except:
                pass

        def probe(obj):
            try:
                obj.close()
            except ValueError:
                pass
        """, rules=["swallowed-exception"])
    assert rules_of(res) == ["swallowed-exception"] * 2
    assert "bare `except:`" in res.violations[0].message


def test_swallowed_exception_negative(tmp_path):
    res = lint_src(tmp_path, """\
        import logging
        log = logging.getLogger(__name__)

        def load(path):
            try:
                return open(path).read()
            except OSError:
                log.debug("unreadable %s", path, exc_info=True)
                return None

        def strict(obj):
            try:
                obj.close()
            except ValueError:
                raise
        """, rules=["swallowed-exception"])
    assert res.violations == []


def test_swallowed_exception_suppressed(tmp_path):
    res = lint_src(tmp_path, """\
        class H:
            def __del__(self):
                try:
                    self.close()
                # lint: swallowed-exception-ok (destructor must not raise)
                except Exception:
                    pass
        """, rules=["swallowed-exception"])
    assert res.violations == []
    assert [v.rule for v in res.suppressed] == ["swallowed-exception"]


# ------------------------------------------------------------ adhoc-sharding
def test_adhoc_sharding_positive(tmp_path):
    res = lint_src(tmp_path, """\
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        def place(mesh, x):
            s = NamedSharding(mesh, P("data"))
            t = jax.sharding.PartitionSpec(None, "model")
            return s, t
        """, rules=["adhoc-sharding"])
    assert rules_of(res) == ["adhoc-sharding", "adhoc-sharding"]


def test_adhoc_sharding_negative(tmp_path):
    # engine-sanctioned constructors and unrelated names of the same spelling
    res = lint_src(tmp_path, """\
        from deeplearning4j_tpu.parallel import partition

        def PartitionSpec(x):  # local helper, not jax.sharding's
            return x

        def place(mesh, tree):
            spec = partition.pspec("data")
            PartitionSpec(spec)
            return partition.named_sharding(mesh, spec)
        """, rules=["adhoc-sharding"])
    assert res.violations == []


def test_adhoc_sharding_suppressed(tmp_path):
    res = lint_src(tmp_path, """\
        from jax.sharding import NamedSharding

        def stage(mesh, spec, x):
            # lint: adhoc-sharding-ok (host staging buffer, not a layout decision)
            s = NamedSharding(mesh, spec)
            return s
        """, rules=["adhoc-sharding"])
    assert res.violations == []
    assert [v.rule for v in res.suppressed] == ["adhoc-sharding"]


def test_adhoc_sharding_excludes_engine_files():
    rule = next(r for r in lint.default_rules()
                if r.name == "adhoc-sharding")
    assert any("partition.py" in g for g in rule.exclude)
    assert any("compile_seam.py" in g for g in rule.exclude)


# ------------------------------------------------------- suppression grammar
def test_suppression_without_reason_rejected(tmp_path):
    res = lint_src(tmp_path, """\
        def report(x):
            print(x)  # lint: bare-print-ok
        """, rules=["bare-print"])
    found = sorted(rules_of(res))
    # the reasonless marker does NOT suppress, and is itself a violation
    assert found == [BAD_SUPPRESSION, "bare-print"]
    assert res.suppressed == []


def test_suppression_of_unknown_rule_rejected(tmp_path):
    res = lint_src(tmp_path, """\
        x = 1  # lint: no-such-rule-ok (typo fixture)
        """, rules=["bare-print"])
    assert rules_of(res) == [BAD_SUPPRESSION]
    assert "unknown rule" in res.violations[0].message


def test_suppressed_findings_stay_in_report_with_reason(tmp_path):
    res = lint_src(tmp_path, """\
        def report(x):
            print(x)  # lint: bare-print-ok (fixture)
        """, rules=["bare-print"])
    j = res.to_json()
    assert j["ok"] is True
    assert j["violations"] == []
    assert j["suppressed"][0]["rule"] == "bare-print"
    assert j["suppressed"][0]["reason"] == "fixture"


def test_standalone_marker_applies_to_next_code_line(tmp_path):
    res = lint_src(tmp_path, """\
        def report(x):
            # lint: bare-print-ok (covers the next line only)
            print(x)
            print(x)
        """, rules=["bare-print"])
    assert rules_of(res) == ["bare-print"]
    assert res.violations[0].line == 4
    assert [v.line for v in res.suppressed] == [3]


def test_unknown_rule_subset_raises():
    with pytest.raises(ValueError, match="unknown rule"):
        lint.run_paths([PKG], ["bare-print", "not-a-rule"])


def test_syntax_error_is_reported_not_crash(tmp_path):
    res = lint_src(tmp_path, "def broken(:\n    pass\n")
    assert not res.ok
    assert res.violations == []
    assert len(res.errors) == 1


# ------------------------------------------------------------ dense-kv-alloc
def _kv_fixture(tmp_path, source, name="decode_x.py"):
    d = tmp_path / "keras_server"
    d.mkdir(exist_ok=True)
    f = d / name
    f.write_text(textwrap.dedent(source))
    return lint.run_paths([f], ["dense-kv-alloc"])


def test_dense_kv_alloc_positive(tmp_path):
    res = _kv_fixture(tmp_path, """\
        import jax.numpy as jnp

        def make_blocks(cap, max_context, n_heads, head_dim):
            return jnp.zeros((cap, max_context, n_heads, head_dim),
                             jnp.float32)
        """)
    assert rules_of(res) == ["dense-kv-alloc"]
    assert res.violations[0].line == 4


def test_dense_kv_alloc_attribute_dim_positive(tmp_path):
    res = _kv_fixture(tmp_path, """\
        import jax.numpy as jnp

        class Engine:
            def _blocks(self, cap, h, d):
                return {"k": jnp.zeros((cap, self.max_context, h, d))}
        """)
    assert rules_of(res) == ["dense-kv-alloc"]


def test_dense_kv_alloc_negative(tmp_path):
    res = _kv_fixture(tmp_path, """\
        import jax.numpy as jnp
        import numpy as np

        def other(cap, h, max_context):
            hidden = jnp.zeros((cap, h))          # no context dimension
            pos = np.zeros((max_context,), np.int32)  # host array, not KV
            limit = max_context + 1               # bare use is fine
            return hidden, pos, limit
        """)
    assert res.violations == []


def test_dense_kv_alloc_paging_module_scoped_out(tmp_path):
    res = _kv_fixture(tmp_path, """\
        import jax.numpy as jnp

        def alloc_dense_kv(cap, max_context, n_heads, head_dim):
            return jnp.zeros((cap, max_context, n_heads, head_dim))
        """, name="paging.py")
    assert res.violations == []


def test_dense_kv_alloc_outside_keras_server_scoped_out(tmp_path):
    res = lint_src(tmp_path, """\
        import jax.numpy as jnp

        def scores(batch, max_context):
            return jnp.zeros((batch, max_context))
        """, rules=["dense-kv-alloc"])
    assert res.violations == []


def test_dense_kv_alloc_suppressed(tmp_path):
    res = _kv_fixture(tmp_path, """\
        import jax.numpy as jnp

        def oracle(cap, max_context, n_heads, head_dim):
            return jnp.zeros((cap, max_context, n_heads, head_dim))  # lint: dense-kv-alloc-ok (test-only dense oracle)
        """)
    assert res.violations == []
    assert [v.rule for v in res.suppressed] == ["dense-kv-alloc"]


# -------------------------------------------------------------- CLI contract
def test_cli_registry_lists_all_rules(capsys):
    assert set(rule_names()) == set(REGISTRY) and len(REGISTRY) >= 6
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for name in rule_names():
        assert name in out


def test_cli_json_and_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("print('x')\n")
    assert lint_main([str(bad), "--json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["ok"] is False
    assert payload["counts"] == {"bare-print": 1}
    assert payload["violations"][0]["path"] == "bad.py"

    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    assert lint_main([str(clean), "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["ok"] is True

    assert lint_main([str(clean), "--rules", "bogus"]) == 2


def test_cli_human_output_shape(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("print('x')\n")
    assert lint_main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "bad.py:1: [bare-print]" in out
    assert "1 violation(s)" in out


# ------------------------------------------------------- the real package
def test_package_is_lint_clean():
    """The gate the repo ships under: the full registry over the real tree
    finds zero unsuppressed violations, zero parse errors, and every
    suppression carries its reason."""
    res = lint.run_paths([PKG])
    assert res.errors == []
    assert res.violations == [], "\n".join(
        v.render() for v in res.violations)
    assert res.files_scanned > 100
    for v in res.suppressed:
        assert v.reason, f"reasonless suppression survived: {v.render()}"
