"""Clustering + t-SNE tests (reference deeplearning4j-core clustering tests +
TsneTest)."""
import numpy as np
import pytest

from deeplearning4j_tpu.clustering import KDTree, KMeansClustering, SPTree, VPTree
from deeplearning4j_tpu.plot import BarnesHutTsne, Tsne


def _blobs(n_per=50, seed=0):
    rng = np.random.default_rng(seed)
    centers = np.array([[0, 0, 0], [10, 10, 10], [-10, 10, -10]], np.float64)
    pts = np.concatenate([c + rng.normal(0, 1.0, (n_per, 3)) for c in centers])
    labels = np.repeat(np.arange(3), n_per)
    return pts, labels


def test_kmeans_recovers_blobs():
    pts, labels = _blobs()
    km = KMeansClustering.setup(3, max_iterations=50, seed=4)
    cs = km.apply_to(pts)
    a = np.asarray(cs.assignments)
    # each true cluster maps to exactly one predicted cluster
    for c in range(3):
        vals, counts = np.unique(a[labels == c], return_counts=True)
        assert counts.max() / counts.sum() > 0.98
    # predict on new points near a center lands in that center's cluster
    pred = km.predict(cs, pts[:5])
    assert len(set(pred.tolist())) == 1


def test_kmeans_distances():
    pts, _ = _blobs(20)
    for dist in ("euclidean", "manhattan", "cosine"):
        cs = KMeansClustering.setup(3, 30, distance=dist, seed=1).apply_to(pts)
        assert np.isfinite(float(cs.inertia))
    with pytest.raises(ValueError):
        KMeansClustering(3, distance="hamming")


def test_kdtree_matches_bruteforce():
    rng = np.random.default_rng(7)
    pts = rng.normal(size=(200, 5))
    tree = KDTree(pts)
    for _ in range(10):
        q = rng.normal(size=5)
        d = np.linalg.norm(pts - q, axis=1)
        expect = set(np.argsort(d)[:4].tolist())
        got = {i for i, _ in tree.knn(q, 4)}
        assert got == expect


def test_vptree_matches_bruteforce():
    rng = np.random.default_rng(8)
    pts = rng.normal(size=(150, 4))
    tree = VPTree(pts)
    for _ in range(10):
        q = rng.normal(size=4)
        d = np.linalg.norm(pts - q, axis=1)
        expect = set(np.argsort(d)[:5].tolist())
        got = {i for i, _ in tree.knn(q, 5)}
        assert got == expect


def test_sptree_forces_match_exact():
    """theta=0 Barnes-Hut forces == exact repulsive forces."""
    rng = np.random.default_rng(9)
    y = rng.normal(size=(40, 2))
    tree = SPTree(y)
    neg_f = np.zeros_like(y)
    z = 0.0
    for i in range(40):
        z += tree.compute_non_edge_forces(i, 0.0, neg_f[i])
    # exact computation
    d = y[:, None] - y[None]
    q = 1.0 / (1.0 + (d ** 2).sum(-1))
    np.fill_diagonal(q, 0.0)
    z_exact = q.sum()
    neg_exact = np.einsum("ij,ijc->ic", q * q, d)
    assert abs(z - z_exact) / z_exact < 1e-9
    np.testing.assert_allclose(neg_f, neg_exact, rtol=1e-9)


def test_tsne_separates_clusters():
    pts, labels = _blobs(30, seed=3)
    emb = Tsne(perplexity=10, max_iter=250, seed=5).fit_transform(pts)
    assert emb.shape == (90, 2)
    # mean within-cluster distance far below between-cluster distance
    cents = np.stack([emb[labels == c].mean(0) for c in range(3)])
    within = np.mean([np.linalg.norm(emb[labels == c] - cents[c], axis=1).mean()
                      for c in range(3)])
    between = np.mean([np.linalg.norm(cents[a] - cents[b])
                       for a in range(3) for b in range(a + 1, 3)])
    assert between > 3 * within, (within, between)


def test_barnes_hut_tsne_separates_clusters():
    pts, labels = _blobs(40, seed=6)
    bh = (BarnesHutTsne.builder().theta(0.5).perplexity(10)
          .set_max_iter(250).seed(2).build())
    emb = bh.fit(pts)
    assert emb.shape == (120, 2)
    cents = np.stack([emb[labels == c].mean(0) for c in range(3)])
    within = np.mean([np.linalg.norm(emb[labels == c] - cents[c], axis=1).mean()
                      for c in range(3)])
    between = np.mean([np.linalg.norm(cents[a] - cents[b])
                       for a in range(3) for b in range(a + 1, 3)])
    assert between > 2 * within, (within, between)
