"""Config DSL + JSON/YAML round-trip tests.

Modeled on the reference's config serde battery (deeplearning4j-core src/test
MultiLayerTest / serde tests): toJson->fromJson must reproduce the configuration.
"""
import dataclasses

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization, ConvolutionLayer, DenseLayer, GravesLSTM, OutputLayer,
    RnnOutputLayer, SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.multilayer import MultiLayerConfiguration


def lenet_conf():
    return (NeuralNetConfiguration.builder()
            .seed(12345)
            .learning_rate(0.01)
            .updater("nesterovs").momentum(0.9)
            .weight_init("xavier")
            .list()
            .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5), stride=(1, 1),
                                    activation="identity"))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5), activation="identity"))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=500, activation="relu"))
            .layer(OutputLayer(n_out=10, loss="mcxent", activation="softmax"))
            .set_input_type(InputType.convolutional_flat(28, 28, 1))
            .build())


def test_json_roundtrip_mlp():
    conf = (NeuralNetConfiguration.builder()
            .seed(42).learning_rate(0.1).updater("adam")
            .list()
            .layer(DenseLayer(n_in=4, n_out=10, activation="tanh"))
            .layer(OutputLayer(n_in=10, n_out=3, loss="mcxent", activation="softmax"))
            .build())
    s = conf.to_json()
    conf2 = MultiLayerConfiguration.from_json(s)
    assert conf2.to_json() == s
    assert len(conf2.layers) == 2
    assert conf2.layers[0].n_out == 10
    assert conf2.layers[1].loss == "mcxent"
    # baked global defaults survive round-trip
    assert conf2.layers[0].updater == "adam"


def test_yaml_roundtrip():
    conf = lenet_conf()
    conf2 = MultiLayerConfiguration.from_yaml(conf.to_yaml())
    assert conf2.to_json() == conf.to_json()


def test_input_type_inference_lenet():
    conf = lenet_conf()
    # conv layers get n_in from channel propagation
    assert conf.layers[0].n_in == 1
    assert conf.layers[2].n_in == 20
    # dense layer n_in = flattened conv output: 28->24->12->8->4; 4*4*50 = 800
    assert conf.layers[4].n_in == 800
    assert conf.layers[5].n_in == 500
    # preprocessors: flat->cnn at 0, cnn->ff at dense
    assert conf.preprocessor(0) is not None
    assert conf.preprocessor(4) is not None


def test_global_default_baking():
    conf = (NeuralNetConfiguration.builder()
            .learning_rate(0.05).activation("relu").weight_init("relu")
            .l2(1e-4).regularization(True)
            .list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(DenseLayer(n_out=8, activation="tanh"))  # per-layer override
            .layer(OutputLayer(n_out=3, loss="mse", activation="identity"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    assert conf.layers[0].activation == "relu"
    assert conf.layers[1].activation == "tanh"
    assert conf.layers[0].l2 == 1e-4
    assert conf.layers[1].n_in == 8
    assert conf.global_conf.use_regularization


def test_rnn_conf():
    conf = (NeuralNetConfiguration.builder()
            .list()
            .layer(GravesLSTM(n_in=10, n_out=20))
            .layer(RnnOutputLayer(n_in=20, n_out=5, loss="mcxent", activation="softmax"))
            .backprop_type("TruncatedBPTT")
            .t_bptt_forward_length(8)
            .build())
    conf2 = MultiLayerConfiguration.from_json(conf.to_json())
    assert conf2.backprop_type == "TruncatedBPTT"
    assert conf2.tbptt_fwd_length == 8
    assert conf2.layers[0].peephole


def test_custom_layer_registration():
    from deeplearning4j_tpu.nn.conf.layers.base import Layer
    from deeplearning4j_tpu.nn.conf.serde import register_config, from_json, to_json

    @register_config("MyCustomScale")
    @dataclasses.dataclass
    class MyCustomScale(Layer):
        factor: float = 2.0

        def apply(self, params, state, x, **kw):
            return x * self.factor, state

    layer = MyCustomScale(factor=3.5)
    restored = from_json(to_json(layer))
    assert isinstance(restored, MyCustomScale)
    assert restored.factor == 3.5


def test_vae_composite_distribution_roundtrip():
    """Reconstruction distributions serialize polymorphically (reference
    CompositeReconstructionDistribution Jackson serde)."""
    from deeplearning4j_tpu.nn.conf.layers import VariationalAutoencoder
    from deeplearning4j_tpu.nn.conf.layers.variational import (
        CompositeReconstructionDistribution,
        ExponentialReconstructionDistribution,
        GaussianReconstructionDistribution,
    )

    comp = (CompositeReconstructionDistribution()
            .add(3, GaussianReconstructionDistribution(activation="tanh"))
            .add(2, ExponentialReconstructionDistribution()))
    conf = (NeuralNetConfiguration.builder()
            .seed(1)
            .list()
            .layer(VariationalAutoencoder(n_in=5, n_out=2,
                                          reconstruction_distribution=comp))
            .layer(OutputLayer(n_in=2, n_out=2, loss="mse",
                               activation="identity"))
            .build())
    conf2 = type(conf).from_json(conf.to_json())
    rd = conf2.layers[0].reconstruction_distribution
    assert isinstance(rd, CompositeReconstructionDistribution)
    assert int(rd.components[0][0]) == 3
    assert isinstance(rd.components[0][1], GaussianReconstructionDistribution)
    assert rd.components[0][1].activation == "tanh"
    assert isinstance(rd.components[1][1],
                      ExponentialReconstructionDistribution)
    assert rd.input_size(5) == 3 * 2 + 2


def test_serde_fuzz_random_configs_roundtrip():
    """Property test: randomly assembled configurations round-trip through
    JSON with identical serialized form AND identical network outputs
    (config JSON is the checkpoint schema — it must be total over the layer
    space, not just the layouts other tests happen to use)."""
    import numpy as np

    from deeplearning4j_tpu.nn.conf.layers import (
        AutoEncoder, BatchNormalization, DenseLayer, DropoutLayer,
        GravesLSTM, OutputLayer, RnnOutputLayer,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    rng = np.random.default_rng(2026)
    updaters = ["sgd", "adam", "rmsprop", "nesterovs", "lamb"]
    acts = ["tanh", "relu", "sigmoid", "identity"]
    for trial in range(8):
        width_in = int(rng.integers(2, 6))
        recurrent = bool(rng.integers(0, 2))
        b = (NeuralNetConfiguration.builder()
             .seed(int(rng.integers(0, 10000)))
             .learning_rate(float(rng.uniform(0.001, 0.2)))
             .updater(str(rng.choice(updaters)))
             .list())
        cur = width_in
        for _ in range(int(rng.integers(1, 4))):
            kind = int(rng.integers(0, 4)) if not recurrent else 4
            n_out = int(rng.integers(3, 9))
            if kind == 0:
                b.layer(DenseLayer(n_in=cur, n_out=n_out,
                                   activation=str(rng.choice(acts)),
                                   l1=float(rng.choice([0.0, 0.01])),
                                   l2=float(rng.choice([0.0, 0.02]))))
            elif kind == 1:
                b.layer(BatchNormalization(n_in=cur))
                n_out = cur
            elif kind == 2:
                b.layer(DropoutLayer(dropout=0.8))
                n_out = cur
            elif kind == 3:
                b.layer(AutoEncoder(n_in=cur, n_out=n_out,
                                    activation="sigmoid"))
            else:
                b.layer(GravesLSTM(n_in=cur, n_out=n_out, activation="tanh"))
            cur = n_out
        if recurrent:
            b.layer(RnnOutputLayer(n_in=cur, n_out=3, loss="mcxent",
                                   activation="softmax"))
        else:
            b.layer(OutputLayer(n_in=cur, n_out=3, loss="mcxent",
                                activation="softmax"))
        conf = b.build()
        js = conf.to_json()
        conf2 = type(conf).from_json(js)
        assert conf2.to_json() == js, f"trial {trial}: serialized form drifted"

        net1 = MultiLayerNetwork(conf).init()
        net2 = MultiLayerNetwork(conf2).init()
        shape = (4, 5, width_in) if recurrent else (4, width_in)
        x = rng.normal(size=shape).astype(np.float32)
        np.testing.assert_allclose(np.asarray(net1.output(x)),
                                   np.asarray(net2.output(x)),
                                   rtol=1e-6, atol=1e-7,
                                   err_msg=f"trial {trial}")
