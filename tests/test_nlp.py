"""NLP tests (reference deeplearning4j-nlp Word2VecTests, ParagraphVectorsTest,
GloveTest, TsneTest corpora — small synthetic corpus here)."""
import numpy as np
import pytest

from deeplearning4j_tpu.nlp import Glove, ParagraphVectors, SequenceVectors, Word2Vec
from deeplearning4j_tpu.nlp.bagofwords import BagOfWordsVectorizer, TfidfVectorizer
from deeplearning4j_tpu.nlp.iterators import (
    CollectionSentenceIterator, LabelAwareListSentenceIterator, LabelledDocument,
    SimpleLabelAwareIterator,
)
from deeplearning4j_tpu.nlp.serializer import read_word_vectors, write_word_vectors
from deeplearning4j_tpu.nlp.tokenization import (
    CommonPreprocessor, DefaultTokenizerFactory, NGramTokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import VocabConstructor, build_huffman


def _corpus(n_reps=40):
    """Two topic clusters: animals and numbers; co-occurring words should embed
    closer than cross-topic words."""
    base = [
        "the cat sat on the mat with the dog",
        "a dog chased the cat around the house",
        "cat and dog are friendly animals in the house",
        "one two three four five six seven",
        "two plus three equals five numbers",
        "seven six five four three two one numbers count",
    ]
    return base * n_reps


def test_tokenizer_and_preprocess():
    tf = DefaultTokenizerFactory()
    tf.set_token_pre_processor(CommonPreprocessor())
    toks = tf.create("The CAT, sat. (on) a MAT!?").get_tokens()
    assert toks == ["the", "cat", "sat", "on", "a", "mat"]
    ng = NGramTokenizerFactory(DefaultTokenizerFactory(), 1, 2)
    toks = ng.create("a b c").get_tokens()
    assert "a b" in toks and "b c" in toks and "a" in toks


def test_vocab_and_huffman():
    seqs = [s.split() for s in _corpus(2)]
    cache = VocabConstructor(min_word_frequency=2).build_joint_vocabulary(seqs)
    assert cache.num_words() > 5
    assert cache.index_of("the") == 0  # most frequent word gets index 0
    # Huffman: every word has a code; code lengths satisfy Kraft equality
    kraft = sum(2.0 ** -len(vw.code) for vw in cache.vocab_words())
    assert abs(kraft - 1.0) < 1e-9
    # frequent words get shorter codes
    the = cache.word_for("the")
    rare = cache.vocab_words()[-1]
    assert len(the.code) <= len(rare.code)


@pytest.mark.parametrize("mode", ["hs", "neg"])
def test_word2vec_topic_similarity(mode):
    w2v = (Word2Vec.builder()
           .layer_size(32).window_size(4).min_word_frequency(2)
           .learning_rate(0.05).epochs(3).seed(7)
           .use_hierarchic_softmax(mode == "hs")
           .negative_sample(5 if mode == "neg" else 0)
           .iterate(CollectionSentenceIterator(_corpus()))
           .build())
    w2v.fit()
    assert w2v.get_word_vector("cat") is not None
    sim_in = w2v.similarity("cat", "dog")
    sim_cross = w2v.similarity("cat", "five")
    assert sim_in > sim_cross, (sim_in, sim_cross)
    nearest = w2v.words_nearest("two", top_n=5)
    number_words = {"one", "three", "four", "five", "six", "seven", "numbers"}
    assert len(number_words.intersection(nearest)) >= 2, nearest


def test_word2vec_cbow_trains():
    w2v = (Word2Vec.builder()
           .layer_size(24).window_size(4).min_word_frequency(2)
           .elements_learning_algorithm("CBOW").epochs(3).seed(3)
           .iterate(CollectionSentenceIterator(_corpus()))
           .build())
    w2v.fit()
    assert w2v.similarity("cat", "dog") > w2v.similarity("cat", "five")


def test_word_vector_serialization_roundtrip(tmp_path):
    w2v = (Word2Vec.builder()
           .layer_size(16).min_word_frequency(2).epochs(1).seed(1)
           .iterate(CollectionSentenceIterator(_corpus(5)))
           .build())
    w2v.fit()
    for binary in (False, True):
        p = str(tmp_path / f"vecs.{'bin' if binary else 'txt'}")
        write_word_vectors(w2v, p, binary=binary)
        loaded = read_word_vectors(p, binary=binary)
        v0 = w2v.get_word_vector("cat")
        v1 = loaded.get_word_vector("cat")
        np.testing.assert_allclose(v0, v1, atol=1e-5)
        assert set(loaded.vocab.words()) == set(w2v.vocab.words())


def test_paragraph_vectors_dbow_and_infer():
    docs = ([LabelledDocument(s, [f"ANIMAL_{i}"]) for i, s in
             enumerate(_corpus(10)[:3] * 10)]
            + [LabelledDocument(s, [f"NUM_{i}"]) for i, s in
               enumerate(_corpus(10)[3:6] * 10)])
    pv = (ParagraphVectors.builder()
          .layer_size(24).window_size(4).min_word_frequency(2)
          .learning_rate(0.05).epochs(2).seed(11)
          .iterate(SimpleLabelAwareIterator(docs))
          .build())
    pv.fit()
    # label vectors exist
    assert pv.get_word_vector("ANIMAL_0") is not None
    # inference produces a finite vector of the right size
    vec = pv.infer_vector("the cat sat with the dog")
    assert vec.shape == (24,) and np.all(np.isfinite(vec))


def test_glove_trains_and_embeds():
    glove = (Glove.builder()
             .layer_size(24).window_size(4).min_word_frequency(2)
             .learning_rate(0.1).epochs(8).seed(5)
             .build())
    glove.fit([s.split() for s in _corpus()])
    sim_in = glove.similarity("cat", "dog")
    sim_cross = glove.similarity("cat", "five")
    assert sim_in > sim_cross, (sim_in, sim_cross)


def test_bow_and_tfidf():
    docs = ["the cat sat", "the dog sat", "numbers one two three"]
    bow = BagOfWordsVectorizer().fit(docs)
    row = bow.transform("the cat and the dog")
    assert row[bow.vocab.index_of("the")] == 2.0
    assert row[bow.vocab.index_of("cat")] == 1.0
    tfidf = TfidfVectorizer().fit(docs)
    r = tfidf.transform("the cat sat")
    # 'the' appears in 2/3 docs -> lower idf than 'cat' (1/3 docs)
    assert r[tfidf.vocab.index_of("cat")] > r[tfidf.vocab.index_of("the")]


def test_label_aware_iterator_labels():
    it = LabelAwareListSentenceIterator(["a b", "c d"])
    docs = list(it)
    assert docs[0].labels == ["DOC_0"] and docs[1].labels == ["DOC_1"]


def test_word2vec_vocab_from_file_trains(tmp_path):
    """build_vocab_from_file (native count phase) then fit: embeddings train
    and similar words exist in the vocab."""
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    corpus = tmp_path / "corpus.txt"
    corpus.write_text("the cat sat on the mat\nthe dog sat on the rug\n" * 30)
    w2v = Word2Vec(vector_length=16, min_word_frequency=1, seed=7)
    w2v.build_vocab_from_file(str(corpus))
    assert "cat" in w2v.vocab and "rug" in w2v.vocab
    sents = [l.split() for l in corpus.read_text().splitlines() if l]
    w2v.fit(sents)
    assert w2v.get_word_vector("cat").shape == (16,)


def test_dense_update_path_matches_scatter():
    """The one-hot-matmul (MXU) embedding update must be bit-compatible with
    the XLA scatter path: duplicates accumulate, OOB padding rows drop
    (the TPU throughput optimization for the word2vec kernels — reference
    SkipGram.java:168-178 batched native exec, in TPU form)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nlp.learning import (
        BatchAccumulator, make_train_step)

    V, D = 50, 16
    rng = np.random.default_rng(0)
    acc = BatchAccumulator(batch_size=8, window_width=3, code_length=4,
                           n_words=V)
    batch = None
    for i in range(8):
        batch = acc.add([int(rng.integers(0, V)) for _ in range(3)],
                        int(rng.integers(0, V)),
                        [int(rng.integers(0, V)) for _ in range(3)],
                        [float(rng.integers(0, 2)) for _ in range(3)]) or batch
    syn0 = jnp.asarray(np.random.default_rng(1).normal(size=(V, D)),
                       jnp.float32)
    syn1 = jnp.asarray(np.random.default_rng(2).normal(size=(V, D)),
                       jnp.float32)
    syn1neg = jnp.asarray(np.random.default_rng(3).normal(size=(V, D)),
                          jnp.float32)
    cum = jnp.cumsum(jnp.ones((V,)) / V)
    key = jax.random.PRNGKey(7)

    outs = {}
    for dense in (False, True):
        step = make_train_step(use_hs=True, negative=3, chunk=4,
                               dense_update=dense)
        outs[dense] = step(syn0.copy(), syn1.copy(), syn1neg.copy(), cum,
                           batch, 0.025, key)
    for a, b in zip(outs[False], outs[True]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
