"""Worker process for the 2-process jax.distributed smoke test.

Run as: python tests/_dist_worker.py <process_id> <coordinator_port>

Initializes the cluster through the framework's own entry point
(parallel/mesh.py init_distributed — the replacement for the reference's
Spark driver/executor bring-up), runs ONE synchronous-DP train step with the
global batch sharded across the two processes' CPU devices, and prints a JSON
record of the resulting (replicated) parameters for the parent to compare
against a single-process step.
"""
import json
import sys


def main() -> None:
    pid = int(sys.argv[1])
    port = sys.argv[2]

    from deeplearning4j_tpu.parallel.mesh import (
        data_parallel_mesh, init_distributed)

    init_distributed(coordinator_address=f"127.0.0.1:{port}",
                     num_processes=2, process_id=pid)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import (
        MultiLayerNetwork, make_train_step)

    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 2, jax.devices()

    conf = (NeuralNetConfiguration.builder()
            .seed(9).learning_rate(0.1).updater("sgd")
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                               activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)
    B = 8
    x = rng.normal(size=(B, 4)).astype(np.float32)
    y = np.zeros((B, 3), np.float32)
    y[np.arange(B), rng.integers(0, 3, B)] = 1

    mesh = data_parallel_mesh()
    repl = NamedSharding(mesh, P())
    bsh = NamedSharding(mesh, P("data"))
    half = B // 2
    gx = jax.make_array_from_process_local_data(
        bsh, x[pid * half:(pid + 1) * half])
    gy = jax.make_array_from_process_local_data(
        bsh, y[pid * half:(pid + 1) * half])

    mode = sys.argv[3] if len(sys.argv) > 3 else "step"
    if mode == "step":
        step = jax.jit(make_train_step(conf),
                       in_shardings=(repl, repl, repl, bsh, bsh, repl, repl),
                       out_shardings=(repl, repl, repl, repl))
        params, _, _, loss = step(net.params_list, net.state_list,
                                  net.updater_state, gx, gy,
                                  jax.random.PRNGKey(0), jnp.int32(0))
        loss_val = float(loss)
    else:  # "wrapper": the production ParallelWrapper sync-DP fit over the
        #            2-process mesh (multi-host batch staging via
        #            make_array_from_callback inside _stage)
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

        batches = [DataSet(x.copy(), y.copy()) for _ in range(4)]
        pw = ParallelWrapper(net, prefetch=0, mesh=mesh)
        pw.fit(ListDataSetIterator(batches))
        params = net.params_list
        loss_val = net.score_value

    flat = np.concatenate([np.ravel(np.asarray(leaf)) for leaf in
                           jax.tree_util.tree_leaves(params)])
    print(json.dumps({"pid": pid, "loss": loss_val,
                      "psum": float(flat.sum()),
                      "head": [float(v) for v in flat[:5]]}), flush=True)
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
