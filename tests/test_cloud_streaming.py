"""Cloud storage SPI + streaming training/serving routes."""
import numpy as np
import pytest

from deeplearning4j_tpu.cloud import (
    LocalFileSystemProvider, S3Provider, TpuProvisioner,
)
from deeplearning4j_tpu.streaming import ServingRoute, TrainingRoute


def _net():
    from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    conf = (NeuralNetConfiguration.builder().seed(0).learning_rate(0.1)
            .list().layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=2, loss="mcxent",
                               activation="softmax")).build())
    return MultiLayerNetwork(conf).init()


def test_local_storage_roundtrip(tmp_path):
    store = LocalFileSystemProvider(str(tmp_path / "store"))
    src = tmp_path / "artifact.bin"
    src.write_bytes(b"\x01\x02\x03")
    store.upload(str(src), "models/run1/artifact.bin")
    assert store.list("models") == ["models/run1/artifact.bin"]
    dst = tmp_path / "restored.bin"
    store.download("models/run1/artifact.bin", str(dst))
    assert dst.read_bytes() == b"\x01\x02\x03"
    with pytest.raises(ValueError):
        store.upload(str(src), "../escape.bin")


def test_http_storage_roundtrip_over_socket(tmp_path):
    """The object-store contract exercised through a real socket (the role
    reference S3Uploader.java fills): PUT/GET/list against a loopback
    server, with bearer auth and the path-escape guard enforced remotely."""
    import threading
    import urllib.error

    from deeplearning4j_tpu.cloud import HttpStorageProvider, serve_storage

    server, base_url = serve_storage(str(tmp_path / "remote"), token="tok")
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        store = HttpStorageProvider(base_url, token="tok")
        src = tmp_path / "model.zip"
        src.write_bytes(b"weights" * 100)
        url = store.upload(str(src), "runs/exp1/model.zip")
        assert url.endswith("runs/exp1/model.zip")
        store.upload(str(src), "runs/exp2/model.zip")
        assert store.list("runs") == ["runs/exp1/model.zip",
                                      "runs/exp2/model.zip"]
        dst = tmp_path / "back.zip"
        store.download("runs/exp1/model.zip", str(dst))
        assert dst.read_bytes() == src.read_bytes()
        # wrong token -> 401; escape -> 400; missing -> 404
        bad = HttpStorageProvider(base_url, token="wrong")
        with pytest.raises(urllib.error.HTTPError):
            bad.list("")
        with pytest.raises(urllib.error.HTTPError):
            store.download("../../etc/passwd", str(tmp_path / "x"))
        with pytest.raises(urllib.error.HTTPError):
            store.download("runs/nope.zip", str(tmp_path / "x"))
    finally:
        server.shutdown()


def test_s3_provider_gated():
    with pytest.raises(RuntimeError):
        S3Provider("bucket")


def test_provisioner_render():
    req = TpuProvisioner(accelerator_type="v5litepod-16",
                         num_slices=2).render("trainer")
    assert req["accelerator_type"] == "v5litepod-16"
    assert req["num_slices"] == 2 and req["name"] == "trainer"


def test_training_route_fits_online():
    net = _net()
    route = TrainingRoute(net).start()
    rng = np.random.default_rng(0)
    try:
        for _ in range(5):
            labels = rng.integers(0, 2, 16)
            x = rng.normal(0, 0.3, (16, 4)).astype(np.float32)
            x[np.arange(16), labels] += 2.0
            y = np.eye(2, dtype=np.float32)[labels]
            route.send(x, y)
        route.drain()
    finally:
        route.stop()
    assert route.processed == 5 and not route.errors


def test_serving_route_predicts():
    net = _net()
    route = ServingRoute(net).start()
    try:
        route.send("req-1", np.ones((3, 4), np.float32))
        rid, out = route.receive()
    finally:
        route.stop()
    assert rid == "req-1" and out.shape == (3, 2)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-5)


def test_http_storage_server_rejects_bad_uploads(tmp_path):
    """Truncated or length-less PUTs must not be acknowledged (a corrupt
    checkpoint stored as success is worse than a failed upload)."""
    import http.client
    import threading

    from deeplearning4j_tpu.cloud import serve_storage

    server, base_url = serve_storage(str(tmp_path / "remote"))
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        host = base_url.split("//")[1]
        # no Content-Length -> 411, nothing stored
        c = http.client.HTTPConnection(host, timeout=10)
        c.putrequest("PUT", "/a.bin", skip_accept_encoding=True)
        c.endheaders()
        assert c.getresponse().status == 411
        assert not (tmp_path / "remote" / "a.bin").exists()
        # truncated body -> 400, partial file removed
        c2 = http.client.HTTPConnection(host, timeout=10)
        c2.putrequest("PUT", "/b.bin")
        c2.putheader("Content-Length", "1000000")
        c2.endheaders()
        c2.send(b"short")
        c2.close()  # disconnect mid-body
        import time
        time.sleep(0.3)
        assert not (tmp_path / "remote" / "b.bin").exists()
    finally:
        server.shutdown()
