"""2-process jax.distributed execution proof (VERDICT round-2 item 4).

The reference proves its cluster semantics by running distributed logic in a
local[N] Spark context (reference BaseSparkTest.java:90); the TPU-native
equivalent is two OS processes, each owning one CPU device, joined into one
JAX cluster by `init_distributed` (parallel/mesh.py:26) — the same code path
a real multi-host TPU pod uses, with DCN collectives replaced by local
transport. One synchronous-DP step over the 2-process mesh must produce the
same parameters as a single-process step on the full batch.
"""
import json
import os
import socket
import subprocess
import sys

import numpy as np

_WORKER = os.path.join(os.path.dirname(__file__), "_dist_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(mode: str):
    port = _free_port()
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""  # keep the TPU relay out of workers
    env.pop("XLA_FLAGS", None)  # one CPU device per process
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    return [subprocess.Popen(
        [sys.executable, _WORKER, str(i), str(port), mode], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for i in (0, 1)]


def test_two_process_sync_dp_matches_single_process():
    procs = _run_workers("step")
    outs = []
    for p in procs:
        stdout, stderr = p.communicate(timeout=180)
        assert p.returncode == 0, f"worker failed:\n{stderr[-2000:]}"
        rec = json.loads(stdout.strip().splitlines()[-1])
        outs.append(rec)

    # result is replicated: both processes must report identical params
    assert outs[0]["psum"] == outs[1]["psum"]
    assert outs[0]["head"] == outs[1]["head"]
    assert abs(outs[0]["loss"] - outs[1]["loss"]) < 1e-7

    # single-process reference on the full batch
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import (
        MultiLayerNetwork, make_train_step)

    conf = (NeuralNetConfiguration.builder()
            .seed(9).learning_rate(0.1).updater("sgd")
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                               activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    B = 8
    x = rng.normal(size=(B, 4)).astype(np.float32)
    y = np.zeros((B, 3), np.float32)
    y[np.arange(B), rng.integers(0, 3, B)] = 1
    step = jax.jit(make_train_step(conf))
    params, _, _, loss = step(net.params_list, net.state_list,
                              net.updater_state, jnp.asarray(x),
                              jnp.asarray(y), jax.random.PRNGKey(0),
                              jnp.int32(0))
    flat = np.concatenate([np.ravel(np.asarray(leaf)) for leaf in
                           jax.tree_util.tree_leaves(params)])
    np.testing.assert_allclose(outs[0]["psum"], float(flat.sum()),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(outs[0]["head"], flat[:5],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(outs[0]["loss"], float(loss),
                               rtol=1e-5, atol=1e-6)


def test_two_process_parallel_wrapper_fit_matches_single_process():
    """The PRODUCTION ParallelWrapper.fit over a 2-process jax.distributed
    mesh == single-process fit on the same batches (multi-host batch staging
    via make_array_from_callback; reference analog: the same Spark job giving
    the same model regardless of executor count)."""
    procs = _run_workers("wrapper")
    outs = []
    for p in procs:
        stdout, stderr = p.communicate(timeout=240)
        assert p.returncode == 0, f"worker failed:\n{stderr[-2000:]}"
        outs.append(json.loads(stdout.strip().splitlines()[-1]))

    assert outs[0]["psum"] == outs[1]["psum"]
    assert outs[0]["head"] == outs[1]["head"]

    # single-process oracle: same net, same 4 batches, plain fit_iterator
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder()
            .seed(9).learning_rate(0.1).updater("sgd")
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                               activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    B = 8
    x = rng.normal(size=(B, 4)).astype(np.float32)
    y = np.zeros((B, 3), np.float32)
    y[np.arange(B), rng.integers(0, 3, B)] = 1
    net.fit_iterator(ListDataSetIterator(
        [DataSet(x.copy(), y.copy()) for _ in range(4)]))

    import jax
    flat = np.concatenate([np.ravel(np.asarray(leaf)) for leaf in
                           jax.tree_util.tree_leaves(net.params_list)])
    np.testing.assert_allclose(outs[0]["psum"], float(flat.sum()),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(outs[0]["head"], flat[:5],
                               rtol=1e-5, atol=1e-6)
