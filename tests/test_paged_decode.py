"""Paged KV-cache memory plane + speculative decoding: ISSUE-16 acceptance.

Contracts pinned here:
- page-table attention is BITWISE identical to the dense masked oracle
  (tokens AND probability rows) at every capacity bucket and for session
  lengths that end mid-page — the gather indirection is pure layout;
- copy-on-write prefix sharing engages (shared tokens > 0) without
  touching the math: a fork mid-page diverges correctly and never
  corrupts the donor session's stream;
- page refcounts never leak: 1k churned sessions leave pool bytes flat
  (``jax.live_arrays`` idiom), every page back on the free list and the
  prefix registry empty;
- speculative decode emits the EXACT greedy stream at every acceptance
  rate — identical draft (acceptance == 1.0 by construction), a real
  partial-acceptance draft, and a sign-flipped near-zero draft;
- a session that can never fit the pool is refused at submit with the
  RejectedError the HTTP layer maps to 429 — pool pressure degrades to
  preemption/parking, never to OOM;
- the paged engine admits >= 2x the dense session count at EQUAL state
  bytes (the ISSUE-16 headline ratio);
- capacity growth no longer round-trips KV blocks through the host: the
  bytes billed to dl4j_decode_state_copy_bytes_total are the small host
  scheduling arrays, orders of magnitude under the device blocks.
"""
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.keras_server import RejectedError
from deeplearning4j_tpu.keras_server.decode import DecodeEngine
from deeplearning4j_tpu.keras_server.paging import TRASH_PAGE, PagePool
from deeplearning4j_tpu.models.transformer import transformer_lm
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.observability import names
from deeplearning4j_tpu.ops.paged_attention import paged_gather

V = 24


def _tf_net(seed=5, width=32):
    return MultiLayerNetwork(
        transformer_lm(vocab_size=V, width=width, n_layers=2, n_heads=2,
                       max_len=64, seed=seed)).init()


def _workload(n, rng=None, lo=2, hi=9):
    rng = rng or np.random.default_rng(0)
    prompts = [list(map(int, rng.integers(0, V,
                                          size=int(rng.integers(1, 5)))))
               for _ in range(n)]
    budgets = [int(rng.integers(lo, hi)) for _ in range(n)]
    return prompts, budgets


def _run(eng, prompts, budgets):
    sessions = [eng.submit(p, b) for p, b in zip(prompts, budgets)]
    for s in sessions:
        s.result(timeout=300)
    return sessions


def _live_device_bytes() -> int:
    return sum(a.nbytes for a in jax.live_arrays() if not a.is_deleted())


# ----------------------------------------------- paged == dense, bitwise
@pytest.mark.parametrize("cap", [2, 4, 8])
def test_paged_vs_dense_bitwise_per_capacity(cap):
    """Same sessions, same tokens AND probability rows bit-for-bit whether
    KV lives in dense per-slot blocks or gathered pages, at every capacity
    bucket. The workload's prompt+budget spans deliberately straddle page
    boundaries (page_size=8, sessions end mid-page)."""
    net = _tf_net()
    prompts, budgets = _workload(12, np.random.default_rng(cap), lo=3,
                                 hi=14)
    dense = DecodeEngine(net, max_context=64, min_slots=cap, max_slots=cap,
                         capture_probs=True)
    paged = DecodeEngine(net, max_context=64, min_slots=cap, max_slots=cap,
                         capture_probs=True, kv="paged", page_size=8)
    try:
        ds = _run(dense, prompts, budgets)
        ps = _run(paged, prompts, budgets)
    finally:
        dense.close()
        paged.close()
    for d, p in zip(ds, ps):
        assert d.tokens == p.tokens
        for dp, pp in zip(d.probs, p.probs):
            assert np.array_equal(dp, pp)
    st = paged.stats()
    assert st["kv"] == "paged" and st["pages_in_use"] == 0


def test_odd_session_tails_park_on_trash_page():
    """Sessions whose final position lands mid-page read only written
    offsets: the j <= position mask never selects a row past the write
    head, so the page's uninitialised tail is unobservable (bitwise check
    against dense is the proof; the trash page absorbs suppressed
    writes)."""
    net = _tf_net(seed=3)
    # one-token prompts + budgets chosen so totals hit every residue
    # class mod page_size=4
    prompts = [[t % V] for t in range(8)]
    budgets = [2 + (t % 4) for t in range(8)]
    dense = DecodeEngine(net, max_context=64, min_slots=4, max_slots=4)
    paged = DecodeEngine(net, max_context=64, min_slots=4, max_slots=4,
                         kv="paged", page_size=4)
    try:
        ds = _run(dense, prompts, budgets)
        ps = _run(paged, prompts, budgets)
    finally:
        dense.close()
        paged.close()
    assert [d.tokens for d in ds] == [p.tokens for p in ps]


# --------------------------------------------------- copy-on-write forks
def test_cow_fork_mid_page_diverges_without_corrupting_donor():
    """B maps A's registered prompt pages copy-on-write, then forks
    mid-page where its prompt diverges. Both streams must equal the
    dense oracle — the fork copies A's earlier offsets device-side, and
    A's own pages are untouched by B's writes."""
    net = _tf_net(seed=7)
    pa = [1, 2, 3, 4, 5, 6, 7, 8, 2, 3, 9]          # 11 tokens, ps=8
    pb = pa[:6] + [11, 12]                          # diverges mid-page
    dense = DecodeEngine(net, max_context=64, min_slots=2, max_slots=2)
    paged = DecodeEngine(net, max_context=64, min_slots=2, max_slots=2,
                         kv="paged", page_size=8)
    try:
        da = dense.submit(pa, 16)
        db = dense.submit(pb, 10)
        da.result(timeout=300)
        db.result(timeout=300)
        a = paged.submit(pa, 16)
        # wait until A has written (and registered) its prompt pages so
        # B's admission can actually map them copy-on-write
        deadline = time.time() + 60
        while len(a.tokens) < 2 and time.time() < deadline:
            time.sleep(0.01)
        assert len(a.tokens) >= 2
        b = paged.submit(pb, 10)
        a.result(timeout=300)
        b.result(timeout=300)
    finally:
        st = paged.stats()
        dense.close()
        paged.close()
    assert a.tokens == da.tokens
    assert b.tokens == db.tokens
    # sharing genuinely engaged: B skipped re-prefilling the common prefix
    assert st["prefix_share_ratio"] > 0.0


def test_page_boundary_share_remaps_without_fork():
    """A shared page whose boundary coincides with the divergence point
    needs no fork at all — the follower keeps the whole page by
    reference and allocates fresh pages from the boundary on. Bitwise
    equality with dense is the contract either way."""
    net = _tf_net(seed=9)
    pa = [4, 5, 6, 7, 8, 9, 10, 11, 1]              # first page exactly full
    pb = pa[:8] + [13]                              # diverges ON the boundary
    dense = DecodeEngine(net, max_context=64, min_slots=2, max_slots=2)
    paged = DecodeEngine(net, max_context=64, min_slots=2, max_slots=2,
                         kv="paged", page_size=8)
    try:
        da = dense.submit(pa, 12).result(timeout=300)
        db = dense.submit(pb, 12).result(timeout=300)
        a = paged.submit(pa, 12)
        deadline = time.time() + 60
        while len(a.tokens) < 2 and time.time() < deadline:
            time.sleep(0.01)
        b = paged.submit(pb, 12)
        ta = a.result(timeout=300)
        tb = b.result(timeout=300)
    finally:
        dense.close()
        paged.close()
    assert ta == da and tb == db


# ------------------------------------------------------- refcount hygiene
def test_pool_refcounts_drain_after_1k_session_churn():
    """1000 churned sessions leave the pool exactly where it started:
    zero pages in use, the full free list back, the prefix registry
    empty, and device-resident bytes flat — the physical pool is the
    ONLY decode memory and it never grows."""
    net = _tf_net(seed=5)
    eng = DecodeEngine(net, max_context=64, min_slots=8, max_slots=8,
                       kv="paged", page_size=8)
    rng = np.random.default_rng(1)
    try:
        # warm wave: compile + allocate everything once
        prompts, budgets = _workload(8, rng, lo=2, hi=4)
        _run(eng, prompts, budgets)
        baseline_state = eng.state_bytes()
        baseline_live = _live_device_bytes()
        prompts = [[int(rng.integers(0, V))] for _ in range(1000)]
        budgets = [2] * 1000
        _run(eng, prompts, budgets)
        st = eng.stats()
        assert eng.state_bytes() == baseline_state
        grown = _live_device_bytes() - baseline_live
        assert grown <= 0, f"device bytes grew by {grown} after 1k sessions"
    finally:
        eng.close()
    assert st["pages_in_use"] == 0
    assert st["pages_free"] == st["pool_pages"]
    assert st["prefix_entries"] == 0


def test_pagepool_decref_drops_prefix_keys():
    pool = PagePool(4, 8)
    pid = pool.alloc()
    pool.register((1, 2, 3), pid)
    pids, covered = pool.match_prompt([1, 2, 3, 4])
    assert pids == [pid] and covered == 3
    pool.decref(pid)
    assert pool.free_pages == 4
    assert pool.prefix_entries == 0
    assert pool.match_prompt([1, 2, 3, 4])[1] == 0
    assert pid != TRASH_PAGE


# -------------------------------------------------- speculative decoding
def _spec_ab(draft_net, seed=5, n=8):
    net = _tf_net(seed=seed)
    prompts, budgets = _workload(n, np.random.default_rng(17), lo=4,
                                 hi=12)
    greedy = DecodeEngine(net, max_context=64, min_slots=4, max_slots=4)
    spec = DecodeEngine(net, max_context=64, min_slots=4, max_slots=4,
                        draft_net=draft_net, spec_tokens=3)
    try:
        gs = _run(greedy, prompts, budgets)
        ss = _run(spec, prompts, budgets)
        st = spec.stats()
    finally:
        greedy.close()
        spec.close()
    assert [g.tokens for g in gs] == [s.tokens for s in ss]
    assert st["spec_proposed"] > 0
    return st["spec_acceptance"]


def test_spec_identical_draft_acceptance_exactly_one():
    """A draft with the target's own weights proposes the target's own
    argmaxes: every judged proposal is accepted, and — the real
    contract — the emitted stream is still bit-for-bit greedy."""
    acc = _spec_ab(_tf_net(seed=5))
    assert acc == 1.0


def test_spec_partial_acceptance_bitwise_greedy():
    """A genuinely different (smaller, differently-seeded) draft is
    right only sometimes; rejected suffixes roll back behind the
    position mask and the stream is STILL exactly greedy."""
    acc = _spec_ab(_tf_net(seed=9, width=16))
    assert 0.0 < acc < 1.0


def test_spec_near_zero_acceptance_bitwise_greedy():
    """Sign-flipping every draft parameter makes its argmax essentially
    uncorrelated with the target's (~1/V agreement): verification falls
    back to one guaranteed token per round and the stream is STILL
    exactly greedy — the speedup degrades, never the math."""
    draft = _tf_net(seed=5)
    draft.set_params(-draft.params())
    acc = _spec_ab(draft)
    assert acc < 0.35


def test_spec_on_paged_kv_bitwise_greedy():
    """The two planes compose: spec-decode on the paged memory plane
    still emits the dense greedy stream bit-for-bit."""
    net = _tf_net(seed=5)
    prompts, budgets = _workload(8, np.random.default_rng(23), lo=3,
                                 hi=10)
    greedy = DecodeEngine(net, max_context=64, min_slots=4, max_slots=4)
    both = DecodeEngine(net, max_context=64, min_slots=4, max_slots=4,
                        kv="paged", page_size=8,
                        draft_net=_tf_net(seed=9, width=16), spec_tokens=3)
    try:
        gs = _run(greedy, prompts, budgets)
        bs = _run(both, prompts, budgets)
    finally:
        greedy.close()
        both.close()
    assert [g.tokens for g in gs] == [b.tokens for b in bs]


# ------------------------------------------------------ admission control
def test_never_fit_session_rejected_429_not_oom():
    """A session whose worst-case span needs more pages than the pool
    HAS is refused at submit with the RejectedError the HTTP layer maps
    to 429 — it must not be admitted only to OOM mid-decode."""
    net = _tf_net(seed=5)
    eng = DecodeEngine(net, max_context=64, min_slots=2, max_slots=2,
                       kv="paged", page_size=16, n_pages=2)
    try:
        with pytest.raises(RejectedError) as ei:
            eng.submit(list(range(20)), 20)  # span 40 -> 3 pages > 2
        assert ei.value.limit == 2 and ei.value.pending == 3
        assert ei.value.retry_after_s > 0
        # a session that fits completes normally on the same tiny pool
        toks = eng.submit([1, 2, 3], 8).result(timeout=300)
        assert len(toks) == 8
    finally:
        eng.close()


def test_tiny_pool_overload_degrades_to_preemption_not_oom():
    """Oversubscribing a pool with individually-fitting sessions must
    finish every session (preemption/parking reorders work, never
    crashes) and drain the pool."""
    net = _tf_net(seed=5)
    eng = DecodeEngine(net, max_context=64, min_slots=4, max_slots=4,
                       kv="paged", page_size=8, n_pages=6)
    prompts, budgets = _workload(12, np.random.default_rng(3), lo=2,
                                 hi=6)
    try:
        sessions = _run(eng, prompts, budgets)
        st = eng.stats()
    finally:
        eng.close()
    assert all(s.done.is_set() for s in sessions)
    assert st["pages_in_use"] == 0


# ----------------------------------------- headline: 2x sessions, = bytes
def test_paged_admits_2x_sessions_at_equal_state_bytes():
    """THE ISSUE-16 ratio: size the paged pool to the dense engine's
    exact KV bytes (n_pages = slots * pages_per_ctx - 1; the +1 trash
    page balances the ledger) and the paged engine holds 2x the
    concurrent sessions, emitting the identical streams."""
    net = _tf_net(seed=5)
    prompts, budgets = _workload(16, np.random.default_rng(11), lo=4,
                                 hi=9)
    dense = DecodeEngine(net, max_context=64, min_slots=4, max_slots=4)
    paged = DecodeEngine(net, max_context=64, min_slots=8, max_slots=8,
                         kv="paged", page_size=16,
                         n_pages=4 * (64 // 16) - 1)
    try:
        ds = _run(dense, prompts, budgets)
        ps = _run(paged, prompts, budgets)
        dst, pst = dense.stats(), paged.stats()
        dbytes, pbytes = dense.state_bytes(), paged.state_bytes()
    finally:
        dense.close()
        paged.close()
    assert [d.tokens for d in ds] == [p.tokens for p in ps]
    # equal memory: the paged plane pays only the tiny host page table
    # on top of the identical device pool bytes
    assert pbytes <= int(dbytes * 1.02)
    assert pst["peak_active"] >= 2 * dst["peak_active"]


# ------------------------------------------------------- growth copy path
def test_grow_copy_bytes_billed_and_small():
    """Capacity growth copies slot state device-side; only the small
    host scheduling arrays still round-trip, and THOSE bytes are billed
    to dl4j_decode_state_copy_bytes_total — far under the device blocks
    a host KV round-trip would have cost."""
    net = _tf_net(seed=5)
    for kv in ("dense", "paged"):
        eng = DecodeEngine(net, max_context=64, min_slots=2, max_slots=8,
                           kv=kv, page_size=16)
        try:
            assert eng.stats()["state_copy_bytes"] == 0
            prompts, budgets = _workload(12, np.random.default_rng(5))
            _run(eng, prompts, budgets)
            st = eng.stats()
            copied, blocks = st["state_copy_bytes"], eng.state_bytes()
        finally:
            eng.close()
        assert copied > 0, f"{kv}: growth billed nothing"
        assert copied < blocks // 10, \
            f"{kv}: {copied}B copied vs {blocks}B blocks — KV is " \
            "round-tripping through the host again"


# ------------------------------------------------------------ ops + names
def test_paged_gather_pallas_interpret_matches_xla(monkeypatch):
    rng = np.random.default_rng(2)
    pool = jnp.asarray(rng.standard_normal((9, 4, 2, 8)), jnp.float32)
    table = jnp.asarray(rng.integers(0, 9, size=(3, 5)), jnp.int32)
    ref = np.asarray(paged_gather(pool, table, impl="xla"))
    monkeypatch.setenv("DL4J_PAGED_GATHER_IMPL", "pallas")
    monkeypatch.setenv("DL4J_PAGED_GATHER_INTERPRET", "1")
    got = np.asarray(paged_gather(pool, table))
    assert got.shape == (3, 20, 2, 8)
    assert np.array_equal(ref, got)


def test_page_metric_names_registered():
    for name in (names.DECODE_PAGES_IN_USE,
                 names.DECODE_PREFIX_SHARE_RATIO,
                 names.DECODE_SPEC_ACCEPTANCE,
                 names.DECODE_SPEC_TOKENS_TOTAL,
                 names.DECODE_STATE_COPY_BYTES_TOTAL):
        assert name in names.ALL_METRIC_NAMES
        assert name.startswith("dl4j_decode_")
