"""Keras 1.x HDF5 import: fixtures are written with the framework's own
libhdf5 ctypes binding in the exact archive layout Keras 1 produces
(model_config/training_config root attrs, model_weights group with
layer_names/weight_names attrs)."""
import json

import numpy as np
import pytest

from deeplearning4j_tpu.modelimport.hdf5 import H5File, hdf5_available
from deeplearning4j_tpu.modelimport.keras_import import (
    InvalidKerasConfigurationException, KerasModelImport,
)

pytestmark = pytest.mark.skipif(not hdf5_available(),
                                reason="libhdf5 not present")


def _write_archive(path, model_config, weights, training_config=None):
    """weights: {layer_name: [(weight_name, array), ...]}"""
    with H5File(str(path), "w") as f:
        f.write_attr("/", "model_config", json.dumps(model_config))
        if training_config is not None:
            f.write_attr("/", "training_config", json.dumps(training_config))
        f.create_group("/model_weights")
        f.write_attr("/model_weights", "layer_names", list(weights))
        for lname, ws in weights.items():
            f.create_group(f"/model_weights/{lname}")
            f.write_attr(f"/model_weights/{lname}", "weight_names",
                         [wn for wn, _ in ws])
            for wn, arr in ws:
                f.write_dataset(f"/model_weights/{lname}/{wn}", arr)


def _seq(layers):
    return {"class_name": "Sequential",
            "config": [{"class_name": c, "config": cfg}
                       for c, cfg in layers]}


def test_dense_sequential_forward_matches_numpy(tmp_path):
    rng = np.random.default_rng(0)
    w1 = rng.normal(size=(4, 8)).astype(np.float32)
    b1 = rng.normal(size=(8,)).astype(np.float32)
    w2 = rng.normal(size=(8, 3)).astype(np.float32)
    b2 = rng.normal(size=(3,)).astype(np.float32)
    mc = _seq([
        ("Dense", {"name": "dense_1", "output_dim": 8, "activation": "relu",
                   "batch_input_shape": [None, 4]}),
        ("Dense", {"name": "dense_2", "output_dim": 3,
                   "activation": "softmax"}),
    ])
    p = tmp_path / "m.h5"
    _write_archive(p, mc, {
        "dense_1": [("dense_1_W", w1), ("dense_1_b", b1)],
        "dense_2": [("dense_2_W", w2), ("dense_2_b", b2)],
    }, training_config={"loss": "categorical_crossentropy"})

    net = KerasModelImport.import_keras_sequential_model_and_weights(str(p))
    x = rng.normal(size=(5, 4)).astype(np.float32)
    got = np.asarray(net.output(x))
    h = np.maximum(x @ w1 + b1, 0)
    z = h @ w2 + b2
    e = np.exp(z - z.max(axis=1, keepdims=True))
    expect = e / e.sum(axis=1, keepdims=True)
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)


def test_dense_plus_activation_folds_to_output_layer(tmp_path):
    rng = np.random.default_rng(1)
    w = rng.normal(size=(4, 3)).astype(np.float32)
    b = np.zeros(3, np.float32)
    mc = _seq([
        ("Dense", {"name": "dense_1", "output_dim": 3,
                   "activation": "linear", "batch_input_shape": [None, 4]}),
        ("Activation", {"name": "activation_1", "activation": "softmax"}),
    ])
    p = tmp_path / "m.h5"
    _write_archive(p, mc, {"dense_1": [("dense_1_W", w), ("dense_1_b", b)]})
    net = KerasModelImport.import_keras_sequential_model_and_weights(str(p))
    assert net.conf.n_layers == 1
    assert type(net.conf.layers[0]).__name__ == "OutputLayer"
    out = np.asarray(net.output(rng.normal(size=(2, 4)).astype(np.float32)))
    np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-5)


def test_conv_th_ordering_transposed(tmp_path):
    rng = np.random.default_rng(2)
    # th kernel layout: (nb_filter, stack, rows, cols)
    w = rng.normal(size=(2, 1, 3, 3)).astype(np.float32)
    b = np.zeros(2, np.float32)
    wd = rng.normal(size=(2 * 13 * 13, 5)).astype(np.float32)
    bd = np.zeros(5, np.float32)
    mc = _seq([
        ("Convolution2D", {"name": "conv_1", "nb_filter": 2, "nb_row": 3,
                           "nb_col": 3, "dim_ordering": "th",
                           "activation": "relu", "border_mode": "valid",
                           "batch_input_shape": [None, 1, 28, 28]}),
        ("MaxPooling2D", {"name": "pool_1", "pool_size": [2, 2],
                          "dim_ordering": "th"}),
        ("Flatten", {"name": "flat_1"}),
        ("Dense", {"name": "dense_1", "output_dim": 5,
                   "activation": "softmax"}),
    ])
    p = tmp_path / "m.h5"
    _write_archive(p, mc, {
        "conv_1": [("conv_1_W", w), ("conv_1_b", b)],
        "dense_1": [("dense_1_W", wd), ("dense_1_b", bd)],
    }, training_config={"loss": "categorical_crossentropy"})
    net = KerasModelImport.import_keras_sequential_model_and_weights(str(p))
    # kernel must land as HWIO = transpose(2,3,1,0) of the th layout
    np.testing.assert_allclose(np.asarray(net.params_list[0]["W"]),
                               np.transpose(w, (2, 3, 1, 0)))
    out = net.output(rng.normal(size=(2, 28, 28, 1)).astype(np.float32))
    assert out.shape == (2, 5)


def test_lstm_weight_fusion(tmp_path):
    rng = np.random.default_rng(3)
    n_in, h = 6, 4
    gates = {g: (rng.normal(size=(n_in, h)).astype(np.float32),
                 rng.normal(size=(h, h)).astype(np.float32),
                 rng.normal(size=(h,)).astype(np.float32))
             for g in "icfo"}
    ws = []
    for g in "icfo":  # Keras 1 serialization order: i, c, f, o
        W, U, b = gates[g]
        ws += [(f"lstm_1_W_{g}", W), (f"lstm_1_U_{g}", U),
               (f"lstm_1_b_{g}", b)]
    wd = rng.normal(size=(h, 2)).astype(np.float32)
    mc = _seq([
        ("LSTM", {"name": "lstm_1", "output_dim": h, "activation": "tanh",
                  "inner_activation": "sigmoid", "return_sequences": True,
                  "batch_input_shape": [None, 7, n_in]}),
        ("TimeDistributedDense", {"name": "td_1", "output_dim": 2,
                                  "activation": "softmax"}),
    ])
    p = tmp_path / "m.h5"
    _write_archive(p, mc, {
        "lstm_1": ws,
        "td_1": [("td_1_W", wd), ("td_1_b", np.zeros(2, np.float32))],
    }, training_config={"loss": "categorical_crossentropy"})
    net = KerasModelImport.import_keras_sequential_model_and_weights(str(p))
    # our gate order: i, f, c(g), o
    expect_W = np.concatenate([gates["i"][0], gates["f"][0], gates["c"][0],
                               gates["o"][0]], axis=1)
    expect_RW = np.concatenate([gates["i"][1], gates["f"][1], gates["c"][1],
                                gates["o"][1]], axis=1)
    np.testing.assert_allclose(np.asarray(net.params_list[0]["W"]), expect_W)
    np.testing.assert_allclose(np.asarray(net.params_list[0]["RW"]), expect_RW)
    out = net.output(rng.normal(size=(2, 7, n_in)).astype(np.float32))
    assert out.shape == (2, 7, 2)
    assert np.all(np.isfinite(np.asarray(out)))


def test_batchnorm_state_mapping(tmp_path):
    rng = np.random.default_rng(4)
    gamma = rng.normal(size=(4,)).astype(np.float32)
    beta = rng.normal(size=(4,)).astype(np.float32)
    mean = rng.normal(size=(4,)).astype(np.float32)
    var = np.abs(rng.normal(size=(4,))).astype(np.float32)
    wd = rng.normal(size=(4, 2)).astype(np.float32)
    mc = _seq([
        ("BatchNormalization", {"name": "bn_1", "epsilon": 1e-3,
                                "momentum": 0.95,
                                "batch_input_shape": [None, 4]}),
        ("Dense", {"name": "dense_1", "output_dim": 2,
                   "activation": "softmax"}),
    ])
    p = tmp_path / "m.h5"
    _write_archive(p, mc, {
        "bn_1": [("bn_1_gamma", gamma), ("bn_1_beta", beta),
                 ("bn_1_running_mean", mean), ("bn_1_running_std", var)],
        "dense_1": [("dense_1_W", wd), ("dense_1_b", np.zeros(2, np.float32))],
    }, training_config={"loss": "categorical_crossentropy"})
    net = KerasModelImport.import_keras_sequential_model_and_weights(str(p))
    np.testing.assert_allclose(np.asarray(net.params_list[0]["gamma"]), gamma)
    np.testing.assert_allclose(np.asarray(net.state_list[0]["mean"]), mean)
    np.testing.assert_allclose(np.asarray(net.state_list[0]["var"]), var)
    # inference uses imported running stats
    x = rng.normal(size=(3, 4)).astype(np.float32)
    out = np.asarray(net.feed_forward(x)[0])
    expect = gamma * (x - mean) / np.sqrt(var + 1e-3) + beta
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


def test_functional_merge_model(tmp_path):
    rng = np.random.default_rng(5)
    wa = rng.normal(size=(3, 4)).astype(np.float32)
    wb = rng.normal(size=(5, 4)).astype(np.float32)
    wo = rng.normal(size=(8, 2)).astype(np.float32)
    mc = {"class_name": "Model", "config": {
        "name": "model_1",
        "layers": [
            {"class_name": "InputLayer", "config": {
                "name": "in_a", "batch_input_shape": [None, 3]},
             "inbound_nodes": []},
            {"class_name": "InputLayer", "config": {
                "name": "in_b", "batch_input_shape": [None, 5]},
             "inbound_nodes": []},
            {"class_name": "Dense", "config": {
                "name": "da", "output_dim": 4, "activation": "relu"},
             "inbound_nodes": [[["in_a", 0, 0]]]},
            {"class_name": "Dense", "config": {
                "name": "db", "output_dim": 4, "activation": "relu"},
             "inbound_nodes": [[["in_b", 0, 0]]]},
            {"class_name": "Merge", "config": {
                "name": "merge_1", "mode": "concat"},
             "inbound_nodes": [[["da", 0, 0], ["db", 0, 0]]]},
            {"class_name": "Dense", "config": {
                "name": "out", "output_dim": 2, "activation": "softmax"},
             "inbound_nodes": [[["merge_1", 0, 0]]]},
        ],
        "input_layers": [["in_a", 0, 0], ["in_b", 0, 0]],
        "output_layers": [["out", 0, 0]],
    }}
    p = tmp_path / "m.h5"
    _write_archive(p, mc, {
        "da": [("da_W", wa), ("da_b", np.zeros(4, np.float32))],
        "db": [("db_W", wb), ("db_b", np.zeros(4, np.float32))],
        "out": [("out_W", wo), ("out_b", np.zeros(2, np.float32))],
    }, training_config={"loss": "categorical_crossentropy"})
    net = KerasModelImport.import_keras_model_and_weights(str(p))
    xa = rng.normal(size=(6, 3)).astype(np.float32)
    xb = rng.normal(size=(6, 5)).astype(np.float32)
    out = np.asarray(net.output(xa, xb)[0])
    ha = np.maximum(xa @ wa, 0)
    hb = np.maximum(xb @ wb, 0)
    z = np.concatenate([ha, hb], axis=1) @ wo
    e = np.exp(z - z.max(axis=1, keepdims=True))
    np.testing.assert_allclose(out, e / e.sum(axis=1, keepdims=True),
                               rtol=1e-4, atol=1e-6)


def test_config_only_import_and_unsupported_layer():
    mc = _seq([("Dense", {"name": "d", "output_dim": 3,
                          "activation": "softmax",
                          "batch_input_shape": [None, 4]})])
    conf = KerasModelImport.import_keras_model_configuration(json.dumps(mc))
    assert conf.n_layers == 1
    bad = _seq([("LocallyConnected2D", {"name": "x"})])
    with pytest.raises(InvalidKerasConfigurationException):
        KerasModelImport.import_keras_model_configuration(json.dumps(bad))


def test_attr_overwrite_and_uint_dataset(tmp_path):
    import numpy as np
    p = tmp_path / "o.h5"
    with H5File(str(p), "w") as f:
        f.write_attr("/", "model_config", "old")
        f.write_attr("/", "model_config", "new")  # must overwrite
        f.write_dataset("/labels", np.arange(4, dtype=np.uint32))
    with H5File(str(p)) as f:
        assert f.read_attr("/", "model_config") == "new"
        out = f.read_dataset("/labels")
        assert out.dtype == np.uint32
        np.testing.assert_array_equal(out, [0, 1, 2, 3])


def test_lstm_weight_fusion_scrambled_weight_names(tmp_path):
    """Gate arrays are matched by weight_names suffix, not list position
    (advisor round-1 medium finding): an archive listing the 12 LSTM arrays
    in non-canonical order must import identical parameters."""
    rng = np.random.default_rng(13)
    n_in, h = 5, 3
    gates = {g: (rng.normal(size=(n_in, h)).astype(np.float32),
                 rng.normal(size=(h, h)).astype(np.float32),
                 rng.normal(size=(h,)).astype(np.float32))
             for g in "icfo"}

    def archive(path, order):
        ws = []
        for g in order:
            W, U, b = gates[g]
            ws += [(f"lstm_1_W_{g}", W), (f"lstm_1_U_{g}", U),
                   (f"lstm_1_b_{g}", b)]
        mc = _seq([
            ("LSTM", {"name": "lstm_1", "output_dim": h, "activation": "tanh",
                      "inner_activation": "sigmoid", "return_sequences": True,
                      "batch_input_shape": [None, 4, n_in]}),
            ("TimeDistributedDense", {"name": "td_1", "output_dim": 2,
                                      "activation": "softmax"}),
        ])
        _write_archive(path, mc, {
            "lstm_1": ws,
            "td_1": [("td_1_W",
                      rng.normal(size=(h, 2)).astype(np.float32)),
                     ("td_1_b", np.zeros(2, np.float32))],
        }, training_config={"loss": "categorical_crossentropy"})

    p1, p2 = tmp_path / "canon.h5", tmp_path / "scrambled.h5"
    archive(p1, "icfo")   # canonical Keras-1 order
    archive(p2, "ofci")   # scrambled: positional mapping would swap gates
    net1 = KerasModelImport.import_keras_sequential_model_and_weights(str(p1))
    net2 = KerasModelImport.import_keras_sequential_model_and_weights(str(p2))
    np.testing.assert_allclose(np.asarray(net1.params_list[0]["W"]),
                               np.asarray(net2.params_list[0]["W"]))
    np.testing.assert_allclose(np.asarray(net1.params_list[0]["RW"]),
                               np.asarray(net2.params_list[0]["RW"]))
    np.testing.assert_allclose(np.asarray(net1.params_list[0]["b"]),
                               np.asarray(net2.params_list[0]["b"]))
