"""End-to-end request tracing + SLO burn-rate engine (ISSUE 17).

What is pinned here and why:

- W3C ``traceparent`` roundtrip: a caller-minted id threads through the
  real HTTP stack, comes back on the response, and resolves to a stored
  span tree whose parent/child ids and monotonic timestamps describe the
  actual request path (HTTP -> admission -> batch.queue), with the
  batch.dispatch span linking every coalesced request's trace.
- Tail-based sampling: a 429'd request is ALWAYS kept even at sample=0.0
  — the traces you need during an incident are exactly the ones head
  sampling throws away.
- The decode plane: one session's trace spans queue -> prefill -> decode,
  and a page-starved engine leaves park/preempt evidence in some trace.
- SLO burn-rate math on synthetic histogram windows with an injected
  clock: the multi-window AND-guard, the gauge flip, the flight-recorder
  bundle on the alert transition, and the histogram->trace exemplar that
  names a stored trace.
- The MetricsRegistry label-cardinality guard and the graftlint
  orphan-span rule that polices the cross-thread ``start_span`` idiom.
"""
import json
import pathlib
import textwrap
import threading
import time

import numpy as np
import pytest

import deeplearning4j_tpu.lint as lint
from deeplearning4j_tpu.keras_server import InferenceServer, ModelRegistry
from deeplearning4j_tpu.keras_server.batcher import MicroBatcher
from deeplearning4j_tpu.keras_server.replica import ReplicaSet
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.observability import names as _n
from deeplearning4j_tpu.observability.flight_recorder import FlightRecorder
from deeplearning4j_tpu.observability.metrics import MetricsRegistry
from deeplearning4j_tpu.observability.slo import SLO, SLOEngine
from deeplearning4j_tpu.observability.tracing import (
    NOOP_SPAN, TRACEPARENT_HEADER, TraceStore, format_traceparent,
    global_trace_store, parse_traceparent, set_global_trace_store,
    start_span, trace_span,
)

N_IN, N_OUT = 12, 3


def _mlp(seed=7):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(0.1).updater("adam")
            .weight_init("xavier")
            .list()
            .layer(DenseLayer(n_in=N_IN, n_out=24, activation="relu"))
            .layer(OutputLayer(n_in=24, n_out=N_OUT, loss="mcxent",
                               activation="softmax"))
            .build())
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    return MultiLayerNetwork(conf).init()


def _post(port, path, obj, headers=None, timeout=30):
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        h = {"Content-Type": "application/json"}
        h.update(headers or {})
        conn.request("POST", path, body=json.dumps(obj), headers=h)
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def _get(port, path):
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


@pytest.fixture()
def store():
    """Fresh 100%-sampled store swapped in as the process global; the
    previous store is restored on teardown so suite order can't leak."""
    prev = global_trace_store()
    st = TraceStore(enabled=True, sample=1.0, capacity=256,
                    registry=MetricsRegistry())
    set_global_trace_store(st)
    yield st
    set_global_trace_store(prev)


def _spans_by_name(record):
    out = {}
    for s in record["spans"]:
        out.setdefault(s["name"], []).append(s)
    return out


# ------------------------------------------------------------- traceparent

def test_traceparent_roundtrip_and_malformed():
    tid, sid = "a" * 32, "b" * 16
    header = format_traceparent(tid, sid)
    assert header == f"00-{tid}-{sid}-01"
    ref = parse_traceparent(header)
    assert ref.trace_id == tid and ref.span_id == sid
    for bad in (None, "", "junk", "00-short-b-01",
                f"00-{'0' * 32}-{sid}-01",      # all-zero trace id
                f"00-{tid}-{'0' * 16}-01",      # all-zero span id
                f"zz-{tid}-{sid}-01",           # bad version
                f"00-{'g' * 32}-{sid}-01"):     # non-hex
        assert parse_traceparent(bad) is None, bad


# ------------------------------------------------- span trees in the store

def test_span_tree_parents_and_monotonic_timestamps(store):
    with trace_span("root", kind="test") as root:
        with trace_span("child_a") as a:
            with trace_span("leaf") as leaf:
                pass
        with trace_span("child_b"):
            pass
    rec = store.get(root.trace_id)
    assert rec is not None and rec["n_spans"] == 4
    by = {s["name"]: s for s in rec["spans"]}
    assert by["root"]["parent_id"] is None
    assert by["child_a"]["parent_id"] == by["root"]["span_id"]
    assert by["leaf"]["parent_id"] == by["child_a"]["span_id"]
    assert by["child_b"]["parent_id"] == by["root"]["span_id"]
    assert leaf.trace_id == a.trace_id == root.trace_id
    # finalized span list is sorted by start mono; starts are monotonic
    monos = [s["mono"] for s in rec["spans"]]
    assert monos == sorted(monos)
    # a child starts after its parent and fits inside its duration
    assert by["child_a"]["mono"] >= by["root"]["mono"]
    assert (by["leaf"]["mono"] + by["leaf"]["dur_s"]
            <= by["child_a"]["mono"] + by["child_a"]["dur_s"] + 1e-6)


def test_disabled_store_returns_the_noop_singleton():
    prev = global_trace_store()
    try:
        set_global_trace_store(TraceStore(enabled=False,
                                          registry=MetricsRegistry()))
        sp = trace_span("anything")
        assert sp is NOOP_SPAN and sp.traceparent() == ""
        assert start_span("other") is NOOP_SPAN
        with sp:
            pass  # usable as a context manager, records nothing
    finally:
        set_global_trace_store(prev)


# ------------------------------------------------------ HTTP end to end

def test_http_request_traces_end_to_end(store):
    registry = ModelRegistry()
    registry.register("mlp", _mlp(), version="v1")
    srv = InferenceServer(registry, max_batch=8, max_latency_s=0.002,
                          max_queue=64).start()
    try:
        caller = format_traceparent("c" * 32, "d" * 16)
        status, headers, _ = _post(
            srv.port, "/v1/predict",
            {"model": "mlp", "inputs": [[0.0] * N_IN]},
            headers={TRACEPARENT_HEADER: caller})
        assert status == 200
        echoed = parse_traceparent(headers.get(TRACEPARENT_HEADER.title())
                                   or headers.get(TRACEPARENT_HEADER))
        # the response names the caller's trace, with the server root span
        assert echoed is not None and echoed.trace_id == "c" * 32
        assert echoed.span_id != "d" * 16

        # concurrent load: every request's tree has the full path with
        # consistent parent/child ids and monotonic timestamps
        ids, lock = [], threading.Lock()

        def client():
            s, h, _ = _post(srv.port, "/v1/predict",
                            {"model": "mlp", "inputs": [[0.0] * N_IN]})
            ref = parse_traceparent(h.get(TRACEPARENT_HEADER.title())
                                    or h.get(TRACEPARENT_HEADER))
            with lock:
                ids.append((s, ref))
        threads = [threading.Thread(target=client) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert all(s == 200 and r is not None for s, r in ids)
        deadline = time.time() + 10
        while time.time() < deadline and any(
                store.get(r.trace_id) is None for _, r in ids):
            time.sleep(0.01)  # queue spans finish on the dispatcher thread
        for _, ref in ids:
            rec = store.get(ref.trace_id)
            assert rec is not None, ref.trace_id
            by = _spans_by_name(rec)
            root = by["http /v1/predict"][0]
            assert root["parent_id"] is None
            admission = by["admission"][0]
            queue = by["batch.queue"][0]
            assert admission["parent_id"] == root["span_id"]
            assert queue["parent_id"] == root["span_id"]
            assert root["mono"] <= admission["mono"] <= queue["mono"]
            assert queue["attrs"]["model"] == "mlp"

        # the trace is fetchable over the wire, and /serve/traces lists it
        s, body = _get(srv.port, f"/serve/traces/{ids[0][1].trace_id}")
        assert s == 200
        assert json.loads(body)["trace_id"] == ids[0][1].trace_id
        s, body = _get(srv.port, "/serve/traces")
        listed = {t["trace_id"] for t in json.loads(body)["traces"]}
        assert ids[0][1].trace_id in listed
        s, body = _get(srv.port, "/serve/slo")
        assert s == 200 and {o["name"] for o in json.loads(body)["slo"]} \
            >= {"request_p99", "availability"}
    finally:
        srv.stop()


def test_batched_dispatch_links_every_request_trace(store):
    """N coalesced requests produce ONE batch.dispatch span whose links
    name all N parent request traces (the OTel batch-consumer shape)."""
    registry = ModelRegistry()
    registry.register("mlp", _mlp(), version="v1")
    # generous latency window so one group collects every submit
    batcher = MicroBatcher(registry, max_batch=8, max_latency_s=0.25,
                           max_queue=64)
    try:
        x = np.zeros((1, N_IN), np.float32)
        roots, futs = [], []
        for _ in range(4):
            with trace_span("test.request") as sp:
                futs.append(batcher.submit("mlp", x))
                roots.append(sp)
        for f in futs:
            f.result(timeout=30)
        assert batcher.stats()["dispatches"] == 1
    finally:
        batcher.close()
    dispatch = None
    for summary in store.list():
        rec = store.get(summary["trace_id"])
        names = _spans_by_name(rec)
        if "batch.dispatch" in names:
            assert dispatch is None, "more than one dispatch span"
            dispatch = names["batch.dispatch"][0]
    assert dispatch is not None
    linked = {parse_traceparent(tp).trace_id for tp in dispatch["links"]}
    assert linked == {r.trace_id for r in roots} and len(linked) == 4
    assert dispatch["attrs"]["rows"] == 4
    assert dispatch["attrs"]["compile_cache_hit"] in (True, False, None)


def test_429_is_always_kept_even_at_sample_zero():
    prev = global_trace_store()
    st = TraceStore(enabled=True, sample=0.0, capacity=64,
                    registry=MetricsRegistry())
    set_global_trace_store(st)
    registry = ModelRegistry()
    mv = registry.register("mlp", _mlp(seed=9), version="v1")
    release = threading.Event()
    real_pf = mv.predict_fn

    class _Blocking:
        def __call__(self, x):
            release.wait(timeout=30)
            return real_pf(x)

    srv = InferenceServer(registry, max_batch=1, max_latency_s=0.0,
                          max_queue=2).start()
    mv.predict_fn = _Blocking()
    results, lock = [], threading.Lock()

    def client():
        s, h, _ = _post(srv.port, "/v1/predict",
                        {"model": "mlp", "inputs": [[0.0] * N_IN]})
        ref = parse_traceparent(h.get(TRACEPARENT_HEADER.title())
                                or h.get(TRACEPARENT_HEADER))
        with lock:
            results.append((s, ref))
    try:
        threads = [threading.Thread(target=client) for _ in range(8)]
        for t in threads:
            t.start()
        deadline = time.time() + 10
        while srv.batcher.admission.rejected == 0 \
                and time.time() < deadline:
            time.sleep(0.005)
        release.set()
        for t in threads:
            t.join(timeout=30)
        deadline = time.time() + 10
        while time.time() < deadline and not any(
                st.get(r.trace_id) for s, r in results if s == 429):
            time.sleep(0.01)
    finally:
        release.set()
        srv.stop()
        set_global_trace_store(prev)
    rejected = [(s, r) for s, r in results if s == 429]
    assert rejected, "backpressure never tripped"
    for _, ref in rejected:
        rec = st.get(ref.trace_id)
        assert rec is not None, "429 trace was sampled away"
        assert rec["status"] == "error"
        assert rec["keep_reason"] == "error"
        root = rec["spans"][0]
        assert root["attrs"]["http_status"] == 429
    # at sample=0.0 the successful requests' traces were dropped
    kept_ok = [r for s, r in results if s == 200 and st.get(r.trace_id)]
    assert len(kept_ok) < len([1 for s, _ in results if s == 200]) + 1


# ------------------------------------------------------------ decode plane

def test_decode_session_trace_spans_queue_prefill_decode(store):
    from deeplearning4j_tpu.keras_server.decode import DecodeEngine
    from deeplearning4j_tpu.models.transformer import transformer_lm
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    net = MultiLayerNetwork(
        transformer_lm(vocab_size=24, width=32, n_layers=1, n_heads=2,
                       max_len=32, seed=5)).init()
    eng = DecodeEngine(net, max_context=32, min_slots=2, max_slots=2)
    try:
        sess = eng.submit([1, 2, 3], max_new_tokens=4)
        sess.result(timeout=300)
    finally:
        eng.close()
    rec = store.get(sess._span.trace_id)
    assert rec is not None
    by = _spans_by_name(rec)
    queue = by["decode.queue"][0]
    prefill = by["decode.prefill"][0]
    decode = by["decode.decode"][0]
    assert queue["parent_id"] is None
    assert prefill["parent_id"] == queue["span_id"]
    assert decode["parent_id"] == queue["span_id"]
    assert queue["attrs"]["prompt_len"] == 3
    assert prefill["attrs"]["ttft_s"] > 0
    assert decode["attrs"]["reason"] == "max_tokens"
    assert decode["attrs"]["tokens"] == 4
    assert prefill["mono"] <= decode["mono"]


def test_decode_pool_starvation_leaves_park_or_preempt_spans(store):
    """Oversubscribed paged pool: every session still finishes, and the
    starvation episodes are visible as decode.park / decode.preempt spans
    parented under the affected sessions' traces."""
    from deeplearning4j_tpu.keras_server.decode import DecodeEngine
    from deeplearning4j_tpu.models.transformer import transformer_lm
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    net = MultiLayerNetwork(
        transformer_lm(vocab_size=24, width=32, n_layers=2, n_heads=2,
                       max_len=64, seed=5)).init()
    # four active sessions want 4 x ceil(23/8) = 12 pages against a 6-page
    # pool: page planning MUST park or preempt to make progress
    eng = DecodeEngine(net, max_context=64, min_slots=4, max_slots=4,
                       kv="paged", page_size=8, n_pages=6)
    rng = np.random.default_rng(3)
    prompts = [list(map(int, rng.integers(0, 24, size=3)))
               for _ in range(12)]
    try:
        sessions = [eng.submit(p, max_new_tokens=20) for p in prompts]
        for s in sessions:
            s.result(timeout=300)
        pages_in_use = eng.stats()["pages_in_use"]
    finally:
        eng.close()
    assert pages_in_use == 0
    assert all(s.done.is_set() for s in sessions)
    park = preempt = 0
    for s in sessions:
        rec = store.get(s._span.trace_id)
        assert rec is not None
        by = _spans_by_name(rec)
        queue_id = by["decode.queue"][0]["span_id"]
        for name in ("decode.park", "decode.preempt"):
            for sp in by.get(name, ()):
                assert sp["parent_id"] == queue_id
        park += len(by.get("decode.park", ()))
        preempt += len(by.get("decode.preempt", ()))
    assert park + preempt > 0, "pool never starved: workload too small"


# --------------------------------------------------------- replica routing

def test_replica_router_propagates_request_trace(store):
    rs = ReplicaSet(2, max_latency_s=0.001)
    try:
        rs.register("m", _mlp(), version="v1")
        x = np.zeros((1, N_IN), np.float32)
        with trace_span("test.request") as root:
            fut = rs.submit("m", x)
        res = fut.result(timeout=60)
        assert res["replica"] in (0, 1)
    finally:
        rs.close()
    deadline = time.time() + 10
    rec = None
    while time.time() < deadline:
        rec = store.get(root.trace_id)
        if rec is not None and "batch.queue" in _spans_by_name(rec):
            break
        time.sleep(0.01)
    by = _spans_by_name(rec)
    route = by["replica.route"][0]
    queue = by["batch.queue"][0]
    assert route["parent_id"] == by["test.request"][0]["span_id"]
    # the queue span lives on the chosen replica's batcher but still
    # belongs to the caller's trace, under the routing span
    assert queue["parent_id"] == route["span_id"]
    assert route["attrs"]["replica"] == res["replica"]


# ------------------------------------------------------------ SLO engine

def _ttft_slo(threshold_s=0.5):
    return SLO("ttft_p99", kind="latency", metric=_n.SERVE_TTFT_SECONDS,
               threshold_s=threshold_s, target=0.99)


def test_slo_burn_rate_math_on_synthetic_windows(tmp_path):
    reg = MetricsRegistry()
    hist = reg.histogram(_n.SERVE_TTFT_SECONDS)
    store = TraceStore(enabled=True, sample=1.0, registry=MetricsRegistry())
    rec = FlightRecorder(capacity=16, dump_dir=str(tmp_path), registry=reg)
    now = [1000.0]
    eng = SLOEngine([_ttft_slo()], registry=reg, store=store,
                    recorder=rec, clock=lambda: now[0])

    # a stored trace supplies the exemplar a burning SLO must name
    prev = global_trace_store()
    set_global_trace_store(store)
    try:
        with trace_span("http /v1/generate") as sp:
            tid = sp.trace_id
    finally:
        set_global_trace_store(prev)
    store.put_exemplar(_n.SERVE_TTFT_SECONDS, 5.0, tid)

    # burn below both thresholds: 10% bad over a 1% budget = 10x — above
    # the 1h threshold (6) but below the 5m threshold (14.4): NOT firing
    for _ in range(90):
        hist.observe(0.01)
    for _ in range(10):
        hist.observe(5.0)
    now[0] += 60.0
    (entry,) = eng.evaluate()
    short, long_ = entry["windows"]
    assert short["total"] == 100 and short["bad"] == 10
    assert short["burn_rate"] == pytest.approx(10.0)
    assert long_["burn_rate"] == pytest.approx(10.0)
    assert entry["alerting"] is False
    alerts_fam = reg.snapshot().get(_n.SLO_ALERTS_TOTAL, {"series": []})
    assert all(s["value"] == 0 for s in alerts_fam["series"])
    assert not list(tmp_path.iterdir()), "no alert -> no dump"

    # inject a TTFT breach: the fresh window is 50% bad = 50x burn,
    # exceeding EVERY window's threshold -> alert fires once
    for _ in range(50):
        hist.observe(0.01)
    for _ in range(50):
        hist.observe(5.0)
    now[0] += 60.0
    (entry,) = eng.evaluate()
    assert entry["alerting"] is True
    assert entry["windows"][0]["burn_rate"] > 14.4
    # the gauge flipped above the page threshold
    burn_series = reg.snapshot()[_n.SLO_BURN_RATE]["series"]
    short_gauge = [s for s in burn_series
                   if s["labels"].get("window") == "300s"]
    assert short_gauge and short_gauge[0]["value"] > 14.4
    alerting = [s for s in reg.snapshot()[_n.SLO_ALERTING]["series"]
                if s["labels"].get("slo") == "ttft_p99"]
    assert alerting[0]["value"] == 1.0
    # budget is visibly spent
    assert entry["budget_remaining"] == 0.0
    # the flight-recorder bundle dumped, tagged with the objective
    bundles = [p for p in tmp_path.iterdir() if "slo-burn-ttft_p99" in p.name]
    assert len(bundles) == 1
    extra = json.loads((bundles[0] / "extra.json").read_text())
    assert extra["slo"]["name"] == "ttft_p99"
    # the exemplar names the stored trace, and it resolves
    assert entry["exemplar"]["trace_id"] == tid
    assert store.get(tid) is not None

    # still firing on the next evaluation: no re-dump (transition-edge +
    # cooldown), no double alert count
    now[0] += 30.0
    (entry,) = eng.evaluate()
    assert entry["alerting"] is True
    assert len(list(tmp_path.iterdir())) == 1
    alerts = [s for s in reg.snapshot()[_n.SLO_ALERTS_TOTAL]["series"]
              if s["labels"].get("slo") == "ttft_p99"]
    assert alerts[0]["value"] == 1.0


def test_slo_availability_objective_counts_errors():
    reg = MetricsRegistry()
    total = reg.counter(_n.SERVE_REQUESTS_TOTAL)
    bad = reg.counter(_n.SERVE_ERRORS_TOTAL)
    now = [0.0]
    slo = SLO("availability", kind="availability",
              total_metric=_n.SERVE_REQUESTS_TOTAL,
              bad_metric=_n.SERVE_ERRORS_TOTAL, target=0.999)
    eng = SLOEngine([slo], registry=reg, store=None, recorder=FlightRecorder(
        capacity=4, registry=reg), clock=lambda: now[0])
    for _ in range(1000):
        total.inc()
    for _ in range(20):
        bad.inc()
    now[0] += 60.0
    (entry,) = eng.evaluate()
    # 2% errors over a 0.1% budget = 20x burn on every window -> firing
    assert entry["windows"][0]["burn_rate"] == pytest.approx(20.0)
    assert entry["alerting"] is True


# ------------------------------------------------- metrics cardinality cap

def test_metrics_label_cardinality_guard(monkeypatch):
    monkeypatch.setenv("DL4J_METRICS_MAX_LABELSETS", "4")
    reg = MetricsRegistry()
    fam = reg.counter("dl4j_test_guarded_total")
    for i in range(4):
        fam.labels(k=f"v{i}").inc()
    # the 5th labelset lands on the shared overflow series, never exported
    fam.labels(k="v4").inc()
    fam.labels(k="v5").inc(2.0)
    snap = reg.snapshot()
    series = snap["dl4j_test_guarded_total"]["series"]
    assert len(series) == 4
    assert {s["labels"]["k"] for s in series} == {f"v{i}" for i in range(4)}
    dropped = snap[_n.METRICS_DROPPED_LABELSETS_TOTAL]["series"]
    assert sum(s["value"] for s in dropped) == 2
    assert dropped[0]["labels"]["family"] == "dl4j_test_guarded_total"
    # existing labelsets keep working at the cap
    fam.labels(k="v0").inc()
    snap = reg.snapshot()
    v0 = [s for s in snap["dl4j_test_guarded_total"]["series"]
          if s["labels"]["k"] == "v0"]
    assert v0[0]["value"] == 2


# ------------------------------------------------------- orphan-span lint

def _lint_serving_fixture(tmp_path, source):
    d = tmp_path / "keras_server"
    d.mkdir(exist_ok=True)
    f = d / "fixture.py"
    f.write_text(textwrap.dedent(source))
    return lint.run_paths([f], ["orphan-span"])


def test_orphan_span_rule_positive(tmp_path):
    res = _lint_serving_fixture(tmp_path, """\
        from deeplearning4j_tpu.observability.tracing import start_span

        def leak_discarded(x):
            start_span("dropped")      # result thrown away: never finished
            return x

        def leak_no_finally(x):
            sp = start_span("queue")
            do_work(x)                 # an exception here leaks the span
            sp.finish()
            return x
        """)
    assert [v.rule for v in res.violations] == ["orphan-span"] * 2
    assert res.violations[0].line == 4


def test_orphan_span_rule_negative(tmp_path):
    res = _lint_serving_fixture(tmp_path, """\
        from deeplearning4j_tpu.observability.tracing import (
            start_span, trace_span)

        def with_block(x):
            with trace_span("scoped"):
                return x

        def finally_finished(x):
            sp = start_span("queue")
            try:
                return work(x)
            finally:
                sp.finish()

        def owned_by_object(self, x):
            self.span = start_span("queue")   # ownership transferred

        def escapes(x):
            return start_span("handed-off")

        def finish_chain(x):
            start_span("instant", sid=x).set_status("ok").finish()
        """)
    assert res.violations == []


def test_orphan_span_rule_out_of_jurisdiction(tmp_path):
    # the cross-thread ownership idiom is only policed where it's used;
    # unrelated trees (examples, tests) are not
    f = tmp_path / "example.py"
    f.write_text("def f():\n    start_span('x')\n")
    assert lint.run_paths([f], ["orphan-span"]).violations == []


# -------------------------------------------------------- overhead budget

def test_tracing_overhead_budget():
    """Tracing at 100% sampling must cost <=2% of a serve request.
    Budget-style like test_telemetry_overhead_budget (a wall-clock A/B
    flakes on shared hosts): measure the real per-request latency of the
    traced HTTP serve path, count the spans + exemplar writes one request
    issues, microbenchmark those primitives, and require
    ops_per_request * per_op_cost <= 2% of the request time."""
    prev = global_trace_store()
    st = TraceStore(enabled=True, sample=1.0, capacity=256,
                    registry=MetricsRegistry())
    set_global_trace_store(st)
    registry = ModelRegistry()
    registry.register("mlp", _mlp(), version="v1")
    srv = InferenceServer(registry, max_batch=8, max_latency_s=0.001,
                          max_queue=256).start()
    try:
        for _ in range(30):   # warm: compile + connection path
            _post(srv.port, "/v1/predict",
                  {"model": "mlp", "inputs": [[0.0] * N_IN]})
        spans_before = len(st._ring)
        n_req = 100
        t0 = time.perf_counter()
        for _ in range(n_req):
            _post(srv.port, "/v1/predict",
                  {"model": "mlp", "inputs": [[0.0] * N_IN]})
        request_s = (time.perf_counter() - t0) / n_req
        assert len(st._ring) > spans_before  # the loop really was traced
    finally:
        srv.stop()
        set_global_trace_store(prev)

    # ops per request on the predict path: one root trace finalize (the
    # HTTP span), two child spans (admission + batch.queue), the dispatch
    # span amortized over its group (worst case: group of 1 -> one more
    # root), and one exemplar write
    probe = TraceStore(enabled=True, sample=1.0, capacity=256,
                       registry=MetricsRegistry())
    prev = global_trace_store()
    set_global_trace_store(probe)
    try:
        n_probe = 3000
        t0 = time.perf_counter()
        for _ in range(n_probe):
            with trace_span("probe.root"):
                pass
        root_s = (time.perf_counter() - t0) / n_probe
        with trace_span("probe.parent") as parent:
            t0 = time.perf_counter()
            for _ in range(n_probe):
                with trace_span("probe.child", parent=parent):
                    pass
            child_s = (time.perf_counter() - t0) / n_probe
        t0 = time.perf_counter()
        for _ in range(n_probe):
            probe.put_exemplar("probe_metric", 0.001, "f" * 32)
        exemplar_s = (time.perf_counter() - t0) / n_probe
    finally:
        set_global_trace_store(prev)

    overhead = 2 * root_s + 2 * child_s + exemplar_s
    assert overhead <= 0.02 * request_s, (
        f"tracing budget blown: 2x{root_s * 1e6:.1f}us root + "
        f"2x{child_s * 1e6:.1f}us child + {exemplar_s * 1e6:.1f}us "
        f"exemplar = {overhead * 1e6:.1f}us vs request "
        f"{request_s * 1e3:.2f}ms")


# --------------------------------------------- tail-sampler thread safety

def test_tail_sampler_survives_concurrent_finalize(store):
    """Regression: _finalize used to append to _durs and refresh the p99
    cache OUTSIDE the store lock. Two request threads finishing together
    could interleave the check-then-sort-then-cache sequence — and
    sorted() over a deque that another thread is appending to raises
    RuntimeError mid-iteration. Hammer enough roots through concurrent
    threads that the p99 refresh (every 32 finalizes) overlaps appends."""
    n_threads, n_each = 8, 100
    start = threading.Barrier(n_threads)
    errors = []

    def worker():
        start.wait()
        try:
            for _ in range(n_each):
                with trace_span("hammer.root"):
                    pass
        except Exception as e:  # pragma: no cover - the regression itself
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []
    # every finalize landed: the duration window saturated its maxlen and
    # the ring holds exactly its capacity of most-recent traces
    assert len(store._durs) == store._durs.maxlen
    assert len(store) == store.capacity
    assert isinstance(store._p99(), float)


def test_labelset_cap_warns_once_under_concurrent_overflow(
        monkeypatch, caplog):
    """Regression: the once-a-minute cap warning was a check-then-set on
    _warned_families outside the registry lock, so N threads hitting the
    cap together all read `last is None` and all warned. The RMW is now
    atomic: one warning per family per window, however many racers."""
    import logging

    monkeypatch.setenv("DL4J_METRICS_MAX_LABELSETS", "1")
    reg = MetricsRegistry()
    fam = reg.counter("dl4j_test_warn_once_total")
    fam.labels(k="keeper").inc()  # occupy the single allowed labelset
    n_threads = 16
    start = threading.Barrier(n_threads)

    def overflow(i):
        start.wait()
        fam.labels(k=f"spill{i}").inc()

    with caplog.at_level(
            logging.WARNING,
            logger="deeplearning4j_tpu.observability.metrics"):
        threads = [threading.Thread(target=overflow, args=(i,))
                   for i in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    warned = [r for r in caplog.records
              if "hit the labelset cap" in r.getMessage()]
    assert len(warned) == 1
    # and every overflow was still counted on the drop counter
    dropped = reg.snapshot()[_n.METRICS_DROPPED_LABELSETS_TOTAL]["series"]
    mine = [s for s in dropped
            if s["labels"]["family"] == "dl4j_test_warn_once_total"]
    assert sum(s["value"] for s in mine) == n_threads
