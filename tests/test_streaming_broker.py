"""Loopback broker + reconnecting consumer tests (reference dl4j-streaming
CamelKafkaRouteBuilder's Kafka leg): offset-addressed delivery, committed-
offset resume across forced connection drops (zero message loss), the
queue-seam compatibility with streaming.Route, and the route-error
observability satellite."""
import queue
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.observability.flight_recorder import (
    global_recorder,
)
from deeplearning4j_tpu.observability.metrics import global_registry
from deeplearning4j_tpu.observability.names import ROUTE_ERRORS_TOTAL
from deeplearning4j_tpu.streaming import Route
from deeplearning4j_tpu.streaming.broker import (
    BrokerProducer, BrokerTrainingRoute, LoopbackBroker,
    ReconnectingConsumer,
)


@pytest.fixture()
def broker():
    b = LoopbackBroker().start()
    yield b
    b.stop()


def _msg(i, n=4):
    return {"x": np.full((2, n), float(i), np.float32),
            "y": np.eye(3, dtype=np.float32)[[i % 3, (i + 1) % 3]]}


def _net():
    conf = (NeuralNetConfiguration.builder()
            .seed(12345).learning_rate(0.1).updater("sgd")
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                               activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def test_publish_fetch_roundtrip(broker):
    prod = BrokerProducer(broker.address)
    cons = ReconnectingConsumer(broker.address, "t", group="g")
    try:
        assert prod.publish("t", _msg(0), meta={"tag": "a"}) == 0
        assert prod.publish("t", _msg(1)) == 1
        meta, arrays = cons.get(timeout=2.0)
        assert meta["tag"] == "a"
        np.testing.assert_array_equal(arrays["x"], _msg(0)["x"])
        cons.task_done()
        _, arrays = cons.get(timeout=2.0)
        np.testing.assert_array_equal(arrays["x"], _msg(1)["x"])
        cons.task_done()
        with pytest.raises(queue.Empty):
            cons.get(timeout=0.05)  # log exhausted
        assert broker.depth("t") == 2
    finally:
        prod.close()
        cons.close()


def test_forced_drop_loses_no_messages(broker):
    """The headline satellite: 10 messages, connections force-dropped
    mid-stream; the consumer reconnects, resumes from its committed offset,
    and every message arrives exactly once in order."""
    prod = BrokerProducer(broker.address)
    cons = ReconnectingConsumer(broker.address, "t", group="g")
    try:
        for i in range(10):
            prod.publish("t", _msg(i), meta={"i": i})
        seen = []
        for _ in range(5):
            meta, _ = cons.get(timeout=2.0)
            seen.append(meta["i"])
            cons.task_done()

        assert broker.drop_connections() >= 1  # kill every live socket

        for _ in range(5):
            meta, _ = cons.get(timeout=5.0)
            seen.append(meta["i"])
            cons.task_done()
        assert seen == list(range(10))  # nothing lost, nothing duplicated
        assert cons.reconnects == 1
    finally:
        prod.close()
        cons.close()


def test_uncommitted_message_redelivers_after_drop(broker):
    """At-least-once pin: a message delivered but not task_done'd when the
    connection dies is redelivered after reconnect — never silently
    skipped."""
    prod = BrokerProducer(broker.address)
    cons = ReconnectingConsumer(broker.address, "t", group="g")
    try:
        prod.publish("t", _msg(0), meta={"i": 0})
        meta, _ = cons.get(timeout=2.0)
        assert meta["i"] == 0
        broker.drop_connections()  # dies BEFORE task_done commits offset 0
        cons.task_done()           # commit is lost with the connection
        meta, _ = cons.get(timeout=5.0)
        assert meta["i"] == 0      # redelivered
        cons.task_done()
    finally:
        prod.close()
        cons.close()


def test_consumer_groups_track_independent_offsets(broker):
    prod = BrokerProducer(broker.address)
    a = ReconnectingConsumer(broker.address, "t", group="a")
    b = ReconnectingConsumer(broker.address, "t", group="b")
    try:
        for i in range(3):
            prod.publish("t", _msg(i), meta={"i": i})
        a.get(timeout=2.0)
        a.task_done()  # group a committed offset 0
        assert b.get(timeout=2.0)[0]["i"] == 0  # group b starts at 0 anyway
    finally:
        prod.close()
        a.close()
        b.close()


def test_training_route_through_broker_survives_drop(broker):
    """A training loop fed by the broker: publish -> fit, with a forced
    connection drop mid-stream; every batch still reaches model.fit."""
    net = _net()
    rng = np.random.default_rng(0)
    batches = []
    for _ in range(6):
        x = rng.normal(size=(8, 4)).astype(np.float32)
        lab = (x[:, 0] + x[:, 1] > 0).astype(int)
        batches.append({"x": x, "y": np.eye(3, dtype=np.float32)[lab]})

    prod = BrokerProducer(broker.address)
    route = BrokerTrainingRoute(net, broker.address, "train").start()
    try:
        for b in batches[:3]:
            prod.publish("train", b)
        deadline = time.time() + 10
        while route.processed < 3 and time.time() < deadline:
            time.sleep(0.02)
        broker.drop_connections()
        for b in batches[3:]:
            prod.publish("train", b)
        deadline = time.time() + 10
        while route.processed < 6 and time.time() < deadline:
            time.sleep(0.02)
        assert route.processed == 6 and route.errors == []
        assert route.source.reconnects >= 1
    finally:
        route.stop()
        prod.close()


# ----------------------------------------------------- route observability

def test_route_handler_errors_are_counted_and_recorded():
    """Satellite (c): a poisoned handler used to leave only a silent
    .errors list — now it increments dl4j_route_errors_total and leaves a
    flight-recorder breadcrumb, while the route keeps consuming."""
    reg = global_registry()
    fam = reg.counter(ROUTE_ERRORS_TOTAL)
    series = fam.labels(route="Route")
    before = series.value

    def handler(msg):
        if msg == "poison":
            raise ValueError("bad message")

    src = queue.Queue()
    route = Route(src, handler).start()
    try:
        src.put("ok")
        src.put("poison")
        src.put("ok")
        route.drain(timeout=10)
        assert route.processed == 2
        assert route.errors == ["ValueError: bad message"]
        assert series.value == before + 1
        events = [e for e in global_recorder().snapshot()
                  if e.get("kind") == "route_error"]
        assert events and "bad message" in events[-1]["error"]
    finally:
        route.stop()


def test_broker_training_route_error_isolated_per_message(broker):
    """A malformed message (missing 'y') errors its fit but does not poison
    the subscription: later good messages still train."""
    net = _net()
    prod = BrokerProducer(broker.address)
    route = BrokerTrainingRoute(net, broker.address, "train").start()
    try:
        prod.publish("train", {"x": np.zeros((2, 4), np.float32)})  # no y
        good = {"x": np.zeros((2, 4), np.float32),
                "y": np.eye(3, dtype=np.float32)[[0, 1]]}
        prod.publish("train", good)
        deadline = time.time() + 10
        while route.processed < 1 and time.time() < deadline:
            time.sleep(0.02)
        assert route.processed == 1 and len(route.errors) == 1
    finally:
        route.stop()
        prod.close()
