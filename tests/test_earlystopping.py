"""Early stopping tests (reference deeplearning4j-core TestEarlyStopping.java)."""
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.earlystopping import (
    BestScoreEpochTerminationCondition, DataSetLossCalculator,
    EarlyStoppingConfiguration, EarlyStoppingTrainer, InMemoryModelSaver,
    InvalidScoreIterationTerminationCondition, LocalFileModelSaver,
    MaxEpochsTerminationCondition, MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition, ScoreImprovementEpochTerminationCondition,
    TerminationReason,
)
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _iris_like(n=60, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    labels = (x[:, 0] + x[:, 1] > 0).astype(int)
    y = np.zeros((n, 3), np.float32)
    y[np.arange(n), labels] = 1
    return [DataSet(x[i:i + 10], y[i:i + 10]) for i in range(0, n, 10)]


def _net(lr=0.05):
    conf = (NeuralNetConfiguration.builder()
            .seed(12).learning_rate(lr)
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent", activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def test_max_epochs_termination():
    data = _iris_like()
    it = ListDataSetIterator(data)
    conf = (EarlyStoppingConfiguration.builder()
            .epoch_termination_conditions(MaxEpochsTerminationCondition(5))
            .score_calculator(DataSetLossCalculator(ListDataSetIterator(data)))
            .model_saver(InMemoryModelSaver())
            .build())
    result = EarlyStoppingTrainer(conf, _net(), it).fit()
    assert result.termination_reason == TerminationReason.EPOCH_TERMINATION_CONDITION
    assert result.total_epochs == 5
    assert result.best_model is not None
    assert len(result.score_vs_epoch) == 5
    # training on a learnable problem: best score should beat the first epoch's
    assert result.best_model_score <= result.score_vs_epoch[0] + 1e-9


def test_invalid_score_termination():
    data = _iris_like()
    it = ListDataSetIterator(data)
    net = _net(lr=1e9)  # diverges to NaN quickly
    conf = (EarlyStoppingConfiguration.builder()
            .epoch_termination_conditions(MaxEpochsTerminationCondition(500))
            .iteration_termination_conditions(
                InvalidScoreIterationTerminationCondition(),
                MaxScoreIterationTerminationCondition(50.0))
            .score_calculator(DataSetLossCalculator(ListDataSetIterator(data)))
            .build())
    result = EarlyStoppingTrainer(conf, net, it).fit()
    assert result.termination_reason == TerminationReason.ITERATION_TERMINATION_CONDITION
    assert result.total_epochs < 500


def test_max_time_termination():
    data = _iris_like()
    it = ListDataSetIterator(data)
    conf = (EarlyStoppingConfiguration.builder()
            .epoch_termination_conditions(MaxEpochsTerminationCondition(100000))
            .iteration_termination_conditions(
                MaxTimeIterationTerminationCondition(1.5))
            .score_calculator(DataSetLossCalculator(ListDataSetIterator(data)))
            .build())
    result = EarlyStoppingTrainer(conf, _net(), it).fit()
    assert result.termination_reason == TerminationReason.ITERATION_TERMINATION_CONDITION
    assert "MaxTime" in result.termination_details


def test_score_improvement_termination():
    data = _iris_like()
    it = ListDataSetIterator(data)
    # lr=0 -> score never improves -> stops after N no-improvement epochs
    conf = (EarlyStoppingConfiguration.builder()
            .epoch_termination_conditions(
                ScoreImprovementEpochTerminationCondition(3),
                MaxEpochsTerminationCondition(500))
            .score_calculator(DataSetLossCalculator(ListDataSetIterator(data)))
            .build())
    result = EarlyStoppingTrainer(conf, _net(lr=0.0), it).fit()
    assert result.termination_reason == TerminationReason.EPOCH_TERMINATION_CONDITION
    assert "ScoreImprovement" in result.termination_details
    assert result.total_epochs <= 6


def test_best_score_termination():
    data = _iris_like()
    it = ListDataSetIterator(data)
    conf = (EarlyStoppingConfiguration.builder()
            .epoch_termination_conditions(
                BestScoreEpochTerminationCondition(10.0),  # any score < 10 stops
                MaxEpochsTerminationCondition(100))
            .score_calculator(DataSetLossCalculator(ListDataSetIterator(data)))
            .build())
    result = EarlyStoppingTrainer(conf, _net(), it).fit()
    assert result.termination_reason == TerminationReason.EPOCH_TERMINATION_CONDITION
    assert result.total_epochs == 1


def test_local_file_saver_roundtrip(tmp_path):
    data = _iris_like()
    it = ListDataSetIterator(data)
    saver = LocalFileModelSaver(str(tmp_path))
    conf = (EarlyStoppingConfiguration.builder()
            .epoch_termination_conditions(MaxEpochsTerminationCondition(3))
            .score_calculator(DataSetLossCalculator(ListDataSetIterator(data)))
            .model_saver(saver)
            .save_last_model(True)
            .build())
    result = EarlyStoppingTrainer(conf, _net(), it).fit()
    best = saver.get_best_model()
    latest = saver.get_latest_model()
    assert best is not None and latest is not None
    x = data[0].features
    np.testing.assert_allclose(np.asarray(best.output(x)),
                               np.asarray(result.best_model.output(x)), rtol=1e-5)


def test_early_stopping_computation_graph():
    from deeplearning4j_tpu.nn.graph_network import ComputationGraph

    data = _iris_like()
    it = ListDataSetIterator(data)
    conf = (NeuralNetConfiguration.builder()
            .seed(12).learning_rate(0.05)
            .graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_in=4, n_out=8, activation="tanh"), "in")
            .add_layer("out", OutputLayer(n_in=8, n_out=3, loss="mcxent",
                                          activation="softmax"), "d")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    es = (EarlyStoppingConfiguration.builder()
          .epoch_termination_conditions(MaxEpochsTerminationCondition(3))
          .score_calculator(DataSetLossCalculator(ListDataSetIterator(data)))
          .build())
    result = EarlyStoppingTrainer(es, net, it).fit()
    assert result.termination_reason == TerminationReason.EPOCH_TERMINATION_CONDITION
    assert result.total_epochs == 3
    assert result.best_model is not None
