"""Golden-file serialization regression tests (VERDICT round-2 item 7).

The committed fixtures in tests/golden/ were produced by
tests/golden/make_golden.py at a fixed point in time; these tests load them
through the CURRENT serde code and assert bit-compatible behavior — the
reference's RegressionTest071.java pattern: old checkpoints must keep
loading, byte-for-byte, across framework changes. If a test here fails, the
serialization schema broke; fix the code (or, for a deliberate schema
change, version the container) rather than regenerating the fixtures.
"""
import os

import numpy as np

GOLDEN = os.path.join(os.path.dirname(__file__), "golden")


def _expected():
    return np.load(os.path.join(GOLDEN, "golden_expected.npz"))


def test_mln_golden_loads_and_reproduces_outputs():
    from deeplearning4j_tpu.utils.model_serializer import (
        restore_multi_layer_network, restore_normalizer)

    exp = _expected()
    path = os.path.join(GOLDEN, "mln_golden.zip")
    net = restore_multi_layer_network(path, load_updater=True)
    norm = restore_normalizer(path)
    assert norm is not None

    from deeplearning4j_tpu.datasets.dataset import DataSet
    ds = DataSet(exp["mln_in"].copy(),
                 np.zeros((len(exp["mln_in"]), 3), np.float32))
    norm.transform(ds)
    out = np.asarray(net.output(ds.features))
    np.testing.assert_allclose(out, exp["mln_out"], rtol=1e-6, atol=1e-7)

    # updater state restored exactly (resume-compatible checkpoints)
    from deeplearning4j_tpu.utils.pytree import flatten_params
    got = np.asarray(flatten_params(net.updater_state, None), np.float32)
    np.testing.assert_allclose(got, exp["mln_updater_flat"], rtol=0, atol=0)


def test_cg_golden_loads_and_reproduces_outputs():
    from deeplearning4j_tpu.utils.model_serializer import (
        restore_computation_graph)

    exp = _expected()
    net = restore_computation_graph(os.path.join(GOLDEN, "cg_golden.zip"),
                                    load_updater=True)
    out = np.asarray(net.output(exp["cg_in_a"], exp["cg_in_b"])[0])
    np.testing.assert_allclose(out, exp["cg_out"], rtol=1e-6, atol=1e-7)

    from deeplearning4j_tpu.utils.pytree import flatten_params
    got = np.asarray(flatten_params(net.updater_state, None), np.float32)
    np.testing.assert_allclose(got, exp["cg_updater_flat"], rtol=0, atol=0)


def test_guess_model_distinguishes_golden_fixtures():
    from deeplearning4j_tpu.nn.graph_network import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.utils.model_serializer import guess_model

    assert isinstance(guess_model(os.path.join(GOLDEN, "mln_golden.zip")),
                      MultiLayerNetwork)
    assert isinstance(guess_model(os.path.join(GOLDEN, "cg_golden.zip")),
                      ComputationGraph)


def test_lm_golden_loads_and_reproduces_outputs():
    """Round-5 fixture: a trained transformer + Switch-MoE LM zip (attention,
    MoE router/expert tensors, aux-loss state schema) must stay loadable and
    bit-reproduce its recorded outputs and updater state forever."""
    from deeplearning4j_tpu.utils.model_serializer import (
        restore_multi_layer_network)
    from deeplearning4j_tpu.utils.pytree import flatten_params

    exp = np.load(os.path.join(GOLDEN, "lm_golden_expected.npz"))
    net = restore_multi_layer_network(os.path.join(GOLDEN, "lm_golden.zip"),
                                      load_updater=True)
    out = np.asarray(net.output(exp["lm_in"]))
    np.testing.assert_allclose(out, exp["lm_out"], atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(flatten_params(net.updater_state, None)),
        exp["lm_updater_flat"], atol=1e-6)
