"""End-to-end training tests: networks must actually learn.

Reference analog: deeplearning4j-core MultiLayerTest / LenetMnistExample-style smoke
tests — fit on small data, assert score decreases and accuracy beats chance.
"""
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.datasets.mnist import IrisDataSetIterator, MnistDataSetIterator
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ConvolutionLayer, DenseLayer, GravesLSTM, OutputLayer, RnnOutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.listeners import CollectScoresIterationListener


def test_iris_mlp_learns():
    it = IrisDataSetIterator(batch=30)
    conf = (NeuralNetConfiguration.builder()
            .seed(123).learning_rate(0.1).updater("adam")
            .weight_init("xavier")
            .list()
            .layer(DenseLayer(n_in=4, n_out=16, activation="relu"))
            .layer(OutputLayer(n_in=16, n_out=3, loss="mcxent", activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    collector = CollectScoresIterationListener()
    net.set_listeners(collector)
    net.fit_iterator(it, epochs=30)
    ev = net.evaluate(it)
    assert ev.accuracy() > 0.9, ev.stats()
    scores = [s for _, s in collector.scores]
    assert scores[-1] < scores[0] * 0.5


def test_score_decreases_sgd():
    x = np.random.default_rng(0).normal(size=(64, 10)).astype(np.float32)
    w_true = np.random.default_rng(1).normal(size=(10, 2)).astype(np.float32)
    y = x @ w_true
    conf = (NeuralNetConfiguration.builder()
            .seed(1).learning_rate(0.05).updater("sgd")
            .list()
            .layer(DenseLayer(n_in=10, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=2, loss="mse", activation="identity"))
            .build())
    net = MultiLayerNetwork(conf).init()
    s0 = net.score(x, y)
    for _ in range(50):
        net.fit(x, y)
    assert net.score(x, y) < s0 * 0.5


def test_mnist_lenet_smoke():
    """Tiny LeNet on (synthetic) MNIST: one pass improves over chance."""
    it = MnistDataSetIterator(batch=32, num_examples=512, seed=1)
    conf = (NeuralNetConfiguration.builder()
            .seed(12345).learning_rate(0.01).updater("adam")
            .weight_init("xavier")
            .list()
            .layer(ConvolutionLayer(n_out=8, kernel_size=(5, 5), activation="relu"))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=16, kernel_size=(5, 5), activation="relu"))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=64, activation="relu"))
            .layer(OutputLayer(n_out=10, loss="mcxent", activation="softmax"))
            .set_input_type(InputType.convolutional_flat(28, 28, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit_iterator(it, epochs=3)
    ev = net.evaluate(it)
    assert ev.accuracy() > 0.5, ev.stats()


def test_rnn_learns_sequence():
    """LSTM learns to echo the previous input token class."""
    rng = np.random.default_rng(0)
    B, T, C = 32, 8, 4
    idx = rng.integers(0, C, (B, T))
    x = np.zeros((B, T, C), np.float32)
    for b in range(B):
        x[b, np.arange(T), idx[b]] = 1
    y = np.zeros((B, T, C), np.float32)
    y[:, 1:] = x[:, :-1]
    y[:, 0, 0] = 1
    conf = (NeuralNetConfiguration.builder()
            .seed(5).learning_rate(0.02).updater("adam")
            .list()
            .layer(GravesLSTM(n_in=C, n_out=16, activation="tanh"))
            .layer(RnnOutputLayer(n_in=16, n_out=C, loss="mcxent", activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()
    s0 = net.score(x, y)
    for _ in range(60):
        net.fit(x, y)
    assert net.score(x, y) < s0 * 0.5


def test_tbptt_runs():
    rng = np.random.default_rng(0)
    B, T, C = 8, 20, 3
    x = rng.normal(size=(B, T, C)).astype(np.float32)
    y = np.zeros((B, T, C), np.float32)
    y[..., 0] = 1
    conf = (NeuralNetConfiguration.builder()
            .seed(5).learning_rate(0.05)
            .list()
            .layer(GravesLSTM(n_in=C, n_out=8, activation="tanh"))
            .layer(RnnOutputLayer(n_in=8, n_out=C, loss="mcxent", activation="softmax"))
            .backprop_type("TruncatedBPTT")
            .t_bptt_forward_length(5)
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit(x, y)
    assert net.iteration == 4  # 20 timesteps / 5 per chunk
    assert np.isfinite(net.score_value)


def test_rnn_time_step_streaming():
    conf = (NeuralNetConfiguration.builder()
            .seed(5)
            .list()
            .layer(GravesLSTM(n_in=3, n_out=6, activation="tanh"))
            .layer(RnnOutputLayer(n_in=6, n_out=2, loss="mcxent", activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(1).normal(size=(2, 6, 3)).astype(np.float32)
    full = np.asarray(net.output(x))
    net.rnn_clear_previous_state()
    outs = [np.asarray(net.rnn_time_step(x[:, t:t + 1])) for t in range(6)]
    streamed = np.concatenate(outs, axis=1)
    np.testing.assert_allclose(full, streamed, atol=1e-5)


def test_updaters_all_run():
    x = np.random.default_rng(0).normal(size=(16, 4)).astype(np.float32)
    y = np.zeros((16, 2), np.float32)
    y[:, 0] = 1
    for upd in ["sgd", "nesterovs", "adam", "adagrad", "rmsprop", "adadelta",
                "adamax", "lars", "lamb"]:
        conf = (NeuralNetConfiguration.builder()
                .seed(1).learning_rate(0.01).updater(upd)
                .list()
                .layer(DenseLayer(n_in=4, n_out=6, activation="tanh"))
                .layer(OutputLayer(n_in=6, n_out=2, loss="mcxent", activation="softmax"))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.fit(x, y)
        net.fit(x, y)
        assert np.isfinite(net.score_value), upd


def test_lr_schedules():
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.updaters import effective_lr

    assert float(effective_lr(0.1, None, 5)) == pytest.approx(0.1)
    assert float(effective_lr(0.1, "exponential", 2, decay=0.5)) == pytest.approx(0.025)
    assert float(effective_lr(0.1, "step", 10, decay=0.5, steps=5)) == pytest.approx(0.025)
    assert float(effective_lr(0.1, "schedule", 7,
                              schedule={0: 0.1, 5: 0.01})) == pytest.approx(0.01)


def test_gradient_normalization_clipping():
    conf = (NeuralNetConfiguration.builder()
            .seed(1).learning_rate(1.0)
            .gradient_normalization("ClipL2PerLayer")
            .gradient_normalization_threshold(0.5)
            .list()
            .layer(DenseLayer(n_in=4, n_out=6, activation="tanh"))
            .layer(OutputLayer(n_in=6, n_out=2, loss="mse", activation="identity"))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32) * 100
    y = np.random.default_rng(1).normal(size=(8, 2)).astype(np.float32) * 100
    p0 = np.asarray(net.params())
    net.fit(x, y)
    p1 = np.asarray(net.params())
    # with lr=1 and clip threshold 0.5, per-layer param change norm <= ~0.5
    delta = p1 - p0
    assert np.linalg.norm(delta) < 1.5


def test_params_flat_view_roundtrip():
    conf = (NeuralNetConfiguration.builder()
            .seed(1)
            .list()
            .layer(DenseLayer(n_in=4, n_out=6))
            .layer(OutputLayer(n_in=6, n_out=2, loss="mse", activation="identity"))
            .build())
    net = MultiLayerNetwork(conf).init()
    flat = np.asarray(net.params())
    assert flat.shape == (net.num_params(),)
    assert net.num_params() == 4 * 6 + 6 + 6 * 2 + 2
    net2 = MultiLayerNetwork(conf).init()
    net2.set_params(flat)
    np.testing.assert_allclose(np.asarray(net2.params()), flat)


def test_multistep_equals_sequential_steps():
    """K scanned steps per dispatch == K individual dispatches (bit-for-bit
    modulo float assoc). This is the TPU dispatch-amortization path bench.py
    measures; it must be semantically identical to the reference's
    per-minibatch fit loop (MultiLayerNetwork.java:1540)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.multilayer import (
        make_multistep_train_step, make_train_step)

    conf = (NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.05).updater("adam")
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                               activation="softmax"))
            .build())
    rng = np.random.default_rng(0)
    K, B = 4, 16
    xs = jnp.asarray(rng.normal(size=(K, B, 4)).astype(np.float32))
    ys_np = np.zeros((K, B, 3), np.float32)
    ys_np[..., 0] = 1
    ys = jnp.asarray(ys_np)
    key = jax.random.PRNGKey(3)

    net_a = MultiLayerNetwork(conf).init()
    multi = jax.jit(make_multistep_train_step(conf))
    pa, sa, ua, loss_multi = multi(net_a.params_list, net_a.state_list,
                                   net_a.updater_state, xs, ys, key,
                                   jnp.int32(0))

    net_b = MultiLayerNetwork(conf).init()
    step = jax.jit(make_train_step(conf))
    pb, sb, ub = net_b.params_list, net_b.state_list, net_b.updater_state
    losses = []
    for i in range(K):
        pb, sb, ub, loss = step(pb, sb, ub, xs[i], ys[i],
                                jax.random.fold_in(key, i), jnp.int32(i))
        losses.append(float(loss))

    for a, b in zip(jax.tree_util.tree_leaves(pa),
                    jax.tree_util.tree_leaves(pb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    # multistep returns the per-step loss stack (for lazy listener reads)
    np.testing.assert_allclose(np.asarray(loss_multi), np.asarray(losses),
                               rtol=1e-5, atol=1e-6)


def test_graph_multistep_equals_sequential_steps():
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.graph_network import (
        ComputationGraph, make_graph_multistep_train_step,
        make_graph_train_step)

    conf = (NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.05).updater("sgd")
            .graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_in=4, n_out=8, activation="tanh"), "in")
            .add_layer("out", OutputLayer(n_in=8, n_out=3, loss="mcxent",
                                          activation="softmax"), "d")
            .set_outputs("out")
            .build())
    rng = np.random.default_rng(1)
    K, B = 3, 8
    xs = jnp.asarray(rng.normal(size=(K, B, 4)).astype(np.float32))
    ys_np = np.zeros((K, B, 3), np.float32)
    ys_np[..., 1] = 1
    ys = jnp.asarray(ys_np)
    key = jax.random.PRNGKey(5)

    net_a = ComputationGraph(conf).init()
    multi = jax.jit(make_graph_multistep_train_step(conf))
    pa, _, _, loss_multi = multi(net_a.params_list, net_a.state_list,
                                 net_a.updater_state, [xs], [ys], key,
                                 jnp.int32(0))

    net_b = ComputationGraph(conf).init()
    step = jax.jit(make_graph_train_step(conf))
    pb, sb, ub = net_b.params_list, net_b.state_list, net_b.updater_state
    for i in range(K):
        pb, sb, ub, _ = step(pb, sb, ub, [xs[i]], [ys[i]],
                             jax.random.fold_in(key, i), jnp.int32(i))

    for a, b in zip(jax.tree_util.tree_leaves(pa),
                    jax.tree_util.tree_leaves(pb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_fit_iterator_multistep_equals_per_batch():
    """The production fit() fast path (K-step fused dispatch + lazy score
    sync) must be semantically identical to per-batch dispatch — including
    what listeners observe. Covers group flush (7 batches, K=4 -> groups of
    4+3) and the ragged final batch fallback."""
    rng = np.random.default_rng(0)
    xs = rng.normal(size=(200, 6)).astype(np.float32)
    ys = np.zeros((200, 3), np.float32)
    ys[np.arange(200), rng.integers(0, 3, 200)] = 1

    def build():
        conf = (NeuralNetConfiguration.builder()
                .seed(11).learning_rate(0.05).updater("adam")
                .list()
                .layer(DenseLayer(n_in=6, n_out=12, activation="tanh"))
                .layer(OutputLayer(n_in=12, n_out=3, loss="mcxent",
                                   activation="softmax"))
                .build())
        net = MultiLayerNetwork(conf).init()
        coll = CollectScoresIterationListener()
        net.set_listeners(coll)
        return net, coll

    # batch=32 over 200 examples -> 6 full batches + ragged batch of 8
    it = ArrayDataSetIterator(xs, ys, batch=32)
    net_a, coll_a = build()
    net_a.fit_iterator(it, epochs=2, ksteps=4)

    net_b, coll_b = build()
    net_b.fit_iterator(it, epochs=2, ksteps=1)

    assert net_a.iteration == net_b.iteration
    import jax
    for a, b in zip(jax.tree_util.tree_leaves(net_a.params_list),
                    jax.tree_util.tree_leaves(net_b.params_list)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    sa = np.array([s for _, s in coll_a.scores])
    sb = np.array([s for _, s in coll_b.scores])
    assert [i for i, _ in coll_a.scores] == [i for i, _ in coll_b.scores]
    np.testing.assert_allclose(sa, sb, rtol=1e-5, atol=1e-6)


def test_lazy_score_defers_sync():
    """score_value stores a device scalar / thunk and materializes on read."""
    conf = (NeuralNetConfiguration.builder()
            .seed(3).learning_rate(0.1).updater("sgd")
            .list()
            .layer(DenseLayer(n_in=4, n_out=4, activation="tanh"))
            .layer(OutputLayer(n_in=4, n_out=2, loss="mse",
                               activation="identity"))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = np.zeros((8, 4), np.float32)
    y = np.zeros((8, 2), np.float32)
    net.fit(x, y)
    assert not isinstance(net._score_raw, float)  # still device-resident
    s = net.score_value
    assert isinstance(s, float)
    assert isinstance(net._score_raw, float)  # cached after first read
    assert net.score_value == s


def test_fit_epochs_fused_equals_sequential():
    """fit(x, y, epochs=N) fuses K repeated steps per dispatch (batch staged
    once, broadcast along the scan axis) — must equal N sequential fits."""
    import jax

    rng = np.random.default_rng(2)
    x = rng.normal(size=(24, 5)).astype(np.float32)
    y = np.zeros((24, 3), np.float32)
    y[np.arange(24), rng.integers(0, 3, 24)] = 1

    def build():
        conf = (NeuralNetConfiguration.builder()
                .seed(9).learning_rate(0.05).updater("adam")
                .list()
                .layer(DenseLayer(n_in=5, n_out=8, activation="tanh"))
                .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                                   activation="softmax"))
                .build())
        return MultiLayerNetwork(conf).init()

    net_a = build()
    net_a.fit(x, y, epochs=7)  # K=8 default -> one fused dispatch of 7

    net_b = build()
    net_b.dispatch_ksteps = 1  # forces the sequential per-batch path
    net_b.fit(x, y, epochs=7)

    assert net_a.iteration == net_b.iteration == 7
    for a, b in zip(jax.tree_util.tree_leaves(net_a.params_list),
                    jax.tree_util.tree_leaves(net_b.params_list)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_graph_fit_epochs_fused_equals_sequential():
    import jax

    from deeplearning4j_tpu.nn.graph_network import ComputationGraph

    rng = np.random.default_rng(3)
    x = rng.normal(size=(16, 4)).astype(np.float32)
    y = np.zeros((16, 2), np.float32)
    y[np.arange(16), rng.integers(0, 2, 16)] = 1

    def build():
        conf = (NeuralNetConfiguration.builder()
                .seed(9).learning_rate(0.05).updater("sgd")
                .graph_builder()
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_in=4, n_out=6,
                                           activation="tanh"), "in")
                .add_layer("out", OutputLayer(n_in=6, n_out=2, loss="mcxent",
                                              activation="softmax"), "d")
                .set_outputs("out")
                .build())
        return ComputationGraph(conf).init()

    net_a = build()
    net_a.fit([x], [y], epochs=11)  # 8 + 3 fused dispatches

    net_b = build()
    net_b.dispatch_ksteps = 1
    net_b.fit([x], [y], epochs=11)

    assert net_a.iteration == net_b.iteration == 11
    for a, b in zip(jax.tree_util.tree_leaves(net_a.params_list),
                    jax.tree_util.tree_leaves(net_b.params_list)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_lars_lamb_trust_ratio_scales_update():
    """LARS/LAMB layerwise trust ratio: a parameter with 10x the norm gets a
    proportionally larger raw update under the same gradient (the property
    that makes large-batch scaling work; You et al. 2017/2019)."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.updaters import (
        UpdaterSpec, updater_init, updater_step_with_param)

    for name in ("lars", "lamb"):
        spec = UpdaterSpec(name=name)
        g = jnp.ones((4,)) * 0.5
        small = jnp.ones((4,)) * 0.1
        big = jnp.ones((4,)) * 1.0
        s_small = updater_init(spec, small)
        s_big = updater_init(spec, big)
        step_small, _ = updater_step_with_param(spec, g, small, s_small,
                                                jnp.float32(0.1), 0)
        step_big, _ = updater_step_with_param(spec, g, big, s_big,
                                              jnp.float32(0.1), 0)
        ratio = float(jnp.linalg.norm(step_big)
                      / jnp.linalg.norm(step_small))
        assert 9.0 < ratio < 11.0, (name, ratio)


def test_cosine_and_warmup_schedules():
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.updaters import effective_lr

    # cosine: base at 0, ~half at midpoint, ~0 at the end
    lr0 = float(effective_lr(0.4, "cosine", 0, max_iterations=100))
    lr50 = float(effective_lr(0.4, "cosine", 50, max_iterations=100))
    lr100 = float(effective_lr(0.4, "cosine", 100, max_iterations=100))
    assert abs(lr0 - 0.4) < 1e-6 and abs(lr50 - 0.2) < 1e-6 and lr100 < 1e-6

    # warmup_cosine: linear ramp over `steps`, then cosine down
    w10 = float(effective_lr(0.4, "warmup_cosine", 5, steps=10,
                             max_iterations=100))
    w_peak = float(effective_lr(0.4, "warmup_cosine", 10, steps=10,
                                max_iterations=100))
    w_end = float(effective_lr(0.4, "warmup_cosine", 100, steps=10,
                               max_iterations=100))
    assert abs(w10 - 0.2) < 1e-6
    assert abs(w_peak - 0.4) < 1e-6
    assert w_end < 1e-6


def test_stage_dtype_casts_on_host_before_transfer():
    """stage_dtype's contract is halved host->device wire bytes: the cast
    must happen on the HOST numpy array before jnp.asarray on every fit
    path (round-3 weak item: _fit_repeated shipped f32 then cast)."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.multilayer import _stage_host

    x = np.random.default_rng(0).normal(size=(4, 3)).astype(np.float32)
    staged = _stage_host(x, jnp.bfloat16)
    assert isinstance(staged, np.ndarray) and not isinstance(staged, jax.Array)
    assert staged.dtype == jnp.bfloat16  # cast happened host-side, pre-wire
    assert _stage_host(x, None) is x
    # device-resident arrays stay on device (no host round-trip)
    xd = jnp.asarray(x)
    assert isinstance(_stage_host(xd, jnp.bfloat16), jax.Array)

    # the fused-epochs path still trains correctly with staging enabled
    conf = (NeuralNetConfiguration.builder()
            .seed(0).learning_rate(0.1)
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                               activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.stage_dtype = jnp.bfloat16
    xs = np.random.default_rng(1).normal(size=(16, 4)).astype(np.float32)
    ys = np.eye(3, dtype=np.float32)[np.random.default_rng(2).integers(0, 3, 16)]
    net.fit(xs, ys, epochs=4)
    assert np.isfinite(net.score_value)


def test_evaluate_roc_multiclass_and_labeled_top_n():
    """MLN evaluation surface parity: evaluateROCMultiClass (reference
    MultiLayerNetwork.java:2401) and evaluate(iterator, labels, topN):2465."""
    it = IrisDataSetIterator(batch=30)
    conf = (NeuralNetConfiguration.builder()
            .seed(3).learning_rate(0.1).updater("adam")
            .list()
            .layer(DenseLayer(n_in=4, n_out=16, activation="relu"))
            .layer(OutputLayer(n_in=16, n_out=3, loss="mcxent",
                               activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit_iterator(it, epochs=15)
    roc = net.evaluate_roc_multiclass(it, threshold_steps=20)
    aucs = [roc.calculate_auc(c) for c in range(3)]
    assert all(0.8 < a <= 1.0 for a in aucs), aucs
    ev = net.evaluate(it, labels_list=["setosa", "versicolor", "virginica"],
                      top_n=2)
    assert ev.top_n_accuracy() >= ev.accuracy() > 0.85
    assert "setosa" in ev.stats()
