"""NLP extras: annotator pipeline, language tokenizers, stopwords, windows."""
from deeplearning4j_tpu.nlp.annotators import (
    AnnotatorPipeline, StemmerAnnotator,
)
from deeplearning4j_tpu.nlp.languages import (
    JapaneseTokenizerFactory, KoreanTokenizerFactory, StopWords, Windows,
)


def test_annotator_pipeline_sentences_tokens_pos():
    cas = AnnotatorPipeline().annotate(
        "The quick dog runs. She quickly chased the playful cats!")
    assert len(cas.sentences) == 2
    s0 = cas.sentences[0]
    texts = [t.text for t in s0.tokens]
    assert texts == ["The", "quick", "dog", "runs", "."]
    tags = {t.text: t.pos for t in s0.tokens}
    assert tags["The"] == "DET"
    assert tags["dog"] == "NOUN"
    assert tags["."] == "PUNCT"
    s1 = cas.sentences[1]
    tags1 = {t.text: t.pos for t in s1.tokens}
    assert tags1["She"] == "PRON"
    assert tags1["quickly"] == "ADV"
    # offsets index into the original document
    tok = s1.tokens[0]
    assert cas.text[tok.begin:tok.end] == "She"


def test_stemmer():
    st = StemmerAnnotator.stem
    assert st("running") == "runn"
    assert st("ponies") == "poni"
    assert st("cats") == "cat"
    assert st("nation") == "nation"  # too short to strip "ation"


def test_japanese_tokenizer_script_runs():
    tf = JapaneseTokenizerFactory()
    toks = tf.create("私はTPUで学習する").get_tokens()
    assert "TPU" in toks
    assert toks[0] == "私"  # kanji run separated from hiragana particle
    assert "は" in toks


def test_korean_tokenizer_particle_stripping():
    tf = KoreanTokenizerFactory()
    toks = tf.create("나는 학교에 간다").get_tokens()
    assert "학교" in toks  # 에 particle stripped
    assert "간다" in toks


def test_stopwords_and_windows():
    assert StopWords.is_stop_word("The")
    assert not StopWords.is_stop_word("tensor")
    ws = list(Windows.windows(["a", "b", "c"], window_size=3))
    assert ws[0] == ["<s>", "a", "b"]
    assert ws[1] == ["a", "b", "c"]
    assert ws[2] == ["b", "c", "</s>"]


def test_japanese_lattice_splits_particles():
    """Lattice-Viterbi segmentation splits closed-class morphemes out of
    script runs (kuromoji-architecture; reference deeplearning4j-nlp-japanese)
    — pure script-run splitting cannot produce these boundaries."""
    tf = JapaneseTokenizerFactory()
    toks = tf.create("私は東京へ行きます").get_tokens()
    for particle in ("は", "へ"):
        assert particle in toks, toks
    assert "東京" in toks
    # particle boundaries INSIDE a single hiragana run
    toks = tf.create("機械学習について学ぶことがたのしい").get_tokens()
    assert "について" in toks and "こと" in toks and "が" in toks, toks
    # unknown words stay whole (no over-splitting)
    assert tf.create("たのしい").get_tokens() == ["たのしい"]
    assert tf.create("テスト").get_tokens() == ["テスト"]


def test_japanese_conjugation_paradigm_fixtures():
    """Segmentation regression fixtures over the generated verb/adjective
    conjugation paradigms (round-4 lexicon growth; reference
    deeplearning4j-nlp-japanese with full IPADIC — see languages.py header
    for exactly what the embedded lexicon does and does not cover)."""
    tf = JapaneseTokenizerFactory()
    fixtures = {
        "私は東京へ行きます": ["私", "は", "東京", "へ", "行きます"],
        "本を読んだ": ["本", "を", "読んだ"],
        "新しいカメラを買いました": ["新しい", "カメラ", "を", "買いました"],
        "友達と映画を見ました": ["友達", "と", "映画", "を", "見ました"],
        "これは面白かったです": ["これ", "は", "面白かった", "です"],
        # negative-past adjective stays one token (paradigm edge beats
        # unknown-run + auxiliary splits)
        "難しくなかった": ["難しくなかった"],
        "昨日は寒かった": ["昨日", "は", "寒かった"],
        "日本語が分かりません": ["日本語", "が", "分かりません"],
        "もう忘れた": ["もう", "忘れた"],
    }
    for text, expect in fixtures.items():
        assert tf.create(text).get_tokens() == expect, (
            text, tf.create(text).get_tokens())


def test_japanese_open_class_dictionary_segmentation():
    """Round-5 open-class dictionary (nlp/ja_lexicon.py): real sentences
    whose correct boundaries REQUIRE open-class entries — compound kanji
    runs must split at word boundaries the closed-class lexicon cannot see
    (reference bar: kuromoji + IPADIC TokenInfoDictionary)."""
    from deeplearning4j_tpu.nlp.ja_lexicon import entry_count

    assert entry_count() >= 1000  # dictionary-scale, not a demo list
    tf = JapaneseTokenizerFactory()
    fixtures = {
        # compound kanji runs split only via open-class boundaries
        "日本語勉強中": ["日本語", "勉強", "中"],
        "東京大学病院": ["東京", "大学", "病院"],
        "自然言語処理": ["自然", "言語", "処理"],
        "国際関係学部学生": ["国際", "関係", "学部", "学生"],
        # full sentences mixing open + closed class
        "先生は学生に宿題を出しました": ["先生", "は", "学生", "に", "宿題",
                                         "を", "出しました"],
        "来週友達と旅行します": ["来週", "友達", "と", "旅行", "します"],
        "会議の資料を準備した": ["会議", "の", "資料", "を", "準備", "した"],
        "新幹線で大阪へ帰りました": ["新幹線", "で", "大阪", "へ",
                                     "帰りました"],
        "インターネットで情報を調べる": ["インターネット", "で", "情報",
                                         "を", "調べる"],
        "経済成長の原因を分析する": ["経済", "成長", "の", "原因", "を",
                                     "分析", "する"],
    }
    for text, expect in fixtures.items():
        got = tf.create(text).get_tokens()
        assert got == expect, (text, got)


def test_japanese_pos_emission():
    """kuromoji emits POS per token (Token.getPartOfSpeech); ja_tokenize_
    with_pos is that seam: lexicon tags for known tokens, char-class tags
    for unknowns."""
    from deeplearning4j_tpu.nlp.languages import ja_pos, ja_tokenize_with_pos

    pairs = ja_tokenize_with_pos("私は東京で勉強します")
    tags = dict(pairs)
    assert tags["は"] == "助詞"
    assert tags["東京"] == "名詞-固有"
    assert tags["勉強"] == "名詞-サ変"
    assert tags["します"] == "動詞"
    assert ja_pos("ブロックチェーン") == "名詞"  # unknown katakana run


def test_japanese_segmentation_is_lossless():
    """Property: segmentation never drops, duplicates, or reorders a single
    character — for arbitrary text including chars outside every lexicon
    (kuromoji's lattice guarantees the same by construction)."""
    import random

    from deeplearning4j_tpu.nlp.languages import _ja_viterbi

    rng = random.Random(0)
    pools = [
        "".join(chr(c) for c in range(0x3041, 0x3097)),   # hiragana
        "".join(chr(c) for c in range(0x30A1, 0x30FB)),   # katakana
        "".join(chr(c) for c in range(0x4E00, 0x4E80)),   # kanji slice
        "abcXYZ0189",                                     # latin/digits
        "、。！？・「」…─𝕏",                              # punct + astral
    ]
    for _ in range(60):
        n = rng.randint(1, 40)
        chunk = "".join(rng.choice(rng.choice(pools)) for _ in range(n))
        toks = _ja_viterbi(chunk)
        assert "".join(toks) == chunk, chunk
        assert all(toks), chunk  # no empty tokens
