"""Test configuration: run the whole suite on a virtual 8-device CPU mesh.

Mirrors the reference's backend-profile testing (reference pom.xml:123-150,
test-nd4j-native profile; Spark tests' local[N] master at BaseSparkTest.java:90):
the same tests validate single-device math and multi-device sharding without TPU
hardware. MUST set env vars before jax import.
"""
import os
import re

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                os.environ.get("XLA_FLAGS", ""))
os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# A sitecustomize-registered accelerator plugin may force jax_platforms after env
# parsing; re-force CPU so the suite always runs on the virtual 8-device mesh.
jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu" and len(jax.devices()) == 8, (
    "test suite requires the virtual 8-device CPU mesh; backends were initialized "
    f"before conftest could force them (got {jax.devices()})")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 gate (-m 'not slow')")


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture(scope="session", autouse=True)
def _lock_order_witness():
    """Opt-in runtime lock-order witness (DL4J_LOCK_WITNESS=1).

    Patches threading.Lock/RLock for the whole session so every lock the
    suites construct records its acquisition order, then asserts at
    teardown that no two locks were ever taken in both orders — the
    dynamic complement to the static lock-order rule. Off by default:
    ./runtests.sh lock turns it on for the threaded serving suites.
    """
    if os.environ.get("DL4J_LOCK_WITNESS") != "1":
        yield
        return
    from deeplearning4j_tpu.lint import witness
    witness.reset()
    witness.install()
    try:
        yield
    finally:
        witness.uninstall()
        witness.assert_acyclic()


@pytest.fixture(autouse=True)
def _compile_cache_isolation(tmp_path, monkeypatch):
    """Point the executable cache at a per-test tmp dir. Without this a
    warm entry from one test (or a previous run) would turn another test's
    expected cold compile into a disk hit — the compile-storm tests in
    particular pin that recompiles really happen."""
    monkeypatch.setenv("DL4J_COMPILE_CACHE_DIR", str(tmp_path / "xcache"))
