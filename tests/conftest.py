"""Test configuration: run the whole suite on a virtual 8-device CPU mesh.

Mirrors the reference's backend-profile testing (reference pom.xml:123-150,
test-nd4j-native profile; Spark tests' local[N] master at BaseSparkTest.java:90):
the same tests validate single-device math and multi-device sharding without TPU
hardware. MUST set env vars before jax import.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    return jax.devices()
