"""Keras gateway server: HDF5 minibatch iterator + fit/evaluate/predict over
the JSON-lines TCP gateway (reference deeplearning4j-keras module)."""
import json

import numpy as np
import pytest

from deeplearning4j_tpu.modelimport.hdf5 import H5File, hdf5_available
from deeplearning4j_tpu.keras_server import (
    DeepLearning4jEntryPoint, HDF5MiniBatchDataSetIterator, Server, call,
)

pytestmark = pytest.mark.skipif(not hdf5_available(),
                                reason="libhdf5 not present")


def _model_archive(path):
    rng = np.random.default_rng(0)
    mc = {"class_name": "Sequential", "config": [
        {"class_name": "Dense", "config": {
            "name": "dense_1", "output_dim": 8, "activation": "relu",
            "batch_input_shape": [None, 4]}},
        {"class_name": "Dense", "config": {
            "name": "dense_2", "output_dim": 3, "activation": "softmax"}},
    ]}
    with H5File(str(path), "w") as f:
        f.write_attr("/", "model_config", json.dumps(mc))
        f.write_attr("/", "training_config",
                     json.dumps({"loss": "categorical_crossentropy"}))
        f.create_group("/model_weights")
        f.write_attr("/model_weights", "layer_names", ["dense_1", "dense_2"])
        for lname, shape in [("dense_1", (4, 8)), ("dense_2", (8, 3))]:
            f.create_group(f"/model_weights/{lname}")
            f.write_attr(f"/model_weights/{lname}", "weight_names",
                         [f"{lname}_W", f"{lname}_b"])
            f.write_dataset(f"/model_weights/{lname}/{lname}_W",
                            rng.normal(0, 0.3, shape).astype(np.float32))
            f.write_dataset(f"/model_weights/{lname}/{lname}_b",
                            np.zeros(shape[1], np.float32))


def _batches(tmp_path):
    # separable 3-class data in 4-D
    rng = np.random.default_rng(1)
    xdir, ydir = tmp_path / "x", tmp_path / "y"
    xdir.mkdir(), ydir.mkdir()
    for b in range(4):
        labels = rng.integers(0, 3, 32)
        x = rng.normal(0, 0.3, (32, 4)).astype(np.float32)
        x[np.arange(32), labels] += 2.0
        y = np.eye(3, dtype=np.float32)[labels]
        with H5File(str(xdir / f"{b}.h5"), "w") as f:
            f.write_dataset("/data", x)
        with H5File(str(ydir / f"{b}.h5"), "w") as f:
            f.write_dataset("/data", y)
    return str(xdir), str(ydir)


def test_minibatch_iterator_orders_numerically(tmp_path):
    d = tmp_path / "b"
    d.mkdir()
    for i in [10, 2, 0]:
        with H5File(str(d / f"{i}.h5"), "w") as f:
            f.write_dataset("/data", np.full((2, 2), i, np.float32))
    it = HDF5MiniBatchDataSetIterator(str(d))
    vals = [int(a[0, 0]) for a in it]
    assert vals == [0, 2, 10]


def test_entry_point_fit_and_evaluate(tmp_path):
    model = tmp_path / "model.h5"
    _model_archive(model)
    xdir, ydir = _batches(tmp_path)
    ep = DeepLearning4jEntryPoint()
    r = ep.fit(str(model), nb_epoch=12, train_features_directory=xdir,
               train_labels_directory=ydir)
    assert r["batches"] == 4
    ev = ep.evaluate(str(model), xdir, ydir)
    assert ev["accuracy"] > 0.8


def test_gateway_over_tcp(tmp_path):
    model = tmp_path / "model.h5"
    _model_archive(model)
    xdir, ydir = _batches(tmp_path)
    srv = Server().start()
    try:
        r = call("127.0.0.1", srv.port, "fit", model_file_path=str(model),
                 nb_epoch=3, train_features_directory=xdir,
                 train_labels_directory=ydir)
        assert r["epochs"] == 3
        p = call("127.0.0.1", srv.port, "predict",
                 model_file_path=str(model),
                 features=[[2.0, 0.0, 0.0, 0.0]])
        assert len(p["predictions"][0]) == 3
        with pytest.raises(RuntimeError):
            call("127.0.0.1", srv.port, "fit", model_file_path="/nope.h5",
                 nb_epoch=1, train_features_directory=xdir,
                 train_labels_directory=ydir)
    finally:
        srv.stop()


def test_server_refuses_public_bind_without_token():
    import pytest as _pytest

    from deeplearning4j_tpu.keras_server import Server

    with _pytest.raises(ValueError, match="auth_token"):
        Server(host="0.0.0.0")


def test_server_token_auth_enforced(tmp_path):
    from deeplearning4j_tpu.keras_server import Server, call

    srv = Server(host="127.0.0.1", auth_token="s3cret").start()
    try:
        import pytest as _pytest

        with _pytest.raises(RuntimeError, match="auth token"):
            call("127.0.0.1", srv.port, "predict",
                 model_file_path="x", features=[])
        # correct token reaches the method (which then fails on the fake
        # path — proving auth passed, not silently rejected)
        with _pytest.raises(RuntimeError) as ei:
            call("127.0.0.1", srv.port, "predict", token="s3cret",
                 model_file_path="/nonexistent.h5", features=[[1.0]])
        assert "auth token" not in str(ei.value)
    finally:
        srv.stop()
