"""Checkpoint/resume tests.

Reference analog: ModelSerializer tests + regressiontest/ format-stability suite —
save -> restore must reproduce outputs exactly and resume training bit-identically
(updater state included, reference util/ModelSerializer.java:41-118).
"""
import numpy as np

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization, DenseLayer, OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.utils import model_serializer as ms


def _make_net():
    conf = (NeuralNetConfiguration.builder()
            .seed(1).learning_rate(0.05).updater("adam")
            .list()
            .layer(DenseLayer(n_in=6, n_out=10, activation="relu"))
            .layer(BatchNormalization(n_in=10))
            .layer(OutputLayer(n_in=10, n_out=3, loss="mcxent", activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(16, 6)).astype(np.float32)
    y = np.zeros((16, 3), np.float32)
    y[np.arange(16), rng.integers(0, 3, 16)] = 1
    return x, y


def test_save_restore_outputs_identical(tmp_path):
    net = _make_net()
    x, y = _data()
    net.fit(x, y)
    path = str(tmp_path / "model.zip")
    ms.write_model(net, path)
    net2 = ms.restore_multi_layer_network(path)
    np.testing.assert_array_equal(np.asarray(net.output(x)),
                                  np.asarray(net2.output(x)))
    assert net2.iteration == net.iteration


def test_resume_training_bit_identical(tmp_path):
    """Updater state round-trips: continued training matches uninterrupted training."""
    x, y = _data()
    netA = _make_net()
    for _ in range(5):
        netA.fit(x, y)
    path = str(tmp_path / "ckpt.zip")
    ms.write_model(netA, path, save_updater=True)

    # continue A directly
    for _ in range(5):
        netA.fit(x, y)

    # restore and continue B — same rng seed stream position differs, so use
    # deterministic (dropout-free) net: outputs must match exactly
    netB = ms.restore_multi_layer_network(path)
    netB._rng = None
    import jax
    netB._rng = jax.random.fold_in(jax.random.PRNGKey(1), 0xD14)
    # advance B's rng stream to match A's position (5 prior steps consumed 5 keys)
    for _ in range(5):
        netB._next_rng()
    for _ in range(5):
        netB.fit(x, y)
    np.testing.assert_allclose(np.asarray(netA.params()),
                               np.asarray(netB.params()), atol=1e-6)


def test_guess_model(tmp_path):
    net = _make_net()
    path = str(tmp_path / "m.zip")
    ms.write_model(net, path)
    loaded = ms.guess_model(path)
    assert type(loaded).__name__ == "MultiLayerNetwork"


def test_normalizer_roundtrip(tmp_path):
    from deeplearning4j_tpu.datasets.dataset import DataSet, NormalizerStandardize

    net = _make_net()
    x, y = _data()
    norm = NormalizerStandardize()
    norm.fit(DataSet(x, y))
    path = str(tmp_path / "m.zip")
    ms.write_model(net, path, normalizer=norm)
    norm2 = ms.restore_normalizer(path)
    np.testing.assert_allclose(norm.mean, norm2.mean)
    np.testing.assert_allclose(norm.std, norm2.std)


def test_graph_save_restore(tmp_path):
    from deeplearning4j_tpu.nn.graph_network import ComputationGraph

    conf = (NeuralNetConfiguration.builder()
            .seed(2).learning_rate(0.1)
            .graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_in=4, n_out=6, activation="tanh"), "in")
            .add_layer("out", OutputLayer(n_in=6, n_out=2, loss="mcxent",
                                          activation="softmax"), "d")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    x = np.random.default_rng(0).normal(size=(3, 4)).astype(np.float32)
    path = str(tmp_path / "graph.zip")
    ms.write_model(net, path)
    net2 = ms.restore_computation_graph(path)
    np.testing.assert_array_equal(np.asarray(net.output(x)[0]),
                                  np.asarray(net2.output(x)[0]))
