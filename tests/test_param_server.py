"""Async parameter-server engine tests (reference dl4j-spark-parameterserver
ParameterServerParallelWrapper + ParameterServerNode): staleness-bounded
delta pushes, bf16 wire codec, inproc/tcp transport parity, multi-process
loss parity, and regression pins for the two pre-engine bugs (last-pusher
dominance, shutdown double-count)."""
import socket
import threading

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.param_server import (
    DEFAULT_STALENESS_CAP, ParameterServer, ParameterServerParallelWrapper,
    flatten_tree, unflatten_tree,
)
from deeplearning4j_tpu.parallel.ps_transport import (
    InprocTransport, ParameterServerTcpFrontend, TcpTransport,
)
from deeplearning4j_tpu.streaming import wire


def _net(seed=12345, lr=0.1):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(lr).updater("sgd")
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                               activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def _batches(n_batches=16, batch=8, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_batches):
        x = rng.normal(size=(batch, 4)).astype(np.float32)
        labels = (x[:, 0] + x[:, 1] > 0).astype(int)
        y = np.zeros((batch, 3), np.float32)
        y[np.arange(batch), labels] = 1
        out.append(DataSet(x, y))
    return out


def _server(n=8, **kw):
    return ParameterServer([np.zeros(n, np.float32)], **kw)


# ---------------------------------------------------------------- staleness

def test_staleness_weight_is_one_over_one_plus_s():
    """Three pushes all based on version 0: the first lands at weight 1,
    the i-th at 1/(1+i-1) — the old (a+b)/2 soft-average let the LAST
    pusher overwrite half the state regardless of how stale it was
    (last-pusher dominance); the delta rule inverts that."""
    srv = _server()
    delta = np.ones(8, np.float32)
    weights = [srv.push_delta(delta, base_version=0).weight
               for _ in range(3)]
    assert weights == [1.0, 0.5, pytest.approx(1 / 3)]
    # applied sum is 1 + 1/2 + 1/3, not last-writer-dominated
    _, vec = srv.pull_flat()
    np.testing.assert_allclose(vec, (1 + 0.5 + 1 / 3) * delta, rtol=1e-6)
    assert srv.version == 3


def test_staleness_cap_rejects_and_returns_fresh_state():
    srv = _server(staleness_cap=2)
    delta = np.ones(8, np.float32)
    for _ in range(3):  # version -> 3
        srv.push_delta(delta, base_version=srv.version)
    res = srv.push_delta(delta, base_version=0)  # staleness 3 > cap 2
    assert not res.accepted and res.weight == 0.0 and res.staleness == 3
    assert srv.version == 3 and srv.rejected == 1
    # the rejection carries the fresh head: rebase + retry succeeds
    np.testing.assert_allclose(res.params, 3 * delta, rtol=1e-6)
    retry = srv.push_delta(delta, base_version=res.version)
    assert retry.accepted and retry.weight == 1.0 and srv.version == 4


def test_fresh_push_applies_exactly_once_at_weight_one():
    srv = _server()
    delta = np.arange(8, dtype=np.float32)
    res = srv.push_delta(delta, base_version=0)
    assert res.accepted and res.staleness == 0 and res.weight == 1.0
    np.testing.assert_allclose(res.params, delta)
    np.testing.assert_allclose(srv.pull_flat()[1], delta)


def test_server_momentum_optimizer_smooths_deltas():
    srv = _server(optimizer="momentum", momentum=0.5)
    delta = np.ones(8, np.float32)
    srv.push_delta(delta, base_version=0)           # vel = 1
    srv.push_delta(delta, base_version=srv.version)  # vel = 1.5
    np.testing.assert_allclose(srv.pull_flat()[1], 2.5 * delta, rtol=1e-6)


def test_tree_flatten_roundtrip():
    tree = [np.arange(6, dtype=np.float32).reshape(2, 3),
            np.ones((4,), np.float32)]
    vec, spec = flatten_tree(tree)
    assert vec.shape == (10,) and vec.dtype == np.float32
    back = unflatten_tree(vec, spec)
    for a, b in zip(tree, back):
        np.testing.assert_array_equal(a, np.asarray(b))


# --------------------------------------------------------------------- wire

def test_bf16_wire_roundtrip_tolerance():
    rng = np.random.default_rng(7)
    a = rng.normal(0, 3, (32, 17)).astype(np.float32)
    meta, buf = wire.encode_array(a, codec="bf16")
    assert len(buf) == a.size * 2  # halved wire bytes
    back = wire.decode_array(meta, buf)
    assert back.dtype == np.float32 and back.shape == a.shape
    np.testing.assert_allclose(back, a, rtol=1e-2, atol=1e-2)


def test_none_codec_is_exact():
    a = np.random.default_rng(3).normal(size=(5, 5)).astype(np.float32)
    meta, buf = wire.encode_array(a, codec="none")
    np.testing.assert_array_equal(wire.decode_array(meta, buf), a)


def test_wire_frame_roundtrip_over_socket():
    srv, cli = socket.socketpair()
    try:
        payload = b"\x00\x01payload"
        wire.send_frame(cli, {"op": "x", "n": 3}, payload)
        header, buf = wire.recv_frame(srv)
        assert header == {"op": "x", "n": 3} and buf == payload
        cli.close()
        with pytest.raises(ConnectionError):
            wire.recv_frame(srv)  # EOF mid-stream is an error, not b""
    finally:
        srv.close()


# ---------------------------------------------------------------- transport

def test_tcp_transport_parity_with_inproc():
    """The same push/pull sequence through loopback TCP (codec none) lands
    bit-identically with the in-process transport."""
    srv_a = _server()
    srv_b = _server()
    frontend = ParameterServerTcpFrontend(srv_b).start()
    inproc = InprocTransport(srv_a)
    tcp = TcpTransport(("127.0.0.1", frontend.port))
    try:
        rng = np.random.default_rng(11)
        for _ in range(5):
            delta = rng.normal(size=8).astype(np.float32)
            ra = inproc.push(delta, base_version=srv_a.version)
            rb = tcp.push(delta, base_version=tcp.pull()[0])
            assert (ra.accepted, ra.version, ra.staleness, ra.weight) == \
                   (rb.accepted, rb.version, rb.staleness, rb.weight)
            np.testing.assert_array_equal(ra.params, rb.params)
        va, veca = inproc.pull()
        vb, vecb = tcp.pull()
        assert va == vb
        np.testing.assert_array_equal(veca, vecb)
    finally:
        tcp.close()
        frontend.stop()


def test_tcp_transport_bf16_pushes_decode_within_tolerance():
    srv = _server()
    frontend = ParameterServerTcpFrontend(srv).start()
    tcp = TcpTransport(("127.0.0.1", frontend.port), codec="bf16")
    try:
        delta = np.linspace(-2, 2, 8).astype(np.float32)
        res = tcp.push(delta, base_version=0)
        assert res.accepted
        np.testing.assert_allclose(srv.pull_flat()[1], delta,
                                   rtol=1e-2, atol=1e-2)
    finally:
        tcp.close()
        frontend.stop()


# -------------------------------------------------- worker loop regressions

def test_single_worker_matches_single_machine_fit():
    """One worker, no contention: every window delta lands at staleness 0 /
    weight 1, so async-PS training IS single-machine training. This pins the
    shutdown double-count bug — the old wrapper re-pushed the final window
    on shutdown, applying the last deltas twice."""
    data = _batches(n_batches=8)
    ps_net = _net()
    wrapper = (ParameterServerParallelWrapper.builder(ps_net)
               .workers(1).push_frequency(4).build())
    wrapper.fit(ListDataSetIterator(data))

    single = _net()
    for ds in data:
        single.fit(ds.features, ds.labels)

    import jax
    for a, b in zip(jax.tree_util.tree_leaves(ps_net.params_list),
                    jax.tree_util.tree_leaves(single.params_list)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)
    # 8 batches / push_frequency 4 = exactly 2 pushes, no shutdown re-push
    assert wrapper.server.pushes == 2
    assert wrapper.worker_stats[0]["steps"] == 8
    assert wrapper.worker_stats[0]["pushes"] == 2


def test_partial_final_window_flushes_exactly_once():
    """6 batches at push_frequency 4: one full window plus a 2-step flush =
    2 pushes; an empty final window (8 batches) must NOT add a third."""
    data = _batches(n_batches=6)
    wrapper = (ParameterServerParallelWrapper
               .builder(_net()).workers(1).push_frequency(4).build())
    wrapper.fit(ListDataSetIterator(data))
    assert wrapper.server.pushes == 2
    assert wrapper.worker_stats[0]["steps"] == 6


def test_async_multiworker_trains_and_counts_every_step():
    data = _batches(n_batches=16)
    net = _net()
    gx = np.concatenate([d.features for d in data])
    gy = np.concatenate([d.labels for d in data])
    s0 = float(net.score(gx, gy))
    wrapper = (ParameterServerParallelWrapper.builder(net)
               .workers(4).push_frequency(2).staleness(4).build())
    wrapper.fit(ListDataSetIterator(data))
    assert sum(s["steps"] for s in wrapper.worker_stats) == 16
    assert wrapper.server.version == wrapper.server.pushes > 0
    assert float(net.score(gx, gy)) < s0 * 0.9


def test_staleness_cap_zero_forces_rebase_retry_but_loses_no_steps():
    """cap=0 under 4 contending workers: pushes based even one version back
    are rejected; the worker loop's rebase-and-retry must still land every
    window (rejected counted, steps conserved)."""
    data = _batches(n_batches=16)
    wrapper = (ParameterServerParallelWrapper.builder(_net())
               .workers(4).push_frequency(1).staleness(0).build())
    wrapper.fit(ListDataSetIterator(data))
    assert sum(s["steps"] for s in wrapper.worker_stats) == 16
    # every worker's windows all landed (a retry that is itself rejected is
    # dropped only after the second attempt — with cap 0 and 4 workers some
    # retries happen; the accounting must balance regardless)
    assert wrapper.server.pushes + wrapper.server.rejected >= 16


def test_straggler_worker_does_not_stall_the_others():
    """Straggler smoke (the bench.py ps_async A/B in miniature): worker 0
    sleeps 4x the others; total wall time must track the fast workers'
    share + the straggler's own share, NOT workers * straggler_delay (which
    is what the sync barrier pays)."""
    import time as _time
    data = _batches(n_batches=12)
    wrapper = (ParameterServerParallelWrapper.builder(_net())
               .workers(4).push_frequency(2)
               .worker_delays(0.08, 0.02, 0.02, 0.02).build())
    t0 = _time.perf_counter()
    wrapper.fit(ListDataSetIterator(data))
    dt = _time.perf_counter() - t0
    assert sum(s["steps"] for s in wrapper.worker_stats) == 12
    # barrier-world lower bound would be 12 steps * 0.08s = 0.96s
    assert dt < 0.9, f"straggler stalled the pool: {dt:.2f}s"


def test_builder_validation():
    net = _net()
    with pytest.raises(ValueError):
        ParameterServerParallelWrapper(net, transport="carrier-pigeon")
    with pytest.raises(ValueError):
        ParameterServerParallelWrapper(net, compression="zip")
    with pytest.raises(ValueError):
        # hooks run in-interpreter; tcp workers are separate processes
        (ParameterServerParallelWrapper.builder(net)
         .transport("tcp").training_hooks(object()).build())


def test_legacy_push_pull_facade_still_works():
    net = _net()
    srv = ParameterServer(net.params_list)
    tree = srv.pull()
    res = srv.push(tree)  # full-param push against current head
    assert res.accepted and srv.version == 1


# ----------------------------------------------------------- multi-process

@pytest.mark.slow
def test_tcp_two_process_loss_parity():
    """2 separate-process TCP workers with bf16 deltas reach within 5% of a
    single-process sync fit's loss on the same batches (ISSUE 10 phase-B
    acceptance, shrunk fixture)."""
    rng = np.random.default_rng(0)
    means = rng.normal(0.0, 1.0, (3, 4)).astype(np.float32)
    data = []
    for _ in range(24):
        lab = rng.integers(0, 3, 16)
        x = (means[lab] + rng.normal(0, 0.5, (16, 4))).astype(np.float32)
        noisy = np.where(rng.random(16) < 0.25, rng.integers(0, 3, 16), lab)
        data.append(DataSet(x, np.eye(3, dtype=np.float32)[noisy]))
    gx = np.concatenate([d.features for d in data])
    gy = np.concatenate([d.labels for d in data])

    base = _net()
    oracle = base.clone()
    for ds in data:
        oracle.fit(ds.features, ds.labels)
    sync_loss = float(oracle.score(gx, gy))

    tcp_net = base.clone()
    # 20ms/step pacing: the dense fixture steps in ~1ms, which turns 2-proc
    # training into a pure race (workers finish before each other's pushes
    # land); a uniform delay restores realistic push interleaving
    wrapper = (ParameterServerParallelWrapper.builder(tcp_net)
               .workers(2).push_frequency(2).transport("tcp")
               .compression("bf16").worker_delays(0.02, 0.02).build())
    wrapper.fit(ListDataSetIterator(data))
    tcp_loss = float(tcp_net.score(gx, gy))

    assert len(wrapper.worker_stats) == 2
    assert sum(s["steps"] for s in wrapper.worker_stats) == 24
    # 15% on this shrunk, timing-noisy fixture; the 5% acceptance number is
    # measured by bench.py ps_async on the LeNet fixture at full scale
    assert abs(tcp_loss / sync_loss - 1.0) < 0.15, \
        f"tcp async {tcp_loss:.4f} vs sync {sync_loss:.4f}"
    assert tcp_loss < 1.0986  # better than uniform ln(3): it really trained


# ------------------------------------------------------------- concurrency

def test_server_is_thread_safe_under_contention():
    srv = _server(n=4)
    delta = np.ones(4, np.float32)
    n_threads, pushes_each = 8, 50

    def worker():
        for _ in range(pushes_each):
            base = srv.pull_flat()[0]
            srv.push_delta(delta, base)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert srv.version == srv.pushes == n_threads * pushes_each
    # every applied weight is in (0, 1]; the vec is a positive multiple of
    # delta bounded by the push count
    vec = srv.pull_flat()[1]
    assert 0 < vec[0] <= n_threads * pushes_each
