"""Generate the committed golden serialization fixtures.

Run from the repo root:  python tests/golden/make_golden.py

Produces model zips + a reference-outputs npz that
tests/test_golden_serialization.py asserts against forever after — the
regression-test pattern of the reference's RegressionTest071.java: once a
fixture is committed, later serde changes must still load it bit-compatibly.
Regenerating fixtures is a BREAKING schema change and must be deliberate.
"""
import os

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))


def build_mln():
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import (
        DenseLayer, GravesLSTM, OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder()
            .seed(71).learning_rate(0.05).updater("adam")
            .weight_init("xavier")
            .list()
            .layer(DenseLayer(n_in=6, n_out=10, activation="tanh"))
            .layer(OutputLayer(n_in=10, n_out=3, loss="mcxent",
                               activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(71)
    x = rng.normal(size=(16, 6)).astype(np.float32)
    y = np.zeros((16, 3), np.float32)
    y[np.arange(16), rng.integers(0, 3, 16)] = 1
    for _ in range(3):  # non-trivial updater state
        net.fit(x, y)
    return net, x


def build_cg():
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.conf.vertices import MergeVertex
    from deeplearning4j_tpu.nn.graph_network import ComputationGraph

    conf = (NeuralNetConfiguration.builder()
            .seed(72).learning_rate(0.05).updater("rmsprop")
            .graph_builder()
            .add_inputs("a", "b")
            .add_layer("da", DenseLayer(n_in=4, n_out=6, activation="relu"),
                       "a")
            .add_layer("db", DenseLayer(n_in=3, n_out=6, activation="tanh"),
                       "b")
            .add_vertex("m", MergeVertex(), "da", "db")
            .add_layer("out", OutputLayer(n_in=12, n_out=2, loss="mcxent",
                                          activation="softmax"), "m")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(72)
    xa = rng.normal(size=(8, 4)).astype(np.float32)
    xb = rng.normal(size=(8, 3)).astype(np.float32)
    y = np.zeros((8, 2), np.float32)
    y[np.arange(8), rng.integers(0, 2, 8)] = 1
    for _ in range(3):
        net.fit([xa, xb], [y])
    return net, xa, xb


def build_lm():
    """Transformer + Switch-MoE blocks: the round-5 first-class layer types
    get the same forever-loadable guarantee as the original fixtures."""
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (
        EmbeddingLayer, RnnOutputLayer, TransformerBlock)
    from deeplearning4j_tpu.nn.conf.layers.moe import MoETransformerBlock
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    V, W, T = 8, 16, 6
    conf = (NeuralNetConfiguration.builder()
            .seed(73).learning_rate(0.01).updater("adam")
            .weight_init("xavier")
            .list()
            .layer(EmbeddingLayer(n_in=V, n_out=W))
            .layer(TransformerBlock(n_in=W, n_out=W, n_heads=2, causal=True))
            .layer(MoETransformerBlock(n_in=W, n_out=W, n_heads=2,
                                       n_experts=4, causal=True))
            .layer(RnnOutputLayer(n_in=W, n_out=V, loss="mcxent",
                                  activation="softmax"))
            .build())
    conf.layers[0].set_n_in(InputType.recurrent(V, T))
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(73)
    ids = rng.integers(0, V, size=(4, T + 1))
    eye = np.eye(V, dtype=np.float32)
    for _ in range(3):
        net.fit(eye[ids[:, :-1]], eye[ids[:, 1:]])
    return net, eye[ids[:, :-1]]


def main():
    from deeplearning4j_tpu.datasets.dataset import (
        DataSet, NormalizerStandardize)
    from deeplearning4j_tpu.utils.model_serializer import write_model

    net, x = build_mln()
    norm = NormalizerStandardize()
    ds = DataSet(x.copy(), np.zeros((len(x), 3), np.float32))
    norm.fit(ds)
    norm.transform(ds)
    write_model(net, os.path.join(HERE, "mln_golden.zip"), save_updater=True,
                normalizer=norm)
    out = np.asarray(net.output(ds.features))

    cg, xa, xb = build_cg()
    write_model(cg, os.path.join(HERE, "cg_golden.zip"), save_updater=True)
    cg_out = np.asarray(cg.output(xa, xb)[0])

    np.savez(os.path.join(HERE, "golden_expected.npz"),
             mln_in=x, mln_out=out,
             mln_updater_flat=np.asarray(
                 _flat(net.updater_state), np.float32),
             cg_in_a=xa, cg_in_b=xb, cg_out=cg_out,
             cg_updater_flat=np.asarray(_flat(cg.updater_state), np.float32))
    print("golden fixtures written to", HERE)


def main_lm():
    """Additive fixture (round 5): written to its OWN files so regenerating
    it can never silently rewrite the earlier committed expectations."""
    from deeplearning4j_tpu.utils.model_serializer import write_model

    lm, lm_x = build_lm()
    write_model(lm, os.path.join(HERE, "lm_golden.zip"), save_updater=True)
    lm_out = np.asarray(lm.output(lm_x))
    np.savez(os.path.join(HERE, "lm_golden_expected.npz"),
             lm_in=lm_x, lm_out=lm_out,
             lm_updater_flat=np.asarray(_flat(lm.updater_state), np.float32))
    print("lm golden fixture written to", HERE)


def _flat(tree):
    from deeplearning4j_tpu.utils.pytree import flatten_params
    return flatten_params(tree, None)


if __name__ == "__main__":
    import sys
    main_lm() if "--lm-only" in sys.argv else main()
