"""Regenerate tests/golden/xplane_golden.pb — the committed XSpace fixture.

The fixture is a synthetic but wire-format-faithful XSpace protobuf covering
every classification path the parser has: a device plane ("/device:TPU:0")
whose "XLA Ops" line holds one op per category (conv, dot, reduce-fusion,
compute-fusion, collective, datamovement) plus a control-flow `while`
wrapper the parser must skip and an "XLA Modules" container line it must
ignore; and a host plane whose "python" line carries PjitFunction spans
(per-fn share) and a profiler bookkeeping event that must be filtered.

Durations are picked so the category split is exact round percentages
(conv 40 / matmul 30 / fusion:reduce 20 / fusion:compute 5 / collective 3 /
datamovement 2 — summing to 100.0), which the parser unit tests assert
verbatim. Encoding uses observability/xplane.py's own encode_* helpers so
fixture and parser share one field layout.

Run from the repo root:  python tests/golden/make_xplane_golden.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from deeplearning4j_tpu.observability.xplane import (  # noqa: E402
    encode_field, encode_message, encode_varint)

_VARINT, _LEN = 0, 2

#: (HLO string, duration_ps) — device "XLA Ops" events. The while wrapper
#: spans everything and MUST be excluded from totals by the parser.
DEVICE_OPS = [
    ("%convolution.42 = f32[128,112,112,64]{3,2,1,0} convolution(%arg0, "
     "%arg1), window={size=7x7 stride=2x2}", 40_000_000),
    ("%dot.3 = f32[128,1000]{1,0} dot(%x, %y), "
     "lhs_contracting_dims={1}", 30_000_000),
    ("%convert_reduce_fusion.7 = f32[64]{0} fusion(%p0), kind=kInput, "
     "calls=%fused_computation.7", 20_000_000),
    ("%multiply_add_fusion.9 = f32[128]{0} fusion(%a, %b), kind=kLoop",
     5_000_000),
    ("%all-reduce.1 = f32[256]{0} all-reduce(%x), replica_groups={}",
     3_000_000),
    ("%copy.4 = f32[128]{0} copy(%x)", 2_000_000),
    ("%while.1 = (f32[]) while(%init), condition=%cond, body=%body",
     99_000_000),
]

#: host "python" line events: pjit spans feed fn_pct (70/30); the $profiler
#: bookkeeping event must be filtered from every total
HOST_EVENTS = [
    ("PjitFunction(multistep)", 70_000_000),
    ("PjitFunction(train_step)", 30_000_000),
    ("$profiler.py:91 start_trace", 4_400_000_000),
]


def _event(metadata_id: int, dur_ps: int) -> bytes:
    return encode_message(encode_field(1, _VARINT, metadata_id),
                          encode_field(3, _VARINT, dur_ps))


def _metadata_entry(eid: int, name: str) -> bytes:
    meta = encode_message(encode_field(1, _VARINT, eid),
                          encode_field(2, _LEN, name.encode()))
    return encode_message(encode_field(1, _VARINT, eid),
                          encode_field(2, _LEN, meta))


def _line(name: str, events: bytes) -> bytes:
    return encode_message(encode_field(2, _LEN, name.encode()), events)


def _plane(name: str, *parts: bytes) -> bytes:
    return encode_message(encode_field(2, _LEN, name.encode()), *parts)


def build() -> bytes:
    # device plane: metadata ids 1..N for the ops, one "XLA Ops" line with
    # an event per op, and an "XLA Modules" container line (same wall span)
    # the parser must NOT double-count
    dev_meta = b"".join(
        encode_field(4, _LEN, _metadata_entry(i + 1, nm))
        for i, (nm, _) in enumerate(DEVICE_OPS))
    op_events = b"".join(
        encode_field(4, _LEN, _event(i + 1, dur))
        for i, (_, dur) in enumerate(DEVICE_OPS))
    module_meta = encode_field(
        4, _LEN, _metadata_entry(100, "SyncTensorsGraph.1234"))
    module_event = encode_field(4, _LEN, _event(100, 199_000_000))
    device = _plane(
        "/device:TPU:0", dev_meta, module_meta,
        encode_field(3, _LEN, _line("XLA Ops", op_events)),
        encode_field(3, _LEN, _line("XLA Modules", module_event)))

    host_meta = b"".join(
        encode_field(4, _LEN, _metadata_entry(i + 1, nm))
        for i, (nm, _) in enumerate(HOST_EVENTS))
    host_events = b"".join(
        encode_field(4, _LEN, _event(i + 1, dur))
        for i, (_, dur) in enumerate(HOST_EVENTS))
    host = _plane("/host:CPU", host_meta,
                  encode_field(3, _LEN, _line("python", host_events)))

    return encode_message(encode_field(1, _LEN, device),
                          encode_field(1, _LEN, host))


def main() -> None:
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "xplane_golden.pb")
    data = build()
    with open(out, "wb") as f:
        f.write(data)
    print(f"wrote {out} ({len(data)} bytes)")
    # self-check: parse what we just wrote
    from deeplearning4j_tpu.observability.xplane import summarize
    import json
    print(json.dumps(summarize(out), indent=1))
    assert encode_varint(0) == b"\x00"  # tiny encoder sanity


if __name__ == "__main__":
    main()
