"""Warm-start compile plane: the ISSUE-15 acceptance set.

Contracts pinned here:
- cache-hit executables are BITWISE identical to fresh compiles, for the
  donated train step, the serving predict program, and the decode engine's
  per-bucket step (deserialize_and_load must change nothing about math);
- torn / truncated / version-mismatched entries are quarantined and fall
  back to a normal compile — never an error, always a correct result, and
  the flight recorder keeps the trail;
- entries written by one process warm-start another (the elastic-respawn
  and replica-spawn payoff);
- ModelRegistry warmup builds every micro-batch bucket program
  (log2(max_batch)+1 of them) BEFORE the active pointer moves, and serving
  those bucket sizes afterwards compiles nothing new;
- the ``DL4J_COMPILE_CACHE=0`` kill switch restores the exact plain
  ``tracker.wrap(jax.jit(...))`` path: no disk entries, no CachedProgram;
- the store itself prunes oldest-first to its byte bound.

The autouse conftest fixture points ``DL4J_COMPILE_CACHE_DIR`` at a
per-test tmp dir, so every test starts cold and cross-test poisoning is
impossible.
"""
import glob
import os
import subprocess
import sys
import textwrap

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.keras_server import ModelRegistry
from deeplearning4j_tpu.keras_server.decode import (
    DECODE_PROGRAM_NAME, DecodeEngine,
)
from deeplearning4j_tpu.models.char_rnn import char_rnn_lstm
from deeplearning4j_tpu.nn import compile_cache as cc
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.inference import PREDICT_PROGRAM_NAME
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.observability.compile_tracker import global_tracker
from deeplearning4j_tpu.observability.flight_recorder import global_recorder

N_IN, N_OUT = 12, 3
V = 24


def _mlp(seed=3):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(0.1).updater("adam")
            .weight_init("xavier")
            .list()
            .layer(DenseLayer(n_in=N_IN, n_out=16, activation="relu"))
            .layer(OutputLayer(n_in=16, n_out=N_OUT, loss="mcxent",
                               activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def _xy(n=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, N_IN)).astype(np.float32)
    y = np.eye(N_OUT, dtype=np.float32)[rng.integers(0, N_OUT, size=n)]
    return x, y


def _cache_files():
    return sorted(glob.glob(os.path.join(
        os.environ["DL4J_COMPILE_CACHE_DIR"], "*.xc")))


def _events_since(n0):
    return global_tracker().snapshot_events()[n0:]


def _n_events():
    return len(global_tracker().snapshot_events())


# ------------------------------------------------------- bitwise identity
def test_train_and_predict_cache_hit_bitwise_equal(monkeypatch):
    """A net resolved entirely from disk entries trains and predicts
    bit-for-bit like both the cold (populating) run and the kill-switch
    plain-jit run."""
    x, y = _xy()
    xq, _ = _xy(n=5, seed=9)

    monkeypatch.setenv("DL4J_COMPILE_CACHE", "0")
    ref = _mlp()
    ref.fit(x, y, epochs=3)
    ref_out = np.asarray(ref.output(xq))
    assert _cache_files() == []

    monkeypatch.setenv("DL4J_COMPILE_CACHE", "1")
    cold = _mlp()
    cold.fit(x, y, epochs=3)
    cold_out = np.asarray(cold.output(xq))
    assert _cache_files(), "cold run must persist executables"

    n0 = _n_events()
    warm = _mlp()
    warm.fit(x, y, epochs=3)
    warm_out = np.asarray(warm.output(xq))
    ev = _events_since(n0)
    assert ev and all(e.get("cache_hit") for e in ev), \
        f"identical net must resolve every program from disk: {ev}"

    np.testing.assert_array_equal(np.asarray(warm.params()),
                                  np.asarray(cold.params()))
    np.testing.assert_array_equal(np.asarray(warm.params()),
                                  np.asarray(ref.params()))
    np.testing.assert_array_equal(warm_out, cold_out)
    np.testing.assert_array_equal(warm_out, ref_out)


def test_decode_bucket_cache_hit_bitwise_equal(monkeypatch):
    """Greedy decode through deserialized per-bucket step executables
    emits the same token streams as the plain-jit engine."""
    rng = np.random.default_rng(4)
    prompts = [list(map(int, rng.integers(0, V, size=3))) for _ in range(6)]
    budgets = [4, 5, 6, 4, 5, 6]

    def run():
        net = MultiLayerNetwork(
            char_rnn_lstm(vocab_size=V, hidden=16, seed=11)).init()
        eng = DecodeEngine(net, min_slots=2, max_slots=4)
        try:
            sessions = [eng.submit(p, b) for p, b in zip(prompts, budgets)]
            return [s.result(timeout=300) for s in sessions]
        finally:
            eng.close()

    monkeypatch.setenv("DL4J_COMPILE_CACHE", "0")
    ref = run()
    monkeypatch.setenv("DL4J_COMPILE_CACHE", "1")
    cold = run()          # populates the store
    n0 = _n_events()
    warm = run()          # resolves every bucket step from disk
    decode_ev = [e for e in _events_since(n0)
                 if DECODE_PROGRAM_NAME in e.get("fn", "")]
    assert decode_ev and all(e.get("cache_hit") for e in decode_ev)
    assert warm == cold == ref


# ------------------------------------------------------ corruption = miss
@pytest.mark.parametrize("corrupt", ["truncate", "bad-magic", "bit-flip"])
def test_corrupt_entry_falls_back_to_fresh_compile(corrupt):
    xq, _ = _xy(n=4, seed=2)
    good = np.asarray(_mlp().output(xq))
    files = _cache_files()
    assert files
    for path in files:
        raw = open(path, "rb").read()
        if corrupt == "truncate":
            raw = raw[:10]
        elif corrupt == "bad-magic":
            raw = b"NOTDL4J!" + raw[8:]
        else:
            raw = raw[:-1] + bytes([raw[-1] ^ 0xFF])
        open(path, "wb").write(raw)

    n0, r0 = _n_events(), len(global_recorder().snapshot())
    out = np.asarray(_mlp().output(xq))
    np.testing.assert_array_equal(out, good)
    ev = [e for e in _events_since(n0)
          if "output" in e.get("fn", "")]
    assert ev and not any(e.get("cache_hit") for e in ev), \
        "corrupt entries must read as misses, not hits"
    falls = [e for e in global_recorder().snapshot()[r0:]
             if e.get("kind") == "compile_cache_fallback"]
    assert falls, "quarantine must leave a flight-recorder trail"
    # the quarantined bytes are gone: the fresh compile re-persisted a
    # valid entry (magic + digest check out) at the same fingerprint
    import hashlib
    for path in files:
        raw = open(path, "rb").read()
        assert raw.startswith(cc.MAGIC)
        body = raw[len(cc.MAGIC) + 32:]
        assert hashlib.sha256(body).digest() == raw[len(cc.MAGIC):
                                                    len(cc.MAGIC) + 32]


# ------------------------------------------------------- cross-process
def test_cross_process_reuse(tmp_path):
    """An entry serialized by a child process warm-starts this one — the
    mechanism behind elastic respawn and replica-spawn warm recovery."""
    out_npy = str(tmp_path / "child_out.npy")
    child = textwrap.dedent(f"""
        import numpy as np
        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        conf = (NeuralNetConfiguration.builder()
                .seed(3).learning_rate(0.1).updater("adam")
                .weight_init("xavier")
                .list()
                .layer(DenseLayer(n_in={N_IN}, n_out=16, activation="relu"))
                .layer(OutputLayer(n_in=16, n_out={N_OUT}, loss="mcxent",
                                   activation="softmax"))
                .build())
        net = MultiLayerNetwork(conf).init()
        out = np.asarray(net.output(np.zeros((4, {N_IN}), np.float32)))
        np.save({out_npy!r}, out)
    """)
    # the child inherits JAX_PLATFORMS / XLA_FLAGS / DL4J_COMPILE_CACHE_DIR
    # from this process, so its backend key matches ours
    subprocess.run([sys.executable, "-c", child], check=True, timeout=300)
    assert _cache_files(), "child must have persisted its executable"

    n0 = _n_events()
    mine = np.asarray(_mlp().output(np.zeros((4, N_IN), np.float32)))
    ev = [e for e in _events_since(n0) if "output" in e.get("fn", "")]
    assert ev and all(e.get("cache_hit") for e in ev), \
        "parent must load the child's entry instead of compiling"
    np.testing.assert_array_equal(mine, np.load(out_npy))


# ---------------------------------------------------------------- warmup
def test_registry_warmup_builds_all_buckets_before_swap(monkeypatch):
    assert ModelRegistry.warmup_buckets(8) == [1, 2, 4, 8]
    assert ModelRegistry.warmup_buckets(6) == [1, 2, 4, 6]

    reg = ModelRegistry(warmup_max_batch=8)
    seen = {}
    orig = ModelRegistry._warmup

    def spy(self, pf, net, example=None):
        seen["active_at_warmup"] = self._active.get("m")
        n0 = _n_events()
        orig(self, pf, net, example)
        seen["events"] = [e for e in _events_since(n0)
                          if PREDICT_PROGRAM_NAME in e.get("fn", "")]

    monkeypatch.setattr(ModelRegistry, "_warmup", spy)

    reg.register("m", _mlp())
    assert seen["active_at_warmup"] is None, \
        "v1 warmup must run before the pointer first moves"
    assert len(seen["events"]) == 4, \
        "warmup must build exactly log2(max_batch)+1 bucket programs"

    reg.register("m", _mlp())
    assert seen["active_at_warmup"] == "v1", \
        "v2 warmup must run while v1 still serves"
    assert len(seen["events"]) == 4
    assert all(e.get("cache_hit") for e in seen["events"]), \
        "hot swap of a structurally identical model must warm-hit v1's " \
        "entries (fingerprints ignore the @version decoration)"
    assert reg.active("m").version == "v2"

    # every bucket the micro-batcher can form is already resident
    n0 = _n_events()
    pf = reg.active("m").predict_fn
    for b in (1, 2, 4, 8):
        pf(np.zeros((b, N_IN), np.float32))
    assert [e for e in _events_since(n0)
            if PREDICT_PROGRAM_NAME in e.get("fn", "")] == []


def test_warmup_skipped_when_example_underivable():
    """Recurrent first layers have no (1, n_in) shape to derive — warmup
    degrades to a no-op instead of guessing wrong."""
    net = MultiLayerNetwork(
        char_rnn_lstm(vocab_size=V, hidden=16, seed=1)).init()
    reg = ModelRegistry(warmup_max_batch=4)
    n0 = _n_events()
    reg.register("rnn", net)
    assert [e for e in _events_since(n0)
            if PREDICT_PROGRAM_NAME in e.get("fn", "")] == []


# ------------------------------------------------------------ kill switch
def test_kill_switch_restores_plain_path(monkeypatch):
    monkeypatch.setenv("DL4J_COMPILE_CACHE", "0")
    prog = cc.build_program("t", jax.jit(lambda a: a + 1))
    assert not isinstance(prog, cc.CachedProgram)

    x, y = _xy()
    net = _mlp()
    n0 = _n_events()
    net.fit(x, y, epochs=1)
    net.output(x)
    ev = _events_since(n0)
    assert ev and not any(e.get("cache_hit") for e in ev)
    assert _cache_files() == [], "kill switch must never touch disk"


# ------------------------------------------------------------- the store
def test_store_prunes_oldest_to_byte_bound(tmp_path):
    store = cc.CompileCache(str(tmp_path / "s"), max_bytes=4096)
    for i in range(6):
        store.put(f"{i:064x}", os.urandom(1024), None, None, {"i": i})
        os.utime(store.entry_path(f"{i:064x}"), (1000 + i, 1000 + i))
    store._prune()
    left = sorted(glob.glob(os.path.join(str(tmp_path / "s"), "*.xc")))
    total = sum(os.path.getsize(p) for p in left)
    assert total <= 4096
    assert store.entry_path(f"{5:064x}") in left, \
        "prune must evict oldest-mtime first"
    assert store.entry_path(f"{0:064x}") not in left


def test_epoch_env_salts_fingerprint(monkeypatch):
    prog = cc.CachedProgram("t", jax.jit(lambda a: a + 1))
    sig = (("f32[2]",), ())
    a = prog._fp_hex(sig)
    monkeypatch.setenv("DL4J_COMPILE_CACHE_EPOCH", "2")
    b = prog._fp_hex(sig)
    assert a != b, "EPOCH must invalidate without deleting files"
