"""DataVec bridge (record readers + DataSet iterators) and dataset fetchers."""
import numpy as np

from deeplearning4j_tpu.datavec import (
    CollectionRecordReader, CSVRecordReader, CSVSequenceRecordReader,
    ImageRecordReader, RecordReaderDataSetIterator,
    RecordReaderMultiDataSetIterator, SequenceRecordReaderDataSetIterator,
)
from deeplearning4j_tpu.datasets.fetchers import (
    CifarDataSetIterator, CurvesDataSetIterator, LFWDataSetIterator,
)


def test_csv_record_reader_numeric_fast_path(tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("1,2,3\n4,5,6\n7,8,9\n10,11,12\n")
    recs = list(CSVRecordReader(p))
    assert recs == [[1, 2, 3], [4, 5, 6], [7, 8, 9], [10, 11, 12]]


def test_csv_record_reader_mixed_fields(tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("a,1,2\nb,3,4\n")
    recs = list(CSVRecordReader(p))
    assert recs == [["a", 1.0, 2.0], ["b", 3.0, 4.0]]


def test_record_reader_dataset_iterator_classification(tmp_path):
    p = tmp_path / "iris-like.csv"
    rows = ["%f,%f,%d" % (i * 0.1, i * 0.2, i % 3) for i in range(10)]
    p.write_text("\n".join(rows) + "\n")
    it = RecordReaderDataSetIterator(CSVRecordReader(p), batch=4,
                                     label_index=2, num_classes=3)
    batches = list(it)
    assert [b.num_examples() for b in batches] == [4, 4, 2]
    b0 = batches[0]
    assert b0.features.shape == (4, 2) and b0.labels.shape == (4, 3)
    np.testing.assert_array_equal(np.argmax(b0.labels, 1), [0, 1, 2, 0])


def test_record_reader_dataset_iterator_regression():
    recs = [[1.0, 2.0, 3.0, 4.0]] * 6
    it = RecordReaderDataSetIterator(CollectionRecordReader(recs), batch=3,
                                     label_index=2, label_index_to=3,
                                     regression=True)
    b = next(iter(it))
    assert b.features.shape == (3, 2) and b.labels.shape == (3, 2)
    np.testing.assert_allclose(b.labels[0], [3.0, 4.0])


def test_sequence_record_reader_iterator(tmp_path):
    fdir, ldir = tmp_path / "f", tmp_path / "l"
    fdir.mkdir(), ldir.mkdir()
    lengths = [3, 5, 2]
    for i, L in enumerate(lengths):
        (fdir / f"{i}.csv").write_text(
            "\n".join(f"{t},{t * 2}" for t in range(L)) + "\n")
        (ldir / f"{i}.csv").write_text(
            "\n".join(str(t % 2) for t in range(L)) + "\n")
    it = SequenceRecordReaderDataSetIterator(
        CSVSequenceRecordReader(fdir), batch=3,
        labels=CSVSequenceRecordReader(ldir), num_classes=2)
    ds = next(iter(it))
    assert ds.features.shape == (3, 5, 2)
    assert ds.labels.shape == (3, 5, 2)
    np.testing.assert_array_equal(ds.features_mask.sum(axis=1), lengths)
    # padded steps are zero
    assert ds.features[2, 2:].sum() == 0


def test_multi_dataset_iterator():
    recs = [[i, i + 1, i % 2] for i in range(8)]
    it = (RecordReaderMultiDataSetIterator(batch=4)
          .add_reader("r", CollectionRecordReader(recs))
          .add_input("r", 0, 1)
          .add_output_one_hot("r", 2, 2))
    ins, outs = next(iter(it))
    assert ins[0].shape == (4, 2) and outs[0].shape == (4, 2)
    np.testing.assert_array_equal(np.argmax(outs[0], 1), [0, 1, 0, 1])


def test_image_record_reader(tmp_path):
    from PIL import Image
    for person, color in [("alice", 200), ("bob", 50)]:
        d = tmp_path / person
        d.mkdir()
        for i in range(2):
            Image.fromarray(
                np.full((10, 8, 3), color, np.uint8)).save(d / f"{i}.png")
    rr = ImageRecordReader(tmp_path, height=4, width=4, channels=1)
    assert rr.labels == ["alice", "bob"]
    recs = list(rr)
    assert len(recs) == 4 and len(recs[0]) == 17  # 4*4 pixels + label
    assert recs[0][-1] == 0.0 and recs[-1][-1] == 1.0


def test_cifar_iterator_shapes():
    it = CifarDataSetIterator(batch=8, num_examples=32)
    ds = next(iter(it))
    assert ds.features.shape == (8, 32, 32, 3)
    assert ds.labels.shape == (8, 10)
    assert 0.0 <= ds.features.min() and ds.features.max() <= 1.0


def test_lfw_iterator_shapes():
    it = LFWDataSetIterator(batch=10, num_examples=40, num_labels=5,
                            image_size=16)
    ds = next(iter(it))
    assert ds.features.shape == (10, 16, 16, 1)
    assert ds.labels.shape == (10, 5)


def test_curves_iterator_autoencoder_labels():
    it = CurvesDataSetIterator(batch=16, num_examples=32)
    ds = next(iter(it))
    np.testing.assert_array_equal(ds.features, ds.labels)
    assert ds.features.shape == (16, 784)


def test_cifar_flatten_layout_consistent(tmp_path, monkeypatch):
    """flatten=True must yield HWC pixel order from BOTH sources (advisor
    round-1 finding: real CIFAR flattened channel-major, synthetic HWC)."""
    from deeplearning4j_tpu.datasets import fetchers

    # fake real CIFAR binary: label + R/G/B planes; pixel (0,0) = (10,20,30)
    rec = np.zeros(3073, np.uint8)
    rec[0] = 3
    rec[1] = 10
    rec[1 + 1024] = 20
    rec[1 + 2048] = 30
    (tmp_path / "data_batch_1.bin").write_bytes(np.tile(rec, 4).tobytes())
    monkeypatch.setattr(fetchers, "_CIFAR_DIRS", [str(tmp_path)])

    flat = next(iter(CifarDataSetIterator(batch=4, shuffle=False,
                                          flatten=True)))
    img = next(iter(CifarDataSetIterator(batch=4, shuffle=False)))
    np.testing.assert_allclose(np.asarray(flat.features),
                               np.asarray(img.features).reshape(4, -1))
    # first 3 flattened values are pixel (0,0)'s RGB — HWC, not a CHW plane
    np.testing.assert_allclose(np.asarray(flat.features)[0, :3],
                               np.array([10, 20, 30]) / 255.0, atol=1e-6)

    # synthetic source obeys the same contract
    monkeypatch.setattr(fetchers, "_CIFAR_DIRS", [])
    flat_s = next(iter(CifarDataSetIterator(batch=4, shuffle=False,
                                            flatten=True, num_examples=4)))
    img_s = next(iter(CifarDataSetIterator(batch=4, shuffle=False,
                                           num_examples=4)))
    np.testing.assert_allclose(np.asarray(flat_s.features),
                               np.asarray(img_s.features).reshape(4, -1))


def test_csv_strict_single_pass_validation(tmp_path):
    """The numeric fast path validates while parsing in ONE native pass
    (advisor round-1 finding: no more float() pre-pass over the whole file).
    A single non-numeric field routes the file to the general reader."""
    from deeplearning4j_tpu.datavec.records import CSVRecordReader

    ok = tmp_path / "ok.csv"
    ok.write_text("1.5,2,3\n4,5e-1,6\n")
    rows = list(CSVRecordReader(ok).records())
    assert rows == [[1.5, 2.0, 3.0], [4.0, 0.5, 6.0]]

    bad = tmp_path / "bad.csv"
    bad.write_text("1,2,3\n4,NA,6\n")
    rows = list(CSVRecordReader(bad).records())
    assert rows[0] == [1.0, 2.0, 3.0]
    assert rows[1] == [4.0, "NA", 6.0]  # preserved, not coerced to 0

    empty_field = tmp_path / "empty.csv"
    empty_field.write_text("1,2\n3,\n")
    rows = list(CSVRecordReader(empty_field).records())
    assert rows[1][1] == ""  # empty field survives via the general reader
