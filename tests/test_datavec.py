"""DataVec bridge (record readers + DataSet iterators) and dataset fetchers."""
import numpy as np

from deeplearning4j_tpu.datavec import (
    CollectionRecordReader, CSVRecordReader, CSVSequenceRecordReader,
    ImageRecordReader, RecordReaderDataSetIterator,
    RecordReaderMultiDataSetIterator, SequenceRecordReaderDataSetIterator,
)
from deeplearning4j_tpu.datasets.fetchers import (
    CifarDataSetIterator, CurvesDataSetIterator, LFWDataSetIterator,
)


def test_csv_record_reader_numeric_fast_path(tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("1,2,3\n4,5,6\n7,8,9\n10,11,12\n")
    recs = list(CSVRecordReader(p))
    assert recs == [[1, 2, 3], [4, 5, 6], [7, 8, 9], [10, 11, 12]]


def test_csv_record_reader_mixed_fields(tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("a,1,2\nb,3,4\n")
    recs = list(CSVRecordReader(p))
    assert recs == [["a", 1.0, 2.0], ["b", 3.0, 4.0]]


def test_record_reader_dataset_iterator_classification(tmp_path):
    p = tmp_path / "iris-like.csv"
    rows = ["%f,%f,%d" % (i * 0.1, i * 0.2, i % 3) for i in range(10)]
    p.write_text("\n".join(rows) + "\n")
    it = RecordReaderDataSetIterator(CSVRecordReader(p), batch=4,
                                     label_index=2, num_classes=3)
    batches = list(it)
    assert [b.num_examples() for b in batches] == [4, 4, 2]
    b0 = batches[0]
    assert b0.features.shape == (4, 2) and b0.labels.shape == (4, 3)
    np.testing.assert_array_equal(np.argmax(b0.labels, 1), [0, 1, 2, 0])


def test_record_reader_dataset_iterator_regression():
    recs = [[1.0, 2.0, 3.0, 4.0]] * 6
    it = RecordReaderDataSetIterator(CollectionRecordReader(recs), batch=3,
                                     label_index=2, label_index_to=3,
                                     regression=True)
    b = next(iter(it))
    assert b.features.shape == (3, 2) and b.labels.shape == (3, 2)
    np.testing.assert_allclose(b.labels[0], [3.0, 4.0])


def test_sequence_record_reader_iterator(tmp_path):
    fdir, ldir = tmp_path / "f", tmp_path / "l"
    fdir.mkdir(), ldir.mkdir()
    lengths = [3, 5, 2]
    for i, L in enumerate(lengths):
        (fdir / f"{i}.csv").write_text(
            "\n".join(f"{t},{t * 2}" for t in range(L)) + "\n")
        (ldir / f"{i}.csv").write_text(
            "\n".join(str(t % 2) for t in range(L)) + "\n")
    it = SequenceRecordReaderDataSetIterator(
        CSVSequenceRecordReader(fdir), batch=3,
        labels=CSVSequenceRecordReader(ldir), num_classes=2)
    ds = next(iter(it))
    assert ds.features.shape == (3, 5, 2)
    assert ds.labels.shape == (3, 5, 2)
    np.testing.assert_array_equal(ds.features_mask.sum(axis=1), lengths)
    # padded steps are zero
    assert ds.features[2, 2:].sum() == 0


def test_multi_dataset_iterator():
    recs = [[i, i + 1, i % 2] for i in range(8)]
    it = (RecordReaderMultiDataSetIterator(batch=4)
          .add_reader("r", CollectionRecordReader(recs))
          .add_input("r", 0, 1)
          .add_output_one_hot("r", 2, 2))
    ins, outs = next(iter(it))
    assert ins[0].shape == (4, 2) and outs[0].shape == (4, 2)
    np.testing.assert_array_equal(np.argmax(outs[0], 1), [0, 1, 0, 1])


def test_image_record_reader(tmp_path):
    from PIL import Image
    for person, color in [("alice", 200), ("bob", 50)]:
        d = tmp_path / person
        d.mkdir()
        for i in range(2):
            Image.fromarray(
                np.full((10, 8, 3), color, np.uint8)).save(d / f"{i}.png")
    rr = ImageRecordReader(tmp_path, height=4, width=4, channels=1)
    assert rr.labels == ["alice", "bob"]
    recs = list(rr)
    assert len(recs) == 4 and len(recs[0]) == 17  # 4*4 pixels + label
    assert recs[0][-1] == 0.0 and recs[-1][-1] == 1.0


def test_cifar_iterator_shapes():
    it = CifarDataSetIterator(batch=8, num_examples=32)
    ds = next(iter(it))
    assert ds.features.shape == (8, 32, 32, 3)
    assert ds.labels.shape == (8, 10)
    assert 0.0 <= ds.features.min() and ds.features.max() <= 1.0


def test_lfw_iterator_shapes():
    it = LFWDataSetIterator(batch=10, num_examples=40, num_labels=5,
                            image_size=16)
    ds = next(iter(it))
    assert ds.features.shape == (10, 16, 16, 1)
    assert ds.labels.shape == (10, 5)


def test_curves_iterator_autoencoder_labels():
    it = CurvesDataSetIterator(batch=16, num_examples=32)
    ds = next(iter(it))
    np.testing.assert_array_equal(ds.features, ds.labels)
    assert ds.features.shape == (16, 784)
