"""Zero-copy host data plane tests (ISSUE 14): scatter-gather wire codec
fuzz roundtrips, shm segment reaper under SIGKILL chaos (zero orphans),
seqlock ring integrity, ShmTransport negotiate/fallback, shard-segment
shipping, native ingest decode parity, and the three-transport
(inproc/tcp/shm) bitwise fit parity pin."""
import os
import signal
import socket
import struct
import subprocess
import sys

import numpy as np
import pytest

from deeplearning4j_tpu import nativert
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import ps_transport as pst
from deeplearning4j_tpu.parallel.param_server import (
    ParameterServer, ParameterServerParallelWrapper,
)
from deeplearning4j_tpu.streaming import wire
from deeplearning4j_tpu.streaming.broker import (
    BrokerIngestSource, BrokerProducer, LoopbackBroker, ReconnectingConsumer,
)

SHM_DIR = "/dev/shm"

needs_shm = pytest.mark.skipif(not os.path.isdir(SHM_DIR),
                               reason="no /dev/shm on this host")
needs_native = pytest.mark.skipif(not nativert.native_available(),
                                  reason="native runtime unavailable")


def _shm_names():
    try:
        return {n for n in os.listdir(SHM_DIR)
                if n.startswith(pst._SHM_PREFIX)}
    except OSError:
        return set()


# ------------------------------------------------------------- wire codec

_FUZZ_DTYPES = (np.float32, np.float64, np.int32, np.int64, np.uint8)


def _random_arrays(rng, n_arrays):
    out = {}
    for i in range(n_arrays):
        dt = _FUZZ_DTYPES[int(rng.integers(len(_FUZZ_DTYPES)))]
        ndim = int(rng.integers(0, 4))
        # odd/prime extents and occasional zero-length axes on purpose
        shape = tuple(int(rng.integers(0, 8)) for _ in range(ndim))
        if np.dtype(dt).kind == "f":
            a = rng.normal(size=shape).astype(dt)
        else:
            a = rng.integers(0, 200, size=shape).astype(dt)
        out[f"a{i}"] = a
    return out


@pytest.mark.parametrize("codec", ["none", "bf16"])
def test_wire_fuzz_roundtrip_over_socketpair(codec):
    """Random multi-tensor frames (mixed dtypes, empty and odd-length
    shapes) survive pack -> sendmsg scatter-gather -> recv_into -> unpack.
    codec none is bitwise; bf16 widens back exactly (bf16 -> f32 is exact)
    after the documented precision haircut."""
    rng = np.random.default_rng(1234)
    left, right = socket.socketpair()
    try:
        for _ in range(25):
            arrays = _random_arrays(rng, int(rng.integers(1, 5)))
            metas, views = wire.pack_arrays(arrays, codec)
            wire.send_frame(left, {"op": "t", "arrays": metas}, views)
            header, payload = wire.recv_frame(right)
            got = wire.unpack_arrays(header["arrays"], payload)
            assert set(got) == set(arrays)
            for k, a in arrays.items():
                assert got[k].shape == a.shape
                if codec == "bf16" and a.dtype.kind == "f":
                    # the decoded array is the bf16 quantization of a,
                    # widened: re-quantizing a must reproduce it exactly
                    import ml_dtypes
                    expect = np.asarray(a, ml_dtypes.bfloat16).astype(a.dtype)
                    np.testing.assert_array_equal(got[k], expect)
                else:
                    assert got[k].dtype == a.dtype
                    np.testing.assert_array_equal(got[k], a)
    finally:
        left.close()
        right.close()


def test_wire_reusable_buffer_roundtrip():
    left, right = socket.socketpair()
    rbuf = bytearray()
    try:
        for i in range(4):
            a = {"x": np.full((3, 5), float(i), np.float32)}
            metas, views = wire.pack_arrays(a)
            wire.send_frame(left, {"arrays": metas}, views)
            header, payload = wire.recv_frame(right, rbuf)
            got = wire.unpack_arrays(header["arrays"], payload)
            np.testing.assert_array_equal(got["x"], a["x"])
            del got, payload  # release the views so the buffer can be reused
    finally:
        left.close()
        right.close()


def test_wire_truncated_stream_raises():
    """A peer dying mid-frame raises ConnectionError, never returns a short
    read as a frame."""
    # case 1: prefix promises more payload than ever arrives
    left, right = socket.socketpair()
    try:
        hdr = b'{"op":"t"}'
        left.sendall(struct.pack("!II", len(hdr), 64) + hdr + b"\x00" * 10)
        left.close()
        with pytest.raises(ConnectionError):
            wire.recv_frame(right)
    finally:
        right.close()
    # case 2: cut inside the header
    left, right = socket.socketpair()
    try:
        left.sendall(struct.pack("!II", 100, 0) + b'{"op"')
        left.close()
        with pytest.raises(ConnectionError):
            wire.recv_frame(right)
    finally:
        right.close()


def test_wire_unknown_codec_rejected():
    with pytest.raises(ValueError):
        wire.encode_array(np.zeros(3, np.float32), "zstd")


# --------------------------------------------------------- seqlock ring

@needs_shm
def test_shm_ring_roundtrip_and_slot_alternation():
    seg = pst.create_segment(pst.ShmRing.segment_size(64), "ringtest")
    try:
        ring = pst.ShmRing(seg, 64)
        reader = pst.ShmRing(pst.attach_segment(seg.name), 64)
        for i in range(5):
            payload = bytes(range(i, i + 10))
            slot, seq = ring.write(memoryview(payload), version=i)
            assert slot == i % 2  # double buffer alternates
            version, view = reader.read(slot, seq)
            assert version == i
            assert bytes(view) == payload
            del view
        pst.release_segment(reader.shm)
    finally:
        pst.release_segment(seg, unlink=True)


@needs_shm
def test_shm_ring_detects_stale_and_torn_slots():
    seg = pst.create_segment(pst.ShmRing.segment_size(32), "ringtorn")
    try:
        ring = pst.ShmRing(seg, 32)
        slot, seq = ring.write(b"abc", version=1)
        # stale: the control message promised a seq the slot no longer has
        with pytest.raises(ConnectionError):
            ring.read(slot, seq + 2)
        # torn: an odd seq means the writer died mid-write
        pst.ShmRing.SLOT_HDR.pack_into(seg.buf, 0, seq + 1, 1, 3)
        with pytest.raises(ConnectionError, match="torn"):
            ring.read(slot, seq + 1)
        # overflow refuses, never scribbles past the slot
        with pytest.raises(ValueError, match="overflow"):
            ring.write(b"x" * 33, version=2)
    finally:
        pst.release_segment(seg, unlink=True)


# ------------------------------------------------------ reaper + shipping

@needs_shm
def test_shard_segment_roundtrip_owns_data():
    arrays = {"x": np.arange(24, dtype=np.float32).reshape(4, 6),
              "y": np.eye(3, dtype=np.float32)}
    name = pst.write_shard_segment(arrays, kind="t")
    assert name in _shm_names()
    got = pst.read_shard_segment(name)
    assert pst.release_segment_by_name(name)
    assert name not in _shm_names()
    for k in arrays:  # the decoded arrays outlive the unlinked segment
        np.testing.assert_array_equal(got[k], arrays[k])


@needs_shm
def test_reaper_skips_live_owner():
    seg = pst.create_segment(128, "alive")
    try:
        assert pst.reap_orphans() >= 0
        assert seg.name in _shm_names()  # own pid is alive: not garbage
    finally:
        pst.release_segment(seg, unlink=True)


@needs_shm
def test_reaper_collects_sigkilled_creators_segments():
    """SIGKILL chaos: a process that created segments and died without
    atexit (and whose resource tracker died with the group, simulated by
    unregistering) leaves orphans in /dev/shm — reap_orphans() sweeps every
    one of them."""
    child_src = (
        "import os, signal, sys\n"
        "from multiprocessing import resource_tracker\n"
        "from deeplearning4j_tpu.parallel import ps_transport as pst\n"
        "names = []\n"
        "for i in range(3):\n"
        "    seg = pst.create_segment(256, f'chaos{i}')\n"
        "    resource_tracker.unregister(\n"
        "        getattr(seg, '_name', '/' + seg.name), 'shared_memory')\n"
        "    names.append(seg.name)\n"
        "print('\\n'.join(names), flush=True)\n"
        "os.kill(os.getpid(), signal.SIGKILL)\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    proc = subprocess.run([sys.executable, "-c", child_src],
                          capture_output=True, text=True, timeout=120,
                          env=env)
    assert proc.returncode == -signal.SIGKILL
    names = [n for n in proc.stdout.splitlines() if n.strip()]
    assert len(names) == 3, proc.stderr
    live = _shm_names()
    assert all(n in live for n in names), "fixture broke: no orphans to reap"
    assert pst.reap_orphans() >= 3
    left = _shm_names()
    assert not any(n in left for n in names)


# ----------------------------------------------------------- shm transport

@needs_shm
def test_shm_transport_negotiates_and_matches_inproc():
    init = np.zeros(16, np.float32)
    srv = ParameterServer([init.copy()])
    ref = ParameterServer([init.copy()])
    frontend = pst.ParameterServerTcpFrontend(srv).start()
    t = pst.ShmTransport(("127.0.0.1", frontend.port))
    inproc = pst.InprocTransport(ref)
    try:
        v0, vec0 = t.pull()
        assert t.shm_active is True
        rv0, rvec0 = inproc.pull()
        assert (v0, rv0) == (0, 0)
        np.testing.assert_array_equal(vec0, rvec0)
        rng = np.random.default_rng(7)
        for i in range(6):
            delta = rng.normal(size=16).astype(np.float32)
            a = t.push(delta, base_version=i)
            b = inproc.push(delta, base_version=i)
            assert (a.accepted, a.version, a.staleness, a.weight) == \
                   (b.accepted, b.version, b.staleness, b.weight)
            np.testing.assert_array_equal(a.params, b.params)
        seg_names = {t._push_ring.shm.name, t._pull_ring.shm.name}
        assert seg_names <= _shm_names()
    finally:
        t.close()
        frontend.stop()
    # frontend.stop() unlinks the session rings: nothing left behind
    assert not (seg_names & _shm_names())


@needs_shm
def test_shm_transport_falls_back_to_tcp_when_attach_fails(monkeypatch):
    """A peer that can't map the segments (cross-host) degrades permanently
    to the inherited TCP frames with identical results."""
    srv = ParameterServer([np.zeros(8, np.float32)])
    frontend = pst.ParameterServerTcpFrontend(srv).start()
    monkeypatch.setattr(pst, "attach_segment",
                        lambda name: (_ for _ in ()).throw(OSError("nope")))
    t = pst.ShmTransport(("127.0.0.1", frontend.port))
    try:
        version, vec = t.pull()
        assert t.shm_active is False
        assert version == 0 and vec.shape == (8,)
        res = t.push(np.ones(8, np.float32), base_version=0)
        assert res.accepted and res.version == 1
        np.testing.assert_array_equal(res.params, np.ones(8, np.float32))
    finally:
        t.close()
        frontend.stop()


# -------------------------------------------------------- native ingest

def test_ingest_python_decoder_paths():
    raw = np.arange(12, dtype=np.float32)
    np.testing.assert_array_equal(
        nativert.decode_records_py(raw.tobytes(), "f32"), raw)
    u8 = bytes(range(256))
    got = nativert.decode_records_py(u8, "u8")
    np.testing.assert_array_equal(
        got, np.arange(256, dtype=np.float32) * np.float32(1.0 / 255.0))


@needs_native
@pytest.mark.parametrize("codec", ["f32", "bf16", "u8"])
def test_ingest_native_python_bitwise_parity(codec):
    rng = np.random.default_rng(42)
    if codec == "f32":
        buf = rng.normal(size=333).astype(np.float32).tobytes()
    elif codec == "bf16":
        import ml_dtypes
        buf = rng.normal(size=333).astype(ml_dtypes.bfloat16).tobytes()
    else:
        buf = rng.integers(0, 256, 333, dtype=np.uint8).tobytes()
    native = nativert.decode_records(buf, codec)
    assert native is not None
    np.testing.assert_array_equal(native,
                                  nativert.decode_records_py(buf, codec))


@needs_native
def test_ingest_ragged_record_rejected():
    assert nativert.decode_records(b"\x00" * 7, "f32") is None
    dec = nativert.IngestDecoder(capacity=4)
    try:
        with pytest.raises(ValueError, match="ragged"):
            dec.submit(b"\x00" * 7, "f32")
    finally:
        dec.close()


@needs_native
def test_ingest_decoder_pipelines_in_order():
    """Bounded staging queue: interleave submits with next() past the
    capacity and records come back f32-decoded in submission order."""
    rng = np.random.default_rng(3)
    records = [rng.normal(size=int(rng.integers(1, 64))).astype(np.float32)
               for _ in range(10)]
    dec = nativert.IngestDecoder(capacity=4)
    out = []
    try:
        for i, rec in enumerate(records):
            dec.submit(rec.tobytes(), "f32")
            if i >= 3:
                out.append(dec.next())
        while True:
            got = dec.next()
            if got is None:
                break
            out.append(got)
    finally:
        dec.close()
    assert len(out) == len(records)
    for got, rec in zip(out, records):
        np.testing.assert_array_equal(got, rec)


# ------------------------------------------------------ broker integration

def test_broker_native_decode_parity_and_ingest_source():
    """native_decode consumers deliver bitwise the same arrays as the plain
    wire decode, and BrokerIngestSource iterates them prefetcher-shaped
    (ends at the fin marker)."""
    broker = LoopbackBroker().start()
    prod = BrokerProducer(broker.address)
    plain = ReconnectingConsumer(broker.address, "t", group="plain")
    native = ReconnectingConsumer(broker.address, "t", group="native",
                                  native_decode=True)
    try:
        rng = np.random.default_rng(9)
        msgs = [{"x": rng.normal(size=(4, 6)).astype(np.float32),
                 "y": rng.normal(size=(4, 3)).astype(np.float32)}
                for _ in range(3)]
        for m in msgs:
            prod.publish("t", m)
        prod.publish("t", {}, meta={"fin": True})
        for m in msgs:
            _, a = plain.get(timeout=5.0)
            plain.task_done()
            _, b = native.get(timeout=5.0)
            native.task_done()
            for k in m:
                np.testing.assert_array_equal(a[k], m[k])
                np.testing.assert_array_equal(b[k], m[k])
        plain.get(timeout=5.0)  # drain plain's fin
        plain.task_done()
        got = list(BrokerIngestSource(native, idle_timeout_s=5.0))
        assert got == []  # fin already next in line: source stops cleanly
    finally:
        plain.close()
        native.close()
        prod.close()
        broker.stop()


def test_broker_ingest_source_yields_batches():
    broker = LoopbackBroker().start()
    prod = BrokerProducer(broker.address)
    cons = ReconnectingConsumer(broker.address, "t", group="g",
                                native_decode=True)
    try:
        msgs = [{"x": np.full((2, 4), float(i), np.float32)} for i in range(3)]
        for m in msgs:
            prod.publish("t", m)
        prod.publish("t", {}, meta={"fin": True})
        got = list(BrokerIngestSource(cons, idle_timeout_s=5.0))
        assert len(got) == 3
        for g, m in zip(got, msgs):
            np.testing.assert_array_equal(g["x"], m["x"])
    finally:
        cons.close()
        prod.close()
        broker.stop()


# ------------------------------------------------- three-transport parity

def _net(seed=12345, lr=0.1):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(lr).updater("sgd")
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, loss="mcxent",
                               activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def _batches(n_batches=8, batch=8, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_batches):
        x = rng.normal(size=(batch, 4)).astype(np.float32)
        labels = (x[:, 0] + x[:, 1] > 0).astype(int)
        y = np.zeros((batch, 3), np.float32)
        y[np.arange(batch), labels] = 1
        out.append(DataSet(x, y))
    return out


def _leaves(net):
    import jax
    return [np.array(x) for x in jax.tree_util.tree_leaves(net.params_list)]


@needs_shm
@pytest.mark.slow
def test_fit_parity_inproc_tcp_shm_bitwise():
    """2-worker fits over tcp and shm produce bitwise-identical parameters
    when the push schedule is deterministic (one flush push per worker,
    strictly ordered by worker_delays) — the transports move bytes, they
    don't do arithmetic. The threaded inproc engine schedules its rebases
    slightly differently, so it anchors within tolerance rather than
    bitwise. The shm run also leaves zero segments behind."""
    data = _batches()
    before = _shm_names()
    results = {}
    for kind in ("inproc", "tcp", "shm"):
        net = _net()
        wrapper = (ParameterServerParallelWrapper.builder(net)
                   .workers(2).push_frequency(100)
                   .worker_delays(0.0, 0.2).transport(kind).build())
        wrapper.fit(ListDataSetIterator(data))
        assert sum(s["steps"] for s in wrapper.worker_stats) == len(data)
        results[kind] = _leaves(net)
    for a, b in zip(results["tcp"], results["shm"]):
        np.testing.assert_array_equal(a, b, err_msg="shm diverged from tcp")
    for a, b in zip(results["inproc"], results["tcp"]):
        np.testing.assert_allclose(
            a, b, atol=5e-2, err_msg="tcp drifted from the inproc anchor")
    assert not (_shm_names() - before), "shm fit leaked segments"
