"""Attention/transformer layers + pipeline parallelism equivalence."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.layers import (
    SelfAttentionLayer, TransformerBlock,
)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.parallel.mesh import build_mesh
from deeplearning4j_tpu.parallel.pipeline import (
    PipelineParallel, stack_block_params, unstack_block_params,
)


def test_self_attention_layer_causal_matches_reference():
    from deeplearning4j_tpu.parallel.ring_attention import attention_reference
    lyr = SelfAttentionLayer(n_in=16, n_out=16, n_heads=4, causal=True,
                             activation="identity")
    params = lyr.init_params(jax.random.PRNGKey(0),
                             InputType.recurrent(16, 8))
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 8, 16)),
                    jnp.float32)
    out, _ = lyr.apply(params, {}, x)
    assert out.shape == (2, 8, 16)
    # manual recomputation through the reference attention math
    qkv = x @ params["Wqkv"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    o = attention_reference(q.reshape(2, 8, 4, 4), k.reshape(2, 8, 4, 4),
                            v.reshape(2, 8, 4, 4), causal=True)
    expect = o.reshape(2, 8, 16) @ params["Wo"] + params["b"]
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               rtol=2e-4, atol=2e-5)


def test_transformer_block_gradcheck_smoke():
    blk = TransformerBlock(n_in=8, n_out=8, n_heads=2, ffn_multiplier=2)
    params = blk.init_params(jax.random.PRNGKey(1), InputType.recurrent(8, 4))
    x = jnp.asarray(np.random.default_rng(1).normal(size=(2, 4, 8)),
                    jnp.float32)

    def loss(p):
        y, _ = blk.apply(p, {}, x)
        return jnp.sum(y ** 2)

    g = jax.grad(loss)(params)
    for k, v in g.items():
        assert np.all(np.isfinite(np.asarray(v))), k
    # central-difference numeric check on a couple of scalar params
    eps = 1e-2
    for name in ("ln1_g", "b1"):
        plus = dict(params)
        plus[name] = params[name].at[0].add(eps)
        minus = dict(params)
        minus[name] = params[name].at[0].add(-eps)
        num = (loss(plus) - loss(minus)) / (2 * eps)
        np.testing.assert_allclose(float(num), float(g[name][0]),
                                   rtol=5e-2, atol=1e-2)


def test_pipeline_matches_sequential():
    blk = TransformerBlock(n_in=8, n_out=8, n_heads=2, ffn_multiplier=2,
                           causal=True)
    n_blocks = 4
    keys = jax.random.split(jax.random.PRNGKey(2), n_blocks)
    plist = [blk.init_params(k, InputType.recurrent(8, 4)) for k in keys]
    stacked = stack_block_params(plist)
    assert len(unstack_block_params(stacked)) == n_blocks

    mesh = build_mesh({"stage": 4})
    block_fn = lambda p, x: blk.apply(p, {}, x)[0]
    pipe = PipelineParallel(mesh, block_fn, n_blocks, n_microbatches=4)
    x = jnp.asarray(np.random.default_rng(2).normal(size=(8, 4, 8)),
                    jnp.float32)
    got = pipe(stacked, x)
    expect = pipe.reference_forward(stacked, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=2e-4, atol=2e-5)


def test_pipeline_multiple_blocks_per_stage():
    blk = TransformerBlock(n_in=8, n_out=8, n_heads=2, ffn_multiplier=2)
    n_blocks = 8
    keys = jax.random.split(jax.random.PRNGKey(3), n_blocks)
    stacked = stack_block_params(
        [blk.init_params(k, InputType.recurrent(8, 4)) for k in keys])
    mesh = build_mesh({"stage": 4})
    block_fn = lambda p, x: blk.apply(p, {}, x)[0]
    pipe = PipelineParallel(mesh, block_fn, n_blocks, n_microbatches=2)
    x = jnp.asarray(np.random.default_rng(3).normal(size=(4, 4, 8)),
                    jnp.float32)
    np.testing.assert_allclose(np.asarray(pipe(stacked, x)),
                               np.asarray(pipe.reference_forward(stacked, x)),
                               rtol=2e-4, atol=2e-5)


def test_pipeline_is_differentiable():
    blk = TransformerBlock(n_in=8, n_out=8, n_heads=2, ffn_multiplier=2)
    n_blocks = 4
    keys = jax.random.split(jax.random.PRNGKey(4), n_blocks)
    stacked = stack_block_params(
        [blk.init_params(k, InputType.recurrent(8, 4)) for k in keys])
    mesh = build_mesh({"stage": 4})
    block_fn = lambda p, x: blk.apply(p, {}, x)[0]
    pipe = PipelineParallel(mesh, block_fn, n_blocks, n_microbatches=4)
    x = jnp.asarray(np.random.default_rng(4).normal(size=(4, 4, 8)),
                    jnp.float32)

    def loss_pipe(p):
        return jnp.sum(pipe(p, x) ** 2)

    def loss_seq(p):
        return jnp.sum(pipe.reference_forward(p, x) ** 2)

    gp = jax.grad(loss_pipe)(stacked)
    gs = jax.grad(loss_seq)(stacked)
    for k in gs:
        np.testing.assert_allclose(np.asarray(gp[k]), np.asarray(gs[k]),
                                   rtol=5e-3, atol=5e-4)


def test_transformer_lm_end_to_end():
    from deeplearning4j_tpu.models.transformer import transformer_lm
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = transformer_lm(vocab_size=12, width=16, n_layers=2, n_heads=2,
                          max_len=8)
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(5)
    # learnable task: next token = current token (shifted identity)
    ids = np.tile(np.arange(8) % 12, (16, 1))
    x = np.eye(12, dtype=np.float32)[ids]
    first = None
    for i in range(15):
        net.fit(x, x)
        if first is None:
            first = net.score_value
    assert net.score_value < first
    # config serde round trip includes the new layer types
    from deeplearning4j_tpu.nn.conf.multilayer import MultiLayerConfiguration
    back = MultiLayerConfiguration.from_json(conf.to_json())
    assert type(back.layers[1]).__name__ == "TransformerBlock"


def test_self_attention_mask_excludes_padded_keys():
    from deeplearning4j_tpu.ops.pallas_kernels import masked_attention
    from deeplearning4j_tpu.parallel.ring_attention import attention_reference
    lyr = SelfAttentionLayer(n_in=8, n_out=8, n_heads=2, causal=False,
                             activation="identity")
    params = lyr.init_params(jax.random.PRNGKey(7),
                             InputType.recurrent(8, 6))
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(1, 6, 8)), jnp.float32)
    mask = jnp.asarray([[1, 1, 1, 1, 0, 0]], jnp.float32)
    out_m, _ = lyr.apply(params, {}, x, mask=mask)
    # oracle: run unmasked attention on the truncated (real-only) sequence
    out_trunc, _ = lyr.apply(params, {}, x[:, :4])
    np.testing.assert_allclose(np.asarray(out_m[:, :4]),
                               np.asarray(out_trunc), rtol=1e-4, atol=1e-5)
    # direct masked_attention helper agrees with truncation too
    q = jnp.asarray(rng.normal(size=(1, 6, 2, 4)), jnp.float32)
    got = masked_attention(q, q, q, mask)
    ref = attention_reference(q[:, :4], q[:, :4], q[:, :4])
    np.testing.assert_allclose(np.asarray(got[:, :4]), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_embedding_int_ids_not_mistaken_for_onehot():
    from deeplearning4j_tpu.nn.conf.layers import EmbeddingLayer
    lyr = EmbeddingLayer(n_in=4, n_out=3)
    params = lyr.init_params(jax.random.PRNGKey(0), InputType.feed_forward(4))
    ids = jnp.asarray([[0, 3, 2, 1]], jnp.int32)  # T == n_in collision
    out, _ = lyr.apply(params, {}, ids)
    expect = params["W"][jnp.asarray([0, 3, 2, 1])] + params["b"]
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(expect))
