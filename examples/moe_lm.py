"""Switch-transformer character LM: sparse MoE blocks + bf16 activations.

TPU-native additions working together: MoETransformerBlock (pre-LN residual
attention + top-1 expert FFN with the load-balance aux loss in the
objective), the config-declared bfloat16_full dtype policy, and the K-step
fused fit path.

Run: python examples/moe_lm.py [--steps 60] [--experts 4] [--bf16]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse

import numpy as np

from deeplearning4j_tpu.models import moe_transformer_lm
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

TEXT = ("the quick brown fox jumps over the lazy dog. "
        "pack my box with five dozen liquor jugs. " * 30)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--seq", type=int, default=32)
    ap.add_argument("--experts", type=int, default=4)
    ap.add_argument("--bf16", action="store_true",
                    help="declare bfloat16_full in the config")
    ap.add_argument("--expert-parallel", action="store_true",
                    help="train with GShard all_to_all expert dispatch over "
                         "the device mesh (config + fit, no model changes)")
    args = ap.parse_args()

    chars = sorted(set(TEXT))
    idx = {c: i for i, c in enumerate(chars)}
    V = len(chars)
    conf = moe_transformer_lm(vocab_size=V, width=64, n_layers=2, n_heads=2,
                              n_experts=args.experts, max_len=args.seq,
                              learning_rate=0.01)
    if args.bf16:
        conf.global_conf.dtype = "bfloat16_full"
    net = MultiLayerNetwork(conf).init()

    ids = np.array([idx[c] for c in TEXT], np.int32)
    rng = np.random.default_rng(0)

    def batch(n=8):
        starts = rng.integers(0, len(ids) - args.seq - 1, n)
        x = np.stack([ids[s:s + args.seq] for s in starts])
        y = np.stack([ids[s + 1:s + args.seq + 1] for s in starts])
        eye = np.eye(V, dtype=np.float32)
        return eye[x], eye[y]

    x, y = batch()
    print(f"vocab={V} experts={args.experts} "
          f"dtype={conf.global_conf.dtype or 'float32 (global policy)'}")
    print("initial loss:", round(net.score(x, y), 4))
    if args.expert_parallel:
        # expert parallelism IS a fit() feature: the wrapper publishes the
        # mesh, MoE layers dispatch all_to_all (parallel/moe.py) — the data
        # axis doubles as the expert axis, the standard EP layout
        import jax

        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

        n = len(jax.devices())
        pw = (ParallelWrapper.builder(net).workers(n).prefetch_buffer(0)
              .expert_parallel("data").build())
        for step in range(args.steps):
            x, y = batch()
            pw.fit(ListDataSetIterator([DataSet(x, y)]))
            if (step + 1) % 20 == 0:
                print(f"step {step + 1}: loss {net.score(x, y):.4f}")
        print(f"expert-parallel fit OK over {n} devices")
    else:
        for step in range(args.steps):
            x, y = batch()
            net.fit(x, y)
            if (step + 1) % 20 == 0:
                print(f"step {step + 1}: loss {net.score(x, y):.4f}")

    # routing balance after training, measured from the block's REAL router
    # input: the Switch balance term E*sum(f_e*P_e) is exactly 1.0 at perfect
    # balance and E when everything routes to one expert
    import contextlib

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import common

    # probe under the SAME dtype policy training used (conf-declared policies
    # are applied inside the network's compiled programs, not globally)
    ctx = (common.override_policy(conf.global_conf.dtype)
           if conf.global_conf.dtype else contextlib.nullcontext())
    with ctx:
        h0, _ = conf.layers[0].apply(net.params_list[0], net.state_list[0],
                                     jnp.asarray(x))
        _, ns = conf.layers[1].apply(net.params_list[1], net.state_list[1], h0,
                                     train=True, rng=jax.random.PRNGKey(0))
    print(f"block-1 load-balance term: {float(ns['aux_loss']):.3f} "
          f"(1.0 = perfectly balanced, {args.experts} = collapsed)")


if __name__ == "__main__":
    main()
