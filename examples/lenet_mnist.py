"""LeNet-5 on MNIST — the reference's LenetMnistExample equivalent.

Run: python examples/lenet_mnist.py [--epochs 1]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse

from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator
from deeplearning4j_tpu.models.lenet import lenet_mnist
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.listeners import ScoreIterationListener


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--num-examples", type=int, default=8192)
    args = ap.parse_args()

    net = MultiLayerNetwork(lenet_mnist()).init()
    net.set_listeners(ScoreIterationListener(10))
    train = MnistDataSetIterator(args.batch, train=True,
                                 num_examples=args.num_examples)
    net.fit_iterator(train, epochs=args.epochs)
    test = MnistDataSetIterator(args.batch, train=False, num_examples=2048)
    print(net.evaluate(test).stats())


if __name__ == "__main__":
    main()
