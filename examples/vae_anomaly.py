"""VAE anomaly detection: unsupervised pretraining + VAE-objective scoring.

The classic DL4J workflow (reference examples' VaeMNISTAnomaly pattern over
nn/layers/variational/VariationalAutoencoder.java): pretrain a VAE vertex on
"normal" data with ComputationGraph.pretrain_layer — only the VAE's params
move — then rank unseen examples by the VAE's own per-example objective
(reconstruction + KL): high loss = the model has never seen anything like
it. Exercises the round-4 surface: CG layerwise pretraining and per-example
scoring against the pretrain objective.

Run: python examples/vae_anomaly.py [--steps 40]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ExistingDataSetIterator
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import OutputLayer, VariationalAutoencoder
from deeplearning4j_tpu.nn.graph_network import ComputationGraph


def make_data(rng, n, anomalous=False):
    """Normal data lives on a low-dim manifold; anomalies are isotropic."""
    if anomalous:
        return rng.normal(size=(n, 8)).astype(np.float32) * 2.0
    basis = np.linspace(0, 1, 8, dtype=np.float32)
    phase = rng.uniform(0, np.pi, (n, 1)).astype(np.float32)
    return np.sin(2 * np.pi * basis[None, :] + phase) \
        + 0.05 * rng.normal(size=(n, 8)).astype(np.float32)


def vae_scores(net, vae_name, x, seed=0):
    """Per-example VAE objective (reconstruction + KL), rng held fixed so
    scores are comparable across examples — the anomaly score."""
    layer = net.conf.vertices[vae_name].layer
    params = net.params_list[vae_name]
    key = jax.random.PRNGKey(seed)
    per = jax.vmap(lambda xi: layer.pretrain_loss(params, xi[None], rng=key))(
        np.asarray(x))
    return np.asarray(per)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=40)
    args = ap.parse_args()
    rng = np.random.default_rng(0)

    # the supervised head exists (the graph is a full classifier) but
    # anomaly detection only ever trains + scores the VAE vertex
    conf = (NeuralNetConfiguration.builder()
            .seed(12345).learning_rate(0.02).updater("adam")
            .graph_builder()
            .add_inputs("in")
            .add_layer("vae", VariationalAutoencoder(
                n_in=8, n_out=3, encoder_layer_sizes=(16,),
                decoder_layer_sizes=(16,), activation="tanh"), "in")
            .add_layer("out", OutputLayer(n_in=3, n_out=2, loss="mcxent",
                                          activation="softmax"), "vae")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()

    train = make_data(rng, 256)
    labels = np.zeros((256, 2), np.float32)
    labels[:, 0] = 1
    it = ExistingDataSetIterator([DataSet(train, labels)])
    for _ in range(args.steps):
        net.pretrain_layer("vae", it)  # unsupervised: only the VAE moves
    print(f"pretrained VAE for {args.steps} passes, "
          f"final objective {net.score_value:.4f}")

    # rank held-out normals vs anomalies by the VAE's OWN objective
    normal = make_data(rng, 64)
    weird = make_data(rng, 64, anomalous=True)
    scores = vae_scores(net, "vae", np.concatenate([normal, weird]))
    n_score, a_score = scores[:64].mean(), scores[64:].mean()
    print(f"mean VAE objective  normal={n_score:.4f}  "
          f"anomalous={a_score:.4f}")
    ranked = np.argsort(scores)[::-1][:10]
    frac = float(np.mean(ranked >= 64))
    print(f"top-10 highest-scored examples that are true anomalies: "
          f"{frac:.0%}")
    assert a_score > n_score, "anomalies should score higher"


if __name__ == "__main__":
    main()
