"""Character-level LSTM language model (reference GravesLSTMCharModelling).

Run: python examples/char_rnn.py [--steps 30]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse

import numpy as np

from deeplearning4j_tpu.models.char_rnn import char_rnn_lstm
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

TEXT = ("the quick brown fox jumps over the lazy dog. " * 40)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--seq", type=int, default=32)
    args = ap.parse_args()

    chars = sorted(set(TEXT))
    idx = {c: i for i, c in enumerate(chars)}
    V = len(chars)
    conf = char_rnn_lstm(vocab_size=V, hidden=128, tbptt_length=args.seq,
                         learning_rate=0.03)
    net = MultiLayerNetwork(conf).init()

    ids = np.array([idx[c] for c in TEXT])
    B, T = 16, args.seq
    starts = np.random.default_rng(0).integers(0, len(ids) - T - 1, B)
    x = np.eye(V, dtype=np.float32)[np.stack([ids[s:s + T] for s in starts])]
    y = np.eye(V, dtype=np.float32)[np.stack([ids[s + 1:s + T + 1]
                                              for s in starts])]
    # fused fit: K steps per XLA dispatch, batch staged on device once;
    # the listener's periodic score read is the only host sync
    from deeplearning4j_tpu.optimize.listeners import ScoreIterationListener
    net.set_listeners(ScoreIterationListener(10))
    net.fit(x, y, epochs=args.steps)

    # streaming generation via rnn_time_step (reference rnnTimeStep)
    net.rnn_clear_previous_state()
    cur = np.zeros((1, 1, V), np.float32)
    cur[0, 0, idx["t"]] = 1
    out = ["t"]
    for _ in range(60):
        probs = np.asarray(net.rnn_time_step(cur))[0, -1]
        nxt = int(np.argmax(probs))
        out.append(chars[nxt])
        cur = np.zeros((1, 1, V), np.float32)
        cur[0, 0, nxt] = 1
    print("sample:", "".join(out))


if __name__ == "__main__":
    main()
