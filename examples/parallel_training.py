"""Data-parallel training over the device mesh (reference ParallelWrapper /
Spark parameter averaging). On CPU run with:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
  PALLAS_AXON_POOL_IPS= python examples/parallel_training.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator
from deeplearning4j_tpu.models.lenet import lenet_mnist
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper


def main():
    n = min(len(jax.devices()), 8)
    net = MultiLayerNetwork(lenet_mnist()).init()
    wrapper = (ParallelWrapper.builder(net)
               .workers(n)
               .averaging_frequency(1)
               .shard_optimizer_state()   # ZeRO-1: moments live 1/n per chip
               .build())
    it = MnistDataSetIterator(batch=16 * n, num_examples=4096)
    wrapper.fit(it, epochs=1)
    print(f"{n}-way DP done; score {net.score_value:.4f}")
    # proof the optimizer state is sharded, not replicated: the largest
    # moment tensor holds 1/n of its bytes per device
    leaf = max(jax.tree_util.tree_leaves(net.updater_state),
               key=lambda a: a.nbytes)
    frac = leaf.addressable_shards[0].data.nbytes / leaf.nbytes
    print(f"ZeRO-1: largest updater moment holds {frac:.0%} per device")
    test = MnistDataSetIterator(batch=256, train=False, num_examples=1024)
    print("accuracy:", net.evaluate(test).accuracy())


if __name__ == "__main__":
    main()
