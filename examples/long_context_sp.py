"""Long-context attention via sequence parallelism: ring + Ulysses.

The framework's long-context story (SURVEY.md §5): a sequence too long for
one chip's HBM is sharded along its length over a mesh axis, and attention
runs as a collective —

* ring_attention: K/V blocks rotate around the ring (lax.ppermute) while
  each device holds its query shard; memory per device is O(T/N).
* ulysses_attention: all_to_all swaps sequence sharding for HEAD sharding,
  runs the tiled flash kernel on full-length sequences for 1/N of the
  heads, and swaps back — two collectives total.

Both are exact (same math as single-device attention) and differentiable.
This demo runs on an 8-virtual-device CPU mesh; on TPU hardware the same
code runs over ICI with the pallas flash kernel inside ulysses.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
     python examples/long_context_sp.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import time

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.parallel.mesh import build_mesh
from deeplearning4j_tpu.parallel.ring_attention import (
    attention_reference, ring_attention, ulysses_attention,
)


def main():
    n = len(jax.devices())
    mesh = build_mesh({"sp": n})
    B, T, H, D = 2, 128 * n, n, 16  # sequence length scales with the mesh
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(size=(B, T, H, D)).astype(np.float32))
               for _ in range(3))
    print(f"mesh: {n} devices on axis 'sp'; sequence length {T} "
          f"({T // n} per device)")

    want = attention_reference(q, k, v, causal=True)
    for name, fn in (("ring", ring_attention), ("ulysses", ulysses_attention)):
        t0 = time.time()
        got = fn(q, k, v, mesh, causal=True)
        err = float(jnp.max(jnp.abs(got - want)))
        print(f"{name:8s} attention: max err vs single-device = {err:.2e} "
              f"({time.time() - t0:.2f}s incl. compile)")
        assert err < 1e-3

    # differentiable: gradients flow through the collectives
    def loss(q):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True) ** 2)

    g = jax.grad(loss)(q)
    def ref_loss(q):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)
    g_ref = jax.grad(ref_loss)(q)
    gerr = float(jnp.max(jnp.abs(g - g_ref)))
    print(f"ring backward: max grad err = {gerr:.2e}")
    assert gerr < 1e-2
    print("sequence parallelism OK: exact attention at O(T/N) memory/device")

    # ---- the framework path: the same thing as config + fit() -------------
    # No shard_map in user code: a plain transformer_lm config trained via
    # ParallelWrapper with a sequence axis. The attention layers dispatch
    # Ulysses/ring over the mesh automatically (nn/conf/layers/attention.py
    # attend()).
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.models import transformer_lm
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

    V, Tc = 8, 64
    conf = transformer_lm(V, width=32, n_layers=2, n_heads=4, max_len=Tc,
                          learning_rate=0.01)
    net = MultiLayerNetwork(conf).init()
    ids = np.random.default_rng(1).integers(0, V, size=(8, Tc + 1))
    eye = np.eye(V, dtype=np.float32)
    ds = DataSet(eye[ids[:, :-1]], eye[ids[:, 1:]])
    pw = (ParallelWrapper.builder(net)
          .mesh(build_mesh({"data": 2, "sp": n // 2}))
          .prefetch_buffer(0)
          .sequence_parallel("sp")          # <- the whole long-context story
          .build())
    first = None
    for _ in range(6):
        pw.fit(ListDataSetIterator([ds]))
        first = first if first is not None else float(net.score_value)
    print(f"config+fit sequence parallelism OK: loss {first:.3f} -> "
          f"{float(net.score_value):.3f} on a data{2}xsp{n // 2} mesh")


if __name__ == "__main__":
    main()
