"""ComputationGraph char-RNN: TBPTT training + streaming generation
(reference ComputationGraph fit with BackpropType.TruncatedBPTT +
rnnTimeStep:1788 — the graph-side twin of examples/char_rnn.py).

Run: python examples/graph_char_rnn.py [--steps 100]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse

import numpy as np

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer
from deeplearning4j_tpu.nn.graph_network import ComputationGraph

TEXT = ("the quick brown fox jumps over the lazy dog. " * 40)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--seq", type=int, default=32)
    args = ap.parse_args()

    chars = sorted(set(TEXT))
    idx = {c: i for i, c in enumerate(chars)}
    V = len(chars)
    conf = (NeuralNetConfiguration.builder()
            .seed(12).learning_rate(0.03).updater("adam")
            .graph_builder()
            .add_inputs("chars")
            .add_layer("lstm", GravesLSTM(n_in=V, n_out=128,
                                          activation="tanh"), "chars")
            .add_layer("out", RnnOutputLayer(n_in=128, n_out=V, loss="mcxent",
                                             activation="softmax"), "lstm")
            .set_outputs("out")
            .backprop_type("TruncatedBPTT")
            .t_bptt_forward_length(16)
            .build())
    net = ComputationGraph(conf).init()

    ids = np.array([idx[c] for c in TEXT])
    B, T = 16, args.seq
    starts = np.random.default_rng(0).integers(0, len(ids) - T - 1, B)
    x = np.eye(V, dtype=np.float32)[np.stack([ids[s:s + T] for s in starts])]
    y = np.eye(V, dtype=np.float32)[np.stack([ids[s + 1:s + T + 1]
                                              for s in starts])]
    # TBPTT configs run the exact per-chunk path; a Standard-backprop graph
    # would take the fused K-step dispatch here (see examples/char_rnn.py)
    from deeplearning4j_tpu.optimize.listeners import ScoreIterationListener
    net.set_listeners(ScoreIterationListener(10))
    net.fit([x], [y], epochs=args.steps)

    # streaming generation carries LSTM-vertex state across calls
    net.rnn_clear_previous_state()
    cur = np.zeros((1, 1, V), np.float32)
    cur[0, 0, idx["t"]] = 1
    out = ["t"]
    rng = np.random.default_rng(7)
    for _ in range(60):
        probs = np.asarray(net.rnn_time_step(cur)[0])[0, -1]
        c = int(rng.choice(V, p=probs / probs.sum()))
        out.append(chars[c])
        cur = np.zeros((1, 1, V), np.float32)
        cur[0, 0, c] = 1
    print("generated:", "".join(out))


if __name__ == "__main__":
    main()
