"""Tensor-parallel training + sharded checkpointing on a device mesh.

The TPU-native capabilities the JVM reference never had: Megatron-style
output-dim param sharding over a 'model' mesh axis (XLA GSPMD inserts the
collectives), and an orbax checkpoint whose leaves keep their sharding on
disk — no host gather — restored directly onto the mesh.

Run (CPU virtual mesh):
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/tensor_parallel_checkpoint.py
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork, make_train_step
from deeplearning4j_tpu.parallel.mesh import (
    batch_sharding, build_mesh, shard_params_for_tp)
from deeplearning4j_tpu.utils.sharded_checkpoint import (
    restore_sharded, save_sharded)


def main():
    n = len(jax.devices())
    mesh = build_mesh({"data": max(n // 2, 1), "model": 2 if n >= 2 else 1})
    print(f"mesh: {dict(mesh.shape)} over {n} devices")

    conf = (NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.1).updater("lamb")
            .list()
            .layer(DenseLayer(n_in=16, n_out=64, activation="relu"))
            .layer(DenseLayer(n_in=64, n_out=64, activation="relu"))
            .layer(OutputLayer(n_in=64, n_out=4, loss="mcxent",
                               activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()

    # Megatron-style TP: 2-D weights sharded on the output dim over 'model'
    params = shard_params_for_tp(net.params_list, conf, mesh)
    bsh = batch_sharding(mesh)
    # computation follows the input shardings: params carry TP layouts,
    # the batch is DP-sharded, GSPMD inserts the collectives. Donated
    # training state -> in-place updates, no 2x HBM (same as the fit path).
    step = jax.jit(make_train_step(conf), donate_argnums=(0, 1, 2))

    rng = np.random.default_rng(0)
    B = 8 * mesh.shape["data"]  # divisible by the data axis at any scale
    x = jax.device_put(
        jnp.asarray(rng.normal(size=(B, 16)).astype(np.float32)), bsh)
    labels = rng.integers(0, 4, B)
    y = jax.device_put(jnp.asarray(np.eye(4, dtype=np.float32)[labels]), bsh)
    states, upd = net.state_list, net.updater_state
    key = jax.random.PRNGKey(0)
    for i in range(20):
        params, states, upd, loss = step(params, states, upd, x, y,
                                         jax.random.fold_in(key, i),
                                         jnp.int32(i))
        if i % 5 == 0:
            print(f"step {i}: loss {float(loss):.4f} | W1 sharding "
                  f"{params[1]['W'].sharding.spec}")

    # sharded checkpoint: each leaf written in its mesh layout
    net.params_list, net.state_list, net.updater_state = params, states, upd
    ckpt = os.path.join(tempfile.mkdtemp(), "tp_ckpt")
    save_sharded(ckpt, net, step=20)

    # restore DIRECTLY onto the same TP sharding
    shardings = jax.tree_util.tree_map(lambda a: a.sharding, params)
    restored = restore_sharded(ckpt, MultiLayerNetwork(conf),
                               shardings=shardings)
    w = restored.params_list[1]["W"]
    print(f"restored W1: sharding {w.sharding.spec}, "
          f"{len(w.sharding.device_set)} devices, "
          f"max|diff|={float(jnp.max(jnp.abs(w - params[1]['W']))):.2e}")


if __name__ == "__main__":
    main()
