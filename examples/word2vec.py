"""Word2Vec skip-gram embeddings (reference Word2VecRawTextExample).

Run: python examples/word2vec.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu.nlp.word2vec import Word2Vec

CORPUS = (["the king rules the royal castle"] * 30
          + ["the queen rules the royal castle"] * 30
          + ["a dog chases a cat in the garden"] * 30
          + ["a cat flees a dog in the garden"] * 30)


def main():
    w2v = (Word2Vec.Builder()
           .layer_size(32).window_size(4).min_word_frequency(3)
           .negative_sample(5).epochs(10).learning_rate(0.05).seed(42)
           .build())
    w2v.fit([s.split() for s in CORPUS])
    print("similarity(king, queen):", w2v.similarity("king", "queen"))
    print("similarity(king, garden):", w2v.similarity("king", "garden"))
    print("nearest to 'castle':", w2v.words_nearest("castle", 3))


if __name__ == "__main__":
    main()
