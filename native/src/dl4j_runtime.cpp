// dl4j_tpu native runtime: dataset parsers, async prefetch loader, CSV reader,
// stats wire codec.
//
// This is the TPU-native equivalent of the reference's native substrate
// (SURVEY.md §2.10): where deeplearning4j reaches native code through JavaCPP
// (libnd4j backends, cuDNN helpers, HDF5) and runs its data path through
// AsyncDataSetIterator (background prefetch thread + blocking queue,
// reference deeplearning4j-nn datasets/iterator/AsyncDataSetIterator.java:36)
// and MagicQueue (per-device bucketed queue, deeplearning4j-core
// parallelism/MagicQueue.java:21), this library provides the host-side IO +
// staging pipeline in C++: IDX (MnistDbFile.java header handling) and
// CIFAR-binary parsing, a producer-thread batch assembler with a bounded
// ring queue, a numeric CSV reader (DataVec CSVRecordReader fast path), and
// a compact binary stats codec standing in for the generated SBE codecs
// (reference ui-model ui/stats/sbe/*). Device compute stays in XLA; this
// library only ever touches host memory.
//
// C ABI only (consumed via ctypes from Python).

#include <algorithm>
#include <cctype>
#include <fstream>
#include <unordered_map>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// IDX parsing (big-endian header: magic [dtype|ndim], then ndim int32 dims)
// ---------------------------------------------------------------------------

struct IdxFile {
  std::vector<int64_t> dims;
  std::vector<uint8_t> data;  // raw uint8 payload
};

uint32_t be32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

IdxFile* idx_load(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  uint8_t hdr[4];
  if (std::fread(hdr, 1, 4, f) != 4) { std::fclose(f); return nullptr; }
  uint32_t magic = be32(hdr);
  int dtype = (magic >> 8) & 0xFF;
  int ndim = magic & 0xFF;
  if (dtype != 0x08 || ndim < 1 || ndim > 4) { std::fclose(f); return nullptr; }
  // Sanity-bound the payload by the actual file size so a corrupt header
  // can't trigger an overflowing/teradbyte resize (bad_alloc must not escape
  // the C ABI into the ctypes caller).
  long data_start = std::ftell(f) + 4L * ndim;
  std::fseek(f, 0, SEEK_END);
  long fsize = std::ftell(f);
  std::fseek(f, data_start - 4L * ndim, SEEK_SET);
  int64_t max_total = fsize - data_start;
  auto* out = new IdxFile();
  int64_t total = 1;
  for (int i = 0; i < ndim; i++) {
    uint8_t d[4];
    if (std::fread(d, 1, 4, f) != 4) { std::fclose(f); delete out; return nullptr; }
    int64_t v = int64_t(be32(d));
    out->dims.push_back(v);
    if (v <= 0 || (max_total > 0 && total > max_total / v)) {
      std::fclose(f); delete out; return nullptr;
    }
    total *= v;
  }
  if (total > max_total) { std::fclose(f); delete out; return nullptr; }
  try {
    out->data.resize(size_t(total));
  } catch (const std::bad_alloc&) {
    std::fclose(f); delete out; return nullptr;
  }
  if (std::fread(out->data.data(), 1, size_t(total), f) != size_t(total)) {
    std::fclose(f); delete out; return nullptr;
  }
  std::fclose(f);
  return out;
}

// ---------------------------------------------------------------------------
// Async batch loader: producer thread assembles float32 batches into a
// bounded queue; the consumer blocks in next(). One epoch per run; reset()
// reshuffles and restarts (AsyncDataSetIterator.reset semantics).
// ---------------------------------------------------------------------------

struct Batch {
  std::vector<float> x;
  std::vector<float> y;
};

struct Loader {
  // immutable after construction
  std::vector<uint8_t> features;  // [n, feat] uint8
  std::vector<uint8_t> labels;    // [n] uint8 class ids
  int64_t n = 0;
  int64_t feat = 0;
  int num_classes = 10;
  int batch = 0;
  int capacity = 4;
  bool shuffle = true;
  bool normalize = true;
  uint64_t seed = 0;
  uint64_t epoch = 0;

  // queue state
  std::deque<Batch> queue;
  std::mutex mu;
  std::condition_variable cv_put, cv_get;
  bool epoch_done = false;
  std::atomic<bool> stop{false};
  std::thread producer;

  ~Loader() { shutdown(); }

  void shutdown() {
    {
      // Hold the mutex while setting stop so a producer that has evaluated
      // its wait-predicate but not yet re-blocked can't miss the wakeup.
      std::lock_guard<std::mutex> l(mu);
      stop.store(true);
    }
    cv_put.notify_all();
    cv_get.notify_all();
    if (producer.joinable()) producer.join();
  }

  void start_epoch() {
    shutdown();
    stop.store(false);
    {
      std::lock_guard<std::mutex> l(mu);
      queue.clear();
      epoch_done = false;
    }
    producer = std::thread([this] { run_producer(); });
  }

  void run_producer() {
    std::vector<int64_t> order(static_cast<size_t>(n));
    for (int64_t i = 0; i < n; i++) order[size_t(i)] = i;
    if (shuffle) {
      std::mt19937_64 rng(seed + epoch);
      std::shuffle(order.begin(), order.end(), rng);
    }
    const float scale = normalize ? 1.0f / 255.0f : 1.0f;
    int64_t nb = n / batch;  // drop last partial (reference iterator default)
    for (int64_t b = 0; b < nb && !stop.load(); b++) {
      Batch bt;
      bt.x.resize(size_t(batch) * size_t(feat));
      bt.y.assign(size_t(batch) * size_t(num_classes), 0.0f);
      for (int i = 0; i < batch; i++) {
        int64_t idx = order[size_t(b * batch + i)];
        const uint8_t* src = features.data() + idx * feat;
        float* dst = bt.x.data() + int64_t(i) * feat;
        for (int64_t j = 0; j < feat; j++) dst[j] = float(src[j]) * scale;
        int cls = labels[size_t(idx)];
        if (cls >= 0 && cls < num_classes)
          bt.y[size_t(i) * num_classes + cls] = 1.0f;
      }
      std::unique_lock<std::mutex> l(mu);
      cv_put.wait(l, [this] {
        return stop.load() || int(queue.size()) < capacity;
      });
      if (stop.load()) return;
      queue.push_back(std::move(bt));
      cv_get.notify_one();
    }
    std::lock_guard<std::mutex> l(mu);
    epoch_done = true;
    cv_get.notify_all();
  }

  // 1 = batch written, 0 = epoch exhausted
  int next(float* x_out, float* y_out) {
    std::unique_lock<std::mutex> l(mu);
    cv_get.wait(l, [this] {
      return stop.load() || !queue.empty() || epoch_done;
    });
    if (queue.empty()) return 0;
    Batch bt = std::move(queue.front());
    queue.pop_front();
    cv_put.notify_one();
    l.unlock();
    std::memcpy(x_out, bt.x.data(), bt.x.size() * sizeof(float));
    std::memcpy(y_out, bt.y.data(), bt.y.size() * sizeof(float));
    return 1;
  }
};

// ---------------------------------------------------------------------------
// CSV numeric reader
// ---------------------------------------------------------------------------

struct CsvFile {
  int64_t rows = 0;
  int64_t cols = 0;
  std::vector<float> values;
};

// strict != 0: reject the file (return nullptr) on the first field that is
// empty or not fully numeric, or on a ragged row — the caller then takes its
// general (string-preserving) reader. This makes one native pass both
// validate AND parse, replacing the old Python float()-prevalidation pass
// that read the whole file twice.
CsvFile* csv_load(const char* path, char delim, int skip_lines, int strict) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  std::fseek(f, 0, SEEK_END);
  long sz = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::string buf(size_t(sz), '\0');
  if (std::fread(buf.data(), 1, size_t(sz), f) != size_t(sz)) {
    std::fclose(f);
    return nullptr;
  }
  std::fclose(f);

  auto* out = new CsvFile();
  size_t pos = 0;
  int line_no = 0;
  while (pos < buf.size()) {
    size_t eol = buf.find('\n', pos);
    if (eol == std::string::npos) eol = buf.size();
    if (line_no++ < skip_lines || eol == pos) { pos = eol + 1; continue; }
    int64_t ncol = 0;
    size_t p = pos;
    while (true) {
      size_t next = buf.find(delim, p);
      size_t fend = (next == std::string::npos || next >= eol) ? eol : next;
      // Null-terminate the field in place so strtof can't scan past it
      // (e.g. steal a number from the next line through the '\n').
      float v = 0.0f;
      bool field_ok = false;
      if (fend > p) {
        char saved = '\0';
        bool restore = fend < buf.size();
        if (restore) { saved = buf[fend]; buf[fend] = '\0'; }
        char* end = nullptr;
        v = std::strtof(buf.data() + p, &end);
        if (end == buf.data() + p) {
          v = 0.0f;  // non-numeric field -> 0 (lenient mode)
        } else {
          // fully consumed modulo trailing whitespace/CR == Python float()
          const char* q = end;
          const char* fe = buf.data() + fend;
          while (q < fe && (*q == ' ' || *q == '\t' || *q == '\r')) q++;
          field_ok = (q == fe);
        }
        if (restore) buf[fend] = saved;
      }
      if (strict && !field_ok) { delete out; return nullptr; }
      out->values.push_back(v);  // empty field (incl. trailing delim) -> 0
      ncol++;
      if (fend == eol) break;
      p = fend + 1;
    }
    if (out->cols == 0) out->cols = ncol;
    if (ncol != out->cols && strict) { delete out; return nullptr; }
    if (ncol < out->cols) {  // ragged short row: pad with zeros
      while (ncol < out->cols) { out->values.push_back(0.0f); ncol++; }
    } else if (ncol > out->cols) {  // ragged long row: truncate
      out->values.resize(out->values.size() - size_t(ncol - out->cols));
    }
    out->rows++;
    pos = eol + 1;
  }
  return out;
}

// ---------------------------------------------------------------------------
// Stats codec — same DLTS wire format as the Python codec in ui/stats.py
// (magic "DLTS", version u16, then length-prefixed strings, packed scalars,
// three sections of named {mean-magnitude, min, max, histogram}).
// ---------------------------------------------------------------------------

struct StatsBuilder {
  std::vector<uint8_t> buf;
  std::vector<std::vector<uint8_t>> sections[3];

  template <typename T>
  static void put(std::vector<uint8_t>& b, T v) {
    const uint8_t* p = reinterpret_cast<const uint8_t*>(&v);
    b.insert(b.end(), p, p + sizeof(T));
  }
  static void put_str(std::vector<uint8_t>& b, const char* s) {
    uint16_t n = uint16_t(std::strlen(s));
    put<uint16_t>(b, n);
    b.insert(b.end(), s, s + n);
  }
};

}  // namespace

extern "C" {

// ----- IDX -----
void* dl4j_idx_open(const char* path) { return idx_load(path); }
int dl4j_idx_ndim(void* h) { return int(static_cast<IdxFile*>(h)->dims.size()); }
void dl4j_idx_dims(void* h, int64_t* out) {
  auto* f = static_cast<IdxFile*>(h);
  for (size_t i = 0; i < f->dims.size(); i++) out[i] = f->dims[i];
}
void dl4j_idx_read(void* h, uint8_t* out) {
  auto* f = static_cast<IdxFile*>(h);
  std::memcpy(out, f->data.data(), f->data.size());
}
void dl4j_idx_close(void* h) { delete static_cast<IdxFile*>(h); }

// ----- async loader -----
void* dl4j_loader_create_from_arrays(const uint8_t* features,
                                     const uint8_t* labels, int64_t n,
                                     int64_t feat, int num_classes, int batch,
                                     int capacity, int shuffle,
                                     uint64_t seed, int normalize) {
  if (n <= 0 || feat <= 0 || batch <= 0 || batch > n) return nullptr;
  auto* l = new Loader();
  l->features.assign(features, features + n * feat);
  l->labels.assign(labels, labels + n);
  l->n = n;
  l->feat = feat;
  l->num_classes = num_classes;
  l->batch = batch;
  l->capacity = std::max(1, capacity);
  l->shuffle = shuffle != 0;
  l->normalize = normalize != 0;
  l->seed = seed;
  l->start_epoch();
  return l;
}

void* dl4j_mnist_loader_create(const char* img_path, const char* lbl_path,
                               int batch, int capacity, int shuffle,
                               uint64_t seed, int normalize) {
  IdxFile* imgs = idx_load(img_path);
  if (!imgs) return nullptr;
  IdxFile* lbls = idx_load(lbl_path);
  if (!lbls) { delete imgs; return nullptr; }
  int64_t n = imgs->dims[0];
  int64_t feat = 1;
  for (size_t i = 1; i < imgs->dims.size(); i++) feat *= imgs->dims[i];
  void* l = nullptr;
  if (lbls->dims.size() == 1 && lbls->dims[0] == n) {
    l = dl4j_loader_create_from_arrays(imgs->data.data(), lbls->data.data(), n,
                                       feat, 10, batch, capacity, shuffle,
                                       seed, normalize);
  }
  delete imgs;
  delete lbls;
  return l;
}

// CIFAR-10 binary format: records of [1 label byte][3072 pixel bytes]
void* dl4j_cifar_loader_create(const char** paths, int npaths, int batch,
                               int capacity, int shuffle, uint64_t seed) {
  std::vector<uint8_t> feats, lbls;
  const int64_t rec = 3073;
  for (int i = 0; i < npaths; i++) {
    FILE* f = std::fopen(paths[i], "rb");
    if (!f) return nullptr;
    std::fseek(f, 0, SEEK_END);
    long sz = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    std::vector<uint8_t> raw(static_cast<size_t>(sz));
    if (std::fread(raw.data(), 1, size_t(sz), f) != size_t(sz)) {
      std::fclose(f);
      return nullptr;
    }
    std::fclose(f);
    int64_t nrec = sz / rec;
    for (int64_t r = 0; r < nrec; r++) {
      lbls.push_back(raw[size_t(r * rec)]);
      feats.insert(feats.end(), raw.begin() + r * rec + 1,
                   raw.begin() + (r + 1) * rec);
    }
  }
  int64_t n = int64_t(lbls.size());
  if (n == 0) return nullptr;
  return dl4j_loader_create_from_arrays(feats.data(), lbls.data(), n, 3072, 10,
                                        batch, capacity, shuffle, seed, 1);
}

int64_t dl4j_loader_num_examples(void* h) { return static_cast<Loader*>(h)->n; }
int64_t dl4j_loader_feature_size(void* h) { return static_cast<Loader*>(h)->feat; }
int dl4j_loader_num_classes(void* h) { return static_cast<Loader*>(h)->num_classes; }
int dl4j_loader_batch_size(void* h) { return static_cast<Loader*>(h)->batch; }

int dl4j_loader_next(void* h, float* x_out, float* y_out) {
  return static_cast<Loader*>(h)->next(x_out, y_out);
}

void dl4j_loader_reset(void* h) {
  auto* l = static_cast<Loader*>(h);
  l->epoch++;
  l->start_epoch();
}

void dl4j_loader_close(void* h) { delete static_cast<Loader*>(h); }

// ----- CSV -----
void* dl4j_csv_open(const char* path, char delim, int skip_lines) {
  return csv_load(path, delim, skip_lines, /*strict=*/0);
}
// v2: strict validate-while-parsing (nullptr on any non-numeric/ragged data)
void* dl4j_csv_open2(const char* path, char delim, int skip_lines,
                     int strict) {
  return csv_load(path, delim, skip_lines, strict);
}
int64_t dl4j_csv_rows(void* h) { return static_cast<CsvFile*>(h)->rows; }
int64_t dl4j_csv_cols(void* h) { return static_cast<CsvFile*>(h)->cols; }
void dl4j_csv_read(void* h, float* out) {
  auto* f = static_cast<CsvFile*>(h);
  std::memcpy(out, f->values.data(), f->values.size() * sizeof(float));
}
void dl4j_csv_close(void* h) { delete static_cast<CsvFile*>(h); }

// ----- stats codec -----
void* dl4j_stats_begin(const char* session_id, const char* worker_id,
                       int64_t timestamp, int32_t iteration, double score,
                       double iter_time_ms, double samples_per_sec,
                       int64_t mem_rss, int64_t device_mem) {
  auto* b = new StatsBuilder();
  auto& o = b->buf;
  o.insert(o.end(), {'D', 'L', 'T', 'S'});
  StatsBuilder::put<uint16_t>(o, 1);  // version
  StatsBuilder::put_str(o, session_id);
  StatsBuilder::put_str(o, worker_id);
  StatsBuilder::put<int64_t>(o, timestamp);
  StatsBuilder::put<int32_t>(o, iteration);
  StatsBuilder::put<double>(o, score);
  StatsBuilder::put<double>(o, iter_time_ms);
  StatsBuilder::put<double>(o, samples_per_sec);
  StatsBuilder::put<int64_t>(o, mem_rss);
  StatsBuilder::put<int64_t>(o, device_mem);
  return b;
}

// section: 0 = params, 1 = gradients, 2 = updates
int dl4j_stats_add(void* h, int section, const char* name, double mean_mag,
                   double lo, double hi, const int32_t* hist, int nhist) {
  if (section < 0 || section > 2) return -1;
  auto* b = static_cast<StatsBuilder*>(h);
  std::vector<uint8_t> e;
  StatsBuilder::put_str(e, name);
  StatsBuilder::put<double>(e, mean_mag);
  StatsBuilder::put<double>(e, lo);
  StatsBuilder::put<double>(e, hi);
  StatsBuilder::put<uint16_t>(e, uint16_t(nhist));
  for (int i = 0; i < nhist; i++) StatsBuilder::put<int32_t>(e, hist[i]);
  b->sections[section].push_back(std::move(e));
  return 0;
}

int64_t dl4j_stats_finish(void* h, uint8_t* out, int64_t cap) {
  auto* b = static_cast<StatsBuilder*>(h);
  std::vector<uint8_t> full = b->buf;
  for (int s = 0; s < 3; s++) {
    StatsBuilder::put<uint16_t>(full, uint16_t(b->sections[s].size()));
    for (auto& e : b->sections[s]) full.insert(full.end(), e.begin(), e.end());
  }
  int64_t n = int64_t(full.size());
  if (out && cap >= n) {
    std::memcpy(out, full.data(), size_t(n));
    delete b;
  }
  return n;  // when out==null or cap too small: required size (builder kept)
}

void dl4j_stats_abort(void* h) { delete static_cast<StatsBuilder*>(h); }

int dl4j_runtime_version(void) { return 4; }

}  // extern "C"

// ------------------------------------------------------------ ingest decode
// Batched record decoder for the zero-copy host data plane: raw broker/wire
// record bytes -> float32, either one synchronous call (ctypes releases the
// GIL for its duration, so Python peers keep running) or a producer-thread
// pipeline mirroring Loader (submit on the consumer thread, decode happens
// on the worker, next() hands back finished records) so decode overlaps the
// training step the way AsyncDataSetIterator overlapped fetch.
namespace {

// codec ids shared with nativert/__init__.py INGEST_CODECS
constexpr int kIngestF32 = 0;   // passthrough
constexpr int kIngestBf16 = 1;  // bf16 -> f32 (bits << 16)
constexpr int kIngestU8 = 2;    // u8 -> f32 / 255

// -1 on bad codec or a length that is not a whole number of elements
int64_t ingest_decode_into(const uint8_t* src, int64_t nbytes, int codec,
                           float* out) {
  switch (codec) {
    case kIngestF32: {
      if (nbytes % 4) return -1;
      std::memcpy(out, src, size_t(nbytes));
      return nbytes / 4;
    }
    case kIngestBf16: {
      if (nbytes % 2) return -1;
      int64_t n = nbytes / 2;
      for (int64_t i = 0; i < n; i++) {
        uint32_t bits = uint32_t(src[2 * i] | (uint32_t(src[2 * i + 1]) << 8))
                        << 16;
        std::memcpy(out + i, &bits, 4);
      }
      return n;
    }
    case kIngestU8: {
      const float scale = 1.0f / 255.0f;
      for (int64_t i = 0; i < nbytes; i++) out[i] = float(src[i]) * scale;
      return nbytes;
    }
    default:
      return -1;
  }
}

struct IngestRec {
  std::vector<uint8_t> raw;
  int codec = 0;
};

struct Ingest {
  int capacity = 8;
  std::deque<IngestRec> inbox;
  std::deque<std::vector<float>> outbox;
  std::mutex mu;
  std::condition_variable cv_work, cv_done;
  std::atomic<bool> stop{false};
  bool bad = false;    // a submitted record failed to decode
  int in_flight = 0;   // popped from inbox, not yet in outbox
  std::thread worker;

  ~Ingest() { shutdown(); }

  void shutdown() {
    {
      std::lock_guard<std::mutex> l(mu);
      stop.store(true);
    }
    cv_work.notify_all();
    cv_done.notify_all();
    if (worker.joinable()) worker.join();
  }

  void run_worker() {
    while (true) {
      IngestRec rec;
      {
        std::unique_lock<std::mutex> l(mu);
        cv_work.wait(l, [this] { return stop.load() || !inbox.empty(); });
        if (stop.load()) return;
        rec = std::move(inbox.front());
        inbox.pop_front();
        in_flight++;
      }
      std::vector<float> dec;
      int64_t n = -1;
      size_t cap = rec.codec == kIngestU8 ? rec.raw.size()
                   : rec.codec == kIngestBf16 ? rec.raw.size() / 2
                                              : rec.raw.size() / 4;
      dec.resize(cap);
      n = ingest_decode_into(rec.raw.data(), int64_t(rec.raw.size()),
                             rec.codec, dec.data());
      std::lock_guard<std::mutex> l(mu);
      in_flight--;
      if (n < 0) {
        bad = true;
      } else {
        dec.resize(size_t(n));
        outbox.push_back(std::move(dec));
      }
      cv_done.notify_all();
    }
  }
};

}  // namespace

extern "C" {

// one-shot decode: floats written, or -1 on bad codec / ragged length /
// insufficient cap. GIL-free for the whole call when invoked via ctypes.
int64_t dl4j_ingest_decode(const uint8_t* src, int64_t nbytes, int codec,
                           float* out, int64_t cap) {
  int64_t need = codec == kIngestU8 ? nbytes
                 : codec == kIngestBf16 ? nbytes / 2
                                        : nbytes / 4;
  if (need > cap) return -1;
  return ingest_decode_into(src, nbytes, codec, out);
}

void* dl4j_ingest_create(int capacity) {
  auto* g = new Ingest();
  g->capacity = std::max(1, capacity);
  g->worker = std::thread([g] { g->run_worker(); });
  return g;
}

// 0 = queued; -1 = pipeline poisoned by an earlier bad record. Blocks only
// when `capacity` records are already in flight (bounded staging).
int dl4j_ingest_submit(void* h, const uint8_t* src, int64_t nbytes,
                       int codec) {
  auto* g = static_cast<Ingest*>(h);
  IngestRec rec;
  rec.raw.assign(src, src + nbytes);
  rec.codec = codec;
  std::unique_lock<std::mutex> l(g->mu);
  g->cv_done.wait(l, [g] {
    return g->stop.load() || g->bad ||
           int(g->inbox.size() + g->outbox.size()) < g->capacity;
  });
  if (g->bad || g->stop.load()) return -1;
  g->inbox.push_back(std::move(rec));
  g->cv_work.notify_one();
  return 0;
}

// floats written for the next finished record; 0 when nothing is in flight
// (caller submitted everything and drained); -1 on poisoned pipeline or cap
// too small for the record.
int64_t dl4j_ingest_next(void* h, float* out, int64_t cap) {
  auto* g = static_cast<Ingest*>(h);
  std::unique_lock<std::mutex> l(g->mu);
  g->cv_done.wait(l, [g] {
    return g->stop.load() || g->bad || !g->outbox.empty() ||
           (g->inbox.empty() && g->in_flight == 0);
  });
  if (g->bad) return -1;
  if (g->outbox.empty()) return 0;  // drained (or stopping)
  std::vector<float> dec = std::move(g->outbox.front());
  g->outbox.pop_front();
  g->cv_done.notify_all();
  l.unlock();
  if (int64_t(dec.size()) > cap) return -1;
  std::memcpy(out, dec.data(), dec.size() * sizeof(float));
  return int64_t(dec.size());
}

void dl4j_ingest_close(void* h) { delete static_cast<Ingest*>(h); }

}  // extern "C"

// ----------------------------------------------------------- vocab counter
// Parallel vocabulary build (the reference's VocabConstructor.java:33 counts
// tokens with worker threads before the Huffman pass). Whitespace tokens;
// mode 1 additionally applies CommonPreprocessor semantics: strip the
// punctuation/digit set [\d.:,"'()\[\]|/?!;] and ASCII-lowercase. ASCII-only
// by contract — any byte >= 0x80 makes the counter return null and the
// caller falls back to the Python pipeline (whose str.lower() has unicode
// semantics this pass does not replicate).
namespace {

struct VocabCount {
  std::vector<std::pair<std::string, int64_t>> entries;  // sorted count desc
  int64_t total = 0;
};

// Python str.split() whitespace for the ASCII range: \t\n\v\f\r, space,
// and the \x1c-\x1f separators (C isspace excludes the latter).
inline bool vc_is_space(unsigned char c) {
  return c == ' ' || (c >= 0x09 && c <= 0x0d) || (c >= 0x1c && c <= 0x1f);
}

inline bool vc_strip_char(unsigned char c) {
  switch (c) {
    case '.': case ':': case ',': case '"': case '\'': case '(': case ')':
    case '[': case ']': case '|': case '/': case '?': case '!': case ';':
      return true;
    default:
      return c >= '0' && c <= '9';
  }
}

bool vc_count_range(const char* data, size_t begin, size_t end, bool common,
                    std::unordered_map<std::string, int64_t>* counts,
                    int64_t* total) {
  std::string tok;
  for (size_t i = begin; i <= end; i++) {
    unsigned char c = (i < end) ? (unsigned char)data[i] : ' ';
    if (c >= 0x80) return false;  // non-ASCII: caller must fall back
    // non-printable control bytes outside the whitespace set (NUL etc.)
    // would be silently truncated by the C-string readout; decline so the
    // Python fallback keeps the token intact
    if (c < 0x20 && !vc_is_space(c)) return false;
    if (vc_is_space(c)) {
      if (!tok.empty()) {
        (*counts)[tok]++;
        (*total)++;
        tok.clear();
      }
      continue;
    }
    if (common) {
      if (vc_strip_char(c)) continue;
      if (c >= 'A' && c <= 'Z') c = (unsigned char)(c - 'A' + 'a');
    }
    tok.push_back((char)c);
  }
  return true;
}

}  // namespace

extern "C" {

// Returns a handle, or null on IO error / non-ASCII content (caller falls
// back to the Python tokenizer pipeline). nthreads <= 0 -> hardware default.
void* dl4j_vocab_count_file(const char* path, int common_preprocess,
                            int nthreads) {
  std::ifstream f(path, std::ios::binary | std::ios::ate);
  if (!f) return nullptr;
  std::streamsize sz = f.tellg();
  f.seekg(0);
  std::string data((size_t)sz, '\0');
  if (sz && !f.read(&data[0], sz)) return nullptr;

  unsigned hw = std::thread::hardware_concurrency();
  int nt = nthreads > 0 ? nthreads : (hw ? (int)hw : 1);
  if ((int64_t)sz < (int64_t)1 << 20) nt = 1;  // small file: skip thread cost
  // chunk boundaries snapped forward to whitespace so no token spans chunks
  std::vector<size_t> bounds{0};
  for (int t = 1; t < nt; t++) {
    size_t b = (size_t)sz * (size_t)t / (size_t)nt;
    while (b < (size_t)sz && !vc_is_space((unsigned char)data[b])) b++;
    bounds.push_back(b);
  }
  bounds.push_back((size_t)sz);

  int real_nt = (int)bounds.size() - 1;
  std::vector<std::unordered_map<std::string, int64_t>> maps(real_nt);
  std::vector<int64_t> totals(real_nt, 0);
  std::vector<char> ok(real_nt, 1);
  std::vector<std::thread> threads;
  for (int t = 0; t < real_nt; t++) {
    threads.emplace_back([&, t]() {
      ok[t] = vc_count_range(data.data(), bounds[t], bounds[t + 1],
                             common_preprocess != 0, &maps[t], &totals[t]);
    });
  }
  for (auto& th : threads) th.join();
  for (int t = 0; t < real_nt; t++)
    if (!ok[t]) return nullptr;

  auto* vc = new VocabCount();
  std::unordered_map<std::string, int64_t> merged;
  for (int t = 0; t < real_nt; t++) {
    for (auto& kv : maps[t]) merged[kv.first] += kv.second;
    vc->total += totals[t];
  }
  vc->entries.assign(merged.begin(), merged.end());
  // deterministic order: count desc, then word asc
  std::sort(vc->entries.begin(), vc->entries.end(),
            [](const auto& a, const auto& b) {
              if (a.second != b.second) return a.second > b.second;
              return a.first < b.first;
            });
  return vc;
}

int64_t dl4j_vocab_num_words(void* h) {
  return (int64_t)static_cast<VocabCount*>(h)->entries.size();
}

int64_t dl4j_vocab_total_tokens(void* h) {
  return static_cast<VocabCount*>(h)->total;
}

// Writes word idx into out (NUL-terminated, truncated to cap) and returns its
// count; -1 for out-of-range idx.
int64_t dl4j_vocab_entry(void* h, int64_t idx, char* out, int64_t cap) {
  auto* vc = static_cast<VocabCount*>(h);
  if (idx < 0 || (size_t)idx >= vc->entries.size()) return -1;
  const auto& e = vc->entries[(size_t)idx];
  if (out && cap > 0) {
    int64_t n = std::min<int64_t>((int64_t)e.first.size(), cap - 1);
    std::memcpy(out, e.first.data(), (size_t)n);
    out[n] = '\0';
  }
  return e.second;
}

void dl4j_vocab_close(void* h) { delete static_cast<VocabCount*>(h); }

}  // extern "C"
